"""Transformer NMT model (flagship).

Capability parity with the reference's Transformer benchmark model
(reference: python/paddle/fluid/tests/unittests/dist_transformer.py:1331,
Transformer-base on WMT16 en-de), built TPU-first:

- Dense padded batches + additive attention-bias tensors instead of LoD.
- Parameter names follow a tensor-parallel convention consumed by
  parallel/strategy.py regex rules: column-parallel weights (`*_colp.w_*`)
  shard their output dim over the 'model' mesh axis, row-parallel weights
  (`*_rowp.w_*`) shard their input dim; GSPMD inserts the all-reduces.
- Everything is ordinary Program-IR ops, so the whole train step (fwd +
  autodiff + Adam) compiles to one XLA computation.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers, unique_name
from paddle_tpu.param_attr import ParamAttr


class TransformerConfig:
    """Transformer-base hyperparameters (matching the reference benchmark
    config in dist_transformer.py ModelHyperParams)."""

    def __init__(
        self,
        src_vocab_size: int = 10000,
        trg_vocab_size: int = 10000,
        max_length: int = 256,
        d_model: int = 512,
        d_inner: int = 2048,
        n_head: int = 8,
        n_layer: int = 6,
        dropout: float = 0.1,
        label_smooth_eps: float = 0.1,
        dtype: str = "float32",
    ):
        self.src_vocab_size = src_vocab_size
        self.trg_vocab_size = trg_vocab_size
        self.max_length = max_length
        self.d_model = d_model
        self.d_inner = d_inner
        self.n_head = n_head
        self.n_layer = n_layer
        self.dropout = dropout
        self.label_smooth_eps = label_smooth_eps
        self.dtype = dtype

    @property
    def d_head(self):
        return self.d_model // self.n_head


def base() -> TransformerConfig:
    return TransformerConfig()


def _pname(prefix: str, kind: str) -> ParamAttr:
    # kind: colp (column-parallel), rowp (row-parallel), repl (replicated)
    return ParamAttr(name=f"{prefix}_{kind}.w")


def _fc(x, size, prefix, kind, act=None, num_flatten_dims=2):
    return layers.fc(
        x,
        size,
        num_flatten_dims=num_flatten_dims,
        param_attr=ParamAttr(name=f"{prefix}_{kind}.w"),
        bias_attr=ParamAttr(name=f"{prefix}_{kind}.b"),
        act=act,
    )


def _positional_encoding(max_len: int, d_model: int) -> np.ndarray:
    """Sinusoidal table (reference: dist_transformer.py position_encoding_init)."""
    pos = np.arange(max_len)[:, None].astype(np.float64)
    i = np.arange(d_model // 2)[None, :].astype(np.float64)
    angle = pos / np.power(10000.0, 2 * i / d_model)
    table = np.zeros((max_len, d_model), dtype=np.float32)
    table[:, 0::2] = np.sin(angle)
    table[:, 1::2] = np.cos(angle)
    return table


def _multi_head_attention(q_in, kv_in, bias, cfg: TransformerConfig, prefix: str,
                          is_test: bool, causal: bool = False):
    h, dh, d = cfg.n_head, cfg.d_head, cfg.d_model

    # BTHD layout: [b, t, h, dh] straight off the projection reshape. The
    # head transpose the reference does (dist_transformer.py __split_heads)
    # forced per-custom-call layout copies around the attention kernel,
    # measured at ~15 ms/step on the bench config.
    def split_heads(x):
        return layers.reshape(x, [0, 0, h, dh])

    if q_in is kv_in:
        # self-attention: one fused [d, 3d] projection (one MXU pass
        # instead of three; the reference emits separate q/k/v fcs)
        qkv = _fc(q_in, 3 * d, f"{prefix}_qkv", "colp")
        q, k, v = layers.split(qkv, 3, dim=-1)
    else:
        q = _fc(q_in, d, f"{prefix}_q", "colp")
        k = _fc(kv_in, d, f"{prefix}_k", "colp")
        v = _fc(kv_in, d, f"{prefix}_v", "colp")
    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper(f"{prefix}_sdpa")
    ctx = helper.create_variable_for_type_inference(dtype=cfg.dtype)
    # logsumexp rows, consumed by the paired grad op (DCE'd at inference)
    lse = helper.create_variable_for_type_inference(dtype="float32")
    lse.stop_gradient = True
    inputs = {"Q": q, "K": k, "V": v}
    if bias is not None:
        inputs["Bias"] = bias
    helper.append_op(
        "scaled_dot_product_attention",
        inputs=inputs,
        outputs={"Out": ctx, "Lse": lse},
        attrs={
            "scale": 1.0 / math.sqrt(dh),
            "dropout_prob": float(cfg.dropout),
            "is_test": is_test,
            "layout": "bthd",
            # causal rides IN-KERNEL (position mask + dead-block skip in
            # the flash kernels): no [t, t] bias tensor ever exists, the
            # O(t) HBM property holds for decoder self-attention too
            "causal": causal,
        },
    )
    ctx = layers.reshape(ctx, [0, 0, d])
    return _fc(ctx, d, f"{prefix}_out", "rowp")


def _ffn(x, cfg: TransformerConfig, prefix: str, is_test: bool):
    h = _fc(x, cfg.d_inner, f"{prefix}_ffn1", "colp", act="relu")
    if cfg.dropout and not is_test:
        h = layers.dropout(h, cfg.dropout, is_test=is_test,
                           dropout_implementation="upscale_in_train")
    return _fc(h, cfg.d_model, f"{prefix}_ffn2", "rowp")


def _pre_post(x, residual, cfg, prefix, is_test):
    """post-norm residual block wiring (reference uses preprocess 'n',
    postprocess 'da': norm -> sublayer -> dropout -> add)."""
    out = x
    if cfg.dropout and not is_test:
        out = layers.dropout(out, cfg.dropout, is_test=is_test,
                             dropout_implementation="upscale_in_train")
    out = layers.elementwise_add(out, residual)
    return out


def _ln(x, prefix):
    return layers.layer_norm(
        x, begin_norm_axis=2,
        param_attr=ParamAttr(name=f"{prefix}_ln.scale"),
        bias_attr=ParamAttr(name=f"{prefix}_ln.bias"),
    )


def _embed(ids, vocab, cfg: TransformerConfig, name: str, pos_table_name: str,
           is_test: bool):
    emb = layers.embedding(
        ids, size=[vocab, cfg.d_model],
        param_attr=ParamAttr(
            name=name,
            initializer=fluid.initializer.NormalInitializer(
                0.0, cfg.d_model ** -0.5),
        ),
    )
    emb = layers.scale(emb, scale=cfg.d_model ** 0.5)
    pos = layers.embedding(
        _position_ids(ids), size=[cfg.max_length, cfg.d_model],
        param_attr=ParamAttr(
            name=pos_table_name,
            initializer=fluid.initializer.NumpyArrayInitializer(
                _positional_encoding(cfg.max_length, cfg.d_model)
            ),
            trainable=False,
        ),
    )
    x = layers.elementwise_add(emb, pos)
    if cfg.dropout and not is_test:
        x = layers.dropout(x, cfg.dropout, is_test=is_test,
                           dropout_implementation="upscale_in_train")
    return x


def _position_ids(ids):
    """[b, t] int positions built from ops (static shapes at trace time)."""
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("pos_ids")
    out = helper.create_variable_for_type_inference(dtype="int64",
                                                    stop_gradient=True)
    helper.append_op("position_ids", inputs={"X": ids}, outputs={"Out": out})
    return out


def encoder_layer(x, bias, cfg, i, is_test):
    p = f"enc{i}"
    ln_x = _ln(x, f"{p}_preattn")
    attn = _multi_head_attention(ln_x, ln_x, bias, cfg, f"{p}_attn", is_test)
    x = _pre_post(attn, x, cfg, p, is_test)
    ff = _ffn(_ln(x, f"{p}_preffn"), cfg, p, is_test)
    return _pre_post(ff, x, cfg, p, is_test)


def decoder_layer(x, enc_out, self_bias, cross_bias, cfg, i, is_test):
    p = f"dec{i}"
    attn = _multi_head_attention(_ln(x, f"{p}_preself"), _ln(x, f"{p}_preself"),
                                 self_bias, cfg, f"{p}_self", is_test,
                                 causal=True)
    x = _pre_post(attn, x, cfg, p, is_test)
    ln_x = _ln(x, f"{p}_precross")
    cross = _multi_head_attention(ln_x, enc_out, cross_bias, cfg,
                                  f"{p}_cross", is_test)
    x = _pre_post(cross, x, cfg, p, is_test)
    ff = _ffn(_ln(x, f"{p}_preffn"), cfg, p, is_test)
    return _pre_post(ff, x, cfg, p, is_test)



def _train_feeds_and_biases():
    """Shared feed vars + attention biases for build()/build_scan()."""
    from paddle_tpu.layer_helper import LayerHelper

    src = layers.data("src_ids", shape=[-1], dtype="int64",
                      append_batch_size=True)
    trg = layers.data("trg_ids", shape=[-1], dtype="int64")
    lbl = layers.data("lbl_ids", shape=[-1], dtype="int64")
    src_pad = layers.data("src_pad_mask", shape=[-1], dtype="float32")
    trg_pad = layers.data("trg_pad_mask", shape=[-1], dtype="float32")
    helper = LayerHelper("attn_bias")
    enc_bias = helper.create_variable_for_type_inference("float32", True)
    helper.append_op("attn_bias", inputs={"PadMask": src_pad},
                     outputs={"Out": enc_bias}, attrs={"causal": False})
    dec_self_bias = helper.create_variable_for_type_inference("float32", True)
    # pad-only [b, 1, 1, t]: the causal future-mask is applied in-kernel
    # by the decoder self-attention (sdpa attr), never materialized
    helper.append_op("attn_bias", inputs={"PadMask": trg_pad},
                     outputs={"Out": dec_self_bias}, attrs={"causal": False})
    return src, trg, lbl, src_pad, trg_pad, enc_bias, dec_self_bias


def _loss_head(dec, lbl, trg_pad, cfg):
    """Shared projection + (optionally label-smoothed) masked token loss."""
    logits = layers.fc(
        dec, cfg.trg_vocab_size, num_flatten_dims=2,
        param_attr=ParamAttr(name="proj_colp.w"), bias_attr=False,
    )
    if cfg.label_smooth_eps:
        smooth = layers.label_smooth(
            layers.one_hot(lbl, cfg.trg_vocab_size),
            epsilon=cfg.label_smooth_eps,
        )
        ce = layers.softmax_with_cross_entropy(logits, smooth,
                                               soft_label=True)
    else:
        ce = layers.softmax_with_cross_entropy(
            logits, layers.unsqueeze(lbl, [2]))
    ce = layers.reshape(ce, [0, -1])
    masked = layers.elementwise_mul(ce, trg_pad)
    token_count = layers.reduce_sum(trg_pad)
    loss = layers.elementwise_div(
        layers.reduce_sum(masked), layers.elementwise_max(
            token_count, layers.fill_constant_like(token_count, 1.0))
    )
    return logits, token_count, loss


def build(cfg: Optional[TransformerConfig] = None, is_test: bool = False):
    """Builds the full training graph in the current main/startup programs.

    Feeds: src_ids[b,s], trg_ids[b,t], lbl_ids[b,t], src_mask[b,1,1,s] (1 =
    real token), trg_mask is derived causally inside. Returns dict of key
    variables."""
    cfg = cfg or base()
    (src, trg, lbl, src_pad, trg_pad,
     enc_bias, dec_self_bias) = _train_feeds_and_biases()
    cross_bias = enc_bias  # same src padding bias, broadcast over query dim

    enc = _embed(src, cfg.src_vocab_size, cfg, "src_emb.w", "src_pos.w", is_test)
    for i in range(cfg.n_layer):
        enc = encoder_layer(enc, enc_bias, cfg, i, is_test)
    enc = _ln(enc, "enc_post")

    dec = _embed(trg, cfg.trg_vocab_size, cfg, "trg_emb.w", "trg_pos.w", is_test)
    for i in range(cfg.n_layer):
        dec = decoder_layer(dec, enc, dec_self_bias, cross_bias, cfg, i, is_test)
    dec = _ln(dec, "dec_post")

    logits, token_count, loss = _loss_head(dec, lbl, trg_pad, cfg)
    return {
        "feeds": [src, trg, lbl, src_pad, trg_pad],
        "loss": loss,
        "logits": logits,
        "token_count": token_count,
        "config": cfg,
    }


def make_batch(cfg: TransformerConfig, batch: int, src_len: int, trg_len: int,
               seed: int = 0) -> Dict[str, np.ndarray]:
    """Synthetic padded batch matching the feed contract."""
    r = np.random.RandomState(seed)
    src = r.randint(3, cfg.src_vocab_size, (batch, src_len)).astype(np.int64)
    trg = r.randint(3, cfg.trg_vocab_size, (batch, trg_len)).astype(np.int64)
    lbl = r.randint(3, cfg.trg_vocab_size, (batch, trg_len)).astype(np.int64)
    src_lens = r.randint(src_len // 2, src_len + 1, batch)
    trg_lens = r.randint(trg_len // 2, trg_len + 1, batch)
    src_pad = (np.arange(src_len)[None, :] < src_lens[:, None]).astype(np.float32)
    trg_pad = (np.arange(trg_len)[None, :] < trg_lens[:, None]).astype(np.float32)
    return {
        "src_ids": src * src_pad.astype(np.int64),
        "trg_ids": trg * trg_pad.astype(np.int64),
        "lbl_ids": lbl,
        "src_pad_mask": src_pad,
        "trg_pad_mask": trg_pad,
    }


# --- beam-search decoding (reference: operators/beam_search_op.cc driven by
# a while loop in the NMT infer program; here the whole decode loop is one
# `while` op lowered to lax.while_loop, so the entire beam search compiles
# into a single XLA computation) ---


def _encode_source(src, src_pad, cfg: TransformerConfig):
    """Encoder stack over a padded source batch (weights shared with
    build() by parameter name). Returns ``(enc [b, s, d], enc_bias
    [b, 1, 1, s])`` — the shared front half of every decode-side
    program (beam decode, serving prefill)."""
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("encode_src")
    enc_bias = helper.create_variable_for_type_inference("float32", True)
    helper.append_op("attn_bias", inputs={"PadMask": src_pad},
                     outputs={"Out": enc_bias}, attrs={"causal": False})
    enc = _embed(src, cfg.src_vocab_size, cfg, "src_emb.w", "src_pos.w",
                 True)
    for i in range(cfg.n_layer):
        enc = encoder_layer(enc, enc_bias, cfg, i, True)
    return _ln(enc, "enc_post"), enc_bias


def build_decode(cfg: Optional[TransformerConfig] = None, beam_size: int = 4,
                 max_len: int = 32, src_len: int = 32, bos_id: int = 0,
                 end_id: int = 1):
    """Builds a beam-search translation graph in the current program.

    Feeds: src_ids [b, src_len] int64, src_pad_mask [b, src_len] f32
    (1 = real). Returns {"feeds", "ids" [b, K, max_len], "scores" [b, K],
    "config"}. ``src_len`` is static (XLA shape discipline); pad or bucket
    sources to it. Re-runs the decoder over the full (static-shape) prefix
    each step — O(T^2) per step like the reference's cache-less while-loop
    decoder.
    """
    from paddle_tpu.layer_helper import LayerHelper

    cfg = cfg or base()
    k, t_max, s_len = int(beam_size), int(max_len), int(src_len)
    src = layers.data("src_ids", shape=[s_len], dtype="int64")
    src_pad = layers.data("src_pad_mask", shape=[s_len], dtype="float32")

    helper = LayerHelper("beam_decode")

    def _op(op_type, inputs, attrs=None, dtype="float32", n_out=1,
            out_slot="Out"):
        outs = [helper.create_variable_for_type_inference(dtype, True)
                for _ in range(n_out)]
        helper.append_op(op_type, inputs=inputs,
                         outputs={out_slot: outs[0]} if n_out == 1 else None,
                         attrs=attrs or {})
        return outs[0]

    # encoder (shared weights with build() by parameter name)
    enc, enc_bias = _encode_source(src, src_pad, cfg)

    # replicate encoder state per beam: [b,s,d] -> [b*K,s,d]
    enc_beam = layers.reshape(
        layers.expand(layers.unsqueeze(enc, [1]), [1, k, 1, 1]),
        [-1, s_len, cfg.d_model],
    )
    cross_beam = layers.reshape(
        layers.expand(layers.unsqueeze(enc_bias, [1]), [1, k, 1, 1, 1]),
        [-1, 1, 1, s_len],
    )

    # beam state init
    seed = _op("slice", {"X": src},
               {"axes": [1], "starts": [0], "ends": [1]}, dtype="int64")
    tmpl = layers.expand(layers.unsqueeze(seed, [2]), [1, k, t_max])
    ids = _op("fill_any_like", {"X": tmpl}, {"value": float(bos_id)},
              dtype="int64")
    zk = layers.cast(
        layers.squeeze(
            _op("slice", {"X": tmpl},
                {"axes": [2], "starts": [0], "ends": [1]}, dtype="int64"),
            [2]),
        "float32")
    zeros_bk = _op("fill_any_like", {"X": zk}, {"value": 0.0})
    beam_mask = _op(
        "assign_value", {},
        {"shape": [k], "dtype": "float32",
         "values": [0.0] + [-1e9] * (k - 1)})
    scores = layers.elementwise_add(zeros_bk, beam_mask)
    finished = layers.cast(zeros_bk, "bool")

    t = layers.fill_constant([1], "int64", 1)
    n_total = layers.reduce_sum(
        _op("fill_any_like", {"X": zeros_bk}, {"value": 1.0}))
    t_lim = layers.fill_constant([1], "int64", t_max)
    cond = layers.less_than(t, t_lim)

    from paddle_tpu.layers.control_flow import While

    with While(cond).block():
        # time mask: positions < t are live
        tpos = _op("range", {}, {"start": 0, "end": t_max, "dtype": "int64"},
                   dtype="int64")
        live = layers.cast(layers.less_than(tpos, t), "float32")  # [T]
        ids_flat = layers.reshape(ids, [-1, t_max])
        trg_pad = layers.elementwise_mul(
            layers.cast(_op("fill_any_like", {"X": ids_flat}, {"value": 1.0},
                            dtype="int64"), "float32"),
            live)
        self_bias = _op("attn_bias", {"PadMask": trg_pad},
                        {"causal": False})  # causal is in-kernel (sdpa attr)
        dec = _embed(ids_flat, cfg.trg_vocab_size, cfg, "trg_emb.w",
                     "trg_pos.w", True)
        for i in range(cfg.n_layer):
            dec = decoder_layer(dec, enc_beam, self_bias, cross_beam, cfg, i,
                                True)
        dec = _ln(dec, "dec_post")
        # logits at the last generated position (t-1)
        tm1 = layers.increment(t, value=-1.0, in_place=False)
        dec_t = _op("dynamic_slice",
                    {"X": layers.transpose(dec, [1, 0, 2]), "Index": tm1})
        logits = layers.fc(
            dec_t, cfg.trg_vocab_size, num_flatten_dims=1,
            param_attr=ParamAttr(name="proj_colp.w"), bias_attr=False,
        )
        logp = layers.reshape(layers.log_softmax(logits),
                              [-1, k, cfg.trg_vocab_size])

        new_ids = helper.create_variable_for_type_inference("int64", True)
        new_scores = helper.create_variable_for_type_inference("float32", True)
        new_fin = helper.create_variable_for_type_inference("bool", True)
        parent = helper.create_variable_for_type_inference("int64", True)
        helper.append_op(
            "beam_search_step",
            inputs={"Ids": ids, "Scores": scores, "LogProbs": logp,
                    "Finished": finished, "StepIdx": t},
            outputs={"Ids": new_ids, "Scores": new_scores,
                     "Finished": new_fin, "Parent": parent},
            attrs={"end_id": end_id},
        )
        layers.assign(new_ids, output=ids)
        layers.assign(new_scores, output=scores)
        layers.assign(new_fin, output=finished)

        layers.increment(t, value=1.0, in_place=True)
        n_fin = layers.reduce_sum(layers.cast(finished, "float32"))
        layers.assign(
            layers.logical_and(layers.less_than(t, t_lim),
                               layers.less_than(n_fin, n_total)),
            output=cond)

    return {"feeds": [src, src_pad], "ids": ids, "scores": scores,
            "config": cfg}


_decode_prog_cache: Dict[tuple, tuple] = {}


def translate(exe, scope, src_ids: np.ndarray, src_pad: np.ndarray,
              cfg: Optional[TransformerConfig] = None, beam_size: int = 4,
              max_len: int = 32, bos_id: int = 0, end_id: int = 1):
    """Beam-decode a padded source batch with weights from ``scope``.

    The decode Program is cached per (config, beam, lengths) so repeated
    calls reuse the same program object and hit the Executor's compile
    cache. Returns (ids [b, K, max_len], scores [b, K]) as numpy arrays.
    """
    from paddle_tpu import executor as _executor

    cfg = cfg or base()
    key = (
        cfg.src_vocab_size, cfg.trg_vocab_size, cfg.d_model, cfg.d_inner,
        cfg.n_head, cfg.n_layer, cfg.max_length,
        beam_size, max_len, int(src_ids.shape[1]), bos_id, end_id,
    )
    cached = _decode_prog_cache.get(key)
    if cached is None:
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            dec = build_decode(cfg, beam_size=beam_size, max_len=max_len,
                               src_len=int(src_ids.shape[1]), bos_id=bos_id,
                               end_id=end_id)
        _decode_prog_cache[key] = (prog, dec)
    else:
        prog, dec = cached
    with _executor.scope_guard(scope):
        ids, scores = exe.run(
            prog,
            feed={"src_ids": src_ids, "src_pad_mask": src_pad},
            fetch_list=[dec["ids"], dec["scores"]],
        )
    return ids, scores


# --- scan-over-layers build (compile-time optimization) ---
#
# The per-layer build unrolls n_layer copies of the same subgraph, so
# trace size and XLA compile time grow linearly (superlinearly after
# fusion) with depth. This variant stacks each weight kind across layers
# ([L, ...] parameters) and runs ONE `scan` op whose sub-block is a single
# layer: the program, the trace, and the HLO are O(1) in depth, and the
# scan grad is XLA's scan transpose. Same math as build() — a parity test
# maps per-layer weights onto the stacks and checks losses match.


def _w_fc(x, w, b=None, act=None):
    """fc with EXPLICIT weight vars (no parameter creation) — for scan
    sub-blocks where weights are per-layer slices."""
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("wfc")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        "mul", inputs={"X": x, "Y": w}, outputs={"Out": out},
        attrs={"x_num_col_dims": 2, "y_num_col_dims": 1},
    )
    if b is not None:
        out2 = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(
            "elementwise_add", inputs={"X": out, "Y": b},
            outputs={"Out": out2}, attrs={"axis": 2},
        )
        out = out2
    if act:
        out = getattr(layers, act)(out)
    return out


def _w_ln(x, scale, bias):
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("wln")
    y = helper.create_variable_for_type_inference(dtype=x.dtype)
    mean = helper.create_variable_for_type_inference(dtype="float32", stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype="float32", stop_gradient=True)
    helper.append_op(
        "layer_norm",
        inputs={"X": x, "Scale": scale, "Bias": bias},
        outputs={"Y": y, "Mean": mean, "Variance": var},
        attrs={"begin_norm_axis": 2, "epsilon": 1e-5},
    )
    return y


def _w_sdpa(q, k, v, bias, cfg, is_test, causal=False):
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("wsdpa")
    ctx = helper.create_variable_for_type_inference(dtype=cfg.dtype)
    lse = helper.create_variable_for_type_inference(dtype="float32")
    lse.stop_gradient = True
    inputs = {"Q": q, "K": k, "V": v}
    if bias is not None:
        inputs["Bias"] = bias
    helper.append_op(
        "scaled_dot_product_attention",
        inputs=inputs,
        outputs={"Out": ctx, "Lse": lse},
        attrs={
            "scale": 1.0 / math.sqrt(cfg.d_head),
            "dropout_prob": float(cfg.dropout),
            "is_test": is_test,
            "layout": "bthd",
            "causal": causal,
        },
    )
    return ctx


def _w_attention(q_in, kv_in, bias, cfg, weights, is_test, fused_qkv,
                 causal=False):
    h, dh, d = cfg.n_head, cfg.d_head, cfg.d_model

    def split_heads(z):
        return layers.reshape(z, [0, 0, h, dh])  # BTHD, see _multi_head_attention

    if fused_qkv:
        qkv = _w_fc(q_in, weights["qkv.w"], weights["qkv.b"])
        q, k, v = layers.split(qkv, 3, dim=-1)
    else:
        q = _w_fc(q_in, weights["q.w"], weights["q.b"])
        k = _w_fc(kv_in, weights["k.w"], weights["k.b"])
        v = _w_fc(kv_in, weights["v.w"], weights["v.b"])
    ctx = _w_sdpa(split_heads(q), split_heads(k), split_heads(v), bias,
                  cfg, is_test, causal=causal)
    ctx = layers.reshape(ctx, [0, 0, d])
    return _w_fc(ctx, weights["out.w"], weights["out.b"])


def _w_drop_add(x, residual, cfg, is_test):
    if cfg.dropout and not is_test:
        x = layers.dropout(x, cfg.dropout, is_test=is_test,
                           dropout_implementation="upscale_in_train")
    return layers.elementwise_add(x, residual)


# (slot key, per-layer shape fn, maps-from per-layer param name fn)
def _enc_weight_specs(cfg):
    d, di = cfg.d_model, cfg.d_inner
    return [
        ("preattn_ln.scale", [d], lambda i: f"enc{i}_preattn_ln.scale"),
        ("preattn_ln.bias", [d], lambda i: f"enc{i}_preattn_ln.bias"),
        ("qkv.w", [d, 3 * d], lambda i: f"enc{i}_attn_qkv_colp.w"),
        ("qkv.b", [3 * d], lambda i: f"enc{i}_attn_qkv_colp.b"),
        ("out.w", [d, d], lambda i: f"enc{i}_attn_out_rowp.w"),
        ("out.b", [d], lambda i: f"enc{i}_attn_out_rowp.b"),
        ("preffn_ln.scale", [d], lambda i: f"enc{i}_preffn_ln.scale"),
        ("preffn_ln.bias", [d], lambda i: f"enc{i}_preffn_ln.bias"),
        ("ffn1.w", [d, di], lambda i: f"enc{i}_ffn1_colp.w"),
        ("ffn1.b", [di], lambda i: f"enc{i}_ffn1_colp.b"),
        ("ffn2.w", [di, d], lambda i: f"enc{i}_ffn2_rowp.w"),
        ("ffn2.b", [d], lambda i: f"enc{i}_ffn2_rowp.b"),
    ]


def _dec_weight_specs(cfg):
    d, di = cfg.d_model, cfg.d_inner
    specs = [
        ("preself_ln.scale", [d], lambda i: f"dec{i}_preself_ln.scale"),
        ("preself_ln.bias", [d], lambda i: f"dec{i}_preself_ln.bias"),
        ("self_q.w", [d, d], lambda i: f"dec{i}_self_q_colp.w"),
        ("self_q.b", [d], lambda i: f"dec{i}_self_q_colp.b"),
        ("self_k.w", [d, d], lambda i: f"dec{i}_self_k_colp.w"),
        ("self_k.b", [d], lambda i: f"dec{i}_self_k_colp.b"),
        ("self_v.w", [d, d], lambda i: f"dec{i}_self_v_colp.w"),
        ("self_v.b", [d], lambda i: f"dec{i}_self_v_colp.b"),
        ("self_out.w", [d, d], lambda i: f"dec{i}_self_out_rowp.w"),
        ("self_out.b", [d], lambda i: f"dec{i}_self_out_rowp.b"),
        ("precross_ln.scale", [d], lambda i: f"dec{i}_precross_ln.scale"),
        ("precross_ln.bias", [d], lambda i: f"dec{i}_precross_ln.bias"),
        ("q.w", [d, d], lambda i: f"dec{i}_cross_q_colp.w"),
        ("q.b", [d], lambda i: f"dec{i}_cross_q_colp.b"),
        ("k.w", [d, d], lambda i: f"dec{i}_cross_k_colp.w"),
        ("k.b", [d], lambda i: f"dec{i}_cross_k_colp.b"),
        ("v.w", [d, d], lambda i: f"dec{i}_cross_v_colp.w"),
        ("v.b", [d], lambda i: f"dec{i}_cross_v_colp.b"),
        ("cross_out.w", [d, d], lambda i: f"dec{i}_cross_out_rowp.w"),
        ("cross_out.b", [d], lambda i: f"dec{i}_cross_out_rowp.b"),
        ("preffn_ln.scale", [d], lambda i: f"dec{i}_preffn_ln.scale"),
        ("preffn_ln.bias", [d], lambda i: f"dec{i}_preffn_ln.bias"),
        ("ffn1.w", [d, di], lambda i: f"dec{i}_ffn1_colp.w"),
        ("ffn1.b", [di], lambda i: f"dec{i}_ffn1_colp.b"),
        ("ffn2.w", [di, d], lambda i: f"dec{i}_ffn2_rowp.w"),
        ("ffn2.b", [d], lambda i: f"dec{i}_ffn2_rowp.b"),
    ]
    return specs


def _layer_scan(x, cfg, specs, body_fn, stack_prefix, is_test,
                batch_vars=(), unroll=1):
    """Run ``body_fn(x_var, weights)`` once per layer via the scan op,
    with each weight kind stacked [n_layer, ...] and scanned.

    ``batch_vars``: names of captured vars with the carry's batch dim
    (attention biases, the encoder output) — under a pipeline strategy
    these must be microbatched in step with the activation stream
    (scan attr ``stream_names``)."""
    from paddle_tpu.layer_helper import LayerHelper
    from paddle_tpu.layers.control_flow import _captured_names

    prog = fluid.default_main_program()
    parent = prog.current_block()
    helper = LayerHelper(stack_prefix)
    stacked = {}
    for key, shape, _src in specs:
        is_bias_like = len(shape) == 1
        if is_bias_like:
            init = fluid.initializer.ConstantInitializer(
                1.0 if key.endswith("ln.scale") else 0.0)
        else:
            # match build()'s LayerHelper default (Xavier over the
            # PER-LAYER fan, not the stacked shape) so from-scratch runs
            # start from the same distribution in both modes
            init = fluid.initializer.XavierInitializer(
                fan_in=shape[0], fan_out=shape[1])
        stacked[key] = helper.create_parameter(
            ParamAttr(name=f"{stack_prefix}_{key}_stacked",
                      initializer=init),
            shape=[cfg.n_layer] + shape,
            dtype=cfg.dtype,
        )

    sub = prog._create_block()
    try:
        slice_vars = {}
        for key, shape, _src in specs:
            slice_vars[key] = sub.create_var(
                name=unique_name.generate(f"{stack_prefix}_{key}_slice"),
                dtype=cfg.dtype, shape=tuple(shape),
            )
        x_in = sub.create_var(
            name=unique_name.generate(f"{stack_prefix}_carry"),
            dtype=x.dtype, shape=x.shape,
        )
        x_out = body_fn(x_in, slice_vars)
    finally:
        prog._rollback()

    x_names = [slice_vars[k].name for k, _s, _f in specs]
    captured = _captured_names(sub, parent, exclude=x_names + [x_in.name])
    final = parent.create_var(
        name=unique_name.generate(f"{stack_prefix}_out"),
        dtype=x.dtype, shape=x.shape,
    )
    parent.append_op(
        "scan",
        inputs={
            "X": [stacked[k].name for k, _s, _f in specs],
            "Init": [x.name],
            "Captured": captured,
        },
        outputs={"Y": [], "FinalState": [final.name]},
        attrs={
            "sub_block": sub,
            "x_names": x_names,
            "state_in_names": [x_in.name],
            "state_out_names": [x_out.name],
            "y_names": [],
            "captured_names": captured,
            # one scan step per LAYER with a single carried activation:
            # eligible for the GPipe schedule under a strategy pipe_axis
            "pipelinable": True,
            "unroll": int(unroll),
            "stream_names": [n for n in captured
                             if n in set(batch_vars)],
        },
    )
    return final


def build_scan(cfg: Optional[TransformerConfig] = None,
               is_test: bool = False, unroll: int = 1):
    """Same model as build() with the layer stacks rolled into scan ops.
    Parameters are stacked per weight kind (``enc_stack_*_stacked``
    [n_layer, ...]); use ``stack_weights_from_layers`` to map build()'s
    per-layer weights onto them for parity checks.

    ``unroll``: layers per scan-loop iteration (chunked scan). 1 = max
    compile-time savings; n_layer = full unroll inside the scan op
    (near-build() step time, keeps the stacked-parameter layout)."""
    cfg = cfg or base()
    (src, trg, lbl, src_pad, trg_pad,
     enc_bias, dec_self_bias) = _train_feeds_and_biases()

    enc_in = _embed(src, cfg.src_vocab_size, cfg, "src_emb.w", "src_pos.w",
                    is_test)

    def enc_body(x, w):
        attn = _w_attention(
            _w_ln(x, w["preattn_ln.scale"], w["preattn_ln.bias"]), None,
            enc_bias, cfg,
            {"qkv.w": w["qkv.w"], "qkv.b": w["qkv.b"],
             "out.w": w["out.w"], "out.b": w["out.b"]},
            is_test, fused_qkv=True)
        x = _w_drop_add(attn, x, cfg, is_test)
        ff = _w_fc(
            _w_ln(x, w["preffn_ln.scale"], w["preffn_ln.bias"]),
            w["ffn1.w"], w["ffn1.b"], act="relu")
        if cfg.dropout and not is_test:
            ff = layers.dropout(ff, cfg.dropout, is_test=is_test,
                                dropout_implementation="upscale_in_train")
        ff = _w_fc(ff, w["ffn2.w"], w["ffn2.b"])
        return _w_drop_add(ff, x, cfg, is_test)

    enc = _layer_scan(enc_in, cfg, _enc_weight_specs(cfg), enc_body,
                      "enc_stack", is_test,
                      batch_vars=(enc_bias.name,), unroll=unroll)
    enc = _ln(enc, "enc_post")

    dec_in = _embed(trg, cfg.trg_vocab_size, cfg, "trg_emb.w", "trg_pos.w",
                    is_test)

    def dec_body(x, w):
        # build()'s decoder self-attention projects q/k/v separately (its
        # two _ln calls are distinct vars, so the fused-qkv branch never
        # fires there) — mirror that exactly for weight-level parity
        ln_self = _w_ln(x, w["preself_ln.scale"], w["preself_ln.bias"])
        attn = _w_attention(
            ln_self, ln_self, dec_self_bias, cfg,
            {"q.w": w["self_q.w"], "q.b": w["self_q.b"],
             "k.w": w["self_k.w"], "k.b": w["self_k.b"],
             "v.w": w["self_v.w"], "v.b": w["self_v.b"],
             "out.w": w["self_out.w"], "out.b": w["self_out.b"]},
            is_test, fused_qkv=False, causal=True)
        x = _w_drop_add(attn, x, cfg, is_test)
        ln_x = _w_ln(x, w["precross_ln.scale"], w["precross_ln.bias"])
        cross = _w_attention(
            ln_x, enc, enc_bias, cfg,
            {"q.w": w["q.w"], "q.b": w["q.b"], "k.w": w["k.w"],
             "k.b": w["k.b"], "v.w": w["v.w"], "v.b": w["v.b"],
             "out.w": w["cross_out.w"], "out.b": w["cross_out.b"]},
            is_test, fused_qkv=False)
        x = _w_drop_add(cross, x, cfg, is_test)
        ff = _w_fc(
            _w_ln(x, w["preffn_ln.scale"], w["preffn_ln.bias"]),
            w["ffn1.w"], w["ffn1.b"], act="relu")
        if cfg.dropout and not is_test:
            ff = layers.dropout(ff, cfg.dropout, is_test=is_test,
                                dropout_implementation="upscale_in_train")
        ff = _w_fc(ff, w["ffn2.w"], w["ffn2.b"])
        return _w_drop_add(ff, x, cfg, is_test)

    dec = _layer_scan(dec_in, cfg, _dec_weight_specs(cfg), dec_body,
                      "dec_stack", is_test,
                      batch_vars=(dec_self_bias.name, enc_bias.name,
                                  enc.name), unroll=unroll)
    dec = _ln(dec, "dec_post")

    logits, token_count, loss = _loss_head(dec, lbl, trg_pad, cfg)
    return {
        "feeds": [src, trg, lbl, src_pad, trg_pad],
        "loss": loss,
        "logits": logits,
        "token_count": token_count,
        "config": cfg,
    }


# --- serving-plane programs: prefill + single-token KV-cache decode ---
#
# build_decode() above re-runs the decoder over the full prefix every
# step (O(T^2) per emitted token) and owns its whole batch for the whole
# decode — fine for offline translation, wrong for serving. The serving
# split (serving.py ServingEngine) compiles TWO programs per engine:
#
# - build_prefill: admit ONE request into a batch *slot* — run the
#   encoder once, project every decoder layer's cross-attention K/V, and
#   write them (plus reset per-slot decode state) into slot-indexed
#   persistable cache tensors that stay device-resident between steps.
# - build_decode_step: ONE token for EVERY slot — embed each slot's
#   current token at its own position, append this step's self-attention
#   K/V rows to the on-device cache (ops/serving_ops.py kv_cache_write),
#   attend over the per-slot visible prefix (kv_step_bias), and emit the
#   greedy next token, all as one fixed-shape XLA computation. O(T) per
#   token, one compiled executable for any mix of in-flight requests.
#
# Cache state (per engine, shapes from serving_state_specs) carries
# through the executor's ordinary donated-state path: the executor
# gathers the persistable vars from the serving scope, donates them to
# XLA (in-place update on device), and commits the returned buffers —
# the KV cache never round-trips through the host.


def serving_state_specs(cfg: TransformerConfig, slots: int, src_len: int,
                        max_len: int) -> Dict[str, tuple]:
    """name -> (shape, numpy dtype) for the engine's device-resident
    serving state. ``serve_k/v{i}`` are the decoder self-attention KV
    rings (slot x position), ``serve_ck/cv{i}`` the per-request
    cross-attention K/V written at prefill, plus per-slot scalars:
    current token, its position, and the live flag."""
    h, dh = cfg.n_head, cfg.d_head
    specs: Dict[str, tuple] = {
        "serve_cur_ids": ((slots,), "int64"),
        "serve_pos": ((slots,), "int64"),
        "serve_live": ((slots,), "bool"),
        "serve_cross_bias": ((slots, 1, 1, src_len), "float32"),
    }
    for i in range(cfg.n_layer):
        specs[f"serve_k{i}"] = ((slots, max_len, h, dh), cfg.dtype)
        specs[f"serve_v{i}"] = ((slots, max_len, h, dh), cfg.dtype)
        specs[f"serve_ck{i}"] = ((slots, src_len, h, dh), cfg.dtype)
        specs[f"serve_cv{i}"] = ((slots, src_len, h, dh), cfg.dtype)
    return specs


def _serve_state_vars(cfg, slots, src_len, max_len):
    """Declare the serving-state vars (persistable: the executor reads
    them from the engine's scope and donates their buffers) in the
    current program."""
    block = fluid.default_main_program().global_block()
    out = {}
    for name, (shape, dtype) in serving_state_specs(
            cfg, slots, src_len, max_len).items():
        out[name] = block.create_var(
            name=name, shape=list(shape), dtype=dtype, persistable=True,
            stop_gradient=True)
    return out


def build_prefill(cfg: Optional[TransformerConfig] = None, slots: int = 4,
                  src_len: int = 32, max_len: int = 32, bos_id: int = 0):
    """Admission program: encode one request and install it into a slot.

    Feeds: src_ids [1, src_len] int64, src_pad_mask [1, src_len] f32,
    slot [1] int64 (the batch slot this request occupies). Writes the
    slot's cross-attention K/V + bias rows and resets its decode state
    (cur=BOS at position 0, live). No fetches — admission is a pure
    device-state update."""
    from paddle_tpu.layer_helper import LayerHelper

    cfg = cfg or base()
    if src_len > cfg.max_length or max_len > cfg.max_length:
        raise ValueError(
            f"src_len/max_len ({src_len}/{max_len}) exceed the position "
            f"table (max_length={cfg.max_length})")
    src = layers.data("src_ids", shape=[src_len], dtype="int64")
    src_pad = layers.data("src_pad_mask", shape=[src_len], dtype="float32")
    slot = layers.data("slot", shape=[1], dtype="int64",
                       append_batch_size=False)
    state = _serve_state_vars(cfg, slots, src_len, max_len)
    helper = LayerHelper("prefill")

    def _slot_update(cache_var, value):
        # cache[slot] = value (scalar slot index: the dynamic_update op)
        out = helper.create_variable_for_type_inference(cache_var.dtype,
                                                        True)
        helper.append_op(
            "dynamic_update",
            inputs={"X": cache_var, "Index": slot, "Value": value},
            outputs={"Out": out})
        layers.assign(out, output=cache_var)

    enc, enc_bias = _encode_source(src, src_pad, cfg)  # [1, s, d]
    h, dh = cfg.n_head, cfg.d_head
    for i in range(cfg.n_layer):
        # cross-attention K/V projected ONCE per request at admission
        # (build_decode recomputes them from enc every step)
        k = _fc(enc, cfg.d_model, f"dec{i}_cross_k", "colp")
        v = _fc(enc, cfg.d_model, f"dec{i}_cross_v", "colp")
        # [1, s, d] -> [s, h, dh] (batch is literally 1 at admission)
        k = layers.reshape(k, [-1, h, dh])
        v = layers.reshape(v, [-1, h, dh])
        _slot_update(state[f"serve_ck{i}"], k)
        _slot_update(state[f"serve_cv{i}"], v)
    _slot_update(state["serve_cross_bias"],
                 layers.reshape(enc_bias, [1, 1, -1]))  # [1, 1, s] row
    # slot decode state: BOS at position 0, live
    _scatter_reset = [
        ("serve_cur_ids", layers.fill_constant([1], "int64",
                                               float(bos_id))),
        ("serve_pos", layers.fill_constant([1], "int64", 0.0)),
        ("serve_live", layers.fill_constant([1], "bool", 1.0)),
    ]
    for name, updates in _scatter_reset:
        new = layers.scatter(state[name], slot, updates)
        layers.assign(new, output=state[name])
    return {"feeds": [src, src_pad, slot], "state": state, "config": cfg}


def build_decode_step(cfg: Optional[TransformerConfig] = None,
                      slots: int = 4, src_len: int = 32, max_len: int = 32,
                      end_id: int = 1):
    """One greedy decode token for every slot, against the on-device KV
    cache. Feed: active_mask [slots] bool (host-side admission/eviction
    control — a slot the host has evicted decodes as dead whatever the
    device live flag says). Fetches: emitted token [slots] int64, live
    [slots] bool (False = finished: EOS or length cap), position
    [slots] int64 of the emitted token, and max |logit| per slot
    (f32 — non-finite marks the slot poisoned; serving evicts it)."""
    from paddle_tpu.layer_helper import LayerHelper

    cfg = cfg or base()
    d, h, dh = cfg.d_model, cfg.n_head, cfg.d_head
    active = layers.data("active_mask", shape=[slots], dtype="bool",
                         append_batch_size=False)
    state = _serve_state_vars(cfg, slots, src_len, max_len)
    cur, pos, live = (state["serve_cur_ids"], state["serve_pos"],
                      state["serve_live"])
    helper = LayerHelper("decode_step")

    # embed each slot's current token at its own position (the training
    # graph's _embed, with position_ids replaced by the per-slot pos)
    emb = layers.embedding(
        layers.unsqueeze(cur, [1]), size=[cfg.trg_vocab_size, d],
        param_attr=ParamAttr(
            name="trg_emb.w",
            initializer=fluid.initializer.NormalInitializer(
                0.0, cfg.d_model ** -0.5)))
    emb = layers.scale(emb, scale=d ** 0.5)
    pemb = layers.embedding(
        layers.unsqueeze(pos, [1]), size=[cfg.max_length, d],
        param_attr=ParamAttr(
            name="trg_pos.w",
            initializer=fluid.initializer.NumpyArrayInitializer(
                _positional_encoding(cfg.max_length, cfg.d_model)),
            trainable=False))
    x = layers.elementwise_add(emb, pemb)  # [S, 1, d]

    # per-slot causal bias over the self-attention cache: position j
    # visible iff j <= pos[s] (stale rows from a slot's previous
    # occupant sit above pos and stay masked)
    step_bias = helper.create_variable_for_type_inference("float32", True)
    helper.append_op("kv_step_bias", inputs={"Pos": pos},
                     outputs={"Out": step_bias},
                     attrs={"length": int(max_len)})

    def split_heads(z):
        return layers.reshape(z, [0, 0, h, dh])

    def cache_append(cache_var, row):
        # cache[s, pos[s]] = row[s] — then attend the UPDATED cache so
        # the current token sees its own K/V (full-prefix semantics)
        out = helper.create_variable_for_type_inference(cache_var.dtype,
                                                        True)
        helper.append_op("kv_cache_write",
                         inputs={"Cache": cache_var, "New": row,
                                 "Pos": pos},
                         outputs={"Out": out})
        layers.assign(out, output=cache_var)
        return out

    for i in range(cfg.n_layer):
        p = f"dec{i}"
        # self-attention against the slot's KV ring
        ln_x = _ln(x, f"{p}_preself")
        q = split_heads(_fc(ln_x, d, f"{p}_self_q", "colp"))
        kc = cache_append(state[f"serve_k{i}"],
                          split_heads(_fc(ln_x, d, f"{p}_self_k", "colp")))
        vc = cache_append(state[f"serve_v{i}"],
                          split_heads(_fc(ln_x, d, f"{p}_self_v", "colp")))
        ctx = _w_sdpa(q, kc, vc, step_bias, cfg, True)
        attn = _fc(layers.reshape(ctx, [0, 0, d]), d, f"{p}_self_out",
                   "rowp")
        x = layers.elementwise_add(attn, x)
        # cross-attention against the prefill-cached encoder K/V
        ln_x = _ln(x, f"{p}_precross")
        q = split_heads(_fc(ln_x, d, f"{p}_cross_q", "colp"))
        ctx = _w_sdpa(q, state[f"serve_ck{i}"], state[f"serve_cv{i}"],
                      state["serve_cross_bias"], cfg, True)
        cross = _fc(layers.reshape(ctx, [0, 0, d]), d, f"{p}_cross_out",
                    "rowp")
        x = layers.elementwise_add(cross, x)
        ff = _ffn(_ln(x, f"{p}_preffn"), cfg, p, True)
        x = layers.elementwise_add(ff, x)
    x = _ln(x, "dec_post")
    logits = layers.fc(
        x, cfg.trg_vocab_size, num_flatten_dims=2,
        param_attr=ParamAttr(name="proj_colp.w"), bias_attr=False,
    )
    flat = layers.reshape(logits, [slots, cfg.trg_vocab_size])
    nxt = layers.argmax(flat, axis=-1)  # [S] int64, greedy
    # per-slot poison probe: max |logit| per slot (NaN/Inf propagate
    # through the max) — the serving plane checks np.isfinite on the
    # host and evicts ONLY the poisoned slot(s), the decode-path twin of
    # the numerics plane's nonfinite/maxabs reduction
    maxabs = layers.reduce_max(layers.abs(flat), dim=1)  # [S] f32
    # the greedy token's own logit (the row max — argmax's value): the
    # request-trace plane samples it onto decode-step trace events so a
    # request's track shows WHAT was emitted and how confident the head
    # was, without a second device round-trip
    score = layers.reduce_max(flat, dim=1)  # [S] f32

    # liveness: host mask AND device EOS/length tracking. A dead slot
    # freezes (emits end_id, position pinned) until the next prefill
    # re-arms it.
    end_const = layers.fill_constant([slots], "int64", float(end_id))
    live_now = layers.logical_and(live, active)
    emit = layers.where(live_now, nxt, end_const)
    new_live = layers.logical_and(
        live_now, layers.logical_not(layers.equal(emit, end_const)))
    limit = layers.fill_constant([slots], "int64", float(max_len - 1))
    new_live = layers.logical_and(new_live, layers.less_than(pos, limit))
    emit_pos = layers.elementwise_add(
        pos, layers.cast(live_now, "int64"))  # position the token holds
    layers.assign(emit, output=cur)
    layers.assign(emit_pos, output=pos)
    layers.assign(new_live, output=live)
    return {"feeds": [active], "emit": emit, "live": new_live,
            "pos": emit_pos, "maxabs": maxabs, "score": score,
            "state": state, "config": cfg}


def build_slot_scrub(cfg: Optional[TransformerConfig] = None,
                     slots: int = 4, src_len: int = 32,
                     max_len: int = 32):
    """Zero ONE slot's row in every device-resident serving tensor, on
    device (serving.py's poisoned-slot eviction: a stale non-finite K/V
    row would re-poison the slot's next occupant through the softmax
    mask, and a host round-trip of the full caches to zero one row
    would stall the decode loop). Feed: slot [1] int64. No fetches —
    like prefill, a pure device-state update."""
    from paddle_tpu.layer_helper import LayerHelper

    cfg = cfg or base()
    slot = layers.data("slot", shape=[1], dtype="int64",
                       append_batch_size=False)
    state = _serve_state_vars(cfg, slots, src_len, max_len)
    helper = LayerHelper("slot_scrub")
    for name, (shape, dtype) in serving_state_specs(
            cfg, slots, src_len, max_len).items():
        var = state[name]
        if len(shape) == 1:
            # per-slot scalar (cur/pos/live): scatter one zero element
            new = layers.scatter(
                var, slot, layers.fill_constant([1], dtype, 0.0))
            layers.assign(new, output=var)
        else:
            # cache row: cache[slot] = zeros(shape[1:]) (the prefill
            # _slot_update idiom)
            zero = layers.fill_constant(list(shape[1:]), dtype, 0.0)
            out = helper.create_variable_for_type_inference(var.dtype,
                                                            True)
            helper.append_op(
                "dynamic_update",
                inputs={"X": var, "Index": slot, "Value": zero},
                outputs={"Out": out})
            layers.assign(out, output=var)
    return {"feeds": [slot], "state": state, "config": cfg}


_serving_prog_cache: Dict[tuple, dict] = {}


def build_serving(cfg: TransformerConfig, slots: int, src_len: int,
                  max_len: int, bos_id: int = 0, end_id: int = 1) -> dict:
    """Build (or return cached) the serving program pair for this
    (config, geometry). Engines sharing a geometry share program
    OBJECTS — their executors' compile caches then key per scope, and
    the persistent compile cache sees content-identical programs across
    replicas (the warm-replica start path)."""
    key = (
        cfg.src_vocab_size, cfg.trg_vocab_size, cfg.d_model, cfg.d_inner,
        cfg.n_head, cfg.n_layer, cfg.max_length, cfg.dtype,
        slots, src_len, max_len, bos_id, end_id,
    )
    cached = _serving_prog_cache.get(key)
    if cached is not None:
        return cached
    prefill_prog, decode_prog = fluid.Program(), fluid.Program()
    scrub_prog = fluid.Program()
    with fluid.program_guard(prefill_prog, fluid.Program()):
        prefill = build_prefill(cfg, slots=slots, src_len=src_len,
                                max_len=max_len, bos_id=bos_id)
    with fluid.program_guard(decode_prog, fluid.Program()):
        decode = build_decode_step(cfg, slots=slots, src_len=src_len,
                                   max_len=max_len, end_id=end_id)
    with fluid.program_guard(scrub_prog, fluid.Program()):
        scrub = build_slot_scrub(cfg, slots=slots, src_len=src_len,
                                 max_len=max_len)
    entry = {
        "prefill_program": prefill_prog, "prefill": prefill,
        "decode_program": decode_prog, "decode": decode,
        "scrub_program": scrub_prog, "scrub": scrub,
        "state_specs": serving_state_specs(cfg, slots, src_len, max_len),
        "config": cfg,
    }
    _serving_prog_cache[key] = entry
    return entry


def stack_weights_from_layers(cfg, per_layer_scope, scan_scope):
    """Copy build()-style per-layer weights into build_scan()'s stacked
    parameters (for parity tests / migration)."""
    for prefix, specs in (("enc_stack", _enc_weight_specs(cfg)),
                          ("dec_stack", _dec_weight_specs(cfg))):
        for key, _shape, src_fn in specs:
            stack = np.stack([
                np.asarray(per_layer_scope.find_var(src_fn(i)))
                for i in range(cfg.n_layer)
            ])
            scan_scope.set(f"{prefix}_{key}_stacked", stack)
