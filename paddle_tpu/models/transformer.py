"""Transformer NMT model (flagship).

Capability parity with the reference's Transformer benchmark model
(reference: python/paddle/fluid/tests/unittests/dist_transformer.py:1331,
Transformer-base on WMT16 en-de), built TPU-first:

- Dense padded batches + additive attention-bias tensors instead of LoD.
- Parameter names follow a tensor-parallel convention consumed by
  parallel/strategy.py regex rules: column-parallel weights (`*_colp.w_*`)
  shard their output dim over the 'model' mesh axis, row-parallel weights
  (`*_rowp.w_*`) shard their input dim; GSPMD inserts the all-reduces.
- Everything is ordinary Program-IR ops, so the whole train step (fwd +
  autodiff + Adam) compiles to one XLA computation.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.param_attr import ParamAttr


class TransformerConfig:
    """Transformer-base hyperparameters (matching the reference benchmark
    config in dist_transformer.py ModelHyperParams)."""

    def __init__(
        self,
        src_vocab_size: int = 10000,
        trg_vocab_size: int = 10000,
        max_length: int = 256,
        d_model: int = 512,
        d_inner: int = 2048,
        n_head: int = 8,
        n_layer: int = 6,
        dropout: float = 0.1,
        label_smooth_eps: float = 0.1,
        dtype: str = "float32",
    ):
        self.src_vocab_size = src_vocab_size
        self.trg_vocab_size = trg_vocab_size
        self.max_length = max_length
        self.d_model = d_model
        self.d_inner = d_inner
        self.n_head = n_head
        self.n_layer = n_layer
        self.dropout = dropout
        self.label_smooth_eps = label_smooth_eps
        self.dtype = dtype

    @property
    def d_head(self):
        return self.d_model // self.n_head


def base() -> TransformerConfig:
    return TransformerConfig()


def _pname(prefix: str, kind: str) -> ParamAttr:
    # kind: colp (column-parallel), rowp (row-parallel), repl (replicated)
    return ParamAttr(name=f"{prefix}_{kind}.w")


def _fc(x, size, prefix, kind, act=None, num_flatten_dims=2):
    return layers.fc(
        x,
        size,
        num_flatten_dims=num_flatten_dims,
        param_attr=ParamAttr(name=f"{prefix}_{kind}.w"),
        bias_attr=ParamAttr(name=f"{prefix}_{kind}.b"),
        act=act,
    )


def _positional_encoding(max_len: int, d_model: int) -> np.ndarray:
    """Sinusoidal table (reference: dist_transformer.py position_encoding_init)."""
    pos = np.arange(max_len)[:, None].astype(np.float64)
    i = np.arange(d_model // 2)[None, :].astype(np.float64)
    angle = pos / np.power(10000.0, 2 * i / d_model)
    table = np.zeros((max_len, d_model), dtype=np.float32)
    table[:, 0::2] = np.sin(angle)
    table[:, 1::2] = np.cos(angle)
    return table


def _multi_head_attention(q_in, kv_in, bias, cfg: TransformerConfig, prefix: str,
                          is_test: bool):
    h, dh, d = cfg.n_head, cfg.d_head, cfg.d_model

    def split_heads(x):
        x = layers.reshape(x, [0, 0, h, dh])
        return layers.transpose(x, [0, 2, 1, 3])  # [b, h, t, dh]

    if q_in is kv_in:
        # self-attention: one fused [d, 3d] projection (one MXU pass
        # instead of three; the reference emits separate q/k/v fcs)
        qkv = _fc(q_in, 3 * d, f"{prefix}_qkv", "colp")
        q, k, v = layers.split(qkv, 3, dim=-1)
    else:
        q = _fc(q_in, d, f"{prefix}_q", "colp")
        k = _fc(kv_in, d, f"{prefix}_k", "colp")
        v = _fc(kv_in, d, f"{prefix}_v", "colp")
    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper(f"{prefix}_sdpa")
    ctx = helper.create_variable_for_type_inference(dtype=cfg.dtype)
    # logsumexp rows, consumed by the paired grad op (DCE'd at inference)
    lse = helper.create_variable_for_type_inference(dtype="float32")
    lse.stop_gradient = True
    inputs = {"Q": q, "K": k, "V": v}
    if bias is not None:
        inputs["Bias"] = bias
    helper.append_op(
        "scaled_dot_product_attention",
        inputs=inputs,
        outputs={"Out": ctx, "Lse": lse},
        attrs={
            "scale": 1.0 / math.sqrt(dh),
            "dropout_prob": float(cfg.dropout),
            "is_test": is_test,
        },
    )
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    ctx = layers.reshape(ctx, [0, 0, d])
    return _fc(ctx, d, f"{prefix}_out", "rowp")


def _ffn(x, cfg: TransformerConfig, prefix: str, is_test: bool):
    h = _fc(x, cfg.d_inner, f"{prefix}_ffn1", "colp", act="relu")
    if cfg.dropout and not is_test:
        h = layers.dropout(h, cfg.dropout, is_test=is_test,
                           dropout_implementation="upscale_in_train")
    return _fc(h, cfg.d_model, f"{prefix}_ffn2", "rowp")


def _pre_post(x, residual, cfg, prefix, is_test):
    """post-norm residual block wiring (reference uses preprocess 'n',
    postprocess 'da': norm -> sublayer -> dropout -> add)."""
    out = x
    if cfg.dropout and not is_test:
        out = layers.dropout(out, cfg.dropout, is_test=is_test,
                             dropout_implementation="upscale_in_train")
    out = layers.elementwise_add(out, residual)
    return out


def _ln(x, prefix):
    return layers.layer_norm(
        x, begin_norm_axis=2,
        param_attr=ParamAttr(name=f"{prefix}_ln.scale"),
        bias_attr=ParamAttr(name=f"{prefix}_ln.bias"),
    )


def _embed(ids, vocab, cfg: TransformerConfig, name: str, pos_table_name: str,
           is_test: bool):
    emb = layers.embedding(
        ids, size=[vocab, cfg.d_model],
        param_attr=ParamAttr(
            name=name,
            initializer=fluid.initializer.NormalInitializer(
                0.0, cfg.d_model ** -0.5),
        ),
    )
    emb = layers.scale(emb, scale=cfg.d_model ** 0.5)
    pos = layers.embedding(
        _position_ids(ids), size=[cfg.max_length, cfg.d_model],
        param_attr=ParamAttr(
            name=pos_table_name,
            initializer=fluid.initializer.NumpyArrayInitializer(
                _positional_encoding(cfg.max_length, cfg.d_model)
            ),
            trainable=False,
        ),
    )
    x = layers.elementwise_add(emb, pos)
    if cfg.dropout and not is_test:
        x = layers.dropout(x, cfg.dropout, is_test=is_test,
                           dropout_implementation="upscale_in_train")
    return x


def _position_ids(ids):
    """[b, t] int positions built from ops (static shapes at trace time)."""
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("pos_ids")
    out = helper.create_variable_for_type_inference(dtype="int64",
                                                    stop_gradient=True)
    helper.append_op("position_ids", inputs={"X": ids}, outputs={"Out": out})
    return out


def encoder_layer(x, bias, cfg, i, is_test):
    p = f"enc{i}"
    ln_x = _ln(x, f"{p}_preattn")
    attn = _multi_head_attention(ln_x, ln_x, bias, cfg, f"{p}_attn", is_test)
    x = _pre_post(attn, x, cfg, p, is_test)
    ff = _ffn(_ln(x, f"{p}_preffn"), cfg, p, is_test)
    return _pre_post(ff, x, cfg, p, is_test)


def decoder_layer(x, enc_out, self_bias, cross_bias, cfg, i, is_test):
    p = f"dec{i}"
    attn = _multi_head_attention(_ln(x, f"{p}_preself"), _ln(x, f"{p}_preself"),
                                 self_bias, cfg, f"{p}_self", is_test)
    x = _pre_post(attn, x, cfg, p, is_test)
    ln_x = _ln(x, f"{p}_precross")
    cross = _multi_head_attention(ln_x, enc_out, cross_bias, cfg,
                                  f"{p}_cross", is_test)
    x = _pre_post(cross, x, cfg, p, is_test)
    ff = _ffn(_ln(x, f"{p}_preffn"), cfg, p, is_test)
    return _pre_post(ff, x, cfg, p, is_test)


def build(cfg: Optional[TransformerConfig] = None, is_test: bool = False):
    """Builds the full training graph in the current main/startup programs.

    Feeds: src_ids[b,s], trg_ids[b,t], lbl_ids[b,t], src_mask[b,1,1,s] (1 =
    real token), trg_mask is derived causally inside. Returns dict of key
    variables."""
    cfg = cfg or base()
    src = layers.data("src_ids", shape=[-1], dtype="int64",
                      append_batch_size=True)
    trg = layers.data("trg_ids", shape=[-1], dtype="int64")
    lbl = layers.data("lbl_ids", shape=[-1], dtype="int64")
    src_pad = layers.data("src_pad_mask", shape=[-1], dtype="float32")  # [b,s] 1=real
    trg_pad = layers.data("trg_pad_mask", shape=[-1], dtype="float32")  # [b,t]

    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("attn_bias")
    enc_bias = helper.create_variable_for_type_inference("float32", True)
    helper.append_op("attn_bias", inputs={"PadMask": src_pad},
                     outputs={"Out": enc_bias}, attrs={"causal": False})
    dec_self_bias = helper.create_variable_for_type_inference("float32", True)
    helper.append_op("attn_bias", inputs={"PadMask": trg_pad},
                     outputs={"Out": dec_self_bias}, attrs={"causal": True})
    cross_bias = enc_bias  # same src padding bias, broadcast over query dim

    enc = _embed(src, cfg.src_vocab_size, cfg, "src_emb.w", "src_pos.w", is_test)
    for i in range(cfg.n_layer):
        enc = encoder_layer(enc, enc_bias, cfg, i, is_test)
    enc = _ln(enc, "enc_post")

    dec = _embed(trg, cfg.trg_vocab_size, cfg, "trg_emb.w", "trg_pos.w", is_test)
    for i in range(cfg.n_layer):
        dec = decoder_layer(dec, enc, dec_self_bias, cross_bias, cfg, i, is_test)
    dec = _ln(dec, "dec_post")

    logits = layers.fc(
        dec, cfg.trg_vocab_size, num_flatten_dims=2,
        param_attr=ParamAttr(name="proj_colp.w"), bias_attr=False,
    )

    if cfg.label_smooth_eps:
        smooth = layers.label_smooth(
            layers.one_hot(lbl, cfg.trg_vocab_size),
            epsilon=cfg.label_smooth_eps,
        )
        ce = layers.softmax_with_cross_entropy(logits, smooth, soft_label=True)
    else:
        ce = layers.softmax_with_cross_entropy(
            logits, layers.unsqueeze(lbl, [2])
        )
    # [b, t, 1] -> [b, t]; mask padding, normalize by real token count
    ce = layers.reshape(ce, [0, -1])
    masked = layers.elementwise_mul(ce, trg_pad)
    token_count = layers.reduce_sum(trg_pad)
    loss = layers.elementwise_div(
        layers.reduce_sum(masked), layers.elementwise_max(
            token_count, layers.fill_constant_like(token_count, 1.0))
    )
    return {
        "feeds": [src, trg, lbl, src_pad, trg_pad],
        "loss": loss,
        "logits": logits,
        "token_count": token_count,
        "config": cfg,
    }


def make_batch(cfg: TransformerConfig, batch: int, src_len: int, trg_len: int,
               seed: int = 0) -> Dict[str, np.ndarray]:
    """Synthetic padded batch matching the feed contract."""
    r = np.random.RandomState(seed)
    src = r.randint(3, cfg.src_vocab_size, (batch, src_len)).astype(np.int64)
    trg = r.randint(3, cfg.trg_vocab_size, (batch, trg_len)).astype(np.int64)
    lbl = r.randint(3, cfg.trg_vocab_size, (batch, trg_len)).astype(np.int64)
    src_lens = r.randint(src_len // 2, src_len + 1, batch)
    trg_lens = r.randint(trg_len // 2, trg_len + 1, batch)
    src_pad = (np.arange(src_len)[None, :] < src_lens[:, None]).astype(np.float32)
    trg_pad = (np.arange(trg_len)[None, :] < trg_lens[:, None]).astype(np.float32)
    return {
        "src_ids": src * src_pad.astype(np.int64),
        "trg_ids": trg * trg_pad.astype(np.int64),
        "lbl_ids": lbl,
        "src_pad_mask": src_pad,
        "trg_pad_mask": trg_pad,
    }


# --- beam-search decoding (reference: operators/beam_search_op.cc driven by
# a while loop in the NMT infer program; here the whole decode loop is one
# `while` op lowered to lax.while_loop, so the entire beam search compiles
# into a single XLA computation) ---


def build_decode(cfg: Optional[TransformerConfig] = None, beam_size: int = 4,
                 max_len: int = 32, src_len: int = 32, bos_id: int = 0,
                 end_id: int = 1):
    """Builds a beam-search translation graph in the current program.

    Feeds: src_ids [b, src_len] int64, src_pad_mask [b, src_len] f32
    (1 = real). Returns {"feeds", "ids" [b, K, max_len], "scores" [b, K],
    "config"}. ``src_len`` is static (XLA shape discipline); pad or bucket
    sources to it. Re-runs the decoder over the full (static-shape) prefix
    each step — O(T^2) per step like the reference's cache-less while-loop
    decoder.
    """
    from paddle_tpu.layer_helper import LayerHelper

    cfg = cfg or base()
    k, t_max, s_len = int(beam_size), int(max_len), int(src_len)
    src = layers.data("src_ids", shape=[s_len], dtype="int64")
    src_pad = layers.data("src_pad_mask", shape=[s_len], dtype="float32")

    helper = LayerHelper("beam_decode")

    def _op(op_type, inputs, attrs=None, dtype="float32", n_out=1,
            out_slot="Out"):
        outs = [helper.create_variable_for_type_inference(dtype, True)
                for _ in range(n_out)]
        helper.append_op(op_type, inputs=inputs,
                         outputs={out_slot: outs[0]} if n_out == 1 else None,
                         attrs=attrs or {})
        return outs[0]

    # encoder (shared weights with build() by parameter name)
    enc_bias = _op("attn_bias", {"PadMask": src_pad}, {"causal": False})
    enc = _embed(src, cfg.src_vocab_size, cfg, "src_emb.w", "src_pos.w", True)
    for i in range(cfg.n_layer):
        enc = encoder_layer(enc, enc_bias, cfg, i, True)
    enc = _ln(enc, "enc_post")

    # replicate encoder state per beam: [b,s,d] -> [b*K,s,d]
    enc_beam = layers.reshape(
        layers.expand(layers.unsqueeze(enc, [1]), [1, k, 1, 1]),
        [-1, s_len, cfg.d_model],
    )
    cross_beam = layers.reshape(
        layers.expand(layers.unsqueeze(enc_bias, [1]), [1, k, 1, 1, 1]),
        [-1, 1, 1, s_len],
    )

    # beam state init
    seed = _op("slice", {"X": src},
               {"axes": [1], "starts": [0], "ends": [1]}, dtype="int64")
    tmpl = layers.expand(layers.unsqueeze(seed, [2]), [1, k, t_max])
    ids = _op("fill_any_like", {"X": tmpl}, {"value": float(bos_id)},
              dtype="int64")
    zk = layers.cast(
        layers.squeeze(
            _op("slice", {"X": tmpl},
                {"axes": [2], "starts": [0], "ends": [1]}, dtype="int64"),
            [2]),
        "float32")
    zeros_bk = _op("fill_any_like", {"X": zk}, {"value": 0.0})
    beam_mask = _op(
        "assign_value", {},
        {"shape": [k], "dtype": "float32",
         "values": [0.0] + [-1e9] * (k - 1)})
    scores = layers.elementwise_add(zeros_bk, beam_mask)
    finished = layers.cast(zeros_bk, "bool")

    t = layers.fill_constant([1], "int64", 1)
    n_total = layers.reduce_sum(
        _op("fill_any_like", {"X": zeros_bk}, {"value": 1.0}))
    t_lim = layers.fill_constant([1], "int64", t_max)
    cond = layers.less_than(t, t_lim)

    from paddle_tpu.layers.control_flow import While

    with While(cond).block():
        # time mask: positions < t are live
        tpos = _op("range", {}, {"start": 0, "end": t_max, "dtype": "int64"},
                   dtype="int64")
        live = layers.cast(layers.less_than(tpos, t), "float32")  # [T]
        ids_flat = layers.reshape(ids, [-1, t_max])
        trg_pad = layers.elementwise_mul(
            layers.cast(_op("fill_any_like", {"X": ids_flat}, {"value": 1.0},
                            dtype="int64"), "float32"),
            live)
        self_bias = _op("attn_bias", {"PadMask": trg_pad}, {"causal": True})
        dec = _embed(ids_flat, cfg.trg_vocab_size, cfg, "trg_emb.w",
                     "trg_pos.w", True)
        for i in range(cfg.n_layer):
            dec = decoder_layer(dec, enc_beam, self_bias, cross_beam, cfg, i,
                                True)
        dec = _ln(dec, "dec_post")
        # logits at the last generated position (t-1)
        tm1 = layers.increment(t, value=-1.0, in_place=False)
        dec_t = _op("dynamic_slice",
                    {"X": layers.transpose(dec, [1, 0, 2]), "Index": tm1})
        logits = layers.fc(
            dec_t, cfg.trg_vocab_size, num_flatten_dims=1,
            param_attr=ParamAttr(name="proj_colp.w"), bias_attr=False,
        )
        logp = layers.reshape(layers.log_softmax(logits),
                              [-1, k, cfg.trg_vocab_size])

        new_ids = helper.create_variable_for_type_inference("int64", True)
        new_scores = helper.create_variable_for_type_inference("float32", True)
        new_fin = helper.create_variable_for_type_inference("bool", True)
        parent = helper.create_variable_for_type_inference("int64", True)
        helper.append_op(
            "beam_search_step",
            inputs={"Ids": ids, "Scores": scores, "LogProbs": logp,
                    "Finished": finished, "StepIdx": t},
            outputs={"Ids": new_ids, "Scores": new_scores,
                     "Finished": new_fin, "Parent": parent},
            attrs={"end_id": end_id},
        )
        layers.assign(new_ids, output=ids)
        layers.assign(new_scores, output=scores)
        layers.assign(new_fin, output=finished)

        layers.increment(t, value=1.0, in_place=True)
        n_fin = layers.reduce_sum(layers.cast(finished, "float32"))
        layers.assign(
            layers.logical_and(layers.less_than(t, t_lim),
                               layers.less_than(n_fin, n_total)),
            output=cond)

    return {"feeds": [src, src_pad], "ids": ids, "scores": scores,
            "config": cfg}


_decode_prog_cache: Dict[tuple, tuple] = {}


def translate(exe, scope, src_ids: np.ndarray, src_pad: np.ndarray,
              cfg: Optional[TransformerConfig] = None, beam_size: int = 4,
              max_len: int = 32, bos_id: int = 0, end_id: int = 1):
    """Beam-decode a padded source batch with weights from ``scope``.

    The decode Program is cached per (config, beam, lengths) so repeated
    calls reuse the same program object and hit the Executor's compile
    cache. Returns (ids [b, K, max_len], scores [b, K]) as numpy arrays.
    """
    from paddle_tpu import executor as _executor

    cfg = cfg or base()
    key = (
        cfg.src_vocab_size, cfg.trg_vocab_size, cfg.d_model, cfg.d_inner,
        cfg.n_head, cfg.n_layer, cfg.max_length,
        beam_size, max_len, int(src_ids.shape[1]), bos_id, end_id,
    )
    cached = _decode_prog_cache.get(key)
    if cached is None:
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            dec = build_decode(cfg, beam_size=beam_size, max_len=max_len,
                               src_len=int(src_ids.shape[1]), bos_id=bos_id,
                               end_id=end_id)
        _decode_prog_cache[key] = (prog, dec)
    else:
        prog, dec = cached
    with _executor.scope_guard(scope):
        ids, scores = exe.run(
            prog,
            feed={"src_ids": src_ids, "src_pad_mask": src_pad},
            fetch_list=[dec["ids"], dec["scores"]],
        )
    return ids, scores
