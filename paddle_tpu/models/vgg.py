"""VGG-16 (reference: benchmark/fluid/models/vgg.py)."""

from __future__ import annotations

from paddle_tpu import layers


def conv_block(x, filters, n, is_test=False):
    for _ in range(n):
        x = layers.conv2d(x, filters, 3, padding=1, act="relu")
    return layers.pool2d(x, 2, "max", 2)


def vgg16(img, class_dim=1000, is_test=False, fc_dim=4096):
    x = conv_block(img, 64, 2, is_test)
    x = conv_block(x, 128, 2, is_test)
    x = conv_block(x, 256, 3, is_test)
    x = conv_block(x, 512, 3, is_test)
    x = conv_block(x, 512, 3, is_test)
    x = layers.fc(x, fc_dim, act="relu")
    if not is_test:
        x = layers.dropout(x, 0.5)
    x = layers.fc(x, fc_dim, act="relu")
    if not is_test:
        x = layers.dropout(x, 0.5)
    return layers.fc(x, class_dim)


def get_model(batch_size=32, data_shape=(3, 224, 224), class_dim=1000,
              is_test=False):
    img = layers.data("data", shape=list(data_shape), dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    fc_dim = 4096 if data_shape[-1] >= 224 else 512
    logits = vgg16(img, class_dim, is_test, fc_dim)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(logits, label)
    return {"feeds": [img, label], "loss": loss, "acc": acc, "logits": logits}
