"""Runtime telemetry plane: metrics, step logs, spans, compile reports,
the live /metrics endpoint, and the collective stall watchdog.

The reference framework shipped a real observability stack (RecordEvent
host spans + CUPTI DeviceTracer + tools/timeline.py chrome traces); this
module is its runtime-metrics half, grown past the reference: one
process-wide plane with three pillars.

1. **Metrics registry** — ``counter()``/``gauge()``/``histogram()`` return
   process-wide named instruments with optional labels. Every mutation
   checks one module-level boolean first, so with telemetry off (the
   default) a call costs a flag check and allocates nothing — hot paths
   (``Executor.run``) stay instrumented permanently. ``snapshot()``
   returns plain dicts; ``dump_metrics()`` exports Prometheus text or
   JSON.

2. **Structured step logs** — ``log_step(record)`` appends one JSONL
   record per executor step to the ``step_log_path`` flag's file. The
   schema is versioned (``STEP_LOG_SCHEMA_VERSION``) and documented
   field-by-field in ``STEP_LOG_FIELDS`` (also README "Observability").

3. **Span unification** — ``span(name)`` wraps
   ``profiler.record_event`` so host spans from the executor, trainer
   epoch/step events, fleet barrier waits, ring-attention rotations and
   pipeline schedules all land in ONE chrome-trace timeline under
   consistent dotted names; with telemetry on, every span additionally
   feeds the ``pt_span_seconds`` histogram (interval measured with
   ``time.perf_counter`` — wall clock is only ever used for
   human-readable timestamps).

Grown in PR 2 with the compile & memory observability plane:

4. **Compile reports** — ``record_compile_report`` stores one versioned
   JSON document per fresh executor compile (XLA flops / bytes accessed /
   device-memory breakdown, op-lowering histogram; schema in
   ``COMPILE_REPORT_FIELDS``), written under the ``compile_report_dir``
   flag and mirrored into ``pt_compile_*`` gauges.
   ``estimate_memory(program, feed_shapes)`` is the static pre-flight
   twin: a shape-table estimate that can warn BEFORE a compile that
   would blow the ``device_memory_budget_bytes`` flag.

5. **Live endpoint** — ``serve(port)`` (or the ``metrics_port`` flag)
   runs a stdlib ``http.server`` background thread on localhost with
   ``/metrics`` (Prometheus text), ``/healthz``, ``/steps`` (the bounded
   step ring buffer) and ``/compile`` (latest compile reports). Zero
   dependencies beyond the standard library.

6. **Stall watchdog** — ``stall_guard(name)`` arms a timer around
   blocking collectives (fleet barriers/rendezvous, ring-attention and
   pipeline dispatch); past the ``stall_timeout_ms`` deadline it
   increments ``pt_stall_total``, records a structured stall record
   carrying the active span stack + last step record, and (gated on
   ``stall_dump_dir``) dumps the flight recorder to disk.

Grown in PR 4 with the time-attribution plane:

7. **Step phases + boundedness verdict** — executors split every step
   into ``feed`` (host->device staging), ``dispatch`` (Python + tracing
   overhead), ``device`` (delta to ``jax.block_until_ready``) and
   ``fetch`` (device->host + decode); ``record_step_phases`` feeds the
   ``pt_step_phase_seconds`` histograms and a rolling window whose
   verdict (``input_bound`` / ``dispatch_bound`` / ``device_bound``)
   names the bottleneck. Input-pipeline consumer waits (reader queues,
   data_feeder batch assembly) accumulate via ``note_input_wait`` and
   weigh into the verdict, so a starved step is attributed to the input
   pipeline, not the device.

8. **Trace-event timeline** — every host span (via the
   ``profiler.record_event`` hook), step phase, compile and stall
   record becomes one Chrome-trace/Perfetto event in a bounded
   in-memory ring; ``export_trace()`` writes
   ``trace-<host>-<pid>.json`` under the ``trace_dir`` flag (also at
   process exit), the ``/trace`` route serves it live, and
   ``merge_traces()`` combines fleet-worker files onto per-rank tracks
   with clock-offset alignment.

Grown in PR 9 with the fleet observability plane:

9. **Fleet digests + cluster view** — the schema constants for the
   cross-rank metric digests workers publish into fleet KV
   (``FLEET_DIGEST_FIELDS``; assembly/aggregation lives in
   fleet_monitor.py), the ``/fleet`` cluster-view route and the merged
   ``/metrics?fleet=1`` Prometheus exposition, plus a ``/`` JSON index
   of every route.

10. **Device-memory watermarks + OOM forensics** —
    ``sample_device_memory`` reads guarded ``Device.memory_stats()``
    into ``pt_device_bytes_in_use/peak{device=}`` gauges every
    ``device_memory_every_n_steps`` executor steps (CPU / backends
    without the API degrade silently); ``maybe_record_oom`` turns a
    RESOURCE_EXHAUSTED failure during compile or run into a forensics
    report (compile-report peak bytes vs the budget flag, largest live
    buffers, recent step records) dumped under ``stall_dump_dir``.

Everything is off by default behind typed flags (flags.py); flipping
``telemetry`` at runtime takes effect immediately via a flag watcher,
and every disabled instrument call costs one module-level boolean check.
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import io
import json
import os
import queue
import sys
import threading
import time
import warnings
from typing import Any, Dict, Iterable, List, Optional, Tuple

from paddle_tpu import flags as _flags
from paddle_tpu import profiler as _profiler

# ---------------------------------------------------------------------------
# enable/disable plumbing
# ---------------------------------------------------------------------------

# THE fast-path flag: every instrument mutation reads this one module-level
# boolean and returns before touching any other state when it is False.
_enabled = False

_LOCK = threading.Lock()

# The step-log writer gets its OWN lock: log_step does disk I/O (write +
# flush per record) and must never stall metric mutations under _LOCK.
_STEP_LOG_LOCK = threading.Lock()

# step-log writer state (lazily opened; keyed by path so a flag change
# mid-process rotates to the new file)
_step_log_file: Optional[io.TextIOBase] = None
_step_log_path: str = ""
_step_seq = 0


def enabled() -> bool:
    """Whether telemetry is on (cached value of the ``telemetry`` flag)."""
    return _enabled


def _sync_from_flags(_value=None):
    global _enabled
    _enabled = bool(_flags.get_flag("telemetry"))


def enable(step_log_path: Optional[str] = None,
           metrics_dump_path: Optional[str] = None):
    """Convenience: flip the ``telemetry`` flag (and optionally the log /
    dump path flags) on. Equivalent to ``flags.set_flags({...})``."""
    new = {"telemetry": True}
    if step_log_path is not None:
        new["step_log_path"] = step_log_path
    if metrics_dump_path is not None:
        new["metrics_dump_path"] = metrics_dump_path
    _flags.set_flags(new)


def disable():
    _flags.set_flags({"telemetry": False})


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

# label values are keyed by a sorted (k, v) tuple; () is the unlabelled cell
_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Dict[str, Any]]) -> _LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


# Label-cardinality cap: a mis-labelled hot-path metric (step index or a
# raw barrier name in a label) would otherwise grow one cell per distinct
# value forever — registry memory AND the Prometheus payload. Past
# MAX_LABEL_SETS distinct label-sets, new ones collapse into one
# overflow="true" cell; the first drop warns and every drop counts into
# pt_metric_label_overflow_total{metric=...}.
MAX_LABEL_SETS = 64
_OVERFLOW_KEY: _LabelKey = (("overflow", "true"),)


def _capped_key(metric, key: _LabelKey):
    """(effective key, dropped, first-drop) — caller holds _LOCK."""
    cells = metric._cells
    if key in cells or key == _OVERFLOW_KEY or len(cells) < MAX_LABEL_SETS:
        return key, False, False
    first = not metric._overflowed
    metric._overflowed = True
    return _OVERFLOW_KEY, True, first


def _note_overflow(name: str, first: bool):
    """Post-mutation bookkeeping, outside _LOCK (the overflow counter's
    own inc takes it)."""
    if first:
        warnings.warn(
            f"metric '{name}' exceeded {MAX_LABEL_SETS} distinct "
            f"label-sets; further label-sets collapse into "
            f'overflow="true"', RuntimeWarning)
    _overflow_total().inc(labels={"metric": name})


_overflow_counter: Optional["Counter"] = None


def _overflow_total() -> "Counter":
    global _overflow_counter
    if _overflow_counter is None:
        _overflow_counter = counter(
            "pt_metric_label_overflow_total",
            "metric mutations dropped into the overflow label bucket "
            "after MAX_LABEL_SETS distinct label-sets, by metric")
    return _overflow_counter


class Counter:
    """Monotonic counter. ``inc`` is a no-op (one flag check, zero
    allocations) while telemetry is off."""

    kind = "counter"
    __slots__ = ("name", "doc", "_cells", "_overflowed")

    def __init__(self, name: str, doc: str):
        self.name = name
        self.doc = doc
        self._cells: Dict[_LabelKey, float] = {}
        self._overflowed = False

    def inc(self, n: float = 1, labels: Optional[Dict[str, Any]] = None):
        if not _enabled:
            return
        key = _label_key(labels)
        with _LOCK:
            key, dropped, first = _capped_key(self, key)
            self._cells[key] = self._cells.get(key, 0.0) + n
        if dropped:
            _note_overflow(self.name, first)

    def value(self, labels: Optional[Dict[str, Any]] = None) -> float:
        return self._cells.get(_label_key(labels), 0.0)


class Gauge:
    """Last-value instrument (``set``) with an ``add`` for +/- deltas."""

    kind = "gauge"
    __slots__ = ("name", "doc", "_cells", "_overflowed")

    def __init__(self, name: str, doc: str):
        self.name = name
        self.doc = doc
        self._cells: Dict[_LabelKey, float] = {}
        self._overflowed = False

    def set(self, v: float, labels: Optional[Dict[str, Any]] = None):
        if not _enabled:
            return
        key = _label_key(labels)
        with _LOCK:
            key, dropped, first = _capped_key(self, key)
            self._cells[key] = float(v)
        if dropped:
            _note_overflow(self.name, first)

    def add(self, n: float = 1, labels: Optional[Dict[str, Any]] = None):
        if not _enabled:
            return
        key = _label_key(labels)
        with _LOCK:
            key, dropped, first = _capped_key(self, key)
            self._cells[key] = self._cells.get(key, 0.0) + n
        if dropped:
            _note_overflow(self.name, first)

    def replace(self, values: Iterable[Tuple[Optional[Dict[str, Any]],
                                             float]]):
        """Atomically swap EVERY cell for ``values`` ([(labels, value),
        ...]) — for gauges that mirror one bounded snapshot at a time
        (e.g. the roofline plane's top-K op seconds, whose per-compile
        HLO label values would otherwise accrete stale cells forever).
        A concurrent scrape sees either the old set or the new one,
        never a partial mix. The MAX_LABEL_SETS cap applies here too:
        values past it are dropped (first-listed win — callers pass
        rank order), metered into pt_metric_label_overflow_total and
        warned once, same as every other mutator. No-op while
        telemetry is off."""
        if not _enabled:
            return
        cells: Dict[_LabelKey, float] = {}
        dropped = 0
        for labels, v in values:
            key = _label_key(labels)
            if len(cells) >= MAX_LABEL_SETS and key not in cells:
                dropped += 1
                continue
            cells[key] = float(v)
        with _LOCK:
            first = dropped > 0 and not self._overflowed
            self._cells = cells
            # sticky, like _capped_key's lifetime-once contract: a
            # small replace must not re-arm the once-only warning
            self._overflowed = self._overflowed or dropped > 0
        if dropped:
            if first:
                warnings.warn(
                    f"metric '{self.name}' replace() exceeded "
                    f"{MAX_LABEL_SETS} label-sets; {dropped} values "
                    f"dropped", RuntimeWarning)
            _overflow_total().inc(dropped, labels={"metric": self.name})

    def value(self, labels: Optional[Dict[str, Any]] = None) -> float:
        return self._cells.get(_label_key(labels), 0.0)


# default buckets: tuned for step/compile/barrier latencies in seconds
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: each bucket
    counts observations <= its upper bound; +Inf is implicit)."""

    kind = "histogram"
    __slots__ = ("name", "doc", "buckets", "_cells", "_overflowed")

    def __init__(self, name: str, doc: str,
                 buckets: Iterable[float] = DEFAULT_BUCKETS):
        self.name = name
        self.doc = doc
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # cell: [counts per bucket..., +inf count, sum]
        self._cells: Dict[_LabelKey, list] = {}
        self._overflowed = False

    def observe(self, v: float, labels: Optional[Dict[str, Any]] = None):
        if not _enabled:
            return
        v = float(v)
        key = _label_key(labels)
        with _LOCK:
            key, dropped, first = _capped_key(self, key)
            cell = self._cells.get(key)
            if cell is None:
                cell = [0] * (len(self.buckets) + 1) + [0.0]
                self._cells[key] = cell
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    cell[i] += 1
                    break
            else:
                cell[len(self.buckets)] += 1
            cell[-1] += v
        if dropped:
            _note_overflow(self.name, first)

    def count(self, labels: Optional[Dict[str, Any]] = None) -> int:
        cell = self._cells.get(_label_key(labels))
        return int(sum(cell[:-1])) if cell else 0

    def sum(self, labels: Optional[Dict[str, Any]] = None) -> float:
        cell = self._cells.get(_label_key(labels))
        return float(cell[-1]) if cell else 0.0

    def quantile(self, q: float,
                 labels: Optional[Dict[str, Any]] = None) -> Optional[float]:
        """Bucket-interpolated quantile estimate (None when empty)."""
        cell = self._cells.get(_label_key(labels))
        if not cell:
            return None
        return _hist_quantile(self.buckets, cell, q)


# quantile summaries exported alongside the raw buckets so the p50/p95/p99
# of barrier waits or compile times are readable without a Prometheus
# server doing histogram_quantile() for you
QUANTILE_LABELS = (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))


def _hist_quantile(bounds, cell, q: float) -> Optional[float]:
    """Linear interpolation inside the target bucket (the same estimate
    Prometheus's histogram_quantile makes). Observations in the +Inf
    bucket clamp to the top finite bound."""
    total = sum(cell[:-1])
    if total == 0:
        return None
    target = q * total
    acc = 0.0
    lo = 0.0
    for i, ub in enumerate(bounds):
        c = cell[i]
        if c and acc + c >= target:
            return lo + (ub - lo) * ((target - acc) / c)
        acc += c
        lo = ub
    return bounds[-1] if bounds else 0.0


_REGISTRY: Dict[str, Any] = {}


def _get_or_create(cls, name: str, doc: str, **kwargs):
    with _LOCK:
        m = _REGISTRY.get(name)
        if m is not None:
            if not isinstance(m, cls):
                raise TypeError(
                    f"metric '{name}' already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m
        m = cls(name, doc, **kwargs)
        _REGISTRY[name] = m
        return m


def counter(name: str, doc: str = "") -> Counter:
    return _get_or_create(Counter, name, doc)


def gauge(name: str, doc: str = "") -> Gauge:
    return _get_or_create(Gauge, name, doc)


def histogram(name: str, doc: str = "",
              buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
    h = _get_or_create(Histogram, name, doc, buckets=buckets)
    want = tuple(sorted(float(b) for b in buckets))
    if h.buckets != want:
        # silently returning the existing instrument would bucket the
        # caller's observations against bounds it never asked for
        raise ValueError(
            f"histogram '{name}' already registered with buckets "
            f"{h.buckets}, requested {want}")
    return h


def reset():
    """Zero every registered metric and close the step-log writer (test
    isolation). Metric OBJECTS survive — instrumented modules hold
    references to them, so dropping the registry would orphan live
    instruments into invisible counters."""
    global _step_log_file, _step_log_path, _step_seq, _step_log_warned
    global _stall_seq
    with _LOCK:
        for m in _REGISTRY.values():
            m._cells.clear()
            m._overflowed = False
    with _STEP_LOG_LOCK:
        _step_log_warned = False
        if _step_log_file is not None:
            try:
                _step_log_file.close()
            except OSError:
                pass
        _step_log_file = None
        _step_log_path = ""
        _step_seq = 0
        _STEP_RING.clear()
    with _COMPILE_LOCK:
        _COMPILE_REPORTS.clear()
    _STALLS.clear()
    _stall_seq = 0
    global _oom_seq
    _OOM_RECORDS.clear()
    _oom_seq = 0
    with _TRACE_LOCK:
        _TRACE_RING.clear()
        _DYN_TRACKS.clear()
    global _input_wait_s, _last_bound
    with _BOUND_LOCK:
        _input_wait_s = 0.0
        _bound_window.clear()
        _last_bound = None
    import sys

    # numerics and the fleet plane ride the same test-isolation hook;
    # lazy so importing monitor alone never pulls either in
    numerics = sys.modules.get("paddle_tpu.numerics")
    if numerics is not None:
        numerics.reset()
    fm = sys.modules.get("paddle_tpu.fleet_monitor")
    if fm is not None:
        fm.reset()
    rl = sys.modules.get("paddle_tpu.roofline")
    if rl is not None:
        rl.reset()
    st = sys.modules.get("paddle_tpu.serving_trace")
    if st is not None:
        st.reset()


def snapshot() -> Dict[str, Any]:
    """Plain-dict view of every registered metric.

    ``{name: {"kind", "doc", "values": [{"labels": {...}, ...}]}}`` —
    counters/gauges carry ``value``; histograms carry ``count``, ``sum``
    and cumulative ``buckets`` ``[[upper_bound, count], ...]`` ending in
    the +Inf bucket.
    """
    out: Dict[str, Any] = {}
    with _LOCK:
        for name, m in sorted(_REGISTRY.items()):
            values = []
            for key, cell in sorted(m._cells.items()):
                labels = {k: v for k, v in key}
                if m.kind == "histogram":
                    cum, acc = [], 0
                    for ub, c in zip(m.buckets, cell):
                        acc += c
                        cum.append([ub, acc])
                    acc += cell[len(m.buckets)]
                    cum.append(["+Inf", acc])
                    val = {"labels": labels, "count": acc,
                           "sum": cell[-1], "buckets": cum}
                    for qname, q in QUANTILE_LABELS:
                        val[qname] = _hist_quantile(m.buckets, cell, q)
                    values.append(val)
                else:
                    values.append({"labels": labels, "value": cell})
            out[name] = {"kind": m.kind, "doc": m.doc, "values": values}
    return out


# --- exporters ---

def _prom_labels(labels: Dict[str, str], extra: Optional[tuple] = None) -> str:
    items = list(labels.items())
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    body = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", "\\\\")
                     .replace('"', '\\"').replace("\n", "\\n"))
        for k, v in items
    )
    return "{%s}" % body


def to_prometheus(snap: Optional[Dict[str, Any]] = None) -> str:
    """Prometheus text exposition format (# HELP / # TYPE / samples)."""
    snap = snapshot() if snap is None else snap
    lines = []
    for name, m in snap.items():
        if m["doc"]:
            lines.append(f"# HELP {name} {m['doc']}")
        lines.append(f"# TYPE {name} {m['kind']}")
        for cell in m["values"]:
            labels = cell["labels"]
            if m["kind"] == "histogram":
                for ub, c in cell["buckets"]:
                    lines.append(
                        f"{name}_bucket"
                        f"{_prom_labels(labels, ('le', ub))} {c}")
                lines.append(
                    f"{name}_sum{_prom_labels(labels)} {cell['sum']}")
                lines.append(
                    f"{name}_count{_prom_labels(labels)} {cell['count']}")
                for qname, _q in QUANTILE_LABELS:
                    if cell.get(qname) is not None:
                        lines.append(
                            f"{name}_{qname}"
                            f"{_prom_labels(labels)} {cell[qname]}")
            else:
                lines.append(
                    f"{name}{_prom_labels(labels)} {cell['value']}")
    return "\n".join(lines) + ("\n" if lines else "")


def to_json(snap: Optional[Dict[str, Any]] = None) -> str:
    return json.dumps(snapshot() if snap is None else snap,
                      sort_keys=True, indent=1)


def dump_metrics(path: Optional[str] = None, fmt: str = "prometheus") -> str:
    """Export all metrics; returns the text, writes it to ``path`` (or the
    ``metrics_dump_path`` flag when set) too. ``fmt``: 'prometheus' or
    'json'."""
    if fmt in ("prometheus", "prom", "text"):
        text = to_prometheus()
    elif fmt == "json":
        text = to_json()
    else:
        raise ValueError(f"unknown metrics format '{fmt}'")
    path = path or _flags.get_flag("metrics_dump_path")
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text


def _dump_at_exit():
    if _enabled and _flags.get_flag("metrics_dump_path"):
        try:
            dump_metrics()
        except OSError:
            pass


atexit.register(_dump_at_exit)


# ---------------------------------------------------------------------------
# structured step logs
# ---------------------------------------------------------------------------

STEP_LOG_SCHEMA_VERSION = 1

# field name -> (accepted types, required, doc). The contract tests and
# README both derive from this table; bump STEP_LOG_SCHEMA_VERSION on any
# incompatible change.
STEP_LOG_FIELDS: Dict[str, tuple] = {
    "v": ((int,), True, "schema version (STEP_LOG_SCHEMA_VERSION)"),
    "ts": ((float, int), True,
           "wall-clock unix timestamp (human-readable anchor only; all "
           "durations are perf_counter intervals)"),
    "seq": ((int,), True, "process-wide record sequence number"),
    "kind": ((str,), True, "'step' (Executor.run) or 'window' (run_steps)"),
    "step": ((int,), True, "executor step index (first step of a window)"),
    "steps": ((int,), False, "window length (kind == 'window' only)"),
    "wall_ms": ((float, int), True,
                "host wall time of the run call, perf_counter-based"),
    "compile_ms": ((float, int, type(None)), True,
                   "XLA lower+jit wrap time (disk-cache deserialize "
                   "time on a 'disk' outcome); null on an in-memory hit"),
    "cache": ((str,), True,
              "compile-cache outcome: 'hit' (in-memory), 'disk' "
              "(executable resolved from the persistent level-2 cache) "
              "or 'miss' (fresh compile)"),
    "evictions": ((int,), True,
                  "cache entries evicted by this step's insert"),
    "feed_bytes": ((int,), True, "total bytes across feed arrays"),
    "fetch_bytes": ((int,), True, "total bytes across fetch arrays"),
    "nan_check": ((str, type(None)), True,
                  "'ok'/'fail' when check_nan_inf ran, else null"),
    "nan_step": ((int,), False,
                 "GLOBAL index of the first non-finite step inside a "
                 "compiled window (only on a window nan_check fail)"),
    "numerics": ((dict,), False,
                 "sampled numerics-bundle summary (numerics.py): "
                 "instrumented var count, non-finite var count, "
                 "first_bad {op, op_type, var} or null, aux gauges"),
    "phases": ((dict,), False,
               "per-phase time attribution in ms: feed (host->device "
               "staging), dispatch (Python + tracing overhead), device "
               "(delta to block_until_ready), fetch (device->host + "
               "decode); windows carry whole-window totals"),
    "bound": ((str,), False,
              "boundedness verdict over the trailing step window: "
              "'input_bound', 'dispatch_bound' or 'device_bound'"),
    "sampled": ((bool,), False,
                "whether the step-phase plane sampled this step "
                "(step_phases_every_n): false = the step dispatched "
                "fully async, so wall_ms excludes device time and the "
                "record carries no phases; absent while the phase "
                "plane is off entirely"),
    "strategy": ((str, type(None)), True,
                 "SPMD strategy id (mesh axes) or null for plain runs"),
}


def _validate_fields(rec, fields: Dict[str, tuple], version: int,
                     kind: str):
    """Shared field-table validator behind every validate_* entry point
    (step records, compile reports, fleet digests, OOM reports): dict
    shape, required fields, per-field types, unknown-field rejection,
    schema-version match."""
    if not isinstance(rec, dict):
        raise ValueError(f"{kind} must be a dict, got {type(rec)}")
    for field, (types, required, _doc) in fields.items():
        if field not in rec:
            if required:
                raise ValueError(f"{kind} missing field '{field}'")
            continue
        if not isinstance(rec[field], types):
            raise ValueError(
                f"{kind} field '{field}' has type "
                f"{type(rec[field]).__name__}, expected one of "
                f"{[t.__name__ for t in types]}")
    unknown = set(rec) - set(fields)
    if unknown:
        raise ValueError(f"{kind} has unknown fields {sorted(unknown)}")
    if rec["v"] != version:
        raise ValueError(f"{kind} schema v{rec['v']} != v{version}")


def validate_step_record(rec: Dict[str, Any]):
    """Raise ValueError unless ``rec`` conforms to STEP_LOG_FIELDS."""
    _validate_fields(rec, STEP_LOG_FIELDS, STEP_LOG_SCHEMA_VERSION,
                     "step record")


def step_log_active() -> bool:
    """True when telemetry is on AND a step_log_path is configured."""
    return _enabled and bool(_flags.get_flag("step_log_path"))


def step_records_active() -> bool:
    """True when executors should assemble per-step records: with
    telemetry on every record feeds the in-memory ring buffer (the
    /steps endpoint + flight recorder), whether or not a step_log_path
    routes them to disk too."""
    return _enabled


# Bounded flight-recorder ring of the last N step records. Fed by every
# log_step call; served by /steps and dumped by the stall watchdog. The
# deque bound is the memory contract — a week-long job holds the same
# 256 records as a smoke test.
STEP_RING_CAPACITY = 256
_STEP_RING: collections.deque = collections.deque(maxlen=STEP_RING_CAPACITY)


def recent_steps(n: Optional[int] = None) -> List[Dict[str, Any]]:
    """Last ``n`` (default: all buffered) step records, oldest first."""
    with _STEP_LOG_LOCK:
        recs = list(_STEP_RING)
    if n is None:
        return recs
    n = int(n)
    return recs[-n:] if n > 0 else []


_step_log_warned = False


def log_step(record: Dict[str, Any]):
    """Record one step: fills ``v``, ``ts`` and ``seq``, appends to the
    bounded ring buffer, and — when ``step_log_path`` is configured —
    appends a JSONL line (flushed per record so a live tail sees every
    one). No-op when telemetry is off. An unwritable path warns once and
    drops the DISK copy only — callers invoke this from ``finally``
    blocks, and a telemetry failure must never mask the step's real
    result (or the exception being recorded)."""
    global _step_log_file, _step_log_path, _step_seq, _step_log_warned
    if not _enabled:
        return
    path = _flags.get_flag("step_log_path")
    with _STEP_LOG_LOCK:
        record = dict(record)
        record.setdefault("v", STEP_LOG_SCHEMA_VERSION)
        record.setdefault("ts", time.time())  # human-readable anchor
        record["seq"] = _step_seq
        _step_seq += 1
        _STEP_RING.append(record)
        if not path:
            return
        try:
            if _step_log_file is None or path != _step_log_path:
                if _step_log_file is not None:
                    try:
                        _step_log_file.close()
                    except OSError:
                        pass
                _step_log_file = None
                _step_log_file = open(path, "a")
                _step_log_path = path
                _step_log_warned = False
            # default=str: a numpy scalar (or anything else json chokes
            # on) degrades to its string form instead of raising
            _step_log_file.write(
                json.dumps(record, sort_keys=True, default=str) + "\n")
            _step_log_file.flush()
        except Exception as e:  # never-raise contract: callers log from
            # finally blocks and the step's real exception must win
            if not _step_log_warned:
                _step_log_warned = True
                warnings.warn(
                    f"step log write to {path!r} failed; records are "
                    f"being dropped: {e!r}", RuntimeWarning)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

_span_seconds: Optional[Histogram] = None

# Per-thread stack of active span names (telemetry-on spans only): the
# stall watchdog snapshots it at arm time so a stall record says WHERE
# the thread was ("trainer.step" > "executor.run_step"), not just that
# it stalled.
_TLS = threading.local()


def span_stack() -> Tuple[str, ...]:
    """Names of this thread's active telemetry spans, outermost first."""
    return tuple(getattr(_TLS, "spans", ()))


def span(name: str):
    """RAII span with one timeline: always emits a host chrome-trace span
    through ``profiler.record_event`` (a no-op unless the profiler is
    on); with telemetry on, additionally times the body with
    ``perf_counter`` into the ``pt_span_seconds`` histogram labelled by
    span name. When telemetry is off this returns the record_event
    context manager directly — byte-identical behavior and allocation
    profile to calling the profiler yourself."""
    if not _enabled:
        return _profiler.record_event(name)
    return _timed_span(name)


@contextlib.contextmanager
def _timed_span(name: str):
    global _span_seconds
    if _span_seconds is None:
        _span_seconds = histogram(
            "pt_span_seconds", "host span durations by span name")
    stack = getattr(_TLS, "spans", None)
    if stack is None:
        stack = _TLS.spans = []
    stack.append(name)
    t0 = time.perf_counter()
    with _profiler.record_event(name):
        try:
            yield
        finally:
            _span_seconds.observe(time.perf_counter() - t0,
                                  labels={"span": name})
            stack.pop()


# ---------------------------------------------------------------------------
# compile reports
# ---------------------------------------------------------------------------

COMPILE_REPORT_SCHEMA_VERSION = 1

# field name -> (accepted types, required, doc). Cost/memory numbers are
# null (with source == "estimate") when the jax/backend version exposes
# no cost_analysis()/memory_analysis(); bump the version on any
# incompatible change. The doc-coverage test and README both derive from
# this table.
COMPILE_REPORT_FIELDS: Dict[str, tuple] = {
    "v": ((int,), True,
          "schema version (COMPILE_REPORT_SCHEMA_VERSION)"),
    "ts": ((float, int), True, "wall-clock unix timestamp of the compile"),
    "program": ((str,), True, "program id ('program<uid>')"),
    "program_uid": ((int,), True, "Program._uid of the compiled program"),
    "cache_key": ((str,), True,
                  "hash of the executor cache key (program version + "
                  "feed signature + fetch list)"),
    "kind": ((str,), True, "'step' (run) or 'window' (run_steps)"),
    "backend": ((str,), True, "jax backend the program compiled for"),
    "source": ((str,), True,
               "'xla' when cost/memory numbers come from the compiled "
               "executable; 'estimate' when the analysis APIs were "
               "unavailable and only op-count estimates are present"),
    "compile_ms": ((float, int, type(None)), True,
                   "executor-side build time (trace + jit wrap)"),
    "analysis_ms": ((float, int, type(None)), True,
                    "AOT lower+compile time of the analysis twin — the "
                    "closest measure of true XLA compile cost; null "
                    "when source == 'estimate'"),
    "flops": ((float, int, type(None)), True,
              "XLA cost-analysis flop count; null when unavailable"),
    "bytes_accessed": ((float, int, type(None)), True,
                       "XLA cost-analysis bytes accessed (HBM traffic "
                       "estimate); null when unavailable"),
    "peak_bytes": ((int, type(None)), True,
                   "argument + output + temp - aliased bytes: the "
                   "device-memory high-water estimate; null when "
                   "unavailable"),
    "argument_bytes": ((int, type(None)), True,
                       "device bytes of the program's arguments"),
    "output_bytes": ((int, type(None)), True,
                     "device bytes of the program's outputs"),
    "temp_bytes": ((int, type(None)), True,
                   "XLA temp-buffer bytes (workspace/scratch)"),
    "alias_bytes": ((int, type(None)), True,
                    "argument bytes aliased into outputs (donation)"),
    "generated_code_bytes": ((int, type(None)), True,
                             "compiled executable code size"),
    "n_ops": ((int,), True, "Program-IR ops lowered into this XLA "
                            "program"),
    "op_histogram": ((dict,), True,
                     "op type -> count over the lowered block (the "
                     "op-lowering histogram)"),
    "strategy": ((str, type(None)), True,
                 "SPMD strategy id (mesh axes) or null"),
    "window_steps": ((int, type(None)), False,
                     "steps compiled into a 'window' report's program "
                     "(its flops/bytes cover the WHOLE window; the "
                     "roofline plane divides by this); absent on "
                     "'step' reports"),
}


def validate_compile_report(rec: Dict[str, Any]):
    """Raise ValueError unless ``rec`` conforms to COMPILE_REPORT_FIELDS."""
    _validate_fields(rec, COMPILE_REPORT_FIELDS,
                     COMPILE_REPORT_SCHEMA_VERSION, "compile report")
    if rec["source"] not in ("xla", "estimate"):
        raise ValueError(
            f"compile report source {rec['source']!r} not in "
            f"('xla', 'estimate')")


_COMPILE_LOCK = threading.Lock()
# program id -> latest report; insertion-ordered so eviction drops the
# program that compiled longest ago
_COMPILE_REPORTS: Dict[str, Dict[str, Any]] = {}
MAX_COMPILE_REPORTS = 32

_M_COMPILE_REPORTS = None
_M_COMPILE_FLOPS = None
_M_COMPILE_PEAK = None
_M_COMPILE_SECONDS = None


def _compile_instruments():
    global _M_COMPILE_REPORTS, _M_COMPILE_FLOPS, _M_COMPILE_PEAK
    global _M_COMPILE_SECONDS
    if _M_COMPILE_REPORTS is None:
        _M_COMPILE_REPORTS = counter(
            "pt_compile_reports_total", "compile reports recorded")
        _M_COMPILE_FLOPS = gauge(
            "pt_compile_flops",
            "XLA cost-analysis flops of the latest compile, by program")
        _M_COMPILE_PEAK = gauge(
            "pt_compile_peak_bytes",
            "device-memory high-water estimate of the latest compile, "
            "by program")
        _M_COMPILE_SECONDS = histogram(
            "pt_compile_seconds",
            "XLA compile time per fresh executor compile")


def compile_reports_active() -> bool:
    """Executors consult this per cache miss: reports are generated when
    telemetry is on AND someone can see them (a compile_report_dir is
    configured or the live endpoint is up). Each report costs one extra
    AOT lower+compile, so it is never on by accident."""
    return _enabled and (bool(_flags.get_flag("compile_report_dir"))
                         or _server is not None)


def record_compile_report(report: Dict[str, Any]):
    """Store a compile report: ring-buffered in memory (the /compile
    endpoint), mirrored into pt_compile_* instruments, and written as
    ``<program>-<cache_key>.json`` under the ``compile_report_dir`` flag
    when set. Never raises — telemetry must not fail a step."""
    try:
        report = dict(report)
        report.setdefault("v", COMPILE_REPORT_SCHEMA_VERSION)
        report.setdefault("ts", time.time())
        _compile_instruments()
        prog = report.get("program", "?")
        with _COMPILE_LOCK:
            _COMPILE_REPORTS.pop(prog, None)
            _COMPILE_REPORTS[prog] = report
            while len(_COMPILE_REPORTS) > MAX_COMPILE_REPORTS:
                _COMPILE_REPORTS.pop(next(iter(_COMPILE_REPORTS)))
        _M_COMPILE_REPORTS.inc()
        if report.get("flops") is not None:
            _M_COMPILE_FLOPS.set(report["flops"],
                                 labels={"program": prog})
        if report.get("peak_bytes") is not None:
            _M_COMPILE_PEAK.set(report["peak_bytes"],
                                labels={"program": prog})
        ms = report.get("analysis_ms") or report.get("compile_ms")
        if ms is not None:
            _M_COMPILE_SECONDS.observe(ms / 1e3)
        out_dir = _flags.get_flag("compile_report_dir")
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
            fname = f"{prog}-{report.get('cache_key', 'nokey')}.json"
            with open(os.path.join(out_dir, fname), "w") as f:
                json.dump(report, f, sort_keys=True, indent=1,
                          default=str)
    except Exception as e:
        warnings.warn(f"compile report dropped: {e!r}", RuntimeWarning)


def compile_reports() -> Dict[str, Dict[str, Any]]:
    """Latest compile report per program (insertion order = compile
    order, oldest first)."""
    with _COMPILE_LOCK:
        return {k: dict(v) for k, v in _COMPILE_REPORTS.items()}


# ---------------------------------------------------------------------------
# pre-flight memory estimate
# ---------------------------------------------------------------------------

def _var_nbytes(shape, dtype, batch: int) -> int:
    n = 1
    for d in shape:
        n *= batch if int(d) < 0 else max(int(d), 1)
    # np.dtype('bfloat16') raises without ml_dtypes registered; its width
    # is what matters here
    itemsize = 2 if str(dtype) == "bfloat16" else __import__(
        "numpy").dtype(dtype).itemsize
    return n * itemsize


def estimate_memory(program, feed_shapes: Optional[Dict[str, Any]] = None,
                    budget_bytes: Optional[int] = None) -> Dict[str, Any]:
    """Static pre-flight device-memory estimate for ``program``: sums
    declared var shapes in block 0 (``-1`` batch dims resolved from
    ``feed_shapes``' leading dim, else 1) into parameter / feed /
    activation byte totals. A LOWER BOUND — XLA temps, donation aliasing
    and fusion are unknowable before the compile — but params +
    activations catch the common will-it-OOM case before paying a
    multi-minute compile for an OOM.

    Returns ``{param_bytes, feed_bytes, activation_bytes, total_bytes,
    budget_bytes, fits}`` (``fits`` is None when no budget applies, from
    the ``device_memory_budget_bytes`` flag unless passed here)."""
    feed_shapes = feed_shapes or {}
    if budget_bytes is None:
        budget_bytes = _flags.get_flag("device_memory_budget_bytes")
    batch = 1
    for shp in feed_shapes.values():
        if len(shp) and int(shp[0]) > 0:
            batch = int(shp[0])
            break
    param = feed = act = 0
    block = program.blocks[0]
    for name, var in block.vars.items():
        if var.shape is None or var.dtype is None:
            continue
        if name in feed_shapes:
            nb = _var_nbytes(feed_shapes[name], var.dtype, batch)
            feed += nb
        else:
            nb = _var_nbytes(var.shape, var.dtype, batch)
            if var.persistable:
                param += nb
            else:
                act += nb
    total = param + feed + act
    return {
        "param_bytes": param,
        "feed_bytes": feed,
        "activation_bytes": act,
        "total_bytes": total,
        "budget_bytes": int(budget_bytes),
        "fits": None if not budget_bytes else total <= budget_bytes,
    }


# cached hot value of the device_memory_budget_bytes flag so the
# executor's pre-compile check is one int compare when no budget is set
_mem_budget = 0


def memory_budget_bytes() -> int:
    return _mem_budget


def _sync_mem_budget(value):
    global _mem_budget
    _mem_budget = int(value)


def check_memory_budget(program, feed_shapes: Optional[Dict] = None):
    """Pre-compile budget gate: estimate and warn when over. Returns the
    estimate (or None when no budget is configured). Never raises."""
    if _mem_budget <= 0:
        return None
    try:
        est = estimate_memory(program, feed_shapes,
                              budget_bytes=_mem_budget)
    except Exception as e:
        warnings.warn(f"memory pre-flight failed: {e!r}", RuntimeWarning)
        return None
    if est["fits"] is False:
        warnings.warn(
            f"program{program._uid}: static memory estimate "
            f"{est['total_bytes']:,} B (params {est['param_bytes']:,} + "
            f"feeds {est['feed_bytes']:,} + activations "
            f"{est['activation_bytes']:,}) exceeds the "
            f"device_memory_budget_bytes flag ({_mem_budget:,} B) — "
            f"this compile is likely to OOM at run time",
            RuntimeWarning)
    return est


# ---------------------------------------------------------------------------
# live endpoint (/metrics /healthz /steps /compile)
# ---------------------------------------------------------------------------

_server = None
_server_thread: Optional[threading.Thread] = None
_server_started_ts = 0.0

# Route table served by "/" (the JSON index) — one source for the docs
# and the handler, so a new route cannot silently miss the index.
ROUTES: Dict[str, str] = {
    "/": "this JSON index of available routes",
    "/metrics": "Prometheus text exposition of the metrics registry "
                "(?fleet=1: merged cross-rank exposition, rank= labels)",
    "/healthz": "JSON liveness: status, telemetry state, uptime",
    "/steps": "JSON ring buffer of recent step records (?n= trims)",
    "/compile": "JSON latest compile report per program",
    "/numerics": "JSON numerics plane: NaN/Inf provenance + tensor stats",
    "/lint": "JSON static-verifier plane: latest lint record per program",
    "/trace": "Chrome-trace JSON timeline (Perfetto-loadable)",
    "/fleet": "JSON cluster view: per-rank digests, heartbeat ages, "
              "stragglers, OOM reports + the serving-fleet router "
              "section (per-replica state, queue depth, generation "
              "tag, last-heartbeat age) when a ServingFleet is live",
    "/profile": "JSON roofline plane: latest device profile per "
                "program (top ops, verdict, measured MFU)",
    "/serve": "JSON serving plane: per-engine slot/queue stats, token "
              "throughput, TTFT + per-token latency quantiles",
    "/requests": "JSON request plane: in-flight serving requests + the "
                 "recently-terminated ring (per-phase latency "
                 "breakdowns, deadline attribution, SLO accounting)",
}


def serve(port: Optional[int] = None, host: str = "127.0.0.1") -> int:
    """Start the observability HTTP server on a background daemon thread
    (idempotent; returns the bound port). ``port=0`` binds an ephemeral
    port — the test / multi-worker-per-host pattern. Routes:

    - ``/``         JSON index of every route (this table)
    - ``/metrics``  Prometheus text exposition of the registry;
      ``?fleet=1`` serves the merged cross-rank exposition instead
      (every rank's digest samples labelled ``rank=`` — fleet_monitor)
    - ``/healthz``  JSON liveness (status, telemetry state, uptime)
    - ``/steps``    JSON ring buffer of recent step records (``?n=``)
    - ``/compile``  JSON latest compile report per program
    - ``/numerics`` JSON numerics plane: NaN/Inf provenance records +
      latest decoded tensor stats per program (numerics.py)
    - ``/lint``     JSON static-verifier plane: latest lint record per
      program (mode, severity counts, findings — analysis.py)
    - ``/trace``    Chrome-trace JSON of the timeline ring (load it in
      Perfetto / chrome://tracing directly)
    - ``/fleet``    JSON cluster view: one row per rank (digest + phase
      breakdown + heartbeat age + dead flag) plus straggler records and
      OOM reports (fleet_monitor.py)
    - ``/profile``  JSON roofline plane: latest device profile per
      program — top ops by device seconds, roofline verdict, measured
      MFU (roofline.py)

    Binds localhost by default: metrics can carry program names — scrape
    through a sidecar or port-forward, don't expose it."""
    global _server, _server_thread, _server_started_ts
    if _server is not None:
        return _server.server_address[1]
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    if port is None:
        port = _flags.get_flag("metrics_port")

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (http.server API)
            path, _, query = self.path.partition("?")
            try:
                if path in ("", "/"):
                    # JSON index: the zero-knowledge entry point — every
                    # route with a one-line description (previously 404)
                    body = json.dumps(
                        {"routes": ROUTES}, sort_keys=True).encode()
                    ctype = "application/json"
                elif path == "/metrics":
                    if "fleet=1" in query.split("&"):
                        # merged cross-rank exposition from the latest
                        # aggregated digests (lazy import:
                        # fleet_monitor.py imports monitor.py)
                        from paddle_tpu import fleet_monitor as _fm

                        body = _fm.to_prometheus_fleet().encode()
                    else:
                        body = to_prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/healthz":
                    # serving-engine lifecycle rows (serving/draining/
                    # closed) WITHOUT importing the serving plane into
                    # processes that never used it: a replica being
                    # rotated out must be visible to its health probe
                    # before its queue is torn down
                    srv = sys.modules.get("paddle_tpu.serving")
                    body = json.dumps({
                        "status": "ok",
                        "telemetry": _enabled,
                        "uptime_s": time.time() - _server_started_ts,
                        "steps_buffered": len(_STEP_RING),
                        "stalls": len(_STALLS),
                        "engines": (srv.engine_states()
                                    if srv is not None else {}),
                    }).encode()
                    ctype = "application/json"
                elif path == "/steps":
                    n = None
                    for part in query.split("&"):
                        if part.startswith("n="):
                            n = int(part[2:])
                    body = json.dumps(recent_steps(n),
                                      default=str).encode()
                    ctype = "application/json"
                elif path == "/compile":
                    body = json.dumps(compile_reports(), sort_keys=True,
                                      default=str).encode()
                    ctype = "application/json"
                elif path == "/numerics":
                    # lazy import: numerics.py imports monitor.py
                    from paddle_tpu import numerics as _numerics

                    body = json.dumps(_numerics.summary(), sort_keys=True,
                                      default=str).encode()
                    ctype = "application/json"
                elif path == "/lint":
                    # lazy import: analysis.py imports monitor.py
                    from paddle_tpu import analysis as _analysis

                    body = json.dumps(_analysis.summary(), sort_keys=True,
                                      default=str).encode()
                    ctype = "application/json"
                elif path == "/trace":
                    body = json.dumps(trace_snapshot(),
                                      default=str).encode()
                    ctype = "application/json"
                elif path == "/fleet":
                    # lazy import: fleet_monitor.py imports monitor.py
                    from paddle_tpu import fleet_monitor as _fm

                    view = _fm.cluster_view()
                    # serving-fleet rollup only when that plane is
                    # loaded (lazy — fleet_serving imports monitor)
                    fs = sys.modules.get("paddle_tpu.fleet_serving")
                    if fs is not None:
                        sfleet = fs.fleet_view()
                        if sfleet is not None:
                            view = dict(view)
                            view["serving_fleet"] = sfleet
                    body = json.dumps(view, sort_keys=True,
                                      default=str).encode()
                    ctype = "application/json"
                elif path == "/profile":
                    # lazy import: roofline.py imports monitor.py
                    from paddle_tpu import roofline as _roofline

                    body = json.dumps(_roofline.summary(),
                                      sort_keys=True,
                                      default=str).encode()
                    ctype = "application/json"
                elif path == "/serve":
                    # lazy import: serving.py imports monitor.py
                    from paddle_tpu import serving as _serving

                    body = json.dumps(_serving.summary(),
                                      sort_keys=True,
                                      default=str).encode()
                    ctype = "application/json"
                elif path == "/requests":
                    # lazy import: serving_trace.py imports monitor.py
                    # (it reads the serving plane via sys.modules, so a
                    # process that never served answers an empty view)
                    from paddle_tpu import serving_trace as _strace

                    body = json.dumps(_strace.requests_view(),
                                      sort_keys=True,
                                      default=str).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
            except Exception as e:  # surface as 500, never kill the thread
                self.send_error(500, str(e))
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # scrapes every few seconds —
            pass                       # stderr noise helps nobody

    _server = ThreadingHTTPServer((host, int(port)), _Handler)
    _server.daemon_threads = True
    _server_started_ts = time.time()
    _server_thread = threading.Thread(
        target=_server.serve_forever, name="pt-monitor-http", daemon=True)
    _server_thread.start()
    _sync_trace_on()  # a live /trace route makes the timeline visible
    return _server.server_address[1]


def server_address() -> Optional[Tuple[str, int]]:
    return None if _server is None else tuple(_server.server_address[:2])


def stop_server():
    global _server, _server_thread
    srv, _server = _server, None
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if _server_thread is not None:
        _server_thread.join(timeout=5)
        _server_thread = None
    _sync_trace_on()


def _maybe_autostart_server(_value=None):
    """Flag watcher: bring the server up once `telemetry` is on and
    `metrics_port` is nonzero, whichever flips last."""
    port = _flags.get_flag("metrics_port")
    if _enabled and port > 0 and _server is None:
        try:
            serve(port)
        except OSError as e:
            warnings.warn(
                f"metrics server failed to bind port {port}: {e!r}",
                RuntimeWarning)


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------

STALL_RECORD_SCHEMA_VERSION = 1

_STALLS: collections.deque = collections.deque(maxlen=32)
_stall_seq = 0
# each guard's watchdog is its own timer thread: concurrent stalls (one
# peer death stalls several sites at once) must not share a seq or
# overwrite each other's flight-recorder dump
_STALL_LOCK = threading.Lock()

_M_STALLS = None


def _stall_counter():
    global _M_STALLS
    if _M_STALLS is None:
        _M_STALLS = counter(
            "pt_stall_total",
            "guarded collective sections that exceeded their watchdog "
            "deadline, by site")
    return _M_STALLS


# cached hot value of stall_timeout_ms (same pattern as `telemetry`)
_stall_ms = 0


def _sync_stall_ms(value):
    global _stall_ms
    _stall_ms = int(value)


_NULL_CTX = contextlib.nullcontext()


def stall_guard(name: str, deadline_ms: Optional[float] = None):
    """Watchdog context for a blocking collective (barrier, rendezvous,
    multi-host dispatch). If the body outlives the deadline (the
    ``stall_timeout_ms`` flag unless given here), a timer thread fires
    ONCE: ``pt_stall_total{site=name}`` increments, a structured stall
    record (site, deadline, the arming thread's active span stack, the
    last step record) is buffered + warned, and — when the
    ``stall_dump_dir`` flag is set — the flight recorder (stall record,
    step ring buffer, full metrics snapshot) is dumped to disk. The body
    is never interrupted: a watchdog that kills a slow-but-alive
    collective would convert stragglers into crashes.

    Disabled (telemetry off, or no deadline anywhere) this returns a
    shared nullcontext — one boolean/int check, zero allocations."""
    if not _enabled:
        return _NULL_CTX
    ms = _stall_ms if deadline_ms is None else deadline_ms
    if ms <= 0:
        return _NULL_CTX
    return _StallGuard(name, float(ms))


class _StallGuard:
    __slots__ = ("name", "ms", "_timer")

    def __init__(self, name: str, ms: float):
        self.name = name
        self.ms = ms

    def __enter__(self):
        self._timer = threading.Timer(
            self.ms / 1e3, _record_stall,
            args=(self.name, self.ms, threading.current_thread().name,
                  span_stack()))
        self._timer.daemon = True
        self._timer.start()
        return self

    def __exit__(self, *exc):
        self._timer.cancel()
        return False


def _record_stall(site: str, deadline_ms: float, thread_name: str,
                  spans: Tuple[str, ...]):
    """Runs on the watchdog timer thread. Never raises."""
    global _stall_seq
    try:
        last_steps = recent_steps(1)
        with _STALL_LOCK:
            seq = _stall_seq
            _stall_seq += 1
        rec = {
            "v": STALL_RECORD_SCHEMA_VERSION,
            "ts": time.time(),
            "seq": seq,
            "site": site,
            "deadline_ms": deadline_ms,
            "thread": thread_name,
            "span_stack": list(spans),
            "last_step": last_steps[0] if last_steps else None,
        }
        _STALLS.append(rec)
        _stall_counter().inc(labels={"site": site})
        trace_event(f"stall:{site}", "stall", time.perf_counter(),
                    args={"deadline_ms": deadline_ms, "thread": thread_name,
                          "span_stack": list(spans)})
        warnings.warn(
            f"stall watchdog: {site!r} exceeded {deadline_ms:.0f} ms "
            f"(thread {thread_name}, spans {list(spans)}); the section "
            f"is still blocked", RuntimeWarning)
        dump_dir = _flags.get_flag("stall_dump_dir")
        if dump_dir:
            os.makedirs(dump_dir, exist_ok=True)
            path = os.path.join(
                dump_dir, f"stall-{rec['seq']}-{int(rec['ts'])}.json")
            dump = {
                "stall": rec,
                "steps": recent_steps(),
                "metrics": snapshot(),
                "compile_reports": compile_reports(),
                "oom_reports": oom_records(),
            }
            # a multi-host stall is often a straggler: attach the fleet
            # plane's latest cluster view + straggler records when the
            # plane is loaded (lazy — fleet_monitor imports monitor)
            import sys as _sys
            fm = _sys.modules.get("paddle_tpu.fleet_monitor")
            if fm is not None:
                dump["fleet"] = fm.summary()
            with open(path, "w") as f:
                json.dump(dump, f, sort_keys=True, indent=1, default=str)
    except Exception as e:
        try:
            warnings.warn(f"stall record dropped: {e!r}", RuntimeWarning)
        except Exception:
            pass


def stalls() -> List[Dict[str, Any]]:
    """Buffered stall records, oldest first."""
    return [dict(r) for r in _STALLS]


# ---------------------------------------------------------------------------
# fleet digest schema (assembly/aggregation: fleet_monitor.py)
# ---------------------------------------------------------------------------

FLEET_DIGEST_SCHEMA_VERSION = 1

# field name -> (accepted types, required, doc). One digest per worker,
# published into fleet KV under fleet/metrics/g<gen>/<rank> and
# aggregated by rank 0 into the /fleet cluster view. Compact on
# purpose: counters/gauges carry values, histograms only sum/count —
# full buckets stay on each worker's own /metrics. Bump the version on
# any incompatible change.
FLEET_DIGEST_FIELDS: Dict[str, tuple] = {
    "v": ((int,), True, "schema version (FLEET_DIGEST_SCHEMA_VERSION)"),
    "ts": ((float, int), True,
           "wall-clock unix timestamp of the publish (heartbeat-age "
           "anchor: the aggregator marks a rank dead when now - ts "
           "exceeds the staleness window)"),
    "seq": ((int,), True, "per-process publish sequence number"),
    "rank": ((int,), True, "fleet worker index of the publisher"),
    "world": ((int,), True, "fleet worker count at publish time"),
    "gen": ((int,), True, "elastic-resize generation (fleet PT_GEN)"),
    "host": ((str,), True, "publisher hostname (short form)"),
    "pid": ((int,), True, "publisher process id"),
    "counters": ((dict,), True,
                 "counter name -> [{labels, value}] cells"),
    "gauges": ((dict,), True, "gauge name -> [{labels, value}] cells"),
    "hists": ((dict,), True,
              "histogram name -> [{labels, sum, count}] cells (no "
              "buckets — the digest stays KV-sized)"),
    "last_step": ((dict, type(None)), True,
                  "the publisher's most recent step record "
                  "(STEP_LOG_FIELDS schema, phases + verdict included) "
                  "or null before the first step"),
    "bound": ((dict, type(None)), True,
              "latest boundedness verdict ({verdict, shares, steps}) "
              "or null"),
    "step_wall_ms": ((float, int, type(None)), True,
                     "median wall_ms over the trailing step-record "
                     "window — median, so one compile-inflated warmup "
                     "step cannot skew the straggler detector's "
                     "per-rank signal"),
    "phases_ms": ((dict, type(None)), True,
                  "median per-phase ms over the trailing window (phase "
                  "-> ms) or null when no attributed steps landed yet"),
    "steps": ((int,), True,
              "pt_executor_steps_total at publish time (bounds straggler "
              "detection latency in steps)"),
    "roofline": ((dict, type(None)), False,
                 "per-program roofline rollup from the device-profile "
                 "plane: program -> {measured_mfu, verdict, source} "
                 "(roofline.digest_section); absent before the first "
                 "profile — optional, schema stays v1"),
    "serving": ((dict, type(None)), False,
                "per-replica serving rollup from the request plane: "
                "engine rows (state, queue depth, active slots, token "
                "EWMA) + TTFT/token latency quantiles + SLO counts "
                "(serving_trace.digest_section); absent on ranks that "
                "never served — optional, schema stays v1"),
}


def validate_fleet_digest(rec: Dict[str, Any]):
    """Raise ValueError unless ``rec`` conforms to FLEET_DIGEST_FIELDS."""
    _validate_fields(rec, FLEET_DIGEST_FIELDS,
                     FLEET_DIGEST_SCHEMA_VERSION, "fleet digest")


# Straggler records ({v, ts, rank, phase, step_wall_ms, median_wall_ms,
# factor, steps, world, deltas_ms}) are produced by fleet_monitor's
# cross-rank skew detector; the version lives here with the other
# telemetry schemas (the stall-record precedent: version constant, doc
# in the producing module).
STRAGGLER_RECORD_SCHEMA_VERSION = 1


# ---------------------------------------------------------------------------
# device-memory watermarks
# ---------------------------------------------------------------------------

_M_DEV_IN_USE = None
_M_DEV_PEAK = None


def _devmem_instruments():
    global _M_DEV_IN_USE, _M_DEV_PEAK
    if _M_DEV_IN_USE is None:
        _M_DEV_IN_USE = gauge(
            "pt_device_bytes_in_use",
            "device memory in use at the last sampled step, by device "
            "(guarded Device.memory_stats(); absent on backends without "
            "the API)")
        _M_DEV_PEAK = gauge(
            "pt_device_bytes_peak",
            "device-memory high-water mark reported at the last sampled "
            "step, by device (guarded Device.memory_stats())")


# cached hot value of device_memory_every_n_steps (0 = off); sampling
# additionally needs telemetry on
_devmem_every = 0


def _sync_devmem_every(value):
    global _devmem_every
    _devmem_every = int(value)


def devmem_active() -> bool:
    """Whether executors should sample device-memory watermarks."""
    return _enabled and _devmem_every > 0


def device_memory() -> Dict[str, Dict[str, int]]:
    """Guarded read of every local device's ``memory_stats()``:
    ``{device: {bytes_in_use, peak_bytes}}``, silently empty on CPU or
    any backend without the API. Never raises."""
    out: Dict[str, Dict[str, int]] = {}
    try:
        import jax

        for d in jax.local_devices():
            stats_fn = getattr(d, "memory_stats", None)
            stats = stats_fn() if stats_fn is not None else None
            if not stats:
                continue
            in_use = stats.get("bytes_in_use")
            peak = stats.get("peak_bytes_in_use")
            cell: Dict[str, int] = {}
            if in_use is not None:
                cell["bytes_in_use"] = int(in_use)
            if peak is not None:
                cell["peak_bytes"] = int(peak)
            if cell:
                out[str(d)] = cell
    except Exception:
        pass  # watermarks are strictly best-effort
    return out


def sample_device_memory(step: int, steps: int = 1):
    """Sample device-memory watermarks into the
    ``pt_device_bytes_in_use/peak{device=}`` gauges when the
    ``device_memory_every_n_steps`` period has a sample point inside
    ``[step, step + steps)`` (the trace_step_sampled convention, so
    run_steps windows sample whenever any inner step would). No-op —
    one int check — while telemetry is off or the period is 0; degrades
    silently on backends without ``Device.memory_stats()``."""
    if not _enabled or _devmem_every <= 0:
        return
    if _devmem_every > 1 and (-step) % _devmem_every >= steps:
        return
    _devmem_instruments()
    for dev, cell in device_memory().items():
        if "bytes_in_use" in cell:
            _M_DEV_IN_USE.set(cell["bytes_in_use"], labels={"device": dev})
        if "peak_bytes" in cell:
            _M_DEV_PEAK.set(cell["peak_bytes"], labels={"device": dev})


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

OOM_REPORT_SCHEMA_VERSION = 1

# field name -> (accepted types, required, doc); the report an operator
# reads AFTER a device OOM killed the step — what was the high-water
# estimate, what was the budget, what was live, what were the last steps.
OOM_REPORT_FIELDS: Dict[str, tuple] = {
    "v": ((int,), True, "schema version (OOM_REPORT_SCHEMA_VERSION)"),
    "ts": ((float, int), True, "wall-clock unix timestamp of the OOM"),
    "seq": ((int,), True, "process-wide OOM report sequence number"),
    "phase": ((str,), True,
              "'compile' (OOM while building the executable) or 'run' "
              "(OOM while executing a step)"),
    "program": ((str, type(None)), True,
                "program id ('program<uid>') or null"),
    "error": ((str,), True, "the failure message (truncated)"),
    "budget_bytes": ((int,), True,
                     "the device_memory_budget_bytes flag at OOM time "
                     "(0 = no budget configured)"),
    "compile_peak_bytes": ((int, type(None)), True,
                           "peak-bytes estimate from the program's "
                           "latest compile report, or null when no "
                           "report exists"),
    "device_memory": ((dict,), True,
                      "per-device {bytes_in_use, peak_bytes} watermarks "
                      "at OOM time (empty when the API is absent)"),
    "largest_buffers": ((list,), True,
                        "largest live device buffers, descending: "
                        "[{nbytes, shape, dtype}] (best-effort via "
                        "jax.live_arrays)"),
    "last_steps": ((list,), True,
                   "trailing step records from the flight recorder"),
}

_OOM_RECORDS: collections.deque = collections.deque(maxlen=8)
_oom_seq = 0

_M_OOM = None


def _oom_counter():
    global _M_OOM
    if _M_OOM is None:
        _M_OOM = counter(
            "pt_oom_events_total",
            "RESOURCE_EXHAUSTED failures captured by the OOM forensics "
            "hook, by phase (compile/run/fetch/prefetch/serve)")
    return _M_OOM


def is_oom_error(exc) -> bool:
    """Whether ``exc`` is a device out-of-memory failure — jax surfaces
    OOM as XlaRuntimeError text, not a dedicated type, so this is a
    message heuristic (the single copy: bench_common's OOM backoff
    delegates here)."""
    msg = f"{type(exc).__name__}: {exc}"
    return "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg


def _largest_live_buffers(n: int = 10) -> List[Dict[str, Any]]:
    try:
        import jax

        arrs = []
        for a in jax.live_arrays():
            nb = getattr(a, "nbytes", None)
            if nb is None:
                continue
            arrs.append({"nbytes": int(nb),
                         "shape": tuple(getattr(a, "shape", ())),
                         "dtype": str(getattr(a, "dtype", "?"))})
        arrs.sort(key=lambda c: -c["nbytes"])
        return arrs[:n]
    except Exception:
        return []


def maybe_record_oom(exc, program=None, phase: str = "run"):
    """OOM forensics hook: when telemetry is on and ``exc`` is a device
    OOM, assemble a report (compile-report peak vs the memory-budget
    flag, largest live buffers, device watermarks, trailing step
    records), buffer it, count ``pt_oom_events_total{phase=}`` and —
    when ``stall_dump_dir`` is set — dump it as
    ``oom-<seq>-<ts>.json``. Never raises and never swallows: callers
    re-raise the original failure."""
    global _oom_seq
    if not _enabled or not is_oom_error(exc):
        return
    try:
        prog = None if program is None else f"program{program._uid}"
        report = None
        if prog is not None:
            report = compile_reports().get(prog)
        with _LOCK:
            seq = _oom_seq
            _oom_seq += 1
        rec = {
            "v": OOM_REPORT_SCHEMA_VERSION,
            "ts": time.time(),
            "seq": seq,
            "phase": str(phase),
            "program": prog,
            "error": f"{type(exc).__name__}: {exc}"[:2000],
            "budget_bytes": int(_mem_budget),
            "compile_peak_bytes": (None if report is None
                                   else report.get("peak_bytes")),
            "device_memory": device_memory(),
            "largest_buffers": _largest_live_buffers(),
            "last_steps": recent_steps(8),
        }
        _OOM_RECORDS.append(rec)
        _oom_counter().inc(labels={"phase": str(phase)})
        warnings.warn(
            f"device OOM during {phase} of {prog or 'a program'}: "
            f"compile-report peak "
            f"{rec['compile_peak_bytes'] or 'unknown'} B vs budget "
            f"{_mem_budget or 'unset'} B — forensics report buffered"
            + (f" and dumped under "
               f"{_flags.get_flag('stall_dump_dir')!r}"
               if _flags.get_flag("stall_dump_dir") else ""),
            RuntimeWarning)
        dump_dir = _flags.get_flag("stall_dump_dir")
        if dump_dir:
            os.makedirs(dump_dir, exist_ok=True)
            path = os.path.join(
                dump_dir, f"oom-{seq}-{int(rec['ts'])}.json")
            with open(path, "w") as f:
                json.dump(rec, f, sort_keys=True, indent=1, default=str)
    except Exception as e:
        try:
            warnings.warn(f"OOM report dropped: {e!r}", RuntimeWarning)
        except Exception:
            pass


def oom_records() -> List[Dict[str, Any]]:
    """Buffered OOM forensics reports, oldest first."""
    return [dict(r) for r in _OOM_RECORDS]


def validate_oom_report(rec: Dict[str, Any]):
    """Raise ValueError unless ``rec`` conforms to OOM_REPORT_FIELDS."""
    _validate_fields(rec, OOM_REPORT_FIELDS,
                     OOM_REPORT_SCHEMA_VERSION, "OOM report")


# ---------------------------------------------------------------------------
# time attribution: step phases + boundedness verdict
# ---------------------------------------------------------------------------

# Phase names, in execution order. The executor measures each with
# perf_counter pairs; the semantics are documented in STEP_LOG_FIELDS
# ('phases') and README "Step-time attribution & traces".
STEP_PHASES = ("feed", "dispatch", "device", "fetch")

BOUND_VERDICTS = ("input_bound", "dispatch_bound", "device_bound")

# Rolling verdict window: per-step (input, dispatch, device) scores of
# the last N steps. Small on purpose — the verdict should track the
# CURRENT bottleneck, not average a warmup compile into steady state.
BOUND_WINDOW = 16

_M_STEP_PHASE = None
_M_STEP_BOUND = None
_M_READER_DEPTH = None
_M_READER_WAIT = None
_M_FEED_BUILD = None
_M_PREFETCH_DEPTH = None
_M_FETCH_OVERLAP = None


def _phase_instruments():
    global _M_STEP_PHASE, _M_STEP_BOUND, _M_READER_DEPTH, _M_READER_WAIT
    global _M_FEED_BUILD, _M_PREFETCH_DEPTH, _M_FETCH_OVERLAP
    if _M_STEP_PHASE is None:
        _M_STEP_PHASE = histogram(
            "pt_step_phase_seconds",
            "per-step executor time attribution, by phase (feed = "
            "host->device staging, dispatch = Python + tracing "
            "overhead, device = delta to block_until_ready, fetch = "
            "device->host + decode)")
        _M_STEP_BOUND = counter(
            "pt_step_bound_total",
            "steps attributed to each boundedness verdict over the "
            "trailing window (input_bound / dispatch_bound / "
            "device_bound)")
        _M_READER_DEPTH = gauge(
            "pt_reader_queue_depth",
            "input-pipeline queue depth after the latest put/get, by "
            "site (buffered, xmap_in, xmap_out, multiprocess, "
            "device_loader)")
        _M_READER_WAIT = histogram(
            "pt_reader_wait_seconds",
            "time blocked on input-pipeline queues, by site and role "
            "(producer = queue full, downstream slow; consumer = queue "
            "empty, input-bound)")
        _M_FEED_BUILD = histogram(
            "pt_feed_build_seconds",
            "DataFeeder.feed batch-assembly time (host input prep on "
            "the critical path)")
        _M_PREFETCH_DEPTH = gauge(
            "pt_prefetch_depth",
            "configured device-feed prefetch depth of the most recently "
            "started DeviceLoader iteration")
        _M_FETCH_OVERLAP = histogram(
            "pt_fetch_overlap_seconds",
            "async-fetch overlap window: time between a step's deferred "
            "device->host fetch being issued and its materialization")


# cached hot gate for the executor's phase marks: telemetry on AND the
# step_phases flag (default True). Separate from `telemetry` because the
# device phase needs a per-step block_until_ready — honest attribution
# costs the async-dispatch overlap, and metrics-only users can opt out.
_phases_on = False
# cached step_phases_every_n: the sampling period bounding how often a
# step pays that sync — unsampled steps dispatch fully async
_phases_every = 16


def phases_active() -> bool:
    """Whether executors should measure per-step phases (telemetry on
    and the ``step_phases`` flag set)."""
    return _phases_on


def phases_sampled(step: int, steps: int = 1) -> bool:
    """Whether the phase plane samples ``[step, step + steps)``: phases
    active AND the ``step_phases_every_n`` period has a sample point
    inside the interval (same no-aliasing window rule as
    ``trace_step_sampled``). Only sampled steps pay the per-step
    ``block_until_ready``; unsampled steps dispatch fully async and log
    ``sampled: false`` records without phases."""
    if not _phases_on:
        return False
    if _phases_every <= 1:
        return True
    return (-step) % _phases_every < steps


def _sync_phases_on(_value=None):
    global _phases_on, _input_wait_s
    was = _phases_on
    _phases_on = _enabled and bool(_flags.get_flag("step_phases"))
    if _phases_on and not was:
        # waits accumulated while nobody was draining (phases off, or a
        # failed-step run) must not dump into the first attributed
        # step's input score and pin the verdict to input_bound
        with _BOUND_LOCK:
            _input_wait_s = 0.0


def _sync_phases_every(value):
    global _phases_every
    _phases_every = int(value)


# input-wait accumulator: reader consumer waits + feed-build time since
# the last executor step, drained into that step's verdict scores
_BOUND_LOCK = threading.Lock()
_input_wait_s = 0.0
_bound_window: collections.deque = collections.deque(maxlen=BOUND_WINDOW)
_last_bound: Optional[Dict[str, Any]] = None


def note_input_wait(seconds: float):
    """Accumulate input-pipeline time (a consumer wait on a reader
    queue, or batch-assembly time) toward the NEXT step's boundedness
    verdict. Gated on ``phases_active()`` — with nobody draining the
    accumulator (phases off), accumulation would only grow a stale
    backlog."""
    global _input_wait_s
    if not _phases_on:
        return
    with _BOUND_LOCK:
        _input_wait_s += seconds


def discard_input_wait():
    """Drop input waits accumulated since the last drain. Executors
    call this after an UNSAMPLED step (``step_phases_every_n``): the
    next sampled step must score only ITS OWN input time — draining a
    whole sampling period's backlog into one step would inflate the
    input share by the period length."""
    global _input_wait_s
    if not _phases_on:
        return
    with _BOUND_LOCK:
        _input_wait_s = 0.0


def reader_wait(site: str, role: str, seconds: float):
    """Record one blocked queue operation from the input pipeline
    (``role``: 'producer' = put blocked on a full queue, 'consumer' =
    get blocked on an empty one). Consumer waits additionally count
    toward the boundedness verdict — a step that waited on its reader
    is input-bound no matter how busy the device was afterwards."""
    if not _enabled:
        return
    _M_READER_WAIT.observe(seconds, labels={"site": site, "role": role})
    if role == "consumer":
        note_input_wait(seconds)


def reader_depth(site: str, depth: int):
    """Gauge the queue depth observed after a put/get at ``site``."""
    if not _enabled:
        return
    _M_READER_DEPTH.set(depth, labels={"site": site})


def feed_build(seconds: float, critical_path: bool = True):
    """Record one DataFeeder.feed batch assembly (host input prep);
    counts toward the boundedness verdict's input score unless
    ``critical_path=False`` (a prefetch worker building batches off the
    step loop — overlapped assembly time must not fake an input_bound
    verdict; the consumer's queue wait is the honest signal there)."""
    if not _enabled:
        return
    _M_FEED_BUILD.observe(seconds)
    if critical_path:
        note_input_wait(seconds)


def prefetch_depth(depth: int):
    """Gauge the configured depth of a starting DeviceLoader iteration."""
    if not _enabled:
        return
    _M_PREFETCH_DEPTH.set(depth)


def fetch_overlap(seconds: float):
    """Record one async-fetch overlap window: issue -> materialization
    of a step's deferred device->host fetch."""
    if not _enabled:
        return
    _M_FETCH_OVERLAP.observe(seconds)


def timed_put(q, item, site: str):
    """``q.put(item)`` with producer-wait + depth telemetry for queue
    ``site`` (a plain put while telemetry is off) — the one shared
    instrumentation point for every reader-pipeline queue
    (``timed_put_stoppable`` is its stop-aware twin)."""
    if not _enabled:
        q.put(item)
        return
    t0 = time.perf_counter()
    q.put(item)
    reader_wait(site, "producer", time.perf_counter() - t0)
    reader_depth(site, q.qsize())


def timed_put_stoppable(q, item, stop, site: str,
                        poll_s: float = 0.1) -> bool:
    """``q.put(item)`` that gives up when ``stop`` is set; returns
    whether the item was enqueued. The stop-aware variant of
    ``timed_put`` (same producer-wait + depth telemetry, one
    instrumentation point) for prefetch workers whose consumer may
    abandon them — ``poll_s`` bounds how long a blocked put takes to
    observe the stop request."""
    t0 = time.perf_counter() if _enabled else 0.0
    while not stop.is_set():
        try:
            q.put(item, timeout=poll_s)
        except queue.Full:
            continue
        if t0:
            reader_wait(site, "producer", time.perf_counter() - t0)
            reader_depth(site, q.qsize())
        return True
    return False


def timed_get(q, site: str):
    """``q.get()`` with consumer-wait + depth telemetry for queue
    ``site`` (consumer waits weigh into the boundedness verdict)."""
    if not _enabled:
        return q.get()
    t0 = time.perf_counter()
    item = q.get()
    reader_wait(site, "consumer", time.perf_counter() - t0)
    reader_depth(site, q.qsize())
    return item


def record_step_phases(feed_s: float, dispatch_s: float, device_s: float,
                       fetch_s: float, scored: bool = True
                       ) -> Optional[str]:
    """Record one step's phase breakdown: observes the
    ``pt_step_phase_seconds`` histograms, drains the input-wait
    accumulator into this step, pushes the scores into the rolling
    verdict window and returns the window's verdict (also counted into
    ``pt_step_bound_total{verdict=}``).

    ``scored=False`` (a fresh-compile / disk-load step): the histograms
    still observe the honest phase durations, but the step stays OUT of
    the verdict window — a compile's host time would pollute the
    dispatch share of the next BOUND_WINDOW sampled steps — and its
    accumulated input waits are discarded rather than dumped into the
    next scored step. Returns None for unscored steps.

    Verdict scoring: ``input`` = reader consumer waits + feed-build
    time since the last step + the feed phase (host->device staging is
    the input pipeline's device half); ``dispatch`` = dispatch + fetch
    (host overhead around the device call); ``device`` = the device
    phase. The largest share over the window names the bottleneck."""
    global _last_bound, _input_wait_s
    if not _enabled:
        return None
    _M_STEP_PHASE.observe(feed_s, labels={"phase": "feed"})
    _M_STEP_PHASE.observe(dispatch_s, labels={"phase": "dispatch"})
    _M_STEP_PHASE.observe(device_s, labels={"phase": "device"})
    _M_STEP_PHASE.observe(fetch_s, labels={"phase": "fetch"})
    if not scored:
        with _BOUND_LOCK:
            _input_wait_s = 0.0
        return None
    with _BOUND_LOCK:
        input_s = _input_wait_s + feed_s
        _input_wait_s = 0.0
        _bound_window.append((input_s, dispatch_s + fetch_s, device_s))
        sums = [sum(col) for col in zip(*_bound_window)]
        total = sum(sums) or 1.0
        scores = dict(zip(("input", "dispatch", "device"), sums))
        verdict = BOUND_VERDICTS[sums.index(max(sums))]
        _last_bound = {
            "verdict": verdict,
            "shares": {k: v / total for k, v in scores.items()},
            "steps": len(_bound_window),
        }
    _M_STEP_BOUND.inc(labels={"verdict": verdict})
    return verdict


def boundedness() -> Optional[Dict[str, Any]]:
    """Latest boundedness verdict: ``{verdict, shares: {input,
    dispatch, device}, steps}`` over the trailing window, or None before
    the first telemetry-on step."""
    with _BOUND_LOCK:
        if _last_bound is None:
            return None
        return {"verdict": _last_bound["verdict"],
                "shares": dict(_last_bound["shares"]),
                "steps": _last_bound["steps"]}


# ---------------------------------------------------------------------------
# trace-event timeline (Chrome trace / Perfetto)
# ---------------------------------------------------------------------------

TRACE_SCHEMA_VERSION = 1

# The memory contract: a week-long job buffers the same trailing window
# as a smoke test. At ~120 B/event this is ~1 MB.
TRACE_RING_CAPACITY = 8192

# One clock for every event: perf_counter intervals anchored ONCE to the
# wall clock at import. ts values are unix-epoch microseconds (what
# Perfetto expects), but their DELTAS are monotonic perf_counter deltas
# — a wall-clock step (NTP slew) can never reorder or stretch the
# timeline within a process.
_TRACE_ANCHOR_PERF = time.perf_counter()
_TRACE_ANCHOR_UNIX = time.time()

# Synthetic track (tid) per event category, so spans, step phases,
# compiles and stalls render as distinct rows instead of interleaving on
# the emitting thread's row. Names are exported as thread_name metadata.
TRACE_TRACKS = {
    "span": (1, "host spans"),
    "phase": (2, "step phases"),
    "compile": (3, "compiles"),
    "stall": (4, "stalls"),
    "profiler": (5, "profiler"),
}

_TRACE_LOCK = threading.Lock()
_TRACE_RING: collections.deque = collections.deque(
    maxlen=TRACE_RING_CAPACITY)

# Dynamic per-request tracks (serving_trace.py): tids at or above this
# base are allocated at runtime and labelled via trace_register_track;
# the registry is bounded so the snapshot's metadata block stays small
# when a server churns through many requests (an aged-out track keeps
# its events — only the thread_name label is dropped).
REQUEST_TRACK_BASE = 32
_DYN_TRACK_CAP = 128
_DYN_TRACKS: "collections.OrderedDict[int, str]" = collections.OrderedDict()


def trace_register_track(tid: int, name: str):
    """Label a dynamically allocated track: exported as thread_name
    metadata in ``trace_snapshot``. No-op while tracing is inactive;
    re-registering a tid replaces its label (tracks are recycled
    round-robin by the request plane)."""
    if not _trace_on:
        return
    tid = int(tid)
    with _TRACE_LOCK:
        _DYN_TRACKS[tid] = str(name)
        _DYN_TRACKS.move_to_end(tid)
        while len(_DYN_TRACKS) > _DYN_TRACK_CAP:
            _DYN_TRACKS.popitem(last=False)

# cached hot gate: telemetry on AND someone can see the trace (trace_dir
# configured or the live endpoint up) — same visibility rule as compile
# reports, so tracing is never on by accident
_trace_on = False
_trace_every = 1
_trace_rank = 0
_HOSTNAME = (os.environ.get("HOSTNAME") or "host").split(".")[0]

_M_TRACE_EVENTS = None
_M_TRACE_DROPPED = None


def _trace_instruments():
    global _M_TRACE_EVENTS, _M_TRACE_DROPPED
    if _M_TRACE_EVENTS is None:
        _M_TRACE_EVENTS = counter(
            "pt_trace_events_total",
            "trace events appended to the timeline ring")
        _M_TRACE_DROPPED = counter(
            "pt_trace_events_dropped_total",
            "oldest trace events evicted by the bounded ring")


def trace_active() -> bool:
    """True when trace events are being collected: telemetry on AND
    (``trace_dir`` configured or the live endpoint running)."""
    return _trace_on


def trace_step_sampled(step: int, steps: int = 1) -> bool:
    """Gate for per-step phase trace events: tracing active and the
    ``trace_every_n_steps`` period has a sample point inside
    ``[step, step + steps)`` — so a run_steps window is sampled whenever
    ANY of its steps would be, instead of aliasing the window stride
    against the period."""
    if not _trace_on:
        return False
    if _trace_every <= 1:
        return True
    return (-step) % _trace_every < steps


def _ts_us(t_perf: float) -> float:
    return (_TRACE_ANCHOR_UNIX + (t_perf - _TRACE_ANCHOR_PERF)) * 1e6


def trace_event(name: str, cat: str, t0: float,
                t1: Optional[float] = None,
                args: Optional[Dict[str, Any]] = None,
                tid: Optional[int] = None):
    """Append one event to the timeline ring (no-op unless
    ``trace_active()``). ``t0``/``t1`` are ``time.perf_counter``
    readings: a pair makes a complete ('X') event with a duration, a
    lone ``t0`` an instant ('i') event. ``tid`` overrides the
    category's synthetic track — the request plane lands a request's
    whole life on one dynamic track this way. Never raises — telemetry
    must not fail a step."""
    if not _trace_on:
        return
    ev: Dict[str, Any] = {
        "name": name,
        "cat": cat,
        "ph": "X" if t1 is not None else "i",
        "ts": _ts_us(t0),
        "pid": os.getpid(),
        "tid": (TRACE_TRACKS.get(cat, (0, ""))[0] if tid is None
                else int(tid)),
    }
    if t1 is not None:
        ev["dur"] = max(t1 - t0, 0.0) * 1e6
    else:
        ev["s"] = "p"  # instant events span the process track
    if args:
        ev["args"] = args
    with _TRACE_LOCK:
        dropped = len(_TRACE_RING) == TRACE_RING_CAPACITY
        _TRACE_RING.append(ev)
    _M_TRACE_EVENTS.inc()
    if dropped:
        _M_TRACE_DROPPED.inc()


def _emit_span_trace(name: str, t0: float, t1: float):
    """profiler.record_event trace hook target: every host span —
    monitor.span bodies AND legacy direct record_event callers — lands
    in the ring through this one function, on the profiler's clock."""
    trace_event(name, "span", t0, t1)


def _span_trace_hook():
    """Installed as profiler._trace_hook: returns the emit function
    while tracing is active, else None (one boolean check, no
    allocation — record_event sits on disabled hot paths)."""
    return _emit_span_trace if _trace_on else None


def set_trace_rank(rank: int):
    """Tag this process's exported trace with its fleet rank (called by
    fleet.init) so merge_traces lands its events on the right track."""
    global _trace_rank
    _trace_rank = int(rank)


def trace_events() -> List[Dict[str, Any]]:
    """Buffered trace events, ts-ordered (the ring is append-ordered
    per thread; sorting makes ts monotone per track)."""
    with _TRACE_LOCK:
        evs = [dict(e) for e in _TRACE_RING]
    evs.sort(key=lambda e: e["ts"])
    return evs


def trace_snapshot() -> Dict[str, Any]:
    """The exportable Chrome-trace JSON object: thread/process metadata
    events + the ts-sorted ring, plus a ``metadata`` block carrying the
    clock anchor and rank that merge_traces aligns on."""
    pid = os.getpid()
    meta_events: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "ts": 0, "pid": pid, "tid": 0,
        "args": {"name": f"rank{_trace_rank} ({_HOSTNAME}:{pid})"},
    }]
    for _cat, (tid, label) in sorted(TRACE_TRACKS.items()):
        meta_events.append({
            "name": "thread_name", "ph": "M", "ts": 0, "pid": pid,
            "tid": tid, "args": {"name": label},
        })
    with _TRACE_LOCK:
        dyn = sorted(_DYN_TRACKS.items())
    for tid, label in dyn:
        meta_events.append({
            "name": "thread_name", "ph": "M", "ts": 0, "pid": pid,
            "tid": tid, "args": {"name": label},
        })
    return {
        "traceEvents": meta_events + trace_events(),
        "displayTimeUnit": "ms",
        "metadata": {
            "v": TRACE_SCHEMA_VERSION,
            "rank": _trace_rank,
            "host": _HOSTNAME,
            "os_pid": pid,
            "anchor_unix": _TRACE_ANCHOR_UNIX,
        },
    }


def export_trace(path: Optional[str] = None) -> Optional[str]:
    """Write the trace snapshot as JSON: to ``path`` when given, else
    as ``trace-<host>-<pid>.json`` under the ``trace_dir`` flag (None
    and no write when neither is set). Returns the written path."""
    if path is None:
        out_dir = _flags.get_flag("trace_dir")
        if not out_dir:
            return None
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(
            out_dir, f"trace-{_HOSTNAME}-{os.getpid()}.json")
    with open(path, "w") as f:
        json.dump(trace_snapshot(), f, default=str)
    return path


def merge_traces(traces: Iterable, out_path: Optional[str] = None,
                 offsets_us: Optional[Dict[int, float]] = None) -> Dict:
    """Combine per-process trace files (paths or already-loaded dicts)
    into ONE timeline: each worker's events move onto ``pid = rank``
    tracks (rank from the trace's metadata, falling back to input
    order) and timestamps align across processes.

    Clock-offset alignment: every export's ts values are anchored to
    that process's wall clock at import (``metadata.anchor_unix``), so
    NTP-synced hosts line up out of the box; a residual measured skew
    can be corrected per rank via ``offsets_us``. The merged timeline
    is rebased to start at 0 — a multi-worker stall reads as one gap
    across all rank tracks."""
    loaded = []
    seen_ranks = set()
    for i, t in enumerate(traces):
        if isinstance(t, str):
            with open(t) as f:
                t = json.load(f)
        meta = t.get("metadata") or {}
        rank = meta.get("rank")
        if rank is None or rank in seen_ranks:
            # collision/absence fallback: the smallest unused rank, so
            # two traces can never share a pid track (input order is
            # preserved for the well-tagged common case)
            rank = 0
            while rank in seen_ranks:
                rank += 1
        seen_ranks.add(rank)
        off = float((offsets_us or {}).get(rank, 0.0))
        loaded.append((rank, off, t))
    base = None
    for rank, off, t in loaded:
        for ev in t.get("traceEvents", ()):
            if ev.get("ph") != "M":
                ts = float(ev.get("ts", 0.0)) + off
                base = ts if base is None else min(base, ts)
    base = base or 0.0
    meta_events: List[Dict[str, Any]] = []
    data_events: List[Dict[str, Any]] = []
    for rank, off, t in loaded:
        for ev in t.get("traceEvents", ()):
            ev = dict(ev)
            ev["pid"] = rank
            if ev.get("ph") == "M":
                meta_events.append(ev)
            else:
                ev["ts"] = float(ev.get("ts", 0.0)) + off - base
                data_events.append(ev)
    data_events.sort(key=lambda e: e["ts"])
    merged = {
        "traceEvents": meta_events + data_events,
        "displayTimeUnit": "ms",
        "metadata": {
            "v": TRACE_SCHEMA_VERSION,
            "merged_ranks": sorted(seen_ranks),
        },
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(merged, f, default=str)
    return merged


def _sync_trace_on(_value=None):
    global _trace_on
    _trace_on = _enabled and (bool(_flags.get_flag("trace_dir"))
                              or _server is not None)


def _sync_trace_every(value):
    global _trace_every
    _trace_every = int(value)


def _dump_trace_at_exit():
    if _enabled and _flags.get_flag("trace_dir"):
        try:
            export_trace()
        except OSError:
            pass


atexit.register(_dump_trace_at_exit)


# Eagerly register monitor-owned instruments: a /metrics scrape (or the
# doc-coverage test) sees the full builtin set even before the first
# span/stall/compile happens.
_span_seconds = histogram(
    "pt_span_seconds", "host span durations by span name")
_overflow_total()
_stall_counter()
_compile_instruments()
_phase_instruments()
_trace_instruments()
_devmem_instruments()
_oom_counter()

# Route every profiler.record_event host span into the trace ring: the
# legacy profiler API and the new timeline share one clock and one
# event stream (the hook returns None while tracing is off, so the
# record_event disabled path stays a bare yield).
_profiler._trace_hook = _span_trace_hook

# register watchers last so the module is fully initialized when the
# immediate callbacks fire (env-set flags take effect at import)
_flags.watch_flag("telemetry", _sync_from_flags)
_flags.watch_flag("telemetry", _maybe_autostart_server)
_flags.watch_flag("telemetry", _sync_trace_on)
_flags.watch_flag("telemetry", _sync_phases_on)
_flags.watch_flag("step_phases", _sync_phases_on)
_flags.watch_flag("step_phases_every_n", _sync_phases_every)
_flags.watch_flag("metrics_port", _maybe_autostart_server)
_flags.watch_flag("trace_dir", _sync_trace_on)
_flags.watch_flag("trace_every_n_steps", _sync_trace_every)
_flags.watch_flag("device_memory_budget_bytes", _sync_mem_budget)
_flags.watch_flag("stall_timeout_ms", _sync_stall_ms)
_flags.watch_flag("device_memory_every_n_steps", _sync_devmem_every)
