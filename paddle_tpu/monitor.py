"""Runtime telemetry plane: metrics registry, structured step logs, spans.

The reference framework shipped a real observability stack (RecordEvent
host spans + CUPTI DeviceTracer + tools/timeline.py chrome traces); this
module is its runtime-metrics half, grown past the reference: one
process-wide plane with three pillars.

1. **Metrics registry** — ``counter()``/``gauge()``/``histogram()`` return
   process-wide named instruments with optional labels. Every mutation
   checks one module-level boolean first, so with telemetry off (the
   default) a call costs a flag check and allocates nothing — hot paths
   (``Executor.run``) stay instrumented permanently. ``snapshot()``
   returns plain dicts; ``dump_metrics()`` exports Prometheus text or
   JSON.

2. **Structured step logs** — ``log_step(record)`` appends one JSONL
   record per executor step to the ``step_log_path`` flag's file. The
   schema is versioned (``STEP_LOG_SCHEMA_VERSION``) and documented
   field-by-field in ``STEP_LOG_FIELDS`` (also README "Observability").

3. **Span unification** — ``span(name)`` wraps
   ``profiler.record_event`` so host spans from the executor, trainer
   epoch/step events, fleet barrier waits, ring-attention rotations and
   pipeline schedules all land in ONE chrome-trace timeline under
   consistent dotted names; with telemetry on, every span additionally
   feeds the ``pt_span_seconds`` histogram (interval measured with
   ``time.perf_counter`` — wall clock is only ever used for
   human-readable timestamps).

Everything is off by default behind the typed flags ``telemetry``,
``step_log_path`` and ``metrics_dump_path`` (flags.py); flipping
``telemetry`` at runtime takes effect immediately via a flag watcher.
"""

from __future__ import annotations

import atexit
import contextlib
import io
import json
import threading
import time
from typing import Any, Dict, Iterable, Optional, Tuple

from paddle_tpu import flags as _flags
from paddle_tpu import profiler as _profiler

# ---------------------------------------------------------------------------
# enable/disable plumbing
# ---------------------------------------------------------------------------

# THE fast-path flag: every instrument mutation reads this one module-level
# boolean and returns before touching any other state when it is False.
_enabled = False

_LOCK = threading.Lock()

# The step-log writer gets its OWN lock: log_step does disk I/O (write +
# flush per record) and must never stall metric mutations under _LOCK.
_STEP_LOG_LOCK = threading.Lock()

# step-log writer state (lazily opened; keyed by path so a flag change
# mid-process rotates to the new file)
_step_log_file: Optional[io.TextIOBase] = None
_step_log_path: str = ""
_step_seq = 0


def enabled() -> bool:
    """Whether telemetry is on (cached value of the ``telemetry`` flag)."""
    return _enabled


def _sync_from_flags(_value=None):
    global _enabled
    _enabled = bool(_flags.get_flag("telemetry"))


def enable(step_log_path: Optional[str] = None,
           metrics_dump_path: Optional[str] = None):
    """Convenience: flip the ``telemetry`` flag (and optionally the log /
    dump path flags) on. Equivalent to ``flags.set_flags({...})``."""
    new = {"telemetry": True}
    if step_log_path is not None:
        new["step_log_path"] = step_log_path
    if metrics_dump_path is not None:
        new["metrics_dump_path"] = metrics_dump_path
    _flags.set_flags(new)


def disable():
    _flags.set_flags({"telemetry": False})


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

# label values are keyed by a sorted (k, v) tuple; () is the unlabelled cell
_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Dict[str, Any]]) -> _LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter. ``inc`` is a no-op (one flag check, zero
    allocations) while telemetry is off."""

    kind = "counter"
    __slots__ = ("name", "doc", "_cells")

    def __init__(self, name: str, doc: str):
        self.name = name
        self.doc = doc
        self._cells: Dict[_LabelKey, float] = {}

    def inc(self, n: float = 1, labels: Optional[Dict[str, Any]] = None):
        if not _enabled:
            return
        key = _label_key(labels)
        with _LOCK:
            self._cells[key] = self._cells.get(key, 0.0) + n

    def value(self, labels: Optional[Dict[str, Any]] = None) -> float:
        return self._cells.get(_label_key(labels), 0.0)


class Gauge:
    """Last-value instrument (``set``) with an ``add`` for +/- deltas."""

    kind = "gauge"
    __slots__ = ("name", "doc", "_cells")

    def __init__(self, name: str, doc: str):
        self.name = name
        self.doc = doc
        self._cells: Dict[_LabelKey, float] = {}

    def set(self, v: float, labels: Optional[Dict[str, Any]] = None):
        if not _enabled:
            return
        with _LOCK:
            self._cells[_label_key(labels)] = float(v)

    def add(self, n: float = 1, labels: Optional[Dict[str, Any]] = None):
        if not _enabled:
            return
        key = _label_key(labels)
        with _LOCK:
            self._cells[key] = self._cells.get(key, 0.0) + n

    def value(self, labels: Optional[Dict[str, Any]] = None) -> float:
        return self._cells.get(_label_key(labels), 0.0)


# default buckets: tuned for step/compile/barrier latencies in seconds
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: each bucket
    counts observations <= its upper bound; +Inf is implicit)."""

    kind = "histogram"
    __slots__ = ("name", "doc", "buckets", "_cells")

    def __init__(self, name: str, doc: str,
                 buckets: Iterable[float] = DEFAULT_BUCKETS):
        self.name = name
        self.doc = doc
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # cell: [counts per bucket..., +inf count, sum]
        self._cells: Dict[_LabelKey, list] = {}

    def observe(self, v: float, labels: Optional[Dict[str, Any]] = None):
        if not _enabled:
            return
        v = float(v)
        key = _label_key(labels)
        with _LOCK:
            cell = self._cells.get(key)
            if cell is None:
                cell = [0] * (len(self.buckets) + 1) + [0.0]
                self._cells[key] = cell
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    cell[i] += 1
                    break
            else:
                cell[len(self.buckets)] += 1
            cell[-1] += v

    def count(self, labels: Optional[Dict[str, Any]] = None) -> int:
        cell = self._cells.get(_label_key(labels))
        return int(sum(cell[:-1])) if cell else 0

    def sum(self, labels: Optional[Dict[str, Any]] = None) -> float:
        cell = self._cells.get(_label_key(labels))
        return float(cell[-1]) if cell else 0.0


_REGISTRY: Dict[str, Any] = {}


def _get_or_create(cls, name: str, doc: str, **kwargs):
    with _LOCK:
        m = _REGISTRY.get(name)
        if m is not None:
            if not isinstance(m, cls):
                raise TypeError(
                    f"metric '{name}' already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m
        m = cls(name, doc, **kwargs)
        _REGISTRY[name] = m
        return m


def counter(name: str, doc: str = "") -> Counter:
    return _get_or_create(Counter, name, doc)


def gauge(name: str, doc: str = "") -> Gauge:
    return _get_or_create(Gauge, name, doc)


def histogram(name: str, doc: str = "",
              buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
    h = _get_or_create(Histogram, name, doc, buckets=buckets)
    want = tuple(sorted(float(b) for b in buckets))
    if h.buckets != want:
        # silently returning the existing instrument would bucket the
        # caller's observations against bounds it never asked for
        raise ValueError(
            f"histogram '{name}' already registered with buckets "
            f"{h.buckets}, requested {want}")
    return h


def reset():
    """Zero every registered metric and close the step-log writer (test
    isolation). Metric OBJECTS survive — instrumented modules hold
    references to them, so dropping the registry would orphan live
    instruments into invisible counters."""
    global _step_log_file, _step_log_path, _step_seq, _step_log_warned
    with _LOCK:
        for m in _REGISTRY.values():
            m._cells.clear()
    with _STEP_LOG_LOCK:
        _step_log_warned = False
        if _step_log_file is not None:
            try:
                _step_log_file.close()
            except OSError:
                pass
        _step_log_file = None
        _step_log_path = ""
        _step_seq = 0


def snapshot() -> Dict[str, Any]:
    """Plain-dict view of every registered metric.

    ``{name: {"kind", "doc", "values": [{"labels": {...}, ...}]}}`` —
    counters/gauges carry ``value``; histograms carry ``count``, ``sum``
    and cumulative ``buckets`` ``[[upper_bound, count], ...]`` ending in
    the +Inf bucket.
    """
    out: Dict[str, Any] = {}
    with _LOCK:
        for name, m in sorted(_REGISTRY.items()):
            values = []
            for key, cell in sorted(m._cells.items()):
                labels = {k: v for k, v in key}
                if m.kind == "histogram":
                    cum, acc = [], 0
                    for ub, c in zip(m.buckets, cell):
                        acc += c
                        cum.append([ub, acc])
                    acc += cell[len(m.buckets)]
                    cum.append(["+Inf", acc])
                    values.append({"labels": labels, "count": acc,
                                   "sum": cell[-1], "buckets": cum})
                else:
                    values.append({"labels": labels, "value": cell})
            out[name] = {"kind": m.kind, "doc": m.doc, "values": values}
    return out


# --- exporters ---

def _prom_labels(labels: Dict[str, str], extra: Optional[tuple] = None) -> str:
    items = list(labels.items())
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    body = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", "\\\\")
                     .replace('"', '\\"').replace("\n", "\\n"))
        for k, v in items
    )
    return "{%s}" % body


def to_prometheus(snap: Optional[Dict[str, Any]] = None) -> str:
    """Prometheus text exposition format (# HELP / # TYPE / samples)."""
    snap = snapshot() if snap is None else snap
    lines = []
    for name, m in snap.items():
        if m["doc"]:
            lines.append(f"# HELP {name} {m['doc']}")
        lines.append(f"# TYPE {name} {m['kind']}")
        for cell in m["values"]:
            labels = cell["labels"]
            if m["kind"] == "histogram":
                for ub, c in cell["buckets"]:
                    lines.append(
                        f"{name}_bucket"
                        f"{_prom_labels(labels, ('le', ub))} {c}")
                lines.append(
                    f"{name}_sum{_prom_labels(labels)} {cell['sum']}")
                lines.append(
                    f"{name}_count{_prom_labels(labels)} {cell['count']}")
            else:
                lines.append(
                    f"{name}{_prom_labels(labels)} {cell['value']}")
    return "\n".join(lines) + ("\n" if lines else "")


def to_json(snap: Optional[Dict[str, Any]] = None) -> str:
    return json.dumps(snapshot() if snap is None else snap,
                      sort_keys=True, indent=1)


def dump_metrics(path: Optional[str] = None, fmt: str = "prometheus") -> str:
    """Export all metrics; returns the text, writes it to ``path`` (or the
    ``metrics_dump_path`` flag when set) too. ``fmt``: 'prometheus' or
    'json'."""
    if fmt in ("prometheus", "prom", "text"):
        text = to_prometheus()
    elif fmt == "json":
        text = to_json()
    else:
        raise ValueError(f"unknown metrics format '{fmt}'")
    path = path or _flags.get_flag("metrics_dump_path")
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text


def _dump_at_exit():
    if _enabled and _flags.get_flag("metrics_dump_path"):
        try:
            dump_metrics()
        except OSError:
            pass


atexit.register(_dump_at_exit)


# ---------------------------------------------------------------------------
# structured step logs
# ---------------------------------------------------------------------------

STEP_LOG_SCHEMA_VERSION = 1

# field name -> (accepted types, required, doc). The contract tests and
# README both derive from this table; bump STEP_LOG_SCHEMA_VERSION on any
# incompatible change.
STEP_LOG_FIELDS: Dict[str, tuple] = {
    "v": ((int,), True, "schema version (STEP_LOG_SCHEMA_VERSION)"),
    "ts": ((float, int), True,
           "wall-clock unix timestamp (human-readable anchor only; all "
           "durations are perf_counter intervals)"),
    "seq": ((int,), True, "process-wide record sequence number"),
    "kind": ((str,), True, "'step' (Executor.run) or 'window' (run_steps)"),
    "step": ((int,), True, "executor step index (first step of a window)"),
    "steps": ((int,), False, "window length (kind == 'window' only)"),
    "wall_ms": ((float, int), True,
                "host wall time of the run call, perf_counter-based"),
    "compile_ms": ((float, int, type(None)), True,
                   "XLA lower+jit wrap time; null on a cache hit"),
    "cache": ((str,), True, "compile-cache outcome: 'hit' or 'miss'"),
    "evictions": ((int,), True,
                  "cache entries evicted by this step's insert"),
    "feed_bytes": ((int,), True, "total bytes across feed arrays"),
    "fetch_bytes": ((int,), True, "total bytes across fetch arrays"),
    "nan_check": ((str, type(None)), True,
                  "'ok'/'fail' when check_nan_inf ran, else null"),
    "strategy": ((str, type(None)), True,
                 "SPMD strategy id (mesh axes) or null for plain runs"),
}


def validate_step_record(rec: Dict[str, Any]):
    """Raise ValueError unless ``rec`` conforms to STEP_LOG_FIELDS."""
    if not isinstance(rec, dict):
        raise ValueError(f"step record must be a dict, got {type(rec)}")
    for field, (types, required, _doc) in STEP_LOG_FIELDS.items():
        if field not in rec:
            if required:
                raise ValueError(f"step record missing field '{field}'")
            continue
        if not isinstance(rec[field], types):
            raise ValueError(
                f"step record field '{field}' has type "
                f"{type(rec[field]).__name__}, expected one of "
                f"{[t.__name__ for t in types]}")
    unknown = set(rec) - set(STEP_LOG_FIELDS)
    if unknown:
        raise ValueError(f"step record has unknown fields {sorted(unknown)}")
    if rec["v"] != STEP_LOG_SCHEMA_VERSION:
        raise ValueError(
            f"step record schema v{rec['v']} != "
            f"v{STEP_LOG_SCHEMA_VERSION}")


def step_log_active() -> bool:
    """True when telemetry is on AND a step_log_path is configured —
    executors consult this once per step before assembling a record."""
    return _enabled and bool(_flags.get_flag("step_log_path"))


_step_log_warned = False


def log_step(record: Dict[str, Any]):
    """Append one JSONL record to the step log. Fills ``v``, ``ts`` and
    ``seq``; flushes per line so a live tail (or a test) sees every
    record. No-op when telemetry is off or no path is configured. An
    unwritable path warns once and drops records — callers invoke this
    from ``finally`` blocks, and a telemetry failure must never mask the
    step's real result (or the exception being recorded)."""
    global _step_log_file, _step_log_path, _step_seq, _step_log_warned
    if not step_log_active():
        return
    path = _flags.get_flag("step_log_path")
    with _STEP_LOG_LOCK:
        try:
            if _step_log_file is None or path != _step_log_path:
                if _step_log_file is not None:
                    try:
                        _step_log_file.close()
                    except OSError:
                        pass
                _step_log_file = None
                _step_log_file = open(path, "a")
                _step_log_path = path
                _step_log_warned = False
            record = dict(record)
            record.setdefault("v", STEP_LOG_SCHEMA_VERSION)
            record.setdefault("ts", time.time())  # human-readable anchor
            record["seq"] = _step_seq
            _step_seq += 1
            # default=str: a numpy scalar (or anything else json chokes
            # on) degrades to its string form instead of raising
            _step_log_file.write(
                json.dumps(record, sort_keys=True, default=str) + "\n")
            _step_log_file.flush()
        except Exception as e:  # never-raise contract: callers log from
            # finally blocks and the step's real exception must win
            if not _step_log_warned:
                _step_log_warned = True
                import warnings

                warnings.warn(
                    f"step log write to {path!r} failed; records are "
                    f"being dropped: {e!r}", RuntimeWarning)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

_span_seconds: Optional[Histogram] = None


def span(name: str):
    """RAII span with one timeline: always emits a host chrome-trace span
    through ``profiler.record_event`` (a no-op unless the profiler is
    on); with telemetry on, additionally times the body with
    ``perf_counter`` into the ``pt_span_seconds`` histogram labelled by
    span name. When telemetry is off this returns the record_event
    context manager directly — byte-identical behavior and allocation
    profile to calling the profiler yourself."""
    if not _enabled:
        return _profiler.record_event(name)
    return _timed_span(name)


@contextlib.contextmanager
def _timed_span(name: str):
    global _span_seconds
    if _span_seconds is None:
        _span_seconds = histogram(
            "pt_span_seconds", "host span durations by span name")
    t0 = time.perf_counter()
    with _profiler.record_event(name):
        try:
            yield
        finally:
            _span_seconds.observe(time.perf_counter() - t0,
                                  labels={"span": name})


# register the watcher last so the module is fully initialized when the
# immediate callback fires
_flags.watch_flag("telemetry", _sync_from_flags)
