"""ctypes bindings for the native runtime (csrc/libpaddle_tpu_native.so).

Builds on demand with make/g++ (no pybind11 in this image). Components:
RecordIO (csrc/recordio.cc), coordination KV/barrier service
(csrc/coord.cc), host arena allocator (csrc/arena.cc), host profiler
(csrc/profiler.cc).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Iterator, List, Optional

_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc")
_LIB_PATH = os.path.abspath(os.path.join(_CSRC, "libpaddle_tpu_native.so"))

_lib: Optional[ctypes.CDLL] = None


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    # Always invoke make: its dependency check rebuilds when csrc/ changed
    # and is a no-op otherwise (the .so is never committed; see .gitignore).
    try:
        subprocess.run(
            ["make", "-C", os.path.abspath(_CSRC)],
            check=True,
            capture_output=True,
        )
    except (subprocess.CalledProcessError, FileNotFoundError):
        if not os.path.exists(_LIB_PATH):
            raise
    lib = ctypes.CDLL(_LIB_PATH)
    # recordio
    lib.rio_writer_open.restype = ctypes.c_void_p
    lib.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.rio_writer_write.restype = ctypes.c_int
    lib.rio_writer_write.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32]
    lib.rio_writer_close.restype = ctypes.c_int
    lib.rio_writer_close.argtypes = [ctypes.c_void_p]
    lib.rio_scanner_open.restype = ctypes.c_void_p
    lib.rio_scanner_open.argtypes = [ctypes.c_char_p]
    lib.rio_scanner_next.restype = ctypes.c_int
    lib.rio_scanner_next.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.rio_scanner_close.argtypes = [ctypes.c_void_p]
    # coord
    lib.coord_server_start.restype = ctypes.c_void_p
    lib.coord_server_start.argtypes = [ctypes.c_int]
    lib.coord_server_stop.argtypes = [ctypes.c_void_p]
    lib.coord_client_connect.restype = ctypes.c_void_p
    lib.coord_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.coord_client_close.argtypes = [ctypes.c_void_p]
    lib.coord_put.restype = ctypes.c_int
    lib.coord_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32]
    lib.coord_get.restype = ctypes.c_int
    lib.coord_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                              ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32]
    lib.coord_barrier.restype = ctypes.c_int
    lib.coord_barrier.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.coord_heartbeat.restype = ctypes.c_int
    lib.coord_heartbeat.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.coord_del.restype = ctypes.c_int
    lib.coord_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.coord_dead_peers.restype = ctypes.c_int
    lib.coord_dead_peers.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_char_p, ctypes.c_uint32]
    # arena
    lib.arena_create.restype = ctypes.c_void_p
    lib.arena_create.argtypes = [ctypes.c_uint64]
    lib.arena_destroy.argtypes = [ctypes.c_void_p]
    lib.arena_alloc.restype = ctypes.c_void_p
    lib.arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.arena_free.restype = ctypes.c_int
    lib.arena_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.arena_in_use.restype = ctypes.c_uint64
    lib.arena_in_use.argtypes = [ctypes.c_void_p]
    lib.arena_peak.restype = ctypes.c_uint64
    lib.arena_peak.argtypes = [ctypes.c_void_p]
    # profiler
    lib.prof_enable.restype = None
    lib.prof_disable.restype = None
    lib.prof_is_enabled.restype = ctypes.c_int
    lib.prof_begin.argtypes = [ctypes.c_char_p]
    lib.prof_end.restype = None
    lib.prof_dump.restype = ctypes.c_int
    lib.prof_dump.argtypes = [ctypes.c_char_p]
    _lib = lib
    return lib


def available() -> bool:
    try:
        _load()
        return True
    except Exception:
        return False


# --- RecordIO ---


class RecordIOWriter:
    """Chunked CRC'd record file (native; csrc/recordio.cc)."""

    def __init__(self, path: str, compressor: str = "none"):
        lib = _load()
        comp = {"none": 0, "zlib": 1}[compressor]
        self._h = lib.rio_writer_open(path.encode(), comp)
        if not self._h:
            raise IOError(f"cannot open {path}")
        self._lib = lib

    def write(self, data: bytes):
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        rc = self._lib.rio_writer_write(self._h, buf, len(data))
        if rc != 0:
            raise IOError("recordio write failed")

    def close(self):
        if self._h:
            rc = self._lib.rio_writer_close(self._h)
            self._h = None
            if rc != 0:
                raise IOError("recordio flush failed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RecordIOScanner:
    def __init__(self, path: str):
        lib = _load()
        self._h = lib.rio_scanner_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path}")
        self._lib = lib

    def __iter__(self) -> Iterator[bytes]:
        data = ctypes.POINTER(ctypes.c_uint8)()
        length = ctypes.c_uint32()
        while True:
            rc = self._lib.rio_scanner_next(
                self._h, ctypes.byref(data), ctypes.byref(length))
            if rc == 0:
                return
            if rc < 0:
                raise IOError("corrupt recordio record")
            yield ctypes.string_at(data, length.value)

    def close(self):
        if self._h:
            self._lib.rio_scanner_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# --- Coordination service ---


class CoordServer:
    """KV + barrier + heartbeat server (native; csrc/coord.cc)."""

    def __init__(self, port: int):
        lib = _load()
        self._h = lib.coord_server_start(port)
        if not self._h:
            raise OSError(f"cannot bind port {port}")
        self._lib = lib

    def stop(self):
        if self._h:
            self._lib.coord_server_stop(self._h)
            self._h = None


class CoordClient:
    def __init__(self, host: str, port: int):
        lib = _load()
        self._h = lib.coord_client_connect(host.encode(), port)
        if not self._h:
            raise OSError(f"cannot connect {host}:{port}")
        self._lib = lib

    def put(self, key: str, value: bytes):
        buf = (ctypes.c_uint8 * len(value)).from_buffer_copy(value)
        if self._lib.coord_put(self._h, key.encode(), buf, len(value)) != 0:
            raise OSError("coord put failed")

    def get(self, key: str, timeout_ms: int = -1, max_len: int = 1 << 20) -> bytes:
        out = (ctypes.c_uint8 * max_len)()
        n = self._lib.coord_get(self._h, key.encode(), timeout_ms, out, max_len)
        if n == -1:
            raise TimeoutError(f"coord get {key!r} timed out / absent")
        if n == -2:
            raise OSError("coord connection failed")
        if n < -2:  # value exists but exceeds max_len; retry with the size
            needed = -n - 3
            if needed <= max_len:
                raise OSError("coord get protocol error")
            return self.get(key, timeout_ms, max_len=needed)
        return bytes(out[:n])

    def barrier(self, name: str, count: int):
        if self._lib.coord_barrier(self._h, name.encode(), count) != 0:
            raise OSError("coord barrier failed")

    def heartbeat(self, worker_id: str):
        if self._lib.coord_heartbeat(self._h, worker_id.encode()) != 0:
            raise OSError("heartbeat failed")

    def delete(self, key: str):
        if self._lib.coord_del(self._h, key.encode()) != 0:
            raise OSError("coord delete failed")

    def dead_peers(self, max_age_ms: int) -> List[str]:
        out = ctypes.create_string_buffer(1 << 16)
        n = self._lib.coord_dead_peers(self._h, max_age_ms, out, 1 << 16)
        if n < 0:
            raise OSError("liveness query failed")
        s = out.value.decode()
        return [x for x in s.split(",") if x]

    def close(self):
        if self._h:
            self._lib.coord_client_close(self._h)
            self._h = None


# --- Arena allocator ---


class Arena:
    """Best-fit host staging arena (native; csrc/arena.cc)."""

    def __init__(self, capacity: int):
        lib = _load()
        self._h = lib.arena_create(capacity)
        if not self._h:
            raise MemoryError("arena allocation failed")
        self._lib = lib

    def _handle(self):
        if not self._h:
            raise ValueError("arena already destroyed")
        return self._h

    def alloc(self, size: int) -> int:
        p = self._lib.arena_alloc(self._handle(), size)
        if not p:
            raise MemoryError(f"arena exhausted (requested {size})")
        return p

    def free(self, ptr: int):
        if self._lib.arena_free(self._handle(), ptr) != 0:
            raise ValueError("pointer not owned by arena")

    @property
    def in_use(self) -> int:
        return self._lib.arena_in_use(self._handle())

    @property
    def peak(self) -> int:
        return self._lib.arena_peak(self._handle())

    def destroy(self):
        if self._h:
            self._lib.arena_destroy(self._h)
            self._h = None


# --- Profiler ---


def profiler_enable():
    _load().prof_enable()


def profiler_disable():
    _load().prof_disable()


def profiler_begin(name: str):
    _load().prof_begin(name.encode())


def profiler_end():
    _load().prof_end()


def profiler_dump(path: str) -> int:
    return _load().prof_dump(path.encode())
