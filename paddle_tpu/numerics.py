"""Device-side numerics observability plane.

The third observability plane (after monitor.py's host telemetry and the
compile-cost reports): *what the numbers are doing on the device*. The
reference could only offer a post-hoc host scan (``FLAGS_check_nan_inf``,
operator.cc:950) that says "something went non-finite"; instrumented-graph
numerics debugging (tfdbg, Cai et al. 2016) is the proven shape for
define-then-run frameworks, and on TPU the stats must be computed
*in-graph* — dragging every tensor to host would serialize the step.

Three pieces:

1. **``numerics_stats`` op** — one registered kernel that reduces every
   instrumented var to a tiny stats vector (non-finite count, max-abs,
   rms, optional log2-magnitude histogram) and concatenates all of them
   plus any registered aux scalars (AMP loss scale, grad global norm)
   into ONE 1-D f32 bundle. The reductions fuse into the step's XLA
   program; the bundle is a single auxiliary fetch — one device->host
   transfer per sampled step, no extra host syncs.

2. **``instrument(program)``** (exposed as the ``instrument_numerics``
   pass in passes.py) — selects op outputs (activations, gradients,
   parameters; filtered by the ``numerics_vars`` flag) and appends the
   stats op, attaching a ``NumericsPlan`` to the program that maps each
   bundle slot back to (var, producing op index, op type).

3. **``decode(...)``** — called by the executor after a sampled step:
   one ``np.asarray`` of the bundle, then pure host bookkeeping into the
   monitor registry (``pt_tensor_maxabs{var=}``, ``pt_tensor_rms{var=}``,
   ``pt_nonfinite_total{op=,var=}``, AMP/clip instruments) plus a
   **provenance record** naming the first op (index, type, output var)
   that produced a non-finite value — browsable via
   ``provenance_records()``, the monitor server's ``/numerics`` route,
   and ``debugger.pprint_program`` annotations.

Everything is off by default: decoding is gated on the ``telemetry`` AND
``numerics`` flags (``active()`` is one module-level boolean read, the
same zero-allocation contract the monitor instruments honor), and the
``numerics_every_n_steps`` flag bounds enabled-mode overhead.
"""

from __future__ import annotations

import collections
import dataclasses
import fnmatch
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from paddle_tpu import flags as _flags
from paddle_tpu import monitor as _monitor
from paddle_tpu.core.registry import register_op

# ---------------------------------------------------------------------------
# instruments (registered eagerly so a first /metrics scrape and the
# doc-coverage test see the full set)
# ---------------------------------------------------------------------------

_M_MAXABS = _monitor.gauge(
    "pt_tensor_maxabs",
    "max |finite value| of an instrumented tensor at the last sampled "
    "step, by var")
_M_RMS = _monitor.gauge(
    "pt_tensor_rms",
    "rms of finite values of an instrumented tensor at the last sampled "
    "step, by var")
_M_NONFINITE = _monitor.counter(
    "pt_nonfinite_total",
    "non-finite elements observed in instrumented tensors at sampled "
    "steps, by producing op type and var")
_M_DECODES = _monitor.counter(
    "pt_numerics_decodes_total",
    "numerics bundles decoded (one auxiliary transfer each)")
_M_AMP_SCALE = _monitor.gauge(
    "pt_amp_loss_scale", "current AMP dynamic loss scale")
_M_AMP_SKIPS = _monitor.counter(
    "pt_amp_overflow_skips_total",
    "AMP steps whose parameter update was skipped on overflow")
_M_GRAD_NORM = _monitor.gauge(
    "pt_grad_global_norm",
    "pre-clip global gradient norm at the last sampled step")
_M_CLIP_RATIO = _monitor.gauge(
    "pt_grad_clip_ratio",
    "global-norm clip scale at the last sampled step (1.0 = no clip)")
_M_CLIPS = _monitor.counter(
    "pt_grad_clips_total",
    "sampled steps where global-norm clipping actually triggered")

# ---------------------------------------------------------------------------
# enable/disable plumbing (cached hot flags; see flags.watch_flag)
# ---------------------------------------------------------------------------

_active = False
_every_n = 1


def active() -> bool:
    """Whether executors should fetch + decode numerics bundles: the
    ``telemetry`` AND ``numerics`` flags (one boolean read)."""
    return _active


def _sync_active(_value=None):
    global _active
    _active = bool(_flags.get_flag("telemetry")) and bool(
        _flags.get_flag("numerics"))


def _sync_every_n(value):
    global _every_n
    _every_n = max(1, int(value))


def should_sample(step: int) -> bool:
    """Whether this executor step's bundle gets decoded (the
    ``numerics_every_n_steps`` sampling gate)."""
    return step % _every_n == 0


def should_sample_window(start: int, steps: int) -> bool:
    """A compiled window samples once when ANY of its steps lands on the
    period (the window's single bundle stands in for all of them)."""
    return (start + steps - 1) // _every_n > (start - 1) // _every_n


# ---------------------------------------------------------------------------
# the in-graph stats kernel
# ---------------------------------------------------------------------------

STAT_FIELDS = ("nonfinite", "maxabs", "rms")
# log2-magnitude histogram range: 2^-16 .. 2^16 covers bf16/f32 training
# streams; values outside clamp into the edge bins
HIST_LO, HIST_HI = -16.0, 16.0


def _stats_vec(x, bins: int):
    xf = x.astype(jnp.float32)
    finite = jnp.isfinite(xf)
    n_finite = jnp.sum(finite, dtype=jnp.int32)
    n_bad = (xf.size - n_finite).astype(jnp.float32)
    safe = jnp.where(finite, xf, 0.0)
    maxabs = jnp.max(jnp.abs(safe))
    # rms over the FINITE values only: dividing the zero-filled sum by
    # the full size would understate it exactly when tensors go bad
    rms = jnp.sqrt(jnp.sum(jnp.square(safe))
                   / jnp.maximum(n_finite, 1).astype(jnp.float32))
    head = jnp.stack([n_bad, maxabs, rms])
    if not bins:
        return head
    mag = jnp.abs(safe)
    nz = (finite & (mag > 0)).reshape(-1)
    l2 = jnp.log2(jnp.where(nz, mag.reshape(-1), 1.0))
    frac = (jnp.clip(l2, HIST_LO, HIST_HI) - HIST_LO) / (HIST_HI - HIST_LO)
    idx = jnp.clip((frac * bins).astype(jnp.int32), 0, bins - 1)
    hist = jnp.zeros((bins,), jnp.float32).at[idx].add(
        nz.astype(jnp.float32))
    return jnp.concatenate([head, hist])


@register_op("numerics_stats", no_grad=True,
             doc="reduce instrumented vars to one stats bundle "
                 "(numerics.py device-side observability)")
def _numerics_stats(ins, attrs):
    bins = int(attrs.get("hist_bins", 0))
    parts = [_stats_vec(x, bins) for x in ins.get("X", [])]
    # aux scalars (loss scale, found-inf flag, grad norms) ride the same
    # bundle so the sampled step still costs exactly one transfer
    parts += [a.astype(jnp.float32).reshape(-1)[:1]
              for a in ins.get("A", [])]
    return {"Out": [jnp.concatenate(parts)]}


# ---------------------------------------------------------------------------
# instrumentation plan
# ---------------------------------------------------------------------------

_FLOAT_DTYPES = frozenset(
    {"float16", "float32", "float64", "bfloat16"})

BUNDLE_VAR = "__numerics_bundle__"


@dataclasses.dataclass
class NumericsPlan:
    """Decode map for an instrumented program: bundle slot -> meaning."""

    program_uid: int
    # (var name, producing op index, op type, kind) per stats slot group
    entries: Tuple[Tuple[str, int, str, str], ...]
    # (aux kind, var name) per trailing scalar slot
    aux: Tuple[Tuple[str, str], ...]
    bundle_var: str = BUNDLE_VAR
    hist_bins: int = 0
    # True while the current non-finite episode has already been recorded
    # (provenance fires on the FIRST sampled decode that sees a bad var)
    _bad_episode: bool = False
    # last decoded value per CUMULATIVE aux kind (amp_overflow_skips):
    # the decoder emits deltas, so sampled/windowed decodes stay exact
    _aux_prev: Dict[str, float] = dataclasses.field(default_factory=dict)

    @property
    def stats_width(self) -> int:
        return len(STAT_FIELDS) + self.hist_bins

    @property
    def bundle_size(self) -> int:
        return len(self.entries) * self.stats_width + len(self.aux)


def register_aux(program, kind: str, var_name: str):
    """Register an in-graph scalar (AMP loss scale, grad global norm ...)
    for bundle pickup. Pure metadata — costs nothing until a plan is
    built and the numerics plane is active."""
    aux = program.__dict__.setdefault("_numerics_aux", [])
    if (kind, var_name) not in aux:
        aux.append((kind, var_name))


def _patterns() -> List[str]:
    raw = _flags.get_flag("numerics_vars")
    return [p.strip() for p in raw.split(",") if p.strip()]


def instrument(program, vars: Optional[Sequence[str]] = None,
               histogram_bins: int = 0,
               include: Sequence[str] = ("activation", "gradient",
                                         "parameter")) -> Optional[NumericsPlan]:
    """Append the ``numerics_stats`` op to ``program``'s global block and
    attach the decode plan. Apply AFTER the program is fully built
    (minimize/clip/AMP included) — later-appended ops are not seen.

    ``vars``: explicit var names to instrument (None = every float op
    output, filtered by the ``numerics_vars`` flag patterns; ``()`` =
    aux-only). Idempotent: an already-instrumented program returns its
    existing plan."""
    existing = getattr(program, "_numerics_plan", None)
    if existing is not None:
        return existing
    block = program.global_block()
    first_writer: Dict[str, Tuple[int, str]] = {}
    for idx, op in enumerate(block.ops):
        for n in op.output_arg_names:
            if n:
                first_writer.setdefault(n, (idx, op.type))

    entries: List[Tuple[str, int, str, str]] = []
    if vars is not None:
        wanted = list(vars)
        for name in wanted:
            if name not in first_writer:
                raise KeyError(
                    f"numerics: var '{name}' is not produced by any op "
                    f"in block 0")
            idx, op_type = first_writer[name]
            entries.append((name, idx, op_type, _kind_of(block, name)))
    else:
        pats = _patterns()
        for name, (idx, op_type) in first_writer.items():
            v = block._find_var_recursive(name)
            if v is None or v.dtype not in _FLOAT_DTYPES:
                continue
            kind = _kind_of(block, name)
            if kind not in include:
                continue
            if pats and not any(fnmatch.fnmatch(name, p) for p in pats):
                continue
            entries.append((name, idx, op_type, kind))
        entries.sort(key=lambda e: e[1])

    aux = tuple(getattr(program, "_numerics_aux", ()) or ())
    if not entries and not aux:
        return None
    plan = NumericsPlan(
        program_uid=int(program._uid),
        entries=tuple(entries),
        aux=aux,
        hist_bins=int(histogram_bins),
    )
    block.create_var(name=plan.bundle_var, dtype="float32",
                     shape=[plan.bundle_size], stop_gradient=True)
    block.append_op(
        "numerics_stats",
        inputs={"X": [e[0] for e in plan.entries],
                "A": [v for _, v in plan.aux]},
        outputs={"Out": [plan.bundle_var]},
        attrs={"hist_bins": plan.hist_bins},
    )
    program._numerics_plan = plan
    return plan


def _kind_of(block, name: str) -> str:
    if name.endswith("@GRAD"):
        return "gradient"
    v = block._find_var_recursive(name)
    if v is not None and v.persistable:
        return "parameter"
    return "activation"


def plan_for(program) -> Optional[NumericsPlan]:
    """The executor's entry point (called only while ``active()``): the
    attached plan, or a lazily built aux-only plan when graph code
    registered aux vars (AMP scale, clip norms) without the full pass."""
    plan = getattr(program, "_numerics_plan", None)
    if plan is None and getattr(program, "_numerics_aux", None):
        plan = instrument(program, vars=())
    return plan


# ---------------------------------------------------------------------------
# decoding
# ---------------------------------------------------------------------------

# Test hook AND the single device->host sync point: decode() calls this
# exactly once per sampled bundle.
_to_host = np.asarray

_LOCK = threading.Lock()
PROVENANCE_CAPACITY = 64
_PROVENANCE: collections.deque = collections.deque(
    maxlen=PROVENANCE_CAPACITY)
# program uid -> latest decoded summary (stats + aux), for /numerics
_LATEST: Dict[int, Dict[str, Any]] = {}

PROVENANCE_SCHEMA_VERSION = 1

# aux kind -> gauge/event handler for PER-STEP values. amp_found_inf is
# report-only here (it rides the step record); the skip COUNTER comes
# from the cumulative amp_overflow_skips kind below, which stays exact
# under sampling and compiled windows.
_AUX_DECODERS = {
    "amp_loss_scale": lambda v: _M_AMP_SCALE.set(v),
    "grad_global_norm": lambda v: _M_GRAD_NORM.set(v),
    "grad_clip_scale": lambda v: (
        _M_CLIP_RATIO.set(v),
        _M_CLIPS.inc() if v < 1.0 else None),
}

# aux kinds whose in-graph var is a monotonically increasing counter:
# the decoder emits value - last_decoded_value into the metric
_AUX_CUMULATIVE = {
    "amp_overflow_skips": _M_AMP_SKIPS,
}


def decode(program, plan: NumericsPlan, bundle, step: int,
           kind: str = "step",
           nan_step: Optional[int] = None) -> Dict[str, Any]:
    """Decode one fetched bundle (ONE ``np.asarray`` — the auxiliary
    transfer) into the monitor registry + provenance ring. Returns the
    compact summary embedded in the step record's ``numerics`` field.
    Never raises — telemetry must not fail a step."""
    try:
        return _decode(program, plan, bundle, step, kind, nan_step)
    except Exception as e:
        import warnings

        warnings.warn(f"numerics decode dropped: {e!r}", RuntimeWarning)
        return {"error": str(e)}


def _decode(program, plan, bundle, step, kind, nan_step):
    arr = np.asarray(_to_host(bundle), dtype=np.float64).reshape(-1)
    _M_DECODES.inc()
    w = plan.stats_width
    stats: Dict[str, Dict[str, float]] = {}
    bad: List[Tuple[str, int, str, Dict[str, float]]] = []
    for i, (var, op_idx, op_type, var_kind) in enumerate(plan.entries):
        off = i * w
        cell = {
            "nonfinite": float(arr[off]),
            "maxabs": float(arr[off + 1]),
            "rms": float(arr[off + 2]),
            "kind": var_kind,
            "op": op_idx,
            "op_type": op_type,
        }
        if plan.hist_bins:
            cell["hist"] = [float(c)
                            for c in arr[off + 3:off + 3 + plan.hist_bins]]
        stats[var] = cell
        _M_MAXABS.set(cell["maxabs"], labels={"var": var})
        _M_RMS.set(cell["rms"], labels={"var": var})
        if cell["nonfinite"] > 0:
            _M_NONFINITE.inc(cell["nonfinite"],
                             labels={"op": op_type, "var": var})
            bad.append((var, op_idx, op_type, cell))
    aux_vals: Dict[str, float] = {}
    base = len(plan.entries) * w
    for j, (aux_kind, _var) in enumerate(plan.aux):
        v = float(arr[base + j])
        aux_vals[aux_kind] = v
        counter_m = _AUX_CUMULATIVE.get(aux_kind)
        if counter_m is not None:
            delta = v - plan._aux_prev.get(aux_kind, 0.0)
            plan._aux_prev[aux_kind] = v
            if delta > 0:
                counter_m.inc(delta)
            continue
        dec = _AUX_DECODERS.get(aux_kind)
        if dec is not None:
            dec(v)

    summary: Dict[str, Any] = {
        "vars": len(plan.entries),
        "nonfinite_vars": len(bad),
        "first_bad": None,
    }
    if aux_vals:
        summary["aux"] = aux_vals
    if bad:
        var, op_idx, op_type, cell = min(bad, key=lambda b: b[1])
        first = {"op": op_idx, "op_type": op_type, "var": var}
        summary["first_bad"] = first
        if not plan._bad_episode:
            plan._bad_episode = True
            rec = {
                "v": PROVENANCE_SCHEMA_VERSION,
                "ts": time.time(),
                "step": int(step),
                "kind": kind,
                "program": f"program{plan.program_uid}",
                "program_uid": plan.program_uid,
                "op_idx": op_idx,
                "op_type": op_type,
                "var": var,
                "nonfinite": cell["nonfinite"],
                "maxabs": cell["maxabs"],
                "rms": cell["rms"],
                "nan_step": nan_step,
            }
            with _LOCK:
                _PROVENANCE.append(rec)
    else:
        plan._bad_episode = False
    with _LOCK:
        _LATEST[plan.program_uid] = {
            "step": int(step), "kind": kind, "stats": stats,
            "aux": aux_vals,
        }
    return summary


def note_nonfinite(op_type: str, var: str, count: float = 1.0, *,
                   program_uid: int = -1, step: int = -1,
                   kind: str = "step",
                   maxabs: float = float("nan"),
                   rms: float = float("nan")):
    """Host-side non-finite report from a plane that detects poison
    OUTSIDE the in-graph bundle (e.g. serving.py's per-slot decode
    probe): counts ``pt_nonfinite_total{op=,var=}`` and appends a
    provenance record so the episode shows on ``/numerics`` beside the
    instrumented-program ones. Gated on telemetry; never raises."""
    if not _monitor.enabled():
        return
    try:
        _M_NONFINITE.inc(float(count), labels={"op": op_type, "var": var})
        rec = {
            "v": PROVENANCE_SCHEMA_VERSION,
            "ts": time.time(),
            "step": int(step),
            "kind": kind,
            "program": f"program{program_uid}",
            "program_uid": int(program_uid),
            "op_idx": -1,  # host-side detection: no in-graph op index
            "op_type": op_type,
            "var": var,
            "nonfinite": float(count),
            "maxabs": float(maxabs),
            "rms": float(rms),
            "nan_step": None,
        }
        with _LOCK:
            _PROVENANCE.append(rec)
    except Exception as e:
        import warnings

        warnings.warn(f"nonfinite note dropped: {e!r}", RuntimeWarning)


# ---------------------------------------------------------------------------
# inspection surface (/numerics route, debugger annotations, tests)
# ---------------------------------------------------------------------------

def provenance_records() -> List[Dict[str, Any]]:
    """Buffered NaN/Inf provenance records, oldest first."""
    with _LOCK:
        return [dict(r) for r in _PROVENANCE]


def provenance_for(program_uid: int) -> Optional[Dict[str, Any]]:
    """Latest provenance record for one program (None when clean)."""
    with _LOCK:
        for r in reversed(_PROVENANCE):
            if r["program_uid"] == program_uid:
                return dict(r)
    return None


def latest_stats() -> Dict[int, Dict[str, Any]]:
    """Latest decoded summary per program uid."""
    with _LOCK:
        return {k: dict(v) for k, v in _LATEST.items()}


def summary() -> Dict[str, Any]:
    """The /numerics route payload."""
    return {
        "active": _active,
        "every_n_steps": _every_n,
        "provenance": provenance_records(),
        "programs": {str(k): v for k, v in latest_stats().items()},
    }


def reset():
    """Drop decoded state (test isolation; monitor.reset calls this)."""
    with _LOCK:
        _PROVENANCE.clear()
        _LATEST.clear()


_flags.watch_flag("telemetry", _sync_active)
_flags.watch_flag("numerics", _sync_active)
_flags.watch_flag("numerics_every_n_steps", _sync_every_n)
