"""Operator library.

TPU-native kernel set covering the reference's operator library
(reference: paddle/fluid/operators/ — 415 REGISTER_OPERATOR sites). Every
kernel is a pure JAX function; XLA fuses, tiles onto the MXU, and schedules.
Grad kernels are auto-derived (core/autodiff.py) unless an op registers a
custom grad maker.
"""

from paddle_tpu.ops import (  # noqa: F401
    activation_ops,
    attention_ops,
    control_flow_ops,
    crf_ops,
    decode_ops,
    detection_ops,
    math_ops,
    metric_ops,
    misc_ops,
    moe_ops,
    nn_ops,
    optimizer_ops,
    quant_ops,
    rnn_ops,
    sequence_ops,
    serving_ops,
    sparse_ops,
    tensor_ops,
    vision_ops,
)
