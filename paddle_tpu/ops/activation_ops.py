"""Activation ops (reference: paddle/fluid/operators/activation_op.cc)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


def _x(ins):
    return ins["X"][0]


def _unary(name, fn):
    @register_op(name)
    def _compute(ins, attrs, fn=fn):
        return {"Out": [fn(_x(ins))]}


_unary("relu", jax.nn.relu)
_unary("sigmoid", jax.nn.sigmoid)
_unary("tanh", jnp.tanh)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", jax.lax.rsqrt)
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("abs", jnp.abs)
_unary("square", jnp.square)
_unary("reciprocal", jnp.reciprocal)
_unary("floor", jnp.floor)
_unary("ceil", jnp.ceil)
_unary("round", jnp.round)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("softsign", jax.nn.soft_sign)
_unary("relu6", lambda x: jnp.clip(x, 0.0, 6.0))
_unary("silu", jax.nn.silu)


@register_op("gelu")
def _gelu(ins, attrs):
    approximate = attrs.get("approximate", False)
    return {"Out": [jax.nn.gelu(_x(ins), approximate=approximate)]}


@register_op("leaky_relu")
def _leaky_relu(ins, attrs):
    alpha = attrs.get("alpha", 0.02)
    return {"Out": [jax.nn.leaky_relu(_x(ins), negative_slope=alpha)]}


@register_op("softplus")
def _softplus(ins, attrs):
    return {"Out": [jax.nn.softplus(_x(ins))]}


@register_op("elu")
def _elu(ins, attrs):
    return {"Out": [jax.nn.elu(_x(ins), alpha=attrs.get("alpha", 1.0))]}


@register_op("pow")
def _pow(ins, attrs):
    return {"Out": [jnp.power(_x(ins), attrs.get("factor", 1.0))]}


@register_op("hard_sigmoid")
def _hard_sigmoid(ins, attrs):
    slope = attrs.get("slope", 0.2)
    offset = attrs.get("offset", 0.5)
    return {"Out": [jnp.clip(_x(ins) * slope + offset, 0.0, 1.0)]}


@register_op("swish")
def _swish(ins, attrs):
    beta = attrs.get("beta", 1.0)
    x = _x(ins)
    return {"Out": [x * jax.nn.sigmoid(beta * x)]}


@register_op("hard_swish")
def _hard_swish(ins, attrs):
    x = _x(ins)
    threshold = attrs.get("threshold", 6.0)
    scale = attrs.get("scale", 6.0)
    offset = attrs.get("offset", 3.0)
    return {"Out": [x * jnp.clip(x + offset, 0.0, threshold) / scale]}


@register_op("logsigmoid")
def _logsigmoid(ins, attrs):
    return {"Out": [jax.nn.log_sigmoid(_x(ins))]}


# --- remaining reference activations (operators/activation_op.cc) ---


@register_op("tanh_shrink")
def _tanh_shrink(ins, attrs):
    x = _x(ins)
    return {"Out": [x - jnp.tanh(x)]}


@register_op("softshrink")
def _softshrink(ins, attrs):
    x = _x(ins)
    lam = attrs.get("lambda", 0.5)
    return {"Out": [jnp.where(x > lam, x - lam,
                              jnp.where(x < -lam, x + lam, 0.0))]}


@register_op("hard_shrink")
def _hard_shrink(ins, attrs):
    x = _x(ins)
    t = attrs.get("threshold", 0.5)
    return {"Out": [jnp.where(jnp.abs(x) > t, x, 0.0)]}


@register_op("brelu")
def _brelu(ins, attrs):
    x = _x(ins)
    return {"Out": [jnp.clip(x, attrs.get("t_min", 0.0),
                             attrs.get("t_max", 24.0))]}


@register_op("soft_relu")
def _soft_relu(ins, attrs):
    x = _x(ins)
    t = attrs.get("threshold", 40.0)
    return {"Out": [jnp.log1p(jnp.exp(jnp.clip(x, -t, t)))]}


@register_op("stanh")
def _stanh(ins, attrs):
    x = _x(ins)
    a = attrs.get("scale_a", 0.67)
    b = attrs.get("scale_b", 1.7159)
    return {"Out": [b * jnp.tanh(a * x)]}


@register_op("thresholded_relu")
def _thresholded_relu(ins, attrs):
    x = _x(ins)
    t = attrs.get("threshold", 1.0)
    return {"Out": [jnp.where(x > t, x, 0.0)]}


@register_op("selu")
def _selu(ins, attrs):
    return {"Out": [jax.nn.selu(_x(ins))]}
