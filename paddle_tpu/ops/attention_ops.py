"""Attention support ops: position ids, additive attention bias, and the
fused scaled-dot-product attention kernel (Pallas on TPU, reference JAX
elsewhere).

These replace the reference's LoD-based attention plumbing in
dist_transformer.py (slice/pad helpers) with static-shape mask tensors
(SURVEY.md section 5).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op

NEG_INF = -1e9


def _x(ins, slot="X", i=0):
    v = ins.get(slot)
    return v[i] if v else None


@register_op("position_ids", no_grad=True)
def _position_ids(ins, attrs):
    x = _x(ins)  # [b, t] any int dtype
    b, t = jnp.shape(x)[0], jnp.shape(x)[1]
    return {"Out": [jnp.broadcast_to(jnp.arange(t, dtype=jnp.int64), (b, t))]}


@register_op("attn_bias", no_grad=True)
def _attn_bias(ins, attrs):
    """PadMask [b, t_k] (1=real token) -> additive bias.

    causal=False: [b, 1, 1, t_k] with -1e9 at padding.
    causal=True:  [b, 1, t_k, t_k] padding + upper-triangular future mask.
    """
    mask = _x(ins, "PadMask")
    pad_bias = (1.0 - mask) * NEG_INF  # [b, t]
    if attrs.get("causal", False):
        t = jnp.shape(mask)[1]
        causal = jnp.triu(jnp.full((t, t), NEG_INF, mask.dtype), k=1)
        out = pad_bias[:, None, None, :] + causal[None, None, :, :]
    else:
        out = pad_bias[:, None, None, :]
    return {"Out": [out]}


def _sdpa_config(ins, attrs, rng):
    """Shared fwd/grad config: (scale, p_drop, seed, use_pallas).

    The grad op's rng is folded with the SAME forward_op_idx as the
    forward's (core/lowering.py), so the derived dropout seed — and hence
    the in-kernel mask — is identical in both directions.
    """
    q = _x(ins, "Q")
    scale = attrs.get("scale", None)
    if scale is None:
        scale = 1.0 / math.sqrt(jnp.shape(q)[-1])
    p_drop = attrs.get("dropout_prob", 0.0)
    training_dropout = p_drop > 0.0 and not attrs.get("is_test", False)
    seed = None
    drop = 0.0
    if training_dropout:
        drop = float(p_drop)
        seed = jax.random.randint(rng, (), 0, 2**31 - 1, dtype=jnp.int32)
    use_pallas = (
        jax.default_backend() == "tpu"
        and attrs.get("use_pallas", True)
    )
    return scale, drop, seed, use_pallas


def _ring_config_t(q, k, t_axis=2):
    """(mesh, context_axis, data_axis) when sequence-parallel ring
    attention applies, else None. Requires a strategy-declared context
    axis, BOTH sequence lengths divisible by the axis size (cross
    attention has tq != tk). Attention dropout rides along since round
    5: the flash-backed ring body draws an independent in-kernel mask
    stream per rotating block (source-rank-mixed seed), regenerated
    identically in forward and backward. Non-qualifying attention falls
    back to the flash/dense path. ``t_axis`` is the sequence dim: 2 for
    BHTD, 1 for BTHD."""
    from paddle_tpu.core.interp import spmd_ctx

    ctx = spmd_ctx()
    if ctx is None:
        return None
    mesh, ctx_axis, data_axis = ctx.mesh, ctx.context_axis, ctx.data_axis
    if ctx_axis is None:
        return None
    n = mesh.shape[ctx_axis]
    if (n <= 1 or jnp.shape(q)[t_axis] % n != 0
            or jnp.shape(k)[t_axis] % n != 0):
        return None
    # the batch dim must divide the (possibly composed slice x data)
    # batch-axis ranks; replicate the batch rather than letting
    # shard_map fail with an opaque uneven-sharding trace error
    from paddle_tpu.parallel.mesh import axis_size

    if data_axis is not None and (
        jnp.shape(q)[0] % axis_size(mesh, data_axis) != 0
    ):
        data_axis = None
    return mesh, ctx_axis, data_axis


def _ring_config(q, k):
    return _ring_config_t(q, k, 2)


@register_op("scaled_dot_product_attention", diff_inputs=("Q", "K", "V"),
             needs_rng=True)
def _sdpa(ins, attrs, rng=None):
    """Fused attention: Q,K,V [b, h, t, dh] + optional additive Bias.

    On TPU this routes to the Pallas flash-attention kernel
    (paddle_tpu/parallel/flash_attention.py), including training-time
    attention dropout, which runs inside the kernel from a per-step seed.
    Off-TPU (or in the numeric-grad harness) it uses the jnp composition,
    which XLA fuses. Also emits the logsumexp rows (Lse) so the paired
    grad op below can run the blocked backward kernels WITHOUT re-running
    the forward (XLA cannot CSE custom calls; DCE'd when unused).
    """
    q, k, v = _x(ins, "Q"), _x(ins, "K"), _x(ins, "V")
    bias = _x(ins, "Bias")
    scale, drop, seed, use_pallas = _sdpa_config(ins, attrs, rng)
    bthd = attrs.get("layout", "bhtd") == "bthd"
    causal = bool(attrs.get("causal", False))
    from paddle_tpu.parallel import flash_attention as fa

    t_axis = 1 if bthd else 2
    ring = _ring_config_t(q, k, t_axis)
    if ring is not None:
        mesh, ctx_axis, data_axis = ring
        from paddle_tpu.parallel import ring_attention as ra

        if bthd:  # ring kernel operates on [b, h, t, dh]
            out = ra.ring_attention(
                jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                jnp.swapaxes(v, 1, 2), mesh, seq_axis=ctx_axis,
                scale=scale, bias=bias, data_axis=data_axis,
                causal=causal, p_drop=float(drop), seed=seed)
            out = jnp.swapaxes(out, 1, 2)
        else:
            out = ra.ring_attention(q, k, v, mesh, seq_axis=ctx_axis,
                                    scale=scale, bias=bias,
                                    data_axis=data_axis, causal=causal,
                                    p_drop=float(drop), seed=seed)
        lse = jnp.zeros(jnp.shape(q)[:3] + (1,), jnp.float32)
    elif bthd:
        if use_pallas:
            out, lse = fa.flash_attention_bthd_with_lse(
                q, k, v, bias, seed, scale, float(drop), causal)
        else:
            out = fa._reference_attention_bthd(
                q, k, v,
                fa._combined_causal_bias(bias, q.shape[1], k.shape[1])
                if causal else bias,
                scale, drop, seed if drop > 0.0 else None)
            lse = jnp.zeros(jnp.shape(q)[:3] + (1,), jnp.float32)
    elif use_pallas:
        # the custom-vjp wrapper makes the op differentiable through
        # jax.vjp too (scan-over-layers grad); the paired grad op below
        # remains the unrolled path's backward
        out, lse = fa.flash_attention_with_lse(q, k, v, bias, seed,
                                               scale, float(drop),
                                               causal=causal)
    else:
        out = fa._reference_attention(q, k, v, bias, scale, drop,
                                      seed if drop > 0.0 else None,
                                      causal=causal)
        lse = jnp.zeros(jnp.shape(q)[:3] + (1,), jnp.float32)
    return {"Out": [out.astype(q.dtype)], "Lse": [lse]}


@register_op("scaled_dot_product_attention_grad", no_grad=True,
             needs_rng=True)
def _sdpa_grad(ins, attrs, rng=None):
    """Blocked flash-attention backward consuming the forward's saved
    (Out, Lse) — no forward re-execution (cf. the auto vjp path, which
    would re-run the kernel because custom calls are opaque to CSE)."""
    q, k, v = _x(ins, "Q"), _x(ins, "K"), _x(ins, "V")
    bias = _x(ins, "Bias")
    out, lse = _x(ins, "Out"), _x(ins, "Lse")
    g = _x(ins, "GRAD::Out")
    scale, drop, seed, use_pallas = _sdpa_config(ins, attrs, rng)
    bthd = attrs.get("layout", "bhtd") == "bthd"
    causal = bool(attrs.get("causal", False))
    from paddle_tpu.parallel import flash_attention as fa

    t_axis = 1 if bthd else 2
    ring = _ring_config_t(q, k, t_axis)
    if ring is not None:
        mesh, ctx_axis, data_axis = ring
        from paddle_tpu.parallel import ring_attention as ra

        def f(q, k, v):
            if bthd:
                o = ra.ring_attention(
                    jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                    jnp.swapaxes(v, 1, 2), mesh, seq_axis=ctx_axis,
                    scale=scale, bias=bias, data_axis=data_axis,
                    causal=causal, p_drop=float(drop), seed=seed)
                return jnp.swapaxes(o, 1, 2)
            return ra.ring_attention(
                q, k, v, mesh, seq_axis=ctx_axis, scale=scale, bias=bias,
                data_axis=data_axis, causal=causal, p_drop=float(drop),
                seed=seed,
            )

        _, vjp = jax.vjp(f, q, k, v)
        dq, dk, dv = vjp(g.astype(q.dtype))
    elif bthd:
        if use_pallas:
            dq, dk, dv = fa.flash_attention_bthd_bwd(
                q, k, v, bias, seed, out, lse, g.astype(q.dtype),
                scale=scale, p_drop=drop, causal=causal)
        else:
            sd = seed if drop > 0.0 else None
            eff_bias = fa._combined_causal_bias(
                bias, q.shape[1], k.shape[1]) if causal else bias

            def f(q, k, v):
                return fa._reference_attention_bthd(
                    q, k, v, eff_bias, scale, drop, sd).astype(q.dtype)

            _, vjp = jax.vjp(f, q, k, v)
            dq, dk, dv = vjp(g.astype(q.dtype))
    elif use_pallas:
        # gates internally between the blocked Pallas kernels and a vjp of
        # the same dense composition the forward used — one source of truth
        # for masks and fallback conditions
        dq, dk, dv = fa.flash_attention_bwd(
            q, k, v, bias, seed, out, lse, g.astype(q.dtype),
            scale=scale, p_drop=drop, causal=causal)
    else:
        sd = seed if drop > 0.0 else None

        def f(q, k, v):
            return fa._reference_attention(q, k, v, bias, scale, drop,
                                           sd, causal=causal).astype(q.dtype)

        _, vjp = jax.vjp(f, q, k, v)
        dq, dk, dv = vjp(g.astype(q.dtype))
    return {"GRAD::Q": [dq], "GRAD::K": [dk], "GRAD::V": [dv]}
