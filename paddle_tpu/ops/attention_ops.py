"""Attention support ops: position ids, additive attention bias, and the
fused scaled-dot-product attention kernel (Pallas on TPU, reference JAX
elsewhere).

These replace the reference's LoD-based attention plumbing in
dist_transformer.py (slice/pad helpers) with static-shape mask tensors
(SURVEY.md section 5).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op

NEG_INF = -1e9


def _x(ins, slot="X", i=0):
    v = ins.get(slot)
    return v[i] if v else None


@register_op("position_ids", no_grad=True)
def _position_ids(ins, attrs):
    x = _x(ins)  # [b, t] any int dtype
    b, t = jnp.shape(x)[0], jnp.shape(x)[1]
    return {"Out": [jnp.broadcast_to(jnp.arange(t, dtype=jnp.int64), (b, t))]}


@register_op("attn_bias", no_grad=True)
def _attn_bias(ins, attrs):
    """PadMask [b, t_k] (1=real token) -> additive bias.

    causal=False: [b, 1, 1, t_k] with -1e9 at padding.
    causal=True:  [b, 1, t_k, t_k] padding + upper-triangular future mask.
    """
    mask = _x(ins, "PadMask")
    pad_bias = (1.0 - mask) * NEG_INF  # [b, t]
    if attrs.get("causal", False):
        t = jnp.shape(mask)[1]
        causal = jnp.triu(jnp.full((t, t), NEG_INF, mask.dtype), k=1)
        out = pad_bias[:, None, None, :] + causal[None, None, :, :]
    else:
        out = pad_bias[:, None, None, :]
    return {"Out": [out]}


@register_op("scaled_dot_product_attention", diff_inputs=("Q", "K", "V"),
             needs_rng=True)
def _sdpa(ins, attrs, rng=None):
    """Fused attention: Q,K,V [b, h, t, dh] + optional additive Bias.

    On TPU this routes to the Pallas flash-attention kernel
    (paddle_tpu/parallel/flash_attention.py), including training-time
    attention dropout, which runs inside the kernel from a per-step seed.
    Off-TPU (or in the numeric-grad harness) it uses the jnp composition,
    which XLA fuses.
    """
    q, k, v = _x(ins, "Q"), _x(ins, "K"), _x(ins, "V")
    bias = _x(ins, "Bias")
    scale = attrs.get("scale", None)
    if scale is None:
        scale = 1.0 / math.sqrt(jnp.shape(q)[-1])
    p_drop = attrs.get("dropout_prob", 0.0)
    training_dropout = p_drop > 0.0 and not attrs.get("is_test", False)
    use_pallas = (
        jax.default_backend() == "tpu"
        and attrs.get("use_pallas", True)
    )
    if use_pallas:
        from paddle_tpu.parallel.flash_attention import flash_attention

        seed = None
        drop = 0.0
        if training_dropout:
            # Attention dropout runs inside the kernel (regenerated from
            # this seed in the backward) — the dense fallback round 1 took
            # here materialized the t x t score matrix in HBM.
            drop = float(p_drop)
            seed = jax.random.randint(rng, (), 0, 2**31 - 1, dtype=jnp.int32)
        out = flash_attention(q, k, v, bias=bias, seed=seed, scale=scale,
                              p_drop=drop)
    else:
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                            preferred_element_type=jnp.float32) * scale
        if bias is not None:
            scores = scores + bias.astype(scores.dtype)
        # softmax reduction in f32, then drop to the value dtype so the
        # materialized attention matrix (the big HBM buffer) is bf16 under
        # AMP and the dropout where() streams half the bytes
        attn = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        if training_dropout:
            keep = jax.random.bernoulli(rng, 1.0 - p_drop, jnp.shape(attn))
            attn = jnp.where(keep, attn / (1.0 - p_drop), 0.0).astype(v.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", attn, v)
    return {"Out": [out.astype(q.dtype)]}
