"""Shared box geometry helpers for the detection op family.

One IoU implementation for every pairwise-xyxy consumer (iou_similarity,
ssd_loss, rpn/proposal ops, detection_map) so the epsilon/clamp
conventions can't drift apart. Convention: zero-clamped edge lengths, no
+1 pixel offsets (the reference mixes both across files; ops needing the
+1 legacy convention, e.g. NMS in vision_ops, keep it locally and say
so)."""

from __future__ import annotations

import jax.numpy as jnp


def xyxy_area(b):
    return jnp.maximum(b[..., 2] - b[..., 0], 0.0) * jnp.maximum(
        b[..., 3] - b[..., 1], 0.0)


def iou_xyxy(a, b):
    """Pairwise IoU: a [..., M, 4], b [..., G, 4] -> [..., M, G]."""
    lt = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    rb = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = xyxy_area(a)[..., :, None] + xyxy_area(b)[..., None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)
