"""Shared box geometry helpers for the detection op family.

One IoU implementation for every pairwise-xyxy consumer (iou_similarity,
ssd_loss, rpn/proposal ops, detection_map) so the epsilon/clamp
conventions can't drift apart. Convention: zero-clamped edge lengths, no
+1 pixel offsets (the reference mixes both across files; ops needing the
+1 legacy convention, e.g. NMS in vision_ops, keep it locally and say
so)."""

from __future__ import annotations

import jax.numpy as jnp


def xyxy_area(b):
    return jnp.maximum(b[..., 2] - b[..., 0], 0.0) * jnp.maximum(
        b[..., 3] - b[..., 1], 0.0)


def iou_xyxy(a, b):
    """Pairwise IoU: a [..., M, 4], b [..., G, 4] -> [..., M, G]."""
    lt = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    rb = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = xyxy_area(a)[..., :, None] + xyxy_area(b)[..., None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


def greedy_bipartite_match(dist):
    """Greedy bipartite matching core shared by the standalone
    bipartite_match op and the fused ssd_loss (reference:
    detection/bipartite_match_op.cc BipartiteMatch): repeatedly take the
    global argmax of ``dist`` [m, n], record col->row, erase that row
    and column. Returns col_match [n] int32 (-1 unmatched).

    The loop is inherently sequential; a device While at realistic
    scale (m=50 gt, n=8732 priors, b=32) measured ~80 ms/step of
    per-iteration overhead (BASELINE.md SSD-300 trace), so small static
    trip counts unroll into straight-line code XLA fuses.
    """
    import jax

    m, n = dist.shape

    def body(_, state):
        col_match, d = state
        idx = jnp.argmax(d)
        r, c = idx // n, idx % n
        ok = d[r, c] > 0
        col_match = jnp.where(ok, col_match.at[c].set(r), col_match)
        d = jnp.where(ok, d.at[r, :].set(-1.0).at[:, c].set(-1.0), d)
        return col_match, d

    col0 = jnp.full((n,), -1, jnp.int32)
    state = (col0, dist.astype(jnp.float32))
    trip = min(m, n)
    if trip <= 64:
        for i in range(trip):
            state = body(i, state)
        return state[0]
    col_match, _ = jax.lax.fori_loop(0, trip, body, state)
    return col_match
