"""Control-flow ops: ``while``, ``cond``, ``scan``.

TPU-native redesign of the reference's control-flow operators
(reference: operators/controlflow/while_op.cc:43,
operators/controlflow/conditional_block_op.cc:75,
operators/recurrent_op.cc:250). The reference interprets a sub-block with a
recursively invoked executor over per-iteration scopes; on TPU the sub-block
is *traced* into the enclosing XLA computation as the closure of a
structural primitive:

- ``while``  -> ``lax.while_loop``  (data-dependent trip count; no gradient,
  matching XLA's non-differentiable While — training loops use ``scan``)
- ``cond``   -> ``lax.cond``        (differentiable via its linearization)
- ``scan``   -> ``lax.scan``        (fixed trip count; differentiable — this
  is the training-time recurrence primitive, replacing RecurrentOp's
  save-everything tape with XLA's scan transpose)

Conventions shared by the three ops: the sub-block reads/writes a functional
env (name -> array). Values crossing the block boundary are *op inputs*
(slots ``X``/``Init``/``Captured``), never Python closure captures, so state
analysis (core/lowering.py:analyze_state) and autodiff see them. Name lists
mapping slot positions to env names ride in attrs.

PRNG: each op folds the incoming key with the iteration counter so stochastic
sub-ops (dropout) draw fresh randomness per step, and the derived grad op
replays the same keys (attrs carry ``forward_op_idx``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core import interp
from paddle_tpu.core.registry import register_op


def _scalar_bool(x):
    return jnp.reshape(jnp.asarray(x), ()).astype(jnp.bool_)


def _sub_env(cap_names, cap_vals):
    env = {}
    for n, v in zip(cap_names, cap_vals):
        env[n] = v
    return env


@register_op("while", no_grad=True, needs_rng=True)
def _while(ins, attrs, rng=None):
    """Run ``sub_block`` while the condition var is true.

    attrs: sub_block, carry_names (env names of loop-carried values, first
    updated by each iteration), cond_name (env name of the bool scalar the
    sub-block must refresh each iteration), captured_names.
    inputs: Condition=[cond0], X=carried initial values, Captured=read-only.
    outputs: Out=final carried values (same order as carry_names).
    """
    sub = attrs["sub_block"]
    carry_names = list(attrs["carry_names"])
    cond_name = attrs["cond_name"]
    cap_names = list(attrs.get("captured_names", []))
    cap_vals = list(ins.get("Captured", []))
    amp = interp.amp_active()
    sub_ops = list(sub.ops)

    def cond_fun(carry):
        return _scalar_bool(carry[1])

    def body_fun(carry):
        i, cond_val = carry[0], carry[1]
        env = _sub_env(cap_names, cap_vals)
        env[cond_name] = cond_val
        env.update(zip(carry_names, carry[2:]))
        key = jax.random.fold_in(rng, i) if rng is not None else None
        interp.exec_ops(sub_ops, env, key=key, amp=amp)
        return (i + 1, _scalar_bool(env[cond_name])) + tuple(
            env[n] for n in carry_names
        )

    init = (
        jnp.zeros((), jnp.int32),
        _scalar_bool(ins["Condition"][0]),
    ) + tuple(ins.get("X", []))
    final = lax.while_loop(cond_fun, body_fun, init)
    return {"Out": list(final[2:]), "CondOut": [final[1]], "Steps": [final[0]]}


@register_op("bounded_while", diff_inputs=("X", "Captured"), needs_rng=True)
def _bounded_while(ins, attrs, rng=None):
    """Differentiable While: fixed trip count + liveness mask — the
    trainable lowering of the reference's while_op grad
    (operators/controlflow/while_op.cc:43 WhileGradOp). XLA's While is
    not reverse-differentiable, so ``While(cond, max_trip_count=N)``
    lowers to a ``lax.scan`` over exactly N steps where a dead step
    passes its carries through a select — gradients flow through the
    selects (dead iterations contribute zero) and through the captured
    values (weights read inside the loop). Costs N body evaluations
    regardless of the dynamic trip count; CondOut still True after N
    steps means the loop was TRUNCATED (the bound is a hard contract).

    Same attrs as ``while`` plus ``max_trip_count``; Steps counts the
    live iterations.
    """
    sub = attrs["sub_block"]
    carry_names = list(attrs["carry_names"])
    cond_name = attrs["cond_name"]
    cap_names = list(attrs.get("captured_names", []))
    n_steps = int(attrs["max_trip_count"])
    cap_vals = list(ins.get("Captured", []))
    amp = interp.amp_active()
    sub_ops = list(sub.ops)
    init = tuple(ins.get("X", []))
    init_dtypes = [jnp.result_type(v) for v in init]

    def body(carry, i):
        live, steps = carry[0], carry[1]
        vals = carry[2:]
        env = _sub_env(cap_names, cap_vals)
        env[cond_name] = live
        env.update(zip(carry_names, vals))
        key = jax.random.fold_in(rng, i) if rng is not None else None
        interp.exec_ops(sub_ops, env, key=key, amp=amp)
        new_vals = tuple(
            jnp.where(live, env[n].astype(dt), v)
            for n, v, dt in zip(carry_names, vals, init_dtypes)
        )
        new_live = jnp.logical_and(live, _scalar_bool(env[cond_name]))
        return ((new_live, steps + live.astype(jnp.int32)) + new_vals,
                None)

    carry0 = (_scalar_bool(ins["Condition"][0]),
              jnp.zeros((), jnp.int32)) + init
    final, _ = lax.scan(body, carry0, jnp.arange(n_steps, dtype=jnp.int32))
    return {"Out": list(final[2:]), "CondOut": [final[0]],
            "Steps": [final[1]]}


@register_op("cond", diff_inputs=("Captured",), needs_rng=True)
def _cond(ins, attrs, rng=None):
    """Select between two sub-blocks on a scalar predicate.

    attrs: true_block, false_block, true_out_names, false_out_names,
    captured_names. Both branches read the same Captured values; outputs are
    paired positionally (``Out[i]`` = true_out_names[i] / false_out_names[i]).
    """
    true_block, false_block = attrs["true_block"], attrs["false_block"]
    t_outs = list(attrs["true_out_names"])
    f_outs = list(attrs["false_out_names"])
    cap_names = list(attrs.get("captured_names", []))
    amp = interp.amp_active()
    pred = _scalar_bool(ins["Cond"][0])
    t_key = jax.random.fold_in(rng, 0) if rng is not None else None
    f_key = jax.random.fold_in(rng, 1) if rng is not None else None

    def make_branch(block, out_names, key):
        ops_ = list(block.ops)

        def branch(cap_vals):
            env = _sub_env(cap_names, cap_vals)
            interp.exec_ops(ops_, env, key=key, amp=amp)
            return tuple(env[n] for n in out_names)

        return branch

    outs = lax.cond(
        pred,
        make_branch(true_block, t_outs, t_key),
        make_branch(false_block, f_outs, f_key),
        tuple(ins.get("Captured", [])),
    )
    return {"Out": list(outs)}


@register_op(
    "scan", diff_inputs=("X", "Init", "Captured"), needs_rng=True
)
def _scan(ins, attrs, rng=None):
    """Fixed-length recurrence: run ``sub_block`` over the leading axis.

    attrs: sub_block, x_names (env names of per-step slices of X),
    state_in_names/state_out_names (parallel: carried state env names read /
    written per step), y_names (env names stacked into Y), captured_names,
    reverse, n_steps (required when X is empty).
    inputs: X=[T, ...] scanned tensors (time-major), Init=initial states,
    Captured=read-only values (parameters live here so gradients flow).
    outputs: Y=stacked per-step outputs [T, ...], FinalState=final states.

    Differentiable: the derived ``scan_grad`` op vjps through ``lax.scan``,
    which XLA transposes into the reverse-time accumulation the reference
    hand-writes in RecurrentGradOp (reference: operators/recurrent_op.cc:250).
    """
    sub = attrs["sub_block"]
    x_names = list(attrs.get("x_names", []))
    s_in = list(attrs.get("state_in_names", []))
    s_out = list(attrs.get("state_out_names", []))
    y_names = list(attrs.get("y_names", []))
    cap_names = list(attrs.get("captured_names", []))
    reverse = bool(attrs.get("reverse", False))
    xs = list(ins.get("X", []))
    init = list(ins.get("Init", []))
    cap_vals = list(ins.get("Captured", []))
    amp = interp.amp_active()
    sub_ops = list(sub.ops)

    if xs:
        n_steps = jnp.shape(xs[0])[0]
    else:
        n_steps = int(attrs["n_steps"])

    init_dtypes = [jnp.result_type(v) for v in init]

    # Pipeline parallelism: a scan marked ``pipelinable`` (scan-over-layers
    # model builds — one step per LAYER, carry = the activation stream)
    # runs the GPipe microbatch schedule over the strategy's pipe axis
    # instead of lax.scan: same math, layers spread one-per-rank with the
    # stacked weights sharded P(pipe) (parallel/pipeline.py). Time-scans
    # (RNNs) are never pipelined — they lack the marker.
    if attrs.get("pipelinable", False):
        ctx = interp.spmd_ctx()
        if ctx is not None and ctx.pipe_axis is not None:
            return _scan_as_gpipe(
                ctx, sub_ops, xs, init, cap_vals, cap_names, x_names,
                s_in, s_out, y_names, init_dtypes, reverse, rng, amp,
                list(attrs.get("stream_names", [])))

    def body(carry, step):
        i, xt = step
        env = _sub_env(cap_names, cap_vals)
        env.update(zip(s_in, carry))
        env.update(zip(x_names, xt))
        key = jax.random.fold_in(rng, i) if rng is not None else None
        interp.exec_ops(sub_ops, env, key=key, amp=amp)
        # AMP may narrow a carried activation to bf16 mid-body; scan
        # requires carry-in/carry-out types to match, so restore the
        # initial dtypes at the step boundary.
        new_carry = tuple(
            env[n].astype(dt) for n, dt in zip(s_out, init_dtypes)
        )
        ys = tuple(env[n] for n in y_names)
        return new_carry, ys

    # `unroll`: layers per loop iteration. unroll >= n_steps drops the
    # scan machinery entirely — a static Python loop with STATIC slices
    # of the stacked inputs, so no scan-transpose residual stacking and
    # no dynamic-update-slices in the backward; this is the re-plumbed
    # "unrolled build over stacked weights" path (measured: lax.scan
    # unroll=1 0.216 MFU / full-unroll-inside-scan 0.341 / this path
    # matches build() — BASELINE.md "scan-over-layers"). Intermediate
    # unrolls measured SLOWER than unroll=1 (0.18-0.19) and are kept
    # only for completeness.
    unroll = int(attrs.get("unroll", 1))
    if unroll >= int(n_steps):
        order = range(int(n_steps))
        if reverse:
            order = reversed(order)
        carry = tuple(init)
        ys_steps = []
        for i in order:
            carry, ys_t = body(carry, (jnp.int32(i),
                                       tuple(x[i] for x in xs)))
            ys_steps.append(ys_t)
        if reverse:
            ys_steps.reverse()
        ys = tuple(
            jnp.stack([st[j] for st in ys_steps])
            for j in range(len(y_names))
        )
        return {"Y": list(ys), "FinalState": list(carry)}
    steps = (jnp.arange(n_steps, dtype=jnp.int32), tuple(xs))
    final, ys = lax.scan(body, tuple(init), steps, reverse=reverse,
                         unroll=max(1, unroll))
    return {"Y": list(ys), "FinalState": list(final)}


def _scan_as_gpipe(ctx, sub_ops, xs, init, cap_vals, cap_names, x_names,
                   s_in, s_out, y_names, init_dtypes, reverse, rng, amp,
                   stream_names):
    """Run a pipelinable layer-scan as a GPipe schedule (see _scan)."""
    from paddle_tpu.parallel import pipeline as pp

    n_stages = ctx.mesh.shape[ctx.pipe_axis]
    if len(init) != 1 or y_names:
        raise ValueError(
            "pipeline strategy: a pipelinable scan must carry exactly one "
            "activation stream and emit no per-step outputs "
            f"(got {len(init)} carries, {len(y_names)} outputs)"
        )
    if not xs or int(xs[0].shape[0]) != n_stages:
        raise ValueError(
            f"pipeline strategy: the scan has {0 if not xs else int(xs[0].shape[0])} "
            f"stacked layers but the pipe axis '{ctx.pipe_axis}' has "
            f"{n_stages} ranks; they must match (one layer per rank)"
        )
    if reverse:
        raise ValueError("pipeline strategy: reverse layer-scan unsupported")

    # Captured values the BUILDER declared batch-shaped (attention biases,
    # the encoder output — scan attr ``stream_names``) are microbatched in
    # step with the activation stream; everything else closes over the
    # stage body unchanged. Declared, not inferred: a replicated constant
    # whose leading dim coincidentally equals the batch size must NOT be
    # sliced.
    b = int(init[0].shape[0])
    declared = set(stream_names)
    stream_idx = [i for i, n in enumerate(cap_names) if n in declared]
    for i in stream_idx:
        v = cap_vals[i]
        if not (hasattr(v, "ndim") and v.ndim >= 1 and v.shape[0] == b):
            raise ValueError(
                f"pipeline strategy: declared stream '{cap_names[i]}' "
                f"does not have the carry's batch dim {b} "
                f"(shape {getattr(v, 'shape', None)})"
            )
    stream_names = [cap_names[i] for i in stream_idx]
    const_pairs = [
        (n, v) for i, (n, v) in enumerate(zip(cap_names, cap_vals))
        if i not in stream_idx
    ]

    def stage(params, act, *streams, micro_idx):
        env = {n: v for n, v in const_pairs}
        env.update(zip(stream_names, streams))
        env.update(zip(s_in, (act,)))
        env.update(zip(x_names, params))
        # layer key: the layer index IS the pipe rank (matching the
        # lax.scan path's fold_in(rng, step)); the microbatch index folds
        # in too so microbatches draw INDEPENDENT dropout masks — the
        # full-batch lax.scan mask differs row to row.
        key = None
        if rng is not None:
            key = jax.random.fold_in(
                jax.random.fold_in(rng, lax.axis_index(ctx.pipe_axis)),
                micro_idx)
        interp.exec_ops(sub_ops, env, key=key, amp=amp)
        return env[s_out[0]].astype(init_dtypes[0])

    out = pp.gpipe(
        stage, tuple(xs), init[0], ctx.mesh, pipe_axis=ctx.pipe_axis,
        n_micro=ctx.pipe_micro,
        batch_streams=tuple(cap_vals[i] for i in stream_idx),
        with_micro_idx=True,
        data_axis=ctx.data_axis,
    )
    return {"Y": [], "FinalState": [out]}
