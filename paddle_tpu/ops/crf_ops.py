"""Structured-prediction ops: linear-chain CRF and CTC loss.

TPU-native redesign of the reference's sequence-labeling operators
(reference: operators/linear_chain_crf_op.cc, crf_decoding_op.cc,
operators/warpctc_op.cc — the last wraps the external warp-ctc CUDA
library, cmake/external/warpctc.cmake). Ragged LoD inputs become padded
``[B, T, ...]`` batches + ``Length`` vectors; the dynamic-programming
recursions (CRF forward, Viterbi, CTC alpha) are ``lax.scan`` loops in
log-space, so XLA compiles them and — for the losses — the gradients fall
out of scan's transpose: no hand-written backward kernels or external CTC
library.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.registry import register_op

NEG = -1e30


def _lengths(ins, slot, t):
    v = ins.get(slot)
    ln = v[0] if v else None
    if ln is None:
        return None
    if jnp.ndim(ln) > 1:
        ln = jnp.squeeze(ln, axis=-1)
    return ln.astype(jnp.int32)


@register_op("linear_chain_crf", diff_inputs=("Emission", "Transition"))
def _linear_chain_crf(ins, attrs):
    """Negative log-likelihood of a linear-chain CRF.

    inputs: Emission [B, T, C] unary scores; Transition [C+2, C] (row 0 =
    start scores, row 1 = end scores, rows 2.. = pairwise a->b, matching
    the reference's layout, linear_chain_crf_op.cc); Label [B, T] int;
    Length [B] optional.
    outputs: LogLikelihood [B, 1] — despite the (reference-inherited)
    name, this is the NEGATIVE log-likelihood -log p(label|x), matching
    the reference kernel's ``return -ll`` (linear_chain_crf_op.h:193):
    minimize it directly.
    """
    em = ins["Emission"][0]
    em = em.astype(jnp.promote_types(em.dtype, jnp.float32))
    trans = ins["Transition"][0].astype(em.dtype)
    label = ins["Label"][0]
    if jnp.ndim(label) > 2:
        label = jnp.squeeze(label, axis=-1)
    b, t, c = jnp.shape(em)
    lengths = _lengths(ins, "Length", t)
    if lengths is None:
        lengths = jnp.full((b,), t, jnp.int32)
    start, end, pair = trans[0], trans[1], trans[2:]

    steps = jnp.arange(t)
    live = steps[None, :] < lengths[:, None]            # [B, T]
    is_last = steps[None, :] == (lengths[:, None] - 1)  # [B, T]

    # --- partition function: log-space forward algorithm ---
    alpha0 = start[None, :] + em[:, 0, :]               # [B, C]

    def fwd(alpha, xs):
        e_t, live_t, last_t = xs                        # [B,C],[B],[B]
        # logsumexp over previous tag
        scores = alpha[:, :, None] + pair[None, :, :]   # [B, Cprev, C]
        new = jax.nn.logsumexp(scores, axis=1) + e_t
        alpha = jnp.where(live_t[:, None], new, alpha)
        # add end scores exactly once, at each row's last live step
        alpha = alpha + jnp.where(last_t[:, None], end[None, :], 0.0)
        return alpha, None

    xs = (
        jnp.swapaxes(em, 0, 1)[1:],                     # [T-1, B, C]
        jnp.swapaxes(live, 0, 1)[1:],
        jnp.swapaxes(is_last, 0, 1)[1:],
    )
    alpha0 = alpha0 + jnp.where(is_last[:, 0][:, None], end[None, :], 0.0)
    alpha, _ = lax.scan(fwd, alpha0, xs)
    log_z = jax.nn.logsumexp(alpha, axis=-1)            # [B]

    # --- gold path score ---
    lab = label.astype(jnp.int32)
    emit = jnp.take_along_axis(em, lab[:, :, None], axis=2)[..., 0]  # [B,T]
    emit_sum = jnp.sum(emit * live.astype(em.dtype), axis=1)
    trans_pair = pair[lab[:, :-1], lab[:, 1:]]          # [B, T-1]
    trans_sum = jnp.sum(
        trans_pair * live[:, 1:].astype(em.dtype), axis=1
    )
    last_idx = jnp.maximum(lengths - 1, 0)
    gold = (
        emit_sum
        + trans_sum
        + start[lab[:, 0]]
        + end[jnp.take_along_axis(lab, last_idx[:, None], axis=1)[:, 0]]
    )
    return {"LogLikelihood": [(log_z - gold)[:, None]]}


@register_op("crf_decoding", no_grad=True)
def _crf_decoding(ins, attrs):
    """Viterbi decode (reference: operators/crf_decoding_op.cc).

    inputs: Emission [B, T, C], Transition [C+2, C], Length [B] optional.
    outputs: ViterbiPath [B, T] int64 (padding positions are 0).
    """
    em = ins["Emission"][0]
    em = em.astype(jnp.promote_types(em.dtype, jnp.float32))
    trans = ins["Transition"][0].astype(em.dtype)
    b, t, c = jnp.shape(em)
    lengths = _lengths(ins, "Length", t)
    if lengths is None:
        lengths = jnp.full((b,), t, jnp.int32)
    start, end, pair = trans[0], trans[1], trans[2:]

    steps = jnp.arange(t)
    live = steps[None, :] < lengths[:, None]
    is_last = steps[None, :] == (lengths[:, None] - 1)

    def step(delta, xs):
        e_t, live_t, last_t = xs
        scores = delta[:, :, None] + pair[None, :, :]   # [B, Cprev, C]
        best_prev = jnp.argmax(scores, axis=1)          # [B, C]
        new = jnp.max(scores, axis=1) + e_t
        delta_new = jnp.where(live_t[:, None], new, delta)
        # dead steps backtrack to themselves (identity pointer)
        ptr = jnp.where(
            live_t[:, None], best_prev, jnp.arange(c)[None, :]
        )
        delta_new = delta_new + jnp.where(
            last_t[:, None], end[None, :], 0.0
        )
        return delta_new, ptr

    delta0 = start[None, :] + em[:, 0, :]
    delta0 = delta0 + jnp.where(is_last[:, 0][:, None], end[None, :], 0.0)
    xs = (
        jnp.swapaxes(em, 0, 1)[1:],
        jnp.swapaxes(live, 0, 1)[1:],
        jnp.swapaxes(is_last, 0, 1)[1:],
    )
    delta, ptrs = lax.scan(step, delta0, xs)            # ptrs [T-1, B, C]

    best_last = jnp.argmax(delta, axis=-1)              # [B]

    def back(tag, ptr_t):
        prev = jnp.take_along_axis(ptr_t, tag[:, None], axis=1)[:, 0]
        return prev, tag

    # ys[i] = tag at position i+1; the final carry is the position-0 tag
    first, path_tail = lax.scan(back, best_last, ptrs, reverse=True)
    path = jnp.concatenate(
        [first[None, :], path_tail], axis=0
    )                                                   # [T, B]
    path = jnp.swapaxes(path, 0, 1)                     # [B, T]
    return {"ViterbiPath": [(path * live).astype(jnp.int64)]}


@register_op("warpctc", diff_inputs=("Logits",))
def _warpctc(ins, attrs):
    """CTC loss (reference: operators/warpctc_op.cc wrapping warp-ctc;
    here the standard log-space alpha recursion under lax.scan).

    inputs: Logits [B, T, C] unnormalized; Label [B, L] int (padded with
    ``blank``); LogitsLength [B] optional; LabelLength [B] optional.
    attrs: blank (default 0), norm_by_times (divide each loss by its
    logit length).
    outputs: Loss [B, 1] (positive NLL).
    """
    logits = ins["Logits"][0]
    logits = logits.astype(jnp.promote_types(logits.dtype, jnp.float32))
    label = ins["Label"][0].astype(jnp.int32)
    b, t, c = jnp.shape(logits)
    l = jnp.shape(label)[1]
    blank = int(attrs.get("blank", 0))
    logit_len = _lengths(ins, "LogitsLength", t)
    if logit_len is None:
        logit_len = jnp.full((b,), t, jnp.int32)
    label_len = _lengths(ins, "LabelLength", l)
    if label_len is None:
        label_len = jnp.full((b,), l, jnp.int32)

    logp = jax.nn.log_softmax(logits, axis=-1)          # [B, T, C]

    # extended label sequence: blank y1 blank y2 ... yL blank  (len 2L+1)
    s = 2 * l + 1
    ext = jnp.full((b, s), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(label)                    # odd positions
    ext_len = 2 * label_len + 1

    pos = jnp.arange(s)[None, :]
    valid = pos < ext_len[:, None]                      # [B, S]
    # allowed skip transition s-2 -> s: ext[s] != blank and != ext[s-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :-2]
    can_skip = (ext != blank) & (ext != ext_m2)

    def emit(logp_t, e):
        # logp_t [B, C]; gather per extended position -> [B, S]
        return jnp.take_along_axis(logp_t, e, axis=1)

    a0 = jnp.full((b, s), NEG)
    a0 = a0.at[:, 0].set(emit(logp[:, 0], ext)[:, 0])
    a0 = a0.at[:, 1].set(
        jnp.where(label_len > 0, emit(logp[:, 0], ext)[:, 1], NEG)
    )

    def step(alpha, xs):
        logp_t, live_t = xs                             # [B, C], [B]
        stay = alpha
        prev1 = jnp.pad(
            alpha, ((0, 0), (1, 0)), constant_values=NEG
        )[:, :-1]
        prev2 = jnp.pad(
            alpha, ((0, 0), (2, 0)), constant_values=NEG
        )[:, :-2]
        prev2 = jnp.where(can_skip, prev2, NEG)
        tot = jnp.logaddexp(jnp.logaddexp(stay, prev1), prev2)
        new = tot + emit(logp_t, ext)
        new = jnp.where(valid, new, NEG)
        return jnp.where(live_t[:, None], new, alpha), None

    live = (jnp.arange(t)[None, :] < logit_len[:, None])
    xs = (jnp.swapaxes(logp, 0, 1)[1:], jnp.swapaxes(live, 0, 1)[1:])
    alpha, _ = lax.scan(step, a0, xs)

    idx_last = jnp.maximum(ext_len - 1, 0)
    a_last = jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(
        alpha, jnp.maximum(idx_last - 1, 0)[:, None], axis=1
    )[:, 0]
    # empty-label rows (ext_len == 1) have only the single blank path —
    # a_prev would alias a_last and double-count it
    a_prev = jnp.where(ext_len >= 2, a_prev, NEG)
    ll = jnp.logaddexp(a_last, a_prev)
    loss = -ll
    if attrs.get("norm_by_times", False):
        loss = loss / logit_len.astype(loss.dtype)
    return {"Loss": [loss[:, None]]}


@register_op("edit_distance", no_grad=True)
def _edit_distance(ins, attrs):
    """Levenshtein distance per row (reference:
    operators/edit_distance_op.cc). Hyps [B, L1], Refs [B, L2] int padded;
    HypsLength/RefsLength [B] optional; attr normalized divides by ref
    length. outputs: Out [B, 1] f32, SequenceNum [1]."""
    hyp = ins["Hyps"][0].astype(jnp.int32)
    ref = ins["Refs"][0].astype(jnp.int32)
    b, l1 = jnp.shape(hyp)
    l2 = jnp.shape(ref)[1]
    hlen = _lengths(ins, "HypsLength", l1)
    if hlen is None:
        hlen = jnp.full((b,), l1, jnp.int32)
    rlen = _lengths(ins, "RefsLength", l2)
    if rlen is None:
        rlen = jnp.full((b,), l2, jnp.int32)

    # DP over hyp positions; row = distances over ref prefix lengths
    row0 = jnp.broadcast_to(
        jnp.arange(l2 + 1, dtype=jnp.float32)[None, :], (b, l2 + 1)
    )
    # positions beyond this row's ref length are clamped to its length
    row0 = jnp.minimum(row0, rlen[:, None].astype(jnp.float32))

    def step(row, xs):
        h_t, i = xs                                     # [B], scalar idx
        i1 = (i + 1).astype(jnp.float32)
        live_h = i < hlen                               # [B]
        sub_cost = (h_t[:, None] != ref).astype(jnp.float32)  # [B, L2]

        def inner(carry, j):
            left = carry                                 # new[j] running
            up = row[:, j + 1]
            diag = row[:, j]
            live_r = j < rlen
            cand = jnp.minimum(
                jnp.minimum(up + 1.0, left + 1.0),
                diag + sub_cost[:, j],
            )
            val = jnp.where(live_r, cand, left)
            return val, val

        first = jnp.where(live_h, i1, row[:, 0])
        _, cols = lax.scan(inner, first, jnp.arange(l2))
        new = jnp.concatenate(
            [first[None, :], cols], axis=0
        ).T                                              # [B, L2+1]
        return jnp.where(live_h[:, None], new, row), None

    row, _ = lax.scan(step, row0, (jnp.swapaxes(hyp, 0, 1), jnp.arange(l1)))
    dist = jnp.take_along_axis(row, rlen[:, None], axis=1)[:, 0]
    if attrs.get("normalized", False):
        dist = dist / jnp.maximum(rlen.astype(dist.dtype), 1.0)
    return {
        "Out": [dist[:, None]],
        "SequenceNum": [jnp.asarray([b], jnp.int64)],
    }


@register_op("ctc_align", no_grad=True)
def _ctc_align(ins, attrs):
    """CTC decode alignment: merge repeats, drop blanks (reference:
    ctc_align_op.h). Dense form: Input [B, T] int tokens (+ optional
    InputLength [B]); Output [B, T] left-compacted with ``padding_value``
    (default 0) fill, OutputLength [B] kept tokens per row; a row with
    nothing kept emits -1 at position 0 (reference's empty-sequence
    convention)."""
    x = ins["Input"][0]
    li = ins.get("InputLength")
    length = li[0] if li else None
    blank = int(attrs.get("blank", 0))
    merge = bool(attrs.get("merge_repeated", True))
    pad_v = int(attrs.get("padding_value", 0))
    x2 = x.reshape(x.shape[0], -1).astype(jnp.int32)
    b, t = x2.shape
    valid = (jnp.arange(t)[None] < length.reshape(-1, 1)
             ) if length is not None else jnp.ones((b, t), bool)
    prev = jnp.pad(x2[:, :-1], ((0, 0), (1, 0)), constant_values=-1)
    keep = valid & (x2 != blank)
    if merge:
        keep = keep & (x2 != prev)
    # left-compact kept tokens (stable order)
    order = jnp.argsort(~keep, axis=1, stable=True)
    compact = jnp.take_along_axis(x2, order, 1)
    n_keep = jnp.sum(keep, 1)
    pos = jnp.arange(t)[None]
    out = jnp.where(pos < n_keep[:, None], compact, pad_v)
    out = jnp.where((n_keep == 0)[:, None] & (pos == 0), -1, out)
    return {"Output": [out.astype(x.dtype)],
            "OutputLength": [n_keep.astype(jnp.int32).reshape(-1, 1)]}
