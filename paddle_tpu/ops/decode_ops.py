"""Decoding ops: beam search.

TPU-native redesign of the reference's beam-search operators
(reference: operators/beam_search_op.cc, beam_search_decode_op.cc,
python/paddle/fluid/layers/control_flow.py beam search wrappers). The
reference keeps per-hypothesis LoD structures and backtracks parent
pointers at the end (beam_search_decode); here beams are a dense
``[batch, beam]`` axis with static shapes, and each step gathers the full
id history by parent beam — O(T) extra copies per step, but branch-free,
fully batched, and compiled into the XLA While body (no host round trips).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.registry import register_op


@register_op("beam_search_step", no_grad=True)
def _beam_search_step(ins, attrs):
    """One beam-search expansion step.

    inputs:
      Ids      [B, K, T] int   — id history (position >= StepIdx is garbage)
      Scores   [B, K] f32      — cumulative log-probs per live hypothesis
      LogProbs [B, K, V] f32   — log p(next token) at the current position
      Finished [B, K] bool     — hypotheses that already emitted end_id
      StepIdx  [] int          — time position the chosen token is written to
    attrs: end_id (int).
    outputs: Ids / Scores / Finished (updated), Parent [B, K] int64.

    Finished hypotheses only extend with end_id at zero cost, so they
    compete in the top-k on their frozen score (reference
    beam_search_op.cc keeps finished hypotheses in the candidate set the
    same way).
    """
    ids = ins["Ids"][0]
    scores = ins["Scores"][0]
    logp = ins["LogProbs"][0]
    finished = ins["Finished"][0].astype(bool)
    t = jnp.reshape(ins["StepIdx"][0], ()).astype(jnp.int32)
    end_id = int(attrs.get("end_id", 1))

    b, k, v = jnp.shape(logp)
    neg_inf = jnp.asarray(jnp.finfo(logp.dtype).min, logp.dtype)

    # Finished rows: only end_id is a legal continuation, with logp 0.
    eos_row = jnp.full((v,), neg_inf, logp.dtype).at[end_id].set(0.0)
    logp = jnp.where(finished[:, :, None], eos_row[None, None, :], logp)

    total = scores[:, :, None] + logp                      # [B, K, V]
    flat = jnp.reshape(total, (b, k * v))
    top_scores, top_idx = lax.top_k(flat, k)               # [B, K]
    parent = (top_idx // v).astype(jnp.int32)
    token = (top_idx % v).astype(ids.dtype)

    new_ids = jnp.take_along_axis(ids, parent[:, :, None], axis=1)
    new_ids = lax.dynamic_update_slice(
        new_ids,
        token[:, :, None],
        (jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32), t),
    )
    new_finished = jnp.take_along_axis(finished, parent, axis=1) | (
        token == end_id
    )
    return {
        "Ids": [new_ids],
        "Scores": [top_scores],
        "Finished": [new_finished],
        "Parent": [parent.astype(jnp.int64)],
    }


@register_op("beam_gather", no_grad=True)
def _beam_gather(ins, attrs):
    """Per-row beam selection: X [B, K, ...] gathered by Index [B] ->
    [B, ...] (the final pick of beam_search_decode; reference:
    beam_search_decode_op.cc selects the top sentence per source)."""
    x = ins["X"][0]
    idx = ins["Index"][0].astype(jnp.int32).reshape(-1)
    return {"Out": [jnp.take_along_axis(
        x, idx.reshape((-1,) + (1,) * (x.ndim - 1)), axis=1)[:, 0]]}
