"""Detection ops: target assignment, proposals, YOLO/SSD losses, FPN
routing, mAP.

Reference kernels: paddle/fluid/operators/detection/{target_assign_op.cc,
mine_hard_examples_op.cc, yolov3_loss_op.h, rpn_target_assign_op.cc,
generate_proposals_op.cc, generate_proposal_labels_op.cc,
distribute_fpn_proposals_op.cc, collect_fpn_proposals_op.cc,
box_decoder_and_assign_op.cc, detection_map_op.cc}.

Dense-padded design (SURVEY.md section 5): where the reference passes
variable-length LoD tensors (ground-truth boxes per image, sampled
indices), these ops take fixed-capacity tensors padded with sentinel
rows — gt boxes with non-positive width/height (YOLO convention,
yolov3_loss_op.h GtValid) or an explicit count/mask — and return
fixed-capacity outputs plus weights/masks. Losses contract with the
weights, so padding never contributes; control flow stays static for
XLA.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.box_util import greedy_bipartite_match
from paddle_tpu.ops.box_util import iou_xyxy as _iou_xyxy
from paddle_tpu.ops.box_util import xyxy_area as _xyxy_area

_NEG = -1e9


def _x(ins, slot="X", i=0):
    v = ins.get(slot)
    return v[i] if v else None


def _decode_anchor(anchors, deltas, variances=None):
    """Decode bbox deltas against xyxy anchors (decode_center_size with
    per-anchor variances; reference generate_proposals_op.cc BoxCoder)."""
    aw = anchors[..., 2] - anchors[..., 0] + 1.0
    ah = anchors[..., 3] - anchors[..., 1] + 1.0
    ax = anchors[..., 0] + aw * 0.5
    ay = anchors[..., 1] + ah * 0.5
    dx, dy, dw, dh = (deltas[..., 0], deltas[..., 1], deltas[..., 2],
                      deltas[..., 3])
    if variances is not None:
        dx = dx * variances[..., 0]
        dy = dy * variances[..., 1]
        dw = dw * variances[..., 2]
        dh = dh * variances[..., 3]
    # kBBoxClipDefault = log(1000/16): keeps exp() finite for wild deltas
    clip = jnp.log(1000.0 / 16.0)
    cx = dx * aw + ax
    cy = dy * ah + ay
    w = jnp.exp(jnp.minimum(dw, clip)) * aw
    h = jnp.exp(jnp.minimum(dh, clip)) * ah
    return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5 - 1.0, cy + h * 0.5 - 1.0], axis=-1)


@register_op("target_assign", no_grad=True)
def _target_assign(ins, attrs):
    """Gather targets by match indices (reference: target_assign_op.cc).

    X [N, G, K] per-image entities (dense analog of the LoD rows),
    MatchIndices [N, P] int32 (-1 = unmatched), optional NegIndices
    [N, S] int32 (-1 padding). Out [N, P, K], OutWeight [N, P, 1].
    """
    x = _x(ins)
    match = _x(ins, "MatchIndices")
    neg = _x(ins, "NegIndices")
    mismatch = attrs.get("mismatch_value", 0.0)
    safe = jnp.maximum(match, 0)
    out = jnp.take_along_axis(x, safe[..., None], axis=1)
    matched = (match >= 0)[..., None]
    out = jnp.where(matched, out, jnp.asarray(mismatch, x.dtype))
    weight = matched.astype(x.dtype)
    if neg is not None:
        n, p = match.shape
        neg_hit = jnp.zeros((n, p), bool)
        cols = jnp.maximum(neg, 0)
        neg_hit = jax.vmap(
            lambda h, c, m: h.at[c].max(m)
        )(neg_hit, cols, neg >= 0)
        out = jnp.where(neg_hit[..., None] & ~matched,
                        jnp.asarray(mismatch, x.dtype), out)
        weight = jnp.maximum(weight, neg_hit[..., None].astype(x.dtype))
    return {"Out": [out], "OutWeight": [weight]}


@register_op("mine_hard_examples", no_grad=True)
def _mine_hard_examples(ins, attrs):
    """Hard-negative mining (reference: mine_hard_examples_op.cc,
    max_negative mode): per image, rank unmatched priors by loss and keep
    the top ``neg_pos_ratio * num_pos``; in hard_example mining
    ``sample_size`` replaces that cap (max_negative ignores it, matching
    the reference). NegIndices [N, P] int32, -1 padded; UpdatedMatchIndices keeps
    matches, sets mined negatives to -1 (they already are)."""
    cls_loss = _x(ins, "ClsLoss")
    loc_loss = _x(ins, "LocLoss")
    match = _x(ins, "MatchIndices")
    ratio = float(attrs.get("neg_pos_ratio", 3.0))
    overlap = attrs.get("neg_dist_threshold", 0.5)
    sample_size = int(attrs.get("sample_size", 0))
    dist = _x(ins, "MatchDist")
    loss = cls_loss.astype(jnp.float32)
    if loc_loss is not None and attrs.get("mining_type",
                                          "max_negative") == "hard_example":
        loss = loss + loc_loss.astype(jnp.float32)
    n, p = match.shape
    is_neg = match < 0
    if dist is not None:
        is_neg = is_neg & (dist < overlap)
    num_pos = jnp.sum(match >= 0, axis=1)
    num_neg = jnp.sum(is_neg, axis=1)
    # sample_size replaces the ratio cap only for hard_example mining
    # (reference mine_hard_examples_op.cc); max_negative always uses
    # neg_pos_ratio * num_pos.
    mining_type = attrs.get("mining_type", "max_negative")
    if mining_type == "hard_example" and sample_size > 0:
        want = jnp.minimum(jnp.int32(sample_size), num_neg)
    else:
        want = jnp.minimum((num_pos * ratio).astype(jnp.int32), num_neg)
    masked = jnp.where(is_neg, loss, _NEG)
    order = jnp.argsort(-masked, axis=1)  # hardest negatives first
    rank = jnp.arange(p)[None, :]
    neg_idx = jnp.where(rank < want[:, None], order.astype(jnp.int32), -1)
    return {"NegIndices": [neg_idx], "UpdatedMatchIndices": [match]}


def _yolo_grids(x, anchors, anchor_mask, class_num, downsample):
    n, c, h, w = x.shape
    m = len(anchor_mask)
    xr = x.reshape(n, m, 5 + class_num, h, w)
    input_size = downsample * h
    gx = (jnp.arange(w, dtype=jnp.float32))[None, None, None, :]
    gy = (jnp.arange(h, dtype=jnp.float32))[None, None, :, None]
    aw = jnp.asarray([anchors[2 * i] for i in anchor_mask], jnp.float32)
    ah = jnp.asarray([anchors[2 * i + 1] for i in anchor_mask], jnp.float32)
    px = (gx + jax.nn.sigmoid(xr[:, :, 0])) / w
    py = (gy + jax.nn.sigmoid(xr[:, :, 1])) / h
    pw = jnp.exp(xr[:, :, 2]) * aw[None, :, None, None] / input_size
    ph = jnp.exp(xr[:, :, 3]) * ah[None, :, None, None] / input_size
    return xr, (px, py, pw, ph), input_size


def _iou_cxcywh(ax, ay, aw, ah, bx, by, bw, bh):
    """IoU of center-format boxes (broadcasting)."""
    l = jnp.maximum(ax - aw / 2, bx - bw / 2)
    r = jnp.minimum(ax + aw / 2, bx + bw / 2)
    t = jnp.maximum(ay - ah / 2, by - bh / 2)
    b = jnp.minimum(ay + ah / 2, by + bh / 2)
    inter = jnp.maximum(r - l, 0.0) * jnp.maximum(b - t, 0.0)
    union = aw * ah + bw * bh - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


def _bce_logits(logit, target):
    return jnp.maximum(logit, 0.0) - logit * target + jnp.log1p(
        jnp.exp(-jnp.abs(logit)))


@register_op("yolov3_loss", diff_inputs=("X",))
def _yolov3_loss(ins, attrs):
    """YOLOv3 loss (reference: yolov3_loss_op.h). X [N, m*(5+C), H, W],
    GTBox [N, B, 4] center-format (x, y, w, h) normalized to [0, 1]
    (rows with w or h <= 0 are padding), GTLabel [N, B] int, optional
    GTScore [N, B] (mixup). Loss [N]; aux ObjectnessMask, GTMatchMask."""
    x = _x(ins)
    gt_box = _x(ins, "GTBox").astype(jnp.float32)
    gt_label = _x(ins, "GTLabel")
    gt_score = _x(ins, "GTScore")
    anchors = [int(a) for a in attrs["anchors"]]
    anchor_mask = [int(a) for a in attrs["anchor_mask"]]
    class_num = int(attrs["class_num"])
    ignore_thresh = float(attrs.get("ignore_thresh", 0.7))
    downsample = int(attrs.get("downsample_ratio", 32))
    smooth = bool(attrs.get("use_label_smooth", True))
    n, c, h, w = x.shape
    m = len(anchor_mask)
    b = gt_box.shape[1]
    xf = x.astype(jnp.float32)
    xr, (px, py, pw, ph), input_size = _yolo_grids(
        xf, anchors, anchor_mask, class_num, downsample)
    if gt_score is None:
        gt_score = jnp.ones((n, b), jnp.float32)
    valid = (gt_box[..., 2] > 0) & (gt_box[..., 3] > 0)       # [N, B]

    # ignore mask: best IoU of each predicted box over valid gts
    iou = _iou_cxcywh(
        px[..., None], py[..., None], pw[..., None], ph[..., None],
        gt_box[:, None, None, None, :, 0], gt_box[:, None, None, None, :, 1],
        gt_box[:, None, None, None, :, 2], gt_box[:, None, None, None, :, 3])
    iou = jnp.where(valid[:, None, None, None, :], iou, 0.0)
    best_iou = jnp.max(iou, axis=-1)                          # [N, m, H, W]

    # per-gt best anchor over the FULL anchor set (shifted to origin)
    an_num = len(anchors) // 2
    aw_all = jnp.asarray(anchors[0::2], jnp.float32) / input_size
    ah_all = jnp.asarray(anchors[1::2], jnp.float32) / input_size
    gt_an_iou = _iou_cxcywh(
        jnp.zeros(()), jnp.zeros(()), gt_box[..., 2:3], gt_box[..., 3:4],
        jnp.zeros(()), jnp.zeros(()), aw_all[None, None, :],
        ah_all[None, None, :])                                # [N, B, A]
    best_n = jnp.argmax(gt_an_iou, axis=-1)                   # [N, B]
    mask_lut = -jnp.ones((an_num,), jnp.int32)
    for pos, a in enumerate(anchor_mask):
        mask_lut = mask_lut.at[a].set(pos)
    mask_idx = jnp.where(valid, mask_lut[best_n], -1)         # [N, B]
    sel = valid & (mask_idx >= 0)

    gi = jnp.clip((gt_box[..., 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gt_box[..., 1] * h).astype(jnp.int32), 0, h - 1)
    midx = jnp.maximum(mask_idx, 0)
    bidx = jnp.broadcast_to(jnp.arange(n)[:, None], (n, b))

    # gather the responsible cell's logits per gt: [N, B, 5+C]
    cell = xr[bidx, midx, :, gj, gi]
    tx = gt_box[..., 0] * w - gi
    ty = gt_box[..., 1] * h - gj
    sel_aw = jnp.asarray(anchors[0::2], jnp.float32)[best_n]
    sel_ah = jnp.asarray(anchors[1::2], jnp.float32)[best_n]
    tw = jnp.log(jnp.maximum(gt_box[..., 2] * input_size, 1e-9) /
                 jnp.maximum(sel_aw, 1e-9))
    th = jnp.log(jnp.maximum(gt_box[..., 3] * input_size, 1e-9) /
                 jnp.maximum(sel_ah, 1e-9))
    scale = (2.0 - gt_box[..., 2] * gt_box[..., 3]) * gt_score
    loc = (_bce_logits(cell[..., 0], tx) + _bce_logits(cell[..., 1], ty)
           + jnp.abs(cell[..., 2] - tw) + jnp.abs(cell[..., 3] - th))
    loc_loss = jnp.sum(jnp.where(sel, loc * scale, 0.0), axis=1)

    if smooth and class_num > 1:
        pos_t, neg_t = 1.0 - 1.0 / class_num, 1.0 / class_num
    else:
        pos_t, neg_t = 1.0, 0.0
    onehot = jax.nn.one_hot(gt_label, class_num, dtype=jnp.float32)
    tcls = onehot * pos_t + (1.0 - onehot) * neg_t
    cls = jnp.sum(_bce_logits(cell[..., 5:], tcls), axis=-1)
    cls_loss = jnp.sum(jnp.where(sel, cls * gt_score, 0.0), axis=1)

    # objectness mask: score at responsible cells, -1 where ignored.
    # Padding rows (sel=False) are routed to the out-of-bounds batch index
    # n and dropped, so a padding row sharing (anchor0, cell 0,0) with a
    # real positive can never overwrite the real write with a stale value.
    obj = jnp.where(best_iou > ignore_thresh, -1.0, 0.0)      # [N, m, H, W]
    bidx_sel = jnp.where(sel, bidx, n)
    obj = obj.at[bidx_sel, midx, gj, gi].set(gt_score, mode="drop")
    obj = jax.lax.stop_gradient(obj)
    obj_logit = xr[:, :, 4]
    obj_loss = jnp.sum(
        jnp.where(obj > 1e-5, _bce_logits(obj_logit, 1.0) * obj,
                  jnp.where(obj > -0.5, _bce_logits(obj_logit, 0.0), 0.0)),
        axis=(1, 2, 3))

    loss = loc_loss + cls_loss + obj_loss
    return {
        "Loss": [loss],
        "ObjectnessMask": [obj],
        "GTMatchMask": [jax.lax.stop_gradient(mask_idx)],
    }


@register_op("ssd_loss", diff_inputs=("Location", "Confidence"))
def _ssd_loss(ins, attrs):
    """Fused SSD multibox loss (reference: layers/detection.py ssd_loss —
    bipartite match + hard-negative mining + target assign + smooth-l1 +
    softmax CE). The reference composes ~10 LoD ops; here the whole loss
    is one fused dense computation (targets/masks under stop_gradient,
    XLA fuses the rest). Location [N, P, 4], Confidence [N, P, C],
    GtBox [N, G, 4] xyxy (zero-area rows padding), GtLabel [N, G] int,
    PriorBox [P, 4], PriorBoxVar [P, 4] optional. Loss [N, 1]."""
    loc = _x(ins, "Location")
    conf = _x(ins, "Confidence")
    gt_box = _x(ins, "GtBox").astype(jnp.float32)
    gt_label = _x(ins, "GtLabel")
    prior = _x(ins, "PriorBox").astype(jnp.float32)
    pvar = _x(ins, "PriorBoxVar")
    bg = int(attrs.get("background_label", 0))
    overlap_t = float(attrs.get("overlap_threshold", 0.5))
    neg_ratio = float(attrs.get("neg_pos_ratio", 3.0))
    neg_overlap = float(attrs.get("neg_overlap", 0.5))
    loc_w = float(attrs.get("loc_loss_weight", 1.0))
    conf_w = float(attrs.get("conf_loss_weight", 1.0))
    match_type = attrs.get("match_type", "per_prediction")
    normalize = bool(attrs.get("normalize", True))
    n, p, c = conf.shape
    g = gt_box.shape[1]
    gt_valid = _xyxy_area(gt_box) > 0                          # [N, G]
    iou = _iou_xyxy(gt_box, prior[None].repeat(n, 0))          # [N, G, P]
    iou = jnp.where(gt_valid[..., None], iou, -1.0)

    def match_one(d):
        # shared greedy core (box_util.greedy_bipartite_match) keeps
        # this fused path and the standalone bipartite_match op from
        # drifting, and carries the static-unroll perf fix for both
        col_match = greedy_bipartite_match(d)
        if match_type == "per_prediction":
            # unmatched priors additionally match their best gt at or
            # above overlap_threshold (reference bipartite_match_op.cc
            # ArgMaxMatch uses >= dist_threshold; same comparison as the
            # standalone bipartite_match op so both paths agree on
            # boundary-IoU priors)
            best = jnp.argmax(d, 0).astype(jnp.int32)
            best_d = jnp.max(d, 0)
            col_match = jnp.where(
                (col_match < 0) & (best_d >= overlap_t), best, col_match)
        dist = jnp.where(
            col_match >= 0,
            jnp.take_along_axis(d, jnp.maximum(col_match, 0)[None], 0)[0],
            0.0)
        return col_match, dist

    match, match_dist = jax.vmap(match_one)(iou)               # [N, P]
    matched = match >= 0
    safe = jnp.maximum(match, 0)
    tlabel = jnp.where(matched, jnp.take_along_axis(
        gt_label.astype(jnp.int32), safe, 1), bg)

    conf_f = conf.astype(jnp.float32)
    lse = jax.nn.logsumexp(conf_f, axis=-1)
    pick = jnp.take_along_axis(conf_f, tlabel[..., None], -1)[..., 0]
    conf_ce = lse - pick                                       # [N, P]

    # hard-negative mining on the pre-assignment CE (max_negative)
    is_neg = ~matched & (match_dist < neg_overlap)
    num_pos = jnp.sum(matched, 1)
    want = jnp.minimum((num_pos * neg_ratio).astype(jnp.int32),
                       jnp.sum(is_neg, 1))[:, None]
    masked_loss = jnp.where(is_neg, jax.lax.stop_gradient(conf_ce), _NEG)
    order = jnp.argsort(-masked_loss, 1)
    rank = jnp.zeros((n, p), jnp.int32).at[
        jnp.arange(n)[:, None], order].set(
            jnp.arange(p, dtype=jnp.int32)[None])
    neg_sel = is_neg & (rank < want)

    # regression targets: encode matched gt against priors
    mg = jnp.take_along_axis(gt_box, safe[..., None], 1)       # [N, P, 4]
    aw = prior[:, 2] - prior[:, 0]
    ah = prior[:, 3] - prior[:, 1]
    ax = prior[:, 0] + aw * 0.5
    ay = prior[:, 1] + ah * 0.5
    gw = jnp.maximum(mg[..., 2] - mg[..., 0], 1e-6)
    gh = jnp.maximum(mg[..., 3] - mg[..., 1], 1e-6)
    gx = mg[..., 0] + gw * 0.5
    gy = mg[..., 1] + gh * 0.5
    tgt = jnp.stack([(gx - ax) / aw, (gy - ay) / ah,
                     jnp.log(gw / aw), jnp.log(gh / ah)], -1)
    if pvar is not None:
        tgt = tgt / pvar.astype(jnp.float32)[None]
    tgt = jax.lax.stop_gradient(jnp.where(matched[..., None], tgt, 0.0))

    diff = loc.astype(jnp.float32) - tgt
    ad = jnp.abs(diff)
    sl1 = jnp.sum(jnp.where(ad < 1.0, 0.5 * ad * ad, ad - 0.5), -1)
    loc_loss = jnp.where(matched, sl1, 0.0)
    conf_loss = jnp.where(matched | neg_sel, conf_ce, 0.0)
    loss = conf_w * conf_loss + loc_w * loc_loss               # [N, P]
    loss = jnp.sum(loss, 1, keepdims=True)
    if normalize:
        norm = jnp.maximum(jnp.sum(matched.astype(jnp.float32)), 1.0)
        loss = loss / norm
    return {"Loss": [loss.astype(loc.dtype)]}


@register_op("rpn_target_assign", no_grad=True, needs_rng=True)
def _rpn_target_assign(ins, attrs, rng=None):
    """Dense RPN anchor labelling (reference: rpn_target_assign_op.cc).

    Anchor [M, 4], GtBoxes [N, G, 4] (zero-area rows are padding),
    ImInfo [N, 3]. Outputs per-anchor dense targets instead of gathered
    LoD rows: ScoreLabel [N, M] f32 (1 pos / 0 neg / -1 ignored),
    ScoreWeight [N, M] (1 on sampled pos+neg), BboxTarget [N, M, 4]
    encoded regression targets, BboxWeight [N, M, 4] (1 on sampled pos).
    Losses contract with the weights, which is the static-shape analog of
    the reference's gathered index lists."""
    anchors = _x(ins, "Anchor")
    gt = _x(ins, "GtBoxes").astype(jnp.float32)
    im_info = _x(ins, "ImInfo")
    is_crowd = _x(ins, "IsCrowd")
    batch_per_im = int(attrs.get("rpn_batch_size_per_im", 256))
    straddle = float(attrs.get("rpn_straddle_thresh", 0.0))
    fg_frac = float(attrs.get("rpn_fg_fraction", 0.5))
    pos_ov = float(attrs.get("rpn_positive_overlap", 0.7))
    neg_ov = float(attrs.get("rpn_negative_overlap", 0.3))
    use_random = bool(attrs.get("use_random", True))
    m = anchors.shape[0]
    n, g = gt.shape[0], gt.shape[1]
    gt_valid = _xyxy_area(gt) > 0                              # [N, G]
    if is_crowd is not None:
        # crowd gt boxes are dropped before labelling (reference
        # rpn_target_assign_op.cc filters is_crowd rows out)
        gt_valid = gt_valid & (is_crowd == 0)

    if straddle >= 0 and im_info is not None:
        hgt, wid = im_info[:, 0:1], im_info[:, 1:2]            # [N, 1]
        inside = ((anchors[None, :, 0] >= -straddle)
                  & (anchors[None, :, 1] >= -straddle)
                  & (anchors[None, :, 2] < wid + straddle)
                  & (anchors[None, :, 3] < hgt + straddle))    # [N, M]
    else:
        inside = jnp.ones((n, m), bool)

    iou = _iou_xyxy(anchors[None], gt)                         # [N, M, G]
    iou = jnp.where(gt_valid[:, None, :] & inside[..., None], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=2)                          # [N, M]
    best_iou = jnp.max(iou, axis=2)
    # (i) anchors with max IoU per gt are positive even below threshold
    gt_best = jnp.max(iou, axis=1, keepdims=True)              # [N, 1, G]
    is_gt_best = jnp.any(
        (iou >= gt_best) & (gt_best > 0) & gt_valid[:, None, :], axis=2)
    pos = (best_iou >= pos_ov) | is_gt_best
    neg = (best_iou < neg_ov) & (best_iou >= 0) & ~pos

    def sample(mask, want, key):
        score = jax.random.uniform(key, mask.shape) if use_random else (
            -jnp.arange(m, dtype=jnp.float32) / m)[None]
        score = jnp.where(mask, score, -1.0)
        order = jnp.argsort(-score, axis=1)
        rank = jnp.zeros((n, m), jnp.int32).at[
            jnp.arange(n)[:, None], order].set(
                jnp.arange(m, dtype=jnp.int32)[None, :])
        return mask & (rank < want)

    k1, k2 = (jax.random.split(rng) if rng is not None
              else (jax.random.key(0), jax.random.key(1)))
    want_fg = jnp.minimum(int(batch_per_im * fg_frac),
                          jnp.sum(pos, 1))[:, None]
    fg_sel = sample(pos, want_fg, k1)
    want_bg = jnp.minimum(batch_per_im - jnp.sum(fg_sel, 1),
                          jnp.sum(neg, 1))[:, None]
    bg_sel = sample(neg, want_bg, k2)

    matched_gt = jnp.take_along_axis(gt, best_gt[..., None], 1)  # [N, M, 4]
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    ax = anchors[:, 0] + aw * 0.5
    ay = anchors[:, 1] + ah * 0.5
    gw = matched_gt[..., 2] - matched_gt[..., 0] + 1.0
    gh = matched_gt[..., 3] - matched_gt[..., 1] + 1.0
    gx = matched_gt[..., 0] + gw * 0.5
    gy = matched_gt[..., 1] + gh * 0.5
    tgt = jnp.stack([(gx - ax) / aw, (gy - ay) / ah,
                     jnp.log(gw / aw), jnp.log(gh / ah)], axis=-1)
    label = jnp.where(fg_sel, 1.0, jnp.where(bg_sel, 0.0, -1.0))
    return {
        "ScoreLabel": [label],
        "ScoreWeight": [(fg_sel | bg_sel).astype(jnp.float32)],
        "BboxTarget": [jnp.where(fg_sel[..., None], tgt, 0.0)],
        "BboxWeight": [jnp.broadcast_to(
            fg_sel[..., None], tgt.shape).astype(jnp.float32)],
    }


def _nms_mask(boxes, scores, thresh, top_k):
    """Greedy NMS keep-mask over [K, 4] boxes (scores descending order
    assumed). Returns keep mask [K]."""
    k = boxes.shape[0]
    iou = _iou_xyxy(boxes, boxes)

    def body(i, keep):
        sup = jnp.any((iou[i] > thresh) & keep & (jnp.arange(k) < i))
        return keep.at[i].set(keep[i] & ~sup)

    keep0 = scores > _NEG / 2
    keep = jax.lax.fori_loop(0, k, body, keep0)
    if top_k > 0:
        keep = keep & (jnp.cumsum(keep) <= top_k)
    return keep


@register_op("generate_proposals", no_grad=True)
def _generate_proposals(ins, attrs):
    """RPN proposal generation (reference: generate_proposals_op.cc).
    Scores [N, A, H, W], BboxDeltas [N, 4A, H, W], ImInfo [N, 3],
    Anchors [H, W, A, 4], Variances like Anchors. Dense outputs:
    RpnRois [N, post_nms_topN, 4] (rows beyond RpnRoisNum are zero),
    RpnRoiProbs [N, post_nms_topN, 1], RpnRoisNum [N]."""
    scores = _x(ins, "Scores")
    deltas = _x(ins, "BboxDeltas")
    im_info = _x(ins, "ImInfo")
    anchors = _x(ins, "Anchors").reshape(-1, 4)
    variances = _x(ins, "Variances")
    if variances is not None:
        variances = variances.reshape(-1, 4)
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_thresh = float(attrs.get("nms_thresh", 0.5))
    min_size = float(attrs.get("min_size", 0.1))
    n, a, h, w = scores.shape
    total = a * h * w
    # [N, A, H, W] -> [N, H*W*A] matching anchors' [H, W, A] order
    sc = scores.transpose(0, 2, 3, 1).reshape(n, total).astype(jnp.float32)
    dl = (deltas.reshape(n, a, 4, h, w).transpose(0, 3, 4, 1, 2)
          .reshape(n, total, 4).astype(jnp.float32))
    pre_n = min(pre_n, total)
    top_sc, top_idx = jax.lax.top_k(sc, pre_n)
    top_dl = jnp.take_along_axis(dl, top_idx[..., None], 1)
    top_an = anchors[top_idx]
    top_var = variances[top_idx] if variances is not None else None
    props = _decode_anchor(top_an, top_dl, top_var)
    hgt, wid = im_info[:, 0:1, None], im_info[:, 1:2, None]
    props = jnp.concatenate([
        jnp.clip(props[..., 0:1], 0.0, wid - 1.0),
        jnp.clip(props[..., 1:2], 0.0, hgt - 1.0),
        jnp.clip(props[..., 2:3], 0.0, wid - 1.0),
        jnp.clip(props[..., 3:4], 0.0, hgt - 1.0)], axis=-1)
    ws = props[..., 2] - props[..., 0] + 1.0
    hs = props[..., 3] - props[..., 1] + 1.0
    min_sz = jnp.maximum(min_size, 1.0) * im_info[:, 2:3]
    alive = (ws >= min_sz) & (hs >= min_sz)
    top_sc = jnp.where(alive, top_sc, _NEG)

    def per_image(boxes, sc):
        order = jnp.argsort(-sc)
        boxes, sc = boxes[order], sc[order]
        keep = _nms_mask(boxes, sc, nms_thresh, post_n)
        sc = jnp.where(keep, sc, _NEG)
        order2 = jnp.argsort(-sc)[:post_n]
        out_b = jnp.where((sc[order2] > _NEG / 2)[:, None],
                          boxes[order2], 0.0)
        out_s = jnp.where(sc[order2] > _NEG / 2, sc[order2], 0.0)
        return out_b, out_s, jnp.sum(sc > _NEG / 2).astype(jnp.int32)

    rois, probs, num = jax.vmap(per_image)(props, top_sc)
    return {"RpnRois": [rois], "RpnRoiProbs": [probs[..., None]],
            "RpnRoisNum": [num]}


@register_op("generate_proposal_labels", no_grad=True, needs_rng=True)
def _generate_proposal_labels(ins, attrs, rng=None):
    """Sample RoIs for the second stage (reference:
    generate_proposal_labels_op.cc). RpnRois [N, R, 4], GtClasses [N, G],
    GtBoxes [N, G, 4] (zero-area padding), ImInfo [N, 3]. Outputs a fixed
    ``batch_size_per_im`` sample per image: Rois [N, B, 4],
    LabelsInt32 [N, B] (-1 on unused slots), BboxTargets
    [N, B, 4*class_nums], plus inside/outside weights of the same shape
    (1 on the foreground slots' class columns)."""
    rois = _x(ins, "RpnRois").astype(jnp.float32)
    gt_classes = _x(ins, "GtClasses")
    gt_boxes = _x(ins, "GtBoxes").astype(jnp.float32)
    is_crowd = _x(ins, "IsCrowd")
    batch = int(attrs.get("batch_size_per_im", 512))
    fg_frac = float(attrs.get("fg_fraction", 0.25))
    fg_thresh = float(attrs.get("fg_thresh", 0.5))
    bg_hi = float(attrs.get("bg_thresh_hi", 0.5))
    bg_lo = float(attrs.get("bg_thresh_lo", 0.0))
    class_nums = int(attrs.get("class_nums", 81))
    use_random = bool(attrs.get("use_random", True))
    n, r = rois.shape[:2]
    g = gt_boxes.shape[1]
    gt_valid = _xyxy_area(gt_boxes) > 0
    if is_crowd is not None:
        # crowd regions are excluded from sampling entirely
        # (reference generate_proposal_labels filters them out)
        gt_valid = gt_valid & (is_crowd == 0)
    # gt boxes join the candidate pool (reference appends them); rois
    # with zero area are generate_proposals padding, not candidates
    cand = jnp.concatenate([rois, gt_boxes], axis=1)           # [N, R+G, 4]
    cand_valid = jnp.concatenate(
        [_xyxy_area(rois) > 0, gt_valid], axis=1)
    iou = _iou_xyxy(cand, gt_boxes)
    # invalid gt rows contribute 0 overlap (a valid roi with no gt is
    # background, matching the reference); invalid CANDIDATES get -1 so
    # they can never satisfy fg or bg thresholds
    iou = jnp.where(gt_valid[:, None, :], iou, 0.0)
    iou = jnp.where(cand_valid[..., None], iou, -1.0)
    best_gt = jnp.argmax(iou, 2)
    best_iou = jnp.max(iou, 2)
    fg = best_iou >= fg_thresh
    bg = (best_iou < bg_hi) & (best_iou >= bg_lo)
    k1, k2 = (jax.random.split(rng) if rng is not None
              else (jax.random.key(0), jax.random.key(1)))
    total = cand.shape[1]

    def sample(mask, want, key):
        sc = (jax.random.uniform(key, mask.shape) if use_random
              else -jnp.arange(total, dtype=jnp.float32)[None] / total)
        sc = jnp.where(mask, sc, -1.0)
        order = jnp.argsort(-sc, 1)
        rank = jnp.zeros_like(order).at[
            jnp.arange(n)[:, None], order].set(
                jnp.arange(total, dtype=order.dtype)[None])
        return mask & (rank < want)

    want_fg = jnp.minimum(int(batch * fg_frac), jnp.sum(fg, 1))[:, None]
    fg_sel = sample(fg, want_fg, k1)
    want_bg = jnp.minimum(batch - jnp.sum(fg_sel, 1), jnp.sum(bg, 1))[:, None]
    bg_sel = sample(bg, want_bg, k2)

    # compact: fg rows first, then bg, padded to `batch`
    key_order = jnp.where(fg_sel, 0, jnp.where(bg_sel, 1, 2))
    order = jnp.argsort(key_order, axis=1, stable=True)[:, :batch]
    take = lambda v: jnp.take_along_axis(v, order, 1)
    sel_rois = jnp.take_along_axis(cand, order[..., None], 1)
    sel_gt = jnp.take_along_axis(best_gt, order, 1)
    sel_fg = take(fg_sel)
    sel_used = take(fg_sel | bg_sel)
    labels = jnp.take_along_axis(gt_classes, sel_gt, 1)
    labels = jnp.where(sel_fg, labels,
                       jnp.where(sel_used, 0, -1)).astype(jnp.int32)
    matched = jnp.take_along_axis(gt_boxes, sel_gt[..., None], 1)
    rw = sel_rois[..., 2] - sel_rois[..., 0] + 1.0
    rh = sel_rois[..., 3] - sel_rois[..., 1] + 1.0
    rx = sel_rois[..., 0] + rw * 0.5
    ry = sel_rois[..., 1] + rh * 0.5
    gw = matched[..., 2] - matched[..., 0] + 1.0
    gh = matched[..., 3] - matched[..., 1] + 1.0
    gx = matched[..., 0] + gw * 0.5
    gy = matched[..., 1] + gh * 0.5
    tgt = jnp.stack([(gx - rx) / rw, (gy - ry) / rh,
                     jnp.log(gw / rw), jnp.log(gh / rh)], -1)  # [N, B, 4]
    onehot = jax.nn.one_hot(jnp.maximum(labels, 0), class_nums)
    col = (onehot[..., None] *
           jnp.where(sel_fg[..., None], tgt, 0.0)[:, :, None, :])
    bbox_targets = col.reshape(n, batch, 4 * class_nums)
    w_in = jnp.broadcast_to(
        (onehot * sel_fg[..., None])[..., None],
        (n, batch, class_nums, 4)).reshape(n, batch, 4 * class_nums)
    return {
        "Rois": [jnp.where(sel_used[..., None], sel_rois, 0.0)],
        "LabelsInt32": [labels],
        "BboxTargets": [bbox_targets],
        "BboxInsideWeights": [w_in],
        "BboxOutsideWeights": [w_in],
    }


@register_op("distribute_fpn_proposals", no_grad=True)
def _distribute_fpn_proposals(ins, attrs):
    """Route RoIs to FPN levels by scale (reference:
    distribute_fpn_proposals_op.cc): level = clip(floor(refer_level +
    log2(sqrt(area) / refer_scale)), min_level, max_level). FpnRois
    [N, R, 4] (zero rows = padding). Outputs one [N, R, 4] tensor per
    level with non-level rows zeroed and compacted to the front,
    per-level counts, and RestoreInd [N, R] mapping
    concat-of-level-compactions back to input order."""
    rois = _x(ins, "FpnRois").astype(jnp.float32)
    min_level = int(attrs.get("min_level", 2))
    max_level = int(attrs.get("max_level", 5))
    refer_level = int(attrs.get("refer_level", 4))
    refer_scale = int(attrs.get("refer_scale", 224))
    n, r = rois.shape[:2]
    area = _xyxy_area(rois)
    valid = area > 0
    lvl = jnp.floor(refer_level + jnp.log2(
        jnp.sqrt(jnp.maximum(area, 1e-6)) / refer_scale + 1e-12))
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    lvl = jnp.where(valid, lvl, max_level + 1)
    outs, nums = [], []
    pos_in_concat = jnp.zeros((n, r), jnp.int32)
    for li, level in enumerate(range(min_level, max_level + 1)):
        mask = lvl == level
        order = jnp.argsort(~mask, axis=1, stable=True)        # level first
        sel = jnp.take_along_axis(rois, order[..., None], 1)
        cnt = jnp.sum(mask, 1).astype(jnp.int32)
        keep = jnp.arange(r)[None] < cnt[:, None]
        outs.append(jnp.where(keep[..., None], sel, 0.0))
        nums.append(cnt)
        rank = (jnp.cumsum(mask, axis=1) - 1).astype(jnp.int32)
        # position in the PADDED concat of the per-level outputs (each
        # level occupies a fixed r-row band, unlike the reference's LoD
        # concat): level_band_start + rank-within-level
        pos_in_concat = jnp.where(mask, li * r + rank, pos_in_concat)
    restore = jnp.where(valid, pos_in_concat, -1)
    return {"MultiFpnRois": outs,
            "MultiLevelRoIsNum": nums,
            "RestoreInd": [restore]}


@register_op("collect_fpn_proposals", no_grad=True)
def _collect_fpn_proposals(ins, attrs):
    """Merge per-level RoIs by score (reference:
    collect_fpn_proposals_op.cc): concat levels, keep global top
    ``post_nms_topN``. MultiLevelRois: list of [N, R_l, 4];
    MultiLevelScores: list of [N, R_l] (or [N, R_l, 1]); zero-area rows
    are padding. Output FpnRois [N, K, 4] + RoisNum [N]."""
    rois_l = list(ins.get("MultiLevelRois", []))
    scores_l = list(ins.get("MultiLevelScores", []))
    post = int(attrs.get("post_nms_topN", 1000))
    rois = jnp.concatenate([x.astype(jnp.float32) for x in rois_l], axis=1)
    scores = jnp.concatenate(
        [s.reshape(s.shape[0], -1).astype(jnp.float32) for s in scores_l],
        axis=1)
    valid = _xyxy_area(rois) > 0
    scores = jnp.where(valid, scores, _NEG)
    k = min(post, rois.shape[1])
    top_sc, top_idx = jax.lax.top_k(scores, k)
    out = jnp.take_along_axis(rois, top_idx[..., None], 1)
    alive = top_sc > _NEG / 2
    return {"FpnRois": [jnp.where(alive[..., None], out, 0.0)],
            "RoisNum": [jnp.sum(alive, 1).astype(jnp.int32)]}


@register_op("box_decoder_and_assign", no_grad=True)
def _box_decoder_and_assign(ins, attrs):
    """Decode per-class bbox deltas and pick the best class's box
    (reference: box_decoder_and_assign_op.cc). PriorBox [P, 4],
    PriorBoxVar [4] or [P, 4], TargetBox [P, 4*C], BoxScore [P, C]."""
    prior = _x(ins, "PriorBox").astype(jnp.float32)
    pvar = _x(ins, "PriorBoxVar")
    target = _x(ins, "TargetBox").astype(jnp.float32)
    score = _x(ins, "BoxScore").astype(jnp.float32)
    box_clip = float(attrs.get("box_clip", jnp.log(1000.0 / 16.0)))
    p = prior.shape[0]
    c = score.shape[1]
    deltas = target.reshape(p, c, 4)
    if pvar is not None:
        pvar = pvar.astype(jnp.float32)
        var = pvar if pvar.ndim == 2 else jnp.broadcast_to(pvar[None], (p, 4))
        deltas = deltas * var[:, None, :]
    aw = prior[:, 2] - prior[:, 0] + 1.0
    ah = prior[:, 3] - prior[:, 1] + 1.0
    ax = prior[:, 0] + aw * 0.5
    ay = prior[:, 1] + ah * 0.5
    cx = deltas[..., 0] * aw[:, None] + ax[:, None]
    cy = deltas[..., 1] * ah[:, None] + ay[:, None]
    w = jnp.exp(jnp.minimum(deltas[..., 2], box_clip)) * aw[:, None]
    h = jnp.exp(jnp.minimum(deltas[..., 3], box_clip)) * ah[:, None]
    decoded = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                         cx + w * 0.5 - 1.0, cy + h * 0.5 - 1.0], -1)
    best = jnp.argmax(score, axis=1)
    assigned = jnp.take_along_axis(
        decoded, best[:, None, None].repeat(4, -1), 1)[:, 0]
    return {"DecodeBox": [decoded.reshape(p, c * 4)],
            "OutputAssignBox": [assigned]}


@register_op("detection_map", no_grad=True)
def _detection_map(ins, attrs):
    """Batch mAP (reference: detection_map_op.cc, integral mode plus
    11-point). DetectRes [N, D, 6] rows (label, score, x1, y1, x2, y2)
    with label < 0 padding; Label [N, G, 5] rows
    (label, x1, y1, x2, y2) or [N, G, 6] rows
    (label, difficult, x1, y1, x2, y2), label < 0 padding. With
    evaluate_difficult=False, difficult gts neither count toward npos
    nor consume matches (VOC convention). Computes AP per class over the
    whole batch and averages — the stateless analog of the reference's
    accumulating metric op.

    Cross-batch accumulation (the reference's HasState/PosCount/TruePos/
    FalsePos plumbing, detection_map_op.cc GetInputPos): the reference
    grows LoD state tensors with every batch — dynamic shapes, hostile
    to XLA. Redesigned with FIXED-SIZE states: per-class TP/FP counts
    binned over ``score_bins`` (default 1024) confidence bins in [0,1]
    plus a per-class positive count. The accumulated mAP walks the
    binned PR curve from the top bin down — the binned analog of the
    exact score sort, within ~1/score_bins of exact. Engaged when the
    ``TruePos``/``FalsePos``/``PosCount``/``HasState`` inputs are wired
    (metrics.DetectionMAP does this); batch-level matching stays
    exact either way."""
    det = _x(ins, "DetectRes").astype(jnp.float32)
    gt = _x(ins, "Label").astype(jnp.float32)
    class_num = int(attrs["class_num"])
    overlap = float(attrs.get("overlap_threshold", 0.5))
    ap_type = attrs.get("ap_type", "integral")
    evaluate_difficult = bool(attrs.get("evaluate_difficult", True))
    has_state = _x(ins, "HasState")
    with_states = has_state is not None
    n_bins = int(attrs.get("score_bins", 1024))
    tp_hists, fp_hists, nposs = [], [], []
    n, d = det.shape[:2]
    g = gt.shape[1]
    gt_boxes = gt[..., -4:]
    gt_label = gt[..., 0]
    gt_valid = gt_label >= 0
    if gt.shape[-1] >= 6 and not evaluate_difficult:
        gt_valid = gt_valid & (gt[..., 1] == 0)
    det_label, det_score, det_boxes = det[..., 0], det[..., 1], det[..., 2:]
    det_valid = det_label >= 0
    iou = _iou_xyxy(det_boxes, gt_boxes)                       # [N, D, G]

    aps = []
    for cls in range(class_num):
        gmask = gt_valid & (gt_label == cls)                   # [N, G]
        dmask = det_valid & (det_label == cls)                 # [N, D]
        npos = jnp.sum(gmask)
        # greedy match per image in score order
        def per_image(sc, dm, ious, gm):
            order = jnp.argsort(-jnp.where(dm, sc, _NEG))

            def body(k, carry):
                used, tp = carry
                di = order[k]
                ious_k = jnp.where(gm & ~used, ious[di], -1.0)
                best = jnp.argmax(ious_k)
                hit = (ious_k[best] >= overlap) & dm[di]
                used = used.at[best].set(used[best] | hit)
                tp = tp.at[di].set(hit)
                return used, tp

            used0 = jnp.zeros((g,), bool)
            tp0 = jnp.zeros((d,), bool)
            _, tp = jax.lax.fori_loop(0, d, body, (used0, tp0))
            return tp

        tp = jax.vmap(per_image)(det_score, dmask, iou, gmask)  # [N, D]
        sc_flat = jnp.where(dmask, det_score, _NEG).reshape(-1)
        tp_flat = tp.reshape(-1)
        order = jnp.argsort(-sc_flat)
        tp_sorted = tp_flat[order].astype(jnp.float32)
        alive = (sc_flat[order] > _NEG / 2).astype(jnp.float32)
        ctp = jnp.cumsum(tp_sorted * alive)
        cfp = jnp.cumsum((1.0 - tp_sorted) * alive)
        prec = ctp / jnp.maximum(ctp + cfp, 1.0)
        rec = ctp / jnp.maximum(npos, 1)
        if ap_type == "11point":
            pts = [jnp.max(jnp.where(rec >= t, prec, 0.0))
                   for t in [i / 10.0 for i in range(11)]]
            ap = sum(pts) / 11.0
        else:
            drec = jnp.diff(jnp.concatenate([jnp.zeros((1,)), rec]))
            ap = jnp.sum(prec * drec * alive)
        aps.append(jnp.where(npos > 0, ap, -1.0))

        if with_states:
            # score-binned TP/FP counts for the fixed-size accumulator
            # states (see docstring)
            w_alive = jnp.where(dmask, 1.0, 0.0).reshape(-1)
            bins = jnp.clip((sc_flat * n_bins).astype(jnp.int32),
                            0, n_bins - 1)
            w_tp = tp_flat.astype(jnp.float32) * w_alive
            tp_hists.append(jnp.zeros((n_bins,)).at[bins].add(w_tp))
            fp_hists.append(jnp.zeros((n_bins,)).at[bins].add(
                (1.0 - tp_flat.astype(jnp.float32)) * w_alive))
        nposs.append(npos.astype(jnp.float32))
    aps = jnp.stack(aps)
    have = aps >= 0
    m_ap = jnp.sum(jnp.where(have, aps, 0.0)) / jnp.maximum(
        jnp.sum(have), 1)
    out = {"MAP": [m_ap.astype(jnp.float32)]}

    if with_states:
        tp_hist = jnp.stack(tp_hists)                  # [C, B]
        fp_hist = jnp.stack(fp_hists)
        npos_v = jnp.stack(nposs)                      # [C]
        has = has_state.reshape(()).astype(jnp.float32)
        tp_acc = tp_hist + has * _x(ins, "TruePos").astype(jnp.float32)
        fp_acc = fp_hist + has * _x(ins, "FalsePos").astype(jnp.float32)
        npos_acc = npos_v + has * _x(ins, "PosCount").astype(jnp.float32)
        # accumulated mAP from the binned PR curve: walk bins from the
        # highest score down (the binned analog of the exact score sort)
        ctp = jnp.cumsum(tp_acc[:, ::-1], axis=1)      # [C, B]
        cfp = jnp.cumsum(fp_acc[:, ::-1], axis=1)
        prec = ctp / jnp.maximum(ctp + cfp, 1.0)
        rec = ctp / jnp.maximum(npos_acc[:, None], 1.0)
        if ap_type == "11point":
            pts = [jnp.max(jnp.where(rec >= t, prec, 0.0), axis=1)
                   for t in [i / 10.0 for i in range(11)]]
            acc_aps = sum(pts) / 11.0
        else:
            drec = jnp.diff(
                jnp.concatenate([jnp.zeros((class_num, 1)), rec], 1), axis=1)
            acc_aps = jnp.sum(prec * drec, axis=1)
        have_a = npos_acc > 0
        acc_map = jnp.sum(jnp.where(have_a, acc_aps, 0.0)) / jnp.maximum(
            jnp.sum(have_a), 1)
        out["AccumMAP"] = [acc_map.astype(jnp.float32)]
        out["AccumTruePos"] = [tp_acc]
        out["AccumFalsePos"] = [fp_acc]
        out["AccumPosCount"] = [npos_acc]
    return out


def _point_in_polygon(px, py, verts, n_valid):
    """Crossing-number fill over a padded vertex list. px/py [M, M]
    pixel-center sample points; verts [V, 2]; n_valid <= V real
    vertices (edges wrap at n_valid). Padding edges contribute nothing."""
    v = verts.shape[0]
    idx = jnp.arange(v)
    nxt = jnp.where(idx + 1 >= n_valid, 0, idx + 1)
    x1, y1 = verts[:, 0], verts[:, 1]
    x2 = verts[nxt, 0]
    y2 = verts[nxt, 1]
    edge_ok = idx < n_valid
    px = px[..., None]
    py = py[..., None]
    crosses = ((y1 > py) != (y2 > py)) & (
        px < (x2 - x1) * (py - y1) / jnp.where(
            y2 - y1 == 0, 1e-12, y2 - y1) + x1
    ) & edge_ok
    return jnp.sum(crosses, axis=-1) % 2 == 1


@register_op("generate_mask_labels", no_grad=True)
def _generate_mask_labels(ins, attrs):
    """Mask R-CNN mask targets (reference: generate_mask_labels_op.cc).

    Dense-padded redesign of the 3-level-LoD polygon input: GtSegms
    [N, G, Q, V, 2] holds up to Q polygon parts of up to V vertices per
    gt, with PolyLens [N, G, Q] real vertex counts (0 = unused part).
    GtClasses/IsCrowd [N, G] (class 0 = padding), Rois [N, R, 4],
    LabelsInt32 [N, R] per-roi class (0 = background), ImInfo [N, 3].

    Outputs (fixed capacity R, fg rois compacted to the front):
    MaskRois [N, R, 4], RoiHasMaskInt32 [N, R] (source roi index, -1
    pad), MaskInt32 [N, R, resolution^2 * num_classes] (-1 ignore
    outside the roi's class block, as the reference's ExpandMaskTarget),
    MaskNum [N]. Rasterization samples pixel centers with a
    crossing-number fill; the reference delegates to pycocotools' RLE
    rasterizer, so boundary pixels can differ by up to one pixel (the
    training target semantics match)."""
    im_info = _x(ins, "ImInfo").astype(jnp.float32)
    gt_classes = _x(ins, "GtClasses").astype(jnp.int32)
    is_crowd = _x(ins, "IsCrowd")
    gt_segms = _x(ins, "GtSegms").astype(jnp.float32)
    poly_lens = _x(ins, "PolyLens")
    if poly_lens is not None:
        poly_lens = poly_lens.astype(jnp.int32)
    rois = _x(ins, "Rois").astype(jnp.float32)
    labels = _x(ins, "LabelsInt32").astype(jnp.int32)
    num_classes = int(attrs["num_classes"])
    m = int(attrs["resolution"])
    if is_crowd is None:
        is_crowd = jnp.zeros_like(gt_classes)

    n, g, q, v, _2 = gt_segms.shape
    if poly_lens is None:
        # no vertex counts declared: every part slot is a full-V polygon
        poly_lens = jnp.full((n, g, q), v, jnp.int32)
    r = rois.shape[1]

    def one(im, cls, crowd, segs, plens, roi, lab):
        valid_gt = (cls > 0) & (crowd.astype(jnp.int32) == 0) & (
            jnp.sum(plens, axis=-1) > 0)
        vert_ok = (jnp.arange(v)[None, None, :] < plens[..., None])
        xs = jnp.where(vert_ok, segs[..., 0], jnp.inf)
        ys = jnp.where(vert_ok, segs[..., 1], jnp.inf)
        x0 = jnp.min(xs, axis=(1, 2))
        y0 = jnp.min(ys, axis=(1, 2))
        xs = jnp.where(vert_ok, segs[..., 0], -jnp.inf)
        ys = jnp.where(vert_ok, segs[..., 1], -jnp.inf)
        x1 = jnp.max(xs, axis=(1, 2))
        y1 = jnp.max(ys, axis=(1, 2))
        poly_boxes = jnp.stack([x0, y0, x1, y1], axis=-1)       # [G, 4]
        poly_boxes = jnp.where(valid_gt[:, None], poly_boxes, 0.0)

        scale = im[2]
        roi_s = roi / scale
        iou = _iou_xyxy(roi_s[None], poly_boxes[None])[0]       # [R, G]
        iou = jnp.where(valid_gt[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)     # [R]

        fg = lab > 0
        fg_num = jnp.sum(fg.astype(jnp.int32))

        # rasterize each roi's matched gt polygons wrt the (unscaled) roi
        bx0, by0 = roi_s[:, 0], roi_s[:, 1]
        bw = jnp.maximum(roi_s[:, 2] - bx0, 1.0)
        bh = jnp.maximum(roi_s[:, 3] - by0, 1.0)
        # reference Poly2Mask samples the integer grid of the scaled
        # polygon; pixel centers (j + 0.5) are the dense equivalent
        grid = (jnp.arange(m, dtype=jnp.float32) + 0.5)
        py_, px_ = jnp.meshgrid(grid, grid, indexing="ij")      # [M, M]

        segs_r = segs[best_gt]                                  # [R, Q, V, 2]
        plens_r = plens[best_gt]                                # [R, Q]
        sx = (segs_r[..., 0] - bx0[:, None, None]) * m / bw[:, None, None]
        sy = (segs_r[..., 1] - by0[:, None, None]) * m / bh[:, None, None]
        verts = jnp.stack([sx, sy], axis=-1)                    # [R, Q, V, 2]

        def raster_roi(vr, pl):
            def raster_part(vp, np_):
                return _point_in_polygon(px_, py_, vp, np_) & (np_ > 2)

            parts = jax.vmap(raster_part)(vr, pl)               # [Q, M, M]
            return jnp.any(parts, axis=0)

        masks = jax.vmap(raster_roi)(verts, plens_r)            # [R, M, M]
        masks = masks.reshape(r, m * m).astype(jnp.int32)

        # expand into the per-class block (-1 = ignore)
        mdim = m * m * num_classes
        expanded = jnp.full((r, mdim), -1, jnp.int32)
        col = lab[:, None] * (m * m) + jnp.arange(m * m)[None, :]
        rowi = jnp.broadcast_to(jnp.arange(r)[:, None], (r, m * m))
        expanded = expanded.at[rowi, col].set(
            jnp.where(fg[:, None], masks, -1))

        # compact fg rois to the front (stable)
        order = jnp.argsort(jnp.where(fg, 0, 1), stable=True)
        has_fg = fg_num > 0
        # fg_num == 0: the first bg roi with an all -1 mask, class 0
        take = jnp.where(has_fg, order, jnp.arange(r))
        keep = jnp.where(
            has_fg,
            (jnp.arange(r) < fg_num),
            jnp.arange(r) < 1,
        )
        mask_rois = jnp.where(keep[:, None], roi[take], -1.0)
        roi_has_mask = jnp.where(keep, take.astype(jnp.int32), -1)
        out_masks = jnp.where(
            keep[:, None] & has_fg, expanded[take], -1)
        count = jnp.where(has_fg, fg_num, 1)
        return mask_rois, roi_has_mask, out_masks, count

    mask_rois, roi_has_mask, mask_int32, counts = jax.vmap(one)(
        im_info, gt_classes, is_crowd, gt_segms, poly_lens, rois, labels)
    return {"MaskRois": [mask_rois], "RoiHasMaskInt32": [roi_has_mask],
            "MaskInt32": [mask_int32], "MaskNum": [counts]}
