"""Dense math ops: elementwise, matmul, reductions, casts.

Reference kernels: paddle/fluid/operators/elementwise/*, mul_op.cc,
matmul_op.cc, reduce_ops/*, sum_op.cc, cast_op.cc, scale_op.cc, clip_op.cc.
Broadcasting follows the reference's ``axis`` convention for elementwise ops
(Y aligned to X starting at ``axis``; -1 = numpy trailing alignment).
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


def _x(ins, slot="X", i=0):
    return ins[slot][i]


def _bcast_y(x, y, axis: int):
    """Reshape y per the reference's elementwise axis rule."""
    if axis is None or axis == -1 or jnp.ndim(y) == jnp.ndim(x):
        return y
    ydim = jnp.ndim(y)
    xdim = jnp.ndim(x)
    axis = int(axis)
    new_shape = (1,) * axis + jnp.shape(y) + (1,) * (xdim - axis - ydim)
    return jnp.reshape(y, new_shape)


def _make_elementwise(name, fn):
    @register_op(name, doc=f"elementwise {name}")
    def _compute(ins, attrs, name=name, fn=fn):
        x, y = _x(ins), _x(ins, "Y")
        y = _bcast_y(x, y, attrs.get("axis", -1))
        return {"Out": [fn(x, y)]}

    return _compute


_make_elementwise("elementwise_add", jnp.add)
_make_elementwise("elementwise_sub", jnp.subtract)
_make_elementwise("elementwise_mul", jnp.multiply)
_make_elementwise("elementwise_div", jnp.divide)
_make_elementwise("elementwise_pow", jnp.power)
_make_elementwise("elementwise_max", jnp.maximum)
_make_elementwise("elementwise_min", jnp.minimum)
_make_elementwise("elementwise_floordiv", lambda x, y: jnp.floor_divide(x, y))
_make_elementwise("elementwise_mod", jnp.mod)


def _make_compare(name, fn):
    @register_op(name, no_grad=True)
    def _compute(ins, attrs, fn=fn):
        x, y = _x(ins), _x(ins, "Y")
        y = _bcast_y(x, y, attrs.get("axis", -1))
        return {"Out": [fn(x, y)]}


_make_compare("equal", jnp.equal)
_make_compare("not_equal", jnp.not_equal)
_make_compare("less_than", jnp.less)
_make_compare("less_equal", jnp.less_equal)
_make_compare("greater_than", jnp.greater)
_make_compare("greater_equal", jnp.greater_equal)


def _make_logical(name, fn, unary=False):
    @register_op(name, no_grad=True)
    def _compute(ins, attrs, fn=fn, unary=unary):
        if unary:
            return {"Out": [fn(_x(ins))]}
        return {"Out": [fn(_x(ins), _x(ins, "Y"))]}


_make_logical("logical_and", jnp.logical_and)
_make_logical("logical_or", jnp.logical_or)
_make_logical("logical_xor", jnp.logical_xor)
_make_logical("logical_not", jnp.logical_not, unary=True)


@register_op("mul", doc="2D projection matmul with flatten dims (mul_op.cc)")
def _mul(ins, attrs):
    x, y = _x(ins), _x(ins, "Y")
    xnc = attrs.get("x_num_col_dims", 1)
    ync = attrs.get("y_num_col_dims", 1)
    import math

    xs, ys = jnp.shape(x), jnp.shape(y)
    x2 = jnp.reshape(x, (math.prod(xs[:xnc]), -1))
    y2 = jnp.reshape(y, (math.prod(ys[:ync]), -1))
    out2 = x2 @ y2
    out_shape = xs[:xnc] + ys[ync:]
    return {"Out": [jnp.reshape(out2, out_shape)]}


@register_op("matmul", doc="batched matmul w/ transpose flags (matmul_op.cc)")
def _matmul(ins, attrs):
    x, y = _x(ins), _x(ins, "Y")
    tx = attrs.get("transpose_X", False)
    ty = attrs.get("transpose_Y", False)
    alpha = attrs.get("alpha", 1.0)
    if jnp.ndim(x) == 1:
        x = x[None, :]
    if jnp.ndim(y) == 1:
        y = y[:, None]
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": [out]}


@register_op("sum", doc="add N tensors (sum_op.cc)")
def _sum(ins, attrs):
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


@register_op("mean", doc="mean over all elements (mean_op.cc)")
def _mean(ins, attrs):
    return {"Out": [jnp.mean(_x(ins))]}


def _reduce_attrs(x, attrs):
    if attrs.get("reduce_all", False):
        dims = None
    else:
        dims = attrs.get("dim", [0])
        if isinstance(dims, int):
            dims = [dims]
        dims = tuple(d % jnp.ndim(x) for d in dims)
    return dims, attrs.get("keep_dim", False)


def _make_reduce(name, fn):
    @register_op(name)
    def _compute(ins, attrs, fn=fn):
        x = _x(ins)
        dims, keep = _reduce_attrs(x, attrs)
        return {"Out": [fn(x, axis=dims, keepdims=keep)]}


_make_reduce("reduce_sum", jnp.sum)
_make_reduce("reduce_mean", jnp.mean)
_make_reduce("reduce_max", jnp.max)
_make_reduce("reduce_min", jnp.min)
_make_reduce("reduce_prod", jnp.prod)


@register_op("cast")
def _cast(ins, attrs):
    return {"Out": [_x(ins).astype(attrs["out_dtype"])]}


@register_op("scale")
def _scale(ins, attrs):
    x = _x(ins)
    s = attrs.get("scale", 1.0)
    b = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        return {"Out": [x * s + b]}
    return {"Out": [(x + b) * s]}


@register_op("clip")
def _clip(ins, attrs):
    return {"Out": [jnp.clip(_x(ins), attrs.get("min"), attrs.get("max"))]}


@register_op("clip_by_norm")
def _clip_by_norm(ins, attrs):
    x = _x(ins)
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": [x * scale]}


@register_op("squared_l2_norm")
def _squared_l2_norm(ins, attrs):
    return {"Out": [jnp.sum(jnp.square(_x(ins)))[None]]}


@register_op("increment")
def _increment(ins, attrs):
    x = _x(ins)
    step = jnp.asarray(attrs.get("step", 1.0)).astype(x.dtype)
    return {"Out": [x + step]}


@register_op("isfinite", no_grad=True, doc="all-finite check (FLAGS_check_nan_inf analog)")
def _isfinite(ins, attrs):
    flags = [jnp.all(jnp.isfinite(x)) for x in ins["X"]]
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_and(out, f)
    return {"Out": [out]}


@register_op("p_norm")
def _p_norm(ins, attrs):
    x = _x(ins)
    porder = attrs.get("porder", 2.0)
    axis = attrs.get("axis", None)
    keepdim = attrs.get("keepdim", False)
    out = jnp.sum(jnp.abs(x) ** porder, axis=axis, keepdims=keepdim) ** (1.0 / porder)
    return {"Out": [out]}
