"""Dense math ops: elementwise, matmul, reductions, casts.

Reference kernels: paddle/fluid/operators/elementwise/*, mul_op.cc,
matmul_op.cc, reduce_ops/*, sum_op.cc, cast_op.cc, scale_op.cc, clip_op.cc.
Broadcasting follows the reference's ``axis`` convention for elementwise ops
(Y aligned to X starting at ``axis``; -1 = numpy trailing alignment).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


def _x(ins, slot="X", i=0):
    return ins[slot][i]


def _bcast_y(x, y, axis: int):
    """Reshape y per the reference's elementwise axis rule."""
    if axis is None or axis == -1 or jnp.ndim(y) == jnp.ndim(x):
        return y
    ydim = jnp.ndim(y)
    xdim = jnp.ndim(x)
    axis = int(axis)
    new_shape = (1,) * axis + jnp.shape(y) + (1,) * (xdim - axis - ydim)
    return jnp.reshape(y, new_shape)


def _make_elementwise(name, fn):
    @register_op(name, doc=f"elementwise {name}")
    def _compute(ins, attrs, name=name, fn=fn):
        x, y = _x(ins), _x(ins, "Y")
        y = _bcast_y(x, y, attrs.get("axis", -1))
        return {"Out": [fn(x, y)]}

    return _compute


_make_elementwise("elementwise_add", jnp.add)
_make_elementwise("elementwise_sub", jnp.subtract)
_make_elementwise("elementwise_mul", jnp.multiply)
_make_elementwise("elementwise_div", jnp.divide)
_make_elementwise("elementwise_pow", jnp.power)
_make_elementwise("elementwise_max", jnp.maximum)
_make_elementwise("elementwise_min", jnp.minimum)
_make_elementwise("elementwise_floordiv", lambda x, y: jnp.floor_divide(x, y))
_make_elementwise("elementwise_mod", jnp.mod)


def _make_compare(name, fn):
    @register_op(name, no_grad=True)
    def _compute(ins, attrs, fn=fn):
        x, y = _x(ins), _x(ins, "Y")
        y = _bcast_y(x, y, attrs.get("axis", -1))
        return {"Out": [fn(x, y)]}


_make_compare("equal", jnp.equal)
_make_compare("not_equal", jnp.not_equal)
_make_compare("less_than", jnp.less)
_make_compare("less_equal", jnp.less_equal)
_make_compare("greater_than", jnp.greater)
_make_compare("greater_equal", jnp.greater_equal)


def _make_logical(name, fn, unary=False):
    @register_op(name, no_grad=True)
    def _compute(ins, attrs, fn=fn, unary=unary):
        if unary:
            return {"Out": [fn(_x(ins))]}
        return {"Out": [fn(_x(ins), _x(ins, "Y"))]}


_make_logical("logical_and", jnp.logical_and)
_make_logical("logical_or", jnp.logical_or)
_make_logical("logical_xor", jnp.logical_xor)
_make_logical("logical_not", jnp.logical_not, unary=True)


@register_op("mul", doc="2D projection matmul with flatten dims (mul_op.cc)")
def _mul(ins, attrs):
    x, y = _x(ins), _x(ins, "Y")
    xnc = attrs.get("x_num_col_dims", 1)
    ync = attrs.get("y_num_col_dims", 1)
    import math

    xs, ys = jnp.shape(x), jnp.shape(y)
    x2 = jnp.reshape(x, (math.prod(xs[:xnc]), -1))
    y2 = jnp.reshape(y, (math.prod(ys[:ync]), -1))
    out2 = x2 @ y2
    out_shape = xs[:xnc] + ys[ync:]
    return {"Out": [jnp.reshape(out2, out_shape)]}


@register_op("matmul", doc="batched matmul w/ transpose flags (matmul_op.cc)")
def _matmul(ins, attrs):
    x, y = _x(ins), _x(ins, "Y")
    tx = attrs.get("transpose_X", False)
    ty = attrs.get("transpose_Y", False)
    alpha = attrs.get("alpha", 1.0)
    if jnp.ndim(x) == 1:
        x = x[None, :]
    if jnp.ndim(y) == 1:
        y = y[:, None]
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": [out]}


@register_op("sum", doc="add N tensors (sum_op.cc)")
def _sum(ins, attrs):
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


@register_op("mean", doc="mean over all elements (mean_op.cc)")
def _mean(ins, attrs):
    return {"Out": [jnp.mean(_x(ins))]}


def _reduce_attrs(x, attrs):
    if attrs.get("reduce_all", False):
        dims = None
    else:
        dims = attrs.get("dim", [0])
        if isinstance(dims, int):
            dims = [dims]
        dims = tuple(d % jnp.ndim(x) for d in dims)
    return dims, attrs.get("keep_dim", False)


def _make_reduce(name, fn):
    @register_op(name)
    def _compute(ins, attrs, fn=fn):
        x = _x(ins)
        dims, keep = _reduce_attrs(x, attrs)
        return {"Out": [fn(x, axis=dims, keepdims=keep)]}


_make_reduce("reduce_sum", jnp.sum)
_make_reduce("reduce_mean", jnp.mean)
_make_reduce("reduce_max", jnp.max)
_make_reduce("reduce_min", jnp.min)
_make_reduce("reduce_prod", jnp.prod)


@register_op("cast")
def _cast(ins, attrs):
    return {"Out": [_x(ins).astype(attrs["out_dtype"])]}


@register_op("scale")
def _scale(ins, attrs):
    x = _x(ins)
    s = attrs.get("scale", 1.0)
    b = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        return {"Out": [x * s + b]}
    return {"Out": [(x + b) * s]}


@register_op("clip")
def _clip(ins, attrs):
    return {"Out": [jnp.clip(_x(ins), attrs.get("min"), attrs.get("max"))]}


@register_op("clip_by_norm")
def _clip_by_norm(ins, attrs):
    x = _x(ins)
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": [x * scale]}


@register_op("squared_l2_norm")
def _squared_l2_norm(ins, attrs):
    return {"Out": [jnp.sum(jnp.square(_x(ins)))[None]]}


@register_op("increment")
def _increment(ins, attrs):
    x = _x(ins)
    step = jnp.asarray(attrs.get("step", 1.0)).astype(x.dtype)
    return {"Out": [x + step]}


@register_op("isfinite", no_grad=True, doc="all-finite check (FLAGS_check_nan_inf analog)")
def _isfinite(ins, attrs):
    flags = [jnp.all(jnp.isfinite(x)) for x in ins["X"]]
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_and(out, f)
    return {"Out": [out]}


@register_op("p_norm")
def _p_norm(ins, attrs):
    x = _x(ins)
    porder = attrs.get("porder", 2.0)
    axis = attrs.get("axis", None)
    keepdim = attrs.get("keepdim", False)
    out = jnp.sum(jnp.abs(x) ** porder, axis=axis, keepdims=keepdim) ** (1.0 / porder)
    return {"Out": [out]}


# --- pairwise / ranking / distribution losses (operators/*_loss_op.cc) ---


@register_op("log_loss", diff_inputs=("Predicted",))
def _log_loss(ins, attrs):
    """-(y*log(p) + (1-y)*log(1-p)) (reference: log_loss_op.cc)."""
    p = ins["Predicted"][0]
    y = ins["Labels"][0]
    eps = attrs.get("epsilon", 1e-4)
    out = -(y * jnp.log(p + eps) + (1.0 - y) * jnp.log(1.0 - p + eps))
    return {"Loss": [out]}


@register_op("rank_loss", diff_inputs=("Left", "Right"))
def _rank_loss(ins, attrs):
    """RankNet pairwise loss (reference: rank_loss_op.cc)."""
    label = ins["Label"][0]
    left, right = ins["Left"][0], ins["Right"][0]
    d = left - right
    return {"Out": [jnp.logaddexp(0.0, d) - label * d]}


@register_op("margin_rank_loss", diff_inputs=("X1", "X2"))
def _margin_rank_loss(ins, attrs):
    """max(0, -label*(x1-x2)+margin) (reference: margin_rank_loss_op.cc)."""
    label = ins["Label"][0]
    x1, x2 = ins["X1"][0], ins["X2"][0]
    m = attrs.get("margin", 0.0)
    act = jnp.maximum(0.0, -label * (x1 - x2) + m)
    return {"Out": [act], "Activated": [(act > 0).astype(x1.dtype)]}


@register_op("hinge_loss", diff_inputs=("Logits",))
def _hinge_loss(ins, attrs):
    """max(0, 1 - (2y-1)*logit) (reference: hinge_loss_op.cc)."""
    logits = ins["Logits"][0]
    y = ins["Labels"][0]
    return {"Loss": [jnp.maximum(0.0, 1.0 - (2.0 * y - 1.0) * logits)]}


@register_op("kldiv_loss", diff_inputs=("X",))
def _kldiv_loss(ins, attrs):
    """KL(target || x) with x in log-space (reference: kldiv_loss_op.cc)."""
    x = ins["X"][0]
    t = ins["Target"][0]
    out = t * (jnp.log(jnp.maximum(t, 1e-30)) - x)
    out = jnp.where(t > 0, out, 0.0)
    red = attrs.get("reduction", "mean")
    if red == "mean":
        out = jnp.mean(out)
    elif red == "sum":
        out = jnp.sum(out)
    elif red == "batchmean":
        out = jnp.sum(out) / jnp.shape(x)[0]
    return {"Loss": [out]}


@register_op("bpr_loss", diff_inputs=("X",))
def _bpr_loss(ins, attrs):
    """Bayesian personalized ranking loss over softmax scores
    (reference: bpr_loss_op.cc). X [N, C] raw scores, Label [N, 1]."""
    x = ins["X"][0]
    label = ins["Label"][0]
    if jnp.ndim(label) > 1:
        label = jnp.squeeze(label, -1)
    n, c = jnp.shape(x)
    pos = jnp.take_along_axis(x, label[:, None].astype(jnp.int32), axis=1)
    diff = pos - x                                     # [N, C]
    lo = jnp.logaddexp(0.0, -diff)                     # -log(sigmoid(diff))
    mask = jax.nn.one_hot(label, c, dtype=x.dtype)
    out = jnp.sum(lo * (1.0 - mask), axis=1, keepdims=True) / (c - 1)
    return {"Y": [out]}


@register_op("cos_sim", diff_inputs=("X", "Y"))
def _cos_sim(ins, attrs):
    """Row-wise cosine similarity (reference: cos_sim_op.cc)."""
    x, y = ins["X"][0], ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / jnp.maximum(
        xn * yn, 1e-12
    )
    return {"Out": [out], "XNorm": [xn], "YNorm": [yn]}


@register_op("fake_quantize_dequantize", diff_inputs=("X",))
def _fake_quantize_dequantize(ins, attrs):
    """Simulated symmetric quantization with a straight-through estimator
    (reference: operators/fake_quantize_op.cc, abs-max variant). The STE
    is baked into the expression — ``x + sg(q(x) - x)`` — so the auto
    vjp gives identity gradients inside the clip range."""
    from paddle_tpu.ops.quant_ops import _ste

    x = ins["X"][0]
    bits = int(attrs.get("bits", 8))
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    return {"Out": [_ste(x, scale, qmax)]}


@register_op("sign", no_grad=True)
def _sign(ins, attrs):
    return {"Out": [jnp.sign(ins["X"][0])]}


@register_op("minus", diff_inputs=("X", "Y"))
def _minus(ins, attrs):
    return {"Out": [ins["X"][0] - ins["Y"][0]]}


@register_op("l1_norm", diff_inputs=("X",))
def _l1_norm(ins, attrs):
    return {"Out": [jnp.sum(jnp.abs(ins["X"][0]))]}


@register_op("squared_l2_distance", diff_inputs=("X", "Y"))
def _squared_l2_distance(ins, attrs):
    """Row-wise ||x - y||^2 (reference: squared_l2_distance_op.cc)."""
    x, y = ins["X"][0], ins["Y"][0]
    sub = x - y
    out = jnp.sum(jnp.square(sub), axis=tuple(range(1, sub.ndim)),
                  keepdims=False)[:, None]
    return {"Out": [out], "sub_result": [sub]}


@register_op("modified_huber_loss", diff_inputs=("X",))
def _modified_huber_loss(ins, attrs):
    """y in {0,1} relabeled to {-1,1} (reference:
    modified_huber_loss_op.cc)."""
    x, y = ins["X"][0], ins["Y"][0]
    t = 2.0 * y - 1.0
    z = x * t
    loss = jnp.where(
        z < -1.0, -4.0 * z,
        jnp.where(z < 1.0, jnp.square(1.0 - z), jnp.zeros_like(z)),
    )
    return {"Out": [loss], "IntermediateVal": [z]}


@register_op("teacher_student_sigmoid_loss", diff_inputs=("X",))
def _teacher_student_sigmoid_loss(ins, attrs):
    """CTR distillation loss (reference:
    teacher_student_sigmoid_loss_op.cc): label <= 0 -> hard 0/1 part,
    else teacher-score part."""
    x, label = ins["X"][0], ins["Label"][0]
    soft_max_up = float(attrs.get("soft_max_up_bound", 15.0))
    soft_max_lo = float(attrs.get("soft_max_lower_bound", -15.0))
    z = jnp.clip(x, soft_max_lo, soft_max_up)
    # log(1 + exp(z)) - z * indicator(label > 0) + teacher term
    hard = jnp.log1p(jnp.exp(z)) - jnp.where(label > 0.0, z, 0.0)
    teacher = jnp.where(
        label > 0.0,
        jnp.log1p(jnp.exp(z)) - z * label,
        jnp.zeros_like(z),
    )
    return {"Y": [hard + teacher]}


@register_op("cvm", diff_inputs=("X",))
def _cvm(ins, attrs):
    """Click-value normalization for CTR features (reference:
    cvm_op.cc): first two columns are (show, click); use_cvm keeps them
    log-normalized, else strips them."""
    x = ins["X"][0]
    show = jnp.log(x[:, 0:1] + 1.0)
    click = jnp.log(x[:, 1:2] + 1.0) - show
    rest = x[:, 2:]
    if attrs.get("use_cvm", True):
        return {"Y": [jnp.concatenate([show, click, rest], axis=1)]}
    return {"Y": [rest]}


@register_op("data_norm", diff_inputs=("X",))
def _data_norm(ins, attrs):
    """Normalization by accumulated batch statistics (reference:
    data_norm_op.cc): means = batch_sum/batch_size and
    scales = sqrt(batch_size/batch_square_sum) — no mean subtraction in
    the scale, matching the reference exactly."""
    x = ins["X"][0]
    bsize = ins["BatchSize"][0]
    bsum = ins["BatchSum"][0]
    bsq = ins["BatchSquareSum"][0]
    means = bsum / bsize
    scales = jnp.sqrt(bsize / bsq)
    return {"Y": [(x - means) * scales], "Means": [means],
            "Scales": [scales]}


@register_op("spectral_norm", diff_inputs=("Weight",),
             inplace={"UOut": "U", "VOut": "V"})
def _spectral_norm(ins, attrs):
    """Spectral normalization via stored power-iteration vectors,
    persisted across steps like the reference's in-place U/V update
    (reference: spectral_norm_op.cc) — without persistence a
    power_iters=1 estimate would restart from random init every step."""
    w = ins["Weight"][0]
    u0 = ins["U"][0]
    v0 = ins["V"][0]
    u = u0.reshape(-1)
    v = v0.reshape(-1)
    dim = int(attrs.get("dim", 0))
    power_iters = int(attrs.get("power_iters", 1))
    eps = float(attrs.get("eps", 1e-12))
    wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    for _ in range(power_iters):
        v = wm.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = wm @ v
        u = u / (jnp.linalg.norm(u) + eps)
    u = jax.lax.stop_gradient(u)
    v = jax.lax.stop_gradient(v)
    sigma = u @ wm @ v
    return {"Out": [w / sigma], "UOut": [u.reshape(u0.shape)],
            "VOut": [v.reshape(v0.shape)]}


@register_op("fsp", diff_inputs=("X", "Y"))
def _fsp(ins, attrs):
    """Flow-of-solution-procedure matrix for distillation (reference:
    fsp_op.cc): Gram matrix between two feature maps."""
    x, y = ins["X"][0], ins["Y"][0]
    n, cx, h, w = x.shape
    cy = y.shape[1]
    xf = x.reshape(n, cx, h * w)
    yf = y.reshape(n, cy, h * w)
    out = jnp.einsum("ncl,nkl->nck", xf, yf) / (h * w)
    return {"Out": [out]}


@register_op("is_empty", no_grad=True)
def _is_empty(ins, attrs):
    return {"Out": [jnp.asarray(ins["X"][0].size == 0)]}


@register_op("fill", no_grad=True)
def _fill(ins, attrs):
    import numpy as _np

    data = _np.asarray(attrs["value"], dtype=attrs.get("dtype", "float32"))
    return {"Out": [jnp.asarray(data.reshape(attrs["shape"]))]}


@register_op("fill_constant_batch_size_like", no_grad=True)
def _fill_constant_batch_size_like(ins, attrs):
    ref = ins["Input"][0]
    shape = list(attrs["shape"])
    in_dim = int(attrs.get("input_dim_idx", 0))
    out_dim = int(attrs.get("output_dim_idx", 0))
    shape[out_dim] = ref.shape[in_dim]
    return {"Out": [jnp.full(tuple(shape), attrs.get("value", 0.0),
                             dtype=attrs.get("dtype", "float32"))]}


@register_op("uniform_random_batch_size_like", needs_rng=True, no_grad=True)
def _uniform_random_batch_size_like(ins, attrs, rng=None):
    ref = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[int(attrs.get("output_dim_idx", 0))] = ref.shape[
        int(attrs.get("input_dim_idx", 0))]
    lo, hi = attrs.get("min", -1.0), attrs.get("max", 1.0)
    out = jax.random.uniform(rng, tuple(shape), minval=lo, maxval=hi,
                             dtype=attrs.get("dtype", "float32"))
    return {"Out": [out]}


@register_op("gaussian_random_batch_size_like", needs_rng=True, no_grad=True)
def _gaussian_random_batch_size_like(ins, attrs, rng=None):
    ref = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[int(attrs.get("output_dim_idx", 0))] = ref.shape[
        int(attrs.get("input_dim_idx", 0))]
    mean, std = attrs.get("mean", 0.0), attrs.get("std", 1.0)
    out = mean + std * jax.random.normal(
        rng, tuple(shape), dtype=attrs.get("dtype", "float32"))
    return {"Out": [out]}


@register_op("cross_entropy2", diff_inputs=("X",))
def _cross_entropy2(ins, attrs):
    """Hard-label cross entropy also emitting the matched probability
    (reference: cross_entropy_op.h CrossEntropyOpKernel2): Y [N, 1] =
    -log(X[i, label_i]), MatchX the picked probabilities, XShape for
    reshape-style reconstruction."""
    x = _x(ins)
    label = _x(ins, "Label")
    ignore_index = int(attrs.get("ignore_index", -100))
    c = x.shape[-1]
    x2 = x.reshape(-1, c)
    lab = label.reshape(-1).astype(jnp.int32)
    safe = jnp.clip(lab, 0, c - 1)
    match = jnp.take_along_axis(x2, safe[:, None], 1)
    y = -jnp.log(jnp.maximum(match, 1e-20))
    ignored = (lab == ignore_index)[:, None]
    y = jnp.where(ignored, 0.0, y)
    out_shape = tuple(x.shape[:-1]) + (1,)
    return {
        "Y": [y.reshape(out_shape).astype(x.dtype)],
        "MatchX": [jnp.where(ignored, 1.0, match).reshape(out_shape)
                   .astype(x.dtype)],
        "XShape": [jnp.zeros(tuple(x.shape) + (0,), x.dtype)],
    }


@register_op("fill_zeros_like2", no_grad=True)
def _fill_zeros_like2(ins, attrs):
    x = _x(ins)
    return {"Out": [jnp.zeros_like(x)]}


@register_op("reduce_all", no_grad=True)
def _reduce_all(ins, attrs):
    """Logical-AND reduction (reference: reduce_all_op.cc)."""
    x = _x(ins)
    dim = attrs.get("dim", None)
    keep = bool(attrs.get("keep_dim", False))
    axis = tuple(dim) if dim else None
    return {"Out": [jnp.all(x.astype(bool), axis=axis, keepdims=keep)]}


@register_op("reduce_any", no_grad=True)
def _reduce_any(ins, attrs):
    """Logical-OR reduction (reference: reduce_any_op.cc)."""
    x = _x(ins)
    dim = attrs.get("dim", None)
    keep = bool(attrs.get("keep_dim", False))
    axis = tuple(dim) if dim else None
    return {"Out": [jnp.any(x.astype(bool), axis=axis, keepdims=keep)]}


@register_op("has_inf", no_grad=True)
def _has_inf(ins, attrs):
    """Any +-inf present (reference: isinf_op)."""
    return {"Out": [jnp.any(jnp.isinf(_x(ins)))]}


@register_op("has_nan", no_grad=True)
def _has_nan(ins, attrs):
    """Any NaN present (reference: isnan_op)."""
    return {"Out": [jnp.any(jnp.isnan(_x(ins)))]}
