"""Evaluation-metric ops: chunk_eval, precision_recall,
positive_negative_pair.

Reference kernels: paddle/fluid/operators/{chunk_eval_op.h,
metrics/precision_recall_op.h, positive_negative_pair_op.h}. Dense
design: LoD sequence inputs become padded [B, T] tensors with a
SeqLength input; the metric outputs (scalar counts/ratios) are identical
to the reference's.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


def _x(ins, slot="X", i=0):
    v = ins.get(slot)
    return v[i] if v else None


def _chunk_marks(tags, types, valid, scheme, other_type):
    """(starts, ends) boolean marks per position for one tag sequence.

    IOB: tag 0 = begin, 1 = inside. plain: every tag is its own chunk.
    A chunk starts at B, or at I whose predecessor is padding/other/a
    different type (the reference's malformed-sequence tolerance in
    ChunkEvalKernel::GetSegments). It ends before a start or at the
    sequence end."""
    if scheme == "plain":
        is_chunk = valid & (types != other_type)
        starts = is_chunk
        ends = is_chunk
        return starts, ends, is_chunk
    # IOB
    is_chunk = valid & (types != other_type)
    prev_chunk = jnp.pad(is_chunk[:, :-1], ((0, 0), (1, 0)))
    prev_type = jnp.pad(types[:, :-1], ((0, 0), (1, 0)),
                        constant_values=-1)
    begins = is_chunk & (
        (tags == 0)
        | ~prev_chunk
        | (prev_type != types)
    )
    next_begin = jnp.pad(begins[:, 1:], ((0, 0), (0, 1)))
    next_chunk = jnp.pad(is_chunk[:, 1:], ((0, 0), (0, 1)))
    ends = is_chunk & (next_begin | ~next_chunk)
    return begins, ends, is_chunk


@register_op("chunk_eval", no_grad=True)
def _chunk_eval(ins, attrs):
    """Chunk-level precision/recall/F1 for sequence tagging (reference:
    chunk_eval_op.h). Inference/Label [B, T] int labels encoded
    ``chunk_type * num_tag_types + tag`` (IOB: B=0, I=1), SeqLength [B]
    optional. Schemes: 'IOB' (default) and 'plain'."""
    infer = _x(ins, "Inference")
    label = _x(ins, "Label")
    length = _x(ins, "SeqLength")
    num_chunk_types = int(attrs["num_chunk_types"])
    scheme = attrs.get("chunk_scheme", "IOB")
    excluded = set(int(t) for t in attrs.get("excluded_chunk_types", []))
    if scheme not in ("IOB", "plain"):
        raise ValueError(f"chunk_eval: unsupported scheme '{scheme}' "
                         "(IOB and plain implemented)")
    num_tags = 1 if scheme == "plain" else 2
    other_type = num_chunk_types  # labels >= num_chunk_types*num_tags
    infer = infer.reshape(infer.shape[0], -1).astype(jnp.int32)
    label = label.reshape(label.shape[0], -1).astype(jnp.int32)
    b, t = infer.shape
    if length is not None:
        valid = (jnp.arange(t)[None, :]
                 < length.reshape(-1, 1).astype(jnp.int32))
    else:
        valid = jnp.ones((b, t), bool)

    def split(x):
        types = jnp.where(x < other_type * num_tags, x // num_tags,
                          other_type)
        tags = x % num_tags
        for e in excluded:
            types = jnp.where(types == e, other_type, types)
        return tags, types

    i_tag, i_type = split(infer)
    l_tag, l_type = split(label)
    i_start, i_end, _ = _chunk_marks(i_tag, i_type, valid, scheme,
                                     other_type)
    l_start, l_end, _ = _chunk_marks(l_tag, l_type, valid, scheme,
                                     other_type)
    num_infer = jnp.sum(i_start)
    num_label = jnp.sum(l_start)
    # a correct chunk: same start position, same type, same end position.
    # end-position match: the next end at-or-after each start must agree.
    # Dense form: segment ids via cumsum of starts; a chunk is correct iff
    # start/end/type align, i.e. positions where both start AND the two
    # chunks end together with equal types throughout. Since chunks are
    # contiguous runs, it suffices that starts coincide, types at the
    # start coincide, and the ends nearest those starts coincide — which
    # is equivalent to: every position of the chunk is marked chunk in
    # both with the same type, bounded by common start/end marks.
    both_start = i_start & l_start & (i_type == l_type)
    # A chunk is correct iff it jointly starts at some p (same type),
    # stays matching (no single-sided start, types equal) through its
    # extent, and jointly ends at the same q. Left-to-right scan per row
    # tracking whether the current jointly-started chunk still matches:
    run_ok = (i_type == l_type) & valid

    def row(bs, le, ie, ok, lst, ist):
        def body(carry, x):
            # walking left-to-right tracking whether the current jointly-
            # started chunk is still matching
            active, = carry
            bstart, lend, iend, okx, lstart, istart = x
            active = jnp.where(bstart, True, active)
            # a new single-sided start breaks the match
            active = active & okx & ~(
                (lstart | istart) & ~bstart)
            corr = active & lend & iend
            # chunk closed
            active = active & ~(lend | iend)
            return (active,), corr

        (_,), corr = jax.lax.scan(
            body, (jnp.asarray(False),),
            (bs, le, ie, ok, lst, ist))
        return corr

    corr = jax.vmap(row)(both_start, l_end, i_end, run_ok,
                         l_start, i_start)
    num_correct = jnp.sum(corr)
    num_infer_f = num_infer.astype(jnp.float32)
    num_label_f = num_label.astype(jnp.float32)
    num_corr_f = num_correct.astype(jnp.float32)
    precision = jnp.where(num_infer_f > 0, num_corr_f / num_infer_f, 0.0)
    recall = jnp.where(num_label_f > 0, num_corr_f / num_label_f, 0.0)
    f1 = jnp.where(precision + recall > 0,
                   2 * precision * recall / (precision + recall), 0.0)
    as1 = lambda v: v.reshape(1)
    return {
        "Precision": [as1(precision)],
        "Recall": [as1(recall)],
        "F1-Score": [as1(f1)],
        "NumInferChunks": [as1(num_infer.astype(jnp.int64))],
        "NumLabelChunks": [as1(num_label.astype(jnp.int64))],
        "NumCorrectChunks": [as1(num_correct.astype(jnp.int64))],
    }


@register_op("precision_recall", no_grad=True)
def _precision_recall(ins, attrs):
    """Multi-class precision/recall/F1, macro + micro averaged
    (reference: metrics/precision_recall_op.h). MaxProbs [N, 1] with
    Indices [N, 1] (argmax class), Labels [N, 1]; optional Weights.
    Outputs BatchMetrics [6] (macro P/R/F1, micro P/R/F1) and
    AccumMetrics/AccumStatesInfo for streaming (accumulated with the
    optional StatesInfo input [C, 4])."""
    indices = _x(ins, "Indices").reshape(-1).astype(jnp.int32)
    labels = _x(ins, "Labels").reshape(-1).astype(jnp.int32)
    weights = _x(ins, "Weights")
    states_in = _x(ins, "StatesInfo")
    c = int(attrs["class_number"])
    w = (weights.reshape(-1).astype(jnp.float32)
         if weights is not None else jnp.ones(labels.shape, jnp.float32))
    onehot_pred = jax.nn.one_hot(indices, c, dtype=jnp.float32)
    onehot_lab = jax.nn.one_hot(labels, c, dtype=jnp.float32)
    tp = jnp.sum(onehot_pred * onehot_lab * w[:, None], 0)       # [C]
    fp = jnp.sum(onehot_pred * (1 - onehot_lab) * w[:, None], 0)
    fn = jnp.sum((1 - onehot_pred) * onehot_lab * w[:, None], 0)
    tn = jnp.sum(w) - tp - fp - fn

    def metrics(tp, fp, fn):
        prec = jnp.where(tp + fp > 0, tp / (tp + fp), 1.0)
        rec = jnp.where(tp + fn > 0, tp / (tp + fn), 1.0)
        f1 = jnp.where(prec + rec > 0, 2 * prec * rec / (prec + rec), 0.0)
        return prec, rec, f1

    mp, mr, mf = metrics(tp, fp, fn)
    macro = jnp.stack([jnp.mean(mp), jnp.mean(mr), jnp.mean(mf)])
    up, ur, uf = metrics(jnp.sum(tp), jnp.sum(fp), jnp.sum(fn))
    batch = jnp.concatenate([macro, jnp.stack([up, ur, uf])])
    states = jnp.stack([tp, fp, tn, fn], axis=1)                 # [C, 4]
    if states_in is not None:
        states = states + states_in.astype(jnp.float32)
    atp, afp, _atn, afn = (states[:, 0], states[:, 1], states[:, 2],
                           states[:, 3])
    amp_, amr, amf = metrics(atp, afp, afn)
    amacro = jnp.stack([jnp.mean(amp_), jnp.mean(amr), jnp.mean(amf)])
    aup, aur, auf = metrics(jnp.sum(atp), jnp.sum(afp), jnp.sum(afn))
    accum = jnp.concatenate([amacro, jnp.stack([aup, aur, auf])])
    return {
        "BatchMetrics": [batch],
        "AccumMetrics": [accum],
        "AccumStatesInfo": [states],
    }


@register_op("positive_negative_pair", no_grad=True)
def _positive_negative_pair(ins, attrs):
    """Ranking pair statistics per query (reference:
    positive_negative_pair_op.h): among same-query item pairs with
    different labels, count pairs ranked correctly by Score (positive),
    incorrectly (negative), ties as neutral (0.5 each side in the
    reference's ratio; kept as separate Neutral count here, matching the
    op's three outputs)."""
    score = _x(ins, "Score").reshape(-1).astype(jnp.float32)
    label = _x(ins, "Label").reshape(-1).astype(jnp.float32)
    qid = _x(ins, "QueryID").reshape(-1).astype(jnp.int32)
    acc_pos = _x(ins, "AccumulatePositivePair")
    acc_neg = _x(ins, "AccumulateNegativePair")
    acc_neu = _x(ins, "AccumulateNeutralPair")
    same_q = qid[:, None] == qid[None, :]
    upper = jnp.triu(jnp.ones(same_q.shape, bool), k=1)
    pairs = same_q & upper & (label[:, None] != label[None, :])
    hi_lab = label[:, None] > label[None, :]
    hi_score = score[:, None] > score[None, :]
    eq_score = score[:, None] == score[None, :]
    pos = jnp.sum(pairs & ~eq_score & (hi_lab == hi_score))
    neu = jnp.sum(pairs & eq_score)
    neg = jnp.sum(pairs) - pos - neu
    pos = pos.astype(jnp.float32)
    neg = neg.astype(jnp.float32)
    neu = neu.astype(jnp.float32)
    if acc_pos is not None and acc_neg is not None and acc_neu is not None:
        pos = pos + acc_pos.reshape(())
        neg = neg + acc_neg.reshape(())
        neu = neu + acc_neu.reshape(())
    elif any(a is not None for a in (acc_pos, acc_neg, acc_neu)):
        raise ValueError(
            "positive_negative_pair: Accumulate{Positive,Negative,Neutral}"
            "Pair must be wired together or not at all")
    return {
        "PositivePair": [pos.reshape(1)],
        "NegativePair": [neg.reshape(1)],
        "NeutralPair": [neu.reshape(1)],
    }
