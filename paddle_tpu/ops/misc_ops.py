"""Host-callback and tail operators: py_func, print, hash, tree_conv.

TPU-native redesigns of the reference's four remaining user-facing ops:

- ``py_func`` (reference: paddle/fluid/operators/py_func_op.cc:105):
  arbitrary user Python runs on the HOST via ``jax.pure_callback`` staged
  inside the compiled XLA program, instead of the reference's
  hold-the-GIL-in-the-executor path. Output shapes/dtypes are declared at
  graph-build time (XLA needs static signatures); the backward callable is
  emitted as a second py_func op by a custom grad maker, mirroring the
  reference's grad-op-desc maker.
- ``print`` (reference: operators/print_op.cc): identity op whose host
  side-effect is staged with ``jax.debug.callback`` (survives XLA DCE and
  runs per executed step, not per trace). ``print_phase`` backward/both is
  a grad-maker-emitted print op over the incoming gradient.
- ``hash`` (reference: operators/hash_op.cc — xxHash64 % mod_by): a
  vectorized FNV-1a-style integer mixer over the last axis, one lane per
  ``num_hash`` seed. Bucket values differ from xxHash (capability parity:
  stable multi-seed feature hashing into ``mod_by`` buckets), but the
  layout [rows, num_hash, 1] and semantics match.
- ``tree_conv`` (reference: operators/tree_conv_op.cc + math/tree2col.cc):
  the reference walks each patch with a host DFS and scatters into a
  tree2col buffer. Here the patch weights become three dense [n, n]
  matrices built from ``max_depth`` adjacency matmuls (R_{d+1} = R_d @ A),
  so the whole op is batched matmuls the MXU runs natively — no
  host graph walk, autodiff via vjp.
"""

from __future__ import annotations

import sys
import time
from typing import Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.core.registry import register_op


def _x(ins, slot="X", i=0):
    vals = ins.get(slot)
    return None if not vals else vals[i]


# --------------------------------------------------------------------------
# py_func
# --------------------------------------------------------------------------

_PY_FUNC_REGISTRY: List[Callable] = []


def register_py_func(fn: Callable) -> int:
    """Register a host callable; returns its id (the analog of the
    reference's ``PyFuncRegistry`` in layers/nn.py:11004)."""
    _PY_FUNC_REGISTRY.append(fn)
    return len(_PY_FUNC_REGISTRY) - 1


def registered_py_func(idx: int) -> Callable:
    return _PY_FUNC_REGISTRY[idx]


def _normalize_results(res, shapes, dtypes):
    if res is None:
        res = ()
    if not isinstance(res, (tuple, list)):
        res = (res,)
    out = []
    for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
        r = res[i] if i < len(res) else None
        if r is None:
            # None = "no gradient for this input" (reference py_func
            # backward contract) -> a zero contribution.
            out.append(np.zeros(shape, dtype))
        else:
            out.append(np.asarray(r).astype(dtype).reshape(shape))
    return tuple(out)


def _py_func_grad_maker(op, block, out_grads, provide, should_skip):
    """Emit the backward py_func op (reference: py_func_op.cc grad maker —
    backward inputs are fwd X + fwd Out + Out grads minus skip vars;
    outputs are the X grads)."""
    bwd_id = int(op.attrs.get("backward_callable_id", -1))
    if bwd_id < 0:
        return []  # no backward_func: non-differentiable boundary
    skip = set(op.attrs.get("backward_skip_vars") or [])
    xs = list(op.inputs.get("X") or [])
    outs = list(op.outputs.get("Out") or [])
    gs = list((out_grads.get("Out") or []))

    in_names, none_pos = [], []
    pos = 0
    for n in xs + outs:
        if n in skip:
            continue
        in_names.append(n)
        pos += 1
    for g in gs:
        if g:
            in_names.append(g)
        else:
            none_pos.append(pos)  # backward_func receives None here
        pos += 1

    from paddle_tpu.core.registry import get_op_def

    opdef = get_op_def("py_func")
    g_out_names, g_shapes, g_dtypes = [], [], []
    for n in xs:
        src = block._find_var_recursive(n)
        if should_skip(n, "X", opdef):
            g_out_names.append("")
            g_shapes.append([1])
            g_dtypes.append("float32")
            continue
        if src is None or src.shape is None:
            raise ValueError(
                f"py_func backward needs a declared shape for input '{n}'")
        gname = provide(n)
        block.create_var(name=gname, shape=src.shape, dtype=src.dtype)
        g_out_names.append(gname)
        g_shapes.append([int(d) for d in src.shape])
        g_dtypes.append(str(src.dtype))
    if not any(g_out_names):
        return []
    return [dict(
        type="py_func",
        inputs={"X": in_names},
        outputs={"Out": g_out_names},
        attrs={
            "forward_callable_id": bwd_id,
            "backward_callable_id": -1,
            "out_shapes": g_shapes,
            "out_dtypes": g_dtypes,
            "none_positions": none_pos,
            # backward_func naturally returns one grad per forward input;
            # grads for skipped (stop_gradient/int) inputs are discarded
            # rather than reshaped into the placeholder slots
            "drop_positions": [i for i, nm in enumerate(g_out_names)
                               if not nm],
        },
    )]


@register_op("py_func", grad_maker=_py_func_grad_maker)
def _py_func(ins, attrs):
    """User Python staged into the compiled step as a host callback
    (reference: py_func_op.cc:105). With outputs: ``jax.pure_callback``
    with declared result shapes. Without outputs: an effect-only
    ``jax.debug.callback`` (the reference's debug-print usage)."""
    xs = [x for x in (ins.get("X") or [])]
    fid = int(attrs["forward_callable_id"])
    none_pos = set(int(p) for p in (attrs.get("none_positions") or []))

    def host_call(*arrs):
        fn = registered_py_func(fid)
        it = iter(arrs)
        args = [None if i in none_pos else next(it)
                for i in range(len(arrs) + len(none_pos))]
        return fn(*args)

    present = [x for x in xs if x is not None]
    shapes = [tuple(int(d) for d in s) for s in (attrs.get("out_shapes") or [])]
    dtypes = [np.dtype(d) for d in (attrs.get("out_dtypes") or [])]
    drop = set(int(p) for p in (attrs.get("drop_positions") or []))
    if not shapes:
        jax.debug.callback(lambda *a: host_call(*a), *present)
        return {}
    result_shape = tuple(
        jax.ShapeDtypeStruct(s, d) for s, d in zip(shapes, dtypes))

    def host_fn(*arrs):
        res = host_call(*arrs)
        if drop:
            if res is None:
                res = ()
            if not isinstance(res, (tuple, list)):
                res = (res,)
            res = [None if i in drop else r for i, r in enumerate(res)]
        return _normalize_results(res, shapes, dtypes)

    outs = jax.pure_callback(host_fn, result_shape, *present)
    return {"Out": list(outs)}


# --------------------------------------------------------------------------
# print
# --------------------------------------------------------------------------

_PRINT_COUNTS: Dict[int, int] = {}


def _print_grad_maker(op, block, out_grads, provide, should_skip):
    """print_phase BACKWARD/BOTH: print the incoming gradient through a
    second print op, then pass it on as In@GRAD (reference: print_op.cc
    print_phase attr)."""
    from paddle_tpu.core.registry import get_op_def

    g = (out_grads.get("Out") or [""])[0]
    name = (op.inputs.get("In") or [""])[0]
    if not g or should_skip(name, "In", get_op_def("print")):
        return []
    src = block._find_var_recursive(name)
    gname = provide(name)
    block.create_var(name=gname, shape=src.shape if src else None,
                     dtype=src.dtype if src else "float32")
    phase = str(op.attrs.get("print_phase", "BOTH")).upper()
    attrs = dict(op.attrs)
    attrs["is_forward"] = False
    attrs["var_name"] = str(op.attrs.get("var_name", "")) + "@GRAD"
    # distinct first_n budget from the forward print (negated uid keys a
    # separate _PRINT_COUNTS slot; layer uids start at 1)
    attrs["print_uid"] = -int(op.attrs.get("print_uid", 0))
    if phase == "FORWARD":
        # no backward printing: plain identity pass-through
        return [dict(type="assign", inputs={"X": [g]},
                     outputs={"Out": [gname]}, attrs={})]
    return [dict(type="print", inputs={"In": [g]}, outputs={"Out": [gname]},
                 attrs=attrs)]


@register_op("print", grad_maker=_print_grad_maker)
def _print(ins, attrs):
    """Identity + staged host print (reference: operators/print_op.cc).
    first_n counts per op instance (``print_uid`` attr) across executed
    steps, on the host."""
    x = _x(ins, "In")
    first_n = int(attrs.get("first_n", -1))
    message = str(attrs.get("message", "") or "")
    summarize = int(attrs.get("summarize", -1))
    uid = int(attrs.get("print_uid", -1))
    var_name = str(attrs.get("var_name", ""))
    show_name = bool(attrs.get("print_tensor_name", True))
    show_type = bool(attrs.get("print_tensor_type", True))
    show_shape = bool(attrs.get("print_tensor_shape", True))
    phase = str(attrs.get("print_phase", "BOTH")).upper()
    is_forward = bool(attrs.get("is_forward", True))

    do_print = not (is_forward and phase == "BACKWARD")

    def host_print(arr):
        if first_n >= 0:
            seen = _PRINT_COUNTS.get(uid, 0)
            if seen >= first_n:
                return
            _PRINT_COUNTS[uid] = seen + 1
        arr = np.asarray(arr)
        # wall clock is ONLY the human-readable stamp on the printed line
        # (reference print_op format); never difference these — interval
        # measurement everywhere in this tree uses time.perf_counter().
        parts = [f"{int(time.time())}\t{message}\t"]
        if show_name and var_name:
            parts.append(f"Tensor[{var_name}]")
        if show_type:
            parts.append(f"\n\tdtype: {arr.dtype}")
        if show_shape:
            parts.append(f"\n\tshape: {list(arr.shape)}")
        flat = arr.reshape(-1)
        if summarize >= 0:
            flat = flat[:summarize]
        parts.append(f"\n\tdata: {np.array2string(flat, threshold=1000)}")
        print("".join(parts), file=sys.stderr)

    if do_print:
        jax.debug.callback(host_print, x)
    return {"Out": [x]}


# --------------------------------------------------------------------------
# hash
# --------------------------------------------------------------------------

_FNV_PRIME = np.uint32(16777619)
_FNV_BASIS = np.uint32(2166136261)

# xxHash64 prime constants (public-domain algorithm, Yann Collet)
_XXP1 = 0x9E3779B185EBCA87
_XXP2 = 0xC2B2AE3D27D4EB4F
_XXP3 = 0x165667B19E3779F9
_XXP4 = 0x85EBCA77C2B2AE63
_XXP5 = 0x27D4EB2F165667C5


def _rotl64(x, r):
    return (x << np.uint64(r)) | (x >> np.uint64(64 - r))


def _xx_round(acc, lane):
    return _rotl64(acc + lane * np.uint64(_XXP2), 31) * np.uint64(_XXP1)


def _xxh64_words(words, seeds):
    """XXH64 of a ``4*n``-byte stream given as little-endian uint32
    words ``[..., n]``, for every seed in ``seeds`` [m]; returns
    ``[..., m]`` uint64. The word count is static, so the stripe/lane
    structure unrolls into straight-line XLA ops — vectorized over all
    leading batch dims and seeds at once. Requires x64 mode (uint64
    lattice). Implements the public xxHash64 spec; input length is
    always a word multiple so there is no single-byte tail."""
    n = words.shape[-1]
    length = np.uint64(4 * n)
    w64 = words.astype(jnp.uint64)
    # 8-byte lanes = little-endian word pairs
    lanes = [w64[..., 2 * k] | (w64[..., 2 * k + 1] << np.uint64(32))
             for k in range(n // 2)]
    batch = words.shape[:-1]
    seeds = jnp.broadcast_to(seeds.astype(jnp.uint64),
                             batch + seeds.shape)
    lanes = [l[..., None] for l in lanes]          # broadcast vs seeds

    n_stripes = n // 8
    if n_stripes:                                   # >= 32 bytes
        v1 = seeds + np.uint64(_XXP1) + np.uint64(_XXP2)
        v2 = seeds + np.uint64(_XXP2)
        v3 = seeds + np.uint64(0)
        v4 = seeds - np.uint64(_XXP1)
        for s in range(n_stripes):
            v1 = _xx_round(v1, lanes[4 * s])
            v2 = _xx_round(v2, lanes[4 * s + 1])
            v3 = _xx_round(v3, lanes[4 * s + 2])
            v4 = _xx_round(v4, lanes[4 * s + 3])
        h = (_rotl64(v1, 1) + _rotl64(v2, 7) + _rotl64(v3, 12)
             + _rotl64(v4, 18))
        for v in (v1, v2, v3, v4):
            h = (h ^ _xx_round(jnp.zeros_like(v), v)) \
                * np.uint64(_XXP1) + np.uint64(_XXP4)
    else:
        h = seeds + np.uint64(_XXP5)
    h = h + length
    for k in range(n_stripes * 4, n // 2):          # leftover 8B lanes
        h = _rotl64(h ^ _xx_round(jnp.zeros_like(h), lanes[k]), 27) \
            * np.uint64(_XXP1) + np.uint64(_XXP4)
    if n % 2:                                       # leftover 4B word
        h = _rotl64(h ^ (w64[..., -1:] * np.uint64(_XXP1)), 23) \
            * np.uint64(_XXP2) + np.uint64(_XXP3)
    h = h ^ (h >> np.uint64(33))
    h = h * np.uint64(_XXP2)
    h = h ^ (h >> np.uint64(29))
    h = h * np.uint64(_XXP3)
    return h ^ (h >> np.uint64(32))


@register_op("hash", no_grad=True)
def _hash(ins, attrs):
    """Multi-seed feature hashing (reference: operators/hash_op.cc/.h —
    out[row, i] = XXH64(row_bytes, sizeof(int)*last_dim, seed=i)
    % mod_by, out dims = in dims minus last + [num_hash, 1]; note the
    reference hashes ``sizeof(int)`` — 4 — bytes per element even for
    int64 rows, i.e. the first 4*last_dim bytes of the row).

    Under x64 mode this is bit-exact XXH64 (same buckets as the
    reference, byte-prefix quirk included). With x64 disabled uint64
    arithmetic is unavailable and a per-seed FNV-1a mix is substituted:
    same contract (deterministic, uniform over [0, mod_by)), DIFFERENT
    bucket values — vocabularies built against reference buckets only
    port under ``jax_enable_x64``."""
    x = _x(ins)
    num_hash = int(attrs.get("num_hash", 1))
    mod_by = int(attrs.get("mod_by", 100000))
    if jax.config.jax_enable_x64:
        if x.dtype in (jnp.int64, jnp.uint64):
            # word stream of the row's bytes, truncated to 4 bytes per
            # element (the reference's sizeof(int) read)
            a = lax.bitcast_convert_type(x, jnp.uint64)
            lo = (a & np.uint64(0xFFFFFFFF)).astype(jnp.uint32)
            hi = (a >> np.uint64(32)).astype(jnp.uint32)
            words = jnp.stack([lo, hi], axis=-1).reshape(
                x.shape[:-1] + (2 * x.shape[-1],))[..., :x.shape[-1]]
        else:
            words = lax.bitcast_convert_type(
                x.astype(jnp.int32), jnp.uint32)
        seeds = jnp.arange(num_hash, dtype=jnp.uint64)
        h = _xxh64_words(words, seeds)
        out = (h % np.uint64(mod_by)).astype(x.dtype)
        return {"Out": [out[..., None]]}
    # Fold the high word before narrowing so 64-bit ids differing only
    # above bit 31 don't collide. Under JAX's default x64-disabled mode
    # int64 feeds are already truncated to int32 at trace entry (the id
    # space is effectively 32-bit); with jax_enable_x64 the fold is real.
    if x.dtype in (jnp.int64, jnp.uint64):
        x = x ^ (x >> 32)
    xi = x.astype(jnp.uint32)
    seeds = jnp.arange(num_hash, dtype=jnp.uint32)
    # h_0 = basis ^ (seed * golden); h = (h ^ elem) * prime per element
    h = _FNV_BASIS ^ (seeds * jnp.uint32(0x9E3779B9))           # [num_hash]
    h = jnp.broadcast_to(h, x.shape[:-1] + (num_hash,))
    for i in range(x.shape[-1]):
        elem = xi[..., i:i + 1]
        h = (h ^ elem) * _FNV_PRIME
        # extra avalanche: xorshift keeps high bits moving
        h = h ^ (h >> jnp.uint32(15))
    out = (h % jnp.uint32(mod_by)).astype(x.dtype)
    return {"Out": [out[..., None]]}


# --------------------------------------------------------------------------
# tree_conv
# --------------------------------------------------------------------------


def _tree_patch_weights(edges, n, max_depth):
    """Dense patch-weight matrices Wl, Wr, Wt [n, n]: W*[u, v] = eta_*
    of node v in the patch rooted at u (reference: math/tree2col.cc
    construct_patch + TreeNode::eta_{l,r,t}). Built from adjacency-matrix
    powers: R_d[u, v] = v is a depth-d descendant of u, d < max_depth."""
    e = edges.shape[0]
    u, v = edges[:, 0], edges[:, 1]
    valid = (u != 0) & (v != 0)
    # reference construct_tree stops at the first invalid edge
    valid = jnp.cumprod(valid.astype(jnp.int32)).astype(bool)
    uz = jnp.where(valid, u - 1, n)   # 0-based; invalid -> dropped row n
    vz = jnp.where(valid, v - 1, n)

    adj = jnp.zeros((n, n), jnp.float32).at[uz, vz].set(
        1.0, mode="drop")                                       # [n, n]

    # child position of v among parent u's children, in edge order
    same_parent = (u[:, None] == u[None, :]) & valid[None, :] & valid[:, None]
    before = jnp.tril(same_parent, k=-1)
    index = 1.0 + jnp.sum(before, axis=1).astype(jnp.float32)   # 1-based
    pclen = jnp.sum(same_parent, axis=1).astype(jnp.float32)
    temp_e = jnp.where(pclen <= 1.0, 0.5,
                       (index - 1.0) / jnp.maximum(pclen - 1.0, 1.0))
    # scatter per-edge temp to the child node id
    temp = jnp.zeros((n,), jnp.float32).at[vz].set(
        temp_e, mode="drop")                                    # [n]

    md = float(max_depth)
    wl = jnp.zeros((n, n), jnp.float32)
    wr = jnp.zeros((n, n), jnp.float32)
    wt = jnp.zeros((n, n), jnp.float32)
    r_d = jnp.eye(n, dtype=jnp.float32)
    for d in range(max_depth):
        eta_t = (md - d) / md
        one_m = 1.0 - eta_t
        eta_l_v = one_m * temp                                  # [n]
        eta_r_v = one_m * (1.0 - eta_l_v)
        wt = wt + r_d * eta_t
        wl = wl + r_d * eta_l_v[None, :]
        wr = wr + r_d * eta_r_v[None, :]
        if d + 1 < max_depth:
            r_d = r_d @ adj
    node_count = jnp.sum(valid) + 1
    exists = (jnp.arange(n) < node_count).astype(jnp.float32)
    return wl, wr, wt, exists


@register_op("tree_conv", diff_inputs=("NodesVector", "Filter"))
def _tree_conv(ins, attrs):
    """Tree-based convolution (reference: tree_conv_op.cc; TBCNN,
    https://arxiv.org/abs/1409.5718). NodesVector [N, n, f], EdgeSet
    [N, e, 2] int 1-indexed parent->child ((0, 0) padding), Filter
    [f, 3, out_size, num_filters] (3 = eta_l/eta_r/eta_t to match the
    reference's tree2col column layout), Out [N, n, out_size,
    num_filters]. Patch weights are dense [n, n] matrices so the op is
    four batched matmuls end to end."""
    nodes = _x(ins, "NodesVector")
    edges = _x(ins, "EdgeSet").astype(jnp.int32)
    filt = _x(ins, "Filter")
    max_depth = int(attrs.get("max_depth", 2))
    n = nodes.shape[1]
    f = nodes.shape[2]
    assert filt.shape[0] == f and filt.shape[1] == 3

    def one(feat, edge):
        wl, wr, wt, exists = _tree_patch_weights(edge, n, max_depth)
        out = (
            jnp.einsum("uv,vf,fod->uod", wl, feat, filt[:, 0])
            + jnp.einsum("uv,vf,fod->uod", wr, feat, filt[:, 1])
            + jnp.einsum("uv,vf,fod->uod", wt, feat, filt[:, 2])
        )
        return out * exists[:, None, None]

    out = jax.vmap(one)(nodes.astype(jnp.float32), edges)
    return {"Out": [out.astype(nodes.dtype)]}
