"""Expert-parallel Mixture-of-Experts op.

Net-new capability vs the reference (SURVEY.md section 2.3: "EP, MoE —
absent in reference; in-scope as native capabilities"). This op makes
``parallel/moe.py`` reachable from the Program IR the same way ring
attention is reachable from scaled_dot_product_attention: when the program
runs under a DistributedStrategy declaring an ``expert_axis``, tokens are
dispatched over ICI with ``lax.all_to_all`` (one expert per rank);
otherwise the identical fixed-capacity Switch math runs densely on one
device, so 1-device and n-device runs of the same program are comparable.

Inputs: X [.., d] tokens (any leading shape), GateW [d, E] router,
stacked expert FFN weights W1 [E, d, dff], B1 [E, dff], W2 [E, dff, d],
B2 [E, d]. Outputs: Out (same shape as X), AuxLoss [] (Switch
load-balancing loss; add ``aux_weight * AuxLoss`` to the training loss).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op

_ACTS = {
    "relu": jax.nn.relu,
    "gelu": jax.nn.gelu,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
}


def _x(ins, slot, i=0):
    v = ins.get(slot)
    return v[i] if v else None


@register_op(
    "switch_moe",
    diff_inputs=("X", "GateW", "W1", "B1", "W2", "B2"),
    doc="Switch-style top-1 MoE FFN; expert-parallel all_to_all dispatch "
        "under a strategy expert axis (parallel/moe.py)",
)
def _switch_moe(ins, attrs):
    """Capacity caveat: expert capacity is ``cap_factor * n_local / e``
    where n_local is the PER-RANK token count under a data axis. Global
    capacity matches the dense path (capacity * ranks == cap_factor*n/e),
    but truncation applies per rank — so a 1-device and an n-device run
    of the same program are bit-comparable only while no expert
    overflows its per-rank capacity (skewed routing truncates earlier
    distributed). Raise ``capacity_factor`` if dropped-token parity
    matters (see tests/test_moe_ir.py)."""
    x = _x(ins, "X")
    gate_w = _x(ins, "GateW")
    w1, b1 = _x(ins, "W1"), _x(ins, "B1")
    w2, b2 = _x(ins, "W2"), _x(ins, "B2")
    act = _ACTS[attrs.get("act", "relu")]
    cap_factor = float(attrs.get("capacity_factor", 2.0))
    e = int(gate_w.shape[-1])

    shape = jnp.shape(x)
    d = shape[-1]
    xf = jnp.reshape(x, (-1, d))
    n = int(xf.shape[0])
    # Router math in f32 regardless of the AMP activation stream: argmax
    # ties and softmax fractions are routing decisions, not a bandwidth
    # bound, and bf16 routing can diverge between runs.
    gate_w = gate_w.astype(jnp.float32)

    def ffn(p, t):
        pw1, pb1, pw2, pb2 = p
        h = act(t @ pw1.astype(t.dtype) + pb1.astype(t.dtype))
        return h @ pw2.astype(t.dtype) + pb2.astype(t.dtype)

    params = (w1, b1, w2, b2)

    from paddle_tpu.core.interp import spmd_ctx
    from paddle_tpu.parallel import moe

    ctx = spmd_ctx()
    dist = None
    if ctx is not None and ctx.expert_axis is not None:
        mesh = ctx.mesh
        # A declared expert axis that cannot serve this op is a strategy
        # configuration error, not a fallback case: silently running the
        # dense path would leave the [E, ...] expert weights sharded by
        # moe_rules with no all_to_all — GSPMD would all-gather them every
        # step with no signal (cf. DistributedStrategy strict rationale).
        if mesh.shape[ctx.expert_axis] != e:
            raise ValueError(
                f"switch_moe: strategy expert_axis '{ctx.expert_axis}' has "
                f"mesh size {mesh.shape[ctx.expert_axis]} but the op has "
                f"{e} experts; they must match (one expert per rank)"
            )
        from paddle_tpu.parallel.mesh import axis_size

        data_axis = ctx.data_axis
        n_ranks = axis_size(mesh, data_axis) if data_axis else 1
        if data_axis is not None and n % n_ranks != 0:
            raise ValueError(
                f"switch_moe: {n} tokens do not divide the data axis "
                f"'{data_axis}' ({n_ranks} ranks)"
            )
        dist = (mesh, ctx.expert_axis, data_axis, n_ranks)

    n_loc = n // (dist[3] if dist else 1)
    capacity = max(1, int(cap_factor * n_loc / e))

    if dist is not None:
        mesh, expert_axis, data_axis, _ = dist
        out, aux = moe.moe_ffn(
            xf, gate_w, params, ffn, mesh,
            expert_axis=expert_axis, data_axis=data_axis, capacity=capacity,
        )
    else:
        out, aux = moe.moe_dense(xf, gate_w, params, ffn, capacity)
    return {
        "Out": [jnp.reshape(out, shape).astype(x.dtype)],
        "AuxLoss": [aux.astype(jnp.float32)],
    }
