"""Neural-network ops: conv, pool, normalization, dropout, losses, metrics.

Reference kernels: paddle/fluid/operators/{conv_op.cc, pool_op.cc,
batch_norm_op.cc, layer_norm_op.cc, dropout_op.cc, softmax_op.cc,
cross_entropy_op.cc, softmax_with_cross_entropy_op.cc,
metrics/accuracy_op.cc}. Convs map straight onto the MXU through
``lax.conv_general_dilated``; XLA picks TPU-friendly layouts regardless of
the NCHW API convention.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


def _x(ins, slot="X", i=0):
    v = ins.get(slot)
    return v[i] if v else None


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v, v)


@register_op("conv2d", diff_inputs=("Input", "Filter"))
def _conv2d(ins, attrs):
    x, w = _x(ins, "Input"), _x(ins, "Filter")
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dil = _pair(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1)
    # Emit the conv in NHWC logical order: the API is NCHW (reference
    # conv_op.cc convention) but XLA's TPU conv emitter tiles NHWC-labelled
    # convs measurably better (ResNet-50 train: +3.5% step time with
    # identical physical layouts — the transposes below fold into layout
    # assignment and emit no copies).
    out = jax.lax.conv_general_dilated(
        jnp.transpose(x, (0, 2, 3, 1)),
        jnp.transpose(w, (2, 3, 1, 0)),
        window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dil,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups,
    )
    return {"Output": [jnp.transpose(out, (0, 3, 1, 2))]}


@register_op("depthwise_conv2d", diff_inputs=("Input", "Filter"))
def _depthwise_conv2d(ins, attrs):
    x, w = _x(ins, "Input"), _x(ins, "Filter")
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dil = _pair(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", jnp.shape(x)[1])
    out = jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dil,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=groups,
    )
    return {"Output": [out]}


@register_op("conv2d_transpose", diff_inputs=("Input", "Filter"))
def _conv2d_transpose(ins, attrs):
    """Gradient-of-conv semantics (reference conv_transpose_op.cc): filter is
    [C_in, C_out/groups, kh, kw]; out H = (H-1)*s - 2p + d*(k-1) + 1.
    Expressed as a fractionally-strided forward conv (lhs_dilation) so XLA
    lowers it onto the MXU like any conv."""
    x, w = _x(ins, "Input"), _x(ins, "Filter")
    sh, sw = _pair(attrs.get("strides", [1, 1]))
    ph, pw = _pair(attrs.get("paddings", [0, 0]))
    dh, dw = _pair(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1)
    kh, kw = jnp.shape(w)[2], jnp.shape(w)[3]
    # [C_in, C_out/g, kh, kw] -> flip spatial, swap io -> [C_out, C_in/g, ...]
    if groups > 1:
        ci = jnp.shape(w)[0]
        wg = jnp.reshape(w, (groups, ci // groups) + tuple(jnp.shape(w)[1:]))
        wg = jnp.flip(wg, axis=(-2, -1))
        wg = jnp.swapaxes(wg, 1, 2)  # [g, C_out/g, C_in/g, kh, kw]
        w_eff = jnp.reshape(wg, (-1, ci // groups, kh, kw))
    else:
        w_eff = jnp.swapaxes(jnp.flip(w, axis=(-2, -1)), 0, 1)
    pad_h = dh * (kh - 1) - ph
    pad_w = dw * (kw - 1) - pw
    out = jax.lax.conv_general_dilated(
        x,
        w_eff,
        window_strides=(1, 1),
        padding=[(pad_h, pad_h), (pad_w, pad_w)],
        lhs_dilation=(sh, sw),
        rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=groups,
    )
    return {"Output": [out]}


@register_op("pool2d")
def _pool2d(ins, attrs):
    x = _x(ins)
    ptype = attrs.get("pooling_type", "max")
    ksize = _pair(attrs.get("ksize", [2, 2]))
    strides = _pair(attrs.get("strides", [2, 2]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    if attrs.get("global_pooling", False):
        ksize = (jnp.shape(x)[2], jnp.shape(x)[3])
        strides = ksize
        pads = (0, 0)
    window = (1, 1) + ksize
    wstrides = (1, 1) + strides
    padding = ((0, 0), (0, 0), (pads[0], pads[0]), (pads[1], pads[1]))
    if ptype == "max":
        init = -jnp.inf
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, wstrides, padding)
    else:
        summed = jax.lax.reduce_window(
            x, 0.0, jax.lax.add, window, wstrides, padding
        )
        if attrs.get("exclusive", True) and pads != (0, 0):
            ones = jnp.ones_like(x)
            count = jax.lax.reduce_window(
                ones, 0.0, jax.lax.add, window, wstrides, padding
            )
            out = summed / count
        else:
            out = summed / (ksize[0] * ksize[1])
    return {"Out": [out]}


@register_op(
    "batch_norm",
    diff_inputs=("X", "Scale", "Bias"),
    inplace={"MeanOut": "Mean", "VarianceOut": "Variance"},
)
def _batch_norm(ins, attrs):
    x = _x(ins)
    scale, bias = _x(ins, "Scale"), _x(ins, "Bias")
    mean, var = _x(ins, "Mean"), _x(ins, "Variance")
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False)
    layout = attrs.get("data_layout", "NCHW")
    axes = tuple(i for i in range(jnp.ndim(x)) if i != (1 if layout == "NCHW" else jnp.ndim(x) - 1))
    c_axis = 1 if layout == "NCHW" else jnp.ndim(x) - 1
    shape = [1] * jnp.ndim(x)
    shape[c_axis] = jnp.shape(x)[c_axis]

    # Stats and normalization math in f32; Y comes back in x's dtype, so
    # a bf16 AMP stream stays bf16 — promoting the whole activation to
    # f32 materialized a full-precision copy of the widest tensors
    # (measured ~1.5 ms/step per early ResNet-50 stage at b=128).
    xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
    if is_test:
        use_mean, use_var = mean, var
        new_mean, new_var = mean, var
        saved_mean = mean
        saved_var = var
    else:
        # One-pass stats: E[x] and E[x^2] reduce in the same traversal (a
        # single multi-output reduction XLA fuses into the producing conv's
        # epilogue), where mean-then-var is two passes over a tensor that
        # is usually the widest in the model. Cancellation in E[x^2]-E[x]^2
        # is benign here: stats are f32 and NN activations keep
        # std/|mean| far from the f32 cliff. Measured on ResNet-50 b=128
        # (1x v5e): 0.292 -> 0.321 MFU together with the affine rewrite
        # below.
        use_mean = jnp.mean(xf, axis=axes)
        use_var = jnp.maximum(
            jnp.mean(jnp.square(xf), axis=axes) - jnp.square(use_mean), 0.0
        )
        new_mean = momentum * mean + (1 - momentum) * use_mean
        new_var = momentum * var + (1 - momentum) * use_var
        saved_mean = use_mean
        saved_var = use_var

    # Affine form y = k*x + c with per-channel k, c: one fused
    # multiply-add over the wide tensor, and its vjp re-derives x-hat
    # without re-centering passes. The affine itself runs in x's dtype
    # (k, c are [C]-sized and cast once): under bf16 AMP an f32 affine
    # whose output has MULTIPLE consumers (SE blocks: pool AND the gate
    # multiply read the same BN output) makes XLA materialize the f32
    # tensor instead of recompute-fusing it into each consumer —
    # measured 817 us/step per stage-0 SE-ResNeXt block of pure f32
    # copy traffic, ~8 ms/step total (round 5; ResNet-50 was immune
    # because every BN output there has a single consumer chain).
    inv = jax.lax.rsqrt(use_var + eps)
    k = inv if scale is None else inv * scale
    c = -use_mean * k
    if bias is not None:
        c = c + bias
    y = x * k.astype(x.dtype).reshape(shape) + c.astype(x.dtype).reshape(shape)
    return {
        "Y": [y],
        "MeanOut": [jax.lax.stop_gradient(new_mean)],
        "VarianceOut": [jax.lax.stop_gradient(new_var)],
        "SavedMean": [jax.lax.stop_gradient(saved_mean)],
        "SavedVariance": [jax.lax.stop_gradient(saved_var)],
    }


@register_op("layer_norm", diff_inputs=("X", "Scale", "Bias"))
def _layer_norm(ins, attrs):
    x = _x(ins)
    scale, bias = _x(ins, "Scale"), _x(ins, "Bias")
    eps = attrs.get("epsilon", 1e-5)
    begin = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(begin, jnp.ndim(x)))
    # All internal math in f32 regardless of the activation dtype (bf16
    # under AMP): stats are precision-sensitive, and doing the affine in
    # f32 keeps the scale/bias gradient reductions in f32 through the vjp.
    # Only the final result returns to x's dtype, so the HBM stream stays
    # bf16 and the f32 intermediates live inside the XLA fusion.
    stat_dtype = jnp.promote_types(x.dtype, jnp.float32)  # f32 unless f64
    xf = x.astype(stat_dtype)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    y = (xf - mean) * inv
    feat_shape = jnp.shape(x)[begin:]
    if scale is not None:
        y = y * jnp.reshape(scale, (1,) * begin + feat_shape).astype(stat_dtype)
    if bias is not None:
        y = y + jnp.reshape(bias, (1,) * begin + feat_shape).astype(stat_dtype)
    y = y.astype(x.dtype)
    return {
        "Y": [y],
        "Mean": [jax.lax.stop_gradient(jnp.reshape(mean, (-1,)))],
        "Variance": [jax.lax.stop_gradient(jnp.reshape(var, (-1,)))],
    }


@register_op("dropout", needs_rng=True)
def _dropout(ins, attrs, rng=None):
    x = _x(ins)
    p = attrs.get("dropout_prob", 0.5)
    is_test = attrs.get("is_test", False)
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if is_test:
        if impl == "upscale_in_train":
            return {"Out": [x], "Mask": []}
        return {"Out": [x * (1.0 - p)], "Mask": []}
    if p <= 0.0:  # keep-everything: the uint16 threshold below would
        return {"Out": [x], "Mask": []}  # overflow at 65536
    # keep-mask from 16-bit random words: RngBitGenerator throughput is
    # random-bits-bound on TPU, so uint16 halves its cost vs the uint32
    # words bernoulli() draws; 1/65536 probability granularity (~2e-5
    # keep-rate bias worst case) is far below dropout's statistical noise.
    bits = jax.random.bits(rng, jnp.shape(x), dtype=jnp.uint16)
    keep = bits < jnp.uint16(min(round((1.0 - p) * 65536.0), 65535))
    if impl == "upscale_in_train":
        y = jnp.where(keep, x / (1.0 - p), jnp.zeros((), x.dtype))
    else:
        y = jnp.where(keep, x, jnp.zeros((), x.dtype))
    return {"Out": [y], "Mask": [keep.astype(jnp.uint8)]}


@register_op("dropout_grad", no_grad=True)
def _dropout_grad(ins, attrs):
    """Mask-consuming backward (overrides the auto vjp derivation, which
    would re-run RngBitGenerator to rebuild the keep mask — measured ~40%
    of the transformer bench's dropout cost; the reference likewise feeds
    the saved mask to its grad kernel, dropout_op.cc DropoutGradKernel)."""
    g = _x(ins, "GRAD::Out")
    mask = _x(ins, "Mask")
    p = attrs.get("dropout_prob", 0.5)
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if attrs.get("is_test", False):
        dx = g if impl == "upscale_in_train" else g * (1.0 - p)
    elif p <= 0.0:  # forward was identity (no mask emitted)
        dx = g
    else:
        keep = mask.astype(jnp.bool_)
        gs = g / (1.0 - p) if impl == "upscale_in_train" else g
        dx = jnp.where(keep, gs, jnp.zeros((), g.dtype))
    return {"GRAD::X": [dx]}


@register_op("softmax")
def _softmax(ins, attrs):
    axis = attrs.get("axis", -1)
    return {"Out": [jax.nn.softmax(_x(ins), axis=axis)]}


@register_op("log_softmax")
def _log_softmax(ins, attrs):
    axis = attrs.get("axis", -1)
    return {"Out": [jax.nn.log_softmax(_x(ins), axis=axis)]}


@register_op("cross_entropy", diff_inputs=("X",))
def _cross_entropy(ins, attrs):
    x, label = _x(ins), _x(ins, "Label")
    eps = 1e-8
    ignore_index = attrs.get("ignore_index", -100)
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        if jnp.ndim(label) == jnp.ndim(x):
            label = jnp.squeeze(label, axis=-1)
        lbl = label.astype(jnp.int32)
        picked = jnp.take_along_axis(
            x, jnp.maximum(lbl, 0)[..., None], axis=-1
        )
        loss = -jnp.log(picked + eps)
        if ignore_index >= 0:
            keep = (lbl != ignore_index)[..., None]
            loss = loss * keep.astype(loss.dtype)
    return {"Y": [loss]}


@register_op("softmax_with_cross_entropy", diff_inputs=("Logits",))
def _softmax_with_cross_entropy(ins, attrs):
    logits, label = _x(ins, "Logits"), _x(ins, "Label")
    soft_label = attrs.get("soft_label", False)
    ignore_index = attrs.get("ignore_index", -100)
    # logsumexp over the vocab in >=f32 even when the logits stream is bf16
    # (AMP): the reduction is precision-sensitive, the cast fuses.
    logp = jax.nn.log_softmax(
        logits.astype(jnp.promote_types(logits.dtype, jnp.float32)), axis=-1)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        lbl = label
        if jnp.ndim(lbl) == jnp.ndim(logits):
            lbl = jnp.squeeze(lbl, axis=-1)
        lbl_i = lbl.astype(jnp.int32)
        picked = jnp.take_along_axis(logp, jnp.maximum(lbl_i, 0)[..., None], axis=-1)
        loss = -picked
        if ignore_index >= 0:
            mask = (lbl_i != ignore_index)[..., None]
            loss = loss * mask.astype(loss.dtype)
    return {"Softmax": [jnp.exp(logp)], "Loss": [loss]}


@register_op("sigmoid_cross_entropy_with_logits", diff_inputs=("X",))
def _sigmoid_ce(ins, attrs):
    x, label = _x(ins), _x(ins, "Label")
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return {"Out": [loss]}


@register_op("huber_loss", diff_inputs=("X",))
def _huber_loss(ins, attrs):
    x, y = _x(ins), _x(ins, "Y")
    delta = attrs.get("delta", 1.0)
    r = y - x
    a = jnp.abs(r)
    loss = jnp.where(a <= delta, 0.5 * r * r, delta * (a - 0.5 * delta))
    return {"Out": [loss], "Residual": [r]}


@register_op("square_error_cost", diff_inputs=("X", "Label"))
def _square_error_cost(ins, attrs):
    x, label = _x(ins), _x(ins, "Label")
    return {"Out": [jnp.square(x - label)]}


@register_op("smooth_l1_loss", diff_inputs=("X",))
def _smooth_l1(ins, attrs):
    x, y = _x(ins), _x(ins, "Y")
    w_in = _x(ins, "InsideWeight")
    w_out = _x(ins, "OutsideWeight")
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    d = x - y
    if w_in is not None:
        d = d * w_in
    a = jnp.abs(d)
    loss = jnp.where(a < 1.0 / s2, 0.5 * d * d * s2, a - 0.5 / s2)
    if w_out is not None:
        loss = loss * w_out
    return {"Out": [jnp.sum(loss, axis=-1, keepdims=True)], "Diff": [d]}


@register_op("accuracy", no_grad=True)
def _accuracy(ins, attrs):
    indices, label = _x(ins, "Indices"), _x(ins, "Label")
    if jnp.ndim(label) > 1:
        label = jnp.squeeze(label, axis=-1)
    correct = jnp.any(indices == label[:, None], axis=-1)
    num_correct = jnp.sum(correct.astype(jnp.float32))
    total = jnp.asarray(jnp.shape(indices)[0], jnp.float32)
    return {
        "Accuracy": [num_correct / total],
        "Correct": [num_correct.astype(jnp.int32)],
        "Total": [total.astype(jnp.int32)],
    }


@register_op("mean_iou", no_grad=True)
def _mean_iou(ins, attrs):
    pred, label = _x(ins, "Predictions"), _x(ins, "Labels")
    n = attrs["num_classes"]
    pred = pred.reshape(-1)
    label = label.reshape(-1)
    cm = jnp.zeros((n, n), jnp.float32).at[label, pred].add(1.0)
    inter = jnp.diag(cm)
    union = jnp.sum(cm, 0) + jnp.sum(cm, 1) - inter
    iou = inter / jnp.maximum(union, 1.0)
    valid = (union > 0).astype(jnp.float32)
    miou = jnp.sum(iou * valid) / jnp.maximum(jnp.sum(valid), 1.0)
    return {"OutMeanIou": [miou], "OutWrong": [], "OutCorrect": []}


@register_op("maxout", diff_inputs=("X",))
def _maxout(ins, attrs):
    x = _x(ins)  # [N, C, H, W]
    g = attrs["groups"]
    n, c, h, w = jnp.shape(x)
    return {"Out": [jnp.max(x.reshape(n, c // g, g, h, w), axis=2)]}


@register_op("label_smooth", diff_inputs=("X",))
def _label_smooth(ins, attrs):
    x = _x(ins)
    eps = attrs.get("epsilon", 0.1)
    k = jnp.shape(x)[-1]
    dist = ins.get("PriorDist")
    if dist and dist[0] is not None:
        return {"Out": [(1 - eps) * x + eps * dist[0]]}
    return {"Out": [(1 - eps) * x + eps / k]}


@register_op("prelu", diff_inputs=("X", "Alpha"))
def _prelu(ins, attrs):
    """out = x > 0 ? x : alpha * x; alpha shared per-op, per-channel, or
    per-element by `mode` (reference: operators/prelu_op.cc)."""
    x, alpha = _x(ins), _x(ins, "Alpha")
    mode = attrs.get("mode", "all")
    if mode == "channel":
        shape = [1] * jnp.ndim(x)
        shape[1] = -1
        alpha = jnp.reshape(alpha, shape)
    elif mode == "element":
        alpha = jnp.reshape(alpha, (1,) + tuple(jnp.shape(x)[1:]))
    else:
        alpha = jnp.reshape(alpha, ())
    return {"Out": [jnp.where(x > 0, x, alpha * x)]}


@register_op("group_norm", diff_inputs=("X", "Scale", "Bias"))
def _group_norm(ins, attrs):
    """Normalize over channel groups of an NCHW tensor
    (reference: operators/group_norm_op.cc)."""
    x = _x(ins)
    scale, bias = _x(ins, "Scale"), _x(ins, "Bias")
    g = attrs.get("groups", 1)
    eps = attrs.get("epsilon", 1e-5)
    n, c = jnp.shape(x)[0], jnp.shape(x)[1]
    spatial = tuple(jnp.shape(x)[2:])
    stat_dtype = jnp.promote_types(x.dtype, jnp.float32)
    xg = jnp.reshape(x.astype(stat_dtype), (n, g, c // g) + spatial)
    axes = tuple(range(2, jnp.ndim(xg)))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    y = (xg - mean) * jax.lax.rsqrt(var + eps)
    y = jnp.reshape(y, jnp.shape(x))
    pshape = [1, c] + [1] * len(spatial)
    if scale is not None:
        y = y * jnp.reshape(scale, pshape).astype(stat_dtype)
    if bias is not None:
        y = y + jnp.reshape(bias, pshape).astype(stat_dtype)
    return {
        "Y": [y.astype(x.dtype)],
        "Mean": [jax.lax.stop_gradient(jnp.reshape(mean, (n, g)))],
        "Variance": [jax.lax.stop_gradient(jnp.reshape(var, (n, g)))],
    }


@register_op(
    "sync_batch_norm",
    diff_inputs=("X", "Scale", "Bias"),
    inplace={"MeanOut": "Mean", "VarianceOut": "Variance"},
)
def _sync_batch_norm(ins, attrs):
    """Cross-device batch norm (reference: operators/sync_batch_norm_op.cu
    — NCCL all-reduce of per-GPU partial sums). TPU-native: the kernel is
    the ordinary batch_norm compute; under GSPMD data parallelism the
    batch axis is sharded, so ``jnp.mean`` over it ALREADY reduces across
    devices (XLA inserts the ICI all-reduce) — global statistics are the
    default, not an extra op."""
    return _batch_norm(ins, attrs)


@register_op("norm", diff_inputs=("X",))
def _norm(ins, attrs):
    """L2-normalize along axis (reference: operators/norm_op.cc)."""
    x = _x(ins)
    axis = attrs.get("axis", 1)
    eps = attrs.get("epsilon", 1e-10)
    n = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True) + eps)
    return {"Out": [x / n], "Norm": [n]}


@register_op("affine_channel", diff_inputs=("X", "Scale", "Bias"))
def _affine_channel(ins, attrs):
    """Per-channel scale+shift (reference: affine_channel_op.cc)."""
    x = _x(ins)
    scale, bias = _x(ins, "Scale"), _x(ins, "Bias")
    layout = attrs.get("data_layout", "NCHW")
    c_axis = 1 if layout == "NCHW" else jnp.ndim(x) - 1
    shape = [1] * jnp.ndim(x)
    shape[c_axis] = jnp.shape(x)[c_axis]
    return {"Out": [x * jnp.reshape(scale, shape) + jnp.reshape(bias, shape)]}


def _interp_out_size(attrs, h, w):
    """Output size resolution matching the reference's precedence
    (interpolate_op.cc: a positive ``scale`` attr WINS over out_h/out_w)."""
    scale = attrs.get("scale", 0.0)
    if scale and scale > 0:
        return int(h * scale), int(w * scale)
    out_h = int(attrs.get("out_h", 0) or 0)
    out_w = int(attrs.get("out_w", 0) or 0)
    return (out_h if out_h > 0 else int(h),
            out_w if out_w > 0 else int(w))


@register_op("bilinear_interp", diff_inputs=("X",))
def _bilinear_interp(ins, attrs):
    """NCHW bilinear resize (reference: operators/interpolate_op.cc).
    align_corners semantics follow the reference default (True)."""
    x = _x(ins)
    n, c, h, w = jnp.shape(x)
    out_h, out_w = _interp_out_size(attrs, h, w)
    align = attrs.get("align_corners", True)
    # align_corners=False splits further by align_mode (reference
    # interpolate_op.cc): mode 1 (the API default) samples src = i*scale,
    # mode 0 samples half-pixel centers
    mode = int(attrs.get("align_mode", 1))
    if align and out_h > 1:
        ys = jnp.linspace(0.0, h - 1.0, out_h)
    elif mode == 1:
        ys = jnp.arange(out_h) * (h / out_h)
    else:
        ys = (jnp.arange(out_h) + 0.5) * h / out_h - 0.5
    if align and out_w > 1:
        xs = jnp.linspace(0.0, w - 1.0, out_w)
    elif mode == 1:
        xs = jnp.arange(out_w) * (w / out_w)
    else:
        xs = (jnp.arange(out_w) + 0.5) * w / out_w - 0.5
    ys = jnp.clip(ys, 0, h - 1)
    xs = jnp.clip(xs, 0, w - 1)
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[None, None, :, None]
    wx = (xs - x0)[None, None, None, :]
    g = lambda yy, xx: x[:, :, yy, :][:, :, :, xx]
    out = (
        g(y0, x0) * (1 - wy) * (1 - wx)
        + g(y1, x0) * wy * (1 - wx)
        + g(y0, x1) * (1 - wy) * wx
        + g(y1, x1) * wy * wx
    )
    return {"Out": [out.astype(x.dtype)]}


@register_op("nearest_interp", diff_inputs=("X",))
def _nearest_interp(ins, attrs):
    """NCHW nearest-neighbor resize (reference: interpolate_op.cc)."""
    x = _x(ins)
    n, c, h, w = jnp.shape(x)
    out_h, out_w = _interp_out_size(attrs, h, w)
    align = attrs.get("align_corners", True)
    if align and out_h > 1:
        ys = jnp.round(jnp.linspace(0.0, h - 1.0, out_h)).astype(jnp.int32)
    else:
        ys = jnp.floor(jnp.arange(out_h) * h / out_h).astype(jnp.int32)
    if align and out_w > 1:
        xs = jnp.round(jnp.linspace(0.0, w - 1.0, out_w)).astype(jnp.int32)
    else:
        xs = jnp.floor(jnp.arange(out_w) * w / out_w).astype(jnp.int32)
    return {"Out": [x[:, :, ys, :][:, :, :, xs]]}


@register_op("row_conv", diff_inputs=("X", "Filter"))
def _row_conv(ins, attrs):
    """Lookahead row convolution over time (reference: row_conv_op.cc).
    X [B, T, D], Filter [future_len, D]."""
    x = _x(ins)
    f = _x(ins, "Filter")
    k = jnp.shape(f)[0]
    xp = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    out = sum(xp[:, i : i + jnp.shape(x)[1], :] * f[i][None, None, :]
              for i in range(k))
    return {"Out": [out]}


@register_op("temporal_shift", diff_inputs=("X",))
def _temporal_shift(ins, attrs):
    """Shift a fraction of channels across the segment (time) dim
    (reference: temporal_shift_op.cc). X [N*T, C, H, W]."""
    x = _x(ins)
    seg = int(attrs.get("seg_num", 1))
    ratio = attrs.get("shift_ratio", 0.25)
    nt, c, h, w = jnp.shape(x)
    n = nt // seg
    x5 = jnp.reshape(x, (n, seg, c, h, w))
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    fwd = jnp.pad(x5[:, 1:, :c1], ((0, 0), (0, 1), (0, 0), (0, 0), (0, 0)))
    bwd = jnp.pad(x5[:, :-1, c1:c2], ((0, 0), (1, 0), (0, 0), (0, 0), (0, 0)))
    keep = x5[:, :, c2:]
    out = jnp.concatenate([fwd, bwd, keep], axis=2)
    return {"Out": [jnp.reshape(out, (nt, c, h, w))]}


@register_op("grid_sampler", diff_inputs=("X", "Grid"))
def _grid_sampler(ins, attrs):
    """Bilinear sampling at normalized grid locations
    (reference: grid_sampler_op.cc). X [N,C,H,W], Grid [N,Ho,Wo,2] in
    [-1, 1]."""
    x = _x(ins)
    grid = _x(ins, "Grid")
    n, c, h, w = jnp.shape(x)
    gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0     # [N, Ho, Wo]
    gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx).astype(jnp.int32)
    y0 = jnp.floor(gy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1

    def sample(yy, xx):
        # out-of-bound corners contribute ZERO, matching the reference's
        # zero padding (grid_sampler_op.h) — not border clamping
        inb = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        yy = jnp.clip(yy, 0, h - 1)
        xx = jnp.clip(xx, 0, w - 1)
        bidx = jnp.arange(n)[:, None, None]
        vals = x[bidx, :, yy, xx]                  # [N, Ho, Wo, C]
        return vals * inb[..., None].astype(vals.dtype)

    wx = gx - x0
    wy = gy - y0
    out = (
        sample(y0, x0) * ((1 - wy) * (1 - wx))[..., None]
        + sample(y1, x0) * (wy * (1 - wx))[..., None]
        + sample(y0, x1) * ((1 - wy) * wx)[..., None]
        + sample(y1, x1) * (wy * wx)[..., None]
    )
    return {"Output": [jnp.transpose(out, (0, 3, 1, 2)).astype(x.dtype)]}


@register_op("auc", no_grad=True)
def _auc(ins, attrs):
    """Batch-local ROC-AUC via threshold buckets (reference:
    operators/metrics/auc_op.cc; streaming state lives in metrics.Auc)."""
    pred = _x(ins, "Predict")   # [N, 2] or [N, 1] prob of positive
    label = _x(ins, "Label")
    if jnp.ndim(label) > 1:
        label = jnp.squeeze(label, -1)
    p = pred[:, -1]
    buckets = int(attrs.get("num_thresholds", 200))
    idx = jnp.clip((p * buckets).astype(jnp.int32), 0, buckets - 1)
    pos = jnp.zeros((buckets,)).at[idx].add(label.astype(jnp.float32))
    neg = jnp.zeros((buckets,)).at[idx].add(1.0 - label.astype(jnp.float32))
    # integrate from the highest threshold down
    tp = jnp.cumsum(pos[::-1])
    fp = jnp.cumsum(neg[::-1])
    tot_pos = jnp.maximum(tp[-1], 1e-12)
    tot_neg = jnp.maximum(fp[-1], 1e-12)
    tpr = jnp.concatenate([jnp.zeros((1,)), tp / tot_pos])
    fpr = jnp.concatenate([jnp.zeros((1,)), fp / tot_neg])
    auc = jnp.sum((fpr[1:] - fpr[:-1]) * (tpr[1:] + tpr[:-1]) / 2.0)
    return {"AUC": [auc]}


@register_op("bilinear_tensor_product",
             diff_inputs=("X", "Y", "Weight", "Bias"))
def _bilinear_tensor_product(ins, attrs):
    """out[b, k] = x[b] @ W[k] @ y[b] + bias[k]
    (reference: operators/bilinear_tensor_product_op.cc)."""
    x, y = _x(ins), _x(ins, "Y")
    w = _x(ins, "Weight")                        # [K, Dx, Dy]
    bias = _x(ins, "Bias")
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if bias is not None:
        out = out + jnp.reshape(bias, (1, -1))
    return {"Out": [out]}


@register_op("nce", diff_inputs=("Input", "Weight", "Bias"), needs_rng=True)
def _nce(ins, attrs, rng=None):
    """Noise-contrastive estimation loss (reference: operators/nce_op.cc,
    uniform sampler). Avoids the full-vocab softmax: per example, score
    the true class plus ``num_neg_samples`` uniform negatives.

    inputs: Input [B, D], Label [B, 1] int, Weight [C, D], Bias [C] opt.
    outputs: Cost [B, 1].
    """
    x = ins["Input"][0]
    label = ins["Label"][0]
    if jnp.ndim(label) > 1:
        label = jnp.squeeze(label, -1)
    w = ins["Weight"][0]
    bias = _x(ins, "Bias")
    c = jnp.shape(w)[0]
    k = int(attrs.get("num_neg_samples", 10))
    b = jnp.shape(x)[0]

    neg = jax.random.randint(rng, (b, k), 0, c)          # uniform sampler
    ids = jnp.concatenate([label[:, None], neg], axis=1)  # [B, 1+K]
    w_sel = jnp.take(w, ids, axis=0)                      # [B, 1+K, D]
    logits = jnp.einsum("bd,bkd->bk", x, w_sel)
    if bias is not None:
        logits = logits + jnp.take(bias, ids)
    # NCE with uniform noise: q = k / C per class
    log_q = jnp.log(jnp.asarray(k, logits.dtype)) - jnp.log(
        jnp.asarray(c, logits.dtype))
    adj = logits - log_q
    pos = jax.nn.log_sigmoid(adj[:, 0])
    negs = jnp.sum(jax.nn.log_sigmoid(-adj[:, 1:]), axis=1)
    return {"Cost": [(-(pos + negs))[:, None]]}


@register_op("hierarchical_sigmoid", diff_inputs=("X", "W", "Bias"))
def _hierarchical_sigmoid(ins, attrs):
    """Binary-tree sigmoid classifier over log2(C) path nodes (reference:
    hsigmoid_op.cc with the default complete-tree SimpleCode: leaf code =
    label + C, ancestors are the code's bit-prefixes). X [b, d],
    W [C-1, d], Label [b, 1] or [b], Bias [C-1] optional ->
    Out [b, 1] cost, PreOut [b, max_len] (padded with zeros)."""
    x, w = _x(ins), _x(ins, "W")
    label = _x(ins, "Label")
    bias = _x(ins, "Bias")
    num_classes = int(attrs["num_classes"])
    if jnp.ndim(label) > 1:
        label = jnp.reshape(label, (-1,))
    code = label.astype(jnp.int32) + num_classes       # [b], in [C, 2C)
    # exact integer bit length (f32 log2 over-counts near 2^k boundaries
    # from C ~ 2^20, silently corrupting tree paths): count thresholds
    length = jnp.sum(
        (code[:, None] >= jnp.left_shift(
            jnp.int32(1), jnp.arange(31, dtype=jnp.int32))[None, :]
         ).astype(jnp.int32),
        axis=1,
    )
    path_len = length - 1                              # internal nodes
    max_len = int(num_classes).bit_length()
    pres, losses = [], []
    for j in range(max_len):
        # j-th step: ancestor = the (j+1)-bit prefix of the code minus 1
        # (root first), direction = the next bit (reference SimpleCode:
        # calc_index/calc_bit)
        bit_shift = path_len - 1 - j
        active = bit_shift >= 0
        safe = jnp.maximum(bit_shift, 0)
        node = jnp.right_shift(code, safe + 1) - 1     # [b] in [0, C-2]
        node = jnp.clip(node, 0, num_classes - 2)
        bit = jnp.bitwise_and(jnp.right_shift(code, safe), 1).astype(x.dtype)
        pre = jnp.sum(jnp.take(w, node, axis=0) * x, axis=-1)
        if bias is not None:
            pre = pre + jnp.take(jnp.reshape(bias, (-1,)), node)
        # per-node logistic loss: log(1+e^pre) - bit*pre
        lj = jax.nn.softplus(pre) - bit * pre
        mask = active.astype(x.dtype)
        pres.append(pre * mask)
        losses.append(lj * mask)
    out = sum(losses)[:, None]
    pre_out = jnp.stack(pres, axis=1)
    return {"Out": [out], "PreOut": [pre_out]}


@register_op("sample_logits", needs_rng=True,
             diff_inputs=("Logits",))
def _sample_logits(ins, attrs, rng=None):
    """Sampled-softmax helper (reference: sample_logits_op.cc): keep the
    true-label logits plus ``num_samples`` uniformly sampled classes,
    subtracting log(q) so softmax over the slice estimates the full one.
    Logits [b, C], Labels [b, T] -> Samples [b, T+S], Probabilities,
    SampledLogits [b, T+S], SampledLabel [b, T]."""
    logits = _x(ins, "Logits")
    labels = _x(ins, "Labels")
    s = int(attrs["num_samples"])
    remove_hits = bool(attrs.get("remove_accidental_hits", True))
    b, c = logits.shape
    t = labels.shape[1]
    labels = labels.astype(jnp.int32)
    sampled = jax.random.randint(rng, (b, s), 0, c, dtype=jnp.int32)
    samples = jnp.concatenate([labels, sampled], axis=1)   # [b, t+s]
    # uniform proposal: q = s / C per draw (with replacement)
    q = jnp.full((b, t + s), float(s) / c, logits.dtype)
    picked = jnp.take_along_axis(logits, samples, axis=1)
    adjusted = picked - jnp.log(q)
    if remove_hits:
        # a sampled class equal to the true label would double-count it
        hit = samples[:, None, t:] == labels[:, :, None]   # [b, t, s]
        hit_any = jnp.any(hit, axis=1)                     # [b, s]
        neg = jnp.asarray(-1e20, adjusted.dtype)
        adjusted = jnp.concatenate(
            [adjusted[:, :t],
             jnp.where(hit_any, neg, adjusted[:, t:])], axis=1)
    sampled_label = jnp.tile(jnp.arange(t, dtype=jnp.int64)[None], (b, 1))
    return {"Samples": [samples.astype(jnp.int64)],
            "Probabilities": [q],
            "SampledLogits": [adjusted],
            "SampledLabel": [sampled_label]}


@register_op("fc", diff_inputs=("Input", "W", "Bias"))
def _fc_fused(ins, attrs):
    """Fused fully-connected op — the rewrite target of the fc_fuse pass
    (reference: operators/fc_op.cc + framework/ir/fc_fuse_pass.cc:
    mul + elementwise_add collapse into one kernel). Mirrors the mul
    op's flatten semantics, then adds the bias on the output columns."""
    import math as _m

    x, w = ins["Input"][0], ins["W"][0]
    b_in = ins.get("Bias")
    b = b_in[0] if b_in else None
    xnc = int(attrs.get("in_num_col_dims", 1))
    xs, wsh = jnp.shape(x), jnp.shape(w)
    x2 = jnp.reshape(x, (_m.prod(xs[:xnc]), -1))
    out2 = x2 @ w
    if b is not None:
        out2 = out2 + jnp.reshape(b, (1, -1))
    return {"Out": [jnp.reshape(out2, xs[:xnc] + wsh[1:])]}
