"""Optimizer update ops.

Reference kernels: paddle/fluid/operators/optimizers/{sgd_op.cc,
momentum_op.cc, adam_op.cc, adagrad_op.cc, rmsprop_op.cc, lamb_op.cc,
ftrl_op.cc, lars_momentum_op.cc}. Updates are functional: the op outputs the
new parameter/accumulator values under the same variable names; the lowering
rebinds, and XLA's buffer donation makes it in-place in HBM.
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


def _g(ins, slot):
    v = ins.get(slot)
    return v[0] if v else None


@register_op("sgd", no_grad=True)
def _sgd(ins, attrs):
    p, g, lr = _g(ins, "Param"), _g(ins, "Grad"), _g(ins, "LearningRate")
    return {"ParamOut": [p - lr.reshape(()).astype(p.dtype) * g.astype(p.dtype)]}


@register_op("momentum", no_grad=True)
def _momentum(ins, attrs):
    p, g, v = _g(ins, "Param"), _g(ins, "Grad"), _g(ins, "Velocity")
    lr = _g(ins, "LearningRate").reshape(()).astype(p.dtype)
    mu = attrs.get("mu", 0.9)
    g = g.astype(p.dtype)
    v_new = mu * v + g
    if attrs.get("use_nesterov", False):
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    return {"ParamOut": [p_new], "VelocityOut": [v_new]}


@register_op("lars_momentum", no_grad=True)
def _lars_momentum(ins, attrs):
    p, g, v = _g(ins, "Param"), _g(ins, "Grad"), _g(ins, "Velocity")
    lr = _g(ins, "LearningRate").reshape(()).astype(p.dtype)
    mu = attrs.get("mu", 0.9)
    coeff = attrs.get("lars_coeff", 0.001)
    decay = attrs.get("lars_weight_decay", 0.0005)
    g = g.astype(p.dtype)
    pn = jnp.sqrt(jnp.sum(jnp.square(p)))
    gn = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (pn > 0) & (gn > 0), lr * coeff * pn / (gn + decay * pn + 1e-12), lr
    )
    v_new = mu * v + local_lr * (g + decay * p)
    return {"ParamOut": [p - v_new], "VelocityOut": [v_new]}


@register_op("adam", no_grad=True)
def _adam(ins, attrs):
    p, g = _g(ins, "Param"), _g(ins, "Grad")
    m1, m2 = _g(ins, "Moment1"), _g(ins, "Moment2")
    b1p, b2p = _g(ins, "Beta1Pow"), _g(ins, "Beta2Pow")
    lr = _g(ins, "LearningRate").reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    g = g.astype(m1.dtype)
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * jnp.square(g)
    b1pn, b2pn = b1p * b1, b2p * b2
    lr_t = lr * jnp.sqrt(1 - b2pn.reshape(())) / (1 - b1pn.reshape(()))
    upd = lr_t.astype(p.dtype) * (m1n / (jnp.sqrt(m2n) + eps)).astype(p.dtype)
    return {
        "ParamOut": [p - upd],
        "Moment1Out": [m1n],
        "Moment2Out": [m2n],
        "Beta1PowOut": [b1pn],
        "Beta2PowOut": [b2pn],
    }


@register_op("adamw", no_grad=True)
def _adamw(ins, attrs):
    p = _g(ins, "Param")
    wd = attrs.get("weight_decay", 0.01)
    lr = _g(ins, "LearningRate").reshape(()).astype(p.dtype)
    outs = _adam(ins, attrs)
    outs["ParamOut"][0] = outs["ParamOut"][0] - lr * wd * p
    return outs


@register_op("adagrad", no_grad=True)
def _adagrad(ins, attrs):
    p, g, m = _g(ins, "Param"), _g(ins, "Grad"), _g(ins, "Moment")
    lr = _g(ins, "LearningRate").reshape(()).astype(p.dtype)
    eps = attrs.get("epsilon", 1e-6)
    g = g.astype(p.dtype)
    m_new = m + jnp.square(g)
    p_new = p - lr * g / (jnp.sqrt(m_new) + eps)
    return {"ParamOut": [p_new], "MomentOut": [m_new]}


@register_op("rmsprop", no_grad=True)
def _rmsprop(ins, attrs):
    p, g = _g(ins, "Param"), _g(ins, "Grad")
    ms, mom = _g(ins, "MeanSquare"), _g(ins, "Moment")
    lr = _g(ins, "LearningRate").reshape(()).astype(p.dtype)
    rho = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    mu = attrs.get("momentum", 0.0)
    g = g.astype(p.dtype)
    ms_new = rho * ms + (1 - rho) * jnp.square(g)
    if attrs.get("centered", False):
        mg = _g(ins, "MeanGrad")
        mg_new = rho * mg + (1 - rho) * g
        denom = ms_new - jnp.square(mg_new) + eps
        mom_new = mu * mom + lr * g / jnp.sqrt(denom)
        return {
            "ParamOut": [p - mom_new],
            "MeanSquareOut": [ms_new],
            "MomentOut": [mom_new],
            "MeanGradOut": [mg_new],
        }
    mom_new = mu * mom + lr * g / jnp.sqrt(ms_new + eps)
    return {
        "ParamOut": [p - mom_new],
        "MeanSquareOut": [ms_new],
        "MomentOut": [mom_new],
    }


@register_op("lamb", no_grad=True)
def _lamb(ins, attrs):
    p, g = _g(ins, "Param"), _g(ins, "Grad")
    m1, m2 = _g(ins, "Moment1"), _g(ins, "Moment2")
    b1p, b2p = _g(ins, "Beta1Pow"), _g(ins, "Beta2Pow")
    lr = _g(ins, "LearningRate").reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    g = g.astype(m1.dtype)
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * jnp.square(g)
    mhat = m1n / (1 - b1p.reshape(()))
    vhat = m2n / (1 - b2p.reshape(()))
    r = mhat / (jnp.sqrt(vhat) + eps) + wd * p.astype(m1.dtype)
    pn = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
    rn = jnp.sqrt(jnp.sum(jnp.square(r.astype(jnp.float32))))
    trust = jnp.where((pn > 0) & (rn > 0), pn / rn, 1.0)
    p_new = p - (lr * trust).astype(p.dtype) * r.astype(p.dtype)
    return {
        "ParamOut": [p_new],
        "Moment1Out": [m1n],
        "Moment2Out": [m2n],
        "Beta1PowOut": [b1p * b1],
        "Beta2PowOut": [b2p * b2],
    }


@register_op("ftrl", no_grad=True)
def _ftrl(ins, attrs):
    p, g = _g(ins, "Param"), _g(ins, "Grad")
    sq, lin = _g(ins, "SquaredAccumulator"), _g(ins, "LinearAccumulator")
    lr = _g(ins, "LearningRate").reshape(()).astype(p.dtype)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    power = attrs.get("lr_power", -0.5)
    g = g.astype(p.dtype)
    sq_new = sq + jnp.square(g)
    sigma = (sq_new**-power - sq**-power) / lr
    lin_new = lin + g - sigma * p
    pre = jnp.clip(lin_new, -l1, l1) - lin_new
    denom = sq_new**-power / lr + 2 * l2
    p_new = pre / denom
    return {
        "ParamOut": [p_new],
        "SquaredAccumOut": [sq_new],
        "LinearAccumOut": [lin_new],
    }


@register_op("decayed_adagrad", no_grad=True)
def _decayed_adagrad(ins, attrs):
    p, g, m = _g(ins, "Param"), _g(ins, "Grad"), _g(ins, "Moment")
    lr = _g(ins, "LearningRate").reshape(()).astype(p.dtype)
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    g = g.astype(p.dtype)
    m_new = decay * m + (1 - decay) * jnp.square(g)
    return {"ParamOut": [p - lr * g / (jnp.sqrt(m_new) + eps)], "MomentOut": [m_new]}


@register_op("adamax", no_grad=True)
def _adamax(ins, attrs):
    """Adamax: Adam with an infinity-norm second moment (reference:
    operators/optimizers/adamax_op.cc; optimizer.py AdamaxOptimizer)."""
    p, g = _g(ins, "Param"), _g(ins, "Grad")
    m, u = _g(ins, "Moment"), _g(ins, "InfNorm")
    b1p = _g(ins, "Beta1Pow")
    lr = _g(ins, "LearningRate").reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    g = g.astype(m.dtype)
    m_new = b1 * m + (1 - b1) * g
    u_new = jnp.maximum(b2 * u, jnp.abs(g))
    b1pn = b1p * b1
    lr_t = (lr / (1 - b1pn.reshape(()))).astype(p.dtype)
    p_new = p - lr_t * (m_new / (u_new + eps)).astype(p.dtype)
    return {"ParamOut": [p_new], "MomentOut": [m_new],
            "InfNormOut": [u_new], "Beta1PowOut": [b1pn]}


@register_op("adadelta", no_grad=True)
def _adadelta(ins, attrs):
    """Adadelta (reference: operators/optimizers/adadelta_op.cc): the
    classic learning-rate-free update from accumulated squared grads and
    squared updates."""
    p, g = _g(ins, "Param"), _g(ins, "Grad")
    eg2, edx2 = _g(ins, "AvgSquaredGrad"), _g(ins, "AvgSquaredUpdate")
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    g = g.astype(p.dtype)
    eg2_new = rho * eg2 + (1 - rho) * jnp.square(g)
    upd = -jnp.sqrt((edx2 + eps) / (eg2_new + eps)) * g
    edx2_new = rho * edx2 + (1 - rho) * jnp.square(upd)
    return {"ParamOut": [p + upd], "AvgSquaredGradOut": [eg2_new],
            "AvgSquaredUpdateOut": [edx2_new]}


@register_op("proximal_gd", no_grad=True)
def _proximal_gd(ins, attrs):
    """Proximal gradient descent with l1/l2 regularization (reference:
    operators/optimizers/proximal_gd_op.cc)."""
    p, g = _g(ins, "Param"), _g(ins, "Grad")
    lr = _g(ins, "LearningRate").reshape(()).astype(p.dtype)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    prox = p - lr * g.astype(p.dtype)
    if l1 > 0:
        prox = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
    return {"ParamOut": [prox / (1.0 + lr * l2)]}


@register_op("proximal_adagrad", no_grad=True)
def _proximal_adagrad(ins, attrs):
    """Proximal Adagrad (reference:
    operators/optimizers/proximal_adagrad_op.cc)."""
    p, g, m = _g(ins, "Param"), _g(ins, "Grad"), _g(ins, "Moment")
    lr = _g(ins, "LearningRate").reshape(()).astype(p.dtype)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    g = g.astype(p.dtype)
    m_new = m + jnp.square(g)
    denom = jnp.sqrt(m_new)
    # zero-grad elements have zero moment on step one: their update is 0,
    # not lr*0/0 = NaN
    step = jnp.where(denom > 0, lr * g / jnp.maximum(denom, 1e-30), 0.0)
    prox = p - step
    # the reference applies the SCALAR learning rate in the l1 shrink and
    # l2 denominator (proximal_adagrad_op.h), not the adaptive rate
    if l1 > 0:
        prox = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
    return {"ParamOut": [prox / (1.0 + lr * l2)], "MomentOut": [m_new]}


@register_op("dgc_momentum", no_grad=True)
def _dgc_momentum(ins, attrs):
    """Fused DGC + momentum update (reference: operators/dgc_op.h
    compress stage + the momentum op that consumes the sparse-allreduced
    gradient; sparse_all_reduce_op_handle.h:30). One op instead of the
    reference's dgc -> sparse allreduce -> momentum chain: the compress /
    exchange / decode happens in paddle_tpu.parallel.dgc, and the
    decoded gradient immediately feeds the velocity update, all inside
    the same XLA program.

    When a data axis is in SPMD scope the (index, value) exchange runs
    as a real all_gather over that axis inside shard_map with
    combine='mean' — in the GSPMD whole-program path the incoming
    gradient is already globally reduced, so every worker sends the same
    selection and the mean restores the right magnitude. The
    sum-combining local-gradient form is exercised directly through
    parallel.dgc.dgc_step in a manually shard_mapped step."""
    import jax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.core import interp as _interp
    from paddle_tpu.parallel import dgc as _dgc

    p, g = _g(ins, "Param"), _g(ins, "Grad")
    u, v = _g(ins, "U"), _g(ins, "V")
    vel = _g(ins, "Velocity")
    lr = _g(ins, "LearningRate").reshape(()).astype(p.dtype)
    step = _g(ins, "CurrentStep").reshape(())
    mu = float(attrs.get("mu", 0.9))
    use_nesterov = bool(attrs.get("use_nesterov", False))
    sparsity = tuple(attrs.get("sparsity", (0.999,)))
    rampup_begin = float(attrs.get("rampup_begin_step", 0.0))
    rampup = float(attrs.get("rampup_step", 1.0))
    clip_norm = attrs.get("local_grad_clip_norm", None)

    g = g.astype(jnp.float32)
    if clip_norm is not None:
        g = _dgc.clip_by_norm_rampup(
            g, step, clip_norm=float(clip_norm),
            rampup_begin_step=rampup_begin)

    ctx = _interp.spmd_ctx()
    if ctx is not None and ctx.data_axis is not None:
        # composed (slice, dp) tuples gather over the product axis —
        # one exchange spanning DCN x ICI, like the 2-level allreduce
        axis = ctx.data_axis

        def _exchange(g_, u_, v_, step_):
            return _dgc.dgc_step(
                g_, u_, v_, step_, momentum=mu, sparsity=sparsity,
                rampup_begin_step=rampup_begin, rampup_step=rampup,
                use_nesterov=use_nesterov, axis=axis, combine="mean")

        # replicated in/out: the exchange is over the axis name only
        dec, u_new, v_new = jax.shard_map(
            _exchange, mesh=ctx.mesh,
            in_specs=(P(), P(), P(), P()),
            out_specs=(P(), P(), P()),
        )(g, u, v, step)
    else:
        dec, u_new, v_new = _dgc.dgc_step(
            g, u, v, step, momentum=mu, sparsity=sparsity,
            rampup_begin_step=rampup_begin, rampup_step=rampup,
            use_nesterov=use_nesterov, axis=None)

    dec = dec.astype(p.dtype)
    vel_new = mu * vel + dec
    if use_nesterov:
        p_new = p - (dec + mu * vel_new) * lr
    else:
        p_new = p - lr * vel_new
    return {"ParamOut": [p_new], "VelocityOut": [vel_new],
            "UOut": [u_new.astype(u.dtype)], "VOut": [v_new.astype(v.dtype)]}
