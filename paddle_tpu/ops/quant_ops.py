"""Quantization ops: the reference's fake-quant family plus the int8
convert pipeline.

Reference kernels: paddle/fluid/operators/fake_quantize_op.cc (abs_max,
channel_wise_abs_max, range_abs_max, moving_average_abs_max variants),
fake_dequantize_op.cc, and operators/{quantize,dequantize,requantize}_op.cc
(int8 convert). Training-time fake-quant ops use the straight-through
estimator baked into the expression (``x + sg(q(x) - x)``) so the auto
vjp yields identity gradients inside the clip range — the reference's
grad kernels do the same pass-through.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


def _x(ins, slot="X", i=0):
    v = ins.get(slot)
    return v[i] if v else None


def _qmax(attrs):
    bits = int(attrs.get("bit_length", attrs.get("bits", 8)))
    return float(2 ** (bits - 1) - 1)


def _ste(x, scale, qmax):
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax) * scale / qmax
    return x + jax.lax.stop_gradient(q - x)


@register_op("fake_quantize_abs_max", diff_inputs=("X",))
def _fake_quantize_abs_max(ins, attrs):
    x = _x(ins)
    qmax = _qmax(attrs)
    scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    return {"Out": [_ste(x, scale, qmax)], "OutScale": [scale.reshape(1)]}


@register_op("fake_channel_wise_quantize_abs_max", diff_inputs=("X",))
def _fake_channel_wise_quantize_abs_max(ins, attrs):
    """Per-output-channel scales (dim 0, the conv-filter convention)."""
    x = _x(ins)
    qmax = _qmax(attrs)
    flat = jnp.abs(x).reshape(x.shape[0], -1)
    scale = jnp.maximum(jnp.max(flat, axis=1), 1e-8)
    s = scale.reshape((-1,) + (1,) * (x.ndim - 1))
    return {"Out": [_ste(x, s, qmax)], "OutScale": [scale]}


@register_op("fake_quantize_range_abs_max", diff_inputs=("X",),
             inplace={"OutScales": "InScales"})
def _fake_quantize_range_abs_max(ins, attrs):
    """Sliding max over a window of per-step scales (reference:
    fake_quantize_op.cc range_abs_max): InScales is the rolling history
    buffer, Iter the step counter."""
    x = _x(ins)
    hist = _x(ins, "InScales")
    it = _x(ins, "Iter")
    qmax = _qmax(attrs)
    cur = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    if attrs.get("is_test", False):
        scale = jnp.maximum(jnp.max(hist), 1e-8)
        return {"Out": [_ste(x, scale, qmax)],
                "OutScale": [scale.reshape(1)],
                "OutScales": [hist], "IterOut": [it]}
    window = hist.shape[0]
    pos = (it.reshape(()).astype(jnp.int32)) % window
    hist = hist.at[pos].set(cur)
    scale = jnp.maximum(jnp.max(hist), 1e-8)
    return {"Out": [_ste(x, scale, qmax)], "OutScale": [scale.reshape(1)],
            "OutScales": [hist], "IterOut": [it + 1]}


@register_op("fake_quantize_moving_average_abs_max", diff_inputs=("X",),
             inplace={"OutState": "InState", "OutAccum": "InAccum"})
def _fake_quantize_moving_average_abs_max(ins, attrs):
    """EMA of abs-max (reference: fake_quantize_op.cc moving_average)."""
    x = _x(ins)
    state = _x(ins, "InState")
    accum = _x(ins, "InAccum")
    rate = float(attrs.get("moving_rate", 0.9))
    qmax = _qmax(attrs)
    cur = jnp.max(jnp.abs(x))
    if attrs.get("is_test", False):
        scale = jnp.maximum(accum.reshape(()) / state.reshape(()), 1e-8)
        return {"Out": [_ste(x, scale, qmax)],
                "OutScale": [scale.reshape(1)],
                "OutState": [state], "OutAccum": [accum]}
    state_n = rate * state.reshape(()) + 1.0
    accum_n = rate * accum.reshape(()) + cur
    scale = jnp.maximum(accum_n / state_n, 1e-8)
    return {"Out": [_ste(x, scale, qmax)], "OutScale": [scale.reshape(1)],
            "OutState": [state_n.reshape(1)], "OutAccum": [accum_n.reshape(1)]}


@register_op("moving_average_abs_max_scale", diff_inputs=("X",),
             inplace={"OutState": "InState", "OutAccum": "InAccum"})
def _moving_average_abs_max_scale(ins, attrs):
    """Scale observer only — passes X through untouched (reference:
    fake_quantize_op.cc MovingAverageAbsMaxScaleOp)."""
    x = _x(ins)
    state = _x(ins, "InState")
    accum = _x(ins, "InAccum")
    rate = float(attrs.get("moving_rate", 0.9))
    cur = jnp.max(jnp.abs(x))
    state_n = rate * state.reshape(()) + 1.0
    accum_n = rate * accum.reshape(()) + cur
    scale = jnp.maximum(accum_n / state_n, 1e-8)
    return {"Out": [x], "OutScale": [scale.reshape(1)],
            "OutState": [state_n.reshape(1)], "OutAccum": [accum_n.reshape(1)]}


@register_op("fake_dequantize_max_abs", diff_inputs=("X",))
def _fake_dequantize_max_abs(ins, attrs):
    x, scale = _x(ins), _x(ins, "Scale")
    qmax = float(attrs.get("max_range", _qmax(attrs)))
    return {"Out": [x.astype(jnp.float32) * scale.reshape(()) / qmax]}


@register_op("fake_channel_wise_dequantize_max_abs", diff_inputs=("X",))
def _fake_channel_wise_dequantize_max_abs(ins, attrs):
    x = _x(ins)
    scales = ins.get("Scales", [])
    qmax = _qmax(attrs)
    out = x.astype(jnp.float32)
    s0 = scales[0]
    out = out * s0.reshape((-1,) + (1,) * (x.ndim - 1)) / qmax
    if len(scales) > 1 and scales[1] is not None:
        out = out * scales[1].reshape(()) / qmax
    return {"Out": [out]}


@register_op("quantize", no_grad=True)
def _quantize(ins, attrs):
    """f32 -> int8 with a given scale (reference: quantize_op.cc)."""
    x = _x(ins, "Input")
    scale = float(attrs.get("Scale", 1.0))
    q = jnp.clip(jnp.round(x * scale), -128, 127).astype(jnp.int8)
    return {"Output": [q]}


@register_op("dequantize", no_grad=True)
def _dequantize(ins, attrs):
    x = _x(ins, "Input")
    scale = float(attrs.get("Scale", 1.0))
    return {"Output": [x.astype(jnp.float32) / scale]}


@register_op("requantize", no_grad=True)
def _requantize(ins, attrs):
    x = _x(ins, "Input")
    scale_in = float(attrs.get("Scale_in", 1.0))
    scale_out = float(attrs.get("Scale_out", 1.0))
    q = jnp.clip(jnp.round(x.astype(jnp.float32) * scale_out / scale_in),
                 -128, 127).astype(jnp.int8)
    return {"Output": [q]}


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             diff_inputs=("X",),
             inplace={"OutState": "InState", "OutAccum": "InAccum"})
def _fake_qdq_moving_average_abs_max(ins, attrs):
    """Quantize-dequantize with a moving-average scale in one op
    (reference: fake_quantize_op.cc
    FakeQuantizeDequantizeMovingAverageAbsMaxOp). Our moving-average
    quantize op already emits the dequantized STE value, so this is a
    registered alias of it."""
    outs = _fake_quantize_moving_average_abs_max(ins, attrs)
    x = _x(ins)
    outs["Out"] = [outs["Out"][0].astype(x.dtype)]  # _ste promotes via
    return outs                                     # the f32 scale


@register_op("quantize_dequantize_static", no_grad=True)
def _quantize_dequantize_static(ins, attrs):
    """Static-scale symmetric quantize-dequantize: the inference-time
    form of the fake-quant family where the scale is a CONSTANT baked
    by activation-range calibration (reference:
    quantization_pass.py:541 QuantizationFreezePass — scales collected
    from warmup data become attrs, no scale state vars). Serving
    numerics match int8 deployment while staying XLA-fusable fp32."""
    x = _x(ins)
    qmax = _qmax(attrs)
    scale = float(attrs.get("scale", 1.0)) or 1.0
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax)
    return {"Out": [q * (scale / qmax)]}
