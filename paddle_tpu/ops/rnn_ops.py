"""Fused recurrent ops: lstm, gru.

TPU-native redesign of the reference's recurrent operators
(reference: operators/lstm_op.cc, operators/gru_op.cc,
operators/math/lstm_compute.cc). The reference consumes a LoD tensor whose
rows are sorted/packed per time step; here the layout is a padded dense batch
``[B, T, ...]`` plus an optional ``Length [B]`` vector (SURVEY.md section 5).

Performance shape: the input-to-hidden projection (the big matmul, ``x @ Wx``
for all timesteps at once) is done OUTSIDE the op by an fc layer — one
``[B*T, D] x [D, 4H]`` MXU matmul — and the op itself scans only the
hidden-to-hidden recurrence (``h @ Wh``, unavoidable sequential part),
mirroring how the reference splits input projection out of lstm_op
(reference: python/paddle/fluid/layers/nn.py dynamic_lstm docs). The scan is
differentiable, so grads come from XLA's scan transpose.

Padding semantics: steps at or beyond a row's length propagate state
unchanged and emit zero outputs, matching LoD sequence termination.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.registry import register_op


def _act(name):
    return {
        "sigmoid": lambda x: jax.nn.sigmoid(x),
        "tanh": jnp.tanh,
        "relu": lambda x: jnp.maximum(x, 0),
        "identity": lambda x: x,
    }[name]


import jax  # noqa: E402  (used by _act closures)


def _opt(ins, slot):
    """Optional slot -> array or None (empty list and [None] both mean absent)."""
    vals = ins.get(slot)
    return vals[0] if vals else None


def _length_mask(ins, b, t, dtype):
    length = ins.get("Length")
    if not length or length[0] is None:
        return None
    ln = length[0]
    if jnp.ndim(ln) > 1:
        ln = jnp.squeeze(ln, -1)
    return (jnp.arange(t)[None, :] < ln[:, None]).astype(dtype)  # [B, T]


@register_op("lstm", diff_inputs=("Input", "Weight", "Bias", "H0", "C0"))
def _lstm(ins, attrs):
    """Fused LSTM over a projected input stream.

    inputs: Input [B,T,4H] (= x @ Wx + b, gate order i,f,c,o), Weight [H,4H]
    (hidden-to-hidden), Bias [4H] optional, H0/C0 [B,H] optional,
    Length [B] optional.
    outputs: Hidden [B,T,H], Cell [B,T,H], LastH [B,H], LastC [B,H].
    attrs: is_reverse, gate_activation, cell_activation,
    candidate_activation, forget_bias.
    """
    x = ins["Input"][0]
    w = ins["Weight"][0]
    bias = _opt(ins, "Bias")
    b_, t_, four_h = x.shape
    h_dim = four_h // 4
    h0 = _opt(ins, "H0")
    c0 = _opt(ins, "C0")
    if h0 is None:
        h0 = jnp.zeros((b_, h_dim), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((b_, h_dim), x.dtype)
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cell_act = _act(attrs.get("cell_activation", "tanh"))
    cand_act = _act(attrs.get("candidate_activation", "tanh"))
    forget_bias = attrs.get("forget_bias", 0.0)
    reverse = bool(attrs.get("is_reverse", False))

    mask = _length_mask(ins, b_, t_, x.dtype)  # [B,T] or None
    xt = jnp.swapaxes(x, 0, 1)  # [T,B,4H]
    if bias is not None:
        xt = xt + bias
    mt = jnp.swapaxes(mask, 0, 1)[..., None] if mask is not None else None

    def step(carry, inp):
        h_prev, c_prev = carry
        if mt is None:
            g, m = inp, None
        else:
            g, m = inp
        g = g + jnp.dot(h_prev, w)
        i, f, c_hat, o = jnp.split(g, 4, axis=-1)
        i = gate_act(i)
        f = gate_act(f + forget_bias)
        o = gate_act(o)
        c = f * c_prev + i * cand_act(c_hat)
        h = o * cell_act(c)
        if m is not None:
            c = m * c + (1 - m) * c_prev
            h_out = m * h
            h = m * h + (1 - m) * h_prev
        else:
            h_out = h
        return (h, c), (h_out, c)

    xs = xt if mt is None else (xt, mt)
    (h_last, c_last), (hs, cs) = lax.scan(
        step, (h0, c0), xs, reverse=reverse
    )
    hidden = jnp.swapaxes(hs, 0, 1)
    cell = jnp.swapaxes(cs, 0, 1)
    return {
        "Hidden": [hidden],
        "Cell": [cell],
        "LastH": [h_last],
        "LastC": [c_last],
    }


@register_op("gru", diff_inputs=("Input", "Weight", "Bias", "H0"))
def _gru(ins, attrs):
    """Fused GRU over a projected input stream.

    inputs: Input [B,T,3H] (= x @ Wx, gate order u,r,c), Weight [H,3H],
    Bias [3H] optional, H0 [B,H] optional, Length [B] optional.
    outputs: Hidden [B,T,H], LastH [B,H].
    attrs: is_reverse, gate_activation (u/r), activation (candidate).
    """
    x = ins["Input"][0]
    w = ins["Weight"][0]
    bias = _opt(ins, "Bias")
    b_, t_, three_h = x.shape
    h_dim = three_h // 3
    h0 = _opt(ins, "H0")
    if h0 is None:
        h0 = jnp.zeros((b_, h_dim), x.dtype)
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cand_act = _act(attrs.get("activation", "tanh"))
    reverse = bool(attrs.get("is_reverse", False))

    w_ur = w[:, : 2 * h_dim]  # [H, 2H]
    w_c = w[:, 2 * h_dim :]  # [H, H]
    mask = _length_mask(ins, b_, t_, x.dtype)
    xt = jnp.swapaxes(x, 0, 1)
    if bias is not None:
        xt = xt + bias
    mt = jnp.swapaxes(mask, 0, 1)[..., None] if mask is not None else None

    def step(carry, inp):
        h_prev = carry
        if mt is None:
            g, m = inp, None
        else:
            g, m = inp
        g_ur = g[..., : 2 * h_dim] + jnp.dot(h_prev, w_ur)
        u, r = jnp.split(gate_act(g_ur), 2, axis=-1)
        c = cand_act(g[..., 2 * h_dim :] + jnp.dot(r * h_prev, w_c))
        h = u * h_prev + (1 - u) * c
        if m is not None:
            h_out = m * h
            h = m * h + (1 - m) * h_prev
        else:
            h_out = h
        return h, h_out

    xs = xt if mt is None else (xt, mt)
    h_last, hs = lax.scan(step, h0, xs, reverse=reverse)
    return {"Hidden": [jnp.swapaxes(hs, 0, 1)], "LastH": [h_last]}


@register_op("gru_unit", diff_inputs=("Input", "HiddenPrev", "Weight", "Bias"))
def _gru_unit(ins, attrs):
    """One GRU step (reference: operators/gru_unit_op.cc).

    inputs: Input [B,3H] (x projection, gate order u,r,c),
    HiddenPrev [B,H], Weight [H,3H], Bias [3H] optional.
    outputs: Hidden [B,H], Gate [B,3H], ResetHiddenPrev [B,H].
    """
    x = ins["Input"][0]
    h_prev = ins["HiddenPrev"][0]
    w = ins["Weight"][0]
    bias = _opt(ins, "Bias")
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cand_act = _act(attrs.get("activation", "tanh"))
    hsz = jnp.shape(h_prev)[-1]
    if bias is not None:
        x = x + bias
    xu, xr, xc = x[:, :hsz], x[:, hsz : 2 * hsz], x[:, 2 * hsz :]
    wu, wr, wc = w[:, :hsz], w[:, hsz : 2 * hsz], w[:, 2 * hsz :]
    u = gate_act(xu + h_prev @ wu)
    r = gate_act(xr + h_prev @ wr)
    rh = r * h_prev
    c = cand_act(xc + rh @ wc)
    h = u * h_prev + (1.0 - u) * c
    gate = jnp.concatenate([u, r, c], axis=-1)
    return {"Hidden": [h], "Gate": [gate], "ResetHiddenPrev": [rh]}


@register_op("lstm_unit", diff_inputs=("X", "C_prev"))
def _lstm_unit(ins, attrs):
    """Single fused LSTM cell step on pre-projected gates (reference:
    lstm_unit_op.h, caffe2-derived (i, f, o, g) gate order: slot 2 is the
    OUTPUT gate, slot 3 the tanh candidate). X [b, 4d], C_prev [b, d]."""
    x, c_prev = ins["X"][0], ins["C_prev"][0]
    forget_bias = float(attrs.get("forget_bias", 0.0))
    d = c_prev.shape[-1]
    i, f, o, g = (x[:, :d], x[:, d:2 * d], x[:, 2 * d:3 * d], x[:, 3 * d:])
    c_new = (jax.nn.sigmoid(f + forget_bias) * c_prev
             + jax.nn.sigmoid(i) * jnp.tanh(g))
    h = jax.nn.sigmoid(o) * jnp.tanh(c_new)
    return {"C": [c_new], "H": [h]}


@register_op("lstmp", diff_inputs=("Input", "Weight", "ProjWeight", "Bias"))
def _lstmp(ins, attrs):
    """LSTM with a recurrent projection layer (reference: lstmp_op.cc).
    Input [b, t, 4d] pre-projected gate activations; Weight [p, 4d]
    recurrent weights over the projected state; ProjWeight [d, p]."""
    x = ins["Input"][0]
    w = ins["Weight"][0]
    w_proj = ins["ProjWeight"][0]
    b_in = ins.get("Bias")
    bias = b_in[0] if b_in else None
    b, t, d4 = x.shape
    d = d4 // 4
    p = w_proj.shape[1]
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cell_act = _act(attrs.get("cell_activation", "tanh"))
    cand_act = _act(attrs.get("candidate_activation", "tanh"))
    proj_act = _act(attrs.get("proj_activation", "tanh"))
    reverse = bool(attrs.get("is_reverse", False))

    def step(carry, xt):
        h_p, c = carry
        gates = xt + h_p @ w
        if bias is not None:
            gates = gates + bias.reshape(-1)[:d4]
        i = gate_act(gates[:, :d])
        f = gate_act(gates[:, d:2 * d])
        g = cand_act(gates[:, 2 * d:3 * d])
        o = gate_act(gates[:, 3 * d:])
        c_new = f * c + i * g
        h = o * cell_act(c_new)
        h_proj = proj_act(h @ w_proj)
        return (h_proj, c_new), (h_proj, c_new)

    h0 = jnp.zeros((b, p), x.dtype)
    c0 = jnp.zeros((b, d), x.dtype)
    (_, _), (hs, cs) = jax.lax.scan(step, (h0, c0), x.transpose(1, 0, 2),
                                    reverse=reverse)
    return {"Projection": [hs.transpose(1, 0, 2)],
            "Cell": [cs.transpose(1, 0, 2)]}
