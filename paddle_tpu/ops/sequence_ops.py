"""Sequence ops over padded/masked dense batches.

The reference's ~30 ``sequence_*`` ops operate on LoD tensors (ragged rows,
reference: framework/lod_tensor.h:58, operators/sequence_ops/*). XLA needs
static shapes, so the TPU-native representation is a padded dense batch
``[B, T, ...]`` plus either an int lengths vector ``[B]`` or a mask
``[B, T]`` (SURVEY.md section 5, "long-context"). These ops take the padded
tensor + Length input instead of LoD metadata.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


def _x(ins, slot="X", i=0):
    v = ins.get(slot)
    return v[i] if v else None


def _mask_from(ins, x):
    """[B, T] float mask from Length input, or all-ones."""
    length = _x(ins, "Length")
    t = jnp.shape(x)[1]
    if length is None:
        return jnp.ones(jnp.shape(x)[:2], jnp.float32)
    if jnp.ndim(length) > 1:
        length = jnp.squeeze(length, axis=-1)
    return (jnp.arange(t)[None, :] < length[:, None]).astype(jnp.float32)


@register_op("sequence_mask", no_grad=True)
def _sequence_mask(ins, attrs):
    length = _x(ins)
    maxlen = attrs.get("maxlen", -1)
    dtype = attrs.get("out_dtype", "float32")
    if maxlen < 0:
        raise ValueError(
            "sequence_mask on TPU needs a static maxlen (blocks are compiled "
            "with static shapes); pass maxlen= explicitly"
        )
    mask = jnp.arange(maxlen)[None, :] < length[:, None]
    return {"Y": [mask.astype(dtype)]}


@register_op("sequence_pool", diff_inputs=("X",))
def _sequence_pool(ins, attrs):
    x = _x(ins)  # [B, T, D]
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    mask = _mask_from(ins, x)[..., None].astype(x.dtype)
    if ptype in ("AVERAGE", "AVG"):
        s = jnp.sum(x * mask, axis=1)
        n = jnp.maximum(jnp.sum(mask, axis=1), 1.0)
        out = s / n
    elif ptype == "SUM":
        out = jnp.sum(x * mask, axis=1)
    elif ptype == "SQRT":
        s = jnp.sum(x * mask, axis=1)
        n = jnp.maximum(jnp.sum(mask, axis=1), 1.0)
        out = s / jnp.sqrt(n)
    elif ptype == "MAX":
        neg = jnp.finfo(x.dtype).min
        out = jnp.max(jnp.where(mask > 0, x, neg), axis=1)
    elif ptype == "LAST":
        length = _x(ins, "Length")
        if length is None:
            out = x[:, -1]
        else:
            if jnp.ndim(length) > 1:
                length = jnp.squeeze(length, -1)
            idx = jnp.maximum(length - 1, 0)
            out = jnp.take_along_axis(
                x, idx[:, None, None].astype(jnp.int32), axis=1
            )[:, 0]
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pooltype {ptype}")
    return {"Out": [out]}


@register_op("sequence_softmax", diff_inputs=("X",))
def _sequence_softmax(ins, attrs):
    x = _x(ins)  # [B, T]
    mask = _mask_from(ins, x)
    neg = jnp.finfo(x.dtype).min
    z = jnp.where(mask > 0, x, neg)
    return {"Out": [jax.nn.softmax(z, axis=-1) * mask.astype(x.dtype)]}


@register_op("sequence_reverse", diff_inputs=("X",))
def _sequence_reverse(ins, attrs):
    x = _x(ins)  # [B, T, ...]
    length = _x(ins, "Length")
    t = jnp.shape(x)[1]
    if length is None:
        return {"Y": [jnp.flip(x, axis=1)]}
    if jnp.ndim(length) > 1:
        length = jnp.squeeze(length, -1)
    idx = jnp.arange(t)[None, :]
    rev = jnp.where(idx < length[:, None], length[:, None] - 1 - idx, idx)
    return {"Y": [jnp.take_along_axis(x, rev.astype(jnp.int32).reshape(rev.shape + (1,) * (jnp.ndim(x) - 2)), axis=1)]}


@register_op("sequence_expand", diff_inputs=("X",))
def _sequence_expand(ins, attrs):
    # Broadcast per-sequence rows across time: X [B, D] -> [B, T, D].
    x, y = _x(ins), _x(ins, "Y")
    t = jnp.shape(y)[1]
    return {"Out": [jnp.broadcast_to(x[:, None, :], (jnp.shape(x)[0], t, jnp.shape(x)[1]))]}


@register_op("im2sequence", diff_inputs=("X",))
def _im2sequence(ins, attrs):
    x = _x(ins)  # [N, C, H, W]
    kernels = attrs.get("kernels", [1, 1])
    strides = attrs.get("strides", [1, 1])
    n, c, h, w = jnp.shape(x)
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=tuple(kernels), window_strides=tuple(strides),
        padding="VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )  # [N, C*kh*kw, OH, OW]
    ph, pw = jnp.shape(patches)[2], jnp.shape(patches)[3]
    out = jnp.transpose(patches, (0, 2, 3, 1)).reshape(n, ph * pw, -1)
    return {"Out": [out]}


@register_op("sequence_pad", no_grad=False, diff_inputs=("X",))
def _sequence_pad(ins, attrs):
    """Mask-out positions past Length with PadValue (reference:
    sequence_pad_op.cc — LoD->padded; here padded->cleanly-padded)."""
    x = _x(ins)
    pad = _x(ins, "PadValue")
    if pad is None:
        pad = jnp.zeros((), x.dtype)
    mask = _mask_from(ins, x)
    shape = jnp.shape(mask) + (1,) * (jnp.ndim(x) - 2)
    m = jnp.reshape(mask, shape).astype(x.dtype)
    # PadValue: scalar, shape-[1] tensor (the reference API's common
    # spelling), or a time-step-shaped tensor (sequence_pad_op.cc)
    if jnp.ndim(pad) and jnp.size(pad) == 1:
        pad = jnp.reshape(pad, ())
    if jnp.ndim(pad):
        pad = jnp.broadcast_to(pad, jnp.shape(x)[2:])
    out = x * m + pad.astype(x.dtype) * (1 - m)
    length = _x(ins, "Length")
    if length is None:
        length = jnp.full((jnp.shape(x)[0],), jnp.shape(x)[1], jnp.int64)
    return {"Out": [out], "OutLength": [length.astype(jnp.int64)]}


@register_op("sequence_unpad", diff_inputs=("X",))
def _sequence_unpad(ins, attrs):
    """Inverse of sequence_pad. Static shapes force the output to stay
    padded [B, T, ...]; dead positions are zeroed and Length carries the
    ragged structure (reference: sequence_unpad_op.cc)."""
    x = _x(ins)
    mask = _mask_from(ins, x)
    shape = jnp.shape(mask) + (1,) * (jnp.ndim(x) - 2)
    return {"Out": [x * jnp.reshape(mask, shape).astype(x.dtype)]}


@register_op("sequence_concat", diff_inputs=("X",))
def _sequence_concat(ins, attrs):
    """Per-row concatenation of live prefixes (reference:
    sequence_concat_op.cc concatenates LoD sequences row-wise).

    inputs: X (multi) [B, Ti, ...]; Length (multi, aligned) [B].
    outputs: Out [B, sum(Ti), ...], OutLength [B].
    """
    xs = ins["X"]
    lengths = ins.get("Length", [])
    b = jnp.shape(xs[0])[0]
    feat = jnp.shape(xs[0])[2:]
    t_tot = sum(jnp.shape(x)[1] for x in xs)
    out = jnp.zeros((b, t_tot + 1) + tuple(feat), xs[0].dtype)
    offset = jnp.zeros((b,), jnp.int32)
    total = jnp.zeros((b,), jnp.int64)
    rows = jnp.arange(b)[:, None]
    for i, x in enumerate(xs):
        t = jnp.shape(x)[1]
        ln = lengths[i] if i < len(lengths) and lengths[i] is not None \
            else jnp.full((b,), t)
        if jnp.ndim(ln) > 1:
            ln = jnp.squeeze(ln, -1)
        ln = ln.astype(jnp.int32)
        steps = jnp.arange(t)[None, :]
        live = steps < ln[:, None]
        # dead tokens write to the dump column t_tot
        pos = jnp.where(live, offset[:, None] + steps, t_tot)
        out = out.at[rows, pos].add(x)
        offset = offset + ln
        total = total + ln.astype(jnp.int64)
    return {"Out": [out[:, :t_tot]], "OutLength": [total]}


@register_op("sequence_slice", diff_inputs=("X",))
def _sequence_slice(ins, attrs):
    """Per-row subsequence [offset, offset+length) (reference:
    sequence_slice_op.cc). Output keeps the padded T; tail is zeroed."""
    x = _x(ins)
    off = _x(ins, "Offset")
    ln = _x(ins, "Length")
    if jnp.ndim(off) > 1:
        off = jnp.squeeze(off, -1)
    if jnp.ndim(ln) > 1:
        ln = jnp.squeeze(ln, -1)
    t = jnp.shape(x)[1]
    steps = jnp.arange(t)[None, :]
    src = jnp.clip(off[:, None].astype(jnp.int32) + steps, 0, t - 1)
    gathered = jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (jnp.ndim(x) - 2)), axis=1
    )
    live = steps < ln[:, None]
    m = live.reshape(live.shape + (1,) * (jnp.ndim(x) - 2))
    return {"Out": [gathered * m.astype(x.dtype)],
            "OutLength": [ln.astype(jnp.int64)]}


@register_op("sequence_erase", no_grad=True)
def _sequence_erase(ins, attrs):
    """Remove the given token values and compact left (reference:
    sequence_erase_op.cc). X [B, T] int; attr tokens: list of ints."""
    x = _x(ins)
    tokens = jnp.asarray(attrs.get("tokens", []), x.dtype)
    mask = _mask_from(ins, x[..., None]).astype(bool)
    keep = mask & ~jnp.isin(x, tokens)
    new_pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    t = jnp.shape(x)[1]
    out = jnp.zeros((jnp.shape(x)[0], t + 1), x.dtype)
    rows = jnp.arange(jnp.shape(x)[0])[:, None]
    pos = jnp.where(keep, new_pos, t)
    out = out.at[rows, pos].set(jnp.where(keep, x, 0))
    return {"Out": [out[:, :t]],
            "OutLength": [keep.sum(axis=1).astype(jnp.int64)]}


@register_op("sequence_enumerate", no_grad=True)
def _sequence_enumerate(ins, attrs):
    """Sliding windows of ids (reference: sequence_enumerate_op.cc).
    X [B, T] -> Out [B, T, win]; positions past a row's length (or the
    array edge) fill with pad_value."""
    x = _x(ins)
    win = int(attrs.get("win_size", 2))
    pad = attrs.get("pad_value", 0)
    b, t = jnp.shape(x)
    mask = _mask_from(ins, x[..., None]).astype(bool)      # [B, T]
    idx = jnp.arange(t)[None, :, None] + jnp.arange(win)[None, None, :]
    padded_mask = jnp.pad(mask, ((0, 0), (0, win)))        # [B, T+win]
    valid = (idx < t) & padded_mask[
        jnp.arange(b)[:, None, None], jnp.clip(idx, 0, t + win - 1)
    ]
    vals = x[jnp.arange(b)[:, None, None], jnp.clip(idx, 0, t - 1)]
    return {"Out": [jnp.where(valid, vals, pad)]}


@register_op("sequence_expand_as", diff_inputs=("X",))
def _sequence_expand_as(ins, attrs):
    """Broadcast each row's vector across Y's live time steps
    (reference: sequence_expand_as_op.cc). X [B, D], Y [B, T, ...]."""
    x = _x(ins)
    y = _x(ins, "Y")
    mask = _mask_from(ins, y)
    out = jnp.broadcast_to(
        x[:, None, :], (jnp.shape(x)[0], jnp.shape(y)[1], jnp.shape(x)[-1])
    )
    return {"Out": [out * mask[:, :, None].astype(out.dtype)]}


@register_op("sequence_conv", diff_inputs=("X", "Filter"))
def _sequence_conv(ins, attrs):
    """1-D context-window convolution over padded [b, t, d] sequences
    (reference: sequence_conv_op.cc; LoD rows become masked rows here).
    Filter [ctx_len * d, m]."""
    x, w = _x(ins), _x(ins, "Filter")
    ctx_len = int(attrs.get("contextLength", 3))
    ctx_start = int(attrs.get("contextStart", -(ctx_len // 2)))
    b, t, d = x.shape
    cols = []
    for i in range(ctx_len):
        off = ctx_start + i
        shifted = jnp.roll(x, -off, axis=1)
        if off < 0:
            mask = (jnp.arange(t) >= -off)[None, :, None]
        elif off > 0:
            mask = (jnp.arange(t) < t - off)[None, :, None]
        else:
            mask = jnp.ones((1, t, 1), bool)
        cols.append(jnp.where(mask, shifted, 0.0))
    im = jnp.concatenate(cols, axis=-1)          # [b, t, ctx_len*d]
    return {"Out": [im @ w]}


@register_op("sequence_reshape", diff_inputs=("X",))
def _sequence_reshape(ins, attrs):
    """Redistribute timesteps so the feature dim becomes new_dim
    (reference: sequence_reshape_op.cc)."""
    x = _x(ins)
    new_dim = int(attrs["new_dim"])
    b, t, d = x.shape
    return {"Out": [x.reshape(b, t * d // new_dim, new_dim)]}


@register_op("sequence_scatter", diff_inputs=("X", "Updates"))
def _sequence_scatter(ins, attrs):
    """Scatter per-sequence updates into X by in-row ids (reference:
    sequence_scatter_op.cc). X [b, d]; Ids [b, k]; Updates [b, k]."""
    x, ids, upd = _x(ins), _x(ins, "Ids"), _x(ins, "Updates")
    if ids.ndim == 3 and ids.shape[-1] == 1:
        ids = ids[..., 0]

    def one(row, ii, uu):
        return row.at[ii].add(uu)

    return {"Out": [jax.vmap(one)(x, ids.astype(jnp.int32), upd)]}


@register_op("add_position_encoding", diff_inputs=("X",))
def _add_position_encoding(ins, attrs):
    """alpha * x + beta * sinusoid(pos) (reference:
    add_position_encoding_op.cc)."""
    x = _x(ins)
    alpha = float(attrs.get("alpha", 1.0))
    beta = float(attrs.get("beta", 1.0))
    b, t, d = x.shape
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    i = jnp.arange(d // 2, dtype=jnp.float32)[None, :]
    angle = pos / jnp.power(10000.0, 2.0 * i / d)
    enc = jnp.zeros((t, d), jnp.float32)
    enc = enc.at[:, 0::2].set(jnp.sin(angle))
    enc = enc.at[:, 1::2].set(jnp.cos(angle))
    return {"Out": [alpha * x + beta * enc[None].astype(x.dtype)]}


@register_op("conv_shift", diff_inputs=("X", "Y"))
def _conv_shift(ins, attrs):
    """Circular convolution (reference: conv_shift_op.cc). X [b, n];
    Y [b, m] with m odd, m <= n."""
    x, y = _x(ins), _x(ins, "Y")
    b, n = x.shape
    m = y.shape[1]
    half = m // 2
    outs = []
    for j in range(m):
        outs.append(jnp.roll(x, half - j, axis=1) * y[:, j:j + 1])
    return {"Out": [sum(outs)]}
