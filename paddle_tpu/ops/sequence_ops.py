"""Sequence ops over padded/masked dense batches.

The reference's ~30 ``sequence_*`` ops operate on LoD tensors (ragged rows,
reference: framework/lod_tensor.h:58, operators/sequence_ops/*). XLA needs
static shapes, so the TPU-native representation is a padded dense batch
``[B, T, ...]`` plus either an int lengths vector ``[B]`` or a mask
``[B, T]`` (SURVEY.md section 5, "long-context"). These ops take the padded
tensor + Length input instead of LoD metadata.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


def _x(ins, slot="X", i=0):
    v = ins.get(slot)
    return v[i] if v else None


def _mask_from(ins, x):
    """[B, T] float mask from Length input, or all-ones."""
    length = _x(ins, "Length")
    t = jnp.shape(x)[1]
    if length is None:
        return jnp.ones(jnp.shape(x)[:2], jnp.float32)
    if jnp.ndim(length) > 1:
        length = jnp.squeeze(length, axis=-1)
    return (jnp.arange(t)[None, :] < length[:, None]).astype(jnp.float32)


@register_op("sequence_mask", no_grad=True)
def _sequence_mask(ins, attrs):
    length = _x(ins)
    maxlen = attrs.get("maxlen", -1)
    dtype = attrs.get("out_dtype", "float32")
    if maxlen < 0:
        raise ValueError(
            "sequence_mask on TPU needs a static maxlen (blocks are compiled "
            "with static shapes); pass maxlen= explicitly"
        )
    mask = jnp.arange(maxlen)[None, :] < length[:, None]
    return {"Y": [mask.astype(dtype)]}


@register_op("sequence_pool", diff_inputs=("X",))
def _sequence_pool(ins, attrs):
    x = _x(ins)  # [B, T, D]
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    mask = _mask_from(ins, x)[..., None].astype(x.dtype)
    if ptype in ("AVERAGE", "AVG"):
        s = jnp.sum(x * mask, axis=1)
        n = jnp.maximum(jnp.sum(mask, axis=1), 1.0)
        out = s / n
    elif ptype == "SUM":
        out = jnp.sum(x * mask, axis=1)
    elif ptype == "SQRT":
        s = jnp.sum(x * mask, axis=1)
        n = jnp.maximum(jnp.sum(mask, axis=1), 1.0)
        out = s / jnp.sqrt(n)
    elif ptype == "MAX":
        neg = jnp.finfo(x.dtype).min
        out = jnp.max(jnp.where(mask > 0, x, neg), axis=1)
    elif ptype == "LAST":
        length = _x(ins, "Length")
        if length is None:
            out = x[:, -1]
        else:
            if jnp.ndim(length) > 1:
                length = jnp.squeeze(length, -1)
            idx = jnp.maximum(length - 1, 0)
            out = jnp.take_along_axis(
                x, idx[:, None, None].astype(jnp.int32), axis=1
            )[:, 0]
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pooltype {ptype}")
    return {"Out": [out]}


@register_op("sequence_softmax", diff_inputs=("X",))
def _sequence_softmax(ins, attrs):
    x = _x(ins)  # [B, T]
    mask = _mask_from(ins, x)
    neg = jnp.finfo(x.dtype).min
    z = jnp.where(mask > 0, x, neg)
    return {"Out": [jax.nn.softmax(z, axis=-1) * mask.astype(x.dtype)]}


@register_op("sequence_reverse", diff_inputs=("X",))
def _sequence_reverse(ins, attrs):
    x = _x(ins)  # [B, T, ...]
    length = _x(ins, "Length")
    t = jnp.shape(x)[1]
    if length is None:
        return {"Y": [jnp.flip(x, axis=1)]}
    if jnp.ndim(length) > 1:
        length = jnp.squeeze(length, -1)
    idx = jnp.arange(t)[None, :]
    rev = jnp.where(idx < length[:, None], length[:, None] - 1 - idx, idx)
    return {"Y": [jnp.take_along_axis(x, rev.astype(jnp.int32).reshape(rev.shape + (1,) * (jnp.ndim(x) - 2)), axis=1)]}


@register_op("sequence_expand", diff_inputs=("X",))
def _sequence_expand(ins, attrs):
    # Broadcast per-sequence rows across time: X [B, D] -> [B, T, D].
    x, y = _x(ins), _x(ins, "Y")
    t = jnp.shape(y)[1]
    return {"Out": [jnp.broadcast_to(x[:, None, :], (jnp.shape(x)[0], t, jnp.shape(x)[1]))]}


@register_op("im2sequence", diff_inputs=("X",))
def _im2sequence(ins, attrs):
    x = _x(ins)  # [N, C, H, W]
    kernels = attrs.get("kernels", [1, 1])
    strides = attrs.get("strides", [1, 1])
    n, c, h, w = jnp.shape(x)
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=tuple(kernels), window_strides=tuple(strides),
        padding="VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )  # [N, C*kh*kw, OH, OW]
    ph, pw = jnp.shape(patches)[2], jnp.shape(patches)[3]
    out = jnp.transpose(patches, (0, 2, 3, 1)).reshape(n, ph * pw, -1)
    return {"Out": [out]}
