"""Serving-plane ops: slot-indexed KV-cache maintenance for the
continuous-batching decode path (serving.py, models/transformer.py
``build_decode_step``).

The reference framework serves autoregressive decode through per-request
LoDTensor caches rebuilt op-by-op (reference: operators/
tensor_array_read_write_op.cc driving the while-loop NMT decoder); here
the cache is ONE dense device-resident tensor shared by every in-flight
request — axis 0 is the batch *slot*, axis 1 the time position — so a
single compiled single-token decode program serves a mixed bag of
requests at different positions. Per-slot positions make the existing
``dynamic_update`` (scalar index) insufficient: these ops take a
``Pos [S]`` vector and scatter/mask per slot.
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.registry import register_op

NEG_INF = -1e9


@register_op("kv_cache_write", no_grad=True)
def _kv_cache_write(ins, attrs):
    """Write this step's K/V rows into the slot-indexed cache.

    inputs:
      Cache [S, T, ...]  — the persistable KV ring (slot-major)
      New   [S, 1, ...]  — the freshly projected per-slot row
      Pos   [S] int      — per-slot write position (clipped to T-1, so a
                           frozen/dead slot rewriting its last position
                           stays in bounds)
    output: Out [S, T, ...] — cache with ``Out[s, Pos[s]] = New[s, 0]``.
    """
    cache = ins["Cache"][0]
    new = ins["New"][0]
    pos = ins["Pos"][0].astype(jnp.int32)
    t = cache.shape[1]
    pos = jnp.clip(pos, 0, t - 1)
    s = cache.shape[0]
    out = cache.at[jnp.arange(s), pos].set(
        jnp.squeeze(new, axis=1).astype(cache.dtype))
    return {"Out": [out]}


@register_op("kv_step_bias", no_grad=True)
def _kv_step_bias(ins, attrs):
    """Per-slot additive attention bias over the KV cache: position j of
    slot s is visible iff ``j <= Pos[s]`` (the causal prefix each
    request has actually written; stale rows from a previous occupant of
    the slot sit above ``Pos`` and stay masked).

    inputs: Pos [S] int; attrs: length (the cache's T axis).
    output: Out [S, 1, 1, T] float32 — 0 where visible, -1e9 elsewhere,
    broadcastable against sdpa's [S, h, tq, T] logits like the pad
    biases the training graph feeds.
    """
    pos = ins["Pos"][0].astype(jnp.int32)
    t = int(attrs["length"])
    vis = jnp.arange(t, dtype=jnp.int32)[None, :] <= pos[:, None]  # [S, T]
    bias = jnp.where(vis, 0.0, NEG_INF).astype(jnp.float32)
    return {"Out": [bias[:, None, None, :]]}
