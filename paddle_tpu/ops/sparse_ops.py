"""Row-sparse gradients — the TPU-native SelectedRows equivalent.

The reference's lookup_table grad is a SelectedRows {rows, values} pair and
its optimizers apply row-wise updates (reference: framework/selected_rows.h:32,
operators/lookup_table_op.cc grad kernel, math/selected_rows_functor.cc
MergeAdd, optimizers/adam_op.h lazy mode). Here the pair is two ordinary
IR variables (``{W}@GRAD@ROWS`` int32, ``{W}@GRAD@VALUES`` [n, D]) produced
by ``lookup_table_sparse_grad`` when the embedding is built with
``is_sparse=True``; sparse optimizer ops consume them and update ONLY the
touched rows with XLA scatters into the donated parameter buffer — the
dense [V, D] gradient never exists in HBM, which is the point for CTR-scale
vocabularies.

Static-shape discipline (XLA): duplicate ids are NOT deduped by resizing.
``_merge_rows`` sorts ids, segment-sums duplicate rows' values into their
first slot, and marks the other slots with an out-of-range sentinel row that
``mode='drop'`` scatters ignore — the reference's MergeAdd with fixed
shapes. Linear updates (plain SGD) skip the merge: scatter-add over
duplicates is already correct.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


def _g(ins, slot):
    v = ins.get(slot)
    return v[0] if v else None


@register_op("lookup_table_sparse_grad", no_grad=True)
def _lookup_table_sparse_grad(ins, attrs):
    """(Ids, dOut) -> (Rows [n] int32, Values [n, D]).

    Padding rows get the ``vocab_size`` sentinel (dropped by the sparse
    optimizer scatters), mirroring the dense path's padding_idx zeroing.
    """
    ids, g = _g(ins, "Ids"), _g(ins, "GRAD::Out")
    vocab = int(attrs["vocab_size"])
    squeeze_last = attrs.get(
        "squeeze_last", jnp.ndim(ids) > 1 and jnp.shape(ids)[-1] == 1
    )
    if squeeze_last:
        ids = jnp.squeeze(ids, axis=-1)
    rows = jnp.reshape(ids, (-1,)).astype(jnp.int32)
    rows = jnp.where(rows < 0, rows + vocab, rows)
    d = jnp.shape(g)[-1]
    values = jnp.reshape(g, (-1, d))
    padding_idx = attrs.get("padding_idx", None)
    if padding_idx is not None:
        if padding_idx < 0:
            padding_idx = vocab + padding_idx
        rows = jnp.where(rows == padding_idx, vocab, rows)
    return {"Rows": [rows], "Values": [values]}


def _merge_rows(rows, values, vocab):
    """Sum duplicate rows' values into one slot each (reference:
    math/selected_rows_functor.cc MergeAdd), keeping [n] static shapes:
    non-first duplicate slots get the ``vocab`` sentinel row and zero
    values, so drop-mode scatters skip them."""
    order = jnp.argsort(rows)
    r = rows[order]
    v = values[order]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), r[1:] != r[:-1]]
    )
    seg = jnp.cumsum(first) - 1                      # [n] segment index
    merged_v = jax.ops.segment_sum(v, seg, num_segments=rows.shape[0])
    merged_r = jnp.full_like(r, vocab)
    merged_r = merged_r.at[seg].set(r)               # same id per segment
    # sentinel rows (slots past the last segment, or padding already at
    # ``vocab``) are dropped by the consumer's scatter
    return merged_r, merged_v


@register_op("sgd_sparse", no_grad=True)
def _sgd_sparse(ins, attrs):
    p = _g(ins, "Param")
    rows, values = _g(ins, "Rows"), _g(ins, "Values")
    lr = _g(ins, "LearningRate").reshape(()).astype(p.dtype)
    # linear update: scatter-add over duplicate rows is already the sum
    upd = (-lr) * values.astype(p.dtype)
    return {"ParamOut": [p.at[rows].add(upd, mode="drop")]}


@register_op("momentum_sparse", no_grad=True)
def _momentum_sparse(ins, attrs):
    p, v = _g(ins, "Param"), _g(ins, "Velocity")
    rows, values = _g(ins, "Rows"), _g(ins, "Values")
    lr = _g(ins, "LearningRate").reshape(()).astype(p.dtype)
    mu = attrs.get("mu", 0.9)
    vocab = p.shape[0]
    rows_m, g_m = _merge_rows(rows, values.astype(p.dtype), vocab)
    safe = jnp.clip(rows_m, 0, vocab - 1)
    v_rows = mu * v[safe] + g_m
    v_new = v.at[rows_m].set(v_rows, mode="drop")
    if attrs.get("use_nesterov", False):
        step = (g_m + mu * v_rows) * lr
    else:
        step = lr * v_rows
    p_new = p.at[rows_m].add(-step, mode="drop")
    return {"ParamOut": [p_new], "VelocityOut": [v_new]}


@register_op("adam_sparse", no_grad=True)
def _adam_sparse(ins, attrs):
    """Lazy Adam on the touched rows only (reference: adam_op.h lazy_mode;
    Paddle's LazyAdam semantics — untouched rows' moments do not decay)."""
    p = _g(ins, "Param")
    m1, m2 = _g(ins, "Moment1"), _g(ins, "Moment2")
    b1p, b2p = _g(ins, "Beta1Pow"), _g(ins, "Beta2Pow")
    rows, values = _g(ins, "Rows"), _g(ins, "Values")
    lr = _g(ins, "LearningRate").reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    vocab = p.shape[0]
    rows_m, g_m = _merge_rows(rows, values.astype(m1.dtype), vocab)
    safe = jnp.clip(rows_m, 0, vocab - 1)
    m1_r = b1 * m1[safe] + (1 - b1) * g_m
    m2_r = b2 * m2[safe] + (1 - b2) * jnp.square(g_m)
    b1pn, b2pn = b1p * b1, b2p * b2
    lr_t = lr * jnp.sqrt(1 - b2pn.reshape(())) / (1 - b1pn.reshape(()))
    upd = lr_t.astype(p.dtype) * (
        m1_r / (jnp.sqrt(m2_r) + eps)
    ).astype(p.dtype)
    return {
        "ParamOut": [p.at[rows_m].add(-upd, mode="drop")],
        "Moment1Out": [m1.at[rows_m].set(m1_r, mode="drop")],
        "Moment2Out": [m2.at[rows_m].set(m2_r, mode="drop")],
        "Beta1PowOut": [b1pn],
        "Beta2PowOut": [b2pn],
    }
