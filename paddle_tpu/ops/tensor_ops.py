"""Tensor creation & manipulation ops.

Reference kernels: paddle/fluid/operators/{fill_constant_op.cc,
gaussian_random_op.cc, uniform_random_op.cc, reshape_op.cc, transpose_op.cc,
concat_op.cc, split_op.cc, slice_op.cc, stack_op.cc, squeeze_op.cc,
unsqueeze_op.cc, expand_op.cc, gather_op.cc, one_hot_op.cc,
lookup_table_op.cc, top_k_op.cc, arg_max_op.cc, assign_op.cc}.

RNG ops are stateless-keyed (Philox-style jax PRNG folded per-op and
per-step by the lowering), replacing the reference's stateful per-op seeds
(SURVEY.md section 7 hard part 6).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op


def _x(ins, slot="X", i=0):
    return ins[slot][i]


@register_op("fill_constant", no_grad=True)
def _fill_constant(ins, attrs):
    shape = tuple(attrs.get("shape", []))
    dtype = attrs.get("dtype", "float32")
    value = attrs.get("value", 0.0)
    return {"Out": [jnp.full(shape, value, dtype=dtype)]}


@register_op("fill_zeros_like", no_grad=True)
def _fill_zeros_like(ins, attrs):
    return {"Out": [jnp.zeros_like(_x(ins))]}


@register_op("fill_any_like", no_grad=True)
def _fill_any_like(ins, attrs):
    return {"Out": [jnp.full_like(_x(ins), attrs.get("value", 0.0))]}


@register_op("gaussian_random", no_grad=True, needs_rng=True)
def _gaussian_random(ins, attrs, rng=None):
    shape = tuple(attrs["shape"])
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    dtype = attrs.get("dtype", "float32")
    return {"Out": [mean + std * jax.random.normal(rng, shape, dtype=dtype)]}


@register_op("uniform_random", no_grad=True, needs_rng=True)
def _uniform_random(ins, attrs, rng=None):
    shape = tuple(attrs["shape"])
    lo = attrs.get("min", -1.0)
    hi = attrs.get("max", 1.0)
    dtype = attrs.get("dtype", "float32")
    return {"Out": [jax.random.uniform(rng, shape, dtype=dtype, minval=lo, maxval=hi)]}


@register_op("truncated_gaussian_random", no_grad=True, needs_rng=True)
def _truncated_gaussian_random(ins, attrs, rng=None):
    shape = tuple(attrs["shape"])
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    dtype = attrs.get("dtype", "float32")
    x = jax.random.truncated_normal(rng, -2.0, 2.0, shape, dtype=dtype)
    return {"Out": [mean + std * x]}


@register_op("assign")
def _assign(ins, attrs):
    return {"Out": [_x(ins)]}


@register_op("assign_value", no_grad=True)
def _assign_value(ins, attrs):
    shape = tuple(attrs["shape"])
    dtype = attrs.get("dtype", "float32")
    vals = np.asarray(attrs["values"], dtype=np.float64)
    return {"Out": [jnp.asarray(vals.reshape(shape)).astype(dtype)]}


@register_op("shape", no_grad=True)
def _shape(ins, attrs):
    return {"Out": [jnp.asarray(jnp.shape(_x(ins)), dtype=jnp.int64)]}


@register_op("reshape2")
def _reshape2(ins, attrs):
    x = _x(ins)
    # Reference semantics: 0 copies the input dim, -1 infers (reshape_op.cc).
    shape = [
        jnp.shape(x)[i] if d == 0 else d for i, d in enumerate(attrs["shape"])
    ]
    return {"Out": [jnp.reshape(x, shape)], "XShape": []}


@register_op("transpose2")
def _transpose2(ins, attrs):
    return {"Out": [jnp.transpose(_x(ins), attrs["axis"])], "XShape": []}


@register_op("flatten2")
def _flatten2(ins, attrs):
    import math

    x = _x(ins)
    axis = attrs.get("axis", 1)
    s = jnp.shape(x)
    return {
        "Out": [jnp.reshape(x, (math.prod(s[:axis]) if axis else 1, -1))],
        "XShape": [],
    }


@register_op("concat")
def _concat(ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))]}


@register_op("split")
def _split(ins, attrs):
    x = _x(ins)
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = attrs.get("sections", [])
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        parts = jnp.split(x, idx, axis=axis)
    else:
        parts = jnp.split(x, num, axis=axis)
    return {"Out": list(parts)}


@register_op("slice")
def _slice(ins, attrs):
    x = _x(ins)
    axes = attrs["axes"]
    starts = attrs["starts"]
    ends = attrs["ends"]
    idx = [slice(None)] * jnp.ndim(x)
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = slice(st, en)
    return {"Out": [x[tuple(idx)]]}


@register_op("stack")
def _stack(ins, attrs):
    return {"Out": [jnp.stack(ins["X"], axis=attrs.get("axis", 0))]}


@register_op("unstack")
def _unstack(ins, attrs):
    x = _x(ins)
    axis = attrs.get("axis", 0)
    num = jnp.shape(x)[axis]
    parts = [jnp.squeeze(p, axis=axis) for p in jnp.split(x, num, axis=axis)]
    return {"Y": parts}


@register_op("squeeze2")
def _squeeze2(ins, attrs):
    axes = tuple(attrs.get("axes", []))
    x = _x(ins)
    return {"Out": [jnp.squeeze(x, axis=axes or None)], "XShape": []}


@register_op("unsqueeze2")
def _unsqueeze2(ins, attrs):
    x = _x(ins)
    for ax in sorted(attrs["axes"]):
        x = jnp.expand_dims(x, ax)
    return {"Out": [x], "XShape": []}


@register_op("expand")
def _expand(ins, attrs):
    x = _x(ins)
    times = attrs["expand_times"]
    return {"Out": [jnp.tile(x, times)]}


@register_op("expand_as")
def _expand_as(ins, attrs):
    x, y = _x(ins), _x(ins, "Y")
    return {"Out": [jnp.broadcast_to(x, jnp.shape(y))]}


@register_op("gather", diff_inputs=("X",))
def _gather(ins, attrs):
    x, index = _x(ins), _x(ins, "Index")
    axis = attrs.get("axis", 0)
    return {"Out": [jnp.take(x, index, axis=axis)]}


@register_op("scatter", diff_inputs=("X", "Updates"))
def _scatter(ins, attrs):
    x, ids, updates = _x(ins), _x(ins, "Ids"), _x(ins, "Updates")
    if attrs.get("overwrite", True):
        return {"Out": [x.at[ids].set(updates)]}
    return {"Out": [x.at[ids].add(updates)]}


@register_op("one_hot", no_grad=True)
def _one_hot(ins, attrs):
    x = _x(ins)
    depth = attrs["depth"]
    if jnp.ndim(x) > 1 and jnp.shape(x)[-1] == 1:
        x = jnp.squeeze(x, axis=-1)
    return {"Out": [jax.nn.one_hot(x, depth, dtype=attrs.get("dtype", "float32"))]}


def _lookup_table_grad_maker(op, block, out_grads, provide, should_skip):
    """Emit the row-sparse grad pair when the layer asked for
    ``is_sparse=True`` (the SelectedRows capability, reference:
    lookup_table_op.cc grad -> SelectedRows); dense lookups defer to the
    generic auto-vjp grad emitter (return None). The sparse pair is two IR
    vars named ``{W}@GRAD@ROWS`` / ``{W}@GRAD@VALUES``; the ``{W}@GRAD``
    variable itself becomes a never-materialized marker carrying
    ``is_selected_rows`` so the optimizer dispatches to its sparse op."""
    from paddle_tpu.core.registry import get_op_def

    if not op.attrs.get("is_sparse", False):
        return None  # generic dense path
    w = op.inputs["W"][0]
    g_out = (out_grads.get("Out") or [""])[0]
    if not g_out:
        return []
    opdef = get_op_def("lookup_table")
    if should_skip(w, "W", opdef):
        return []
    src = block._find_var_recursive(w)
    gname = provide(w)
    if "@RENAME@" in gname:
        raise ValueError(
            f"lookup_table(is_sparse=True): table '{w}' is consumed by "
            f"multiple lookups in the backward path; the row-sparse "
            f"gradient pair cannot be summed. Use is_sparse=False for "
            f"shared tables."
        )
    gv = block.create_var(name=gname, shape=src.shape if src else None,
                          dtype=src.dtype if src else "float32")
    rows_name, values_name = gname + "@ROWS", gname + "@VALUES"
    block.create_var(name=rows_name, dtype="int32")
    block.create_var(name=values_name,
                     dtype=src.dtype if src else "float32")
    gv.is_selected_rows = True
    gv.sparse_rows_name = rows_name
    gv.sparse_values_name = values_name
    attrs = {"vocab_size": int(src.shape[0])}
    # mirror the forward's squeeze behavior exactly (dynamic default when
    # the layer didn't pin it)
    if "squeeze_last" in op.attrs:
        attrs["squeeze_last"] = op.attrs["squeeze_last"]
    if "padding_idx" in op.attrs:
        attrs["padding_idx"] = op.attrs["padding_idx"]
    return [dict(
        type="lookup_table_sparse_grad",
        inputs={"Ids": list(op.inputs["Ids"]), "GRAD::Out": [g_out]},
        outputs={"Rows": [rows_name], "Values": [values_name]},
        attrs=attrs,
    )]


@register_op("lookup_table", diff_inputs=("W",),
             grad_maker=_lookup_table_grad_maker,
             doc="embedding lookup; grad is a dense XLA scatter-add, or a "
                 "row-sparse {rows, values} pair under is_sparse=True "
                 "(the reference's SelectedRows, lookup_table_op.cc)")
def _lookup_table(ins, attrs):
    w, ids = _x(ins, "W"), _x(ins, "Ids")
    # [N, 1] column-ids convention: squeeze unless the layer says the ids
    # are already a padded [b, t] batch (a [b, 1] batch is ambiguous).
    squeeze_last = attrs.get(
        "squeeze_last", jnp.ndim(ids) > 1 and jnp.shape(ids)[-1] == 1
    )
    if squeeze_last:
        ids = jnp.squeeze(ids, axis=-1)
    # Reference semantics: kNoPadding when absent; negative = vocab + idx
    # (lookup_table_op.cc). The layer omits the attr when padding is off.
    padding_idx = attrs.get("padding_idx", None)
    out = None
    if attrs.get("is_distributed", False):
        # Row-sharded table (replaces the reference's pserver-distributed
        # lookup table + RPC prefetch, parameter_prefetch.cc): each shard
        # gathers its local rows, psum over ICI combines. Only active when
        # the program runs under a strategy declaring a table axis.
        from paddle_tpu.core.interp import spmd_ctx

        ctx = spmd_ctx()
        if ctx is not None:
            mesh, table_axis, data_axis = ctx.mesh, ctx.table_axis, ctx.data_axis
            if table_axis is not None and (
                jnp.shape(w)[0] % mesh.shape[table_axis] == 0
            ):
                from paddle_tpu.parallel.embedding import (
                    sharded_embedding_lookup,
                )

                out = sharded_embedding_lookup(
                    w, ids, mesh, shard_axis=table_axis,
                    data_axis=data_axis,
                )
    if out is None:
        out = jnp.take(w, ids, axis=0)
    if padding_idx is not None:
        if padding_idx < 0:
            padding_idx = jnp.shape(w)[0] + padding_idx
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return {"Out": [out]}


@register_op("top_k", no_grad=True)
def _top_k(ins, attrs):
    x = _x(ins)
    k = attrs["k"]
    vals, idx = jax.lax.top_k(x, k)
    return {"Out": [vals], "Indices": [idx.astype(jnp.int64)]}


@register_op("arg_max", no_grad=True)
def _arg_max(ins, attrs):
    axis = attrs.get("axis", -1)
    return {"Out": [jnp.argmax(_x(ins), axis=axis).astype(jnp.int64)]}


@register_op("arg_min", no_grad=True)
def _arg_min(ins, attrs):
    axis = attrs.get("axis", -1)
    return {"Out": [jnp.argmin(_x(ins), axis=axis).astype(jnp.int64)]}


@register_op("range", no_grad=True)
def _range(ins, attrs):
    start = attrs.get("start", 0)
    end = attrs["end"]
    step = attrs.get("step", 1)
    dtype = attrs.get("dtype", "int64")
    return {"Out": [jnp.arange(start, end, step, dtype=dtype)]}


@register_op("where", diff_inputs=("X", "Y"))
def _where(ins, attrs):
    cond, x, y = _x(ins, "Condition"), _x(ins), _x(ins, "Y")
    return {"Out": [jnp.where(cond, x, y)]}


@register_op("cumsum")
def _cumsum(ins, attrs):
    x = _x(ins)
    axis = attrs.get("axis", -1)
    if attrs.get("reverse", False):
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        out = out - x
    if attrs.get("reverse", False):
        out = jnp.flip(out, axis)
    return {"Out": [out]}


@register_op("pad")
def _pad(ins, attrs):
    x = _x(ins)
    paddings = attrs["paddings"]  # [before0, after0, before1, after1, ...]
    value = attrs.get("pad_value", 0.0)
    cfg = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(jnp.ndim(x))]
    return {"Out": [jnp.pad(x, cfg, constant_values=value)]}


@register_op("tile")
def _tile(ins, attrs):
    return {"Out": [jnp.tile(_x(ins), attrs["repeat_times"])]}


@register_op("dynamic_update", diff_inputs=("X", "Value"))
def _dynamic_update(ins, attrs):
    """Write Value at dynamic position Index along axis 0 of X.

    Static-shape stand-in for the reference's LoDTensorArray write
    (reference: operators/controlflow/tensor_array_read_write_op.cc):
    the "array" is a preallocated [maxlen, ...] dense tensor.
    """
    import jax.lax as lax

    x = _x(ins)
    idx = jnp.reshape(ins["Index"][0], ()).astype(jnp.int32)
    v = ins["Value"][0]
    v = jnp.expand_dims(v, 0).astype(x.dtype)
    zero = jnp.zeros((), jnp.int32)
    starts = (idx,) + (zero,) * (x.ndim - 1)
    return {"Out": [lax.dynamic_update_slice(x, v, starts)]}


@register_op("dynamic_slice", diff_inputs=("X",))
def _dynamic_slice(ins, attrs):
    """Read the [Index] slice along axis 0 of X (LoDTensorArray read)."""
    import jax.lax as lax

    x = _x(ins)
    idx = jnp.reshape(ins["Index"][0], ()).astype(jnp.int32)
    sizes = (1,) + tuple(x.shape[1:])
    zero = jnp.zeros((), jnp.int32)
    starts = (idx,) + (zero,) * (x.ndim - 1)
    out = lax.dynamic_slice(x, starts, sizes)
    return {"Out": [jnp.squeeze(out, 0)]}


# --- remaining reference tensor/array ops ---


@register_op("reverse", diff_inputs=("X",))
def _reverse(ins, attrs):
    return {"Out": [jnp.flip(_x(ins), axis=tuple(attrs.get("axis", [0])))]}


@register_op("argsort", no_grad=True)
def _argsort(ins, attrs):
    x = _x(ins)
    axis = attrs.get("axis", -1)
    descending = attrs.get("descending", False)
    idx = jnp.argsort(-x if descending else x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": [out], "Indices": [idx.astype(jnp.int64)]}


@register_op("diag", no_grad=True)
def _diag(ins, attrs):
    return {"Out": [jnp.diag(_x(ins, "Diagonal"))]}


@register_op("linspace", no_grad=True)
def _linspace(ins, attrs):
    start = jnp.reshape(_x(ins, "Start"), ())
    stop = jnp.reshape(_x(ins, "Stop"), ())
    num = int(attrs["num"])
    dtype = attrs.get("dtype", "float32")
    return {"Out": [jnp.linspace(start, stop, num, dtype=dtype)]}


@register_op("gather_nd", diff_inputs=("X",))
def _gather_nd(ins, attrs):
    x, index = _x(ins), _x(ins, "Index")
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return {"Out": [x[idx]]}


@register_op("scatter_nd_add", diff_inputs=("X", "Updates"))
def _scatter_nd_add(ins, attrs):
    x = _x(ins)
    index = _x(ins, "Index")
    updates = _x(ins, "Updates")
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return {"Out": [x.at[idx].add(updates)]}


@register_op("pad2d", diff_inputs=("X",))
def _pad2d(ins, attrs):
    """NCHW spatial padding with constant/reflect/edge modes
    (reference: pad2d_op.cc)."""
    x = _x(ins)
    t, b, l, r = attrs.get("paddings", [0, 0, 0, 0])
    mode = {"constant": "constant", "reflect": "reflect",
            "edge": "edge"}[attrs.get("mode", "constant")]
    kw = {}
    if mode == "constant":
        kw["constant_values"] = attrs.get("pad_value", 0.0)
    out = jnp.pad(x, ((0, 0), (0, 0), (t, b), (l, r)), mode=mode, **kw)
    return {"Out": [out]}


@register_op("pad_constant_like", diff_inputs=("Y",))
def _pad_constant_like(ins, attrs):
    """Pad Y up to X's shape with pad_value
    (reference: pad_constant_like_op.cc)."""
    x, y = _x(ins), _x(ins, "Y")
    pads = [(0, int(a) - int(b)) for a, b in zip(jnp.shape(x), jnp.shape(y))]
    return {"Out": [jnp.pad(y, pads,
                            constant_values=attrs.get("pad_value", 0.0))]}


@register_op("crop", diff_inputs=("X",))
def _crop(ins, attrs):
    """Crop a static-offset window (reference: crop_op.cc)."""
    x = _x(ins)
    offsets = attrs.get("offsets", [0] * jnp.ndim(x))
    shape = attrs["shape"]
    sl = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return {"Out": [x[sl]]}


@register_op("shuffle_channel", diff_inputs=("X",))
def _shuffle_channel(ins, attrs):
    """Channel shuffle for group convs (reference: shuffle_channel_op.cc)."""
    x = _x(ins)
    g = int(attrs.get("group", 1))
    n, c, h, w = jnp.shape(x)
    out = jnp.reshape(
        jnp.swapaxes(jnp.reshape(x, (n, g, c // g, h, w)), 1, 2), (n, c, h, w)
    )
    return {"Out": [out]}


@register_op("pixel_shuffle", diff_inputs=("X",))
def _pixel_shuffle(ins, attrs):
    """Depth-to-space upscaling (reference: pixel_shuffle_op.cc)."""
    x = _x(ins)
    r = int(attrs.get("upscale_factor", 1))
    n, c, h, w = jnp.shape(x)
    out = jnp.reshape(x, (n, c // (r * r), r, r, h, w))
    out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
    return {"Out": [jnp.reshape(out, (n, c // (r * r), h * r, w * r))]}


@register_op("space_to_depth", diff_inputs=("X",))
def _space_to_depth(ins, attrs):
    """Inverse of pixel shuffle (reference: space_to_depth_op.cc)."""
    x = _x(ins)
    r = int(attrs.get("blocksize", 1))
    n, c, h, w = jnp.shape(x)
    out = jnp.reshape(x, (n, c, h // r, r, w // r, r))
    out = jnp.transpose(out, (0, 3, 5, 1, 2, 4))
    return {"Out": [jnp.reshape(out, (n, c * r * r, h // r, w // r))]}


@register_op("multiplex", diff_inputs=("X",))
def _multiplex(ins, attrs):
    """Row-wise select among candidate tensors by index
    (reference: multiplex_op.cc)."""
    xs = jnp.stack(ins["X"], axis=0)        # [K, B, ...]
    ids = _x(ins, "Ids")
    if jnp.ndim(ids) > 1:
        ids = jnp.squeeze(ids, -1)
    b = jnp.shape(xs)[1]
    return {"Out": [xs[ids.astype(jnp.int32), jnp.arange(b)]]}


@register_op("sampling_id", no_grad=True, needs_rng=True)
def _sampling_id(ins, attrs, rng=None):
    """Sample a column index per row from probability rows
    (reference: sampling_id_op.cc)."""
    x = _x(ins)
    ids = jax.random.categorical(rng, jnp.log(jnp.maximum(x, 1e-30)), axis=-1)
    return {"Out": [ids.astype(jnp.int64)]}


@register_op("shard_index", no_grad=True)
def _shard_index(ins, attrs):
    """Map global ids to shard-local ids (reference: shard_index_op.cc)."""
    x = _x(ins)
    index_num = attrs["index_num"]
    nshards = attrs["nshards"]
    shard_id = attrs["shard_id"]
    ignore = attrs.get("ignore_value", -1)
    per = (index_num + nshards - 1) // nshards
    in_shard = (x // per) == shard_id
    return {"Out": [jnp.where(in_shard, x % per, ignore)]}


@register_op("iou_similarity", no_grad=True)
def _iou_similarity(ins, attrs):
    """Pairwise IoU of two box sets [N,4] x [M,4] (xmin,ymin,xmax,ymax)
    (reference: operators/detection/iou_similarity_op.cc)."""
    from paddle_tpu.ops.box_util import iou_xyxy

    x = _x(ins)         # [N, 4]
    y = _x(ins, "Y")    # [M, 4]
    return {"Out": [iou_xyxy(x, y)]}


@register_op("box_coder", no_grad=True)
def _box_coder(ins, attrs):
    """Encode/decode boxes against priors (reference:
    operators/detection/box_coder_op.cc). PriorBox [M,4], TargetBox
    encode:[N,4] / decode:[N,M,4]."""
    prior = _x(ins, "PriorBox")
    target = _x(ins, "TargetBox")
    code_type = attrs.get("code_type", "encode_center_size")
    norm = attrs.get("box_normalized", True)
    one = 0.0 if norm else 1.0
    # variances scale the encoded offsets (box_coder_op.h): per-prior
    # tensor input, or a 4-vector attr, or none (all ones)
    pvar = ins.get("PriorBoxVar", [None])
    pvar = pvar[0] if pvar else None
    if pvar is None:
        va = attrs.get("variance", [])
        pvar = jnp.asarray(va if va else [1.0, 1.0, 1.0, 1.0])
        pvar = jnp.broadcast_to(pvar, (jnp.shape(prior)[0], 4))
    pw = prior[:, 2] - prior[:, 0] + one
    ph = prior[:, 3] - prior[:, 1] + one
    px = prior[:, 0] + pw * 0.5
    py = prior[:, 1] + ph * 0.5
    if code_type.startswith("encode"):
        tw = target[:, 2] - target[:, 0] + one
        th = target[:, 3] - target[:, 1] + one
        tx = target[:, 0] + tw * 0.5
        ty = target[:, 1] + th * 0.5
        ox = (tx[:, None] - px[None, :]) / pw[None, :] / pvar[None, :, 0]
        oy = (ty[:, None] - py[None, :]) / ph[None, :] / pvar[None, :, 1]
        ow = jnp.log(tw[:, None] / pw[None, :]) / pvar[None, :, 2]
        oh = jnp.log(th[:, None] / ph[None, :]) / pvar[None, :, 3]
        out = jnp.stack([ox, oy, ow, oh], axis=-1)     # [N, M, 4]
    else:
        tx = target[..., 0] * pvar[None, :, 0] * pw[None, :] + px[None, :]
        ty = target[..., 1] * pvar[None, :, 1] * ph[None, :] + py[None, :]
        tw = jnp.exp(target[..., 2] * pvar[None, :, 2]) * pw[None, :]
        th = jnp.exp(target[..., 3] * pvar[None, :, 3]) * ph[None, :]
        out = jnp.stack(
            [tx - tw * 0.5, ty - th * 0.5,
             tx + tw * 0.5 - one, ty + th * 0.5 - one], axis=-1)
    return {"OutputBox": [out]}


@register_op("flatten")
def _flatten(ins, attrs):
    # same semantics as flatten2 minus the XShape output
    return {"Out": _flatten2(ins, attrs)["Out"]}


@register_op("prior_box", no_grad=True)
def _prior_box(ins, attrs):
    """SSD prior boxes per feature-map cell (reference:
    operators/detection/prior_box_op.cc). Input [N,C,H,W] feature map,
    Image [N,C,Hi,Wi]. Outputs Boxes/Variances [H, W, P, 4]."""
    feat = _x(ins, "Input")
    img = _x(ins, "Image")
    h, w = jnp.shape(feat)[2], jnp.shape(feat)[3]
    ih, iw = jnp.shape(img)[2], jnp.shape(img)[3]
    min_sizes = list(attrs.get("min_sizes", [100.0]))
    max_sizes = list(attrs.get("max_sizes", []))
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", []):
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(ar)
            if attrs.get("flip", True):
                ars.append(1.0 / ar)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    step_w = attrs.get("step_w", 0.0) or float(iw) / w
    step_h = attrs.get("step_h", 0.0) or float(ih) / h
    offset = attrs.get("offset", 0.5)

    whs = []
    for i, ms in enumerate(min_sizes):
        for ar in ars:
            whs.append((ms * (ar ** 0.5), ms / (ar ** 0.5)))
        # max_sizes pair index-wise with min_sizes (prior_box_op.h):
        # one extra sqrt(min*max) square prior per min size
        if i < len(max_sizes):
            s = (ms * max_sizes[i]) ** 0.5
            whs.append((s, s))
    p = len(whs)
    cw = jnp.asarray([a for a, _ in whs]) / iw    # [P]
    ch = jnp.asarray([b for _, b in whs]) / ih
    cx = (jnp.arange(w) + offset) * step_w / iw   # [W]
    cy = (jnp.arange(h) + offset) * step_h / ih   # [H]
    cxg = jnp.broadcast_to(cx[None, :, None], (h, w, p))
    cyg = jnp.broadcast_to(cy[:, None, None], (h, w, p))
    boxes = jnp.stack([
        cxg - cw / 2, cyg - ch / 2, cxg + cw / 2, cyg + ch / 2
    ], axis=-1)
    if attrs.get("clip", True):
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), (h, w, p, 4))
    return {"Boxes": [boxes], "Variances": [var]}


@register_op("anchor_generator", no_grad=True)
def _anchor_generator(ins, attrs):
    """RPN anchors per cell (reference:
    operators/detection/anchor_generator_op.cc). Outputs
    Anchors/Variances [H, W, A, 4] in input-image pixels."""
    feat = _x(ins, "Input")
    h, w = jnp.shape(feat)[2], jnp.shape(feat)[3]
    sizes = attrs.get("anchor_sizes", [64.0, 128.0, 256.0, 512.0])
    ratios = attrs.get("aspect_ratios", [0.5, 1.0, 2.0])
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    stride = attrs.get("stride", [16.0, 16.0])
    offset = attrs.get("offset", 0.5)
    whs = []
    for r in ratios:
        for s in sizes:
            area = s * s
            aw = (area / r) ** 0.5
            whs.append((aw, aw * r))
    a = len(whs)
    aw = jnp.asarray([x for x, _ in whs])
    ah = jnp.asarray([y for _, y in whs])
    cx = (jnp.arange(w) + offset) * stride[0]
    cy = (jnp.arange(h) + offset) * stride[1]
    cxg = jnp.broadcast_to(cx[None, :, None], (h, w, a))
    cyg = jnp.broadcast_to(cy[:, None, None], (h, w, a))
    anchors = jnp.stack([
        cxg - aw / 2, cyg - ah / 2, cxg + aw / 2, cyg + ah / 2
    ], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances), (h, w, a, 4))
    return {"Anchors": [anchors], "Variances": [var]}


# --- v1-named aliases of the *2 ops (reference registers both; the v1
# forms lack the XShape side output) ---


@register_op("reshape", diff_inputs=("X",))
def _reshape_v1(ins, attrs):
    x = _x(ins)
    shape = [int(s) for s in attrs["shape"]]
    out_shape = []
    for i, s in enumerate(shape):
        if s == 0:
            out_shape.append(x.shape[i])
        else:
            out_shape.append(s)
    return {"Out": [jnp.reshape(x, out_shape)]}


@register_op("transpose", diff_inputs=("X",))
def _transpose_v1(ins, attrs):
    return {"Out": [jnp.transpose(_x(ins), attrs["axis"])]}


@register_op("squeeze", diff_inputs=("X",))
def _squeeze_v1(ins, attrs):
    x = _x(ins)
    axes = attrs.get("axes", [])
    if not axes:
        return {"Out": [jnp.squeeze(x)]}
    return {"Out": [jnp.squeeze(x, axis=tuple(axes))]}


@register_op("unsqueeze", diff_inputs=("X",))
def _unsqueeze_v1(ins, attrs):
    x = _x(ins)
    for a in sorted(attrs["axes"]):
        x = jnp.expand_dims(x, a)
    return {"Out": [x]}
