"""Tensor creation & manipulation ops.

Reference kernels: paddle/fluid/operators/{fill_constant_op.cc,
gaussian_random_op.cc, uniform_random_op.cc, reshape_op.cc, transpose_op.cc,
concat_op.cc, split_op.cc, slice_op.cc, stack_op.cc, squeeze_op.cc,
unsqueeze_op.cc, expand_op.cc, gather_op.cc, one_hot_op.cc,
lookup_table_op.cc, top_k_op.cc, arg_max_op.cc, assign_op.cc}.

RNG ops are stateless-keyed (Philox-style jax PRNG folded per-op and
per-step by the lowering), replacing the reference's stateful per-op seeds
(SURVEY.md section 7 hard part 6).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op


def _x(ins, slot="X", i=0):
    return ins[slot][i]


@register_op("fill_constant", no_grad=True)
def _fill_constant(ins, attrs):
    shape = tuple(attrs.get("shape", []))
    dtype = attrs.get("dtype", "float32")
    value = attrs.get("value", 0.0)
    return {"Out": [jnp.full(shape, value, dtype=dtype)]}


@register_op("fill_zeros_like", no_grad=True)
def _fill_zeros_like(ins, attrs):
    return {"Out": [jnp.zeros_like(_x(ins))]}


@register_op("fill_any_like", no_grad=True)
def _fill_any_like(ins, attrs):
    return {"Out": [jnp.full_like(_x(ins), attrs.get("value", 0.0))]}


@register_op("gaussian_random", no_grad=True, needs_rng=True)
def _gaussian_random(ins, attrs, rng=None):
    shape = tuple(attrs["shape"])
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    dtype = attrs.get("dtype", "float32")
    return {"Out": [mean + std * jax.random.normal(rng, shape, dtype=dtype)]}


@register_op("uniform_random", no_grad=True, needs_rng=True)
def _uniform_random(ins, attrs, rng=None):
    shape = tuple(attrs["shape"])
    lo = attrs.get("min", -1.0)
    hi = attrs.get("max", 1.0)
    dtype = attrs.get("dtype", "float32")
    return {"Out": [jax.random.uniform(rng, shape, dtype=dtype, minval=lo, maxval=hi)]}


@register_op("truncated_gaussian_random", no_grad=True, needs_rng=True)
def _truncated_gaussian_random(ins, attrs, rng=None):
    shape = tuple(attrs["shape"])
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    dtype = attrs.get("dtype", "float32")
    x = jax.random.truncated_normal(rng, -2.0, 2.0, shape, dtype=dtype)
    return {"Out": [mean + std * x]}


@register_op("assign")
def _assign(ins, attrs):
    return {"Out": [_x(ins)]}


@register_op("assign_value", no_grad=True)
def _assign_value(ins, attrs):
    shape = tuple(attrs["shape"])
    dtype = attrs.get("dtype", "float32")
    vals = np.asarray(attrs["values"], dtype=np.float64)
    return {"Out": [jnp.asarray(vals.reshape(shape)).astype(dtype)]}


@register_op("shape", no_grad=True)
def _shape(ins, attrs):
    return {"Out": [jnp.asarray(jnp.shape(_x(ins)), dtype=jnp.int64)]}


@register_op("reshape2")
def _reshape2(ins, attrs):
    x = _x(ins)
    # Reference semantics: 0 copies the input dim, -1 infers (reshape_op.cc).
    shape = [
        jnp.shape(x)[i] if d == 0 else d for i, d in enumerate(attrs["shape"])
    ]
    return {"Out": [jnp.reshape(x, shape)], "XShape": []}


@register_op("transpose2")
def _transpose2(ins, attrs):
    return {"Out": [jnp.transpose(_x(ins), attrs["axis"])], "XShape": []}


@register_op("flatten2")
def _flatten2(ins, attrs):
    import math

    x = _x(ins)
    axis = attrs.get("axis", 1)
    s = jnp.shape(x)
    return {
        "Out": [jnp.reshape(x, (math.prod(s[:axis]) if axis else 1, -1))],
        "XShape": [],
    }


@register_op("concat")
def _concat(ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))]}


@register_op("split")
def _split(ins, attrs):
    x = _x(ins)
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = attrs.get("sections", [])
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        parts = jnp.split(x, idx, axis=axis)
    else:
        parts = jnp.split(x, num, axis=axis)
    return {"Out": list(parts)}


@register_op("slice")
def _slice(ins, attrs):
    x = _x(ins)
    axes = attrs["axes"]
    starts = attrs["starts"]
    ends = attrs["ends"]
    idx = [slice(None)] * jnp.ndim(x)
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = slice(st, en)
    return {"Out": [x[tuple(idx)]]}


@register_op("stack")
def _stack(ins, attrs):
    return {"Out": [jnp.stack(ins["X"], axis=attrs.get("axis", 0))]}


@register_op("unstack")
def _unstack(ins, attrs):
    x = _x(ins)
    axis = attrs.get("axis", 0)
    num = jnp.shape(x)[axis]
    parts = [jnp.squeeze(p, axis=axis) for p in jnp.split(x, num, axis=axis)]
    return {"Y": parts}


@register_op("squeeze2")
def _squeeze2(ins, attrs):
    axes = tuple(attrs.get("axes", []))
    x = _x(ins)
    return {"Out": [jnp.squeeze(x, axis=axes or None)], "XShape": []}


@register_op("unsqueeze2")
def _unsqueeze2(ins, attrs):
    x = _x(ins)
    for ax in sorted(attrs["axes"]):
        x = jnp.expand_dims(x, ax)
    return {"Out": [x], "XShape": []}


@register_op("expand")
def _expand(ins, attrs):
    x = _x(ins)
    times = attrs["expand_times"]
    return {"Out": [jnp.tile(x, times)]}


@register_op("expand_as")
def _expand_as(ins, attrs):
    x, y = _x(ins), _x(ins, "Y")
    return {"Out": [jnp.broadcast_to(x, jnp.shape(y))]}


@register_op("gather", diff_inputs=("X",))
def _gather(ins, attrs):
    x, index = _x(ins), _x(ins, "Index")
    axis = attrs.get("axis", 0)
    return {"Out": [jnp.take(x, index, axis=axis)]}


@register_op("scatter", diff_inputs=("X", "Updates"))
def _scatter(ins, attrs):
    x, ids, updates = _x(ins), _x(ins, "Ids"), _x(ins, "Updates")
    if attrs.get("overwrite", True):
        return {"Out": [x.at[ids].set(updates)]}
    return {"Out": [x.at[ids].add(updates)]}


@register_op("one_hot", no_grad=True)
def _one_hot(ins, attrs):
    x = _x(ins)
    depth = attrs["depth"]
    if jnp.ndim(x) > 1 and jnp.shape(x)[-1] == 1:
        x = jnp.squeeze(x, axis=-1)
    return {"Out": [jax.nn.one_hot(x, depth, dtype=attrs.get("dtype", "float32"))]}


@register_op("lookup_table", diff_inputs=("W",),
             doc="embedding lookup; dense scatter-add grad on TPU replaces "
                 "the reference's SelectedRows sparse grad "
                 "(lookup_table_op.cc)")
def _lookup_table(ins, attrs):
    w, ids = _x(ins, "W"), _x(ins, "Ids")
    # [N, 1] column-ids convention: squeeze unless the layer says the ids
    # are already a padded [b, t] batch (a [b, 1] batch is ambiguous).
    squeeze_last = attrs.get(
        "squeeze_last", jnp.ndim(ids) > 1 and jnp.shape(ids)[-1] == 1
    )
    if squeeze_last:
        ids = jnp.squeeze(ids, axis=-1)
    # Reference semantics: kNoPadding when absent; negative = vocab + idx
    # (lookup_table_op.cc). The layer omits the attr when padding is off.
    padding_idx = attrs.get("padding_idx", None)
    out = None
    if attrs.get("is_distributed", False):
        # Row-sharded table (replaces the reference's pserver-distributed
        # lookup table + RPC prefetch, parameter_prefetch.cc): each shard
        # gathers its local rows, psum over ICI combines. Only active when
        # the program runs under a strategy declaring a table axis.
        from paddle_tpu.core.interp import spmd_ctx

        ctx = spmd_ctx()
        if ctx is not None:
            mesh, _ctx_axis, table_axis, data_axis = ctx
            if table_axis is not None and (
                jnp.shape(w)[0] % mesh.shape[table_axis] == 0
            ):
                from paddle_tpu.parallel.embedding import (
                    sharded_embedding_lookup,
                )

                out = sharded_embedding_lookup(
                    w, ids, mesh, shard_axis=table_axis,
                    data_axis=data_axis,
                )
    if out is None:
        out = jnp.take(w, ids, axis=0)
    if padding_idx is not None:
        if padding_idx < 0:
            padding_idx = jnp.shape(w)[0] + padding_idx
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return {"Out": [out]}


@register_op("top_k", no_grad=True)
def _top_k(ins, attrs):
    x = _x(ins)
    k = attrs["k"]
    vals, idx = jax.lax.top_k(x, k)
    return {"Out": [vals], "Indices": [idx.astype(jnp.int64)]}


@register_op("arg_max", no_grad=True)
def _arg_max(ins, attrs):
    axis = attrs.get("axis", -1)
    return {"Out": [jnp.argmax(_x(ins), axis=axis).astype(jnp.int64)]}


@register_op("arg_min", no_grad=True)
def _arg_min(ins, attrs):
    axis = attrs.get("axis", -1)
    return {"Out": [jnp.argmin(_x(ins), axis=axis).astype(jnp.int64)]}


@register_op("range", no_grad=True)
def _range(ins, attrs):
    start = attrs.get("start", 0)
    end = attrs["end"]
    step = attrs.get("step", 1)
    dtype = attrs.get("dtype", "int64")
    return {"Out": [jnp.arange(start, end, step, dtype=dtype)]}


@register_op("where", diff_inputs=("X", "Y"))
def _where(ins, attrs):
    cond, x, y = _x(ins, "Condition"), _x(ins), _x(ins, "Y")
    return {"Out": [jnp.where(cond, x, y)]}


@register_op("cumsum")
def _cumsum(ins, attrs):
    x = _x(ins)
    axis = attrs.get("axis", -1)
    if attrs.get("reverse", False):
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        out = out - x
    if attrs.get("reverse", False):
        out = jnp.flip(out, axis)
    return {"Out": [out]}


@register_op("pad")
def _pad(ins, attrs):
    x = _x(ins)
    paddings = attrs["paddings"]  # [before0, after0, before1, after1, ...]
    value = attrs.get("pad_value", 0.0)
    cfg = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(jnp.ndim(x))]
    return {"Out": [jnp.pad(x, cfg, constant_values=value)]}


@register_op("tile")
def _tile(ins, attrs):
    return {"Out": [jnp.tile(_x(ins), attrs["repeat_times"])]}


@register_op("dynamic_update", diff_inputs=("X", "Value"))
def _dynamic_update(ins, attrs):
    """Write Value at dynamic position Index along axis 0 of X.

    Static-shape stand-in for the reference's LoDTensorArray write
    (reference: operators/controlflow/tensor_array_read_write_op.cc):
    the "array" is a preallocated [maxlen, ...] dense tensor.
    """
    import jax.lax as lax

    x = _x(ins)
    idx = jnp.reshape(ins["Index"][0], ()).astype(jnp.int32)
    v = ins["Value"][0]
    v = jnp.expand_dims(v, 0).astype(x.dtype)
    zero = jnp.zeros((), jnp.int32)
    starts = (idx,) + (zero,) * (x.ndim - 1)
    return {"Out": [lax.dynamic_update_slice(x, v, starts)]}


@register_op("dynamic_slice", diff_inputs=("X",))
def _dynamic_slice(ins, attrs):
    """Read the [Index] slice along axis 0 of X (LoDTensorArray read)."""
    import jax.lax as lax

    x = _x(ins)
    idx = jnp.reshape(ins["Index"][0], ()).astype(jnp.int32)
    sizes = (1,) + tuple(x.shape[1:])
    zero = jnp.zeros((), jnp.int32)
    starts = (idx,) + (zero,) * (x.ndim - 1)
    out = lax.dynamic_slice(x, starts, sizes)
    return {"Out": [jnp.squeeze(out, 0)]}
