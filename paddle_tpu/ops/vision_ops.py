"""Vision ops: RoI extraction, detection decoding/NMS, 3-D conv/pool,
pooling variants, and spatial transforms.

Reference kernels: paddle/fluid/operators/{roi_pool_op.cc, roi_align_op.cc,
detection/yolo_box_op.cc, detection/box_clip_op.cc,
detection/multiclass_nms_op.cc, detection/density_prior_box_op.cc,
detection/bipartite_match_op.cc, conv_op.cc (3d), pool_op.cc (3d),
max_pool_with_index_op.cc, unpool_op.cc, spp_op.cc, lrn_op.cc,
affine_grid_op.cc, random_crop_op.cc}. All static-shape (XLA discipline):
NMS emits fixed-capacity outputs padded with -1 labels instead of the
reference's variable-length LoD results.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.box_util import greedy_bipartite_match


def _x(ins, slot="X", i=0):
    v = ins.get(slot)
    return v[i] if v else None


def _pair3(v):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v, v, v)


# --------------------------------------------------------------------------
# RoI ops
# --------------------------------------------------------------------------


def _roi_bounds(roi, spatial_scale):
    x1, y1, x2, y2 = roi[0], roi[1], roi[2], roi[3]
    return (x1 * spatial_scale, y1 * spatial_scale,
            x2 * spatial_scale, y2 * spatial_scale)


@register_op("roi_align", diff_inputs=("X",))
def _roi_align(ins, attrs):
    """Bilinear RoI align (reference: roi_align_op.cc). X [n, c, h, w];
    ROIs [r, 4] (x1, y1, x2, y2); RoisNum/batch ids via BatchId [r] (all
    zeros when absent, matching single-image usage)."""
    x = _x(ins)
    rois = _x(ins, "ROIs")
    batch_ids = _x(ins, "BatchId")
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    ratio = int(attrs.get("sampling_ratio", -1))
    n, c, h, w = x.shape
    r = rois.shape[0]
    if batch_ids is None:
        batch_ids = jnp.zeros((r,), jnp.int32)
    # XLA static-shape deviation from the reference: sampling_ratio <= 0
    # means ADAPTIVE ceil(roi_size/pooled_size) samples per bin in
    # roi_align_op.cc, which is a data-dependent shape. A fixed 4x4
    # sample grid per bin is used instead; pass an explicit
    # sampling_ratio for parity-critical pipelines.
    sr = ratio if ratio > 0 else 4

    def one_roi(roi, bid):
        rx1, ry1, rx2, ry2 = _roi_bounds(roi, scale)
        rw = jnp.maximum(rx2 - rx1, 1.0)
        rh = jnp.maximum(ry2 - ry1, 1.0)
        bin_w, bin_h = rw / pw, rh / ph
        img = x[bid]  # (c, h, w)
        # sample grid: ph*sr x pw*sr bilinear points
        ys = ry1 + (jnp.arange(ph * sr) + 0.5) * bin_h / sr
        xs = rx1 + (jnp.arange(pw * sr) + 0.5) * bin_w / sr

        def bilinear(yy, xx):
            y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
            y1_ = jnp.clip(y0 + 1, 0, h - 1)
            x1_ = jnp.clip(x0 + 1, 0, w - 1)
            ly, lx = yy - y0, xx - x0
            y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
            y1i, x1i = y1_.astype(jnp.int32), x1_.astype(jnp.int32)
            v = (img[:, y0i, x0i] * (1 - ly) * (1 - lx)
                 + img[:, y1i, x0i] * ly * (1 - lx)
                 + img[:, y0i, x1i] * (1 - ly) * lx
                 + img[:, y1i, x1i] * ly * lx)
            inside = (yy >= -1) & (yy <= h) & (xx >= -1) & (xx <= w)
            return jnp.where(inside, v, 0.0)

        yy = jnp.repeat(ys, pw * sr).reshape(ph * sr, pw * sr)
        xx = jnp.tile(xs, (ph * sr, 1))
        samples = jax.vmap(
            jax.vmap(bilinear, in_axes=(0, 0)), in_axes=(0, 0)
        )(yy, xx)                                    # (ph*sr, pw*sr, c)
        samples = samples.reshape(ph, sr, pw, sr, c)
        return jnp.mean(samples, axis=(1, 3)).transpose(2, 0, 1)

    out = jax.vmap(one_roi)(rois.astype(jnp.float32), batch_ids)
    return {"Out": [out.astype(x.dtype)]}


@register_op("roi_pool", diff_inputs=("X",))
def _roi_pool(ins, attrs):
    """Quantized max RoI pooling (reference: roi_pool_op.cc)."""
    x = _x(ins)
    rois = _x(ins, "ROIs")
    batch_ids = _x(ins, "BatchId")
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    n, c, h, w = x.shape
    r = rois.shape[0]
    if batch_ids is None:
        batch_ids = jnp.zeros((r,), jnp.int32)

    hh = jnp.arange(h)
    ww = jnp.arange(w)

    def one_roi(roi, bid):
        rx1 = jnp.round(roi[0] * scale)
        ry1 = jnp.round(roi[1] * scale)
        rx2 = jnp.round(roi[2] * scale)
        ry2 = jnp.round(roi[3] * scale)
        rw = jnp.maximum(rx2 - rx1 + 1, 1.0)
        rh = jnp.maximum(ry2 - ry1 + 1, 1.0)
        img = x[bid]

        def one_bin(iy, ix):
            y_lo = jnp.floor(ry1 + iy * rh / ph)
            y_hi = jnp.ceil(ry1 + (iy + 1) * rh / ph)
            x_lo = jnp.floor(rx1 + ix * rw / pw)
            x_hi = jnp.ceil(rx1 + (ix + 1) * rw / pw)
            my = (hh >= y_lo) & (hh < jnp.maximum(y_hi, y_lo + 1))
            mx = (ww >= x_lo) & (ww < jnp.maximum(x_hi, x_lo + 1))
            mask = my[:, None] & mx[None, :]
            neg = jnp.finfo(x.dtype).min
            return jnp.max(jnp.where(mask[None], img, neg), axis=(1, 2))

        iy = jnp.repeat(jnp.arange(ph), pw)
        ix = jnp.tile(jnp.arange(pw), ph)
        bins = jax.vmap(one_bin)(iy, ix)             # (ph*pw, c)
        return bins.T.reshape(c, ph, pw)

    out = jax.vmap(one_roi)(rois.astype(jnp.float32), batch_ids)
    return {"Out": [out]}


# --------------------------------------------------------------------------
# detection decode / NMS
# --------------------------------------------------------------------------


@register_op("yolo_box", no_grad=True)
def _yolo_box(ins, attrs):
    """Decode YOLOv3 head output to boxes+scores (reference:
    detection/yolo_box_op.cc). X [n, an*(5+cls), h, w]; ImgSize [n, 2]."""
    x = _x(ins)
    img_size = _x(ins, "ImgSize")
    anchors = attrs["anchors"]                       # flat [ax, ay, ...]
    class_num = int(attrs["class_num"])
    conf_thresh = float(attrs.get("conf_thresh", 0.01))
    downsample = int(attrs.get("downsample_ratio", 32))
    n, _, h, w = x.shape
    an = len(anchors) // 2
    x = x.reshape(n, an, 5 + class_num, h, w)
    grid_x = jnp.arange(w, dtype=jnp.float32)
    grid_y = jnp.arange(h, dtype=jnp.float32)
    input_h = downsample * h
    input_w = downsample * w
    aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]

    bx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x[None, None, None, :]) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) + grid_y[None, None, :, None]) / h
    bw = jnp.exp(x[:, :, 2]) * aw / input_w
    bh = jnp.exp(x[:, :, 3]) * ah / input_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    keep = conf > conf_thresh
    probs = jnp.where(keep[:, :, None], probs, 0.0)

    img_h = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    img_w = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    # clamp to image bounds like the reference kernel
    x1 = jnp.clip((bx - bw / 2) * img_w, 0.0, img_w - 1.0)
    y1 = jnp.clip((by - bh / 2) * img_h, 0.0, img_h - 1.0)
    x2 = jnp.clip((bx + bw / 2) * img_w, 0.0, img_w - 1.0)
    y2 = jnp.clip((by + bh / 2) * img_h, 0.0, img_h - 1.0)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1, 4)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    return {"Boxes": [boxes], "Scores": [scores]}


@register_op("box_clip", no_grad=True)
def _box_clip(ins, attrs):
    """Clip boxes to image bounds (reference: detection/box_clip_op.cc).
    Input [.., 4], ImInfo [n, 3] (h, w, scale)."""
    boxes = _x(ins, "Input")
    im_info = _x(ins, "ImInfo")
    # per-image bounds: ImInfo rows are (h, w, scale)
    h = (im_info[:, 0] / im_info[:, 2] - 1.0).reshape(
        (-1,) + (1,) * (boxes.ndim - 2))
    w = (im_info[:, 1] / im_info[:, 2] - 1.0).reshape(
        (-1,) + (1,) * (boxes.ndim - 2))
    x1 = jnp.clip(boxes[..., 0], 0, w)
    y1 = jnp.clip(boxes[..., 1], 0, h)
    x2 = jnp.clip(boxes[..., 2], 0, w)
    y2 = jnp.clip(boxes[..., 3], 0, h)
    return {"Output": [jnp.stack([x1, y1, x2, y2], axis=-1)]}


def _iou_matrix(boxes):
    """[m, 4] -> [m, m] pairwise IoU."""
    x1, y1, x2, y2 = (boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3])
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def _nms_keep(boxes, scores, iou_threshold, top_k):
    """Greedy NMS with static shapes: returns a keep mask over the top_k
    score-sorted candidates."""
    order = jnp.argsort(-scores)[:top_k]
    b = boxes[order]
    s = scores[order]
    iou = _iou_matrix(b)
    m = s.shape[0]

    def body(i, keep):
        # suppress i if it overlaps an earlier KEPT box
        over = (iou[i] > iou_threshold) & (jnp.arange(m) < i) & keep
        return keep.at[i].set(~jnp.any(over) & keep[i])

    keep = jax.lax.fori_loop(0, m, body, s > 0)
    return order, keep


@register_op("multiclass_nms", no_grad=True)
def _multiclass_nms(ins, attrs):
    """Static-shape multiclass NMS (reference:
    detection/multiclass_nms_op.cc). BBoxes [n, m, 4]; Scores [n, cls, m].
    Out [n, keep_top_k, 6] rows (label, score, x1, y1, x2, y2), label -1
    padding — fixed capacity instead of the reference's LoD output."""
    bboxes = _x(ins, "BBoxes")
    scores = _x(ins, "Scores")
    score_thresh = float(attrs.get("score_threshold", 0.0))
    nms_thresh = float(attrs.get("nms_threshold", 0.3))
    nms_top_k = int(attrs.get("nms_top_k", 64))
    keep_top_k = int(attrs.get("keep_top_k", 16))
    background = int(attrs.get("background_label", 0))
    n, m, _ = bboxes.shape
    ncls = scores.shape[1]
    nms_top_k = min(nms_top_k, m)

    def one_image(boxes, sc):
        all_scores, all_labels, all_boxes = [], [], []
        for c in range(ncls):
            if c == background:
                continue
            s = jnp.where(sc[c] > score_thresh, sc[c], 0.0)
            order, keep = _nms_keep(boxes, s, nms_thresh, nms_top_k)
            kept_s = jnp.where(keep, s[order], 0.0)
            all_scores.append(kept_s)
            all_labels.append(jnp.full((nms_top_k,), c, jnp.float32))
            all_boxes.append(boxes[order])
        if not all_scores:  # every class was background
            return jnp.concatenate(
                [jnp.full((keep_top_k, 1), -1.0),
                 jnp.zeros((keep_top_k, 5))], axis=1)
        cs = jnp.concatenate(all_scores)
        cl = jnp.concatenate(all_labels)
        cb = jnp.concatenate(all_boxes, axis=0)
        top = jnp.argsort(-cs)[:keep_top_k]
        sel_s = cs[top]
        valid = sel_s > 0
        row = jnp.concatenate(
            [jnp.where(valid, cl[top], -1.0)[:, None], sel_s[:, None],
             cb[top]], axis=1)
        return row

    out = jax.vmap(one_image)(bboxes.astype(jnp.float32),
                              scores.astype(jnp.float32))
    return {"Out": [out]}


@register_op("density_prior_box", no_grad=True)
def _density_prior_box(ins, attrs):
    """Density prior boxes (reference: detection/density_prior_box_op.cc).
    Input [n, c, h, w] feature map, Image [n, c, ih, iw]."""
    feat = _x(ins, "Input")
    img = _x(ins, "Image")
    fixed_sizes = attrs.get("fixed_sizes", [])
    fixed_ratios = attrs.get("fixed_ratios", [1.0])
    densities = attrs.get("densities", [1])
    step_w = float(attrs.get("step_w", 0.0))
    step_h = float(attrs.get("step_h", 0.0))
    offset = float(attrs.get("offset", 0.5))
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    sw = step_w or iw / w
    sh = step_h or ih / h
    boxes = []
    for size, density in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * (ratio ** 0.5)
            bh = size / (ratio ** 0.5)
            shift = size / density
            for dy in range(density):
                for dx in range(density):
                    cx_off = (offset - 0.5 + (dx + 0.5) * shift / sw
                              if density > 1 else offset)
                    cy_off = (offset - 0.5 + (dy + 0.5) * shift / sh
                              if density > 1 else offset)
                    cx = (jnp.arange(w) + cx_off) * sw
                    cy = (jnp.arange(h) + cy_off) * sh
                    cxg = jnp.tile(cx, (h, 1))
                    cyg = jnp.repeat(cy, w).reshape(h, w)
                    boxes.append(jnp.stack([
                        (cxg - bw / 2) / iw, (cyg - bh / 2) / ih,
                        (cxg + bw / 2) / iw, (cyg + bh / 2) / ih,
                    ], axis=-1))
    num = len(boxes)
    out = jnp.clip(jnp.stack(boxes, axis=2), 0.0, 1.0)   # (h, w, num, 4)
    var = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    variances = jnp.broadcast_to(jnp.asarray(var, jnp.float32),
                                 (h, w, num, 4))
    return {"Boxes": [out], "Variances": [variances]}


@register_op("bipartite_match", no_grad=True)
def _bipartite_match(ins, attrs):
    """Greedy bipartite matching (reference:
    detection/bipartite_match_op.cc). DistMat [m, n] (rows: ground
    truth, cols: priors when fed from iou_similarity(gt, prior)); a
    batched [N, m, n] input maps per image — the dense analog of the
    reference's LoD batching."""
    dist = _x(ins, "DistMat")
    if dist.ndim == 3:
        outs = jax.vmap(
            lambda d: _bipartite_match({"DistMat": [d]}, attrs))(dist)
        return {
            "ColToRowMatchIndices": [outs["ColToRowMatchIndices"][0][:, 0]],
            "ColToRowMatchDist": [outs["ColToRowMatchDist"][0][:, 0]],
        }
    m, n = dist.shape
    # Reference semantics (bipartite_match_op.cc): [1, n] per-COLUMN
    # matched ROW indices. Greedy core shared with the fused ssd_loss
    # (box_util.greedy_bipartite_match, incl. the static-unroll fix).
    col_match = greedy_bipartite_match(dist)
    if attrs.get("match_type") == "per_prediction":
        # unmatched columns additionally take their best row when the
        # overlap clears dist_threshold (bipartite_match_op.cc
        # ArgMaxMatch pass)
        thresh = float(attrs.get("dist_threshold", 0.5))
        best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
        best_d = jnp.max(dist, axis=0)
        col_match = jnp.where((col_match < 0) & (best_d >= thresh),
                              best_row, col_match)
    matched_dist = jnp.where(
        col_match >= 0,
        jnp.take_along_axis(
            dist, jnp.maximum(col_match, 0)[None, :], axis=0)[0],
        0.0,
    )
    return {"ColToRowMatchIndices": [col_match[None]],
            "ColToRowMatchDist": [matched_dist[None]]}


# --------------------------------------------------------------------------
# 3-D conv / pool, pooling variants
# --------------------------------------------------------------------------


@register_op("conv3d", diff_inputs=("Input", "Filter"))
def _conv3d(ins, attrs):
    x, w = _x(ins, "Input"), _x(ins, "Filter")
    strides = _pair3(attrs.get("strides", [1, 1, 1]))
    pads = _pair3(attrs.get("paddings", [0, 0, 0]))
    dilations = _pair3(attrs.get("dilations", [1, 1, 1]))
    groups = int(attrs.get("groups", 1))
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides,
        padding=[(p, p) for p in pads],
        rhs_dilation=dilations,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=groups,
    )
    return {"Output": [out]}


@register_op("conv3d_transpose", diff_inputs=("Input", "Filter"))
def _conv3d_transpose(ins, attrs):
    """Gradient-of-conv semantics, filter [C_in, C_out/groups, kd, kh,
    kw] (reference: conv_transpose_op.cc) — the 3-D twin of
    conv2d_transpose, expressed as a fractionally-strided forward conv
    (lhs_dilation) with groups/dilations honored."""
    x, w = _x(ins, "Input"), _x(ins, "Filter")
    sd, sh, sw = _pair3(attrs.get("strides", [1, 1, 1]))
    pd, ph, pw = _pair3(attrs.get("paddings", [0, 0, 0]))
    dd, dh, dw = _pair3(attrs.get("dilations", [1, 1, 1]))
    groups = int(attrs.get("groups", 1))
    kd, kh, kw = jnp.shape(w)[2], jnp.shape(w)[3], jnp.shape(w)[4]
    if groups > 1:
        ci = jnp.shape(w)[0]
        wg = jnp.reshape(w, (groups, ci // groups) + tuple(jnp.shape(w)[1:]))
        wg = jnp.flip(wg, axis=(-3, -2, -1))
        wg = jnp.swapaxes(wg, 1, 2)
        w_eff = jnp.reshape(wg, (-1, ci // groups, kd, kh, kw))
    else:
        w_eff = jnp.swapaxes(jnp.flip(w, axis=(-3, -2, -1)), 0, 1)
    pads_eff = [(dd * (kd - 1) - pd,) * 2, (dh * (kh - 1) - ph,) * 2,
                (dw * (kw - 1) - pw,) * 2]
    out = jax.lax.conv_general_dilated(
        x, w_eff,
        window_strides=(1, 1, 1),
        padding=pads_eff,
        lhs_dilation=(sd, sh, sw),
        rhs_dilation=(dd, dh, dw),
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=groups,
    )
    return {"Output": [out]}


def _pool_nd(x, attrs, spatial):
    ksize = attrs.get("ksize", [2] * spatial)
    strides = attrs.get("strides", ksize)
    pads = attrs.get("paddings", [0] * spatial)
    ptype = attrs.get("pooling_type", "max")
    if isinstance(ksize, int):
        ksize = [ksize] * spatial
    if isinstance(strides, int):
        strides = [strides] * spatial
    if isinstance(pads, int):
        pads = [pads] * spatial
    window = (1, 1) + tuple(ksize)
    stride = (1, 1) + tuple(strides)
    padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pads)
    if attrs.get("global_pooling", False):
        window = (1, 1) + x.shape[2:]
        stride = window
        padding = ((0, 0),) * x.ndim
    if ptype == "max":
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, window, stride, padding)
    s = jax.lax.reduce_window(
        x, 0.0, jax.lax.add, window, stride, padding)
    ones = jnp.ones_like(x)
    cnt = jax.lax.reduce_window(
        ones, 0.0, jax.lax.add, window, stride, padding)
    if attrs.get("exclusive", True):
        return s / cnt
    import math as _math

    return s / float(_math.prod(window))


@register_op("pool3d", diff_inputs=("X",))
def _pool3d(ins, attrs):
    return {"Out": [_pool_nd(_x(ins), attrs, 3)]}


@register_op("max_pool2d_with_index", diff_inputs=("X",))
def _max_pool2d_with_index(ins, attrs):
    """Max pool emitting flat argmax indices (reference:
    max_pool_with_index_op.cc), consumed by unpool."""
    x = _x(ins)
    out = _pool_nd(x, attrs, 2)
    n, c, oh, ow = out.shape
    h, w = x.shape[2], x.shape[3]
    ksize = attrs.get("ksize", [2, 2])
    if isinstance(ksize, int):
        ksize = [ksize, ksize]
    strides = attrs.get("strides", ksize)
    if isinstance(strides, int):
        strides = [strides, strides]
    pads = attrs.get("paddings", [0, 0])
    if isinstance(pads, int):
        pads = [pads, pads]
    # recover indices: for each output cell, find the argmax position
    ys = jnp.arange(oh) * strides[0] - pads[0]
    xs = jnp.arange(ow) * strides[1] - pads[1]

    def cell(img, oy, ox):
        y0, x0 = ys[oy], xs[ox]
        wy = jnp.clip(y0 + jnp.arange(ksize[0]), 0, h - 1)
        wx = jnp.clip(x0 + jnp.arange(ksize[1]), 0, w - 1)
        patch = img[wy][:, wx]
        flat = jnp.argmax(patch)
        iy, ix = flat // ksize[1], flat % ksize[1]
        return (wy[iy] * w + wx[ix]).astype(jnp.int32)

    oy = jnp.repeat(jnp.arange(oh), ow)
    ox = jnp.tile(jnp.arange(ow), oh)
    idx = jax.vmap(
        jax.vmap(lambda img: jax.vmap(lambda a, b: cell(img, a, b))(oy, ox))
    )(x).reshape(n, c, oh, ow)
    return {"Out": [out], "Mask": [idx]}


@register_op("unpool", diff_inputs=("X",))
def _unpool(ins, attrs):
    """Max unpooling via saved indices (reference: unpool_op.cc)."""
    x, idx = _x(ins), _x(ins, "Indices")
    out_h, out_w = attrs["unpooled_height"], attrs["unpooled_width"]
    n, c, h, w = x.shape

    def one(xi, ii):
        flat = jnp.zeros((out_h * out_w,), x.dtype)
        return flat.at[ii.reshape(-1)].add(xi.reshape(-1)).reshape(
            out_h, out_w)

    out = jax.vmap(jax.vmap(one))(x, idx)
    return {"Out": [out]}


@register_op("spp", diff_inputs=("X",))
def _spp(ins, attrs):
    """Spatial pyramid pooling (reference: spp_op.cc): pyramid_height
    levels of global-to-fine pooling, concatenated flat."""
    x = _x(ins)
    levels = int(attrs.get("pyramid_height", 3))
    ptype = attrs.get("pooling_type", "max")
    n, c, h, w = x.shape
    outs = []
    for lvl in range(levels):
        bins = 2 ** lvl
        kh, kw = -(-h // bins), -(-w // bins)  # ceil
        ph, pw = kh * bins - h, kw * bins - w
        lvl_attrs = {"ksize": [kh, kw], "strides": [kh, kw],
                     "paddings": [(ph + 1) // 2, (pw + 1) // 2],
                     "pooling_type": ptype, "exclusive": False}
        o = _pool_nd(x, lvl_attrs, 2)
        outs.append(o.reshape(n, -1))
    return {"Out": [jnp.concatenate(outs, axis=1)]}


@register_op("lrn", diff_inputs=("X",))
def _lrn(ins, attrs):
    """Local response normalization across channels (reference:
    lrn_op.cc)."""
    x = _x(ins)
    nsize = int(attrs.get("n", 5))
    alpha = float(attrs.get("alpha", 1e-4))
    beta = float(attrs.get("beta", 0.75))
    k = float(attrs.get("k", 1.0))
    sq = jnp.square(x)
    half = nsize // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    c = x.shape[1]
    acc = sum(pad[:, i:i + c] for i in range(nsize))
    mid = k + alpha * acc
    return {"Out": [x / jnp.power(mid, beta)], "MidOut": [mid]}


# --------------------------------------------------------------------------
# spatial transforms
# --------------------------------------------------------------------------


@register_op("affine_grid", diff_inputs=("Theta",))
def _affine_grid(ins, attrs):
    """2-D affine sampling grid (reference: affine_grid_op.cc). Theta
    [n, 2, 3] -> Output [n, h, w, 2] normalized coords."""
    theta = _x(ins, "Theta")
    shape = attrs.get("output_shape")
    if shape:
        h, w = int(shape[2]), int(shape[3])
    else:
        out_shape = _x(ins, "OutputShape")
        try:
            h, w = int(out_shape[2]), int(out_shape[3])
        except (jax.errors.TracerIntegerConversionError,
                jax.errors.ConcretizationTypeError) as e:
            raise ValueError(
                "affine_grid: a tensor OutputShape is data-dependent and "
                "cannot set a static XLA shape; pass output_shape as a "
                "Python list instead"
            ) from e
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    grid = jnp.stack([
        jnp.tile(xs, (h, 1)),
        jnp.repeat(ys, w).reshape(h, w),
        jnp.ones((h, w)),
    ], axis=-1)                                      # (h, w, 3)
    out = jnp.einsum("hwk,njk->nhwj", grid, theta)
    return {"Output": [out]}


@register_op("random_crop", needs_rng=True, no_grad=True)
def _random_crop(ins, attrs, rng=None):
    """Random fixed-size crop (reference: random_crop_op.cc). Crops the
    trailing dims to attrs['shape']."""
    x = _x(ins)
    shape = attrs["shape"]
    nd = len(shape)
    lead = x.ndim - nd
    starts = []
    for i, s in enumerate(shape):
        limit = x.shape[lead + i] - s
        key = jax.random.fold_in(rng, i)
        starts.append(
            jax.random.randint(key, (), 0, max(limit, 0) + 1))
    starts_full = [jnp.int32(0)] * lead + starts
    sizes = list(x.shape[:lead]) + list(shape)
    out = jax.lax.dynamic_slice(x, starts_full, sizes)
    return {"Out": [out]}


@register_op("polygon_box_transform", no_grad=True)
def _polygon_box_transform(ins, attrs):
    """EAST-style quad geometry decode (reference:
    detection/polygon_box_transform_op.cc): even channels are x offsets,
    odd channels y offsets; out = 4*coord - in on a 4px grid."""
    x = _x(ins, "Input")
    n, c, h, w = x.shape
    xs = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    ys = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    even = (jnp.arange(c) % 2 == 0)[None, :, None, None]
    return {"Output": [jnp.where(even, 4.0 * xs - x, 4.0 * ys - x)]}


@register_op("psroi_pool", diff_inputs=("X",))
def _psroi_pool(ins, attrs):
    """Position-sensitive RoI average pooling (reference:
    detection/psroi_pool_op.cc): input channels = output_channels*ph*pw;
    bin (i, j) of output channel k averages input channel
    k*ph*pw + i*pw + j over the bin's spatial extent. ROIs [R, 5] rows
    (batch_idx, x1, y1, x2, y2) — dense analog of the LoD rois."""
    x = jnp.asarray(_x(ins))
    rois = jnp.asarray(_x(ins, "ROIs")).astype(jnp.float32)
    out_c = int(attrs["output_channels"])
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    n, c, h, w = x.shape
    if rois.shape[-1] == 5:
        bidx = rois[:, 0].astype(jnp.int32)
        boxes = rois[:, 1:]
    else:
        bidx = jnp.zeros((rois.shape[0],), jnp.int32)
        boxes = rois

    def one(bi, box):
        img = x[bi]                       # [C, H, W]
        x1 = jnp.round(box[0]) * scale
        y1 = jnp.round(box[1]) * scale
        x2 = jnp.round(box[2] + 1.0) * scale
        y2 = jnp.round(box[3] + 1.0) * scale
        rw = jnp.maximum(x2 - x1, 0.1) / pw
        rh = jnp.maximum(y2 - y1, 0.1) / ph
        ii = jnp.arange(h, dtype=jnp.float32)[:, None]
        jj = jnp.arange(w, dtype=jnp.float32)[None, :]
        outs = []
        for i in range(ph):
            for j in range(pw):
                hs, he = y1 + i * rh, y1 + (i + 1) * rh
                ws, we = x1 + j * rw, x1 + (j + 1) * rw
                m = ((ii >= jnp.floor(hs)) & (ii < jnp.ceil(he))
                     & (jj >= jnp.floor(ws)) & (jj < jnp.ceil(we)))
                area = jnp.maximum(jnp.sum(m), 1.0)
                base = (i * pw + j)
                chans = img[base::ph * pw][:out_c]   # [out_c, H, W]
                outs.append(jnp.sum(
                    chans * m[None], axis=(1, 2)) / area)
        o = jnp.stack(outs, 1)            # [out_c, ph*pw]
        return o.reshape(out_c, ph, pw)

    out = jax.vmap(one)(bidx, boxes)
    return {"Out": [out.astype(x.dtype)]}


@register_op("depthwise_conv2d_transpose", diff_inputs=("Input", "Filter"))
def _depthwise_conv2d_transpose(ins, attrs):
    """Depthwise transposed conv = conv2d_transpose with groups = C_in
    (reference: conv_transpose_op.cc registration)."""
    from paddle_tpu.core.registry import get_op_def

    a = dict(attrs)
    a.setdefault("groups", int(jnp.shape(_x(ins, "Input"))[1]))
    return get_op_def("conv2d_transpose").compute(ins, a)


@register_op("max_pool3d_with_index", diff_inputs=("X",))
def _max_pool3d_with_index(ins, attrs):
    """3-D max pool emitting flat argmax indices (reference:
    max_pool_with_index_op.cc)."""
    x = _x(ins)
    out = _pool_nd(x, attrs, 3)
    n, c, od, oh, ow = out.shape
    d, h, w = x.shape[2], x.shape[3], x.shape[4]
    if attrs.get("global_pooling", False):
        ksize, strides, pads = (d, h, w), (d, h, w), (0, 0, 0)
    else:
        ksize = _pair3(attrs.get("ksize", [2, 2, 2]))
        strides = _pair3(attrs.get("strides", ksize))
        pads = _pair3(attrs.get("paddings", [0, 0, 0]))
    zs = jnp.arange(od) * strides[0] - pads[0]
    ys = jnp.arange(oh) * strides[1] - pads[1]
    xs = jnp.arange(ow) * strides[2] - pads[2]

    def cell(vol, oz, oy, ox):
        wz = jnp.clip(zs[oz] + jnp.arange(ksize[0]), 0, d - 1)
        wy = jnp.clip(ys[oy] + jnp.arange(ksize[1]), 0, h - 1)
        wx = jnp.clip(xs[ox] + jnp.arange(ksize[2]), 0, w - 1)
        patch = vol[wz][:, wy][:, :, wx]
        flat = jnp.argmax(patch)
        iz = flat // (ksize[1] * ksize[2])
        rem = flat % (ksize[1] * ksize[2])
        iy, ix = rem // ksize[2], rem % ksize[2]
        return (wz[iz] * h * w + wy[iy] * w + wx[ix]).astype(jnp.int32)

    oz = jnp.repeat(jnp.arange(od), oh * ow)
    oy = jnp.tile(jnp.repeat(jnp.arange(oh), ow), od)
    ox = jnp.tile(jnp.arange(ow), od * oh)
    idx = jax.vmap(
        jax.vmap(lambda v: jax.vmap(
            lambda a, b, e: cell(v, a, b, e))(oz, oy, ox))
    )(x).reshape(n, c, od, oh, ow)
    return {"Out": [out], "Mask": [idx]}


@register_op("similarity_focus", no_grad=True)
def _similarity_focus(ins, attrs):
    """Similarity-focus mask (reference: similarity_focus_op.h): for each
    selected index along ``axis``, greedily pick descending-value
    positions of the remaining two dims with row/col exclusivity (the
    bipartite-greedy pattern), then set 1 at the picked (row, col) across
    the WHOLE focus axis; masks of multiple indexes union."""
    x = _x(ins).astype(jnp.float32)
    axis = int(attrs.get("axis", 1))
    indexes = [int(i) for i in attrs.get("indexes", [0])]
    if x.ndim != 4 or axis not in (1, 2, 3):
        raise ValueError("similarity_focus expects a 4-D input, axis 1-3")
    # move the focus axis to position 1
    perm = [0, axis] + [d for d in (1, 2, 3) if d != axis]
    inv = [perm.index(d) for d in range(4)]
    xt = jnp.transpose(x, perm)                  # [N, C_axis, D2, D3]
    n, _, d2, d3 = xt.shape

    def greedy_mask(plane):                      # [D2, D3] -> 0/1 mask
        def body(_, state):
            mask, vals = state
            idx = jnp.argmax(vals)
            r, c = idx // d3, idx % d3
            ok = vals[r, c] > -jnp.inf
            mask = jnp.where(ok, mask.at[r, c].set(1.0), mask)
            vals = jnp.where(
                ok, vals.at[r, :].set(-jnp.inf).at[:, c].set(-jnp.inf),
                vals)
            return mask, vals

        mask0 = jnp.zeros((d2, d3), jnp.float32)
        mask, _ = jax.lax.fori_loop(0, min(d2, d3), body, (mask0, plane))
        return mask

    masks = []
    for idx in indexes:
        masks.append(jax.vmap(greedy_mask)(xt[:, idx]))
    mask = jnp.minimum(sum(masks), 1.0)          # [N, D2, D3]
    out = jnp.broadcast_to(mask[:, None], xt.shape)
    return {"Out": [jnp.transpose(out, inv).astype(_x(ins).dtype)]}


@register_op("roi_perspective_transform", diff_inputs=("X",))
def _roi_perspective_transform(ins, attrs):
    """Perspective-warp RoI quads to rectangles (reference:
    detection/roi_perspective_transform_op.cc, the EAST/OCR op). X
    [N, C, H, W]; ROIs [R, 9] rows (batch_idx, x1, y1, ..., x4, y4) —
    the dense analog of the LoD [R, 8] + batch offsets. Out
    [R, C, th, tw], bilinear-sampled, zero outside the source bounds."""
    x = jnp.asarray(_x(ins)).astype(jnp.float32)
    rois = jnp.asarray(_x(ins, "ROIs")).astype(jnp.float32)
    th = int(attrs.get("transformed_height", 1))
    tw = int(attrs.get("transformed_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    n, c, h, w = x.shape
    if rois.shape[-1] == 9:
        bidx = rois[:, 0].astype(jnp.int32)
        quads = rois[:, 1:]
    else:
        bidx = jnp.zeros((rois.shape[0],), jnp.int32)
        quads = rois

    def one(bi, q):
        rx = q[0::2] * scale
        ry = q[1::2] * scale
        x0, x1, x2, x3 = rx[0], rx[1], rx[2], rx[3]
        y0, y1, y2, y3 = ry[0], ry[1], ry[2], ry[3]
        # normalized width follows the reference's aspect estimate
        len1 = jnp.sqrt((x0 - x1) ** 2 + (y0 - y1) ** 2)
        len2 = jnp.sqrt((x1 - x2) ** 2 + (y1 - y2) ** 2)
        len3 = jnp.sqrt((x2 - x3) ** 2 + (y2 - y3) ** 2)
        len4 = jnp.sqrt((x3 - x0) ** 2 + (y3 - y0) ** 2)
        est_h = (len2 + len4) / 2.0
        est_w = (len1 + len3) / 2.0
        nh = float(th)
        nw = jnp.minimum(
            jnp.round(est_w * (nh - 1) / jnp.maximum(est_h, 1e-6)) + 1.0,
            float(tw))
        dx1, dx2, dx3 = x1 - x2, x3 - x2, x0 - x1 + x2 - x3
        dy1, dy2, dy3 = y1 - y2, y3 - y2, y0 - y1 + y2 - y3
        # epsilon mirrors the reference kernel's guard; degenerate or
        # single-column quads stay finite instead of NaN-poisoning the
        # whole RoI to zeros
        den = dx1 * dy2 - dx2 * dy1 + 1e-5
        nw1 = jnp.maximum(nw - 1.0, 1e-5)
        nh1 = max(nh - 1.0, 1e-5)
        m6 = (dx3 * dy2 - dx2 * dy3) / den / nw1
        m7 = (dx1 * dy3 - dx3 * dy1) / den / nh1
        m3 = (y1 - y0 + m6 * nw1 * y1) / nw1
        m4 = (y3 - y0 + m7 * nh1 * y3) / nh1
        m0 = (x1 - x0 + m6 * nw1 * x1) / nw1
        m1 = (x3 - x0 + m7 * nh1 * x3) / nh1
        ii = jnp.arange(th, dtype=jnp.float32)[:, None]      # out y
        jj = jnp.arange(tw, dtype=jnp.float32)[None, :]      # out x
        denom = m6 * jj + m7 * ii + 1.0
        sx = (m0 * jj + m1 * ii + x0) / denom
        sy = (m3 * jj + m4 * ii + y0) / denom
        inside = ((sx >= -0.5) & (sx <= w - 0.5)
                  & (sy >= -0.5) & (sy <= h - 0.5)
                  & (jj < nw))
        img = x[bi]
        # clamp BEFORE floor (reference bilinear_interpolate clamps
        # in-bounds), so border-band points interpolate, not extrapolate
        sxc = jnp.clip(sx, 0.0, w - 1.0)
        syc = jnp.clip(sy, 0.0, h - 1.0)
        x0i = jnp.floor(sxc)
        y0i = jnp.floor(syc)
        x1i = jnp.clip(x0i + 1, 0, w - 1)
        y1i = jnp.clip(y0i + 1, 0, h - 1)
        lx, ly = sxc - x0i, syc - y0i
        xi0, yi0 = x0i.astype(jnp.int32), y0i.astype(jnp.int32)
        xi1, yi1 = x1i.astype(jnp.int32), y1i.astype(jnp.int32)
        v = (img[:, yi0, xi0] * (1 - ly) * (1 - lx)
             + img[:, yi1, xi0] * ly * (1 - lx)
             + img[:, yi0, xi1] * (1 - ly) * lx
             + img[:, yi1, xi1] * ly * lx)
        return jnp.where(inside[None], v, 0.0)

    out = jax.vmap(one)(bidx, quads)
    return {"Out": [out.astype(_x(ins).dtype)]}


def _adaptive_pool(x, out_sizes, ptype, spatial):
    """Exact adaptive pooling (reference: pool_op.cc adaptive=True):
    cell i covers [floor(i*L/o), ceil((i+1)*L/o)). Output sizes are
    static, so the cell loop unrolls into slices XLA fuses."""
    in_sizes = x.shape[-spatial:]
    out = x
    for d in range(spatial):
        L, o = in_sizes[d], int(out_sizes[d])
        axis = x.ndim - spatial + d
        cells = []
        for i in range(o):
            lo = (i * L) // o
            hi = -(-((i + 1) * L) // o)  # ceil
            seg = jax.lax.slice_in_dim(out, lo, hi, axis=axis)
            if ptype == "max":
                cells.append(jnp.max(seg, axis=axis, keepdims=True))
            else:
                cells.append(jnp.mean(seg, axis=axis, keepdims=True))
        out = jnp.concatenate(cells, axis=axis)
    return out


@register_op("adaptive_pool2d", diff_inputs=("X",))
def _adaptive_pool2d(ins, attrs):
    return {"Out": [_adaptive_pool(
        _x(ins), attrs["ksize"], attrs.get("pooling_type", "max"), 2)]}


@register_op("adaptive_pool3d", diff_inputs=("X",))
def _adaptive_pool3d(ins, attrs):
    return {"Out": [_adaptive_pool(
        _x(ins), attrs["ksize"], attrs.get("pooling_type", "max"), 3)]}
