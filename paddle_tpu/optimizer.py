"""Optimizers-as-ops (reference: python/paddle/fluid/optimizer.py:50-475).

``minimize`` = append_backward + append optimizer update ops with per-param
accumulators; the whole update is part of the compiled step function, so XLA
fuses it with the backward pass (the analog of the reference's fused
optimizer goal, SURVEY.md section 7 hard part 7).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from paddle_tpu import unique_name
from paddle_tpu.backward import append_backward
from paddle_tpu.framework import (
    Parameter,
    Variable,
    default_main_program,
    program_guard,
)
from paddle_tpu.layer_helper import LayerHelper


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None):
        self._lr_input = learning_rate
        self._lr_var: Optional[Variable] = None
        self.regularization = regularization
        self._name = name
        # {param_name: {acc_name: Variable}}
        self._accumulators: Dict[str, Dict[str, Variable]] = {}
        self.helper: Optional[LayerHelper] = None

    # --- learning rate ---

    def _create_lr_var(self):
        if isinstance(self._lr_input, Variable):
            self._lr_var = self._lr_input
            return
        from paddle_tpu.layers import tensor

        self._lr_var = tensor.create_global_var(
            shape=[1],
            value=float(self._lr_input),
            dtype="float32",
            persistable=True,
            name=unique_name.generate("learning_rate"),
        )

    @property
    def learning_rate(self):
        return self._lr_var

    def _param_lr(self, param: Parameter):
        mult = (param.optimize_attr or {}).get("learning_rate", 1.0)
        if mult == 1.0:
            return self._lr_var
        from paddle_tpu.layers import nn

        return nn.scale(self._lr_var, scale=float(mult))

    # --- accumulators ---

    def _add_accumulator(self, name, param, fill_value=0.0, shape=None, dtype=None):
        from paddle_tpu.layers import tensor

        shape = list(shape if shape is not None else param.shape)
        var = tensor.create_global_var(
            shape=shape,
            value=fill_value,
            dtype=dtype or param.dtype,
            persistable=True,
            name=unique_name.generate(f"{param.name}_{name}"),
        )
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    def slot_descriptor(self) -> Dict[str, Dict[str, str]]:
        """{slot var name -> {"param": owning param, "slot": kind}} for
        every accumulator this optimizer created (moments, velocities,
        beta pows, ...), plus the auto-created learning-rate var.

        This is the identity that survives a rebuild: slot var NAMES
        come from ``unique_name.generate`` and drift whenever a program
        is rebuilt differently (per-stage pipeline programs, a
        differently-ordered build, a warm process's shifted counters),
        but (param, kind) does not. The checkpoint manifest records the
        descriptor per entry (``save_checkpoint(slots=)``), and
        ``checkpoint.reshard_optimizer_state`` re-keys saved slot state
        onto the RESTORING program's names through it."""
        out: Dict[str, Dict[str, str]] = {}
        for kind, d in self._accumulators.items():
            for pname, var in d.items():
                out[var.name] = {"param": pname, "slot": kind}
        if self._lr_var is not None and \
                not isinstance(self._lr_input, Variable):
            # only the var WE created (a user LR-schedule Variable
            # belongs to the program, not the optimizer state)
            out[self._lr_var.name] = {"param": "", "slot": "learning_rate"}
        return out

    # --- hooks for subclasses ---

    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, parameters_and_grads):
        pass

    # --- public API (reference: optimizer.py:352-475) ---

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        """Returns the optimizer update Operators appended to the block."""
        prog = default_main_program()
        block = prog.global_block()
        self._create_lr_var()

        from paddle_tpu import clip as clip_mod
        from paddle_tpu import regularizer as reg_mod

        # Row-sparse (SelectedRows-style) grads bypass clip/regularization
        # and dispatch to the optimizer's sparse op. Silently skipping
        # user-REQUESTED decay/clipping would also skew a global-norm clip
        # (computed over dense grads only), so that combination errors out
        # instead.
        sparse = [(p, g) for p, g in params_grads
                  if getattr(g, "is_selected_rows", False)]
        dense = [(p, g) for p, g in params_grads
                 if not getattr(g, "is_selected_rows", False)]
        for p, _ in sparse:
            if self.regularization is not None or \
                    getattr(p, "regularizer", None) is not None:
                raise NotImplementedError(
                    f"regularization on row-sparse parameter '{p.name}' is "
                    f"not supported; use is_sparse=False for this embedding"
                )
            if clip_mod.clip_applies_to(p.name):
                raise NotImplementedError(
                    f"gradient clipping with row-sparse parameter "
                    f"'{p.name}' is not supported (a global-norm clip over "
                    f"dense grads only would under-clip); use "
                    f"is_sparse=False"
                )
        pre_clip_dense = list(dense)
        dense = clip_mod.append_gradient_clip_ops(dense)
        dense = reg_mod.append_regularization_ops(
            dense, self.regularization
        )
        params_grads = dense + sparse
        self._maybe_instrument_grad_norm(prog, pre_clip_dense)

        self._create_accumulators(block, [p for p, _ in params_grads])
        n_before = len(block.ops)
        for pg in params_grads:
            if getattr(pg[1], "is_selected_rows", False):
                self._append_sparse_optimize_op(block, pg)
            else:
                self._append_optimize_op(block, pg)
        self._finish_update(block, params_grads)
        return block.ops[n_before:]

    def _append_sparse_optimize_op(self, block, param_and_grad):
        raise NotImplementedError(
            f"{type(self).__name__} has no row-sparse update op; use "
            f"SGD/Momentum/Adam for is_sparse=True embeddings, or build "
            f"the embedding with is_sparse=False"
        )

    @staticmethod
    def _maybe_instrument_grad_norm(prog, dense):
        """Numerics-plane grad-norm instrument: with the ``numerics``
        flag on at graph-BUILD time (and no GradientClipByGlobalNorm
        already exporting the norm), append a global-norm reduction over
        the PRE-clip, pre-decay dense gradients — the same semantics the
        clip path exports, so ``pt_grad_global_norm`` always means the
        raw-gradient norm — and register it as an aux var. Flag-gated at
        build so default-off programs carry zero extra ops; unused the
        ops are DCE'd by XLA anyway."""
        from paddle_tpu import flags as _flags

        if not _flags.get_flag("numerics"):
            return
        from paddle_tpu import numerics

        if any(k == "grad_global_norm"
               for k, _ in getattr(prog, "_numerics_aux", ())):
            return
        grads = [g for _, g in dense if g is not None]
        if not grads:
            return
        from paddle_tpu.layer_helper import LayerHelper
        from paddle_tpu.layers import nn

        helper = LayerHelper("grad_norm_instrument")
        sq = []
        for g in grads:
            out = helper.create_variable_for_type_inference(dtype=g.dtype)
            helper.append_op("squared_l2_norm", inputs={"X": g},
                             outputs={"Out": out})
            sq.append(out)
        norm = nn.sqrt(nn.sums(sq))
        numerics.register_aux(prog, "grad_global_norm", norm.name)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from paddle_tpu.dygraph import base as dy_base

        if dy_base._in_dygraph_mode():
            return self._dygraph_minimize(loss, parameter_list)
        params_grads = self.backward(
            loss, startup_program, parameter_list, no_grad_set
        )
        opt_ops = self.apply_gradients(params_grads)
        return opt_ops, params_grads

    # --- dygraph (eager) path ---
    #
    # The eager twin of apply_gradients (reference: optimizer.py dygraph
    # branch of backward()/minimize()): the per-class _append_optimize_op
    # logic is reused verbatim by tracing it once into a throwaway Program
    # whose vars mirror the eager parameters by name, then jitting one
    # function (params, grads, state) -> (params', state') over the traced
    # op list. Accumulator state lives on the optimizer as jax arrays.

    def _dygraph_build(self, params):
        import jax
        import numpy as np

        from paddle_tpu.core.interp import exec_ops
        from paddle_tpu.framework import Program

        if isinstance(self._lr_input, Variable):
            raise TypeError(
                "dygraph minimize needs a float learning rate (static LR "
                "schedule variables belong to a Program)"
            )
        # Carry accumulator state (moments, beta pows, ...) across rebuilds
        # triggered by a changed trainable-parameter set: state is keyed by
        # (accumulator kind, param name), which survives var renaming.
        old_acc = {}
        if getattr(self, "_dy_state", None) is not None:
            for kind, d in self._accumulators.items():
                for pname, var in d.items():
                    if var.name in self._dy_state:
                        old_acc[(kind, pname)] = self._dy_state[var.name]

        prog, startup = Program(), Program()
        with program_guard(prog, startup):
            block = prog.global_block()
            fake_pgs = []
            for p in params:
                dtype = str(np.dtype(p.dtype))
                fp = block.create_parameter(
                    p.name,
                    list(p.shape),
                    dtype,
                    optimize_attr=getattr(
                        p, "optimize_attr", {"learning_rate": 1.0}
                    ),
                    regularizer=getattr(p, "regularizer", None),
                )
                g = block.create_var(
                    name=fp.grad_name, shape=list(p.shape), dtype=dtype
                )
                fake_pgs.append((fp, g))
            opt_ops = self.apply_gradients(fake_pgs)
        del opt_ops  # the full main-block op list includes clip/reg ops
        update_ops = list(prog.global_block().ops)
        state0 = exec_ops(
            list(startup.global_block().ops), {}, key=None, amp=False
        )
        for kind, d in self._accumulators.items():
            for pname, var in d.items():
                if (kind, pname) in old_acc and var.name in state0:
                    state0[var.name] = old_acc[(kind, pname)]
        state_names = sorted(state0)
        param_names = [p.name for p in params]

        def step(state, param_vals, grad_vals):
            env = dict(state)
            for n, v, g in zip(param_names, param_vals, grad_vals):
                env[n] = v
                env[n + "@GRAD"] = g
            exec_ops(update_ops, env, key=None, amp=False)
            return (
                [env[n] for n in param_names],
                {n: env[n] for n in state_names},
            )

        self._dy_state = {n: state0[n] for n in state_names}
        self._dy_step = jax.jit(step)
        self._dy_param_names = param_names

    def _dygraph_minimize(self, loss, parameter_list):
        if not parameter_list:
            raise ValueError(
                "minimize() in dygraph mode requires parameter_list "
                "(e.g. model.parameters())"
            )
        # Only parameters reached by this step's backward get updated —
        # matching the static path, where apply_gradients sees exactly the
        # params on the loss's op path (untouched params must not drift
        # from regularization/moment updates).
        params = [
            p
            for p in parameter_list
            if not p.stop_gradient and p._grad is not None
        ]
        if not params:
            # The reference's eager contract: the user calls
            # loss.backward() first, then minimize() applies the collected
            # gradients. Auto-running backward here would silently reuse
            # stale gradients on later iterations.
            raise RuntimeError(
                "minimize() in dygraph mode found no gradients; call "
                "loss.backward() before minimize(), and "
                "clear_gradients() after each step"
            )
        if getattr(self, "_dy_step", None) is None or [
            p.name for p in params
        ] != self._dy_param_names:
            self._dygraph_build(params)
        grads = [p._grad for p in params]
        new_vals, self._dy_state = self._dy_step(
            self._dy_state, [p._value for p in params], grads
        )
        for p, v in zip(params, new_vals):
            p._value = v
        return [], [(p, p._grad) for p in params]


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op(
            "sgd",
            inputs={"Param": p, "Grad": g, "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p.name},
        )

    def _append_sparse_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op(
            "sgd_sparse",
            inputs={"Param": p, "Rows": g.sparse_rows_name,
                    "Values": g.sparse_values_name,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p.name},
        )


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        block.append_op(
            "momentum",
            inputs={"Param": p, "Grad": g, "Velocity": v,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p.name, "VelocityOut": v.name},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )

    def _append_sparse_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        block.append_op(
            "momentum_sparse",
            inputs={"Param": p, "Rows": g.sparse_rows_name,
                    "Values": g.sparse_values_name, "Velocity": v,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p.name, "VelocityOut": v.name},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class DGCMomentumOptimizer(MomentumOptimizer):
    """Momentum with Deep Gradient Compression (reference:
    optimizer.py:696 DGCMomentumOptimizer; paper arXiv:1712.01887).
    Gradients are momentum-corrected into residual accumulators, only
    the top-k entries are exchanged each step (allgather of
    (index, value) pairs over the data/slice axis — see
    parallel/dgc.py for the TPU collective design and the static-k
    divergence note), and the rest accumulate locally until large
    enough to send. Sparsity ramps per ``sparsity``/``rampup_step``
    after ``rampup_begin_step``; before that the update is exactly
    dense momentum.

    Reference parity notes: parameters under 16384 elements or with
    non-fp32 dtype stay on the dense momentum path (the reference's
    _append_dgc_ops gate); ``local_grad_clip_norm`` clips the
    pre-compression gradient to ``local_grad_clip_norm /
    num_trainers**2`` past rampup (dgc_clip_by_norm_op.h). Static
    graph only, like the reference."""

    _DGC_MIN_NUMEL = 16384

    def __init__(self, learning_rate, momentum, rampup_begin_step,
                 rampup_step=1, sparsity=(0.999,), use_nesterov=False,
                 local_grad_clip_norm=None, num_trainers=None,
                 regularization=None, name=None):
        super().__init__(learning_rate, momentum, use_nesterov,
                         regularization, name)
        self._sparsity = list(sparsity)
        self._rampup_begin_step = float(rampup_begin_step)
        self._rampup_step = float(rampup_step)
        self._clip_norm = None
        if local_grad_clip_norm is not None:
            if not isinstance(num_trainers, int) or num_trainers <= 0:
                raise ValueError(
                    "local_grad_clip_norm needs num_trainers (the world "
                    "size the clip is scaled by)")
            self._clip_norm = float(local_grad_clip_norm) / (
                num_trainers * num_trainers)
        self._step_var = None

    def _dgc_eligible(self, param) -> bool:
        numel = 1
        for d in param.shape or ():
            numel *= int(d)
        return (numel >= self._DGC_MIN_NUMEL
                and str(param.dtype) in ("float32", "FP32"))

    def _create_accumulators(self, block, parameters):
        from paddle_tpu.layers import tensor

        super()._create_accumulators(block, parameters)
        for p in parameters:
            if self._dgc_eligible(p):
                self._add_accumulator("dgc_u", p)
                self._add_accumulator("dgc_v", p)
        if self._step_var is None:
            # the reference's kDGCCounterName global counter: starts at
            # -1, a prepended increment makes it 0 on the first step
            self._step_var = tensor.create_global_var(
                shape=[1], value=-1.0, dtype="float32", persistable=True,
                name=unique_name.generate("dgc_counter"))
            block._prepend_op(
                "increment", inputs={"X": [self._step_var.name]},
                outputs={"Out": [self._step_var.name]},
                attrs={"step": 1.0})

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        if not self._dgc_eligible(p):
            return super()._append_optimize_op(block, param_and_grad)
        v = self._get_accumulator("velocity", p)
        u_acc = self._get_accumulator("dgc_u", p)
        v_acc = self._get_accumulator("dgc_v", p)
        attrs = {"mu": self._momentum,
                 "use_nesterov": self._use_nesterov,
                 "sparsity": list(self._sparsity),
                 "rampup_begin_step": self._rampup_begin_step,
                 "rampup_step": self._rampup_step}
        if self._clip_norm is not None:
            attrs["local_grad_clip_norm"] = self._clip_norm
        block.append_op(
            "dgc_momentum",
            inputs={"Param": p, "Grad": g, "Velocity": v, "U": u_acc,
                    "V": v_acc, "LearningRate": self._param_lr(p),
                    "CurrentStep": self._step_var},
            outputs={"ParamOut": p.name, "VelocityOut": v.name,
                     "UOut": u_acc.name, "VOut": v_acc.name},
            attrs=attrs,
        )

    def _dygraph_build(self, params):
        raise NotImplementedError(
            "DGCMomentumOptimizer is static-graph only (as in the "
            "reference); use MomentumOptimizer in dygraph mode")


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        block.append_op(
            "lars_momentum",
            inputs={"Param": p, "Grad": g, "Velocity": v,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p.name, "VelocityOut": v.name},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay},
        )


class AdamOptimizer(Optimizer):
    _op_type = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None, lazy_mode=False):
        super().__init__(learning_rate, regularization, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow", p, fill_value=1.0, shape=[1])
            self._add_accumulator("beta2_pow", p, fill_value=1.0, shape=[1])

    def _extra_attrs(self):
        return {}

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow", p)
        b2p = self._get_accumulator("beta2_pow", p)
        block.append_op(
            self._op_type,
            inputs={"Param": p, "Grad": g, "Moment1": m1, "Moment2": m2,
                    "Beta1Pow": b1p, "Beta2Pow": b2p,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p.name, "Moment1Out": m1.name,
                     "Moment2Out": m2.name, "Beta1PowOut": b1p.name,
                     "Beta2PowOut": b2p.name},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, **self._extra_attrs()},
        )

    def _append_sparse_optimize_op(self, block, param_and_grad):
        # Lazy Adam on the touched rows (reference: adam_op.h lazy_mode)
        p, g = param_and_grad
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow", p)
        b2p = self._get_accumulator("beta2_pow", p)
        block.append_op(
            "adam_sparse",
            inputs={"Param": p, "Rows": g.sparse_rows_name,
                    "Values": g.sparse_values_name, "Moment1": m1,
                    "Moment2": m2, "Beta1Pow": b1p, "Beta2Pow": b2p,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p.name, "Moment1Out": m1.name,
                     "Moment2Out": m2.name, "Beta1PowOut": b1p.name,
                     "Beta2PowOut": b2p.name},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon},
        )


class AdamWOptimizer(AdamOptimizer):
    _op_type = "adamw"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, weight_decay=0.01, regularization=None,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, regularization, name)
        self._weight_decay = weight_decay

    def _extra_attrs(self):
        return {"weight_decay": self._weight_decay}

    def _append_sparse_optimize_op(self, block, param_and_grad):
        # inheriting adam_sparse would silently drop the decoupled decay
        return Optimizer._append_sparse_optimize_op(
            self, block, param_and_grad)


class LambOptimizer(AdamOptimizer):
    _op_type = "lamb"

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, regularization=None,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, regularization, name)
        self._weight_decay = lamb_weight_decay

    def _extra_attrs(self):
        return {"weight_decay": self._weight_decay}

    def _append_sparse_optimize_op(self, block, param_and_grad):
        # inheriting adam_sparse would silently drop the trust-ratio rule
        return Optimizer._append_sparse_optimize_op(
            self, block, param_and_grad)


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, regularization=None,
                 name=None, initial_accumulator_value=0.0):
        super().__init__(learning_rate, regularization, name)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        block.append_op(
            "adagrad",
            inputs={"Param": p, "Grad": g, "Moment": m,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p.name, "MomentOut": m.name},
            attrs={"epsilon": self._epsilon},
        )


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        block.append_op(
            "decayed_adagrad",
            inputs={"Param": p, "Grad": g, "Moment": m,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p.name, "MomentOut": m.name},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
        )


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("moment", p)
            if self._centered:
                self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        ms = self._get_accumulator("mean_square", p)
        mom = self._get_accumulator("moment", p)
        inputs = {"Param": p, "Grad": g, "MeanSquare": ms, "Moment": mom,
                  "LearningRate": self._param_lr(p)}
        outputs = {"ParamOut": p.name, "MeanSquareOut": ms.name,
                   "MomentOut": mom.name}
        if self._centered:
            mg = self._get_accumulator("mean_grad", p)
            inputs["MeanGrad"] = mg
            outputs["MeanGradOut"] = mg.name
        block.append_op(
            "rmsprop", inputs=inputs, outputs=outputs,
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered},
        )


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        sq = self._get_accumulator("squared", p)
        lin = self._get_accumulator("linear", p)
        block.append_op(
            "ftrl",
            inputs={"Param": p, "Grad": g, "SquaredAccumulator": sq,
                    "LinearAccumulator": lin,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p.name, "SquaredAccumOut": sq.name,
                     "LinearAccumOut": lin.name},
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


class AdamaxOptimizer(Optimizer):
    """Adamax (reference: optimizer.py:41 'Adamax', AdamaxOptimizer)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow", p, fill_value=1.0, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        u = self._get_accumulator("inf_norm", p)
        b1p = self._get_accumulator("beta1_pow", p)
        block.append_op(
            "adamax",
            inputs={"Param": p, "Grad": g, "Moment": m, "InfNorm": u,
                    "Beta1Pow": b1p, "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p.name, "MomentOut": m.name,
                     "InfNormOut": u.name, "Beta1PowOut": b1p.name},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    """Adadelta (reference: optimizer.py:41 'Adadelta'); the op applies
    the classic learning-rate-free rule, matching the reference kernel."""

    def __init__(self, learning_rate=1.0, epsilon=1e-6, rho=0.95,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        eg2 = self._get_accumulator("avg_squared_grad", p)
        edx2 = self._get_accumulator("avg_squared_update", p)
        block.append_op(
            "adadelta",
            inputs={"Param": p, "Grad": g, "AvgSquaredGrad": eg2,
                    "AvgSquaredUpdate": edx2,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p.name, "AvgSquaredGradOut": eg2.name,
                     "AvgSquaredUpdateOut": edx2.name},
            attrs={"rho": self._rho, "epsilon": self._epsilon},
        )


# Short aliases matching the reference's public names.
SGD = SGDOptimizer
Momentum = MomentumOptimizer
DGCMomentum = DGCMomentumOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adagrad = AdagradOptimizer
Adamax = AdamaxOptimizer
Adadelta = AdadeltaOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer


class ExponentialMovingAverage:
    """EMA of parameters (reference: optimizer.py:2292). ``update()`` appends
    shadow-update ops to the main program; ``apply(executor)``/``restore``
    swap shadow and live values in the scope for evaluation."""

    def __init__(self, decay=0.999, name=None):
        self._decay = decay
        self._name = name or "ema"
        self._shadows: List[Tuple[Variable, Variable]] = []
        self._backup: Dict[str, object] = {}
        self._step_var = None

    def update(self):
        from paddle_tpu.layers import nn, tensor

        prog = default_main_program()
        block = prog.global_block()
        # Step counter for zero-debiasing: shadows start at 0, so the raw
        # EMA is biased low by (1 - decay^t) (reference: optimizer.py:2292).
        self._step_var = tensor.create_global_var(
            shape=[1], value=0.0, dtype="float32", persistable=True,
            name=unique_name.generate(f"{self._name}_step"),
        )
        bumped = nn.scale(block.var(self._step_var.name), scale=1.0, bias=1.0)
        block.append_op("assign", inputs={"X": bumped},
                        outputs={"Out": self._step_var.name})
        for p in prog.all_parameters():
            if not p.trainable:
                continue
            shadow = tensor.create_global_var(
                shape=list(p.shape), value=0.0, dtype=p.dtype,
                persistable=True,
                name=unique_name.generate(f"{self._name}_{p.name}"),
            )
            # shadow = decay*shadow + (1-decay)*param
            scaled = nn.scale(block.var(shadow.name), scale=self._decay)
            contrib = nn.scale(block.var(p.name), scale=1.0 - self._decay)
            summed = nn.elementwise_add(scaled, contrib)
            block.append_op("assign", inputs={"X": summed},
                            outputs={"Out": shadow.name})
            self._shadows.append((p, shadow))

    def apply(self, executor=None, need_restore: bool = True):
        """Swap EMA values into the live parameters (scope-level).

        Values are copied to host arrays: Executor runs donate scope buffers
        to XLA, so aliasing one jax.Array under two scope names (or keeping a
        reference across a run) would leave dangling device buffers."""
        import contextlib

        import numpy as np

        from paddle_tpu.executor import global_scope

        scope = global_scope()
        # zero-debias: shadow / (1 - decay^t)
        correction = 1.0
        if self._step_var is not None:
            sv = scope.find_var(self._step_var.name)
            t = float(np.asarray(sv).reshape(-1)[0]) if sv is not None else 0.0
            if t > 0:
                correction = 1.0 / (1.0 - self._decay ** t)
        for p, shadow in self._shadows:
            if need_restore:
                self._backup[p.name] = np.asarray(scope.find_var(p.name))
            sv = scope.find_var(shadow.name)
            if sv is not None:
                scope.set(p.name, np.asarray(sv) * correction)

        @contextlib.contextmanager
        def _guard():
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return _guard()

    def restore(self, executor=None):
        from paddle_tpu.executor import global_scope

        scope = global_scope()
        for name, val in self._backup.items():
            scope.set(name, val)
        self._backup.clear()


class ModelAverage(Optimizer):
    """Placeholder for reference optimizer.py:2132; full averaging windows
    land with the high-level Trainer."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None):
        super().__init__(0.0, regularization, name)
