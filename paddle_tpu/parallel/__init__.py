"""Parallelism: device meshes, sharding strategies, collectives, long-context.

TPU-native replacement for the reference's distributed stack (SURVEY.md
section 2.3): NCCL allreduce rings / hierarchical allreduce / gradient
fusion (reference: platform/nccl_helper.h:90-210,
details/all_reduce_op_handle.cc:86, fuse_all_reduce_op_pass.cc) become
GSPMD shardings over a jax Mesh with XLA collectives on ICI; the
parameter-server path (reference: operators/distributed_ops/
listen_and_serv_op.cc:109) becomes sharded embedding tables + all-to-all
(embedding.py); ring attention covers the long-context capability the
reference lacks (SURVEY.md section 5).
"""

from paddle_tpu.parallel import checkpoint  # noqa: F401
from paddle_tpu.parallel.mesh import (  # noqa: F401
    create_mesh,
    create_slice_mesh,
    get_mesh,
    set_mesh,
)
from paddle_tpu.parallel.strategy import (  # noqa: F401
    DistributedStrategy,
    ShardingRule,
    transformer_rules,
)
