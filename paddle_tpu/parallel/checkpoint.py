"""Sharded, optionally-async, crash-consistent, topology-independent
checkpointing.

The TPU-native replacement for the reference's distributed checkpointing,
where parameters sliced across pservers are saved per-server and re-merged
on load (reference: io.py:282 ``_save_distributed_persistables``, slice
concat io.py:315-360; trainer serial-numbered checkpoint dirs
contrib/trainer.py:100,580). Here the unit is a sharded ``jax.Array``:

- each PROCESS writes only its addressable shards (one ``.npz`` per
  process) plus a manifest fragment of {name -> GLOBAL shape, dtype,
  sharding descriptor, shard index ranges, per-array crc32}, so
  multi-host saves never gather the model onto one host;
- restore reassembles the global value from whatever shard files are
  present — a PARTIAL subset is accepted whenever the surviving shards
  still cover every element (replica coverage), and a subset that does
  not raises a structured ``IOError`` naming the absent shard files —
  and can re-shard the result straight onto the restoring program's
  ``in_shardings`` (``reshard`` / the ``shardings=`` parameter), so a
  checkpoint saved on a 2x4 mesh restores bit-exact onto 1x8, onto a
  shrunk 4-process world, or onto a single host. The manifest carries
  everything needed (format v2: global shape + dtype + sharding spec per
  array); nothing about restore depends on the saving topology;
- ``async_save=True`` issues every device->host copy up front
  (``copy_to_host_async``, overlapping the transfers with each other),
  materializes the host snapshot in the caller's thread — timed into
  ``pt_ckpt_snapshot_seconds`` — and runs checksum + serialize + commit
  on a background thread, overlapping them with the next training steps
  (the orbax async-checkpoint pattern). Snapshotting in the caller is
  what makes the overlap SAFE: the next step may donate the parameter
  buffers, so device arrays must not be read after return.

Crash-consistent commit protocol (the orbax commit-marker pattern)::

    write  checkpoint_<N>.tmp/shards_<pid>.npz      (fsync)
    write  checkpoint_<N>.tmp/manifest.json.<pid>   (fsync)
    -- multi-host: every writer p>0 kv-acks; process 0 collects the
       acks (retry.py-backed fleet KV, deadline-budgeted) BEFORE the
       marker, and kv-publishes after the pointer flip --
    write  checkpoint_<N>.tmp/COMMIT                (fsync, process 0)
    rename checkpoint_<N>.tmp -> checkpoint_<N>     (atomic publish)
    write  latest.tmp; rename -> latest             (atomic pointer)

A crash at ANY point leaves either a ``.tmp`` staging dir (ignored by
``available_steps``/``latest_step``) or a fully committed serial: resume
can never observe a half-written checkpoint. ``validate_checkpoint``
additionally proves integrity (COMMIT marker, replica-coverage of every
array by the shards present, crc32 match), and ``latest_step`` skips
invalid serials — counting them into ``pt_ckpt_invalid_skipped_total`` —
falling back to the newest valid one. Multi-host commits ride the
``FleetCommitCoordinator`` barrier above (auto-engaged when the fleet is
initialized), closing the late-writer race the single-host protocol
could not see.
"""

from __future__ import annotations

import atexit
import json
import os
import shutil
import threading
import time as _time
import warnings
import zlib
from typing import Dict, List, Optional

import jax
import numpy as np

from paddle_tpu import faults as _faults
from paddle_tpu import monitor as _monitor
from paddle_tpu import retry as _retry
from paddle_tpu.parallel import mesh as _mesh

_MANIFEST = "manifest.json"
_LATEST = "latest"
_COMMIT = "COMMIT"
_STAGING_SUFFIX = ".tmp"
# manifest/COMMIT format: v2 adds the per-array sharding descriptor and
# the partial-subset restore contract (v1 checkpoints load unchanged)
_FORMAT = 2

_M_COMMIT_S = _monitor.histogram(
    "pt_ckpt_commit_seconds",
    "checkpoint commit-protocol duration (multi-host ack collection + "
    "COMMIT marker -> published latest pointer)")
_M_SNAPSHOT_S = _monitor.histogram(
    "pt_ckpt_snapshot_seconds",
    "device->host checkpoint snapshot duration (all copies issued "
    "asynchronously up front, then materialized)")
_M_INVALID_SKIPS = _monitor.counter(
    "pt_ckpt_invalid_skipped_total",
    "uncommitted/corrupt checkpoint serials skipped while resolving the "
    "newest valid one")
_M_ASYNC_ERRS = _monitor.counter(
    "pt_ckpt_async_errors_total",
    "background checkpoint-save failures surfaced outside wait()")
_M_PARTIAL = _monitor.counter(
    "pt_ckpt_partial_restores_total",
    "arrays reassembled from a partial shard-file subset whose surviving "
    "shards still covered every element")
_M_SLOT_REKEYS = _monitor.counter(
    "pt_ckpt_slot_rekeys_total",
    "optimizer slot-state entries re-keyed onto a differently-built "
    "restoring program's slot names via the manifest's (param, kind) "
    "descriptors (reshard_optimizer_state)")

_F_WRITE = _faults.site("ckpt.write_shards")
_F_COMMIT = _faults.site("ckpt.commit")
_F_READ = _faults.site("ckpt.read")


def _fsync_dir(path: str):
    """Durably record a rename/create in its parent directory."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse dir fsync; rename is still atomic
    finally:
        os.close(fd)


def _fsync_file(path: str):
    """Flush an already-written file's data to disk (read-only open —
    shared by the inference-export publish path)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _checksum(a: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(a).tobytes())


def _shard_slices(arr) -> List[dict]:
    """Addressable shards of a jax.Array as JSON-able index metadata."""
    out = []
    for sh in arr.addressable_shards:
        idx = []
        for sl, dim in zip(sh.index, arr.shape):
            start = 0 if sl.start is None else int(sl.start)
            stop = dim if sl.stop is None else int(sl.stop)
            idx.append([start, stop])
        out.append({"index": idx, "replica_id": int(sh.replica_id)})
    return out


def _fkey_file(fkey: str) -> str:
    """Shard file that holds a manifest shard key (``name::pid::i``)."""
    try:
        pid = fkey.rsplit("::", 2)[1]
        return f"shards_{pid}.npz"
    except IndexError:
        return "shards_0.npz"


def _copy_async(arr):
    """Start a device->host transfer without blocking; materializing the
    same array later finds the bytes already (or soon) resident."""
    try:
        arr.copy_to_host_async()
    except AttributeError:
        pass  # host numpy / older jax: np.asarray below does the copy


# ---------------------------------------------------------------------------
# multi-host commit coordination (the barrier the v1 docstring admitted
# it was missing)
# ---------------------------------------------------------------------------

# one logical save = one coordination round; the counter gives repeated
# saves of the SAME serial fresh KV keys (the same SPMD call-sequence
# discipline fleet.barrier_or_dead uses for its epoch numbers)
_COORD_SEQ_LOCK = threading.Lock()
_coord_seq = 0


def _next_coord_seq() -> int:
    global _coord_seq
    with _COORD_SEQ_LOCK:
        _coord_seq += 1
        return _coord_seq


class FleetCommitCoordinator:
    """COMMIT/publish coordination over the fleet KV store: writers with
    rank > 0 ack once their shard + manifest files are durable, process 0
    collects every ack BEFORE writing the COMMIT marker, and publishes a
    KV key after the pointer flip so non-zero writers return only once
    the serial is observable. All KV traffic rides fleet.put/get, i.e.
    the unified retry.py backoff + deadline policies; a dead writer
    surfaces as a TimeoutError on process 0 (save fails, staging dir
    stays staged, resume falls back to the previous valid serial).
    """

    def __init__(self, fleet=None, timeout_ms: Optional[int] = None):
        if fleet is None:
            from paddle_tpu.incubate.fleet import fleet as _fleet

            fleet = _fleet
        self._fleet = fleet
        self.rank = fleet.worker_index()
        self.world = fleet.worker_num()
        if timeout_ms is None:
            from paddle_tpu import flags as _flags

            timeout_ms = _flags.get_flag("rpc_deadline_ms")
        self._timeout_ms = int(timeout_ms)

    def _key(self, kind: str, seq: int, step: int, rank=None) -> str:
        tail = "" if rank is None else f"/{rank}"
        return f"ckpt/{kind}/{seq}:{step}{tail}"

    def ack_write(self, seq: int, step: int):
        self._fleet.put(self._key("ack", seq, step, self.rank), b"1")

    def wait_writers(self, seq: int, step: int):
        """Process 0: block until EVERY non-zero writer acked, under one
        shared deadline budget across the sequential gets."""
        dl = _retry.Deadline(self._timeout_ms / 1000.0)
        for r in range(1, self.world):
            self._fleet.get(self._key("ack", seq, step, r),
                            timeout_ms=max(1, dl.remaining_ms()))

    def publish(self, seq: int, step: int):
        self._fleet.put(self._key("pub", seq, step), b"1")

    def wait_published(self, seq: int, step: int):
        self._fleet.get(self._key("pub", seq, step),
                        timeout_ms=self._timeout_ms)


def _resolve_coordinator(coordinator):
    """``"auto"`` -> a FleetCommitCoordinator when the fleet is up with
    >1 workers, else None (single-host protocol); explicit
    None/coordinator objects pass through."""
    if coordinator != "auto":
        return coordinator
    try:
        from paddle_tpu.incubate.fleet import fleet as _fleet

        if _fleet._initialized and _fleet.worker_num() > 1:
            return FleetCommitCoordinator(_fleet)
    except Exception:  # pragma: no cover - fleet plane absent/broken
        pass
    return None


# ---------------------------------------------------------------------------
# async handles: a failed background save must never vanish
# ---------------------------------------------------------------------------

_HANDLES_LOCK = threading.Lock()
_async_handles: List["_AsyncHandle"] = []


class _AsyncHandle:
    __slots__ = ("_thread", "error", "step", "_surfaced")

    def __init__(self, step: int):
        self._thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None
        self.step = step
        self._surfaced = False

    def done(self) -> bool:
        # ident is None until the thread starts — and is_alive() is
        # False then too, so without the ident check a reap racing the
        # handle's registration would drop it (and its eventual error)
        t = self._thread
        return t is not None and t.ident is not None and not t.is_alive()

    def wait(self):
        """Join the background write; raises its error. Idempotent —
        safe to call any number of times (each call re-raises a stored
        error rather than losing it)."""
        t = self._thread
        if t is not None:
            t.join()
        self._surfaced = True
        if self.error is not None:
            raise self.error


def _reap_async(final: bool = False):
    """Surface errors of finished handles nobody ``wait()``ed (called at
    every save and at exit, so a failed background save is loud at most
    one save later). ``final`` joins still-running writers first."""
    with _HANDLES_LOCK:
        handles = list(_async_handles)
    for h in handles:
        if final and h._thread is not None:
            h._thread.join(timeout=30.0)
        if not h.done():
            continue
        with _HANDLES_LOCK:
            if h in _async_handles:
                _async_handles.remove(h)
        if h.error is not None and not h._surfaced:
            h._surfaced = True
            _M_ASYNC_ERRS.inc()
            warnings.warn(
                f"async checkpoint save (step {h.step}) failed and was "
                f"never wait()ed: {h.error!r}", RuntimeWarning)


atexit.register(_reap_async, final=True)


# ---------------------------------------------------------------------------
# save
# ---------------------------------------------------------------------------


def save_checkpoint(
    dirname: str,
    state: Dict[str, object],
    step: int = 0,
    async_save: bool = False,
    coordinator="auto",
    process_index: Optional[int] = None,
    slots: Optional[Dict[str, dict]] = None,
):
    """Write ``state`` (name -> array) to ``dirname/checkpoint_<step>``
    via the staging-dir commit protocol (module docstring).

    Sharded arrays: this process writes its addressable, replica-0 shards
    and records the GLOBAL shape/dtype/sharding in its manifest fragment.
    Host numpy / replicated values: only process 0 writes. Multi-host,
    the COMMIT/publish is coordinated through ``coordinator`` ("auto" =
    a FleetCommitCoordinator when the fleet is initialized; pass None to
    force the uncoordinated single-host protocol). ``process_index``
    overrides the shard-file naming rank (defaults to
    ``jax.process_index()``; the commit-barrier tests simulate a world
    with it). Returns an ``_AsyncHandle`` when ``async_save`` (call
    ``.wait()`` before relying on the files), else None — with
    ``async_save`` only the device->host snapshot happens here; checksum,
    serialization and the commit run on a background thread.

    ``slots`` ({var name -> {"param": ..., "slot": ...}}, e.g. an
    ``Optimizer.slot_descriptor()``) records the optimizer slot-state
    descriptor on each covered manifest entry, so a restore into a
    DIFFERENTLY-BUILT program (per-stage pipeline layouts, drifted
    unique-name counters) can re-key the state through
    ``reshard_optimizer_state`` instead of silently dropping it.
    """
    _reap_async()
    ckpt_dir = os.path.join(dirname, f"checkpoint_{step}")
    stage_dir = ckpt_dir + _STAGING_SUFFIX
    coord = _resolve_coordinator(coordinator)
    if process_index is not None:
        pid = int(process_index)
    elif coord is not None:
        # the writer identity that names shard files / manifest
        # fragments: the FLEET rank when a commit coordinator is
        # engaged. Identical to jax.process_index() in a jax.distributed
        # fleet, but in a coordination-only fleet (PT_COORD_ONLY) every
        # rank's jax process index is 0 — four writers would clobber one
        # shards_0.npz mid-commit
        pid = coord.rank
    else:
        pid = jax.process_index()
    rank = coord.rank if coord is not None else pid
    seq = _next_coord_seq() if coord is not None else 0

    # Pass 1: issue EVERY device->host copy before materializing any —
    # the transfers overlap each other instead of round-tripping one by
    # one (the orbax async-snapshot shape).
    manifest: Dict[str, dict] = {}
    snap: List[tuple] = []  # (file key, array ref) pending materialize
    for name, v in state.items():
        key = name.replace("/", "__")
        if isinstance(v, jax.Array) and len(v.sharding.device_set) > 1:
            entry = {
                "shape": list(v.shape),
                "dtype": str(v.dtype),
                "sharded": True,
                "shards": {},
                "checksums": {},
                "sharding": _mesh.sharding_descriptor(v.sharding),
            }
            slices = _shard_slices(v)
            for i, sh in enumerate(v.addressable_shards):
                if sh.replica_id != 0:
                    continue  # one copy of each logical shard is enough
                fkey = f"{key}::{pid}::{i}"
                _copy_async(sh.data)
                snap.append((fkey, sh.data))
                entry["shards"][fkey] = slices[i]["index"]
            if slots and name in slots:
                entry["slot"] = dict(slots[name])
            manifest[name] = entry
        elif rank == 0:
            if isinstance(v, jax.Array):
                _copy_async(v)
            snap.append((key, v))
            manifest[name] = {
                "sharded": False,
                "file_key": key,
                "sharding": _mesh.sharding_descriptor(
                    getattr(v, "sharding", None)),
            }
            if slots and name in slots:
                manifest[name]["slot"] = dict(slots[name])

    # Pass 2: materialize the host snapshot IN THE CALLER'S THREAD — the
    # next training step may donate these buffers, so device arrays must
    # never be read after save_checkpoint returns.
    t_snap = _time.perf_counter()
    payload: Dict[str, np.ndarray] = {}
    for k, ref in snap:
        host = np.asarray(ref)
        # On the CPU backend np.asarray of a jax.Array is a ZERO-COPY
        # view of the device buffer; an async snapshot must own its
        # bytes or the next training step mutates the payload under
        # the background writer (reused/donated buffers -> checksums
        # recorded over different values than the ones serialized).
        if async_save and not host.flags.owndata:
            host = np.array(host, copy=True)
        payload[k] = host
    _M_SNAPSHOT_S.observe(_time.perf_counter() - t_snap)
    for name, entry in manifest.items():
        if not entry["sharded"]:
            entry["shape"] = list(payload[entry["file_key"]].shape)
            entry["dtype"] = str(payload[entry["file_key"]].dtype)

    def _write():
        # checksums are serialize-side work: under async_save they run
        # here, off-thread, over the already-host-resident snapshot
        for entry in manifest.values():
            if entry["sharded"]:
                entry["checksums"] = {
                    k: _checksum(payload[k]) for k in entry["shards"]}
            else:
                entry["checksum"] = _checksum(payload[entry["file_key"]])
        # uncoordinated multi-host legacy fallback: a non-zero process
        # arriving after process 0 already committed lands its files
        # inside the published dir. With a coordinator this cannot
        # happen — process 0 renames only after every ack.
        target = stage_dir
        if coord is None and rank != 0 and os.path.isdir(ckpt_dir):
            target = ckpt_dir
        os.makedirs(target, exist_ok=True)
        shard_path = os.path.join(target, f"shards_{pid}.npz")
        with open(shard_path, "wb") as f:
            np.savez(f, **payload)
            f.flush()
            os.fsync(f.fileno())
        # chaos hook: raise here = crash after the (possibly partial)
        # shard write, before commit; truncate = torn shard file
        _F_WRITE.hit(path=shard_path)
        # every process writes its manifest fragment; fragments merge on
        # load (shard keys are globally unique)
        with open(os.path.join(target, f"{_MANIFEST}.{pid}"), "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        if coord is not None and rank != 0:
            # files durable -> ack; return only once process 0 made the
            # serial observable (so callers may prune/validate after)
            coord.ack_write(seq, step)
            coord.wait_published(seq, step)
            return
        if rank == 0:
            t0 = _time.perf_counter()
            if coord is not None:
                # the commit barrier: EVERY writer's files are durable
                # before the marker that declares the dir complete
                coord.wait_writers(seq, step)
            _F_COMMIT.hit()
            with open(os.path.join(target, _COMMIT), "w") as f:
                json.dump({"step": step, "format": _FORMAT}, f)
                f.flush()
                os.fsync(f.fileno())
            if target is stage_dir:
                old_dir = ckpt_dir + ".old" + _STAGING_SUFFIX
                # Re-save of the same serial: park the committed old
                # copy aside instead of rmtree-before-replace — a crash
                # in this window must not lose the only copy
                # (_recover_displaced renames it back on discovery).
                # Retried once because a concurrent reader's recovery
                # can recreate ckpt_dir between the two renames; the
                # new save must win, not fail with ENOTEMPTY.
                for attempt in range(2):
                    if os.path.isdir(ckpt_dir):
                        shutil.rmtree(old_dir, ignore_errors=True)
                        os.rename(ckpt_dir, old_dir)
                    try:
                        os.replace(stage_dir, ckpt_dir)
                        break
                    except OSError:
                        if attempt:
                            raise
                shutil.rmtree(old_dir, ignore_errors=True)
            _fsync_dir(dirname)
            latest_tmp = os.path.join(dirname, _LATEST + _STAGING_SUFFIX)
            with open(latest_tmp, "w") as f:
                f.write(str(step))
                f.flush()
                os.fsync(f.fileno())
            os.replace(latest_tmp, os.path.join(dirname, _LATEST))
            _fsync_dir(dirname)
            _M_COMMIT_S.observe(_time.perf_counter() - t0)
            if coord is not None:
                coord.publish(seq, step)
            _sweep_stale_staging(dirname, step)

    if async_save:
        handle = _AsyncHandle(step)

        def _run():
            try:
                _write()
            except BaseException as e:  # surfaced by wait() / next reap
                handle.error = e

        handle._thread = threading.Thread(target=_run, daemon=True)
        with _HANDLES_LOCK:
            _async_handles.append(handle)
        handle._thread.start()
        return handle
    _write()
    return None


# ---------------------------------------------------------------------------
# discovery + validation
# ---------------------------------------------------------------------------


def _sweep_stale_staging(dirname: str, committed_step: int):
    """Garbage-collect `.tmp` staging dirs left by CRASHED saves of
    older serials (a crashed save of THIS serial was replaced above)
    and `.old.tmp` parked copies whose serial exists again. Staging
    dirs of in-flight async saves are left alone."""
    import re

    with _HANDLES_LOCK:
        live = {h.step for h in _async_handles if not h.done()}
    try:
        entries = os.listdir(dirname)
    except OSError:
        return
    for d in entries:
        m = re.match(r"checkpoint_(\d+)\.tmp$", d)
        if m and int(m.group(1)) < committed_step \
                and int(m.group(1)) not in live:
            shutil.rmtree(os.path.join(dirname, d), ignore_errors=True)
            continue
        m = re.match(r"checkpoint_(\d+)\.old\.tmp$", d)
        if m and os.path.isdir(
                os.path.join(dirname, f"checkpoint_{m.group(1)}")):
            shutil.rmtree(os.path.join(dirname, d), ignore_errors=True)


def _recover_displaced(dirname: str):
    """Crash recovery for the re-save window: a serial parked at
    ``checkpoint_<n>.old.tmp`` whose main dir is missing was displaced
    by a save that died before publishing — rename the committed copy
    back so discovery sees it again."""
    import re

    try:
        entries = os.listdir(dirname)
    except OSError:
        return
    for d in entries:
        m = re.match(r"checkpoint_(\d+)\.old\.tmp$", d)
        if m:
            main = os.path.join(dirname, f"checkpoint_{m.group(1)}")
            if not os.path.isdir(main):
                try:
                    os.rename(os.path.join(dirname, d), main)
                except OSError:
                    pass


def _pointer_step(dirname: str) -> Optional[int]:
    try:
        with open(os.path.join(dirname, _LATEST)) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def available_steps(dirname: str) -> List[int]:
    import re

    out = []
    try:
        for d in os.listdir(dirname):
            m = re.match(r"checkpoint_(\d+)$", d)  # excludes .tmp staging
            if m:
                out.append(int(m.group(1)))
    except OSError:
        pass
    return sorted(out)


def validate_checkpoint(dirname: str, step: int,
                        verify_checksums: bool = True) -> bool:
    """True iff ``checkpoint_<step>`` is committed and internally
    consistent: COMMIT marker present and parseable, manifest fragments
    parse, the shards present in the shard files COVER every element of
    every array (a missing shard file is tolerated exactly when replica
    coverage still reassembles the value — the same partial-subset rule
    ``load_checkpoint`` applies), and (by default) every present array's
    crc32 matches its manifest record.

    Legacy tolerance: dirs written BEFORE the commit protocol carry no
    COMMIT marker — they are accepted when structurally complete (the
    new protocol never leaves a markerless final-named dir, so a
    missing marker can only mean pre-plane format; a markerless dir
    torn by an old-style crash still fails the structural checks)."""
    ckpt_dir = os.path.join(dirname, f"checkpoint_{step}")
    try:
        marker = os.path.join(ckpt_dir, _COMMIT)
        if os.path.exists(marker):
            with open(marker) as f:
                json.load(f)
        elif not os.path.isdir(ckpt_dir):
            return False
        manifest, payload = _read_raw(ckpt_dir,
                                      load_payload=verify_checksums)
        if not manifest:
            return False
        for name, entry in manifest.items():
            if entry.get("sharded"):
                present = [k for k in entry["shards"] if k in payload]
                sums = entry.get("checksums", {})
                if set(present) != set(entry["shards"]) and \
                        not _covers(entry, present):
                    return False
            else:
                present = ([entry["file_key"]]
                           if entry["file_key"] in payload else [])
                if not present:
                    return False
                sums = {entry["file_key"]: entry.get("checksum")}
            for k in present:
                want = sums.get(k) if verify_checksums else None
                if want is not None and _checksum(payload[k]) != want:
                    return False
        return True
    except Exception:  # noqa: BLE001 — any torn-file failure = invalid
        return False


def _covers(entry: dict, present: List[str]) -> bool:
    """Do the PRESENT shards of a manifest entry cover every element?"""
    seen = np.zeros(entry["shape"], dtype=bool)
    for fkey in present:
        seen[tuple(slice(a, b) for a, b in entry["shards"][fkey])] = True
    return bool(seen.all())


def latest_step(dirname: str,
                verify_checksums: bool = True) -> Optional[int]:
    """Newest VALID committed serial, scanning the serial dirs on disk
    newest-first — NOT the ``latest`` pointer, which can be one step
    stale (a crash between the dir rename and the pointer update leaves
    a fully committed serial the pointer doesn't name yet; the pointer
    file remains as a cheap human-readable hint). Serials that fail
    validation count into ``pt_ckpt_invalid_skipped_total`` (one count
    per skip EVENT, not per distinct serial) and are skipped.

    COST: the default full verification reads every candidate's arrays
    to prove their crc32s — the honest "is this resumable" answer. Pass
    ``verify_checksums=False`` for a cheap structural probe (npz name
    indexes only), or use ``load_latest`` when the values are needed
    anyway (single read)."""
    _recover_displaced(dirname)
    for s in reversed(available_steps(dirname)):
        if validate_checkpoint(dirname, s, verify_checksums):
            return s
        _M_INVALID_SKIPS.inc()
    return None


# ---------------------------------------------------------------------------
# load
# ---------------------------------------------------------------------------


def load_latest(dirname: str, shardings: Optional[dict] = None):
    """``(step, {name -> array})`` of the newest loadable serial, or
    None. Single-pass: each candidate (newest first) is loaded
    directly — ``_load_one`` verifies shard coverage and crc32 in the
    same read, so resume never reads a multi-GB checkpoint twice.
    Markerless pre-plane dirs load like any other (the structural
    checks reject torn ones; see validate_checkpoint). Unloadable
    serials count into ``pt_ckpt_invalid_skipped_total``.
    ``shardings`` re-shards the result on load (see ``reshard``)."""
    _recover_displaced(dirname)
    for s in reversed(available_steps(dirname)):
        try:
            values = _load_one(dirname, s)
        except Exception:  # noqa: BLE001 — torn/corrupt: try the next
            _M_INVALID_SKIPS.inc()
            continue
        if shardings:
            values = reshard(values, shardings)
        return s, values
    return None


def load_checkpoint(dirname: str, step: Optional[int] = None,
                    shardings: Optional[dict] = None) -> Dict[str, object]:
    """Reassemble {name -> full array} from the shard files of
    ``checkpoint_<step>`` (default: the newest VALID serial —
    uncommitted or corrupt newer ones are skipped, so a crash mid-save
    falls back to the previous committed checkpoint). The result is
    independent of the topology that SAVED it: any per-process shard
    layout reassembles, including a partial file subset when replica
    coverage is complete. ``shardings`` ({name -> jax.sharding.Sharding})
    re-shards named arrays onto the restoring program's layout in the
    same call (``reshard``); everything else stays host numpy, which the
    executor's ``in_shardings`` place at the next run."""
    if step is not None:
        values = _load_one(dirname, step)
        return reshard(values, shardings) if shardings else values
    loaded = load_latest(dirname, shardings=shardings)
    if loaded is None:
        if _pointer_step(dirname) is None and not available_steps(dirname):
            raise FileNotFoundError(f"no checkpoint in {dirname}")
        raise IOError(
            f"no valid committed checkpoint in {dirname} "
            f"(serials on disk: {available_steps(dirname)})")
    return loaded[1]


def reshard(values: Dict[str, object], shardings: dict) -> Dict[str, object]:
    """Place restored host arrays onto target shardings — the
    reshard-on-load half of mesh portability. ``shardings`` maps names
    to ``jax.sharding.Sharding``s (e.g. a DistributedStrategy's
    ``sharding_for`` outputs, i.e. the restoring program's
    ``in_shardings``); names it does not cover stay host numpy. Each
    covered array is built shard-by-shard from the reassembled host
    value (``make_array_from_callback``), so every device gets exactly
    its slice — no whole-array broadcast — and the bytes are bit-exact
    regardless of the mesh the checkpoint was saved on."""
    out: Dict[str, object] = {}
    for n, v in values.items():
        sh = shardings.get(n)
        if sh is None:
            out[n] = v
            continue
        host = np.asarray(v)
        try:
            out[n] = jax.make_array_from_callback(
                host.shape, sh, lambda idx, _h=host: _h[idx])
        except (TypeError, AttributeError):  # older jax fallback
            out[n] = jax.device_put(host, sh)
    return out


def manifest_slots(dirname: str, step: int) -> Dict[str, dict]:
    """{var name -> {"param": ..., "slot": ...}} recorded by
    ``save_checkpoint(slots=)`` for ``checkpoint_<step>``, merged across
    every process's manifest fragment. Manifest-only read (no array
    data) — the resume path calls this right after ``load_latest`` to
    decide whether slot re-keying applies. Empty for checkpoints saved
    without descriptors (pre-ISSUE-14 or slot-less saves)."""
    ckpt_dir = os.path.join(dirname, f"checkpoint_{step}")
    out: Dict[str, dict] = {}
    for fn in sorted(os.listdir(ckpt_dir)):
        if fn.startswith(_MANIFEST):
            path = os.path.join(ckpt_dir, fn)
            _F_READ.hit(path=path)
            with open(path) as f:
                frag = json.load(f)
            for name, entry in frag.items():
                if "slot" in entry:
                    out.setdefault(name, entry["slot"])
    return out


def reshard_optimizer_state(
    values: Dict[str, object],
    saved_slots: Dict[str, dict],
    target_slots: Dict[str, dict],
    shardings: Optional[dict] = None,
    strategy=None,
) -> Dict[str, object]:
    """Re-KEY saved optimizer slot state onto the restoring program's
    slot variables, and optionally re-PLACE it onto that program's
    shardings — the slot-state half of mesh portability (ISSUE 14).

    Parameters restore by NAME (users pin them via ParamAttr), but slot
    var names come from unique-name counters and drift whenever the
    restoring program is built differently — per-stage pipeline
    programs whose stage op sets differ across world sizes, a rebuild
    in a warm process, a reordered build. Restoring those by name
    silently re-initializes the moments to zero. This function joins
    ``saved_slots`` (the manifest's descriptors, ``manifest_slots``)
    against ``target_slots`` (the restoring optimizer's
    ``slot_descriptor()``) on the stable (param, kind) identity:

    - a matched slot moves to the restoring name (metered into
      ``pt_ckpt_slot_rekeys_total`` when the name actually changed) and
      is placed through ``shardings``/``strategy`` exactly like
      ``reshard``/``restore_scope`` place parameters;
    - a saved slot with no target is DROPPED (its parameter is not part
      of the restoring program — the per-stage case);
    - non-slot entries pass through untouched.

    Returns a new dict; ``values`` is not mutated."""
    saved_slots = saved_slots or {}
    target_slots = target_slots or {}
    by_key = {}
    for name, d in saved_slots.items():
        by_key[(d.get("param"), d.get("slot"))] = name
    out = {n: v for n, v in values.items() if n not in saved_slots}
    sh = dict(shardings or {})
    rekeyed = 0
    for tname, d in target_slots.items():
        sname = by_key.get((d.get("param"), d.get("slot")))
        if sname is None or sname not in values:
            continue  # nothing saved for this slot: leave initialized
        v = values[sname]
        if tname not in sh and strategy is not None:
            sh[tname] = strategy.sharding_for(tname)
        target = sh.get(tname)
        if target is not None:
            v = reshard({tname: v}, {tname: target})[tname]
        out[tname] = v
        if tname != sname:
            rekeyed += 1
    if rekeyed:
        _M_SLOT_REKEYS.inc(rekeyed)
    return out


def _read_raw(ckpt_dir: str, load_payload: bool = True):
    """(merged manifest, {file key -> array}) straight off disk. With
    ``load_payload=False`` the payload maps every key present in the
    npz indexes to None (header read only — no array data), which is
    what structural validation needs. Both the manifest parses and the
    shard reads pass through the ``ckpt.read`` fault site, so chaos
    plans can tear the RESTORE path (raise/delay/truncate per file)."""
    manifest: Dict[str, dict] = {}
    for fn in sorted(os.listdir(ckpt_dir)):
        if fn.startswith(_MANIFEST):
            path = os.path.join(ckpt_dir, fn)
            _F_READ.hit(path=path)
            with open(path) as f:
                frag = json.load(f)
            for name, entry in frag.items():
                if name in manifest and entry.get("sharded"):
                    manifest[name]["shards"].update(entry["shards"])
                    manifest[name].setdefault("checksums", {}).update(
                        entry.get("checksums", {}))
                else:
                    manifest.setdefault(name, entry)

    payload: Dict[str, Optional[np.ndarray]] = {}
    for fn in sorted(os.listdir(ckpt_dir)):
        if fn.startswith("shards_") and fn.endswith(".npz"):
            path = os.path.join(ckpt_dir, fn)
            _F_READ.hit(path=path)
            with np.load(path) as z:
                if load_payload:
                    for k in z.files:
                        payload[k] = z[k]
                else:
                    payload.update(dict.fromkeys(z.files))
    return manifest, payload


def _load_one(dirname: str, step: int) -> Dict[str, np.ndarray]:
    ckpt_dir = os.path.join(dirname, f"checkpoint_{step}")
    manifest, payload = _read_raw(ckpt_dir)
    if not manifest:
        # an empty/foreign dir must not load as (step, {}) — resume
        # would pick it over an older REAL checkpoint and then die on
        # the missing-parameters check instead of falling back
        raise IOError(f"checkpoint_{step}: no manifest fragments")

    out: Dict[str, np.ndarray] = {}
    for name, entry in manifest.items():
        if not entry["sharded"]:
            k = entry["file_key"]
            if k not in payload:
                raise IOError(
                    f"checkpoint_{step}: variable '{name}' is missing "
                    f"(shard file '{_fkey_file(k)}' absent, no replica "
                    f"coverage — reassembly impossible)")
            want = entry.get("checksum")
            if want is not None and _checksum(payload[k]) != want:
                raise IOError(
                    f"checkpoint_{step}: checksum mismatch for '{name}' "
                    f"— corrupt shard file")
            out[name] = payload[k]
            continue
        full = np.zeros(entry["shape"], dtype=np.dtype(entry["dtype"]))
        seen = np.zeros(entry["shape"], dtype=bool)
        sums = entry.get("checksums", {})
        absent = [k for k in entry["shards"] if k not in payload]
        for fkey, index in entry["shards"].items():
            if fkey in absent:
                continue
            want = sums.get(fkey)
            if want is not None and _checksum(payload[fkey]) != want:
                raise IOError(
                    f"checkpoint_{step}: checksum mismatch for shard "
                    f"'{fkey}' of '{name}' — corrupt shard file")
            sl = tuple(slice(a, b) for a, b in index)
            full[sl] = payload[fkey]
            seen[sl] = True
        if not seen.all():
            files = sorted({_fkey_file(k) for k in absent})
            raise IOError(
                f"checkpoint_{step}: variable '{name}' is missing shards "
                f"({int((~seen).sum())} of {seen.size} elements uncovered; "
                f"absent shards: {sorted(absent)[:4]} from files {files}) "
                f"— replica coverage does NOT permit reassembly; restore "
                f"the missing processes' shard files"
            )
        if absent:
            # every element still covered by surviving shards: a partial
            # file subset (e.g. a shrunk world lost pure-replica hosts)
            _M_PARTIAL.inc()
        out[name] = full
    return out


def save_scope(dirname: str, scope=None, step: int = 0,
               async_save: bool = False, names=None,
               slots: Optional[Dict[str, dict]] = None):
    """Checkpoint a Scope's state (default: every var in the scope).
    ``slots`` records optimizer slot descriptors in the manifest (see
    ``save_checkpoint``)."""
    from paddle_tpu.executor import global_scope

    scope = scope or global_scope()
    names = list(names) if names is not None else scope.var_names()
    state = {n: scope.find_var(n) for n in names}
    return save_checkpoint(dirname, state, step=step,
                           async_save=async_save, slots=slots)


def restore_scope(dirname: str, scope=None, step: Optional[int] = None,
                  strict: bool = True, shardings: Optional[dict] = None,
                  strategy=None):
    """Load a checkpoint back into a Scope. With ``strict``, every
    restored name simply overwrites/creates the scope entry; missing
    checkpoints raise (a partial restore would silently train from
    re-initialized values — same failure mode io.load_vars guards).
    ``shardings`` ({name -> Sharding}) or ``strategy`` (a
    DistributedStrategy: every restored name goes through its
    ``sharding_for``) re-shards values onto the RESTORING program's
    layout during the load — the saved topology is irrelevant."""
    from paddle_tpu.executor import global_scope

    scope = scope or global_scope()
    values = load_checkpoint(dirname, step=step)
    if strict and not values:
        raise IOError(f"empty checkpoint in {dirname}")
    if strategy is not None:
        sh = {n: strategy.sharding_for(n) for n in values}
        sh.update(shardings or {})
        shardings = sh
    if shardings:
        values = reshard(values, shardings)
    for n, v in values.items():
        scope.set(n, v)
    return list(values)
