"""Sharded, optionally-async checkpointing of training state.

The TPU-native replacement for the reference's distributed checkpointing,
where parameters sliced across pservers are saved per-server and re-merged
on load (reference: io.py:282 ``_save_distributed_persistables``, slice
concat io.py:315-360; trainer serial-numbered checkpoint dirs
contrib/trainer.py:100,580). Here the unit is a sharded ``jax.Array``:

- each PROCESS writes only its addressable shards (one ``.npz`` per
  process) plus a shared JSON manifest of {name -> shape, dtype, shard
  index ranges}, so multi-host saves never gather the model onto one host;
- restore reassembles the global value from shard files and places it
  back in the scope (host numpy); the next ``exe.run`` re-shards it
  according to the program's in_shardings, so training resumes bit-exact
  on any mesh shape — re-sharding on restore replaces the reference's
  slice re-merge;
- ``async_save=True`` snapshots to host in the caller's thread (cheap
  device->host copies) and writes files on a background thread,
  overlapping serialization with the next training steps (the orbax
  async-checkpoint pattern).

Checkpoints are serial-numbered directories ``checkpoint_<step>`` with a
``latest`` pointer file, like the reference Trainer's serial dirs.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

import jax
import numpy as np

_MANIFEST = "manifest.json"
_LATEST = "latest"


def _shard_slices(arr) -> List[dict]:
    """Addressable shards of a jax.Array as JSON-able index metadata."""
    out = []
    for sh in arr.addressable_shards:
        idx = []
        for sl, dim in zip(sh.index, arr.shape):
            start = 0 if sl.start is None else int(sl.start)
            stop = dim if sl.stop is None else int(sl.stop)
            idx.append([start, stop])
        out.append({"index": idx, "replica_id": int(sh.replica_id)})
    return out


class _AsyncHandle:
    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None

    def wait(self):
        self._thread.join()
        if self.error is not None:
            raise self.error


def save_checkpoint(
    dirname: str,
    state: Dict[str, object],
    step: int = 0,
    async_save: bool = False,
):
    """Write ``state`` (name -> array) to ``dirname/checkpoint_<step>``.

    Sharded arrays: this process writes its addressable, replica-0 shards.
    Host numpy / replicated values: only process 0 writes. Returns an
    ``_AsyncHandle`` when ``async_save`` (call ``.wait()`` before relying
    on the files), else None.
    """
    ckpt_dir = os.path.join(dirname, f"checkpoint_{step}")
    os.makedirs(ckpt_dir, exist_ok=True)
    pid = jax.process_index()

    manifest = {}
    shard_payload: Dict[str, np.ndarray] = {}
    for name, v in state.items():
        key = name.replace("/", "__")
        if isinstance(v, jax.Array) and len(v.sharding.device_set) > 1:
            entry = {
                "shape": list(v.shape),
                "dtype": str(v.dtype),
                "sharded": True,
                "shards": {},
            }
            slices = _shard_slices(v)
            for i, sh in enumerate(v.addressable_shards):
                if sh.replica_id != 0:
                    continue  # one copy of each logical shard is enough
                fkey = f"{key}::{pid}::{i}"
                shard_payload[fkey] = np.asarray(sh.data)
                entry["shards"][fkey] = slices[i]["index"]
            manifest[name] = entry
        else:
            if pid == 0:
                shard_payload[key] = np.asarray(v)
                manifest[name] = {
                    "shape": list(np.shape(shard_payload[key])),
                    "dtype": str(shard_payload[key].dtype),
                    "sharded": False,
                    "file_key": key,
                }

    def _write():
        np.savez(os.path.join(ckpt_dir, f"shards_{pid}.npz"),
                 **shard_payload)
        # every process writes its manifest fragment; fragments merge on
        # load (shard keys are globally unique)
        with open(os.path.join(ckpt_dir, f"{_MANIFEST}.{pid}"), "w") as f:
            json.dump(manifest, f)
        if pid == 0:
            with open(os.path.join(dirname, _LATEST), "w") as f:
                f.write(str(step))

    if async_save:
        handle = _AsyncHandle()

        def _run():
            try:
                _write()
            except BaseException as e:  # surfaced by wait()
                handle.error = e

        handle._thread = threading.Thread(target=_run, daemon=True)
        handle._thread.start()
        return handle
    _write()
    return None


def latest_step(dirname: str) -> Optional[int]:
    try:
        with open(os.path.join(dirname, _LATEST)) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def available_steps(dirname: str) -> List[int]:
    import re

    out = []
    try:
        for d in os.listdir(dirname):
            m = re.match(r"checkpoint_(\d+)$", d)
            if m:
                out.append(int(m.group(1)))
    except OSError:
        pass
    return sorted(out)


def load_checkpoint(dirname: str, step: Optional[int] = None) -> Dict[str, np.ndarray]:
    """Reassemble {name -> full numpy array} from all processes' shard
    files of ``checkpoint_<step>`` (default: the ``latest`` pointer).

    Default-load resilience: multi-host saves have no cross-host commit
    barrier (process 0 publishes ``latest`` after writing only ITS files),
    so if the newest checkpoint is incomplete — a preemption hit mid-save —
    older serials are tried before giving up."""
    if step is not None:
        return _load_one(dirname, step)
    latest = latest_step(dirname)
    if latest is None:
        raise FileNotFoundError(f"no 'latest' pointer in {dirname}")
    candidates = [latest] + [
        s for s in reversed(available_steps(dirname)) if s != latest
    ]
    last_err: Optional[Exception] = None
    for s in candidates:
        try:
            return _load_one(dirname, s)
        except Exception as e:  # noqa: BLE001 — any torn-file failure
            # (missing files, truncated npz -> BadZipFile, cut-off JSON)
            # means "this serial is incomplete, try the next one"
            last_err = e
    raise IOError(
        f"no complete checkpoint in {dirname} "
        f"(tried {candidates}): {last_err}"
    )


def _load_one(dirname: str, step: int) -> Dict[str, np.ndarray]:
    ckpt_dir = os.path.join(dirname, f"checkpoint_{step}")
    manifest: Dict[str, dict] = {}
    for fn in sorted(os.listdir(ckpt_dir)):
        if fn.startswith(_MANIFEST):
            with open(os.path.join(ckpt_dir, fn)) as f:
                frag = json.load(f)
            for name, entry in frag.items():
                if name in manifest and entry.get("sharded"):
                    manifest[name]["shards"].update(entry["shards"])
                else:
                    manifest.setdefault(name, entry)

    payload: Dict[str, np.ndarray] = {}
    for fn in sorted(os.listdir(ckpt_dir)):
        if fn.startswith("shards_") and fn.endswith(".npz"):
            with np.load(os.path.join(ckpt_dir, fn)) as z:
                for k in z.files:
                    payload[k] = z[k]

    out: Dict[str, np.ndarray] = {}
    for name, entry in manifest.items():
        if not entry["sharded"]:
            out[name] = payload[entry["file_key"]]
            continue
        full = np.zeros(entry["shape"], dtype=np.dtype(entry["dtype"]))
        seen = np.zeros(entry["shape"], dtype=bool)
        for fkey, index in entry["shards"].items():
            sl = tuple(slice(a, b) for a, b in index)
            full[sl] = payload[fkey]
            seen[sl] = True
        if not seen.all():
            raise IOError(
                f"checkpoint_{step}: variable '{name}' is missing shards "
                f"({int((~seen).sum())} of {seen.size} elements uncovered) "
                f"— were all processes' shard files copied?"
            )
        out[name] = full
    return out


def save_scope(dirname: str, scope=None, step: int = 0,
               async_save: bool = False, names=None):
    """Checkpoint a Scope's state (default: every var in the scope)."""
    from paddle_tpu.executor import global_scope

    scope = scope or global_scope()
    names = list(names) if names is not None else scope.var_names()
    state = {n: scope.find_var(n) for n in names}
    return save_checkpoint(dirname, state, step=step, async_save=async_save)


def restore_scope(dirname: str, scope=None, step: Optional[int] = None,
                  strict: bool = True):
    """Load a checkpoint back into a Scope. With ``strict``, every
    restored name simply overwrites/creates the scope entry; missing
    checkpoints raise (a partial restore would silently train from
    re-initialized values — same failure mode io.load_vars guards)."""
    from paddle_tpu.executor import global_scope

    scope = scope or global_scope()
    values = load_checkpoint(dirname, step=step)
    if strict and not values:
        raise IOError(f"empty checkpoint in {dirname}")
    for n, v in values.items():
        scope.set(n, v)
    return list(values)
