"""Sharded, optionally-async, crash-consistent checkpointing.

The TPU-native replacement for the reference's distributed checkpointing,
where parameters sliced across pservers are saved per-server and re-merged
on load (reference: io.py:282 ``_save_distributed_persistables``, slice
concat io.py:315-360; trainer serial-numbered checkpoint dirs
contrib/trainer.py:100,580). Here the unit is a sharded ``jax.Array``:

- each PROCESS writes only its addressable shards (one ``.npz`` per
  process) plus a shared JSON manifest of {name -> shape, dtype, shard
  index ranges, per-array crc32}, so multi-host saves never gather the
  model onto one host;
- restore reassembles the global value from shard files and places it
  back in the scope (host numpy); the next ``exe.run`` re-shards it
  according to the program's in_shardings, so training resumes bit-exact
  on any mesh shape — re-sharding on restore replaces the reference's
  slice re-merge;
- ``async_save=True`` snapshots to host in the caller's thread (cheap
  device->host copies) and writes files on a background thread,
  overlapping serialization with the next training steps (the orbax
  async-checkpoint pattern).

Crash-consistent commit protocol (the orbax commit-marker pattern)::

    write  checkpoint_<N>.tmp/shards_<pid>.npz      (fsync)
    write  checkpoint_<N>.tmp/manifest.json.<pid>   (fsync)
    write  checkpoint_<N>.tmp/COMMIT                (fsync)
    rename checkpoint_<N>.tmp -> checkpoint_<N>     (atomic publish)
    write  latest.tmp; rename -> latest             (atomic pointer)

A crash at ANY point leaves either a ``.tmp`` staging dir (ignored by
``available_steps``/``latest_step``) or a fully committed serial: resume
can never observe a half-written checkpoint. ``validate_checkpoint``
additionally proves integrity (COMMIT marker, every manifest-referenced
shard present, crc32 match), and ``latest_step`` skips invalid serials —
counting them into ``pt_ckpt_invalid_skipped_total`` — falling back to
the newest valid one. Single-host the protocol is complete; multi-host
commits still need an external barrier before process 0 publishes
(late non-zero writers land their files in the committed dir).
"""

from __future__ import annotations

import atexit
import json
import os
import shutil
import threading
import time as _time
import warnings
import zlib
from typing import Dict, List, Optional

import jax
import numpy as np

from paddle_tpu import faults as _faults
from paddle_tpu import monitor as _monitor

_MANIFEST = "manifest.json"
_LATEST = "latest"
_COMMIT = "COMMIT"
_STAGING_SUFFIX = ".tmp"

_M_COMMIT_S = _monitor.histogram(
    "pt_ckpt_commit_seconds",
    "checkpoint commit-protocol duration (COMMIT marker -> published "
    "latest pointer)")
_M_INVALID_SKIPS = _monitor.counter(
    "pt_ckpt_invalid_skipped_total",
    "uncommitted/corrupt checkpoint serials skipped while resolving the "
    "newest valid one")
_M_ASYNC_ERRS = _monitor.counter(
    "pt_ckpt_async_errors_total",
    "background checkpoint-save failures surfaced outside wait()")

_F_WRITE = _faults.site("ckpt.write_shards")
_F_COMMIT = _faults.site("ckpt.commit")


def _fsync_dir(path: str):
    """Durably record a rename/create in its parent directory."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse dir fsync; rename is still atomic
    finally:
        os.close(fd)


def _fsync_file(path: str):
    """Flush an already-written file's data to disk (read-only open —
    shared by the inference-export publish path)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _checksum(a: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(a).tobytes())


def _shard_slices(arr) -> List[dict]:
    """Addressable shards of a jax.Array as JSON-able index metadata."""
    out = []
    for sh in arr.addressable_shards:
        idx = []
        for sl, dim in zip(sh.index, arr.shape):
            start = 0 if sl.start is None else int(sl.start)
            stop = dim if sl.stop is None else int(sl.stop)
            idx.append([start, stop])
        out.append({"index": idx, "replica_id": int(sh.replica_id)})
    return out


# ---------------------------------------------------------------------------
# async handles: a failed background save must never vanish
# ---------------------------------------------------------------------------

_HANDLES_LOCK = threading.Lock()
_async_handles: List["_AsyncHandle"] = []


class _AsyncHandle:
    __slots__ = ("_thread", "error", "step", "_surfaced")

    def __init__(self, step: int):
        self._thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None
        self.step = step
        self._surfaced = False

    def done(self) -> bool:
        # ident is None until the thread starts — and is_alive() is
        # False then too, so without the ident check a reap racing the
        # handle's registration would drop it (and its eventual error)
        t = self._thread
        return t is not None and t.ident is not None and not t.is_alive()

    def wait(self):
        """Join the background write; raises its error. Idempotent —
        safe to call any number of times (each call re-raises a stored
        error rather than losing it)."""
        t = self._thread
        if t is not None:
            t.join()
        self._surfaced = True
        if self.error is not None:
            raise self.error


def _reap_async(final: bool = False):
    """Surface errors of finished handles nobody ``wait()``ed (called at
    every save and at exit, so a failed background save is loud at most
    one save later). ``final`` joins still-running writers first."""
    with _HANDLES_LOCK:
        handles = list(_async_handles)
    for h in handles:
        if final and h._thread is not None:
            h._thread.join(timeout=30.0)
        if not h.done():
            continue
        with _HANDLES_LOCK:
            if h in _async_handles:
                _async_handles.remove(h)
        if h.error is not None and not h._surfaced:
            h._surfaced = True
            _M_ASYNC_ERRS.inc()
            warnings.warn(
                f"async checkpoint save (step {h.step}) failed and was "
                f"never wait()ed: {h.error!r}", RuntimeWarning)


atexit.register(_reap_async, final=True)


# ---------------------------------------------------------------------------
# save
# ---------------------------------------------------------------------------


def save_checkpoint(
    dirname: str,
    state: Dict[str, object],
    step: int = 0,
    async_save: bool = False,
):
    """Write ``state`` (name -> array) to ``dirname/checkpoint_<step>``
    via the staging-dir commit protocol (module docstring).

    Sharded arrays: this process writes its addressable, replica-0 shards.
    Host numpy / replicated values: only process 0 writes. Returns an
    ``_AsyncHandle`` when ``async_save`` (call ``.wait()`` before relying
    on the files), else None.
    """
    _reap_async()
    ckpt_dir = os.path.join(dirname, f"checkpoint_{step}")
    stage_dir = ckpt_dir + _STAGING_SUFFIX
    pid = jax.process_index()

    manifest = {}
    shard_payload: Dict[str, np.ndarray] = {}
    for name, v in state.items():
        key = name.replace("/", "__")
        if isinstance(v, jax.Array) and len(v.sharding.device_set) > 1:
            entry = {
                "shape": list(v.shape),
                "dtype": str(v.dtype),
                "sharded": True,
                "shards": {},
                "checksums": {},
            }
            slices = _shard_slices(v)
            for i, sh in enumerate(v.addressable_shards):
                if sh.replica_id != 0:
                    continue  # one copy of each logical shard is enough
                fkey = f"{key}::{pid}::{i}"
                shard_payload[fkey] = np.asarray(sh.data)
                entry["shards"][fkey] = slices[i]["index"]
                entry["checksums"][fkey] = _checksum(shard_payload[fkey])
            manifest[name] = entry
        else:
            if pid == 0:
                shard_payload[key] = np.asarray(v)
                manifest[name] = {
                    "shape": list(np.shape(shard_payload[key])),
                    "dtype": str(shard_payload[key].dtype),
                    "sharded": False,
                    "file_key": key,
                    "checksum": _checksum(shard_payload[key]),
                }

    def _write():
        # a non-zero process arriving after process 0 already committed
        # lands its files inside the published dir (multi-host saves
        # still need an external pre-commit barrier; see docstring)
        target = stage_dir
        if pid != 0 and os.path.isdir(ckpt_dir):
            target = ckpt_dir
        os.makedirs(target, exist_ok=True)
        shard_path = os.path.join(target, f"shards_{pid}.npz")
        with open(shard_path, "wb") as f:
            np.savez(f, **shard_payload)
            f.flush()
            os.fsync(f.fileno())
        # chaos hook: raise here = crash after the (possibly partial)
        # shard write, before commit; truncate = torn shard file
        _F_WRITE.hit(path=shard_path)
        # every process writes its manifest fragment; fragments merge on
        # load (shard keys are globally unique)
        with open(os.path.join(target, f"{_MANIFEST}.{pid}"), "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        if pid == 0:
            t0 = _time.perf_counter()
            _F_COMMIT.hit()
            with open(os.path.join(target, _COMMIT), "w") as f:
                json.dump({"step": step, "format": 1}, f)
                f.flush()
                os.fsync(f.fileno())
            if target is stage_dir:
                old_dir = ckpt_dir + ".old" + _STAGING_SUFFIX
                # Re-save of the same serial: park the committed old
                # copy aside instead of rmtree-before-replace — a crash
                # in this window must not lose the only copy
                # (_recover_displaced renames it back on discovery).
                # Retried once because a concurrent reader's recovery
                # can recreate ckpt_dir between the two renames; the
                # new save must win, not fail with ENOTEMPTY.
                for attempt in range(2):
                    if os.path.isdir(ckpt_dir):
                        shutil.rmtree(old_dir, ignore_errors=True)
                        os.rename(ckpt_dir, old_dir)
                    try:
                        os.replace(stage_dir, ckpt_dir)
                        break
                    except OSError:
                        if attempt:
                            raise
                shutil.rmtree(old_dir, ignore_errors=True)
            _fsync_dir(dirname)
            latest_tmp = os.path.join(dirname, _LATEST + _STAGING_SUFFIX)
            with open(latest_tmp, "w") as f:
                f.write(str(step))
                f.flush()
                os.fsync(f.fileno())
            os.replace(latest_tmp, os.path.join(dirname, _LATEST))
            _fsync_dir(dirname)
            _M_COMMIT_S.observe(_time.perf_counter() - t0)
            _sweep_stale_staging(dirname, step)

    if async_save:
        handle = _AsyncHandle(step)

        def _run():
            try:
                _write()
            except BaseException as e:  # surfaced by wait() / next reap
                handle.error = e

        handle._thread = threading.Thread(target=_run, daemon=True)
        with _HANDLES_LOCK:
            _async_handles.append(handle)
        handle._thread.start()
        return handle
    _write()
    return None


# ---------------------------------------------------------------------------
# discovery + validation
# ---------------------------------------------------------------------------


def _sweep_stale_staging(dirname: str, committed_step: int):
    """Garbage-collect `.tmp` staging dirs left by CRASHED saves of
    older serials (a crashed save of THIS serial was replaced above)
    and `.old.tmp` parked copies whose serial exists again. Staging
    dirs of in-flight async saves are left alone."""
    import re

    with _HANDLES_LOCK:
        live = {h.step for h in _async_handles if not h.done()}
    try:
        entries = os.listdir(dirname)
    except OSError:
        return
    for d in entries:
        m = re.match(r"checkpoint_(\d+)\.tmp$", d)
        if m and int(m.group(1)) < committed_step \
                and int(m.group(1)) not in live:
            shutil.rmtree(os.path.join(dirname, d), ignore_errors=True)
            continue
        m = re.match(r"checkpoint_(\d+)\.old\.tmp$", d)
        if m and os.path.isdir(
                os.path.join(dirname, f"checkpoint_{m.group(1)}")):
            shutil.rmtree(os.path.join(dirname, d), ignore_errors=True)


def _recover_displaced(dirname: str):
    """Crash recovery for the re-save window: a serial parked at
    ``checkpoint_<n>.old.tmp`` whose main dir is missing was displaced
    by a save that died before publishing — rename the committed copy
    back so discovery sees it again."""
    import re

    try:
        entries = os.listdir(dirname)
    except OSError:
        return
    for d in entries:
        m = re.match(r"checkpoint_(\d+)\.old\.tmp$", d)
        if m:
            main = os.path.join(dirname, f"checkpoint_{m.group(1)}")
            if not os.path.isdir(main):
                try:
                    os.rename(os.path.join(dirname, d), main)
                except OSError:
                    pass


def _pointer_step(dirname: str) -> Optional[int]:
    try:
        with open(os.path.join(dirname, _LATEST)) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def available_steps(dirname: str) -> List[int]:
    import re

    out = []
    try:
        for d in os.listdir(dirname):
            m = re.match(r"checkpoint_(\d+)$", d)  # excludes .tmp staging
            if m:
                out.append(int(m.group(1)))
    except OSError:
        pass
    return sorted(out)


def validate_checkpoint(dirname: str, step: int,
                        verify_checksums: bool = True) -> bool:
    """True iff ``checkpoint_<step>`` is committed and internally
    consistent: COMMIT marker present and parseable, manifest fragments
    parse, every referenced shard key exists in the shard files, and
    (by default) every array's crc32 matches its manifest record.

    Legacy tolerance: dirs written BEFORE the commit protocol carry no
    COMMIT marker — they are accepted when structurally complete (the
    new protocol never leaves a markerless final-named dir, so a
    missing marker can only mean pre-plane format; a markerless dir
    torn by an old-style crash still fails the structural checks)."""
    ckpt_dir = os.path.join(dirname, f"checkpoint_{step}")
    try:
        marker = os.path.join(ckpt_dir, _COMMIT)
        if os.path.exists(marker):
            with open(marker) as f:
                json.load(f)
        elif not os.path.isdir(ckpt_dir):
            return False
        manifest, payload = _read_raw(ckpt_dir,
                                      load_payload=verify_checksums)
        if not manifest:
            return False
        for name, entry in manifest.items():
            if entry.get("sharded"):
                keys = list(entry["shards"])
                sums = entry.get("checksums", {})
            else:
                keys = [entry["file_key"]]
                sums = {entry["file_key"]: entry.get("checksum")}
            for k in keys:
                if k not in payload:
                    return False
                want = sums.get(k) if verify_checksums else None
                if want is not None and _checksum(payload[k]) != want:
                    return False
        return True
    except Exception:  # noqa: BLE001 — any torn-file failure = invalid
        return False


def latest_step(dirname: str,
                verify_checksums: bool = True) -> Optional[int]:
    """Newest VALID committed serial, scanning the serial dirs on disk
    newest-first — NOT the ``latest`` pointer, which can be one step
    stale (a crash between the dir rename and the pointer update leaves
    a fully committed serial the pointer doesn't name yet; the pointer
    file remains as a cheap human-readable hint). Serials that fail
    validation count into ``pt_ckpt_invalid_skipped_total`` (one count
    per skip EVENT, not per distinct serial) and are skipped.

    COST: the default full verification reads every candidate's arrays
    to prove their crc32s — the honest "is this resumable" answer. Pass
    ``verify_checksums=False`` for a cheap structural probe (npz name
    indexes only), or use ``load_latest`` when the values are needed
    anyway (single read)."""
    _recover_displaced(dirname)
    for s in reversed(available_steps(dirname)):
        if validate_checkpoint(dirname, s, verify_checksums):
            return s
        _M_INVALID_SKIPS.inc()
    return None


# ---------------------------------------------------------------------------
# load
# ---------------------------------------------------------------------------


def load_latest(dirname: str):
    """``(step, {name -> array})`` of the newest loadable serial, or
    None. Single-pass: each candidate (newest first) is loaded
    directly — ``_load_one`` verifies shard coverage and crc32 in the
    same read, so resume never reads a multi-GB checkpoint twice.
    Markerless pre-plane dirs load like any other (the structural
    checks reject torn ones; see validate_checkpoint). Unloadable
    serials count into ``pt_ckpt_invalid_skipped_total``."""
    _recover_displaced(dirname)
    for s in reversed(available_steps(dirname)):
        try:
            return s, _load_one(dirname, s)
        except Exception:  # noqa: BLE001 — torn/corrupt: try the next
            _M_INVALID_SKIPS.inc()
    return None


def load_checkpoint(dirname: str, step: Optional[int] = None) -> Dict[str, np.ndarray]:
    """Reassemble {name -> full numpy array} from all processes' shard
    files of ``checkpoint_<step>`` (default: the newest VALID serial —
    uncommitted or corrupt newer ones are skipped, so a crash mid-save
    falls back to the previous committed checkpoint)."""
    if step is not None:
        return _load_one(dirname, step)
    loaded = load_latest(dirname)
    if loaded is None:
        if _pointer_step(dirname) is None and not available_steps(dirname):
            raise FileNotFoundError(f"no checkpoint in {dirname}")
        raise IOError(
            f"no valid committed checkpoint in {dirname} "
            f"(serials on disk: {available_steps(dirname)})")
    return loaded[1]


def _read_raw(ckpt_dir: str, load_payload: bool = True):
    """(merged manifest, {file key -> array}) straight off disk. With
    ``load_payload=False`` the payload maps every key present in the
    npz indexes to None (header read only — no array data), which is
    what structural validation needs."""
    manifest: Dict[str, dict] = {}
    for fn in sorted(os.listdir(ckpt_dir)):
        if fn.startswith(_MANIFEST):
            with open(os.path.join(ckpt_dir, fn)) as f:
                frag = json.load(f)
            for name, entry in frag.items():
                if name in manifest and entry.get("sharded"):
                    manifest[name]["shards"].update(entry["shards"])
                    manifest[name].setdefault("checksums", {}).update(
                        entry.get("checksums", {}))
                else:
                    manifest.setdefault(name, entry)

    payload: Dict[str, Optional[np.ndarray]] = {}
    for fn in sorted(os.listdir(ckpt_dir)):
        if fn.startswith("shards_") and fn.endswith(".npz"):
            with np.load(os.path.join(ckpt_dir, fn)) as z:
                if load_payload:
                    for k in z.files:
                        payload[k] = z[k]
                else:
                    payload.update(dict.fromkeys(z.files))
    return manifest, payload


def _load_one(dirname: str, step: int) -> Dict[str, np.ndarray]:
    ckpt_dir = os.path.join(dirname, f"checkpoint_{step}")
    manifest, payload = _read_raw(ckpt_dir)
    if not manifest:
        # an empty/foreign dir must not load as (step, {}) — resume
        # would pick it over an older REAL checkpoint and then die on
        # the missing-parameters check instead of falling back
        raise IOError(f"checkpoint_{step}: no manifest fragments")

    out: Dict[str, np.ndarray] = {}
    for name, entry in manifest.items():
        if not entry["sharded"]:
            k = entry["file_key"]
            want = entry.get("checksum")
            if want is not None and _checksum(payload[k]) != want:
                raise IOError(
                    f"checkpoint_{step}: checksum mismatch for '{name}' "
                    f"— corrupt shard file")
            out[name] = payload[k]
            continue
        full = np.zeros(entry["shape"], dtype=np.dtype(entry["dtype"]))
        seen = np.zeros(entry["shape"], dtype=bool)
        sums = entry.get("checksums", {})
        for fkey, index in entry["shards"].items():
            want = sums.get(fkey)
            if want is not None and _checksum(payload[fkey]) != want:
                raise IOError(
                    f"checkpoint_{step}: checksum mismatch for shard "
                    f"'{fkey}' of '{name}' — corrupt shard file")
            sl = tuple(slice(a, b) for a, b in index)
            full[sl] = payload[fkey]
            seen[sl] = True
        if not seen.all():
            raise IOError(
                f"checkpoint_{step}: variable '{name}' is missing shards "
                f"({int((~seen).sum())} of {seen.size} elements uncovered) "
                f"— were all processes' shard files copied?"
            )
        out[name] = full
    return out


def save_scope(dirname: str, scope=None, step: int = 0,
               async_save: bool = False, names=None):
    """Checkpoint a Scope's state (default: every var in the scope)."""
    from paddle_tpu.executor import global_scope

    scope = scope or global_scope()
    names = list(names) if names is not None else scope.var_names()
    state = {n: scope.find_var(n) for n in names}
    return save_checkpoint(dirname, state, step=step, async_save=async_save)


def restore_scope(dirname: str, scope=None, step: Optional[int] = None,
                  strict: bool = True):
    """Load a checkpoint back into a Scope. With ``strict``, every
    restored name simply overwrites/creates the scope entry; missing
    checkpoints raise (a partial restore would silently train from
    re-initialized values — same failure mode io.load_vars guards)."""
    from paddle_tpu.executor import global_scope

    scope = scope or global_scope()
    values = load_checkpoint(dirname, step=step)
    if strict and not values:
        raise IOError(f"empty checkpoint in {dirname}")
    for n, v in values.items():
        scope.set(n, v)
    return list(values)
