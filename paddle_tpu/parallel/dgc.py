"""Deep Gradient Compression: top-k sparsified gradient exchange
(reference: paddle/fluid/operators/dgc_op.h, dgc_clip_by_norm_op.h,
framework/details/sparse_all_reduce_op_handle.h:30; the vendored paper
is Lin et al., "Deep Gradient Compression", arXiv:1712.01887).

TPU-first design. The reference pairs a CUDA k-select kernel with an
NCCL allgather of (index, value) pairs; here the whole step is one pure
function built from ``lax.top_k`` + ``lax.all_gather`` + scatter-add, so
it composes with ``shard_map`` over any mesh axis — the data axis (ICI)
or the slice axis (DCN), where sparse exchange actually pays (see
BASELINE.md: ICI dense psum is byte-cheap enough that DGC only wins on
slow inter-slice links or at extreme sparsity).

One deliberate divergence: the reference's ``k`` varies at runtime with
the sparsity rampup schedule. A dynamic ``k`` would force a dynamic
output shape on ``top_k`` — hostile to XLA — so the selection runs at
TWO static widths behind a ``lax.cond`` on the (replicated) step
counter: the schedule-max width during rampup, the terminal-sparsity
width once the schedule saturates, with the per-step effective k
applied as a mask inside each. Same trajectory, static shapes, and the
steady-state exchange moves only ~n/1000 entries, not the warmup max.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


# The saturation sparsity every schedule converges to past rampup_step
# (the reference's hard-coded 0.999, dgc_op.h:24). period_sparsity's
# saturation value, max_k's tail and dgc_step's steady-state gather
# width k_term all derive from THIS constant — they must agree or the
# steady-state mask silently truncates the exchange.
_TERMINAL_SPARSITY = 0.999


def period_sparsity(sparsity: Sequence[float], step, rampup_step: float):
    """The reference's get_period_sparcity (dgc_op.h:24): index the
    sparsity list by ``step * len / rampup_step`` (note: GLOBAL step,
    the reference quirk), saturating at _TERMINAL_SPARSITY."""
    sp = jnp.asarray(list(sparsity), jnp.float32)
    idx = (step.astype(jnp.float32) * len(sparsity)
           / float(rampup_step)).astype(jnp.int32)
    return jnp.where(idx >= len(sparsity), jnp.float32(_TERMINAL_SPARSITY),
                     sp[jnp.clip(idx, 0, len(sparsity) - 1)])


def max_k(numel: int, sparsity: Sequence[float]) -> int:
    """Static selection width: the largest per-step k the schedule can
    ask for (plus the saturated terminal tail)."""
    ratios = [1.0 - s for s in sparsity] + [1.0 - _TERMINAL_SPARSITY]
    return max(1, int(numel * max(ratios)))


def dgc_step(
    g: jax.Array,
    u: jax.Array,
    v: jax.Array,
    step: jax.Array,
    *,
    momentum: float,
    sparsity: Sequence[float] = (0.999,),
    rampup_begin_step: float = 0.0,
    rampup_step: float = 1.0,
    use_nesterov: bool = False,
    axis: Optional[str] = None,
    combine: str = "sum",
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One DGC iteration for one parameter's gradient.

    Per the reference kernel (dgc_op.h:86-129): momentum-correct the
    residual accumulators (``u = m*u + g; v = v + u``, nesterov:
    ``u = m*(u+g); v = v + u + g``), select the top-k of ``|v|``, zero
    ``u``/``v`` at the selected (sent) positions, and exchange ONLY the
    selected (index, value) pairs over ``axis``; the decoded gradient is
    the scatter-add of every worker's selection. Before
    ``rampup_begin_step`` the dense gradient passes through untouched
    (the reference's early return).

    ``g``/``u``/``v`` may be any shape (flattened internally). ``axis``
    names a mesh axis when called under ``shard_map`` with per-worker
    LOCAL gradients and ``combine='sum'`` — the honest multi-worker
    exchange. ``combine='mean'`` divides the decoded sum by the axis
    size, for gradients that are ALREADY globally reduced (the GSPMD
    whole-program path, where every worker holds the same g and the
    exchange is redundant-but-correct).

    Returns ``(decoded_grad, u_new, v_new)`` with ``g``'s shape.
    """
    shape = g.shape
    gf = g.reshape(-1).astype(jnp.float32)
    uf = u.reshape(-1).astype(jnp.float32)
    vf = v.reshape(-1).astype(jnp.float32)
    n = gf.shape[0]
    step = jnp.asarray(step, jnp.float32).reshape(())

    if use_nesterov:
        u2 = momentum * (uf + gf)
        v2 = vf + u2 + gf
    else:
        u2 = momentum * uf + gf
        v2 = vf + u2

    kmax = min(max_k(n, sparsity), n)
    # steady-state width: once the schedule saturates (step >=
    # rampup_step -> sparsity 0.999), k_eff never exceeds the terminal
    # k again, so gathering the full schedule-max width forever would
    # move ~max_ratio*n entries per step in perpetuity (e.g. n/4 with
    # the paper's 0.75-first warmup) instead of n/1000 — negating the
    # byte cut dgc_allreduce_bytes models. +1 absorbs the f32-vs-python
    # rounding of the reference's int cast.
    k_term = min(n, max(1, int(n * (1.0 - _TERMINAL_SPARSITY))) + 1)
    ratio = 1.0 - period_sparsity(sparsity, step, rampup_step)
    k_eff = jnp.maximum(
        (ratio * n).astype(jnp.int32), 1)            # reference int cast

    def _select_exchange(width):
        _, idx = lax.top_k(jnp.abs(v2), width)
        live = jnp.arange(width) < jnp.minimum(k_eff, width)
        sent_vals = jnp.where(live, v2[idx], 0.0)
        sent_idx = jnp.where(live, idx, 0)           # dead slots add 0.0

        # momentum factor masking: sent positions reset locally
        # (scatter-min so a dead slot's index-0 placeholder can't
        # overwrite a live zero)
        keep = jnp.ones((n,), jnp.float32).at[sent_idx].min(
            jnp.where(live, 0.0, 1.0))
        u3 = u2 * keep
        v3 = v2 * keep

        if axis is not None:
            all_vals = lax.all_gather(sent_vals, axis)   # [W, width]
            all_idx = lax.all_gather(sent_idx, axis)
            dec = jnp.zeros((n,), jnp.float32).at[
                all_idx.reshape(-1)].add(all_vals.reshape(-1))
            if combine == "mean":
                dec = dec / all_vals.shape[0]
        else:
            dec = jnp.zeros((n,), jnp.float32).at[sent_idx].add(sent_vals)
        return dec, u3, v3

    if kmax > k_term:
        # two static widths behind a cond: every rank holds the same
        # replicated step, so all ranks take the same branch and the
        # collective is uniform; steady state moves only k_term entries.
        decoded, u3, v3 = lax.cond(
            step >= float(rampup_step),
            lambda: _select_exchange(k_term),
            lambda: _select_exchange(kmax))
    else:
        decoded, u3, v3 = _select_exchange(kmax)

    active = step >= float(rampup_begin_step)
    decoded = jnp.where(active, decoded, gf)
    u_out = jnp.where(active, u3, uf)
    v_out = jnp.where(active, v3, vf)
    return (decoded.reshape(shape).astype(g.dtype),
            u_out.reshape(shape).astype(u.dtype),
            v_out.reshape(shape).astype(v.dtype))


def clip_by_norm_rampup(g, step, *, clip_norm: float,
                        rampup_begin_step: float):
    """The reference's dgc_clip_by_norm (dgc_clip_by_norm_op.h): past
    the rampup begin step, clip the LOCAL gradient to ``clip_norm``
    (callers pass local_grad_clip_norm / num_trainers**2); before it,
    pass through."""
    norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(norm, 1e-12))
    active = jnp.asarray(step, jnp.float32).reshape(()) >= float(
        rampup_begin_step)
    return jnp.where(active, g * scale.astype(g.dtype), g)


def dgc_allreduce_bytes(numel: int, k: int, world: int) -> dict:
    """Comm cost model for the BASELINE.md note: per-device bytes moved
    by a ring dense allreduce vs the DGC allgather of (idx, val) pairs.
    Dense ring: 2 * numel * 4 * (W-1)/W. DGC allgather: (W-1) * k * 8
    received per device (4B value + 4B index per entry)."""
    dense = 2 * numel * 4 * (world - 1) / world
    sparse = (world - 1) * k * 8
    return {"dense_bytes": dense, "sparse_bytes": sparse,
            "payoff": dense / max(sparse, 1)}
