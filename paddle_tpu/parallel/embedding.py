"""Sharded embedding tables (expert/table parallelism).

The TPU-native replacement for the reference's distributed lookup table:
rows sharded across parameter servers with RPC prefetch-by-ids (reference:
operators/distributed/parameter_prefetch.cc, transpiler
distribute_transpiler.py:1317) and pslib Downpour sparse tables (reference:
framework/fleet/fleet_wrapper.h:62). Here the table is sharded over a mesh
axis; each device gathers its local rows and a psum over the axis combines
partial results (ids outside a shard contribute zeros) — all-to-all traffic
rides ICI instead of pserver RPC.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _sharded_lookup_local(w_local, ids, *, axis_name: str):
    """w_local: [V_loc, D] this shard's rows; ids: [...] global ids
    (replicated). Rows outside the shard contribute zero; psum combines."""
    n = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    v_loc = w_local.shape[0]
    lo = rank * v_loc
    local_ids = ids - lo
    in_shard = (local_ids >= 0) & (local_ids < v_loc)
    safe = jnp.clip(local_ids, 0, v_loc - 1)
    rows = jnp.take(w_local, safe, axis=0)
    rows = rows * in_shard[..., None].astype(rows.dtype)
    return jax.lax.psum(rows, axis_name)


def sharded_embedding_lookup(
    table,
    ids,
    mesh: Mesh,
    shard_axis: str = "model",
    data_axis: Optional[str] = None,
):
    """table: [V, D] sharded over rows on ``shard_axis``; ids: any int
    shape, batch-sharded over ``data_axis`` on dim 0 when given (keeps the
    gathered [b, ..., D] output batch-sharded instead of replicating it).
    Negative ids wrap (reference lookup_table_op.cc: negative = vocab+id),
    matching the dense path."""
    ids = jnp.where(ids < 0, ids + table.shape[0], ids)
    from paddle_tpu.parallel.mesh import axis_size, axis_tuple

    d_axes = axis_tuple(data_axis)
    d = None
    if d_axes and all(a in mesh.axis_names for a in d_axes) and (
        jnp.shape(ids)[0] % axis_size(mesh, d_axes) == 0
    ):
        d = data_axis
    ids_spec = P(d, *([None] * (jnp.ndim(ids) - 1)))
    out_spec = P(d, *([None] * jnp.ndim(ids)))
    fn = jax.shard_map(
        functools.partial(_sharded_lookup_local, axis_name=shard_axis),
        mesh=mesh,
        in_specs=(P(shard_axis, None), ids_spec),
        out_specs=out_spec,
    )
    return fn(table, ids)
