"""Pallas TPU attention kernel.

The hot op of the transformer family (SURVEY.md section 7: "pallas kernels
for the hot ops"). Forward runs as a Pallas kernel that keeps the score
matrix for one query block in VMEM — scores never round-trip to HBM, the
two matmuls hit the MXU back-to-back. Backward recomputes through the jnp
composition under custom_vjp (flash-style rematerialization: trade FLOPs
for HBM, XLA fuses the recompute).

Layout: q, k, v are [b, h, t, dh]; bias is additive [b, 1|h, tq, tk].
Block size over queries is 256 (fits (256, t) f32 scores in VMEM for the
sequence lengths the benchmarks use; lane dim dh is zero-padded to 128 by
Mosaic automatically).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_Q_BLOCK = 256


def _attn_fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, *, scale):
    # q_ref: [1, Bq, dh]; k_ref/v_ref: [1, t, dh]; bias_ref: [1, Bq, t]
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    if bias_ref is not None:
        s = s + bias_ref[0].astype(jnp.float32)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0] = (o / l).astype(o_ref.dtype)


def _reference_attention(q, k, v, bias, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(s.dtype)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def flash_attention(q, k, v, bias=None, scale: Optional[float] = None,
                    q_block: int = DEFAULT_Q_BLOCK):
    return _flash_fwd(q, k, v, bias, scale, q_block)[0]


def _flash_fwd(q, k, v, bias, scale, q_block):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    b, h, tq, dh = q.shape
    tk = k.shape[2]
    bq = min(q_block, tq)
    if tq % bq != 0 or jax.default_backend() != "tpu":
        out = _reference_attention(q, k, v, bias, scale)
        return out, (q, k, v, bias)

    bh = b * h
    q_r = q.reshape(bh, tq, dh)
    k_r = k.reshape(bh, tk, dh)
    v_r = v.reshape(bh, tk, dh)
    nq = tq // bq

    in_specs = [
        pl.BlockSpec((1, bq, dh), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, tk, dh), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, tk, dh), lambda i, j: (i, 0, 0)),
    ]
    args = [q_r, k_r, v_r]
    if bias is not None:
        # Never materialize a broadcast bias: keep the stored rank
        # ([b,1,1,tk] pad rows or [b,1|h,tq,tk] causal) and index size-1
        # dims with a constant 0 block; the kernel broadcasts in VMEM.
        hb, tq_b = bias.shape[1], bias.shape[2]
        if hb == 1:
            bias_bh = bias.reshape(b, tq_b, tk)
            if tq_b == 1:
                spec = pl.BlockSpec((1, 1, tk), lambda i, j, h=h: (i // h, 0, 0))
            else:
                spec = pl.BlockSpec((1, bq, tk), lambda i, j, h=h: (i // h, j, 0))
        else:
            bias_bh = bias.reshape(bh, tq_b, tk)
            if tq_b == 1:
                spec = pl.BlockSpec((1, 1, tk), lambda i, j: (i, 0, 0))
            else:
                spec = pl.BlockSpec((1, bq, tk), lambda i, j: (i, j, 0))
        in_specs.append(spec)
        args.append(bias_bh)
        kernel = functools.partial(_attn_fwd_kernel, scale=scale)
    else:
        kernel = functools.partial(
            lambda qr, kr, vr, orf, scale: _attn_fwd_kernel(
                qr, kr, vr, None, orf, scale=scale),
            scale=scale,
        )

    out = pl.pallas_call(
        kernel,
        grid=(bh, nq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq, dh), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, dh), q.dtype),
    )(*args)
    return out.reshape(b, h, tq, dh), (q, k, v, bias)


def _flash_bwd(scale, q_block, res, g):
    q, k, v, bias = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])

    def f(q, k, v, bias):
        return _reference_attention(q, k, v, bias, scale)

    if bias is None:
        _, vjp = jax.vjp(lambda a, b, c: f(a, b, c, None), q, k, v)
        dq, dk, dv = vjp(g)
        return dq, dk, dv, None
    _, vjp = jax.vjp(f, q, k, v, bias)
    dq, dk, dv, dbias = vjp(g)
    return dq, dk, dv, dbias


flash_attention.defvjp(_flash_fwd, _flash_bwd)
