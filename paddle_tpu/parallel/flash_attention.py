"""Pallas TPU flash attention (FlashAttention-2 style, head-batched).

The hot op of the transformer family (SURVEY.md section 7: "pallas kernels
for the hot ops"). Both directions are K-blocked with online softmax: the
score matrix never exists at full [tq, tk] size in any memory space, so
VMEM use is O(h * block^2) and HBM traffic is O(t) regardless of context
length — the property the long-context/ring-attention path builds on.

Blocks batch ALL heads of one batch element per grid step ((1, h, bq, dh)
blocks over the native [b, h, t, dh] layout). At short sequence lengths a
per-(b*h) grid is dominated by per-step DMA/setup overhead (measured 331us
per 44us-ideal forward at t=256); head-batching amortizes it 8x.

- Forward: grid (b, tq/bq, tk/bk); running (m, l, acc) in VMEM scratch
  across the k-block loop; emits the output AND the logsumexp rows.
- Backward: recompute p = exp(s - lse) per block (no stored attention).
  dq in one kernel (k-blocks inner), dk/dv in a second (q-blocks inner),
  using the standard delta = rowsum(do * o) reduction. Exposed as
  ``flash_attention_bwd`` so the framework's sdpa_grad op can consume the
  forward's saved (out, lse) instead of re-running the forward kernel
  (XLA cannot CSE custom calls, so a vjp-style recompute would execute).
- Attention dropout runs inside the kernels via the TPU PRNG: the mask for
  score block (b, jq, jk) is regenerated from a hash of (seed, b, jq, jk)
  in every kernel, so forward and backward see identical masks and nothing
  is stored.

``bias`` is additive [b, 1|h, 1|tq, tk] mask plumbing, NOT a trainable
input: its cotangent is zeros on the Pallas path (computing it would
materialize a t x t gradient). Use the dense composition for a learnable
additive bias.

Falls back to the dense jnp composition off-TPU or when the sequence
lengths don't divide the block sizes.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_Q_BLOCK = 256
DEFAULT_K_BLOCK = 256
_NEG_INF = -1e30

# Soft cap on the f32 score block (h * bq * bk * 4B). Mosaic sums ALL of
# a kernel's score-sized temps on its ~16MB scoped-vmem stack (the dkv
# kernel holds ~6 of them plus casts and scratch), so the per-block cap
# must stay well under limit/6 — 1.5MB lands bq=128 at h=8, bk=256,
# which compiles with a [*, tq, tk] bias at t=1024 and beyond.
_SCORE_VMEM_BYTES = 3 * 2**19

# Test hook: run the Pallas kernels in interpreter mode on CPU so the
# blocked online-softmax path itself is exercised by the pytest suite
# (the reference-composition fallback would otherwise shadow it off-TPU).
_INTERPRET = False


def _block_seed(seed, i, j, kk):
    """Mix (seed, batch, q-block, k-block) into one scalar for the per-core
    PRNG (the multi-operand prng_seed form doesn't lower on all backends).
    int32 wraparound is the hash."""
    s = seed
    for x in (i, j, kk):
        s = (s * jnp.int32(1000003)) ^ jnp.int32(x)
    return s


def _dropout_mask(p_keep: float, shape):
    """Per-block keep mask from the already-seeded TPU PRNG, scaled by
    1/p_keep (inverted dropout)."""
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    thresh = jnp.uint32(int(p_keep * float(2**32 - 1)))
    return (bits < thresh).astype(jnp.float32) * (1.0 / p_keep)


def _pick_blocks(h, tq, tk, q_block, k_block):
    bq = min(q_block, tq)
    bk = min(k_block, tk)
    while h * bq * bk * 4 > _SCORE_VMEM_BYTES and bq > 64:
        bq //= 2
    while h * bq * bk * 4 > _SCORE_VMEM_BYTES and bk > 128:
        bk //= 2
    return bq, bk


def _use_pallas(tq, tk, bq, bk):
    return (
        (jax.default_backend() == "tpu" or _INTERPRET)
        and tq % bq == 0
        and tk % bk == 0
    )


# ---------------------------------------------------------------------------
# kernels — refs are blocks of the native [b, h, t, dh] layout; index 0
# drops the leading size-1 batch-block dim, so shapes below are
# q (h, bq, dh) / k, v (h, bk, dh) / bias (hb, 1|bq, bk) / lse (h, bq, 1).
# ---------------------------------------------------------------------------


def _causal_mask(s, j, kk, bq, bk, transposed=False):
    """Mask future positions inside score block (h, bq, bk) for q-block
    j / k-block kk (``transposed``: block is (h, bk, bq))."""
    if transposed:
        k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + kk * bk
        q_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2) + j * bq
    else:
        q_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * bq
        k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2) + kk * bk
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


def _causal_live(j, kk, bq, bk):
    """Does block (q=j, k=kk) contain ANY unmasked element? Blocks fully
    above the diagonal are skipped outright — the causal 2x compute cut
    (loads still stream; compute and softmax are the bound)."""
    return kk * bk <= (j + 1) * bq - 1


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, nk, p_drop, causal=False):
    j, kk = pl.program_id(1), pl.program_id(2)
    bq, bk = q_ref.shape[2], k_ref.shape[2]

    @pl.when(kk == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            s = _causal_mask(s, j, kk, bq, bk)

        m_prev = m_scr[:, :, :1]
        l_prev = l_scr[:, :, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)

        if p_drop > 0.0:
            pltpu.prng_seed(
                _block_seed(seed_ref[0], pl.program_id(0), j, kk))
            p = p * _dropout_mask(1.0 - p_drop, p.shape)

        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        pl.when(_causal_live(j, kk, bq, bk))(_compute)
    else:
        _compute()

    @pl.when(kk == nk - 1)
    def _finish():
        l = l_scr[:, :, :1]
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:, :, :1] + jnp.log(l)


def _dq_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
               delta_ref, dq_ref, dq_scr, *, scale, nk, p_drop,
               causal=False):
    j, kk = pl.program_id(1), pl.program_id(2)
    bq, bk = q_ref.shape[2], k_ref.shape[2]

    @pl.when(kk == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]        # (h, bq, 1) f32
        delta = delta_ref[0]    # (h, bq, 1) f32

        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            s = _causal_mask(s, j, kk, bq, bk)
        p = jnp.exp(s - lse)  # post-softmax probabilities, recomputed

        dp = jax.lax.dot_general(
            do, v, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        if p_drop > 0.0:
            pltpu.prng_seed(
                _block_seed(seed_ref[0], pl.program_id(0), j, kk))
            dp = dp * _dropout_mask(1.0 - p_drop, dp.shape)
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(_causal_live(j, kk, bq, bk))(_compute)
    else:
        _compute()

    @pl.when(kk == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                scale, nq, p_drop, causal=False):
    kk, jq = pl.program_id(1), pl.program_id(2)
    bq, bk = q_ref.shape[2], k_ref.shape[2]

    @pl.when(jq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse_t = jnp.transpose(lse_ref[0], (0, 2, 1))      # (h, 1, bq)
        delta_t = jnp.transpose(delta_ref[0], (0, 2, 1))  # (h, 1, bq)

        # Work in the transposed orientation: s_t (h, bk, bq)
        s_t = jax.lax.dot_general(
            k, q, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
        if bias_ref is not None:
            s_t = s_t + jnp.transpose(bias_ref[0].astype(jnp.float32),
                                      (0, 2, 1))
        if causal:
            s_t = _causal_mask(s_t, jq, kk, bq, bk, transposed=True)
        p_t = jnp.exp(s_t - lse_t)

        if p_drop > 0.0:
            # Same (b, q-block, k-block) stream as the forward, generated
            # in the forward's (h, bq, bk) orientation then transposed.
            pltpu.prng_seed(
                _block_seed(seed_ref[0], pl.program_id(0), jq, kk))
            drop_t = jnp.transpose(
                _dropout_mask(
                    1.0 - p_drop,
                    (p_t.shape[0], p_t.shape[2], p_t.shape[1])),
                (0, 2, 1),
            )
            pd_t = p_t * drop_t
        else:
            pd_t = p_t

        dv_scr[:] += jax.lax.dot_general(
            pd_t.astype(do.dtype), do, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        dp_t = jax.lax.dot_general(
            v, do, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        if p_drop > 0.0:
            dp_t = dp_t * drop_t
        ds_t = p_t * (dp_t - delta_t) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds_t.astype(q.dtype), q, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(_causal_live(jq, kk, bq, bk))(_compute)
    else:
        _compute()

    @pl.when(jq == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bias_spec(bias, bq, bk, *, transposed=False):
    """BlockSpec for the stored-rank bias [b, 1|h, 1|tq, tk]. Index maps
    take grid (i=batch, j=qblk, kk=kblk); ``transposed`` grids are
    (i, kk, j)."""
    hb, tq_b = bias.shape[1], bias.shape[2]
    qdim = 1 if tq_b == 1 else bq
    if transposed:
        if tq_b == 1:
            idx = lambda i, kk, j, *_: (i, 0, 0, kk)
        else:
            idx = lambda i, kk, j, *_: (i, 0, j, kk)
    else:
        if tq_b == 1:
            idx = lambda i, j, kk, *_: (i, 0, 0, kk)
        else:
            idx = lambda i, j, kk, *_: (i, 0, j, kk)
    return pl.BlockSpec((1, hb, qdim, bk), idx)


def _reference_scores(q, k, bias, scale, causal):
    """Scaled scores + bias + causal mask — the ONE copy both the dense
    forward and its lse statistic derive from (the ring-attention merge
    combines (out, lse), so they must never desynchronize)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(s.dtype)
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        mask = (jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :])
        s = jnp.where(mask[None, None], s, _NEG_INF)
    return s


def _reference_attention_with_lse(q, k, v, bias, scale, p_drop=0.0,
                                  seed=None, causal=False):
    """(out, lse) from ONE score tensor — the fallback twin of the
    kernels' contract. out and lse must never derive from separately
    constructed scores (different dtype promotion would desynchronize
    them at exactly the tolerance the ring merge relies on)."""
    s = _reference_scores(q, k, bias, scale, causal)
    lse = jax.scipy.special.logsumexp(s, axis=-1, keepdims=True)
    p = jax.nn.softmax(s, axis=-1)
    if p_drop > 0.0:
        key = jax.random.PRNGKey(0 if seed is None else jnp.asarray(seed))
        keep = jax.random.bernoulli(key, 1.0 - p_drop, p.shape)
        p = jnp.where(keep, p / (1.0 - p_drop), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v), lse


def _reference_attention(q, k, v, bias, scale, p_drop=0.0, seed=None,
                         causal=False):
    return _reference_attention_with_lse(q, k, v, bias, scale, p_drop,
                                         seed, causal)[0]


def _seed_arr(seed):
    if seed is None:
        return jnp.zeros((1,), jnp.int32)
    return jnp.asarray(seed, jnp.int32).reshape((1,))


def _seed_cotangent(seed):
    """Symbolic-zero cotangent for the integer seed operand."""
    if seed is None:
        return None
    import numpy as _np

    return _np.zeros(_np.shape(seed), jax.dtypes.float0)


# ---------------------------------------------------------------------------
# functional entry points (used directly by the sdpa op pair)
# ---------------------------------------------------------------------------


def flash_attention_fwd(q, k, v, bias=None, seed=None, scale=None,
                        p_drop: float = 0.0,
                        q_block: int = DEFAULT_Q_BLOCK,
                        k_block: int = DEFAULT_K_BLOCK,
                        causal: bool = False):
    """-> (out, lse) with lse [b, h, tq, 1] f32 — REAL logsumexp rows on
    every path including the dense fallback (the ring-attention merge
    consumes them; the fallback backward still recomputes via vjp).

    ``causal=True`` applies the future mask IN-KERNEL (block-position
    iota compare) and skips fully-masked k-blocks outright — no [tq, tk]
    bias tensor exists anywhere, preserving the O(t) HBM property for
    decoder self-attention, and the dead upper-triangle blocks cost no
    MXU time (the causal ~2x)."""
    if p_drop > 0.0 and seed is None:
        raise ValueError(
            "flash_attention: p_drop > 0 requires a per-step `seed`; "
            "without one the SAME mask would be applied every step, which "
            "is not dropout"
        )
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    b, h, tq, dh = q.shape
    tk = k.shape[2]
    bq, bk = _pick_blocks(h, tq, tk, q_block, k_block)
    if not _use_pallas(tq, tk, bq, bk):
        # REAL logsumexp rows, not placeholder zeros: the ring-attention
        # merge combines per-block (o, lse) partials, and both must
        # derive from one score tensor (_reference_attention_with_lse).
        return _reference_attention_with_lse(
            q, k, v, bias, scale, p_drop,
            seed if p_drop > 0.0 else None, causal=causal)

    nq, nk = tq // bq, tk // bk
    in_specs = [
        pl.BlockSpec((1, h, bq, dh), lambda i, j, kk, *_: (i, 0, j, 0)),
        pl.BlockSpec((1, h, bk, dh), lambda i, j, kk, *_: (i, 0, kk, 0)),
        pl.BlockSpec((1, h, bk, dh), lambda i, j, kk, *_: (i, 0, kk, 0)),
    ]
    args = [q, k, v]
    if bias is not None:
        in_specs.append(_bias_spec(bias, bq, bk))
        args.append(bias)
        kernel = functools.partial(_fwd_kernel, scale=scale, nk=nk,
                                   p_drop=p_drop, causal=causal)
    else:
        kernel = functools.partial(
            lambda sr, qr, kr, vr, orf, lr, ms, ls, accs, **kw: _fwd_kernel(
                sr, qr, kr, vr, None, orf, lr, ms, ls, accs, **kw),
            scale=scale, nk=nk, p_drop=p_drop, causal=causal,
        )

    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, nq, nk),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, h, bq, dh), lambda i, j, kk, *_: (i, 0, j, 0)),
                pl.BlockSpec((1, h, bq, 1), lambda i, j, kk, *_: (i, 0, j, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((h, bq, 128), jnp.float32),
                pltpu.VMEM((h, bq, 128), jnp.float32),
                pltpu.VMEM((h, bq, dh), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tq, dh), q.dtype),
            jax.ShapeDtypeStruct((b, h, tq, 1), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(_seed_arr(seed), *args)
    return out, lse


def flash_attention_bwd(q, k, v, bias, seed, out, lse, g, scale=None,
                        p_drop: float = 0.0,
                        q_block: int = DEFAULT_Q_BLOCK,
                        k_block: int = DEFAULT_K_BLOCK,
                        causal: bool = False, g_lse=None):
    """-> (dq, dk, dv), consuming the forward's saved (out, lse).

    ``g_lse``: optional cotangent of the lse OUTPUT ([b, h, tq, 1]).
    The lse rows are a real differentiated quantity for consumers like
    the ring-attention merge (block weights exp(lse_blk - lse_comb)).
    dlse/ds = p, so the lse cotangent phi folds EXACTLY into the
    existing backward as ds = p*(dp - (delta - phi)) — one subtraction
    on the per-row delta, no kernel changes."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    b, h, tq, dh = q.shape
    tk = k.shape[2]
    bq, bk = _pick_blocks(h, tq, tk, q_block, k_block)
    if not _use_pallas(tq, tk, bq, bk):
        def f(q, k, v):
            return _reference_attention_with_lse(
                q, k, v, bias, scale, p_drop,
                seed if p_drop > 0.0 else None, causal=causal)

        _, vjp = jax.vjp(f, q, k, v)
        return vjp((g, jnp.zeros((b, h, tq, 1), jnp.float32)
                    if g_lse is None else g_lse))

    nq, nk = tq // bq, tk // bk
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [b, h, tq, 1]
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)
    seed_arr = _seed_arr(seed)

    # --- dq: grid (b, nq, nk), k-blocks inner ---
    dq_specs = [
        pl.BlockSpec((1, h, bq, dh), lambda i, j, kk, *_: (i, 0, j, 0)),   # q
        pl.BlockSpec((1, h, bk, dh), lambda i, j, kk, *_: (i, 0, kk, 0)),  # k
        pl.BlockSpec((1, h, bk, dh), lambda i, j, kk, *_: (i, 0, kk, 0)),  # v
    ]
    dq_args = [q, k, v]
    if bias is not None:
        dq_specs.append(_bias_spec(bias, bq, bk))
        dq_args.append(bias)
        dq_kernel = functools.partial(_dq_kernel, scale=scale, nk=nk,
                                      p_drop=p_drop, causal=causal)
    else:
        dq_kernel = functools.partial(
            lambda sr, qr, kr, vr, dor, lr, der, dqr, dqs, **kw: _dq_kernel(
                sr, qr, kr, vr, None, dor, lr, der, dqr, dqs, **kw),
            scale=scale, nk=nk, p_drop=p_drop, causal=causal,
        )
    dq_specs += [
        pl.BlockSpec((1, h, bq, dh), lambda i, j, kk, *_: (i, 0, j, 0)),  # do
        pl.BlockSpec((1, h, bq, 1), lambda i, j, kk, *_: (i, 0, j, 0)),   # lse
        pl.BlockSpec((1, h, bq, 1), lambda i, j, kk, *_: (i, 0, j, 0)),   # delta
    ]
    dq_args += [g, lse, delta]

    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, nq, nk),
            in_specs=dq_specs,
            out_specs=pl.BlockSpec((1, h, bq, dh),
                                   lambda i, j, kk, *_: (i, 0, j, 0)),
            scratch_shapes=[pltpu.VMEM((h, bq, dh), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, tq, dh), q.dtype),
        interpret=_INTERPRET,
    )(seed_arr, *dq_args)

    # --- dk/dv: grid (b, nk, nq), q-blocks inner ---
    dkv_specs = [
        pl.BlockSpec((1, h, bq, dh), lambda i, kk, j, *_: (i, 0, j, 0)),   # q
        pl.BlockSpec((1, h, bk, dh), lambda i, kk, j, *_: (i, 0, kk, 0)),  # k
        pl.BlockSpec((1, h, bk, dh), lambda i, kk, j, *_: (i, 0, kk, 0)),  # v
    ]
    dkv_args = [q, k, v]
    if bias is not None:
        dkv_specs.append(_bias_spec(bias, bq, bk, transposed=True))
        dkv_args.append(bias)
        dkv_kernel = functools.partial(_dkv_kernel, scale=scale, nq=nq,
                                       p_drop=p_drop, causal=causal)
    else:
        dkv_kernel = functools.partial(
            lambda sr, qr, kr, vr, dor, lr, der, dkr, dvr, dks, dvs, **kw:
                _dkv_kernel(sr, qr, kr, vr, None, dor, lr, der, dkr, dvr,
                            dks, dvs, **kw),
            scale=scale, nq=nq, p_drop=p_drop, causal=causal,
        )
    dkv_specs += [
        pl.BlockSpec((1, h, bq, dh), lambda i, kk, j, *_: (i, 0, j, 0)),  # do
        pl.BlockSpec((1, h, bq, 1), lambda i, kk, j, *_: (i, 0, j, 0)),   # lse
        pl.BlockSpec((1, h, bq, 1), lambda i, kk, j, *_: (i, 0, j, 0)),   # delta
    ]
    dkv_args += [g, lse, delta]

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, nk, nq),
            in_specs=dkv_specs,
            out_specs=[
                pl.BlockSpec((1, h, bk, dh),
                             lambda i, kk, j, *_: (i, 0, kk, 0)),
                pl.BlockSpec((1, h, bk, dh),
                             lambda i, kk, j, *_: (i, 0, kk, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((h, bk, dh), jnp.float32),
                pltpu.VMEM((h, bk, dh), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tk, dh), k.dtype),
            jax.ShapeDtypeStruct((b, h, tk, dh), v.dtype),
        ],
        interpret=_INTERPRET,
    )(seed_arr, *dkv_args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# standalone custom-vjp wrapper (public API; the Program IR path uses the
# sdpa/sdpa_grad op pair instead so the backward reuses saved stats)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def flash_attention(q, k, v, bias=None, seed=None,
                    scale: Optional[float] = None, p_drop: float = 0.0,
                    q_block: int = DEFAULT_Q_BLOCK,
                    k_block: int = DEFAULT_K_BLOCK,
                    causal: bool = False):
    """o = dropout(softmax(q k^T * scale + bias)) v.

    ``seed``: int32 scalar array driving attention dropout (ignored when
    p_drop == 0). See the module docstring for the bias-gradient caveat.
    """
    out, _ = flash_attention_fwd(q, k, v, bias, seed, scale, p_drop,
                                 q_block, k_block, causal)
    return out


def _vjp_fwd(q, k, v, bias, seed, scale, p_drop, q_block, k_block,
             causal=False):
    out, lse = flash_attention_fwd(q, k, v, bias, seed, scale, p_drop,
                                   q_block, k_block, causal)
    return out, (q, k, v, bias, seed, out, lse)


def _vjp_bwd(scale, p_drop, q_block, k_block, causal, res, g,
             g_lse=None):
    q, k, v, bias, seed, out, lse = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    bq, bk = _pick_blocks(q.shape[1], q.shape[2], k.shape[2],
                          q_block, k_block)
    if _use_pallas(q.shape[2], k.shape[2], bq, bk):
        dq, dk, dv = flash_attention_bwd(q, k, v, bias, seed, out, lse, g,
                                         scale, p_drop, q_block, k_block,
                                         causal, g_lse=g_lse)
        # Pallas path: bias is mask plumbing, cotangent intentionally zero
        # (see module docstring).
        dbias = None if bias is None else jnp.zeros_like(bias)
    else:
        sd = seed if p_drop > 0.0 else None
        glse = (jnp.zeros_like(lse) if g_lse is None else g_lse)

        def out_and_lse(a, b, c, bb):
            return _reference_attention_with_lse(a, b, c, bb, scale,
                                                 p_drop, sd, causal)

        if bias is None:
            _, vjp = jax.vjp(
                lambda a, b, c: out_and_lse(a, b, c, None), q, k, v)
            dq, dk, dv = vjp((g, glse))
            dbias = None
        else:
            _, vjp = jax.vjp(out_and_lse, q, k, v, bias)
            dq, dk, dv, dbias = vjp((g, glse))
    return dq, dk, dv, dbias, _seed_cotangent(seed)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)


# --- custom-vjp wrapper ---
#
# pallas_call has no JVP rule, so any path that differentiates the forward
# through jax.vjp (the scan-over-layers grad, ring-attention fallback,
# ad-hoc jax.grad over a model fn) would fail on TPU. This wrapper teaches
# autodiff to use the blocked backward kernels instead; the paired
# `scaled_dot_product_attention_grad` op remains for the unrolled Program
# path, sharing the same kernels.


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def flash_attention_with_lse(q, k, v, bias=None, seed=None,
                             scale: Optional[float] = None,
                             p_drop: float = 0.0,
                             q_block: int = DEFAULT_Q_BLOCK,
                             k_block: int = DEFAULT_K_BLOCK,
                             causal: bool = False):
    """(out, lse) variant of ``flash_attention`` — same backward rule
    (shared ``_vjp_bwd``: blocked Pallas kernels, true dbias on the dense
    fallback, float0 seed cotangent). The sdpa op uses this so its saved
    Lse output exists AND jax.vjp through the op (scan-over-layers grad)
    works despite pallas_call having no JVP rule."""
    return flash_attention_fwd(q, k, v, bias, seed, scale, p_drop,
                               q_block, k_block, causal)


def _fa_lse_vjp_fwd(q, k, v, bias, seed, scale, p_drop, q_block, k_block,
                    causal=False):
    out, lse = flash_attention_fwd(q, k, v, bias, seed, scale, p_drop,
                                   q_block, k_block, causal)
    return (out, lse), (q, k, v, bias, seed, out, lse)


def _fa_lse_vjp_bwd(scale, p_drop, q_block, k_block, causal, res, gs):
    g, g_lse = gs
    q = res[0]
    return _vjp_bwd(scale, p_drop, q_block, k_block, causal, res,
                    g.astype(q.dtype), g_lse=g_lse)


flash_attention_with_lse.defvjp(_fa_lse_vjp_fwd, _fa_lse_vjp_bwd)



# ---------------------------------------------------------------------------
# BTHD fast path: q/k/v in [b, t, h, dh] — the layout the attention
# projections naturally produce (reshape of [b, t, d]; no head transpose).
# Profiling the transformer bench showed the BHTD kernels cost ~15 ms/step
# in pure layout copies: XLA must re-lay-out every custom-call operand
# around the [b, h, t, dh] contract, and the b-sized grid pays ~5 us fixed
# cost per program. Here the whole (tq, tk) score fits one kernel program
# (single-block, no online softmax carry) and `bb` batch elements share
# one program, so t <= ~512 runs with 8-32x fewer program invocations and
# zero operand re-layouts. Longer sequences fall back to the K-blocked
# BHTD kernels (one transpose pair) or, beyond that, ring attention.
# ---------------------------------------------------------------------------

_SMALL_T_MAX = 512


def _use_bthd_small(tq, tk):
    return (
        (jax.default_backend() == "tpu" or _INTERPRET)
        and 8 <= tq <= _SMALL_T_MAX
        and 8 <= tk <= _SMALL_T_MAX
        # tq is walked in _CQ-row grid steps: a non-dividing tq would
        # truncate nq = tq // cq and leave the tail rows unwritten
        and (tq <= _CQ or tq % _CQ == 0)
    )


def _small_dropout(seed_ref, i, jc, hi, shape, p_drop):
    """Scaled keep mask for (batch i, row-block jc, head hi). bf16 mask;
    the bf16 rounding of 1/p_keep (~0.2%) shifts the inverted-dropout
    scale identically in both directions, so gradients stay exact for
    the actual forward. 16-bit random words: RNG throughput is
    bits-bound (uint32 masks measured 0.165 ms/call extra across
    fwd+bwd at b=64 t=256 h=8); 1/65536 keep-rate granularity is far
    below dropout's statistical noise."""
    pltpu.prng_seed(_block_seed(seed_ref[0], i, jc, hi))
    p_keep = 1.0 - p_drop
    rows, tk = shape
    if rows % 2 == 0:
        # u32->u16 bitcast doubles the SUBLANE (major) dim: (rows//2, tk)
        # uint32 reinterprets as (rows, tk) uint16. Mosaic can't compare
        # u16 directly, so widen for the compare — the expensive part
        # (random-bit generation) is still halved.
        half = pltpu.prng_random_bits((rows // 2, tk))
        bits = pltpu.bitcast(half, jnp.uint16).astype(jnp.int32)
        thresh = jnp.int32(min(int(p_keep * 65536.0), 65535))
    else:
        bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
        thresh = jnp.uint32(int(p_keep * float(2**32 - 1)))
    return (bits < thresh).astype(jnp.bfloat16) * jnp.bfloat16(1.0 / p_keep)


def _chunked_dropout(seed_ref, i, j, cq, hi, tk, p_drop, key_of_jabs):
    """(cq, tk) keep mask assembled from 128-row sub-blocks keyed by
    ABSOLUTE row-block index (via ``key_of_jabs``), so forward and
    backward kernels regenerate identical streams even when they walk tq
    with different chunk sizes (the forward uses the widest chunk VMEM
    allows; the fused backward runs at 128)."""
    nsub = max(1, cq // _CQ)
    rows = cq // nsub
    subs = [
        _small_dropout(seed_ref, i, key_of_jabs(j * nsub + b), hi,
                       (rows, tk), p_drop)
        for b in range(nsub)
    ]
    return subs[0] if nsub == 1 else jnp.concatenate(subs, axis=0)


def _small_dropout_abs(seed_ref, i, j, cq, hi, tk, p_drop):
    return _chunked_dropout(seed_ref, i, j, cq, hi, tk, p_drop,
                            lambda jabs: jabs)


# Fixed q-chunk for the single-block kernels: tq is walked in _CQ-row grid
# steps with the full tk resident per program (k/v block indices don't
# change with the chunk index, so Pallas skips their re-fetch). Inside a
# program everything is 2-D: heads are LANE slices of the (t, h*dh) view
# (a free minor-dims reshape of the [b, t, h, dh] block), so the kernels
# contain NO vector transposes — Mosaic lowers major-dim transposes to
# element shuffles that measured 4x slower than the whole attention op.
_CQ = 128


def _pick_cq(tq, tk, h):
    """Widest q-chunk that divides tq and keeps the phase-split kernels'
    per-head (cq, tk) f32 temps within Mosaic's scoped-vmem budget (Mosaic
    sums ALL live temps across the unrolled head loop, so the budget
    scales with h). Wider chunks amortize the per-program ramp: the fwd
    kernel measured 0.220 -> 0.152 ms going 128 -> 256 at h=8, tk=256
    (the measured-safe product h*cq*tk anchoring the bound below).
    Dropout streams stay chunk-size-independent via _small_dropout_abs."""
    for c in (256, 128):
        if c <= tq and tq % c == 0 and h * c * tk <= 8 * 256 * 256:
            return c
    return min(tq, _CQ)


def _head(x2, hi, dh):
    return x2[:, hi * dh:(hi + 1) * dh]   # lane slice: (t, dh)


def _scores_head(q2, k2, hi, dh, scale, bias_ref, hb, extra_mask=None):
    s = jax.lax.dot_general(
        _head(q2, hi, dh), _head(k2, hi, dh), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                              # (cq, tk)
    if bias_ref is not None:
        b2 = bias_ref[0, min(hi, hb - 1)]  # (1|cq, tk)
        s = s + b2.astype(jnp.float32)
    if extra_mask is not None:             # causal: True = keep
        s = jnp.where(extra_mask, s, _NEG_INF)
    return s


def _fwd_small_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, o_ref,
                      lse_ref, *, scale, p_drop, h, dh, hb):
    # Phase-split over heads (all score matmuls, then all softmaxes, then
    # all pv matmuls): groups the independent per-head matmuls so Mosaic
    # keeps the MXU busy instead of draining it at every head's softmax.
    # Measured 0.220 -> 0.152 ms/call with cq=256 (b=64 t=256 h=8 dh=64).
    i, j = pl.program_id(0), pl.program_id(1)
    q2, k2, v2 = q_ref[0], k_ref[0], v_ref[0]   # (cq|tk, h*dh)
    cq, tk = q2.shape[0], k2.shape[0]
    ss = [_scores_head(q2, k2, hi, dh, scale, bias_ref, hb)
          for hi in range(h)]
    ms = [jnp.max(s, axis=-1, keepdims=True) for s in ss]
    ps = [jnp.exp(s - m) for s, m in zip(ss, ms)]
    ls = [jnp.sum(p, axis=-1, keepdims=True) for p in ps]
    ps = [p * jax.lax.reciprocal(l) for p, l in zip(ps, ls)]
    if p_drop > 0.0:
        ps = [p * _small_dropout_abs(seed_ref, i, j, cq, hi, tk, p_drop)
              for hi, p in enumerate(ps)]
    outs = [
        jax.lax.dot_general(
            p.astype(v2.dtype), _head(v2, hi, dh), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(o_ref.dtype)
        for hi, p in enumerate(ps)
    ]
    o_ref[0] = jnp.concatenate(outs, axis=-1)       # (cq, h*dh)
    lse_ref[0] = jnp.concatenate(
        [m + jnp.log(l) for m, l in zip(ms, ls)], axis=-1)  # (cq, h)


def _bwd_head_grads(q2, k2, v2, do2, lse2, delta2, bias_ref, scale, p_drop,
                    h, dh, hb, drop_fn, extra_mask=None):
    """Shared per-head backward phase: recompute scores, p = exp(s - lse),
    dp = do @ v^T, then (pds, dss) with the dropout mask applied
    identically to p and dp while dss uses the UNdropped p — the invariant
    both the single-block and K-blocked fused backwards must hold."""
    ss = [_scores_head(q2, k2, hi, dh, scale, bias_ref, hb, extra_mask)
          for hi in range(h)]
    ps = [jnp.exp(s - lse2[:, hi:hi + 1]) for hi, s in enumerate(ss)]
    dps = [jax.lax.dot_general(
        _head(do2, hi, dh), _head(v2, hi, dh), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) for hi in range(h)]
    if p_drop > 0.0:
        drops = [drop_fn(hi) for hi in range(h)]
        pds = [p * d for p, d in zip(ps, drops)]
        dps = [dp * d for dp, d in zip(dps, drops)]
    else:
        pds = ps
    dss = [p * (dp - delta2[:, hi:hi + 1]) * scale
           for hi, (p, dp) in enumerate(zip(ps, dps))]
    return pds, dss


def _dqdkv_small_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, do_ref,
                        lse_ref, delta_ref, dq_ref, dk_ref, dv_ref,
                        dk_scr, dv_scr, *, scale, p_drop, nq, h, dh, hb):
    """Fused backward: one kernel computes dq, dk, dv.

    Separate dq/dkv kernels each recompute the scores s and the dp
    matmul — 7 matmuls total, plus double DMA of q/k/v/do/bias. Fusing
    shares the recompute: 5 matmuls, one operand fetch. Measured
    0.235 + 0.464 -> 0.33 ms/call (b=64 t=256 h=8 dh=64, dropout on).
    Phase-split over heads like the forward. dq writes per (i, j) block;
    dk/dv accumulate in f32 scratch, emitted at the last q-chunk."""
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    q2, k2, v2, do2 = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    lse2, delta2 = lse_ref[0], delta_ref[0]         # (cq, h)
    cq, tk = q2.shape[0], k2.shape[0]
    pds, dss = _bwd_head_grads(
        q2, k2, v2, do2, lse2, delta2, bias_ref, scale, p_drop, h, dh, hb,
        lambda hi: _small_dropout_abs(seed_ref, i, j, cq, hi, tk, p_drop))
    dqs = [jax.lax.dot_general(
        ds.astype(k2.dtype), _head(k2, hi, dh), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
        for hi, ds in enumerate(dss)]
    dq_ref[0] = jnp.concatenate(dqs, axis=-1)       # (cq, h*dh)
    for hi in range(h):
        # dv_h += pd^T @ do_h ; dk_h += ds^T @ q_h   (K = cq, full fill)
        dv_scr[:, hi * dh:(hi + 1) * dh] += jax.lax.dot_general(
            pds[hi].astype(do2.dtype), _head(do2, hi, dh),
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dk_scr[:, hi * dh:(hi + 1) * dh] += jax.lax.dot_general(
            dss[hi].astype(q2.dtype), _head(q2, hi, dh),
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(j == nq - 1)
    def _emit():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bias_spec_bthd(bias, cq, tk):
    hb, tq_b = bias.shape[1], bias.shape[2]
    if tq_b == 1:
        return pl.BlockSpec((1, hb, 1, tk), lambda i, j, *_: (i, 0, 0, 0))
    return pl.BlockSpec((1, hb, cq, tk), lambda i, j, *_: (i, 0, j, 0))


# ---------------------------------------------------------------------------
# K-blocked BTHD kernels (512 < tk <= _KB_T_MAX): same 2-D lane-sliced head
# layout as the single-block kernels — no [b,h,t,dh] transposes around the
# custom calls (those measured 5.3 ms/step at t=1024) — with the k axis
# walked in _BK-column grid steps and FlashAttention-2 online softmax.
# ---------------------------------------------------------------------------

# Preferred k-block width 512 (fewer online-softmax correction passes:
# measured 166.6k -> 183.2k tok/s at t=1024), falling back to 256 when
# 512 does not divide tk (e.g. tk=768 runs nk=3 blocks of 256). The
# width is a pure function of the shape, so forward and backward always
# agree and the dropout streams stay aligned.
_BK_CHOICES = (512, 256)
_KB_T_MAX = 1024   # dk/dv live whole in f32 scratch: 2 * tk*h*dh*4 bytes


def _pick_bk(tk, h, dh):
    for bk in _BK_CHOICES:
        # the fused backward runs at cq=128 and keeps ~4 (cq, bk) f32
        # temps per head; stay within the measured-safe h*cq*bk product
        if tk % bk == 0 and h * _CQ * bk <= 8 * 256 * 256:
            return bk
    return None


def _kb_dropout(seed_ref, i, j, cq, hi, kk, bk, p_drop):
    """(cq, bk) keep mask for q-chunk j, k-block kk — same absolute
    128-row keying as _small_dropout_abs with the (jabs, kk) pair packed
    into the one mixing slot (nk <= 4 at _KB_T_MAX with bk=256,
    jabs <= 4096)."""
    return _chunked_dropout(seed_ref, i, j, cq, hi, bk, p_drop,
                            lambda jabs: jabs * 4096 + kk)


def _kb_causal_mask(cq, bk, j, kk):
    """(cq, bk) keep-mask for q-chunk j / k-block kk. Forward and
    backward MUST share this (and _causal_live for the dead-block skip)
    or the recomputed backward p diverges from the forward."""
    qpos = jax.lax.broadcasted_iota(jnp.int32, (cq, bk), 0) + j * cq
    kpos = jax.lax.broadcasted_iota(jnp.int32, (cq, bk), 1) + kk * bk
    return qpos >= kpos


def _bias_spec_kb(bias, cq, bk):
    hb, tq_b = bias.shape[1], bias.shape[2]
    if tq_b == 1:
        return pl.BlockSpec((1, hb, 1, bk),
                            lambda i, j, kk, *_: (i, 0, 0, kk))
    return pl.BlockSpec((1, hb, cq, bk),
                        lambda i, j, kk, *_: (i, 0, j, kk))


def _fwd_kb_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                   m_scr, l_scr, acc_scr, *, scale, p_drop, nk, h, dh, hb,
                   bk, causal=False):
    i, j, kk = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _compute():
        q2, k2, v2 = q_ref[0], k_ref[0], v_ref[0]  # (cq, hdh) / (bk, hdh)
        cq = q2.shape[0]
        mask = _kb_causal_mask(cq, bk, j, kk) if causal else None
        # Phase-split with ONE batched read-modify-write of each scratch
        # per program (per-head scratch RMW serialized the loop: measured
        # 0.78 ms/call before, vs 0.087 analytic, at t=1024).
        ss = [_scores_head(q2, k2, hi, dh, scale, bias_ref, hb, mask)
              for hi in range(h)]                    # (cq, bk) each
        m_prev = m_scr[...]                          # (cq, h)
        l_prev = l_scr[...]
        m_new = jnp.concatenate(
            [jnp.maximum(m_prev[:, hi:hi + 1],
                         jnp.max(ss[hi], axis=-1, keepdims=True))
             for hi in range(h)], axis=-1)           # (cq, h)
        ps = [jnp.exp(ss[hi] - m_new[:, hi:hi + 1]) for hi in range(h)]
        corr = jnp.exp(m_prev - m_new)               # (cq, h)
        l_scr[...] = l_prev * corr + jnp.concatenate(
            [jnp.sum(p, axis=-1, keepdims=True) for p in ps], axis=-1)
        m_scr[...] = m_new
        if p_drop > 0.0:
            ps = [p * _kb_dropout(seed_ref, i, j, cq, hi, kk, bk, p_drop)
                  for hi, p in enumerate(ps)]
        pv = jnp.concatenate(
            [jax.lax.dot_general(
                p.astype(v2.dtype), _head(v2, hi, dh),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
             for hi, p in enumerate(ps)], axis=-1)   # (cq, hdh)
        corr_full = jnp.concatenate(
            [jnp.broadcast_to(corr[:, hi:hi + 1], (cq, dh))
             for hi in range(h)], axis=-1)
        acc_scr[...] = acc_scr[...] * corr_full + pv

    if causal:
        # fully-future k-blocks contribute nothing: skip their matmuls
        # outright (kk=0 is live for every chunk, so scratch always
        # holds valid running stats before _finish)
        pl.when(_causal_live(j, kk, q_ref.shape[1], bk))(_compute)
    else:
        _compute()

    @pl.when(kk == nk - 1)
    def _finish():
        cq = q_ref.shape[1]
        l_all = l_scr[...]
        recip_full = jnp.concatenate(
            [jnp.broadcast_to(jax.lax.reciprocal(l_all[:, hi:hi + 1]),
                              (cq, dh)) for hi in range(h)], axis=-1)
        o_ref[0] = (acc_scr[...] * recip_full).astype(o_ref.dtype)
        lse_ref[0] = m_scr[...] + jnp.log(l_all)


def _dqdkv_kb_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, do_ref,
                     lse_ref, delta_ref, dq_ref, dk_ref, dv_ref,
                     dq_scr, dk_scr, dv_scr, *, scale, p_drop, nq, nk, h,
                     dh, hb, bk, causal=False):
    """Fused k-blocked backward: dq accumulates over kk per q-chunk;
    dk/dv accumulate into FULL-length (tk, h*dh) f32 scratch across the
    whole (j, kk) walk and are emitted once at the last program."""
    i, j, kk = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(kk == 0)
    def _init_dq():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when(jnp.logical_and(j == 0, kk == 0))
    def _init_dkv():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _compute():
        q2, k2, v2, do2 = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse2, delta2 = lse_ref[0], delta_ref[0]         # (cq, h)
        cq = q2.shape[0]
        mask = _kb_causal_mask(cq, bk, j, kk) if causal else None
        pds, dss = _bwd_head_grads(
            q2, k2, v2, do2, lse2, delta2, bias_ref, scale, p_drop, h, dh,
            hb,
            lambda hi: _kb_dropout(seed_ref, i, j, cq, hi, kk, bk, p_drop),
            extra_mask=mask)
        # Batched scratch RMW: one load+store per scratch per program
        # instead of per head (per-head RMW serializes against the
        # matmuls).
        dq_scr[...] += jnp.concatenate(
            [jax.lax.dot_general(
                ds.astype(k2.dtype), _head(k2, hi, dh),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
             for hi, ds in enumerate(dss)], axis=-1)
        rows = pl.ds(kk * bk, bk)
        dv_scr[rows, :] += jnp.concatenate(
            [jax.lax.dot_general(
                pd.astype(do2.dtype), _head(do2, hi, dh),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
             for hi, pd in enumerate(pds)], axis=-1)
        dk_scr[rows, :] += jnp.concatenate(
            [jax.lax.dot_general(
                ds.astype(q2.dtype), _head(q2, hi, dh),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
             for hi, ds in enumerate(dss)], axis=-1)

    if causal:
        pl.when(_causal_live(j, kk, q_ref.shape[1], bk))(_compute)
    else:
        _compute()

    @pl.when(kk == nk - 1)
    def _emit_dq():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)

    @pl.when(jnp.logical_and(j == nq - 1, kk == nk - 1))
    def _emit_dkv():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _use_bthd_kblock(tq, tk, h, dh):
    # dk/dv live whole in f32 VMEM scratch: 2 * tk * h * dh * 4 bytes must
    # stay well inside the ~16MB scoped-vmem budget (h*dh=512, tk=1024 ->
    # 4MB, the measured-safe point; cap at 2x that product). _pick_bk
    # additionally bounds the per-head score temps.
    return (
        (jax.default_backend() == "tpu" or _INTERPRET)
        and _SMALL_T_MAX < tk <= _KB_T_MAX
        and _pick_bk(tk, h, dh) is not None
        and tq >= 8
        and (tq <= _CQ or tq % _CQ == 0)
        and tk * h * dh <= 2 * 1024 * 512
    )


def _bthd_kb_fwd(q, k, v, bias, seed, scale, p_drop, causal=False):
    b, tq, h, dh = q.shape
    tk = k.shape[1]
    bk = _pick_bk(tk, h, dh)
    cq = _pick_cq(tq, bk, h)
    nq, nk = tq // cq, tk // bk
    hdh = h * dh
    in_specs = [
        pl.BlockSpec((1, cq, hdh), lambda i, j, kk, *_: (i, j, 0)),
        pl.BlockSpec((1, bk, hdh), lambda i, j, kk, *_: (i, kk, 0)),
        pl.BlockSpec((1, bk, hdh), lambda i, j, kk, *_: (i, kk, 0)),
    ]
    args = [q.reshape(b, tq, hdh), k.reshape(b, tk, hdh),
            v.reshape(b, tk, hdh)]
    hb = 1 if bias is None else bias.shape[1]
    if bias is not None:
        in_specs.append(_bias_spec_kb(bias, cq, bk))
        args.append(bias)
        kernel = functools.partial(_fwd_kb_kernel, scale=scale,
                                   p_drop=p_drop, nk=nk, h=h, dh=dh, hb=hb,
                                   bk=bk, causal=causal)
    else:
        kernel = functools.partial(
            lambda sr, qr, kr, vr, orf, lr, ms, ls, ac, **kw:
                _fwd_kb_kernel(sr, qr, kr, vr, None, orf, lr, ms, ls, ac,
                               **kw),
            scale=scale, p_drop=p_drop, nk=nk, h=h, dh=dh, hb=hb, bk=bk,
            causal=causal,
        )
    out2, lse2 = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, nq, nk),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, cq, hdh), lambda i, j, kk, *_: (i, j, 0)),
                pl.BlockSpec((1, cq, h), lambda i, j, kk, *_: (i, j, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((cq, h), jnp.float32),
                pltpu.VMEM((cq, h), jnp.float32),
                pltpu.VMEM((cq, hdh), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, tq, hdh), q.dtype),
            jax.ShapeDtypeStruct((b, tq, h), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(_seed_arr(seed), *args)
    return out2.reshape(b, tq, h, dh), lse2[..., None]


def _bthd_kb_bwd(q, k, v, bias, seed, out, lse, g, scale, p_drop,
                 causal=False):
    b, tq, h, dh = q.shape
    tk = k.shape[1]
    bk = _pick_bk(tk, h, dh)
    cq = min(_pick_cq(tq, bk, h), _CQ)
    nq, nk = tq // cq, tk // bk
    hdh = h * dh
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)
    base_specs = [
        pl.BlockSpec((1, cq, hdh), lambda i, j, kk, *_: (i, j, 0)),
        pl.BlockSpec((1, bk, hdh), lambda i, j, kk, *_: (i, kk, 0)),
        pl.BlockSpec((1, bk, hdh), lambda i, j, kk, *_: (i, kk, 0)),
    ]
    base_args = [q.reshape(b, tq, hdh), k.reshape(b, tk, hdh),
                 v.reshape(b, tk, hdh)]
    hb = 1 if bias is None else bias.shape[1]
    if bias is not None:
        base_specs.append(_bias_spec_kb(bias, cq, bk))
        base_args.append(bias)
    tail_specs = [
        pl.BlockSpec((1, cq, hdh), lambda i, j, kk, *_: (i, j, 0)),
        pl.BlockSpec((1, cq, h), lambda i, j, kk, *_: (i, j, 0)),
        pl.BlockSpec((1, cq, h), lambda i, j, kk, *_: (i, j, 0)),
    ]
    tail_args = [g.reshape(b, tq, hdh), lse[..., 0], delta[..., 0]]
    if bias is not None:
        kernel = functools.partial(_dqdkv_kb_kernel, scale=scale,
                                   p_drop=p_drop, nq=nq, nk=nk, h=h, dh=dh,
                                   hb=hb, bk=bk, causal=causal)
    else:
        kernel = functools.partial(
            lambda sr, qr, kr, vr, dor, lr, der, dqr, dkr, dvr, dqs, dks,
            dvs, **kw: _dqdkv_kb_kernel(sr, qr, kr, vr, None, dor, lr, der,
                                        dqr, dkr, dvr, dqs, dks, dvs, **kw),
            scale=scale, p_drop=p_drop, nq=nq, nk=nk, h=h, dh=dh, hb=hb,
            bk=bk, causal=causal,
        )
    dq2, dk2, dv2 = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, nq, nk),
            in_specs=base_specs + tail_specs,
            out_specs=[
                pl.BlockSpec((1, cq, hdh), lambda i, j, kk, *_: (i, j, 0)),
                pl.BlockSpec((1, tk, hdh), lambda i, j, kk, *_: (i, 0, 0)),
                pl.BlockSpec((1, tk, hdh), lambda i, j, kk, *_: (i, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((cq, hdh), jnp.float32),
                pltpu.VMEM((tk, hdh), jnp.float32),
                pltpu.VMEM((tk, hdh), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, tq, hdh), q.dtype),
            jax.ShapeDtypeStruct((b, tk, hdh), k.dtype),
            jax.ShapeDtypeStruct((b, tk, hdh), v.dtype),
        ],
        # The fused kb backward's phase temps land at ~16.7M of Mosaic
        # scoped-vmem stack when compiled inside a run_steps While body
        # on the current toolchain (16.0M default limit; it fits
        # standalone). 24M is still a small fraction of the v5e's 128M
        # VMEM and keeps cq=128 (halving cq would double the dq-scratch
        # RMW passes on the t=1024 headline config).
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=24 * 1024 * 1024),
        interpret=_INTERPRET,
    )(_seed_arr(seed), *base_args, *tail_args)
    return (dq2.reshape(b, tq, h, dh), dk2.reshape(b, tk, h, dh),
            dv2.reshape(b, tk, h, dh))


def _combined_causal_bias(bias, tq, tk):
    """Fold the causal future-mask into an additive bias for the BTHD
    small/k-blocked kernels (t <= 1024 there, so the [tq, tk] tensor is
    bounded at ~4MB and XLA CSEs the pure computation across layers).
    The long-context BHTD kernels never take this path — they get the
    in-kernel position mask instead."""
    tri = jnp.where(
        jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :],
        jnp.float32(0), jnp.float32(_NEG_INF))[None, None]
    return tri if bias is None else bias.astype(jnp.float32) + tri


def _reference_attention_bthd(q, k, v, bias, scale, p_drop=0.0, seed=None):
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(s.dtype)
    p = jax.nn.softmax(s, axis=-1)
    if p_drop > 0.0:
        key = jax.random.PRNGKey(0 if seed is None else jnp.asarray(seed))
        keep = jax.random.bernoulli(key, 1.0 - p_drop, p.shape)
        p = jnp.where(keep, p / (1.0 - p_drop), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def flash_attention_bthd_fwd(q, k, v, bias=None, seed=None, scale=None,
                             p_drop: float = 0.0, causal: bool = False):
    """q [b, tq, h, dh], k/v [b, tk, h, dh] -> (out [b, tq, h, dh],
    lse [b, tq, h, 1] f32; zeros on the dense fallback). ``causal``:
    in-kernel future mask on the long-context BHTD path (no [tq, tk]
    tensor, dead blocks skipped); folded into a bounded bias on the
    t <= 1024 small/k-blocked paths."""
    if p_drop > 0.0 and seed is None:
        raise ValueError("flash_attention: p_drop > 0 requires `seed`")
    b, tq, h, dh = q.shape
    tk = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(dh)
    if not _use_bthd_small(tq, tk):
        if _use_bthd_kblock(tq, tk, h, dh):
            return _bthd_kb_fwd(q, k, v, bias, seed, scale, p_drop,
                                causal=causal)
        if (jax.default_backend() == "tpu" or _INTERPRET) and tk > _SMALL_T_MAX:
            # very long context: one transpose pair into the head-batched
            # K-blocked kernels (dk/dv won't fit VMEM scratch as one
            # piece); causal rides the in-kernel mask + block skip
            out, lse = flash_attention_fwd(
                jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                jnp.swapaxes(v, 1, 2), bias, seed, scale, p_drop,
                causal=causal)
            return jnp.swapaxes(out, 1, 2), jnp.swapaxes(lse, 1, 2)
        if causal:
            bias = _combined_causal_bias(bias, tq, tk)
        out = _reference_attention_bthd(q, k, v, bias, scale, p_drop,
                                        seed if p_drop > 0.0 else None)
        return out, jnp.zeros((b, tq, h, 1), jnp.float32)
    if causal:
        bias = _combined_causal_bias(bias, tq, tk)

    cq = _pick_cq(tq, tk, h)
    nq = tq // cq
    hdh = h * dh
    in_specs = [
        pl.BlockSpec((1, cq, hdh), lambda i, j, *_: (i, j, 0)),
        pl.BlockSpec((1, tk, hdh), lambda i, j, *_: (i, 0, 0)),
        pl.BlockSpec((1, tk, hdh), lambda i, j, *_: (i, 0, 0)),
    ]
    args = [q.reshape(b, tq, hdh), k.reshape(b, tk, hdh),
            v.reshape(b, tk, hdh)]
    hb = 1 if bias is None else bias.shape[1]
    if bias is not None:
        in_specs.append(_bias_spec_bthd(bias, cq, tk))
        args.append(bias)
        kernel = functools.partial(_fwd_small_kernel, scale=scale,
                                   p_drop=p_drop, h=h, dh=dh, hb=hb)
    else:
        kernel = functools.partial(
            lambda sr, qr, kr, vr, orf, lr, **kw: _fwd_small_kernel(
                sr, qr, kr, vr, None, orf, lr, **kw),
            scale=scale, p_drop=p_drop, h=h, dh=dh, hb=hb,
        )
    out2, lse2 = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, nq),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, cq, hdh), lambda i, j, *_: (i, j, 0)),
                pl.BlockSpec((1, cq, h), lambda i, j, *_: (i, j, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, tq, hdh), q.dtype),
            jax.ShapeDtypeStruct((b, tq, h), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(_seed_arr(seed), *args)
    return out2.reshape(b, tq, h, dh), lse2[..., None]


def flash_attention_bthd_bwd(q, k, v, bias, seed, out, lse, g, scale=None,
                             p_drop: float = 0.0, causal: bool = False):
    """-> (dq, dk, dv) in [b, t, h, dh], consuming the forward's saved
    (out, lse). ``causal`` routes exactly as the forward did, so the
    recomputed p matches block for block."""
    b, tq, h, dh = q.shape
    tk = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(dh)
    if not _use_bthd_small(tq, tk):
        if _use_bthd_kblock(tq, tk, h, dh):
            return _bthd_kb_bwd(q, k, v, bias, seed, out, lse, g, scale,
                                p_drop, causal=causal)
        if (jax.default_backend() == "tpu" or _INTERPRET) and tk > _SMALL_T_MAX:
            dq, dk, dv = flash_attention_bwd(
                jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                jnp.swapaxes(v, 1, 2), bias, seed,
                jnp.swapaxes(out, 1, 2), jnp.swapaxes(lse, 1, 2),
                jnp.swapaxes(g, 1, 2), scale, p_drop, causal=causal)
            return (jnp.swapaxes(dq, 1, 2), jnp.swapaxes(dk, 1, 2),
                    jnp.swapaxes(dv, 1, 2))
        if causal:
            bias = _combined_causal_bias(bias, tq, tk)

        def f(q, k, v):
            return _reference_attention_bthd(
                q, k, v, bias, scale, p_drop,
                seed if p_drop > 0.0 else None)

        _, vjp = jax.vjp(f, q, k, v)
        return vjp(g)
    if causal:
        bias = _combined_causal_bias(bias, tq, tk)

    # The fused kernel keeps four (cq, tk) f32 temps per head live; halve
    # the chunk relative to the forward so the per-head phase temps fit
    # Mosaic's scoped-vmem budget. Dropout streams are chunk-independent.
    cq = min(_pick_cq(tq, tk, h), _CQ)
    nq = tq // cq
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)         # [b, tq, h, 1]
    hdh = h * dh
    base_specs = [
        pl.BlockSpec((1, cq, hdh), lambda i, j, *_: (i, j, 0)),   # q
        pl.BlockSpec((1, tk, hdh), lambda i, j, *_: (i, 0, 0)),   # k
        pl.BlockSpec((1, tk, hdh), lambda i, j, *_: (i, 0, 0)),   # v
    ]
    base_args = [q.reshape(b, tq, hdh), k.reshape(b, tk, hdh),
                 v.reshape(b, tk, hdh)]
    if bias is not None:
        base_specs = base_specs + [_bias_spec_bthd(bias, cq, tk)]
        base_args = base_args + [bias]
    tail_specs = [
        pl.BlockSpec((1, cq, hdh), lambda i, j, *_: (i, j, 0)),   # do
        pl.BlockSpec((1, cq, h), lambda i, j, *_: (i, j, 0)),     # lse
        pl.BlockSpec((1, cq, h), lambda i, j, *_: (i, j, 0)),     # delta
    ]
    tail_args = [g.reshape(b, tq, hdh), lse[..., 0], delta[..., 0]]

    hb = 1 if bias is None else bias.shape[1]
    if bias is not None:
        kernel = functools.partial(_dqdkv_small_kernel, scale=scale,
                                   p_drop=p_drop, nq=nq, h=h, dh=dh, hb=hb)
    else:
        kernel = functools.partial(
            lambda sr, qr, kr, vr, dor, lr, der, dqr, dkr, dvr, dks, dvs,
            **kw: _dqdkv_small_kernel(sr, qr, kr, vr, None, dor, lr, der,
                                      dqr, dkr, dvr, dks, dvs, **kw),
            scale=scale, p_drop=p_drop, nq=nq, h=h, dh=dh, hb=hb,
        )

    dq2, dk2, dv2 = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, nq),
            in_specs=base_specs + tail_specs,
            out_specs=[
                pl.BlockSpec((1, cq, hdh), lambda i, j, *_: (i, j, 0)),
                pl.BlockSpec((1, tk, hdh), lambda i, j, *_: (i, 0, 0)),
                pl.BlockSpec((1, tk, hdh), lambda i, j, *_: (i, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((tk, hdh), jnp.float32),
                pltpu.VMEM((tk, hdh), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, tq, hdh), q.dtype),
            jax.ShapeDtypeStruct((b, tk, hdh), k.dtype),
            jax.ShapeDtypeStruct((b, tk, hdh), v.dtype),
        ],
        interpret=_INTERPRET,
    )(_seed_arr(seed), *base_args, *tail_args)
    return (dq2.reshape(b, tq, h, dh), dk2.reshape(b, tk, h, dh),
            dv2.reshape(b, tk, h, dh))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def flash_attention_bthd_with_lse(q, k, v, bias=None, seed=None,
                                  scale: Optional[float] = None,
                                  p_drop: float = 0.0,
                                  causal: bool = False):
    """(out, lse) in BTHD with a custom vjp over the single-block kernels
    (pallas_call has no JVP rule); the paired sdpa grad op uses the _bwd
    entry directly with the saved stats.

    ``bias`` is mask plumbing, NOT a trainable input: on the Pallas paths
    its cotangent is ZEROS (a true dbias would materialize a tq x tk
    gradient per head). Pass a learnable additive bias only through the
    dense composition (small shapes), which computes the real dbias."""
    return flash_attention_bthd_fwd(q, k, v, bias, seed, scale, p_drop,
                                    causal)


def _bthd_vjp_fwd(q, k, v, bias, seed, scale, p_drop, causal=False):
    out, lse = flash_attention_bthd_fwd(q, k, v, bias, seed, scale, p_drop,
                                        causal)
    return (out, lse), (q, k, v, bias, seed, out, lse)


def _bthd_vjp_bwd(scale, p_drop, causal, res, gs):
    g, _g_lse = gs
    q, k, v, bias, seed, out, lse = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if _use_bthd_small(q.shape[1], k.shape[1]) or k.shape[1] > _SMALL_T_MAX:
        dq, dk, dv = flash_attention_bthd_bwd(
            q, k, v, bias, seed, out, lse, g.astype(q.dtype), scale, p_drop,
            causal)
        dbias = None if bias is None else jnp.zeros_like(bias)
    else:
        sd = seed if p_drop > 0.0 else None
        tq_, tk_ = q.shape[1], k.shape[1]
        if bias is None:
            # the causal fold is a constant here — fold it outside vjp
            eff_bias = (_combined_causal_bias(None, tq_, tk_)
                        if causal else None)
            _, vjp = jax.vjp(
                lambda a, b, c: _reference_attention_bthd(
                    a, b, c, eff_bias, scale, p_drop, sd), q, k, v)
            dq, dk, dv = vjp(g.astype(q.dtype))
            dbias = None
        else:
            # bias is differentiated: the fold must happen INSIDE the
            # vjp'd function so dbias reflects only the caller's bias
            _, vjp = jax.vjp(
                lambda a, b, c, bb_: _reference_attention_bthd(
                    a, b, c,
                    _combined_causal_bias(bb_, tq_, tk_) if causal
                    else bb_,
                    scale, p_drop, sd), q, k, v, bias)
            dq, dk, dv, dbias = vjp(g.astype(q.dtype))
    return dq, dk, dv, dbias, _seed_cotangent(seed)


flash_attention_bthd_with_lse.defvjp(_bthd_vjp_fwd, _bthd_vjp_bwd)
