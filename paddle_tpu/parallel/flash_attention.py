"""Pallas TPU flash attention (FlashAttention-2 style).

The hot op of the transformer family (SURVEY.md section 7: "pallas kernels
for the hot ops"). Both directions are K-blocked with online softmax: the
score matrix never exists at full [tq, tk] size in any memory space, so
VMEM use is O(block^2) and HBM traffic is O(t) regardless of context
length — the property the long-context/ring-attention path builds on.

- Forward: grid (b*h, tq/bq, tk/bk); per q-block running (m, l, acc)
  carried in VMEM scratch across the k-block loop; emits the output and
  the logsumexp rows needed by the backward.
- Backward: recompute p = exp(s - lse) per block (no stored attention
  matrix). dq in one kernel (k-blocks inner), dk/dv in a second kernel
  (q-blocks inner), using the standard delta = rowsum(do * o) reduction.
- Attention dropout runs inside the kernels via the TPU PRNG: the mask
  for score block (bh, jq, jk) is regenerated from (seed, bh, jq, jk) in
  every kernel, so forward and backward see identical masks and nothing
  is stored.

Layout: q, k, v are [b, h, t, dh]; bias is additive [b, 1|h, 1|tq, tk].
Falls back to the dense jnp composition off-TPU or when the sequence
lengths don't divide the block sizes.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_Q_BLOCK = 256
DEFAULT_K_BLOCK = 256
_NEG_INF = -1e30

# Test hook: run the Pallas kernels in interpreter mode on CPU so the
# blocked online-softmax path itself is exercised by the pytest suite
# (the reference-composition fallback would otherwise shadow it off-TPU).
_INTERPRET = False


def _block_seed(seed, i, j, kk):
    """Mix (seed, batch-head, q-block, k-block) into one scalar for the
    per-core PRNG (the multi-operand prng_seed form doesn't lower on all
    backends). int32 wraparound is the hash."""
    s = seed
    for x in (i, j, kk):
        s = (s * jnp.int32(1000003)) ^ jnp.int32(x)
    return s


def _dropout_mask(p_keep: float, shape):
    """Per-block keep mask from the already-seeded TPU PRNG, scaled by
    1/p_keep (inverted dropout)."""
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    thresh = jnp.uint32(int(p_keep * float(2**32 - 1)))
    return (bits < thresh).astype(jnp.float32) * (1.0 / p_keep)


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, nk, p_drop):
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    if bias_ref is not None:
        s = s + bias_ref[0].astype(jnp.float32)

    m_prev = m_scr[:, :1]
    l_prev = l_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m_prev - m_new)
    l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)

    if p_drop > 0.0:
        pltpu.prng_seed(
            _block_seed(seed_ref[0], pl.program_id(0), pl.program_id(1), kk))
        p = p * _dropout_mask(1.0 - p_drop, p.shape)

    acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(kk == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:, :1] + jnp.log(l_scr[:, :1])


def _dq_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
               delta_ref, dq_ref, dq_scr, *, scale, nk, p_drop):
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]        # [bq, 1] f32
    delta = delta_ref[0]    # [bq, 1] f32

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    if bias_ref is not None:
        s = s + bias_ref[0].astype(jnp.float32)
    p = jnp.exp(s - lse)  # post-softmax probabilities, recomputed

    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if p_drop > 0.0:
        pltpu.prng_seed(
            _block_seed(seed_ref[0], pl.program_id(0), pl.program_id(1), kk))
        dp = dp * _dropout_mask(1.0 - p_drop, dp.shape)
    ds = p * (dp - delta) * scale
    dq_scr[:] += jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(kk == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                scale, nq, p_drop):
    jq = pl.program_id(2)

    @pl.when(jq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]      # [bq, 1]
    delta = delta_ref[0]  # [bq, 1]

    # Work in the transposed orientation: s_t[kk, qq]
    s_t = jax.lax.dot_general(
        k, q, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    if bias_ref is not None:
        s_t = s_t + jnp.transpose(bias_ref[0].astype(jnp.float32))
    p_t = jnp.exp(s_t - jnp.transpose(lse))  # [bk, bq]

    if p_drop > 0.0:
        # Same (bh, q-block, k-block) stream as the forward, generated in
        # the forward's (bq, bk) orientation then transposed.
        pltpu.prng_seed(
            _block_seed(seed_ref[0], pl.program_id(0), jq, pl.program_id(1)))
        drop_t = jnp.transpose(
            _dropout_mask(1.0 - p_drop, (p_t.shape[1], p_t.shape[0]))
        )
        pd_t = p_t * drop_t
    else:
        pd_t = p_t

    dv_scr[:] += jax.lax.dot_general(
        pd_t.astype(do.dtype), do, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp_t = jax.lax.dot_general(
        v, do, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if p_drop > 0.0:
        dp_t = dp_t * drop_t
    ds_t = p_t * (dp_t - jnp.transpose(delta)) * scale
    dk_scr[:] += jax.lax.dot_general(
        ds_t.astype(q.dtype), q, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(jq == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bias_spec(bias, b, h, bq, bk, *, transposed=False):
    """BlockSpec for the stored-rank bias [b, 1|h, 1|tq, tk], reshaped to
    (b or b*h, 1|tq, tk). Index maps take grid (i=bh, j=qblk, kk=kblk);
    when ``transposed`` the grid is (i, kk, j)."""
    hb, tq_b = bias.shape[1], bias.shape[2]
    tk = bias.shape[3]
    if hb == 1:
        arr = bias.reshape(bias.shape[0], tq_b, tk)
        bsel = lambda i: i // h
    else:
        arr = bias.reshape(bias.shape[0] * hb, tq_b, tk)
        bsel = lambda i: i
    qdim = 1 if tq_b == 1 else bq
    if transposed:
        if tq_b == 1:
            idx = lambda i, kk, j, *_: (bsel(i), 0, kk)
        else:
            idx = lambda i, kk, j, *_: (bsel(i), j, kk)
    else:
        if tq_b == 1:
            idx = lambda i, j, kk, *_: (bsel(i), 0, kk)
        else:
            idx = lambda i, j, kk, *_: (bsel(i), j, kk)
    return arr, pl.BlockSpec((1, qdim, bk), idx)


def _reference_attention(q, k, v, bias, scale, p_drop=0.0, seed=None):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(s.dtype)
    p = jax.nn.softmax(s, axis=-1)
    if p_drop > 0.0:
        key = jax.random.PRNGKey(0 if seed is None else jnp.asarray(seed))
        keep = jax.random.bernoulli(key, 1.0 - p_drop, p.shape)
        p = jnp.where(keep, p / (1.0 - p_drop), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _seed_cotangent(seed):
    """Symbolic-zero cotangent for the integer seed operand."""
    if seed is None:
        return None
    import numpy as _np

    return _np.zeros(_np.shape(seed), jax.dtypes.float0)


def _use_pallas(tq, tk, bq, bk):
    return (
        (jax.default_backend() == "tpu" or _INTERPRET)
        and tq % bq == 0
        and tk % bk == 0
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def flash_attention(q, k, v, bias=None, seed=None,
                    scale: Optional[float] = None, p_drop: float = 0.0,
                    q_block: int = DEFAULT_Q_BLOCK,
                    k_block: int = DEFAULT_K_BLOCK):
    """o = dropout(softmax(q k^T * scale + bias)) v.

    ``seed``: int32 scalar array driving attention dropout (ignored when
    p_drop == 0).

    ``bias`` is treated as mask plumbing, NOT a trainable input: on the
    Pallas path its cotangent is zeros (computing it would materialize a
    t x t gradient, defeating the kernel). Use the dense composition if a
    learnable additive bias must receive gradients.
    """
    out, _ = _flash_fwd(q, k, v, bias, seed, scale, p_drop, q_block, k_block)
    return out


def _flash_fwd(q, k, v, bias, seed, scale, p_drop, q_block, k_block):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    b, h, tq, dh = q.shape
    tk = k.shape[2]
    bq = min(q_block, tq)
    bk = min(k_block, tk)
    if not _use_pallas(tq, tk, bq, bk):
        out = _reference_attention(q, k, v, bias, scale, p_drop,
                                   seed if p_drop > 0.0 else None)
        return out, (q, k, v, bias, seed, None, None)

    bh = b * h
    nq, nk = tq // bq, tk // bk
    q_r = q.reshape(bh, tq, dh)
    k_r = k.reshape(bh, tk, dh)
    v_r = v.reshape(bh, tk, dh)

    in_specs = [
        pl.BlockSpec((1, bq, dh), lambda i, j, kk, *_: (i, j, 0)),
        pl.BlockSpec((1, bk, dh), lambda i, j, kk, *_: (i, kk, 0)),
        pl.BlockSpec((1, bk, dh), lambda i, j, kk, *_: (i, kk, 0)),
    ]
    args = [q_r, k_r, v_r]
    if bias is not None:
        bias_arr, spec = _bias_spec(bias, b, h, bq, bk)
        in_specs.append(spec)
        args.append(bias_arr)
        kernel = functools.partial(_fwd_kernel, scale=scale, nk=nk,
                                   p_drop=p_drop)
    else:
        kernel = functools.partial(
            lambda sr, qr, kr, vr, orf, lr, ms, ls, accs, **kw: _fwd_kernel(
                sr, qr, kr, vr, None, orf, lr, ms, ls, accs, **kw),
            scale=scale, nk=nk, p_drop=p_drop,
        )

    seed_arr = jnp.zeros((1,), jnp.int32) if seed is None else (
        jnp.asarray(seed, jnp.int32).reshape((1,))
    )

    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, nq, nk),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, bq, dh), lambda i, j, kk, *_: (i, j, 0)),
                pl.BlockSpec((1, bq, 1), lambda i, j, kk, *_: (i, j, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, dh), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, dh), q.dtype),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(seed_arr, *args)
    return out.reshape(b, h, tq, dh), (q, k, v, bias, seed, out, lse)


def _flash_bwd(scale, p_drop, q_block, k_block, res, g):
    q, k, v, bias, seed, out, lse = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    b, h, tq, dh = q.shape
    tk = k.shape[2]
    bq = min(q_block, tq)
    bk = min(k_block, tk)

    if out is None:  # forward took the dense path; mirror it
        def f(q, k, v, bias):
            return _reference_attention(q, k, v, bias, scale, p_drop,
                                        seed if p_drop > 0.0 else None)

        if bias is None:
            _, vjp = jax.vjp(lambda a, bb, c: f(a, bb, c, None), q, k, v)
            dq, dk, dv = vjp(g)
            return dq, dk, dv, None, _seed_cotangent(seed)
        _, vjp = jax.vjp(f, q, k, v, bias)
        dq, dk, dv, dbias = vjp(g)
        return dq, dk, dv, dbias, _seed_cotangent(seed)

    bh = b * h
    nq, nk = tq // bq, tk // bk
    q_r = q.reshape(bh, tq, dh)
    k_r = k.reshape(bh, tk, dh)
    v_r = v.reshape(bh, tk, dh)
    do_r = g.reshape(bh, tq, dh)
    out_r = out  # already [bh, tq, dh]
    delta = jnp.sum(do_r.astype(jnp.float32) * out_r.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [bh, tq, 1]

    seed_arr = jnp.zeros((1,), jnp.int32) if seed is None else (
        jnp.asarray(seed, jnp.int32).reshape((1,))
    )

    # --- dq: grid (bh, nq, nk), k-blocks inner ---
    dq_specs = [
        pl.BlockSpec((1, bq, dh), lambda i, j, kk, *_: (i, j, 0)),   # q
        pl.BlockSpec((1, bk, dh), lambda i, j, kk, *_: (i, kk, 0)),  # k
        pl.BlockSpec((1, bk, dh), lambda i, j, kk, *_: (i, kk, 0)),  # v
    ]
    dq_args = [q_r, k_r, v_r]
    if bias is not None:
        bias_arr, spec = _bias_spec(bias, b, h, bq, bk)
        dq_specs.append(spec)
        dq_args.append(bias_arr)
        dq_kernel = functools.partial(_dq_kernel, scale=scale, nk=nk,
                                      p_drop=p_drop)
    else:
        dq_kernel = functools.partial(
            lambda sr, qr, kr, vr, dor, lr, der, dqr, dqs, **kw: _dq_kernel(
                sr, qr, kr, vr, None, dor, lr, der, dqr, dqs, **kw),
            scale=scale, nk=nk, p_drop=p_drop,
        )
    dq_specs += [
        pl.BlockSpec((1, bq, dh), lambda i, j, kk, *_: (i, j, 0)),  # do
        pl.BlockSpec((1, bq, 1), lambda i, j, kk, *_: (i, j, 0)),   # lse
        pl.BlockSpec((1, bq, 1), lambda i, j, kk, *_: (i, j, 0)),   # delta
    ]
    dq_args += [do_r, lse, delta]

    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, nq, nk),
            in_specs=dq_specs,
            out_specs=pl.BlockSpec((1, bq, dh), lambda i, j, kk, *_: (i, j, 0)),
            scratch_shapes=[pltpu.VMEM((bq, dh), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, tq, dh), q.dtype),
        interpret=_INTERPRET,
    )(seed_arr, *dq_args)

    # --- dk/dv: grid (bh, nk, nq), q-blocks inner ---
    dkv_specs = [
        pl.BlockSpec((1, bq, dh), lambda i, kk, j, *_: (i, j, 0)),   # q
        pl.BlockSpec((1, bk, dh), lambda i, kk, j, *_: (i, kk, 0)),  # k
        pl.BlockSpec((1, bk, dh), lambda i, kk, j, *_: (i, kk, 0)),  # v
    ]
    dkv_args = [q_r, k_r, v_r]
    if bias is not None:
        bias_arr, spec = _bias_spec(bias, b, h, bq, bk, transposed=True)
        dkv_specs.append(spec)
        dkv_args.append(bias_arr)
        dkv_kernel = functools.partial(_dkv_kernel, scale=scale, nq=nq,
                                       p_drop=p_drop)
    else:
        dkv_kernel = functools.partial(
            lambda sr, qr, kr, vr, dor, lr, der, dkr, dvr, dks, dvs, **kw:
                _dkv_kernel(sr, qr, kr, vr, None, dor, lr, der, dkr, dvr,
                            dks, dvs, **kw),
            scale=scale, nq=nq, p_drop=p_drop,
        )
    dkv_specs += [
        pl.BlockSpec((1, bq, dh), lambda i, kk, j, *_: (i, j, 0)),  # do
        pl.BlockSpec((1, bq, 1), lambda i, kk, j, *_: (i, j, 0)),   # lse
        pl.BlockSpec((1, bq, 1), lambda i, kk, j, *_: (i, j, 0)),   # delta
    ]
    dkv_args += [do_r, lse, delta]

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, nk, nq),
            in_specs=dkv_specs,
            out_specs=[
                pl.BlockSpec((1, bk, dh), lambda i, kk, j, *_: (i, kk, 0)),
                pl.BlockSpec((1, bk, dh), lambda i, kk, j, *_: (i, kk, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk, dh), jnp.float32),
                pltpu.VMEM((bk, dh), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, dh), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, dh), v.dtype),
        ],
        interpret=_INTERPRET,
    )(seed_arr, *dkv_args)

    dq = dq.reshape(b, h, tq, dh)
    dk = dk.reshape(b, h, tk, dh)
    dv = dv.reshape(b, h, tk, dh)
    # Bias is mask plumbing (stop_gradient in every model); zeros keeps the
    # vjp structure without materializing a t x t gradient.
    dbias = None if bias is None else jnp.zeros_like(bias)
    return dq, dk, dv, dbias, _seed_cotangent(seed)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
