"""Device mesh management.

Replaces the reference's device-topology plumbing (places lists, NCCL
context maps, `nccl_comm_num` rings, hierarchical inter/exter comms —
reference: platform/nccl_helper.h:90-210, parallel_executor.cc:343-366)
with one object: a named `jax.sharding.Mesh`. Multi-host comes from
jax.distributed + the same mesh spanning all processes; ICI vs DCN layout
is expressed by axis order (outer axes ride DCN across slices).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

_current_mesh: Optional[Mesh] = None


def create_mesh(
    axes: Dict[str, int],
    devices: Optional[Sequence] = None,
    set_as_default: bool = True,
) -> Mesh:
    """Create a named mesh, e.g. create_mesh({"data": 4, "model": 2}).

    Axis sizes must multiply to the device count; -1 on one axis infers it.
    """
    devs = list(devices) if devices is not None else jax.devices()
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devs) // known
    total = int(np.prod(sizes))
    if total != len(devs):
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} needs {total} devices, "
            f"have {len(devs)}"
        )
    arr = np.asarray(devs).reshape(sizes)
    mesh = Mesh(arr, tuple(names))
    if set_as_default:
        set_mesh(mesh)
    return mesh


def axis_tuple(axis) -> tuple:
    """Normalize an axis spec (None | str | tuple of str) to a tuple.
    Composed batch axes — the multi-slice (slice, data) pair — travel
    through SpmdCtx as tuples; single axes stay strings."""
    if axis is None:
        return ()
    if isinstance(axis, str):
        return (axis,)
    return tuple(axis)


def axis_size(mesh: Mesh, axis) -> int:
    """Total ranks across one axis or a composed tuple of axes."""
    n = 1
    for a in axis_tuple(axis):
        n *= int(mesh.shape[a])
    return n


def create_slice_mesh(
    n_slices: int,
    within_axes: Dict[str, int],
    slice_axis: str = "slice",
    devices: Optional[Sequence] = None,
    set_as_default: bool = True,
) -> Mesh:
    """Mesh with an OUTER cross-slice axis riding DCN and inner axes
    riding ICI — the topology behind the reference's 2-level
    hierarchical allreduce (reference: platform/nccl_helper.h:179-210).

    On real multi-slice hardware the devices are ordered so each slice's
    chips are contiguous (``jax.devices()`` groups by slice; for
    irregular topologies use jax.experimental.mesh_utils'
    ``create_hybrid_device_mesh`` and wrap the result in ``Mesh``
    yourself). GSPMD then lowers a gradient all-reduce over
    ``(slice, data)`` into within-slice reduce-scatter (ICI) +
    cross-slice all-reduce (DCN) + within-slice all-gather
    automatically — no hand-placed collectives.
    """
    devs = list(devices) if devices is not None else jax.devices()
    per_slice = int(np.prod(list(within_axes.values())))
    if n_slices * per_slice != len(devs):
        raise ValueError(
            f"slice mesh ({n_slices} x {within_axes}) needs "
            f"{n_slices * per_slice} devices, have {len(devs)}"
        )
    axes = {slice_axis: n_slices, **within_axes}
    return create_mesh(axes, devices=devs, set_as_default=set_as_default)


def axis_sizes(mesh: Mesh) -> Dict[str, int]:
    """{axis name -> size} — the static verifier embeds this in every
    collective-signature entry (analysis.collective_signature) so two
    ranks that built DIFFERENT meshes diff as a participant-set
    divergence; per-axis participant counts / reshard-cost denominators
    use ``axis_size`` (singular, composed-axis aware) above."""
    return {a: int(mesh.shape[a]) for a in mesh.axis_names}


def sharding_descriptor(sharding) -> Optional[dict]:
    """JSON-able description of a NamedSharding — the manifest-v2 field
    that makes checkpoints mesh-portable: ``{"mesh": {axis -> size},
    "spec": [per-dim axis list | None, ...]}``. Device identity is
    deliberately NOT recorded (it is exactly what a restore onto a
    different topology must ignore); axis names + sizes + the partition
    spec are the whole layout. Non-Named shardings (positional/GSPMD) and
    host values return None — their checkpoints still restore, they just
    cannot advertise a layout to rebuild."""
    try:
        from jax.sharding import NamedSharding, PartitionSpec
    except ImportError:  # pragma: no cover
        return None
    if not isinstance(sharding, NamedSharding):
        return None
    spec = []
    for e in tuple(PartitionSpec(*sharding.spec)):
        if e is None:
            spec.append(None)
        elif isinstance(e, (tuple, list)):
            spec.append([str(a) for a in e])
        else:
            spec.append([str(e)])
    return {"mesh": axis_sizes(sharding.mesh), "spec": spec}


def sharding_from_descriptor(desc: dict, devices=None):
    """Rebuild a NamedSharding from a manifest-v2 descriptor over THIS
    process's devices (or ``devices``). The reconstructed mesh shares
    only axis names/sizes with the saving one — which is all a layout
    is; use it to restore a checkpoint in its original sharding when the
    restoring program has no strategy of its own."""
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = create_mesh(dict(desc["mesh"]), devices=devices,
                       set_as_default=False)
    entries = []
    for e in desc["spec"]:
        if e is None:
            entries.append(None)
        elif len(e) == 1:
            entries.append(e[0])
        else:
            entries.append(tuple(e))
    return NamedSharding(mesh, PartitionSpec(*entries))


def set_mesh(mesh: Optional[Mesh]):
    global _current_mesh
    _current_mesh = mesh


def get_mesh() -> Optional[Mesh]:
    return _current_mesh


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None):
    """Multi-host bootstrap (replaces gen_nccl_id RPC bootstrap, reference:
    operators/distributed_ops/gen_nccl_id_op.cc:62): the PJRT distributed
    runtime's KV store handles device discovery and barriers."""
    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    jax.distributed.initialize(**kwargs)
