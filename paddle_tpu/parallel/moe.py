"""Mixture-of-Experts with expert parallelism.

Net-new capability vs the reference (SURVEY.md section 2.3 row
"Pipeline/tensor/.../EP, MoE — absent in reference"). TPU-native design:

- experts shard one-per-rank over an ``expert`` mesh axis (stacked expert
  weights with leading axis E, sharded ``P('expert')``);
- top-k gating runs replicated; tokens route to their expert with
  ``lax.all_to_all`` over ICI (the TPU analog of the pserver
  prefetch-by-id the reference used for its only form of sparse model
  parallelism) — each rank sends every other rank the tokens destined for
  its expert and gets its own expert's tokens back;
- capacity-factor truncation keeps shapes static (XLA discipline):
  each expert processes at most ``capacity`` tokens per source rank;
  overflow tokens bypass the experts (identity path), the standard
  GShard/Switch treatment.

Everything (gate, dispatch, expert FFN, combine) lives inside one
``shard_map``, so XLA overlaps the all_to_all with expert compute.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _gate_and_dispatch(x, gate_w, e: int, capacity: int):
    """Shared Switch-style top-1 gating + fixed-capacity dispatch math.

    One source of truth for both the expert-parallel per-rank body and the
    dense single-device path, so the two are bit-comparable in parity
    tests. Returns (dispatch [e, cap, d], dst, slot, keep, gate_val,
    onehot, probs)."""
    logits = x @ gate_w                          # [n, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)      # [n]
    gate_val = jnp.max(probs, axis=-1)           # [n]

    # position of each token within its expert's capacity window
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)   # [n, E]
    pos_in_expert = jnp.cumsum(onehot, axis=0) * onehot       # 1-based
    pos = jnp.sum(pos_in_expert, axis=-1) - 1                 # [n]
    keep = (pos >= 0) & (pos < capacity)

    # dispatch[e_dst, cap, d]: tokens sent to each expert
    dispatch = jnp.zeros((e, capacity, x.shape[-1]), x.dtype)
    dst = jnp.where(keep, expert_idx, e - 1)
    slot = jnp.clip(pos, 0, capacity - 1)
    contrib = jnp.where(keep[:, None], x, 0.0)
    dispatch = dispatch.at[dst, slot].add(contrib)
    return dispatch, dst, slot, keep, gate_val, onehot, probs


def _moe_local(gate_w, expert_params, x, *, fn: Callable, axis: str,
               capacity: int, data_axis: Optional[str] = None):
    """Per-rank body. x: [n_loc, d] this rank's tokens (batch-sharded);
    gate_w: [d, E] replicated; expert_params: this rank's expert (leading
    axis sliced to 1 by shard_map)."""
    e = lax.psum(1, axis)
    n_loc, d = x.shape

    dispatch, dst, slot, keep, gate_val, onehot, probs = _gate_and_dispatch(
        x, gate_w, e, capacity
    )

    # --- all_to_all: axis of experts <-> axis of source ranks ---
    # after the exchange, this rank holds [src_rank, cap, d] tokens for
    # ITS expert
    received = lax.all_to_all(
        dispatch, axis, split_axis=0, concat_axis=0, tiled=True
    )                                             # [e*cap... actually [E, cap, d] with E=src ranks

    # --- expert computation on [e*capacity, d] ---
    flat = received.reshape(e * capacity, d)
    out = fn(expert_params, flat).reshape(e, capacity, d)

    # --- return trip + combine ---
    returned = lax.all_to_all(
        out, axis, split_axis=0, concat_axis=0, tiled=True
    )                                             # [E, cap, d] per dst expert
    gathered = returned[dst, slot]                # [n_loc, d]
    combined = jnp.where(
        keep[:, None], gathered * gate_val[:, None], x
    )  # overflow tokens take the identity path

    # auxiliary load-balancing loss (Switch: E * sum(frac_tokens * frac_prob)).
    # The fractions are means over ALL tokens: pmean over the data axis too
    # when tokens are batch-sharded, else the aux (and its router gradient)
    # would be one data shard's local statistics.
    from paddle_tpu.parallel.mesh import axis_tuple

    axes = (axis,) + axis_tuple(data_axis)
    frac_tokens = jnp.mean(onehot.astype(x.dtype), axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(lax.pmean(frac_tokens, axes) *
                      lax.pmean(frac_probs, axes))
    return combined, aux


def moe_ffn(
    x,
    gate_w,
    expert_params,
    fn: Callable,
    mesh: Mesh,
    expert_axis: str = "expert",
    data_axis: Optional[str] = None,
    capacity_factor: float = 2.0,
    capacity: Optional[int] = None,
):
    """Expert-parallel MoE layer.

    - ``x`` [n, d] tokens (sharded over ``data_axis`` when given);
    - ``gate_w`` [d, E] router weights (replicated);
    - ``expert_params`` pytree with leading expert axis E == mesh size of
      ``expert_axis`` (each rank keeps one expert);
    - ``fn(params_i, tokens) -> tokens`` the per-expert computation.
    Returns (combined [n, d], aux_loss scalar).
    """
    from paddle_tpu.parallel.mesh import axis_size, axis_tuple

    e = mesh.shape[expert_axis]
    n = x.shape[0]
    d_axes = axis_tuple(data_axis)
    if d_axes and not all(a in mesh.axis_names for a in d_axes):
        data_axis, d_axes = None, ()
    n_ranks = axis_size(mesh, d_axes) if d_axes else 1
    n_loc = n // max(n_ranks, 1)
    if capacity is None:
        capacity = max(1, int(capacity_factor * n_loc / e))

    param_specs = jax.tree.map(
        lambda p: P(expert_axis, *([None] * (p.ndim - 1))), expert_params
    )

    def local(gw, params, xs):
        params = jax.tree.map(lambda p: p[0], params)
        return _moe_local(
            gw, params, xs, fn=fn, axis=expert_axis, capacity=capacity,
            data_axis=data_axis,
        )

    out, aux = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), param_specs, P(data_axis)),
        out_specs=(P(data_axis), P()),
        # combined/aux are value-replicated over the expert axis by
        # construction (x and gate_w are replicated there, and every rank
        # receives every expert's outputs back), but the varying-axis type
        # system cannot see through all_to_all — skip the static check.
        check_vma=False,
    )(gate_w, expert_params, x)
    return out, aux


def moe_dense(x, gate_w, expert_params, fn: Callable, capacity: int):
    """Single-device Switch MoE with the SAME fixed-capacity dispatch math
    as the expert-parallel path (shared ``_gate_and_dispatch``), so a
    1-device run is numerically comparable to an n-device expert-parallel
    run of the same program. Returns (combined [n, d], aux_loss)."""
    e = jax.tree.leaves(expert_params)[0].shape[0]
    dispatch, dst, slot, keep, gate_val, onehot, probs = _gate_and_dispatch(
        x, gate_w, e, capacity
    )
    stacked = jax.vmap(fn)(expert_params, dispatch)   # [e, cap, d]
    gathered = stacked[dst, slot]                # [n, d]
    combined = jnp.where(keep[:, None], gathered * gate_val[:, None], x)
    frac_tokens = jnp.mean(onehot.astype(x.dtype), axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac_tokens * frac_probs)
    return combined, aux


def moe_reference(x, gate_w, expert_params, fn):
    """Dense reference (every token through its argmax expert, no
    capacity truncation) for parity tests."""
    e = jax.tree.leaves(expert_params)[0].shape[0]
    probs = jax.nn.softmax(x @ gate_w, axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    gate = jnp.max(probs, axis=-1)
    outs = []
    for i in range(e):
        params_i = jax.tree.map(lambda p: p[i], expert_params)
        outs.append(fn(params_i, x))
    stacked = jnp.stack(outs, axis=0)            # [E, n, d]
    sel = stacked[idx, jnp.arange(x.shape[0])]
    return sel * gate[:, None]
