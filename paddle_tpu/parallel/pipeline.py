"""Pipeline parallelism: GPipe-style microbatched stage execution.

Net-new capability vs the reference (SURVEY.md section 2.3 row
"Pipeline/tensor/sequence/context parallelism ... absent in reference").
TPU-native design: stages live on a ``pipe`` mesh axis; every rank holds
ONE stage's parameters (a pytree stacked on a leading stage axis, sharded
``P('pipe')``), and activations hop rank -> rank+1 over ICI with
``lax.ppermute`` while microbatches stream through — the classic GPipe
schedule of ``n_micro + n_stages - 1`` ticks with bubble fraction
``(S-1)/(M+S-1)``. The whole schedule is a ``lax.scan`` inside one
``shard_map``, so XLA overlaps the per-tick compute with the neighbor
exchange and the loop compiles once regardless of microbatch count.

The stage body must be shape-preserving (``fn(params_i, x) -> y`` with
``y.shape == x.shape``) — the transformer's homogeneous layer stack, which
is what pipeline parallelism is for. Gradients flow through ppermute/scan
transposes, so ``jax.grad`` (and the Program-IR autodiff that rides on
it) works through the pipeline unchanged.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu import monitor as _monitor

# gpipe() runs at TRACE time (once per compile) — ticks per trace is the
# schedule length n_micro + n_stages - 1; the bubble fraction falls out
# of ticks vs microbatches.
_M_PIPE_TRACES = _monitor.counter(
    "pt_pipeline_traces_total", "GPipe schedule traces (per compile)")
_M_PIPE_TICKS = _monitor.counter(
    "pt_pipeline_ticks_total", "pipeline schedule ticks traced")
_M_PIPE_MICRO = _monitor.counter(
    "pt_pipeline_microbatches_total", "microbatches traced through gpipe")


def _gpipe_local(params, x_micro, streams, *, fn: Callable, axis: str,
                 n_micro: int, with_micro_idx: bool = False):
    """Per-rank body. params: this rank's stage params (leading stage axis
    already sliced away by shard_map); x_micro: [n_micro, mb, ...]
    microbatched input (replicated; only rank 0 reads it); streams:
    tuple of [n_micro, mb, ...] per-microbatch side inputs every stage
    reads for ITS current microbatch (attention biases etc.)."""
    n_stages = lax.psum(1, axis)
    rank = lax.axis_index(axis)
    total = n_micro + n_stages - 1
    mb_shape = x_micro.shape[1:]

    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        incoming, out_buf = carry
        mb_idx = t - rank                       # microbatch this rank runs
        active = (mb_idx >= 0) & (mb_idx < n_micro)
        # rank 0 feeds from the input stream; others from the wire
        feed = lax.dynamic_index_in_dim(
            x_micro, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False
        )
        x_in = jnp.where(rank == 0, feed, incoming)
        mb_clip = jnp.clip(mb_idx, 0, n_micro - 1)
        stream_t = tuple(
            lax.dynamic_index_in_dim(sm, mb_clip, axis=0, keepdims=False)
            for sm in streams
        )
        if with_micro_idx:
            y = fn(params, x_in, *stream_t, micro_idx=mb_clip)
        else:
            y = fn(params, x_in, *stream_t)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # last stage banks its result at the microbatch's slot
        write_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        is_last = rank == n_stages - 1
        bank = jnp.where(
            active & is_last, y, jnp.zeros_like(y)
        )
        out_buf = lax.dynamic_update_index_in_dim(
            out_buf,
            lax.dynamic_index_in_dim(out_buf, write_idx, 0, keepdims=False)
            + bank,
            write_idx,
            axis=0,
        )
        # activations hop to the next stage (ring; the wraparound value
        # into rank 0 is ignored — rank 0 always reads the feed)
        incoming = lax.ppermute(y, axis, fwd)
        return (incoming, out_buf), None

    zero = jnp.zeros(mb_shape, x_micro.dtype)
    out0 = jnp.zeros_like(x_micro)
    # carries become rank-varying inside the body; align the initial type
    # to every manual axis in play (pipe from the params, plus the data
    # axis when dp x pp compose in one shard_map)
    vary = (set(jax.typeof(jax.tree.leaves(params)[0]).vma)
            | set(jax.typeof(x_micro).vma) | {axis})

    def _pcast_to(v):
        missing = tuple(vary - set(jax.typeof(v).vma))
        return lax.pcast(v, missing, to="varying") if missing else v

    zero, out0 = _pcast_to(zero), _pcast_to(out0)
    (_, out), _ = lax.scan(tick, (zero, out0), jnp.arange(total))
    # only the last rank holds nonzero outputs; psum replicates them
    return lax.psum(out, axis)


def gpipe(
    fn: Callable,
    stage_params,
    x,
    mesh: Mesh,
    pipe_axis: str = "pipe",
    n_micro: Optional[int] = None,
    batch_streams=(),
    with_micro_idx: bool = False,
    data_axis: Optional[str] = None,
):
    """Run ``x`` through ``n_stages`` stages pipelined over ``pipe_axis``.

    - ``fn(params_i, x_mb, *stream_mbs) -> y_mb`` — one stage's
      computation, shape preserving in ``x_mb``.
    - ``stage_params`` — pytree whose leaves have a leading ``n_stages``
      axis (sharded onto the pipe axis; each rank holds one slice).
    - ``x`` — [B, ...] global batch; split into ``n_micro`` microbatches
      (default: one per stage).
    - ``batch_streams`` — [B, ...] side inputs every stage reads for its
      current microbatch (attention masks/biases); microbatched in step
      with ``x``.
    - ``with_micro_idx`` — pass the stage's current microbatch index as a
      ``micro_idx`` kwarg (stochastic stages fold it into their PRNG key
      so microbatches draw independent randomness).
    - ``data_axis`` — compose dp x pp in ONE program: each data-rank
      group pipelines ITS batch shard (the microbatch dim is sharded over
      ``data_axis``; ppermute/psum stay scoped to the pipe axis, so the
      schedules run independently per data shard and the gradient
      all-reduce over data happens outside in GSPMD land).
    Returns [B, ...] outputs (replicated over the pipe axis).
    """
    n_stages = mesh.shape[pipe_axis]
    b = x.shape[0]
    n_micro = n_micro or n_stages
    if b % n_micro != 0:
        raise ValueError(f"batch {b} not divisible by n_micro {n_micro}")
    if _monitor.enabled():
        _M_PIPE_TRACES.inc()
        _M_PIPE_TICKS.inc(n_micro + n_stages - 1)
        _M_PIPE_MICRO.inc(n_micro)
    if data_axis:
        from paddle_tpu.parallel.mesh import axis_size

        d = axis_size(mesh, data_axis)
        if (b // n_micro) % d != 0:
            raise ValueError(
                f"dp x pp: microbatch size {b // n_micro} "
                f"(batch {b} / n_micro {n_micro}) not divisible by the "
                f"data axis '{data_axis}' ({d} ranks)")
    x_m = x.reshape((n_micro, b // n_micro) + x.shape[1:])
    streams_m = tuple(
        sv.reshape((n_micro, b // n_micro) + sv.shape[1:])
        for sv in batch_streams
    )

    param_specs = jax.tree.map(
        lambda p: P(pipe_axis, *([None] * (p.ndim - 1))), stage_params
    )

    def local(params, x_micro, streams):
        # shard_map slices the stage axis to length 1; drop it
        params = jax.tree.map(lambda p: p[0], params)
        return _gpipe_local(
            params, x_micro, streams, fn=fn, axis=pipe_axis,
            n_micro=n_micro, with_micro_idx=with_micro_idx
        )

    mb_spec = P(None, data_axis) if data_axis else P()
    # tp x pp composition: mesh axes not named here (e.g. a 'model'
    # tensor-parallel axis) stay AUTO — GSPMD partitions the stage body
    # over them from the stacked weights' own shardings (strategy rules
    # like pipeline_tp_rules put P(pipe, None, model) on a stacked
    # column-parallel weight: dim 0 is the manual stage axis this
    # shard_map slices, the model dim rides through as an auto-axis
    # sharding and GSPMD inserts the row-parallel all-reduces inside the
    # per-tick stage computation).
    manual = {pipe_axis}
    if data_axis:
        manual |= (set(data_axis) if isinstance(data_axis, (tuple, list))
                   else {data_axis})
    # multi-host dispatch can block inside the call (compile-time
    # rendezvous, a stage rank that never arrives): watchdog-guarded so
    # a hung pipeline schedule produces a stall record, not a silent job
    with _monitor.stall_guard("pipeline.dispatch"):
        out = jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(param_specs, mb_spec, mb_spec),
            out_specs=mb_spec,
            axis_names=frozenset(manual),
        )(stage_params, x_m, streams_m)
    return out.reshape((b,) + x.shape[1:])


def collective_signature(mesh: Mesh, pipe_axis: str = "pipe",
                         n_micro: Optional[int] = None) -> dict:
    """Static description of the GPipe schedule's collective footprint
    over ``mesh``: every rank on ``pipe_axis`` runs the same
    ``n_micro + n_stages - 1`` ticks, each ending in one ppermute hop
    (plus the final psum). Consumed by the static verifier's
    collective-order check (analysis.collective_signature) — extraction
    only, no tracing."""
    n_stages = int(mesh.shape[pipe_axis])
    m = int(n_micro) if n_micro else n_stages
    return {
        "participants": n_stages,
        "schedule": "gpipe",
        "ticks": m + n_stages - 1,
    }


def sequential_reference(fn, stage_params, x):
    """Same computation without the pipeline (for parity tests)."""
    n_stages = jax.tree.leaves(stage_params)[0].shape[0]
    y = x
    for i in range(n_stages):
        params_i = jax.tree.map(lambda p: p[i], stage_params)
        y = fn(params_i, y)
    return y
