"""Ring attention: context parallelism over the sequence axis.

Net-new capability vs the reference (SURVEY.md section 5 "long-context":
the 2019 codebase has LoD sequence ops but no way to exceed one device's
memory for a single sequence). Design: shard the sequence axis of Q/K/V
over a mesh axis; each device holds one block and passes its K/V block
around the ring with `lax.ppermute` (ICI neighbor exchange), accumulating
the attention output with the online-softmax (log-sum-exp) recurrence, so
the full t x t score matrix never materializes on any chip and compute
overlaps the ring transfer.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu import monitor as _monitor

_NEG_INF = -1e30

# ring_attention() runs at TRACE time (once per compile, not per step) —
# these count compiled ring programs and the K/V rotations each performs.
_M_RING_CALLS = _monitor.counter(
    "pt_ring_attention_traces_total", "ring-attention traces (per compile)")
_M_RING_ROTATIONS = _monitor.counter(
    "pt_ring_attention_rotations_total",
    "K/V ring-rotation steps traced (ring size per trace)")


def _ring_attention_local(q, k, v, bias, *, axis_name: str, causal: bool,
                          scale: float, p_drop: float = 0.0, seed=None):
    """Per-shard body (runs inside shard_map).

    q: [b, h, tq_loc, dh]; k, v: [b, h, tk_loc, dh] (this rank's block);
    bias: optional additive [b, 1|h, tq_loc, tk_GLOBAL] — the query dim is
    sharded with q, the key dim stays global and is sliced per ring step.

    Each ring step attends q against ONE rotating K/V block through the
    blocked flash kernels (parallel/flash_attention.py, O(block) HBM —
    the [tq_loc, tk_loc] score matrix never materializes on TPU even
    when per-rank chunks are themselves long), then merges the partial
    (o, lse) pairs with the standard logsumexp combine. Causal routing
    is BLOCK-level: source blocks entirely in the future are skipped
    without touching the MXU (the ring analog of the kernels'
    dead-block skip), the diagonal block runs the in-kernel causal
    mask, past blocks run dense.
    """
    n = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    tq = q.shape[2]
    tk = k.shape[2]

    perm = [(i, (i + 1) % n) for i in range(n)]

    from paddle_tpu.parallel import flash_attention as fa

    def _block(k_blk, v_blk, blk_bias, blk_causal, src):
        # the custom-vjp wrapper, NOT flash_attention_fwd: the sdpa grad
        # op differentiates ring_attention through jax.vjp, and a raw
        # pallas_call has no JVP rule on TPU — the wrapper routes the
        # backward through the blocked kernels. Attention dropout works
        # per block: the seed is mixed with the SOURCE rank so every
        # ring step draws an independent mask stream (the kernel's own
        # (b, jq, kk) keying is block-local and would repeat across
        # steps), and forward/backward regenerate identically because
        # the vjp re-derives the same per-step seed.
        blk_seed = None
        if p_drop > 0.0:
            # mix BOTH the source block and the destination rank: the
            # kernel's own (b, jq, kk) keying is block-local, so without
            # the rank term every destination would regenerate identical
            # masks for the same source block (dropout correlated across
            # sequence shards instead of i.i.d.)
            blk_seed = jnp.asarray(seed, jnp.int32)
            for x in (src.astype(jnp.int32), rank.astype(jnp.int32)):
                blk_seed = (blk_seed * jnp.int32(1000003)) ^ x
        o_blk, lse_blk = fa.flash_attention_with_lse(
            q, k_blk, v_blk, blk_bias, blk_seed, scale, p_drop,
            causal=blk_causal)
        return o_blk.astype(jnp.float32), lse_blk[..., 0]  # [b,h,tq]

    def step(carry, i):
        k_blk, v_blk, lse, o = carry
        # source rank of this block: blocks rotate forward each step, so
        # at step i we hold the block of rank (rank - i) mod n.
        src = (rank - i) % n
        blk_bias = None
        if bias is not None:
            blk_bias = jax.lax.dynamic_slice_in_dim(
                bias, src * tk, tk, axis=3)

        if causal and tq == tk:
            # same sequence sharded once: rank-level routing — the
            # diagonal needs the in-kernel mask, the past is dense, the
            # future is skipped outright (identity on the carry).
            def _past(_):
                return _block(k_blk, v_blk, blk_bias, False, src)

            def _diag(_):
                return _block(k_blk, v_blk, blk_bias, True, src)

            def _future(_):
                return (jnp.zeros_like(o),
                        jnp.full_like(lse, _NEG_INF))

            case = jnp.where(src < rank, 0, jnp.where(src == rank, 1, 2))
            o_blk, lse_blk = jax.lax.switch(
                case, (_past, _diag, _future), operand=None)
        elif causal:
            # tq != tk: rank-level classification misaligns with true
            # positions, so mask by GLOBAL positions as an additive bias
            # into the kernel (correct for any chunking; no block skip)
            q_pos = rank * tq + jnp.arange(tq)
            k_pos = src * tk + jnp.arange(tk)
            pos_bias = jnp.where(q_pos[:, None] >= k_pos[None, :],
                                 0.0, _NEG_INF)[None, None]
            eff_bias = (pos_bias if blk_bias is None
                        else blk_bias.astype(jnp.float32) + pos_bias)
            o_blk, lse_blk = _block(k_blk, v_blk, eff_bias, False, src)
        else:
            o_blk, lse_blk = _block(k_blk, v_blk, blk_bias, False, src)

        # logsumexp merge of two attention partials
        lse_new = jnp.logaddexp(lse, lse_blk)
        w_old = jnp.exp(lse - lse_new)[..., None]
        w_blk = jnp.exp(lse_blk - lse_new)[..., None]
        o_new = o * w_old + o_blk * w_blk
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, lse_new, o_new), None

    b, h = q.shape[0], q.shape[1]
    lse0 = jnp.full((b, h, tq), _NEG_INF, jnp.float32)
    o0 = jnp.zeros((b, h, tq, q.shape[3]), jnp.float32)
    # initial carries are rank-invariant; mark them varying over every
    # sharded mesh axis (ring axis + any batch/data axis the inputs carry)
    # so the scan carry type matches the per-rank outputs
    vary = tuple(
        a for a in (jax.typeof(q).vma | {axis_name}) if a is not None
    )
    lse0, o0 = jax.lax.pcast((lse0, o0), vary, to="varying")
    (k_f, v_f, lse, o), _ = jax.lax.scan(
        step, (k, v, lse0, o0), jnp.arange(n)
    )
    return o.astype(q.dtype)


def ring_attention(
    q,
    k,
    v,
    mesh: Mesh,
    seq_axis: str = "sp",
    causal: bool = False,
    scale: Optional[float] = None,
    bias=None,
    data_axis: Optional[str] = None,
    p_drop: float = 0.0,
    seed=None,
):
    """Sequence-parallel attention: q, k, v are [b, h, t, dh] GLOBAL arrays
    (sharded or shardable over ``seq_axis`` on dim 2). ``bias`` is an
    optional additive [b, 1|h, tq, tk] mask (sharded over tq, global over
    tk). ``data_axis`` additionally shards the batch dim. ``p_drop`` +
    ``seed``: attention dropout, applied in-kernel per rotating block
    with a source-rank-mixed seed stream."""
    if p_drop > 0.0 and seed is None:
        raise ValueError("ring_attention: p_drop > 0 requires `seed`")
    if _monitor.enabled():
        _M_RING_CALLS.inc()
        _M_RING_ROTATIONS.inc(mesh.shape[seq_axis])
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    d = data_axis
    spec = P(d, None, seq_axis, None)
    in_specs = [spec, spec, spec]
    if bias is not None:
        # broadcast dims (size 1) cannot be sharded: a [b,1,1,tk] pad-only
        # bias keeps its q dim replicated, and the k dim is always global
        # (sliced per ring step inside the body).
        in_specs.append(P(
            d if bias.shape[0] > 1 else None,
            None,
            seq_axis if bias.shape[2] > 1 else None,
            None,
        ))
    else:
        in_specs.append(P())

    has_bias = bias is not None
    if not has_bias:
        bias = jnp.zeros((), q.dtype)  # placeholder, dropped in `local`

    def local(q, k, v, b):
        return _ring_attention_local(
            q, k, v, b if has_bias else None,
            axis_name=seq_axis, causal=causal, scale=scale,
            p_drop=p_drop, seed=seed,
        )

    fn = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=spec,
    )
    # multi-host dispatch can block inside the call (compile-time
    # rendezvous, a peer that never enters the collective): the watchdog
    # turns that silent hang into a stall record
    with _monitor.stall_guard("ring_attention.dispatch"):
        return fn(q, k, v, bias)


def collective_signature(mesh: Mesh, seq_axis: str = "sp") -> dict:
    """Static description of the collective a ring-attention trace
    emits over ``mesh``: every rank on ``seq_axis`` must enter the same
    ``n`` ppermute rotations in the same order, or the ring deadlocks.
    Consumed by the static verifier's collective-order check
    (analysis.collective_signature) — extraction only, no tracing."""
    n = int(mesh.shape[seq_axis])
    return {
        "participants": n,
        "schedule": "ppermute-ring",
        "rotations": n,
    }


def reference_attention(q, k, v, causal: bool = False, scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t = q.shape[2]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -1e9)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)
