"""Sharding strategies: name-pattern -> PartitionSpec rules.

The TPU-native analog of the reference's BuildStrategy + multi-device graph
rewriting (reference: details/build_strategy.h:57, multi_devices_graph_pass.cc:169):
instead of cloning ops per device and inserting collectives, a strategy maps
variable names to PartitionSpecs; the executor passes them as jit
in_shardings and GSPMD partitions the single program, inserting ICI
collectives where contractions cross shards.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Sequence, Tuple

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass
class ShardingRule:
    pattern: str  # regex matched against the variable name
    spec: P

    def __post_init__(self):
        self._re = re.compile(self.pattern)

    def matches(self, name: str) -> bool:
        return self._re.search(name) is not None


class DistributedStrategy:
    """mesh + data axis + parameter sharding rules."""

    def __init__(
        self,
        mesh: Mesh,
        data_axis: Optional[str] = "data",
        rules: Sequence[ShardingRule] = (),
    ):
        self.mesh = mesh
        self.data_axis = data_axis if data_axis in mesh.axis_names else None
        self.rules = list(rules)

    def spec_for(self, name: str) -> P:
        for r in self.rules:
            if r.matches(name):
                return r.spec
        return P()  # replicated

    def sharding_for(self, name: str) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec_for(name))

    def batch_sharding(self) -> NamedSharding:
        if self.data_axis is None:
            return NamedSharding(self.mesh, P())
        return NamedSharding(self.mesh, P(self.data_axis))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


def transformer_rules(model_axis: str = "model") -> List[ShardingRule]:
    """Megatron-style tensor parallelism for models/transformer.py naming:

    - ``*_colp.w``: [in, out] column-parallel -> shard out dim
    - ``*_colp.b``: bias on the sharded dim
    - ``*_rowp.w``: [in, out] row-parallel -> shard in dim (output needs the
      GSPMD-inserted all-reduce)
    - embeddings/proj: vocab-sharded output projection
    """
    m = model_axis
    return [
        ShardingRule(r"_colp\.w$", P(None, m)),
        ShardingRule(r"_colp\.b$", P(m)),
        ShardingRule(r"_rowp\.w$", P(m, None)),
        ShardingRule(r"_rowp\.b$", P()),
        ShardingRule(r"^(src|trg)_emb\.w$", P(None, None)),
        ShardingRule(r"^proj_colp\.w$", P(None, m)),
        # Optimizer accumulators (moment/velocity/...) inherit the
        # parameter's sharding; beta-pow scalars fall through to replicated.
        ShardingRule(
            r"_colp\.w_(moment1|moment2|velocity|mean_square|mean_grad|squared|linear)",
            P(None, m),
        ),
        ShardingRule(
            r"_rowp\.w_(moment1|moment2|velocity|mean_square|mean_grad|squared|linear)",
            P(m, None),
        ),
        ShardingRule(
            r"_colp\.b_(moment1|moment2|velocity|mean_square|mean_grad|squared|linear)",
            P(m),
        ),
    ]
