"""Sharding strategies: name-pattern -> PartitionSpec rules.

The TPU-native analog of the reference's BuildStrategy + multi-device graph
rewriting (reference: details/build_strategy.h:57, multi_devices_graph_pass.cc:169):
instead of cloning ops per device and inserting collectives, a strategy maps
variable names to PartitionSpecs; the executor passes them as jit
in_shardings and GSPMD partitions the single program, inserting ICI
collectives where contractions cross shards.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Sequence

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass
class ShardingRule:
    pattern: str  # regex matched against the variable name
    spec: P

    def __post_init__(self):
        self._re = re.compile(self.pattern)

    def matches(self, name: str) -> bool:
        return self._re.search(name) is not None


_SCALAR_STATE_RULES = [
    ShardingRule(r"_(beta1_pow|beta2_pow)_\d+$", P()),
    ShardingRule(r"^learning_rate", P()),
]


class DistributedStrategy:
    """mesh + data axis + parameter sharding rules.

    ``strict=True`` makes an unmatched variable name an error instead of a
    silent fall-through to replicated — a typo in a rule pattern otherwise
    degrades tensor parallelism to replication with no signal.
    """

    def __init__(
        self,
        mesh: Mesh,
        data_axis: Optional[str] = "data",
        rules: Sequence[ShardingRule] = (),
        strict: bool = False,
        context_axis: Optional[str] = None,
        table_axis: Optional[str] = None,
        expert_axis: Optional[str] = None,
        pipe_axis: Optional[str] = None,
        pipe_micro: Optional[int] = None,
        slice_axis: Optional[str] = None,
    ):
        self.mesh = mesh
        self.data_axis = data_axis if data_axis in mesh.axis_names else None
        # Multi-slice data parallelism: an OUTER batch axis laid over DCN
        # (slice boundaries), composing with the within-slice ICI data
        # axis. The TPU-native equivalent of the reference's 2-level
        # hierarchical allreduce (reference: platform/nccl_helper.h:179-210
        # MultiNCCLContextMap inter/exter rings, parallel_executor.cc:180):
        # with the batch sharded P((slice, data)), GSPMD decomposes the
        # gradient all-reduce into within-slice reduce-scatter (ICI) +
        # cross-slice all-reduce (DCN) + within-slice all-gather — the
        # hierarchy comes from the mesh's device layout (see
        # mesh.create_slice_mesh), not hand-inserted collectives.
        self.slice_axis = (
            slice_axis if slice_axis in mesh.axis_names else None
        )
        # The shard_map kernels (ring attention, GPipe, MoE, sharded
        # tables) receive the COMPOSED (slice, data) batch axis through
        # SpmdCtx.data_axis (core/interp.py spmd_ctx_scope) — their
        # specs/collectives accept axis tuples, so slice_axis composes
        # with every other axis.
        self.rules = list(rules)
        self.strict = strict
        # Sequence/context parallelism: attention ops route through the
        # ring-attention shard_map over this axis (SURVEY.md section 5
        # "long-context"). None = no sequence sharding.
        self.context_axis = (
            context_axis if context_axis in mesh.axis_names else None
        )
        # Sharded embedding tables: lookup_table(is_distributed=True) rows
        # are sharded over this axis (replaces the reference's distributed
        # lookup table / pserver prefetch).
        self.table_axis = (
            table_axis if table_axis in mesh.axis_names else None
        )
        # Expert parallelism: switch_moe ops dispatch tokens over this axis
        # via all_to_all (one expert per rank, parallel/moe.py).
        self.expert_axis = (
            expert_axis if expert_axis in mesh.axis_names else None
        )
        # Pipeline parallelism: pipelinable scan ops (scan-over-layers
        # model builds) run the GPipe schedule over this axis, one layer
        # per rank (parallel/pipeline.py). pipe_micro = microbatch count
        # (default: one per stage).
        self.pipe_axis = (
            pipe_axis if pipe_axis in mesh.axis_names else None
        )
        self.pipe_micro = pipe_micro

    def spec_for(self, name: str) -> P:
        # Scalar optimizer state (Adam beta pows, LR) can never shard;
        # resolved ahead of user rules so a parameter-suffix rule like
        # ``foo\.w(_|$)`` doesn't claim ``foo.w_beta1_pow_0`` (rank 1) and
        # fail jit's rank check. Checked before user rules but outside
        # ``self.rules`` so strict-with-no-user-rules stays a no-op.
        for r in _SCALAR_STATE_RULES:
            if r.matches(name):
                return r.spec
        for r in self.rules:
            if r.matches(name):
                return r.spec
        if self.strict and self.rules:
            raise ValueError(
                f"strict sharding strategy: variable '{name}' matches no "
                f"rule; add an explicit rule (use PartitionSpec() for "
                f"replicated)"
            )
        return P()  # replicated

    def sharding_for(self, name: str) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec_for(name))

    def batch_sharding(self) -> NamedSharding:
        axes = tuple(a for a in (self.slice_axis, self.data_axis)
                     if a is not None)
        if not axes:
            return NamedSharding(self.mesh, P())
        return NamedSharding(self.mesh, P(axes if len(axes) > 1
                                          else axes[0]))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


def moe_rules(expert_axis: str = "expert") -> List[ShardingRule]:
    """Expert-parallel sharding for layers.switch_moe naming: stacked
    expert weights ``{name}_experts.{w1,b1,w2,b2}`` shard the leading
    expert dim; the router ``{name}_gate.w`` stays replicated. The (_|$)
    suffix makes optimizer accumulators inherit the parameter's spec."""
    e = expert_axis
    return [
        ShardingRule(r"_experts\.(w1|b1|w2|b2)(_|$)", P(e)),
        ShardingRule(r"_gate\.w(_|$)", P()),
    ]


def pipeline_rules(pipe_axis: str = "pipe") -> List[ShardingRule]:
    """Stacked-layer weights ([L, ...] from scan-over-layers builds,
    ``*_stacked`` naming) shard one layer per pipe rank; everything else
    replicates (combine with transformer_rules/data axis as needed)."""
    return [ShardingRule(r"_stacked(_|$)", P(pipe_axis))]


def pipeline_tp_rules(pipe_axis: str = "pipe",
                      model_axis: str = "model") -> List[ShardingRule]:
    """tp INSIDE a pipeline stage (the composition every real
    large-model config uses — SURVEY.md §2.3 final row): stacked-layer
    weights ([L, ...] from scan-over-layers builds) shard dim 0 over the
    pipe axis AND their Megatron dim over the model axis. The pipe dim
    is sliced manually by gpipe's shard_map; the model dim is an AUTO
    axis GSPMD partitions inside the stage body (parallel/pipeline.py).

    Key naming comes from _enc/_dec_weight_specs: stacked slots keep the
    per-layer kind in the slot key (qkv/ffn1/q/k/v = column-parallel,
    out/ffn2 = row-parallel)."""
    p, m = pipe_axis, model_axis
    return [
        # column-parallel: shard the output dim (stacked dim 2 for w)
        ShardingRule(r"_(qkv|ffn1|self_q|self_k|self_v|q|k|v)\.w_stacked(_|$)",
                     P(p, None, m)),
        ShardingRule(r"_(qkv|ffn1|self_q|self_k|self_v|q|k|v)\.b_stacked(_|$)",
                     P(p, m)),
        # row-parallel: shard the input dim (stacked dim 1 for w)
        ShardingRule(r"_(out|self_out|cross_out|ffn2)\.w_stacked(_|$)",
                     P(p, m, None)),
        ShardingRule(r"_(out|self_out|cross_out|ffn2)\.b_stacked(_|$)",
                     P(p)),
        ShardingRule(r"_stacked(_|$)", P(p)),   # norms etc: pipe only
        # non-stacked tails (embeddings stay replicated; the vocab
        # projection column-shards like transformer_rules)
        ShardingRule(r"proj_colp\.w(_|$)", P(None, m)),
    ]


def transformer_rules(model_axis: str = "model") -> List[ShardingRule]:
    """Megatron-style tensor parallelism for models/transformer.py naming:

    - ``*_colp.w``: [in, out] column-parallel -> shard out dim
    - ``*_colp.b``: bias on the sharded dim
    - ``*_rowp.w``: [in, out] row-parallel -> shard in dim (output needs the
      GSPMD-inserted all-reduce)
    - embeddings/proj: vocab-sharded output projection
    """
    m = model_axis
    return [
        # Norms and embeddings stay replicated (scalar optimizer state is
        # handled by the strategy's built-in _SCALAR_STATE_RULES).
        ShardingRule(r"_ln\.(scale|bias)(_|$)", P()),
        ShardingRule(r"^(src|trg)_(emb|pos)\.w(_|$)", P()),
        # Megatron TP: column-parallel shards the output dim, row-parallel
        # the input dim (GSPMD inserts the all-reduce on the row-parallel
        # matmul output). The (_|$) suffix makes optimizer accumulators
        # (``{param}_moment1_0`` etc.) inherit the parameter's spec.
        ShardingRule(r"_colp\.w(_|$)", P(None, m)),
        ShardingRule(r"_colp\.b(_|$)", P(m)),
        ShardingRule(r"_rowp\.w(_|$)", P(m, None)),
        ShardingRule(r"_rowp\.b(_|$)", P()),
    ]
