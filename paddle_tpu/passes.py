"""Program-level pass framework.

The TPU-native analog of the reference's IR pass registry
(reference: paddle/fluid/framework/ir/pass.h + ~45 registered passes).
Fusion/layout/memory passes are delegated to XLA by design (SURVEY.md
section 7 phase 4), so the passes that remain are PROGRAM rewrites —
AMP marking, quantization-aware-training insertion, inference folding,
pruning — and this module gives them one registry + pipeline API instead
of ad-hoc entry points:

    from paddle_tpu import passes
    passes.apply_pass("conv_bn_fuse", program, scope=scope)
    pm = passes.PassManager(["quant_aware", "amp"])
    pm.apply(program)

A pass is ``apply(program, scope=None, **kw) -> program`` (mutating in
place and returning the program; the return value allows rewriting
passes that build a new Program, e.g. inference pruning).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

_PASS_REGISTRY: Dict[str, Callable] = {}


def register_pass(name: str):
    """Decorator registering ``fn(program, scope=None, **kw) -> program``
    (reference: REGISTER_PASS, framework/ir/pass.h)."""

    def deco(fn):
        if name in _PASS_REGISTRY:
            raise ValueError(f"pass '{name}' registered twice")
        _PASS_REGISTRY[name] = fn
        return fn

    return deco


def registered_passes() -> List[str]:
    return sorted(_PASS_REGISTRY)


def get_pass(name: str) -> Callable:
    if name not in _PASS_REGISTRY:
        raise KeyError(
            f"unknown pass '{name}'; registered: {registered_passes()}"
        )
    return _PASS_REGISTRY[name]


def apply_pass(name: str, program, scope=None, **kw):
    out = get_pass(name)(program, scope=scope, **kw)
    return program if out is None else out


class PassManager:
    """Ordered pass pipeline (reference: ir/pass.h PassRegistry usage in
    details/build_strategy.cc:52-230)."""

    def __init__(self, names: Sequence[str] = ()):
        self.names = list(names)

    def append(self, name: str) -> "PassManager":
        self.names.append(name)
        return self

    def apply(self, program, scope=None, **kw):
        for n in self.names:
            program = apply_pass(n, program, scope=scope, **kw)
        return program


# --- built-in passes wrapping the existing rewrites ---


@register_pass("conv_bn_fuse")
def _conv_bn_fuse(program, scope=None, **kw):
    """Fold inference-mode batch norms into the preceding conv
    (transpiler.InferenceTranspiler)."""
    from paddle_tpu.transpiler import InferenceTranspiler

    InferenceTranspiler().transpile(program, scope)
    return program


@register_pass("quant_aware")
def _quant_aware(program, scope=None, weight_bits=8, activation_bits=8,
                 **kw):
    """Insert fake-quant STE ops before matmul/conv inputs
    (slim.quantization.QuantizationTransformPass)."""
    from paddle_tpu.slim.quantization import QuantizationTransformPass

    QuantizationTransformPass(
        weight_bits=weight_bits, activation_bits=activation_bits
    ).apply(program)
    return program


@register_pass("amp")
def _amp(program, scope=None, **kw):
    """Mark the program for bf16 AMP lowering (core/lowering.py reads
    ``program._amp`` at trace time)."""
    program._amp = True
    return program


@register_pass("inference_prune")
def _inference_prune(program, scope=None, targets=None, feeds=None, **kw):
    """Prune to the inference subgraph reaching ``targets`` (io.py's
    save_inference_model pruning, exposed as a standalone pass)."""
    if targets is None:
        raise ValueError("inference_prune needs targets=[vars or names]")
    from paddle_tpu import io as _io

    return _io._prune_for_inference(program, feeds or [], targets)


@register_pass("fc_fuse")
def _fc_fuse(program, scope=None, fetch_targets=(), **kw):
    """Collapse mul + elementwise_add pairs into single fc ops
    (reference: framework/ir/fc_fuse_pass.cc). Program-level rewrite on
    the shared matcher (ir_pattern.match_chain): the mul's output must
    feed ONLY the add, the add's Y must be a 1-D bias that is already
    DEFINED at the mul's position (a parameter or an earlier op's
    output — the fc is spliced where the mul was, so a later-produced
    bias would be read before it exists), added on the TRAILING axis,
    and the mul must use the default y_num_col_dims (2-D W). Mostly
    useful for the sub-block interp path and smaller serialized
    programs — XLA fuses the pair anyway in whole-program compilation.
    The mul's intermediate (pre-bias) var is no longer produced after
    fusion, so fusion is skipped when it is persistable or named in
    ``fetch_targets``; fetch the fc output otherwise."""
    from paddle_tpu.framework import Operator
    from paddle_tpu.ir_pattern import BlockGraph, match_chain

    block = program.global_block()
    graph = BlockGraph(block)
    fetch_names = {
        f if isinstance(f, str) else f.name for f in fetch_targets
    }

    plans = []  # (mul idx, add idx, fused Operator)
    for i, j in match_chain(graph, ("mul",), "Out",
                            "elementwise_add", "X"):
        op, nxt = block.ops[i], block.ops[j]
        out = op.outputs["Out"][0]
        if graph.is_persistable(out) or out in fetch_names:
            continue
        y = nxt.inputs.get("Y", [None])[0]
        yv = block._find_var_recursive(y) if y else None
        xnc = int(op.attrs.get("x_num_col_dims", 1))
        add_axis = int(nxt.attrs.get("axis", -1))
        if (yv is not None and yv.shape is not None
                and len(yv.shape) == 1
                # the fused fc runs at the mul's position
                and graph.available_before(y, i)
                # bias must land on the TRAILING (column) axis: the
                # mul output is rank xnc+1
                and add_axis in (-1, xnc)
                # fc mirrors mul only for 2-D W (default y_num_col_dims)
                and int(op.attrs.get("y_num_col_dims", 1)) == 1):
            plans.append((i, j, Operator(
                block, "fc",
                inputs={"Input": list(op.inputs["X"]),
                        "W": list(op.inputs["Y"]),
                        "Bias": [y]},
                outputs={"Out": list(nxt.outputs["Out"])},
                attrs={"in_num_col_dims": xnc},
            )))

    if plans:
        replace = {i: fc for i, _, fc in plans}
        drop = {j for _, j, _ in plans}
        for i, _, _ in plans:
            # the pre-bias intermediate is no longer produced
            block.vars.pop(block.ops[i].outputs["Out"][0], None)
        block.ops[:] = [
            replace.get(idx, op) for idx, op in enumerate(block.ops)
            if idx not in drop
        ]
        program._bump_version()
    return program
