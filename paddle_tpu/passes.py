"""Program-level pass framework.

The TPU-native analog of the reference's IR pass registry
(reference: paddle/fluid/framework/ir/pass.h + ~45 registered passes).
Fusion/layout/memory passes are delegated to XLA by design (SURVEY.md
section 7 phase 4), so the passes that remain are PROGRAM rewrites —
AMP marking, quantization-aware-training insertion, inference folding,
pruning — and this module gives them one registry + pipeline API instead
of ad-hoc entry points:

    from paddle_tpu import passes
    passes.apply_pass("conv_bn_fuse", program, scope=scope)
    pm = passes.PassManager(["quant_aware", "amp"])
    pm.apply(program)

A pass is ``apply(program, scope=None, **kw) -> program`` (mutating in
place and returning the program; the return value allows rewriting
passes that build a new Program, e.g. inference pruning).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Sequence

_PASS_REGISTRY: Dict[str, Callable] = {}


def register_pass(name: str):
    """Decorator registering ``fn(program, scope=None, **kw) -> program``
    (reference: REGISTER_PASS, framework/ir/pass.h)."""

    def deco(fn):
        if name in _PASS_REGISTRY:
            raise ValueError(f"pass '{name}' registered twice")
        _PASS_REGISTRY[name] = fn
        return fn

    return deco


def registered_passes() -> List[str]:
    return sorted(_PASS_REGISTRY)


def get_pass(name: str) -> Callable:
    if name not in _PASS_REGISTRY:
        raise KeyError(
            f"unknown pass '{name}'; registered: {registered_passes()}"
        )
    return _PASS_REGISTRY[name]


def apply_pass(name: str, program, scope=None, **kw):
    out = get_pass(name)(program, scope=scope, **kw)
    return program if out is None else out


class PassManager:
    """Ordered pass pipeline (reference: ir/pass.h PassRegistry usage in
    details/build_strategy.cc:52-230)."""

    def __init__(self, names: Sequence[str] = ()):
        self.names = list(names)

    def append(self, name: str) -> "PassManager":
        self.names.append(name)
        return self

    def apply(self, program, scope=None, **kw):
        for n in self.names:
            program = apply_pass(n, program, scope=scope, **kw)
        return program


# --- built-in passes wrapping the existing rewrites ---


@register_pass("conv_bn_fuse")
def _conv_bn_fuse(program, scope=None, **kw):
    """Fold inference-mode batch norms into the preceding conv
    (transpiler.InferenceTranspiler)."""
    from paddle_tpu.transpiler import InferenceTranspiler

    InferenceTranspiler().transpile(program, scope)
    return program


@register_pass("quant_aware")
def _quant_aware(program, scope=None, weight_bits=8, activation_bits=8,
                 **kw):
    """Insert fake-quant STE ops before matmul/conv inputs
    (slim.quantization.QuantizationTransformPass)."""
    from paddle_tpu.slim.quantization import QuantizationTransformPass

    QuantizationTransformPass(
        weight_bits=weight_bits, activation_bits=activation_bits
    ).apply(program)
    return program


@register_pass("amp")
def _amp(program, scope=None, **kw):
    """Mark the program for bf16 AMP lowering (core/lowering.py reads
    ``program._amp`` at trace time)."""
    program._amp = True
    return program


@register_pass("instrument_numerics")
def _instrument_numerics(program, scope=None, vars=None, histogram_bins=0,
                         **kw):
    """Append the in-graph tensor-stats bundle (numerics.py): cheap
    on-device reductions (non-finite count, max-abs, rms, optional
    log2-magnitude histogram) over selected op outputs — activations,
    gradients, parameters — fetched by the executor as ONE auxiliary
    array per sampled step and decoded into pt_tensor_* instruments and
    NaN-provenance records. Apply after the program is fully built
    (minimize/clip/AMP included)."""
    from paddle_tpu import numerics

    numerics.instrument(program, vars=vars, histogram_bins=histogram_bins)
    return program


@register_pass("lint")
def _lint(program, scope=None, feeds=None, fetches=None, strategy=None,
          checks=None, **kw):
    """Static program verifier (analysis.py) in pass form: runs every
    registered check over the shared def-use index, meters + stores the
    findings (debugger.pprint_program / the /lint route show them), and
    logs warning/error findings — raising LintError instead when the
    ``static_lint`` flag is 'error'. The program itself is never
    mutated; the pass returns it unchanged so lint composes anywhere in
    a PassManager pipeline."""
    from paddle_tpu import analysis

    findings = analysis.lint(
        program, feeds=feeds, fetches=fetches, strategy=strategy,
        checks=checks, min_severity="debug")
    analysis._dispatch(findings, site="pass")
    return program


@register_pass("inference_prune")
def _inference_prune(program, scope=None, targets=None, feeds=None, **kw):
    """Prune to the inference subgraph reaching ``targets`` (io.py's
    save_inference_model pruning, exposed as a standalone pass)."""
    if targets is None:
        raise ValueError("inference_prune needs targets=[vars or names]")
    from paddle_tpu import io as _io

    return _io._prune_for_inference(program, feeds or [], targets)


@register_pass("fc_fuse")
def _fc_fuse(program, scope=None, fetch_targets=(), **kw):
    """Collapse mul + elementwise_add pairs into single fc ops
    (reference: framework/ir/fc_fuse_pass.cc). Program-level rewrite on
    the shared matcher (ir_pattern.match_chain): the mul's output must
    feed ONLY the add, the add's Y must be a 1-D bias that is already
    DEFINED at the mul's position (a parameter or an earlier op's
    output — the fc is spliced where the mul was, so a later-produced
    bias would be read before it exists), added on the TRAILING axis,
    and the mul must use the default y_num_col_dims (2-D W). Mostly
    useful for the sub-block interp path and smaller serialized
    programs — XLA fuses the pair anyway in whole-program compilation.
    The mul's intermediate (pre-bias) var is no longer produced after
    fusion, so fusion is skipped when it is persistable or named in
    ``fetch_targets``; fetch the fc output otherwise."""
    from paddle_tpu.framework import Operator
    from paddle_tpu.ir_pattern import BlockGraph, match_chain

    block = program.global_block()
    graph = BlockGraph(block)
    fetch_names = {
        f if isinstance(f, str) else f.name for f in fetch_targets
    }

    plans = []  # (mul idx, add idx, fused Operator)
    for i, j in match_chain(graph, ("mul",), "Out",
                            "elementwise_add", "X"):
        op, nxt = block.ops[i], block.ops[j]
        out = op.outputs["Out"][0]
        if graph.is_persistable(out) or out in fetch_names:
            continue
        y = nxt.inputs.get("Y", [None])[0]
        yv = block._find_var_recursive(y) if y else None
        xnc = int(op.attrs.get("x_num_col_dims", 1))
        add_axis = int(nxt.attrs.get("axis", -1))
        if (yv is not None and yv.shape is not None
                and len(yv.shape) == 1
                # the fused fc runs at the mul's position
                and graph.available_before(y, i)
                # bias must land on the TRAILING (column) axis: the
                # mul output is rank xnc+1
                and add_axis in (-1, xnc)
                # fc mirrors mul only for 2-D W (default y_num_col_dims)
                and int(op.attrs.get("y_num_col_dims", 1)) == 1):
            plans.append((i, j, Operator(
                block, "fc",
                inputs={"Input": list(op.inputs["X"]),
                        "W": list(op.inputs["Y"]),
                        "Bias": [y]},
                outputs={"Out": list(nxt.outputs["Out"])},
                attrs={"in_num_col_dims": xnc},
            )))

    if plans:
        replace = {i: fc for i, _, fc in plans}
        drop = {j for _, j, _ in plans}
        for i, _, _ in plans:
            # the pre-bias intermediate is no longer produced
            block.vars.pop(block.ops[i].outputs["Out"][0], None)
        block.ops[:] = [
            replace.get(idx, op) for idx, op in enumerate(block.ops)
            if idx not in drop
        ]
        program._bump_version()
    return program


# Op types safe to deduplicate / fold: deterministic pure functions of
# their inputs+attrs (no PRNG, no state updates, no side effects, no
# sub-blocks). Conservative by construction — unlisted types are left
# alone. Reference analogs: framework/ir/ (constant folding) and the
# executor-level CSE the reference gets from its SSA graph.
_PURE_OP_TYPES = frozenset({
    "scale", "cast", "reshape", "transpose", "unsqueeze", "squeeze",
    "expand", "slice", "concat", "stack", "split",
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow",
    "relu", "sigmoid", "tanh", "exp", "log", "sqrt", "square", "abs",
    "softmax", "log_softmax",
    "matmul", "mul",
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min", "mean",
    "fill_constant", "fill_any_like", "assign_value", "range",
    "less_than", "less_equal", "greater_than", "greater_equal", "equal",
    "not_equal", "logical_and", "logical_or", "logical_not",
    "attn_bias", "one_hot", "lookup_table",
})

# Pure generators with NO inputs: their (type, attrs) alone determines
# the value, so they both seed constant folding and are CSE-able.
_CONST_GENERATORS = frozenset({"fill_constant", "assign_value", "range"})


def _unstable_vars(block):
    """Var names whose value is NOT a pure function of their name within
    the block — reassigned names (multiple writers: assign output=,
    increment in_place, a while op's Out carries) or names read before
    their (only) writer (a feed/outer var later overwritten). Name-keyed
    optimizations (CSE, constant folding) must not treat reads of these
    as referentially transparent: the same name denotes different values
    at different program points."""
    first_write = {}
    writers = {}
    first_read = {}
    for idx, op in enumerate(block.ops):
        for n in op.input_arg_names:
            first_read.setdefault(n, idx)
        for n in op.output_arg_names:
            writers[n] = writers.get(n, 0) + 1
            first_write.setdefault(n, idx)
    unstable = {n for n, c in writers.items() if c > 1}
    for n, w in first_write.items():
        if first_read.get(n, w + 1) < w:
            unstable.add(n)  # read-before-write: the name is reused
    return unstable


def _op_key(op):
    """Hashable identity of a pure op: (type, sorted inputs, sorted
    attrs). None when any attr resists cheap stable serialization."""
    try:
        attrs = tuple(sorted((k, repr(v)) for k, v in op.attrs.items()))
    except Exception:
        return None
    ins = tuple(sorted((slot, tuple(ns)) for slot, ns in op.inputs.items()))
    return (op.type, ins, attrs)


@register_pass("cse")
def _cse(program, scope=None, fetch_targets=(), **kw):
    """Common-subexpression elimination over the global block: two pure
    ops with identical (type, inputs, attrs) compute the same value, so
    the later one's outputs alias the earlier one's (consumers are
    renamed; the duplicate op is dropped). Whole-program XLA lowering
    gets this from XLA itself; this pass exists for SERIALIZED programs
    — inference artifacts and the sub-block interp path — where
    duplicate chains (e.g. per-layer rebuilt attention biases) would
    otherwise execute N times. Persistable or fetched outputs are never
    aliased away."""
    block = program.global_block()
    fetch_names = {f if isinstance(f, str) else f.name
                   for f in fetch_targets}
    unstable = _unstable_vars(block)
    seen = {}           # op key -> canonical op index
    rename = {}         # var name -> canonical var name
    drop = set()
    for idx, op in enumerate(block.ops):
        # apply pending renames so chained duplicates collapse
        # transitively in one pass
        if any(n in rename for ns in op.inputs.values() for n in ns):
            op.inputs = {
                slot: [rename.get(n, n) for n in ns]
                for slot, ns in op.inputs.items()
            }
        if op.type not in _PURE_OP_TYPES:
            continue
        # reads or writes of a reassigned name are position-dependent:
        # two textually identical ops can observe different values
        if any(n in unstable
               for ns in list(op.inputs.values()) + list(op.outputs.values())
               for n in ns):
            continue
        key = _op_key(op)
        if key is None:
            continue
        canon = seen.get(key)
        if canon is None:
            seen[key] = idx
            continue
        outs = [n for ns in op.outputs.values() for n in ns]
        if any(graph_is_persistable(block, n) or n in fetch_names
               for n in outs):
            continue
        canon_op = block.ops[canon]
        for slot, ns in op.outputs.items():
            for a, b in zip(ns, canon_op.outputs.get(slot, [])):
                rename[a] = b
        drop.add(idx)
    if drop:
        for idx in drop:
            for ns in block.ops[idx].outputs.values():
                for n in ns:
                    block.vars.pop(n, None)
        block.ops[:] = [op for idx, op in enumerate(block.ops)
                        if idx not in drop]
        program._bump_version()
    return program


def graph_is_persistable(block, name):
    v = block._find_var_recursive(name)
    return bool(v is not None and getattr(v, "persistable", False))


@register_pass("constant_fold")
def _constant_fold(program, scope=None, fetch_targets=(),
                   max_elems=4096, **kw):
    """Fold pure ops whose inputs are all compile-time constants
    (transitively rooted at fill_constant / assign_value / range) into
    ``assign_value`` literals, evaluated through the op kernels
    themselves (one source of truth for semantics; reference analog:
    the constant-folding IR pass). Results larger than ``max_elems``
    stay unfolded — giant literals would bloat the serialized program
    past what the fold saves."""
    import numpy as np

    from paddle_tpu.core import interp as _interp
    from paddle_tpu.framework import Operator

    block = program.global_block()
    fetch_names = {f if isinstance(f, str) else f.name
                   for f in fetch_targets}
    unstable = _unstable_vars(block)
    const_vals = {}     # var name -> np.ndarray
    replace = {}        # op idx -> Operator (assign_value) or None=drop
    for idx, op in enumerate(block.ops):
        if op.type not in _PURE_OP_TYPES:
            continue
        # a reassigned name is not a constant even when its first writer
        # is one (assign output= / increment / while carries rebind it)
        if any(n in unstable
               for ns in list(op.inputs.values()) + list(op.outputs.values())
               for n in ns):
            continue
        ins = [n for ns in op.inputs.values() for n in ns if n]
        if op.type not in _CONST_GENERATORS and (
                not ins or not all(n in const_vals for n in ins)):
            continue
        if op.type in _CONST_GENERATORS and ins:
            if not all(n in const_vals for n in ins):
                continue
        key = _op_key(op)
        if key is None:
            continue
        try:
            env = {n: const_vals[n] for n in ins}
            _interp.exec_ops([op], env, key=None, amp=False)
        except Exception:
            continue
        outs = [n for ns in op.outputs.values() for n in ns]
        vals = {n: np.asarray(env[n]) for n in outs}
        if any(v.size > max_elems for v in vals.values()):
            continue
        const_vals.update(vals)
        if op.type in _CONST_GENERATORS and len(outs) == 1:
            # already a literal; no rewrite needed, but it seeds folds
            continue
        if len(outs) == 1 and outs[0] not in fetch_names \
                and not graph_is_persistable(block, outs[0]):
            v = vals[outs[0]]
            replace[idx] = Operator(
                block, "assign_value", inputs={},
                outputs={"Out": [outs[0]]},
                attrs={"shape": list(v.shape),
                       "dtype": str(v.dtype),
                       "values": v.reshape(-1).tolist()})
    if replace:
        # ops whose outputs became dead literals' inputs are cleaned by
        # a follow-up inference_prune; here only the folds are applied
        block.ops[:] = [replace.get(idx, op)
                        for idx, op in enumerate(block.ops)]
        program._bump_version()
    return program
