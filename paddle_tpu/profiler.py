"""Profiler front end (reference: python/paddle/fluid/profiler.py).

Host spans go to the native C++ profiler (csrc/profiler.cc -> chrome trace,
the analog of RecordEvent + tools/timeline.py). Device-side profiling is
delegated to jax.profiler (XLA xplane -> TensorBoard/perfetto), replacing
the reference's CUPTI DeviceTracer (reference: platform/device_tracer.cc).
"""

from __future__ import annotations

import contextlib
import time
import warnings
from typing import Optional

# Fast-path flag so per-step record_event calls cost one attribute check
# when profiling is off.
_host_enabled = False

# Trace-timeline hook, installed by monitor.py at import: a zero-arg
# callable returning either an ``emit(name, t0_perf, t1_perf)`` function
# (trace collection active) or None. Keeping the gate on monitor's side
# means record_event needs no monitor import and the old profiler API
# and the new timeline share ONE clock (perf_counter) and one stream.
_trace_hook = None

# Directory of the most recent xplane capture this module started (the
# seam the roofline plane parses: roofline.profile_from_xplane /
# parse_xplane). Set whether or not the capture SUCCEEDED — a failed
# start leaves the dir empty/absent, which the parser reports as one
# degrade warning, not a crash.
_last_xplane_dir: Optional[str] = None


def last_xplane_dir() -> Optional[str]:
    """Trace dir of the most recent ``profiler(with_xplane=True)``
    capture (None before the first): pass it to
    ``roofline.profile_from_xplane`` for per-op device attribution."""
    return _last_xplane_dir


def _trace_mark(name: str):
    """Instant event on the timeline (no-op unless monitor's trace
    collection is active) marking a legacy profiler lifecycle call."""
    import sys

    monitor = sys.modules.get("paddle_tpu.monitor")
    if monitor is not None:
        monitor.trace_event(name, "profiler", time.perf_counter())


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: Optional[str] = None,
             profile_path: str = "/tmp/profile", with_xplane: bool = False):
    """Context manager enabling host-span + device profiling.

    Writes <profile_path>.json (chrome trace of host spans). With
    ``with_xplane=True`` also captures the XLA device trace to
    <profile_path>_xplane/ via jax.profiler (can hang on tunneled/remote
    TPU backends, hence opt-in).
    """
    global _host_enabled, _last_xplane_dir
    from paddle_tpu import native

    use_native = native.available()
    if use_native:
        native.profiler_enable()
        _host_enabled = True
    _trace_mark("profiler.start")
    jax_trace_dir = profile_path + "_xplane"
    jax_started = False
    if with_xplane:
        _last_xplane_dir = jax_trace_dir
        try:
            import jax

            jax.profiler.start_trace(jax_trace_dir)
            jax_started = True
        except Exception as e:
            # a silently-dead xplane capture looks identical to "forgot
            # to open TensorBoard" — make the failure visible
            warnings.warn(
                f"jax.profiler.start_trace({jax_trace_dir!r}) failed; "
                f"no xplane device trace will be captured: {e!r}",
                RuntimeWarning, stacklevel=3)
    try:
        yield
    finally:
        if jax_started:
            import jax

            try:
                jax.profiler.stop_trace()
            except Exception as e:
                warnings.warn(
                    f"jax.profiler.stop_trace() failed; the xplane trace "
                    f"under {jax_trace_dir!r} may be missing or "
                    f"truncated: {e!r}", RuntimeWarning, stacklevel=3)
        _trace_mark("profiler.stop")
        if use_native:
            native.profiler_disable()
            _host_enabled = False
            native.profiler_dump(profile_path + ".json")


@contextlib.contextmanager
def record_event(name: str):
    """RAII host span (reference: platform/profiler.h:81 RecordEvent).

    With monitor's trace collection active every span — including
    legacy direct callers of this API — additionally lands in the
    trace-event ring on the same perf_counter clock as the new
    timeline. Both collectors off: a bare yield."""
    emit = _trace_hook() if _trace_hook is not None else None
    host = _host_enabled
    if not host and emit is None:
        yield
        return
    if host:
        from paddle_tpu import native

        native.profiler_begin(name)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if emit is not None:
            emit(name, t0, time.perf_counter())
        if host:
            native.profiler_end()


def start_profiler(state: str = "All"):
    global _host_enabled
    from paddle_tpu import native

    if native.available():
        native.profiler_enable()
        _host_enabled = True
    _trace_mark("profiler.start")


def stop_profiler(sorted_key: Optional[str] = None,
                  profile_path: str = "/tmp/profile"):
    global _host_enabled
    from paddle_tpu import native

    _trace_mark("profiler.stop")
    if native.available():
        native.profiler_disable()
        _host_enabled = False
        native.profiler_dump(profile_path + ".json")
