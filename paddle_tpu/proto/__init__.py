from .framework_pb2 import *  # noqa: F401,F403
from . import framework_pb2  # noqa: F401
