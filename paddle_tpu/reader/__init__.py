"""Data pipeline (reference: python/paddle/reader/ + fluid/reader.py)."""

from paddle_tpu.reader.decorator import (  # noqa: F401
    buffered,
    cache,
    chain,
    compose,
    firstn,
    map_readers,
    multiprocess_reader,
    shuffle,
    xmap_readers,
)


def batch(reader, batch_size: int, drop_last: bool = True):
    """Group samples into batches (reference: python/paddle/batch.py).

    ``drop_last`` defaults True: XLA static shapes want uniform batches.
    """

    def batch_reader():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


from paddle_tpu.reader.pipeline import DeviceLoader, PyReader  # noqa: F401,E402
