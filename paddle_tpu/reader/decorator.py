"""Reader decorators (reference: python/paddle/reader/decorator.py:82-360).

A reader is a zero-arg callable returning an iterator of samples. Decorators
compose: shuffle, buffered (background-thread prefetch), batch, chain,
compose, map_readers, xmap (multi-thread transform), cache, firstn.
"""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading
import time
from typing import List

from paddle_tpu import monitor as _monitor


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)

    return reader


def shuffle(reader, buf_size: int):
    """(reference: decorator.py:82)"""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return data_reader


def chain(*readers):
    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers, check_alignment: bool = True):
    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        iterator = zip(*rs) if check_alignment else itertools.zip_longest(*rs)
        for outputs in iterator:
            yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader, size: int):
    """Background-thread prefetch (reference: decorator.py buffered) — the
    host half of double-buffering; device prefetch is reader/pipeline.py.

    A producer exception is captured and re-raised in the consumer (the
    ``finally: put(_End)`` still unblocks it first, so propagation is
    bounded by one queue drain, never a hang). With telemetry on, queue
    depth and producer/consumer waits feed the input-pipeline
    instruments (``pt_reader_queue_depth{site="buffered"}``,
    ``pt_reader_wait_seconds``) and the boundedness verdict."""

    class _End:
        pass

    def data_reader():
        q: queue.Queue = queue.Queue(maxsize=size)
        failure: List[BaseException] = []

        def worker():
            try:
                for d in reader():
                    _monitor.timed_put(q, d, "buffered")
            except BaseException as e:  # re-raised by the consumer —
                failure.append(e)       # never a silently short epoch
            finally:
                q.put(_End)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            e = _monitor.timed_get(q, "buffered")
            if e is _End:
                if failure:
                    raise failure[0]
                break
            yield e

    return data_reader


def firstn(reader, n: int):
    def data_reader():
        for i, item in enumerate(reader()):
            if i >= n:
                break
            yield item

    return data_reader


def cache(reader):
    all_data: List = []
    filled = [False]

    def data_reader():
        if not filled[0]:
            all_data.extend(reader())
            filled[0] = True
        yield from all_data

    return data_reader


def xmap_readers(mapper, reader, process_num: int, buffer_size: int,
                 order: bool = False):
    """Multi-thread sample transform (reference: decorator.py xmap_readers).
    ``order=True`` preserves input order via sequence numbers.

    A raising ``mapper`` (or source reader) posts an error sentinel
    before its end marker, and the consumer re-raises on the NEXT get —
    bounded-time propagation in both modes. Without it, a dead worker
    never posts ``_End`` so the consumer blocks forever, and ordered
    mode additionally hangs on the sequence gap the lost sample leaves.
    Telemetry feeds ``pt_reader_queue_depth{site="xmap_in"/"xmap_out"}``
    and the producer/consumer wait histograms."""

    class _End:
        pass

    class _Err:
        def __init__(self, exc: BaseException):
            self.exc = exc

    def data_reader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)

        def feeder():
            try:
                for i, s in enumerate(reader()):
                    _monitor.timed_put(in_q, (i, s), "xmap_in")
            except BaseException as e:  # source reader failed: surface
                out_q.put(_Err(e))      # it in the consumer
            finally:
                for _ in range(process_num):
                    in_q.put(_End)

        def worker():
            while True:
                item = in_q.get()
                if item is _End:
                    out_q.put(_End)
                    break
                i, s = item
                try:
                    mapped = mapper(s)
                except BaseException as e:
                    # error BEFORE the end marker: the consumer raises
                    # on its next get instead of waiting out a sequence
                    # gap / missing _End forever
                    out_q.put(_Err(e))
                    out_q.put(_End)
                    break
                _monitor.timed_put(out_q, (i, mapped), "xmap_out")

        threading.Thread(target=feeder, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=worker, daemon=True).start()

        def _next():
            item = _monitor.timed_get(out_q, "xmap_out")
            if isinstance(item, _Err):
                raise item.exc
            return item

        ended = 0
        if not order:
            while ended < process_num:
                item = _next()
                if item is _End:
                    ended += 1
                    continue
                yield item[1]
            return
        pending = {}
        next_idx = 0
        while ended < process_num or pending:
            if next_idx in pending:
                yield pending.pop(next_idx)
                next_idx += 1
                continue
            if ended == process_num:
                # every worker ended yet the next sequence number never
                # arrived: a sample was lost without an error sentinel
                raise RuntimeError(
                    f"xmap_readers(order=True): sequence gap at sample "
                    f"{next_idx} ({len(pending)} later samples buffered)")
            item = _next()
            if item is _End:
                ended += 1
                continue
            i, mapped = item
            pending[i] = mapped

    return data_reader


def multiprocess_reader(readers, use_pipe: bool = True,
                        queue_size: int = 1000):
    """Run each reader in its own OS process, interleaving their samples
    (reference: decorator.py multiprocess_reader — fork + pipe/queue).

    Worker processes only iterate their reader and enqueue samples, so
    they never touch the TPU runtime (forking after accelerator init is
    the thing to avoid; plain data readers are safe). Samples must be
    picklable. ``use_pipe`` is accepted for API parity; both modes use a
    multiprocessing queue here.

    Messages are tagged tuples so any sample payload works; a worker
    exception is re-raised in the consumer (truncated silent epochs are
    the reference's failure mode too — it forwards an error sentinel);
    a worker killed without cleanup (OOM/SIGKILL) is detected by a
    liveness poll instead of hanging the training loop.
    """
    if not isinstance(readers, (list, tuple)) or not readers:
        raise ValueError("multiprocess_reader needs a non-empty reader list")

    def data_reader():
        import multiprocessing as mp
        import queue as _queue

        ctx = mp.get_context("fork")
        q = ctx.Queue(queue_size)

        def worker(r):
            try:
                for sample in r():
                    q.put(("data", sample))
                q.put(("end", None))
            except BaseException as e:  # propagated to the consumer
                q.put(("error", repr(e)))

        procs = [
            ctx.Process(target=worker, args=(r,), daemon=True)
            for r in readers
        ]
        for p in procs:
            p.start()
        ended = 0
        try:
            while ended < len(readers):
                # gate snapshotted across the wait: a runtime telemetry
                # flip mid-get must not record perf_counter() - 0.0
                obs = _monitor.enabled()
                t_wait0 = time.perf_counter() if obs else 0.0
                while True:
                    try:
                        tag, payload = q.get(timeout=5.0)
                        break
                    except _queue.Empty:
                        if not any(p.is_alive() for p in procs):
                            raise RuntimeError(
                                "multiprocess_reader: worker process died "
                                "without an end/error message (killed?)"
                            )
                if obs:
                    # the total blocked time, Empty-timeout polls included
                    _monitor.reader_wait("multiprocess", "consumer",
                                         time.perf_counter() - t_wait0)
                    try:
                        _monitor.reader_depth("multiprocess", q.qsize())
                    except NotImplementedError:  # qsize unsupported on
                        pass                     # some platforms (macOS)
                if tag == "end":
                    ended += 1
                elif tag == "error":
                    raise RuntimeError(
                        f"multiprocess_reader worker failed: {payload}"
                    )
                else:
                    yield payload
        finally:
            # early exit leaves workers blocked in q.put on the bounded
            # queue; terminate first so join doesn't stall per worker
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=5)

    return data_reader

