"""Host->device prefetch pipeline.

The TPU-native analog of the reference's reader-op stack: ``py_reader``
pushing into a C++ blocking queue plus the double-buffered device prefetch
(reference: operators/reader/create_py_reader_op.cc, buffered_reader.cc,
lod_tensor_blocking_queue.h). Here a background thread converts numpy
batches and issues ``jax.device_put`` ahead of consumption so the chip never
waits on the host (SURVEY.md section 7 hard part: infeed that doesn't starve
the chip).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, Sequence

import jax
import numpy as np

from paddle_tpu import monitor as _monitor


class DeviceLoader:
    """Iterate numpy batches with K-deep device-side prefetch."""

    def __init__(self, reader: Callable[[], Iterator], feed_names: Sequence[str],
                 depth: int = 2, sharding=None):
        self._reader = reader
        self._names = list(feed_names)
        self._depth = depth
        self._sharding = sharding

    def __iter__(self):
        q: queue.Queue = queue.Queue(maxsize=self._depth)
        END = object()
        failure = []

        def worker():
            try:
                for sample in self._reader():
                    if isinstance(sample, dict):
                        feed = {
                            k: jax.device_put(np.asarray(v), self._sharding)
                            for k, v in sample.items()
                        }
                    else:
                        feed = {
                            k: jax.device_put(np.asarray(v), self._sharding)
                            for k, v in zip(self._names, sample)
                        }
                    _monitor.timed_put(q, feed, "device_loader")
            except BaseException as e:  # surface in the consumer, not the
                failure.append(e)       # daemon thread's stderr
            finally:
                q.put(END)

        threading.Thread(target=worker, daemon=True).start()
        while True:
            # the consumer wait is THE input-bound signal: an empty
            # prefetch queue means the step loop outran the host
            # pipeline, and this wait weighs into the boundedness verdict
            item = _monitor.timed_get(q, "device_loader")
            if item is END:
                if failure:
                    raise RuntimeError(
                        "DeviceLoader reader thread failed"
                    ) from failure[0]
                return
            yield item


class PyReader:
    """API-compatible stand-in for the reference PyReader
    (reference: python/paddle/fluid/reader.py:42): decorate with a sample or
    batch reader, iterate feed dicts."""

    def __init__(self, feed_list=None, capacity: int = 2, use_double_buffer=True,
                 iterable: bool = True):
        self._feed_vars = list(feed_list or [])
        self._capacity = capacity
        self._batch_reader = None
        self._places = None

    def decorate_sample_list_generator(self, reader, places=None):
        self._batch_reader = reader
        self._places = places

    def decorate_batch_generator(self, reader, places=None):
        self._batch_reader = reader
        self._places = places

    def __iter__(self):
        from paddle_tpu.data_feeder import DataFeeder

        feeder = DataFeeder(self._feed_vars, place=self._places)
        loader = DeviceLoader(
            lambda: (feeder.feed(b) for b in self._batch_reader()),
            [v.name for v in self._feed_vars],
            depth=self._capacity,
        )
        return iter(loader)

    def start(self):
        pass

    def reset(self):
        pass
