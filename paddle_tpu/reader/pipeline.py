"""Host->device prefetch pipeline.

The TPU-native analog of the reference's reader-op stack: ``py_reader``
pushing into a C++ blocking queue plus the double-buffered device prefetch
(reference: operators/reader/create_py_reader_op.cc, buffered_reader.cc,
lod_tensor_blocking_queue.h). Here a background thread converts numpy
batches and issues ``jax.device_put`` ahead of consumption so the chip never
waits on the host (SURVEY.md section 7 hard part: infeed that doesn't starve
the chip).

Lifecycle: every ``DeviceLoader`` iteration owns a stop event. A consumer
that stops iterating early (a trainer exception, a plain ``break``) used to
leave the worker blocked forever on a full queue with up to ``depth``
device-resident batches pinned; now closing the generator (``GeneratorExit``
from GC or an explicit ``close()``) sets the stop event, the worker's put
loop observes it within one poll interval and exits, and the queue is
drained so nothing stays pinned.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, Optional, Sequence

import jax
import numpy as np

from paddle_tpu import faults as _faults
from paddle_tpu import monitor as _monitor

# chaos hook (faults.py): armed plans can fail or delay the prefetch
# worker's per-batch staging — raise(RESOURCE_EXHAUSTED ...) = infeed
# OOM drill (surfaces in the consumer with forensics), delay = a slow
# host pipeline driving the input_bound verdict
_F_PREFETCH = _faults.site("pipeline.prefetch")

class DeviceLoader:
    """Iterate numpy batches with K-deep device-side prefetch."""

    def __init__(self, reader: Callable[[], Iterator], feed_names: Sequence[str],
                 depth: int = 2, sharding=None):
        self._reader = reader
        self._names = list(feed_names)
        self._depth = depth
        self._sharding = sharding
        # latest iteration's (stop event, queue, worker thread) — close()
        # targets it; a new iteration stops the previous one first, so
        # re-iterating never leaks the old worker
        self._active: Optional[tuple] = None

    def close(self):
        """Stop the active iteration's worker (idempotent): sets the
        stop event and drains the queue so no device-resident batches
        stay pinned behind an abandoned consumer."""
        active, self._active = self._active, None
        if active is None:
            return
        stop, q, _thread = active
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass

    def __iter__(self):
        self.close()  # re-iteration must not leak the previous worker
        q: queue.Queue = queue.Queue(maxsize=self._depth)
        stop = threading.Event()
        END = object()
        failure = []
        _monitor.prefetch_depth(self._depth)

        def worker():
            try:
                for sample in self._reader():
                    if stop.is_set():
                        return
                    _F_PREFETCH.hit()
                    if isinstance(sample, dict):
                        feed = {
                            k: jax.device_put(np.asarray(v), self._sharding)
                            for k, v in sample.items()
                        }
                    else:
                        feed = {
                            k: jax.device_put(np.asarray(v), self._sharding)
                            for k, v in zip(self._names, sample)
                        }
                    if not _monitor.timed_put_stoppable(
                            q, feed, stop, "device_loader"):
                        return
            except BaseException as e:  # surface in the consumer, not the
                failure.append(e)       # daemon thread's stderr
            finally:
                _monitor.timed_put_stoppable(q, END, stop,
                                             "device_loader")

        thread = threading.Thread(target=worker, daemon=True,
                                  name="pt-device-loader")
        self._active = (stop, q, thread)
        thread.start()

        def gen():
            try:
                while True:
                    # the consumer wait is THE input-bound signal: an
                    # empty prefetch queue means the step loop outran the
                    # host pipeline, and this wait weighs into the
                    # boundedness verdict
                    item = _monitor.timed_get(q, "device_loader")
                    if item is END:
                        if failure:
                            exc = failure[0]
                            # an OOM in the prefetch worker (device_put
                            # of a batch) gets the same forensics as an
                            # executor-side OOM, attributed to the
                            # prefetch phase
                            _monitor.maybe_record_oom(exc,
                                                      phase="prefetch")
                            raise RuntimeError(
                                "DeviceLoader reader thread failed: "
                                f"{type(exc).__name__}: {exc}") from exc
                        return
                    yield item
            finally:
                # GeneratorExit (abandoned consumer) and normal
                # exhaustion both release the worker + pinned batches
                if self._active is not None and self._active[0] is stop:
                    self.close()
                else:
                    stop.set()

        return gen()


class PyReader:
    """API-compatible stand-in for the reference PyReader
    (reference: python/paddle/fluid/reader.py:42): decorate with a sample or
    batch reader, iterate feed dicts."""

    def __init__(self, feed_list=None, capacity: int = 2, use_double_buffer=True,
                 iterable: bool = True):
        self._feed_vars = list(feed_list or [])
        self._capacity = capacity
        self._batch_reader = None
        self._places = None
        self._loader: Optional[DeviceLoader] = None

    def decorate_sample_list_generator(self, reader, places=None):
        self._batch_reader = reader
        self._places = places

    def decorate_batch_generator(self, reader, places=None):
        self._batch_reader = reader
        self._places = places

    def __iter__(self):
        from paddle_tpu.data_feeder import DataFeeder

        # a previous iteration's worker must not leak: stop it before
        # starting the next (DeviceLoader.__iter__ also closes its own
        # prior iteration, but self._loader may be a different instance)
        self.reset()
        feeder = DataFeeder(self._feed_vars, place=self._places)
        self._loader = DeviceLoader(
            # assembly runs in the prefetch worker, OFF the verdict's
            # critical path — overlapped batch building must not count
            # into the input score (the consumer's queue wait does)
            lambda: (feeder.feed(b, critical_path=False)
                     for b in self._batch_reader()),
            [v.name for v in self._feed_vars],
            depth=self._capacity,
        )
        return iter(self._loader)

    def start(self):
        """The reference's explicit queue start: iteration starts the
        worker lazily here, so this only validates state."""
        if self._batch_reader is None:
            raise RuntimeError(
                "PyReader.start() before decorate_sample_list_generator/"
                "decorate_batch_generator — no reader to start")

    def reset(self):
        """Stop the active iteration's prefetch worker (the reference's
        queue reset). Safe to call with no iteration active."""
        loader, self._loader = self._loader, None
        if loader is not None:
            loader.close()
