"""Weight-decay regularizers (reference: python/paddle/fluid/regularizer.py)."""

from __future__ import annotations


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        from paddle_tpu.layers import nn

        decay = nn.scale(param, scale=self._coeff)
        return nn.elementwise_add(grad, decay)


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        from paddle_tpu.layers import nn

        sign = nn.elementwise_div(
            param, nn.elementwise_max(nn.abs(param),
                                      nn.fill_constant_like(param, 1e-12))
        )
        decay = nn.scale(sign, scale=self._coeff)
        return nn.elementwise_add(grad, decay)


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer


def append_regularization_ops(params_grads, regularization=None):
    out = []
    for p, g in params_grads:
        reg = getattr(p, "regularizer", None) or regularization
        if reg is None or g is None:
            out.append((p, g))
            continue
        new_g = reg(p, g, p.block)
        out.append((p, new_g))
    return out
