"""Unified retry/backoff policy for coordination-plane calls.

Replaces the ad-hoc fixed-sleep loops (fleet ``_connect_retry``'s 0.1 s
spin) with one policy object: exponential backoff with *decorrelated
jitter* (sleep_n drawn uniformly from [base, 3*sleep_{n-1}], capped —
the AWS-architecture variant that avoids thundering synchronized
retries across a fleet) under a hard *deadline budget*, so a retried
call fails at its deadline rather than after a fixed attempt count.

Per-site defaults come from the ``retry_base_delay_ms`` /
``retry_max_delay_ms`` / ``retry_max_attempts`` flags; callers pass a
deadline (usually their ``timeout_ms``) and the exception types worth
retrying. Every retry/give-up counts into
``pt_retry_total{site=,outcome=}`` (outcome: ``retry`` per re-attempt,
``success`` when a retried call eventually lands, ``exhausted`` when
the deadline/attempt budget runs out).

For deterministic tests, pass ``rng=random.Random(seed)`` and/or
monkeypatch ``retry._sleep``.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type

from paddle_tpu import flags as _flags
from paddle_tpu import monitor as _monitor

_M_RETRY = _monitor.counter(
    "pt_retry_total",
    "retry-policy events, by call site and outcome "
    "(retry / success-after-retry / exhausted)")

# monkeypatch point for deterministic tests (and the only sleep used)
_sleep = time.sleep

_DEFAULT_RETRY_ON: Tuple[Type[BaseException], ...] = (OSError, TimeoutError)


class Deadline:
    """One monotonic budget shared by the SEQUENTIAL calls of a logical
    operation — e.g. the checkpoint commit barrier collecting one ack
    per writer: each call takes ``remaining()`` as ITS timeout, so the
    operation as a whole honors the budget instead of each step getting
    the full budget afresh (N x timeout in the worst case)."""

    __slots__ = ("_at",)

    def __init__(self, budget_s: float):
        self._at = time.monotonic() + float(budget_s)

    def remaining(self) -> float:
        return max(0.0, self._at - time.monotonic())

    def remaining_ms(self) -> int:
        return int(self.remaining() * 1000)

    def expired(self) -> bool:
        return time.monotonic() >= self._at


class RetryPolicy:
    """Backoff parameters; stateless across calls (each ``call`` keeps
    its own attempt counter and sleep history)."""

    __slots__ = ("base_delay", "max_delay", "max_attempts", "retry_on")

    def __init__(
        self,
        base_delay: float = 0.1,
        max_delay: float = 5.0,
        max_attempts: int = 0,
        retry_on: Tuple[Type[BaseException], ...] = _DEFAULT_RETRY_ON,
    ):
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.max_attempts = int(max_attempts)  # 0 = deadline-only
        self.retry_on = retry_on

    def next_sleep(self, prev: Optional[float],
                   rng: Optional[random.Random] = None) -> float:
        """Decorrelated jitter: uniform in [base, 3*prev], capped."""
        if prev is None:
            return min(self.base_delay, self.max_delay)
        r = rng.uniform if rng is not None else random.uniform
        return min(self.max_delay, r(self.base_delay, prev * 3))


_default_policy: Optional[RetryPolicy] = None


def default_policy() -> RetryPolicy:
    """The flag-configured policy (rebuilt on flag change)."""
    global _default_policy
    if _default_policy is None:
        _default_policy = RetryPolicy(
            base_delay=_flags.get_flag("retry_base_delay_ms") / 1000.0,
            max_delay=_flags.get_flag("retry_max_delay_ms") / 1000.0,
            max_attempts=_flags.get_flag("retry_max_attempts"),
        )
    return _default_policy


def _invalidate_default(_value=None):
    global _default_policy
    _default_policy = None


for _name in ("retry_base_delay_ms", "retry_max_delay_ms",
              "retry_max_attempts"):
    _flags.watch_flag(_name, _invalidate_default)


def call(
    fn: Callable,
    *,
    site: str,
    policy: Optional[RetryPolicy] = None,
    deadline_s: Optional[float] = None,
    retry_on: Optional[Tuple[Type[BaseException], ...]] = None,
    rng: Optional[random.Random] = None,
    deadline_at: Optional[float] = None,
):
    """Run ``fn()`` under the retry policy.

    Retries exceptions in ``retry_on`` (default: the policy's) with
    backoff until EITHER the attempt cap is hit OR the deadline budget
    (``deadline_s`` seconds from now, or the absolute
    ``time.monotonic()`` instant ``deadline_at`` — pass the latter when
    ``fn`` checks the SAME deadline itself, so both sides agree to the
    tick; None = unbounded) is exceeded — then the last exception
    propagates. A first-try success is the no-overhead path: no sleep,
    no metric, no allocation here.
    """
    p = policy if policy is not None else default_policy()
    if retry_on is None:
        retry_on = p.retry_on
    deadline = deadline_at if deadline_at is not None else (
        time.monotonic() + deadline_s if deadline_s is not None else None)
    attempt = 0
    prev_sleep = None
    while True:
        try:
            result = fn()
        except retry_on as e:
            attempt += 1
            if p.max_attempts and attempt >= p.max_attempts:
                _M_RETRY.inc(labels={"site": site, "outcome": "exhausted"})
                raise
            prev_sleep = p.next_sleep(prev_sleep, rng)
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    _M_RETRY.inc(
                        labels={"site": site, "outcome": "exhausted"})
                    raise
                # never sleep past the deadline; the final attempt runs
                # with whatever budget is left
                prev_sleep = min(prev_sleep, remaining)
            _M_RETRY.inc(labels={"site": site, "outcome": "retry"})
            _sleep(prev_sleep)
            del e
        else:
            if attempt:
                _M_RETRY.inc(labels={"site": site, "outcome": "success"})
            return result
