"""Device-time roofline attribution: per-op HLO profiles, measured MFU,
and compute/memory-bound verdicts.

Every MFU number the bench suite prints is *analytic* — hand-derived
FLOP counts over wall time — and the time-attribution plane (monitor.py
step phases) stops at host-side phases: nothing says which HLO ops eat
the device, or whether they are compute- or memory-bound. This module
is the device half, the modern analog of the reference's CUPTI tracer +
timeline pair (reference: platform/device_tracer.cc + tools/timeline.py
— the seam profiler.py explicitly delegates to jax.profiler):

1. **Per-op device timings** — ``parse_xplane(dir)`` decodes the XSpace
   protobuf that ``jax.profiler`` writes (a self-contained wire-format
   reader: the tensorflow profiler protos are not a dependency) and
   aggregates per-HLO-op device seconds off the ``/device:*`` planes.
   No device plane (this CPU container), an empty/partial trace dir, or
   a parse failure all degrade to ``None`` with ONE warning — the
   profile then builds from the compile report instead
   (``source: "estimate"``), the same degrade contract as the compile
   report's guarded cost_analysis.

2. **HLO -> framework mapping** — ``classify_hlo`` buckets XLA op names
   into groups (matmul / elementwise / reduction / data_movement /
   collective / fusion / overhead) and ``map_to_framework_ops`` names
   the program ops that lower into each bucket via
   ``LoweredBlock.op_histogram`` — the per-op list the next kernel PR
   starts from.

3. **Roofline verdict + measured MFU** — joining device seconds with
   the compile report's cost_analysis flops/bytes gives arithmetic
   intensity; against the backend's ridge point
   (``peak_flops / peak_bytes_per_sec``, table in ``BACKEND_PEAKS``,
   overridable via the ``device_peak_*`` flags) the program is
   ``compute_bound`` (intensity >= ridge), ``memory_bound`` (below it),
   or ``overhead`` when it achieves under ``OVERHEAD_FRACTION`` of the
   roofline-permitted FLOP rate — neither roof is near, the time went
   to dispatch/latency. ``measured_mfu`` is achieved FLOP/s over
   ``peak_flops`` — the measured twin of the bench tables' analytic
   MFU.

The result is a versioned per-program **device profile**
(``DEVICE_PROFILE_FIELDS``) surfaced everywhere the existing planes
reach: the ``/profile`` monitor route, ``pt_program_mfu{program=}`` and
``pt_device_op_seconds{op=}`` instruments, a ``roofline`` section in
fleet digests (``/fleet`` shows per-rank MFU), a per-op device-time
annotation in ``debugger.pprint_program``, and a ``measured_mfu`` field
in bench rows beside the analytic one.

Sampling: the executor builds a profile every
``device_profile_every_n_steps`` phase-SAMPLED steps (the honest device
phase supplies the device seconds; with ``device_profile_xplane`` on it
additionally wraps the step in a jax.profiler trace). Off by default —
the disabled executor hot path is one boolean check, zero allocations.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

from paddle_tpu import flags as _flags
from paddle_tpu import monitor as _monitor

# ---------------------------------------------------------------------------
# backend peaks + ridge point
# ---------------------------------------------------------------------------

# Peak dense-matmul FLOP/s (bf16) per v5e chip — THE single definition;
# bench_common re-exports it for the analytic-MFU helper so the bench
# tables and the roofline verdicts share one denominator.
V5E_PEAK_BF16 = 197e12

# backend -> (peak FLOP/s, peak memory bytes/s). The ridge point
# (intensity where the compute and memory roofs meet) is their ratio:
# v5e ~240 FLOP/B. CPU numbers are rough single-socket defaults — on
# the CPU container the verdicts are still *ordered* correctly, and the
# device_peak_* flags override both for any specific part.
BACKEND_PEAKS: Dict[str, Tuple[float, float]] = {
    "tpu": (V5E_PEAK_BF16, 819e9),
    "gpu": (989e12, 3.35e12),   # H100 SXM bf16 dense / HBM3
    "cpu": (5e11, 5e10),
}


def backend_peaks(backend: Optional[str] = None) -> Tuple[float, float]:
    """(peak_flops, peak_bytes_per_sec) for ``backend`` (default: the
    current jax backend), honoring the ``device_peak_flops`` /
    ``device_peak_bytes_per_sec`` flag overrides."""
    if backend is None:
        import jax

        backend = jax.default_backend()
    pf, pb = BACKEND_PEAKS.get(str(backend), BACKEND_PEAKS["cpu"])
    f = float(_flags.get_flag("device_peak_flops"))
    b = float(_flags.get_flag("device_peak_bytes_per_sec"))
    return (f if f > 0 else pf), (b if b > 0 else pb)


# Below this fraction of the roofline-permitted FLOP rate the verdict is
# "overhead": the program reaches neither roof, the time went to
# dispatch / latency / launch gaps rather than compute or bandwidth.
OVERHEAD_FRACTION = 1 / 3


# ---------------------------------------------------------------------------
# xplane parsing (self-contained protobuf wire reader)
# ---------------------------------------------------------------------------

# XSpace wire schema (tensorflow/tsl profiler protos; stable since 2020
# — the fields read here have never been renumbered):
#   XSpace.planes = 1;  XPlane.name = 2, .lines = 3, .event_metadata = 4
#   (map<int64, XEventMetadata>: key = 1, value = 2; XEventMetadata.name
#   = 2);  XLine.name = 2, .events = 4;  XEvent.metadata_id = 1,
#   .duration_ps = 3.


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7
        if shift > 70:
            raise ValueError("varint overrun")


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over one message's bytes."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        fnum, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v, i = buf[i:i + 4], i + 4
        elif wt == 1:
            v, i = buf[i:i + 8], i + 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        if i > n:
            raise ValueError("truncated message")
        yield fnum, wt, v


def _parse_plane(buf: bytes):
    """(name, {metadata_id: event_name},
    [(line_name, [(metadata_id, duration_ps), ...]), ...])."""
    name = ""
    meta: Dict[int, str] = {}
    lines: List[Tuple[str, List[Tuple[int, int]]]] = []
    for fnum, _wt, v in _fields(buf):
        if fnum == 2:
            name = v.decode(errors="replace")
        elif fnum == 4:  # event_metadata map entry
            mid, mname = None, ""
            for f2, _w2, v2 in _fields(v):
                if f2 == 1:
                    mid = v2
                elif f2 == 2:  # XEventMetadata
                    for f3, _w3, v3 in _fields(v2):
                        if f3 == 2:
                            mname = v3.decode(errors="replace")
            if mid is not None:
                meta[mid] = mname
        elif fnum == 3:  # XLine
            line_name = ""
            events: List[Tuple[int, int]] = []
            for f2, _w2, v2 in _fields(v):
                if f2 == 2:
                    line_name = v2.decode(errors="replace")
                elif f2 == 4:  # XEvent
                    mid = dur_ps = 0
                    for f3, _w3, v3 in _fields(v2):
                        if f3 == 1:
                            mid = v3
                        elif f3 == 3:
                            dur_ps = v3
                    events.append((mid, dur_ps))
            lines.append((line_name, events))
    return name, meta, lines


# A TPU device plane carries SEVERAL lines covering the same wall
# interval at different granularities ("XLA Modules" > "XLA Ops" >
# "Steps" / "XLA TraceMe"): summing them all would double- or
# triple-count every interval. The op-level line is the one this plane
# attributes; when no line carries that name (GPU stream lines are
# unnamed-per-stream kernel rows), every line EXCEPT the known
# coarser/annotation rows is aggregated.
OP_LINE_NAME = "XLA Ops"
EXCLUDED_LINES = ("XLA Modules", "Steps", "XLA TraceMe",
                  "Framework Ops", "Source code", "SparseCoreOps")


def _select_op_lines(lines):
    ops_lines = [ev for name, ev in lines if OP_LINE_NAME in name]
    if ops_lines:
        return ops_lines
    return [ev for name, ev in lines
            if not any(name.startswith(x) for x in EXCLUDED_LINES)]


def _xplane_files(path: str) -> List[str]:
    if os.path.isfile(path):
        return [path]
    found = []
    for root, _dirs, files in os.walk(path):
        for f in files:
            if f.endswith(".xplane.pb"):
                found.append(os.path.join(root, f))
    return sorted(found)


def _parse_capture(path: str, warn: bool = True):
    """(per-op map, per-plane op-line totals) or None — the shared
    reader behind parse_xplane/profile_from_xplane. Per-op seconds sum
    WORK across every ``/device:*`` plane; the plane totals let the
    profile take the MAX as its wall-clock device interval (concurrent
    devices overlap in time — summing them would report an 8-chip step
    as 8x its wall device time and deflate measured MFU by 8x)."""
    files = _xplane_files(path)
    if not files:
        if warn:
            warnings.warn(
                f"no .xplane.pb under {path!r}; device profile degrades "
                f"to source=\"estimate\"", RuntimeWarning, stacklevel=3)
        return None
    ops: Dict[str, Dict[str, float]] = {}
    plane_totals: List[float] = []
    try:
        for f in files:
            with open(f, "rb") as fh:
                buf = fh.read()
            for fnum, _wt, v in _fields(buf):
                if fnum != 1:  # XSpace.planes
                    continue
                name, meta, lines = _parse_plane(v)
                if "/device:" not in name:
                    continue
                total = 0.0
                for events in _select_op_lines(lines):
                    for mid, dur_ps in events:
                        op = meta.get(mid, f"op#{mid}")
                        cell = ops.get(op)
                        if cell is None:
                            cell = ops[op] = {"seconds": 0.0,
                                              "count": 0}
                        cell["seconds"] += dur_ps / 1e12
                        cell["count"] += 1
                        total += dur_ps / 1e12
                plane_totals.append(total)
    except (ValueError, OSError, IndexError) as e:
        if warn:
            warnings.warn(
                f"xplane parse of {path!r} failed ({type(e).__name__}: "
                f"{e}); device profile degrades to source=\"estimate\"",
                RuntimeWarning, stacklevel=3)
        return None
    if not plane_totals:
        if warn:
            warnings.warn(
                f"xplane capture under {path!r} has no /device:* plane "
                f"(backend without device tracing, e.g. CPU); device "
                f"profile degrades to source=\"estimate\"",
                RuntimeWarning, stacklevel=3)
        return None
    return ops, plane_totals


def parse_xplane(path: str,
                 warn: bool = True) -> Optional[Dict[str, Dict[str, float]]]:
    """Aggregate per-op device seconds from a jax.profiler capture.

    ``path``: a trace dir (searched recursively for ``*.xplane.pb`` —
    the layout ``jax.profiler.start_trace`` writes) or one ``.pb``
    file. Returns ``{op_name: {"seconds", "count"}}`` summed over every
    ``/device:*`` plane, or ``None`` — with exactly ONE warning — when
    the capture is unavailable: no file, a truncated/corrupt proto, or
    no device plane at all (the CPU container's trace has only host
    planes). Callers then take the ``source: "estimate"`` path
    (``warn=False`` suppresses the warning: the executor's sampling
    loop warns once per process, not once per sampled step)."""
    parsed = _parse_capture(path, warn=warn)
    return None if parsed is None else parsed[0]


# ---------------------------------------------------------------------------
# HLO op classification + framework mapping
# ---------------------------------------------------------------------------

# HLO opcode prefix -> group. Keys are matched against the op name with
# its %-sigil and trailing ".<n>"/digit suffix stripped.
HLO_GROUPS: Dict[str, str] = {}
for _g, _names in (
    ("matmul", ("dot", "dot-general", "convolution", "cublas-gemm",
                "triton-gemm", "custom-call-gemm")),
    ("elementwise", ("add", "subtract", "multiply", "divide", "power",
                     "maximum", "minimum", "exponential", "exp", "log",
                     "tanh", "rsqrt", "sqrt", "negate", "abs", "sign",
                     "compare", "select", "and", "or", "not", "xor",
                     "convert", "clamp", "floor", "ceil", "round",
                     "sine", "cosine", "logistic", "remainder",
                     "shift-left", "shift-right-logical",
                     "shift-right-arithmetic", "rng", "rng-bit-generator",
                     "map")),
    ("reduction", ("reduce", "reduce-window", "sort", "argmax", "argmin",
                   "select-and-scatter", "topk")),
    ("data_movement", ("copy", "transpose", "reshape", "broadcast",
                       "slice", "dynamic-slice", "dynamic-update-slice",
                       "concatenate", "gather", "scatter", "pad", "iota",
                       "reverse", "bitcast", "bitcast-convert", "tuple",
                       "get-tuple-element", "constant", "parameter")),
    ("collective", ("all-reduce", "all-gather", "all-to-all",
                    "reduce-scatter", "collective-permute",
                    "collective-broadcast", "partition-id", "replica-id")),
    ("fusion", ("fusion", "loop_fusion", "input_fusion", "output_fusion",
                "while", "conditional", "call", "custom-call")),
    ("overhead", ("infeed", "outfeed", "copy-start", "copy-done", "send",
                  "send-done", "recv", "recv-done", "after-all",
                  "opt-barrier", "async-start", "async-done",
                  "async-update")),
):
    for _n in _names:
        HLO_GROUPS[_n] = _g

# group -> framework op types that lower into it (intersected with the
# program's actual op_histogram by map_to_framework_ops). An HLO op can
# name several candidates — attribution is a shortlist, not a proof.
FRAMEWORK_GROUPS: Dict[str, Tuple[str, ...]] = {
    "matmul": ("matmul", "mul", "fc", "conv2d", "depthwise_conv2d",
               "conv2d_transpose", "sdpa", "flash_attention",
               "sequence_conv"),
    "elementwise": ("elementwise_add", "elementwise_sub",
                    "elementwise_mul", "elementwise_div", "relu",
                    "sigmoid", "tanh", "gelu", "scale", "dropout",
                    "cast", "sqrt", "square", "exp", "clip", "swish"),
    "reduction": ("reduce_sum", "reduce_mean", "reduce_max", "softmax",
                  "softmax_with_cross_entropy", "cross_entropy",
                  "layer_norm", "batch_norm", "mean", "pool2d", "topk"),
    "data_movement": ("reshape", "transpose", "concat", "split", "slice",
                      "lookup_table", "gather", "scatter", "stack",
                      "expand", "squeeze", "unsqueeze", "pad"),
    "collective": ("allreduce", "c_allreduce_sum", "c_allgather",
                   "c_reducescatter", "ring_attention", "pipe_send",
                   "pipe_recv"),
}


def classify_hlo(name: str) -> str:
    """Group an XLA/HLO op name: strips the ``%`` sigil and the
    ``.<uid>`` suffix, then looks the opcode up in ``HLO_GROUPS``.
    Async-pair opcodes (``all-reduce-start``/``-done``/``-update`` —
    modern XLA lowers collectives to these by default) fall back to
    their root opcode's group unless registered explicitly the way
    ``copy-start``/``copy-done`` are. Unknown opcodes -> ``"other"``."""
    base = name.lstrip("%").split(" ")[0]
    base = base.split(".")[0].rstrip("0123456789_")
    base = base or name
    group = HLO_GROUPS.get(base, HLO_GROUPS.get(base.lower()))
    if group is None:
        for suffix in ("-start", "-done", "-update"):
            if base.endswith(suffix):
                group = HLO_GROUPS.get(base[:-len(suffix)])
                break
    return group or "other"


def map_to_framework_ops(hlo_name: str,
                         op_histogram: Optional[Dict[str, int]]
                         ) -> List[str]:
    """Framework op types (from the program's lowering histogram) that
    plausibly lowered into ``hlo_name``'s group — the shortlist a
    kernel PR starts from. Empty when the histogram has no candidate
    (or none was supplied)."""
    if not op_histogram:
        return []
    group = classify_hlo(hlo_name)
    cands = FRAMEWORK_GROUPS.get(group, ())
    return sorted(op for op in cands if op in op_histogram)


# ---------------------------------------------------------------------------
# device-profile schema
# ---------------------------------------------------------------------------

DEVICE_PROFILE_SCHEMA_VERSION = 1

ROOFLINE_VERDICTS = ("compute_bound", "memory_bound", "overhead",
                     "unknown")

# field name -> (accepted types, required, doc); the per-program device
# profile served at /profile and embedded in fleet digests. Cost fields
# are null when the compile report had none; per-op seconds are null on
# the estimate path. Bump the version on any incompatible change.
DEVICE_PROFILE_FIELDS: Dict[str, tuple] = {
    "v": ((int,), True,
          "schema version (DEVICE_PROFILE_SCHEMA_VERSION)"),
    "ts": ((float, int), True, "wall-clock unix timestamp of the sample"),
    "program": ((str,), True, "program id ('program<uid>')"),
    "program_uid": ((int,), True, "Program._uid of the profiled program"),
    "source": ((str,), True,
               "'xplane' (per-op device timings parsed from a "
               "jax.profiler capture) or 'estimate' (compile-report-"
               "derived: no per-op seconds, device time from the "
               "executor's measured device phase)"),
    "backend": ((str,), True, "jax backend the sample ran on"),
    "steps": ((int,), True, "executor steps covered by the sample"),
    "device_seconds": ((float, int, type(None)), True,
                       "wall-clock device time over the sample: the "
                       "MAX per-device-plane op-line total on the "
                       "xplane path (concurrent devices overlap in "
                       "time; per-op seconds/shares aggregate WORK "
                       "across devices), or the executor's measured "
                       "device phase on the estimate path"),
    "wall_seconds": ((float, int, type(None)), True,
                     "host wall time of the sampled call (null when "
                     "the caller supplied only device time)"),
    "flops": ((float, int, type(None)), True,
              "total XLA cost-analysis flops over the sample (compile "
              "report flops x steps); null without a report"),
    "bytes_accessed": ((float, int, type(None)), True,
                       "total XLA cost-analysis bytes accessed over "
                       "the sample; null without a report"),
    "peak_flops": ((float, int), True,
                   "peak device FLOP/s the verdict is scored against"),
    "peak_bytes_per_sec": ((float, int), True,
                           "peak device memory bandwidth the verdict "
                           "is scored against"),
    "ridge_intensity": ((float, int), True,
                        "ridge point (peak_flops / peak_bytes_per_sec, "
                        "FLOP/B): programs above it can be compute-"
                        "bound, below it the memory roof caps them"),
    "intensity": ((float, int, type(None)), True,
                  "arithmetic intensity (flops / bytes_accessed, "
                  "FLOP/B); null without cost numbers"),
    "measured_mfu": ((float, int, type(None)), True,
                     "measured model-FLOPs utilization: achieved "
                     "FLOP/s over peak_flops — the measured twin of "
                     "the bench tables' analytic MFU"),
    "verdict": ((str,), True,
                "roofline verdict: 'compute_bound' (intensity >= "
                "ridge), 'memory_bound' (below it), 'overhead' "
                "(achieved under OVERHEAD_FRACTION of the roofline-"
                "permitted rate — neither roof is near), 'unknown' "
                "(no cost numbers)"),
    "top_ops": ((list,), True,
                "top-K ops by device seconds: [{name, group, seconds, "
                "count, share, framework_ops}]; on the estimate path "
                "the op_histogram's types with null seconds"),
    "groups": ((dict,), True,
               "per-group device-time rollup: group -> {seconds, "
               "share, count} (empty on the estimate path)"),
}


def validate_device_profile(rec: Dict[str, Any]):
    """Raise ValueError unless ``rec`` conforms to
    DEVICE_PROFILE_FIELDS."""
    _monitor._validate_fields(rec, DEVICE_PROFILE_FIELDS,
                              DEVICE_PROFILE_SCHEMA_VERSION,
                              "device profile")
    if rec["source"] not in ("xplane", "estimate"):
        raise ValueError(
            f"device profile source {rec['source']!r} not in "
            f"('xplane', 'estimate')")
    if rec["verdict"] not in ROOFLINE_VERDICTS:
        raise ValueError(
            f"device profile verdict {rec['verdict']!r} not in "
            f"{ROOFLINE_VERDICTS}")


# ---------------------------------------------------------------------------
# profile assembly
# ---------------------------------------------------------------------------

def _roofline_verdict(flops, bytes_accessed, device_seconds,
                      peak_flops, peak_bw) -> Tuple[Optional[float],
                                                    Optional[float], str]:
    """(intensity, measured_mfu, verdict) from the joined numbers."""
    intensity = None
    if flops and bytes_accessed:
        intensity = float(flops) / float(bytes_accessed)
    mfu = None
    if flops and device_seconds:
        mfu = (float(flops) / float(device_seconds)) / peak_flops
    if intensity is None:
        return intensity, mfu, "unknown"
    ridge = peak_flops / peak_bw
    verdict = "compute_bound" if intensity >= ridge else "memory_bound"
    if mfu is not None:
        # the roofline-permitted FLOP rate at this intensity; achieving
        # well under it means neither roof is the limiter
        permitted = min(peak_flops, intensity * peak_bw)
        if (float(flops) / float(device_seconds)) < (
                OVERHEAD_FRACTION * permitted):
            verdict = "overhead"
    return intensity, mfu, verdict


def _report_costs(program, compile_report, steps: int):
    """(flops_total, bytes_total) for ``steps`` executor steps from the
    program's compile report (fetched from monitor when not passed).
    A window report covers ``window_steps`` steps; a step report one."""
    rep = compile_report
    if rep is None and program is not None:
        rep = _monitor.compile_reports().get(f"program{program._uid}")
    if rep is None:
        return None, None, None
    per = rep.get("window_steps") or 1
    scale = float(steps) / float(per)
    flops = rep.get("flops")
    ba = rep.get("bytes_accessed")
    return (None if flops is None else float(flops) * scale,
            None if ba is None else float(ba) * scale, rep)


def build_device_profile(program, *, source: str,
                         op_seconds: Optional[Dict[str, Dict]] = None,
                         device_seconds: Optional[float] = None,
                         wall_seconds: Optional[float] = None,
                         steps: int = 1,
                         compile_report: Optional[Dict] = None,
                         op_histogram: Optional[Dict[str, int]] = None,
                         backend: Optional[str] = None) -> Dict[str, Any]:
    """Assemble one device profile (DEVICE_PROFILE_FIELDS).

    ``op_seconds`` (xplane source): ``parse_xplane``'s per-op map —
    ``device_seconds`` defaults to its sum. Estimate source: no per-op
    seconds; ``top_ops`` lists the op histogram's types (count-ordered)
    with null seconds so the shape is stable across sources."""
    if backend is None:
        import jax

        backend = jax.default_backend()
    peak_flops, peak_bw = backend_peaks(backend)
    if op_histogram is None and compile_report is not None:
        op_histogram = compile_report.get("op_histogram")
    flops, bytes_accessed, rep = _report_costs(
        program, compile_report, steps)
    if op_histogram is None and rep is not None:
        op_histogram = rep.get("op_histogram")
    top_k = max(int(_flags.get_flag("device_profile_top_k")), 1)
    groups: Dict[str, Dict[str, float]] = {}
    top_ops: List[Dict[str, Any]] = []
    if op_seconds:
        # shares are fractions of total device WORK (op seconds summed
        # across planes); device_seconds may be the smaller max-plane
        # wall interval on multi-device captures
        work = sum(c["seconds"] for c in op_seconds.values())
        if device_seconds is None:
            device_seconds = work
        total = work or 1.0
        for name, cell in op_seconds.items():
            g = classify_hlo(name)
            cell_g = groups.get(g)
            if cell_g is None:
                cell_g = groups[g] = {"seconds": 0.0, "share": 0.0,
                                      "count": 0}
            cell_g["seconds"] += cell["seconds"]
            cell_g["count"] += int(cell["count"])
        for g in groups.values():
            g["share"] = g["seconds"] / total
        ranked = sorted(op_seconds.items(),
                        key=lambda kv: -kv[1]["seconds"])[:top_k]
        top_ops = [{
            "name": name,
            "group": classify_hlo(name),
            "seconds": cell["seconds"],
            "count": int(cell["count"]),
            "share": cell["seconds"] / total,
            "framework_ops": map_to_framework_ops(name, op_histogram),
        } for name, cell in ranked]
    elif op_histogram:
        top_ops = [{
            "name": op, "group": "framework", "seconds": None,
            "count": int(n), "share": None, "framework_ops": [op],
        } for op, n in sorted(op_histogram.items(),
                              key=lambda kv: -kv[1])[:top_k]]
    intensity, mfu, verdict = _roofline_verdict(
        flops, bytes_accessed, device_seconds, peak_flops, peak_bw)
    return {
        "v": DEVICE_PROFILE_SCHEMA_VERSION,
        "ts": time.time(),
        "program": f"program{program._uid}" if program is not None
                   else "program?",
        "program_uid": int(program._uid) if program is not None else -1,
        "source": source,
        "backend": str(backend),
        "steps": int(steps),
        "device_seconds": device_seconds,
        "wall_seconds": wall_seconds,
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "peak_flops": peak_flops,
        "peak_bytes_per_sec": peak_bw,
        "ridge_intensity": peak_flops / peak_bw,
        "intensity": intensity,
        "measured_mfu": mfu,
        "verdict": verdict,
        "top_ops": top_ops,
        "groups": groups,
    }


def profile_from_xplane(trace_dir: str, program, *,
                        steps: int = 1,
                        wall_seconds: Optional[float] = None,
                        device_seconds: Optional[float] = None,
                        compile_report: Optional[Dict] = None,
                        op_histogram: Optional[Dict[str, int]] = None,
                        record: bool = True,
                        warn: bool = True) -> Dict[str, Any]:
    """Build (and by default record) a device profile from a
    jax.profiler capture under ``trace_dir``. An unavailable capture
    (see ``parse_xplane``) degrades to the estimate path — the profile
    still builds, with ``source: "estimate"`` and ``device_seconds``
    falling back to the caller's measured value. On a multi-device
    capture the profile's ``device_seconds`` is the max per-plane
    total (devices run concurrently), while per-op seconds aggregate
    work across every plane."""
    parsed = _parse_capture(trace_dir, warn=warn)
    if parsed and parsed[0]:
        ops, plane_totals = parsed
        prof = build_device_profile(
            program, source="xplane", op_seconds=ops,
            device_seconds=max(plane_totals),
            wall_seconds=wall_seconds, steps=steps,
            compile_report=compile_report, op_histogram=op_histogram)
    else:
        prof = build_device_profile(
            program, source="estimate", device_seconds=device_seconds,
            wall_seconds=wall_seconds, steps=steps,
            compile_report=compile_report, op_histogram=op_histogram)
    if record:
        record_profile(prof)
    return prof


def estimate_profile(program, *, device_seconds: Optional[float],
                     steps: int = 1,
                     wall_seconds: Optional[float] = None,
                     compile_report: Optional[Dict] = None,
                     op_histogram: Optional[Dict[str, int]] = None,
                     record: bool = True) -> Dict[str, Any]:
    """The documented degrade path, callable directly (the bench rows
    use it: measured window seconds + the compile report's flops):
    compile-report-derived profile, ``source: "estimate"``."""
    prof = build_device_profile(
        program, source="estimate", device_seconds=device_seconds,
        wall_seconds=wall_seconds, steps=steps,
        compile_report=compile_report, op_histogram=op_histogram)
    if record:
        record_profile(prof)
    return prof


# ---------------------------------------------------------------------------
# recording + instruments + /profile
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
# program id -> latest profile; insertion-ordered, bounded like the
# compile-report buffer
_PROFILES: Dict[str, Dict[str, Any]] = {}
MAX_PROFILES = 32

_M_PROFILES = _monitor.counter(
    "pt_device_profiles_total",
    "device profiles recorded by the roofline plane, by source "
    "(xplane/estimate)")
_M_MFU = _monitor.gauge(
    "pt_program_mfu",
    "measured model-FLOPs utilization of the latest device profile, "
    "by program (achieved cost-analysis FLOP/s over the backend peak)")
_M_OP_SECONDS = _monitor.gauge(
    "pt_device_op_seconds",
    "device seconds of the MOST RECENTLY recorded profile's top-K ops, "
    "by op (xplane source only; cells are replaced wholesale on each "
    "profile, so the top-K cap bounds label cardinality — HLO names "
    "carry per-compile uid suffixes and would otherwise accrete "
    "forever)")


def record_profile(profile: Dict[str, Any]):
    """Store a device profile: bounded per-program buffer (the /profile
    route), mirrored into pt_program_mfu / pt_device_op_seconds. Never
    raises — telemetry must not fail a step."""
    try:
        prog = profile.get("program", "?")
        with _LOCK:
            _PROFILES.pop(prog, None)
            _PROFILES[prog] = profile
            while len(_PROFILES) > MAX_PROFILES:
                _PROFILES.pop(next(iter(_PROFILES)))
        _M_PROFILES.inc(labels={"source": profile.get("source", "?")})
        if profile.get("measured_mfu") is not None:
            _M_MFU.set(profile["measured_mfu"], labels={"program": prog})
        timed = [op for op in profile.get("top_ops", ())
                 if op.get("seconds") is not None]
        # the gauge mirrors ONE profile at a time: the atomic swap
        # keeps cardinality at top-K and stale ops (dead compiles,
        # other programs) out of scrapes — and a concurrent scrape
        # never sees a half-replaced set. An untimed profile (the
        # estimate path, e.g. xplane capture started failing mid-run)
        # EMPTIES the gauge: serving the last successful capture's op
        # mix next to a fresh pt_program_mfu would misattribute it.
        _M_OP_SECONDS.replace(
            ({"op": op["name"]}, op["seconds"]) for op in timed)
    except Exception as e:
        warnings.warn(f"device profile dropped: {e!r}", RuntimeWarning)


def profiles() -> Dict[str, Dict[str, Any]]:
    """Latest device profile per program (insertion order = sample
    order, oldest first)."""
    with _LOCK:
        return {k: dict(v) for k, v in _PROFILES.items()}


def latest(program=None) -> Optional[Dict[str, Any]]:
    """The most recent profile (a copy) — for ``program`` when given,
    else the newest overall."""
    with _LOCK:
        if program is not None:
            prof = _PROFILES.get(f"program{program._uid}")
        elif _PROFILES:
            prof = _PROFILES[next(reversed(_PROFILES))]
        else:
            prof = None
        return dict(prof) if prof is not None else None


def summary() -> Dict[str, Any]:
    """The /profile route body: latest profile per program plus the
    peaks the verdicts were scored against."""
    peak_flops, peak_bw = None, None
    try:
        peak_flops, peak_bw = backend_peaks()
    except Exception:
        pass
    return {
        "profiles": profiles(),
        "peak_flops": peak_flops,
        "peak_bytes_per_sec": peak_bw,
    }


def digest_section() -> Optional[Dict[str, Any]]:
    """Compact per-program roofline rollup for the fleet digest (the
    ``roofline`` section /fleet renders per rank): measured MFU, verdict
    and source only — profiles stay KV-sized. None when no profile has
    been recorded (the field is optional in the digest schema)."""
    with _LOCK:
        if not _PROFILES:
            return None
        return {prog: {"measured_mfu": p.get("measured_mfu"),
                       "verdict": p.get("verdict"),
                       "source": p.get("source")}
                for prog, p in _PROFILES.items()}


def reset():
    """Test isolation (called from monitor.reset)."""
    global _cap_warned, _parse_warned
    with _LOCK:
        _PROFILES.clear()
        _sample_counts.clear()
    _cap_warned = False
    _parse_warned = False


# ---------------------------------------------------------------------------
# executor sampling hooks
# ---------------------------------------------------------------------------

# cached hot flag values — the disabled executor hot path is one
# function call reading one int (plus monitor's telemetry boolean)
_every = 0
_xplane_on = False


def _sync_every(value):
    global _every
    _every = int(value)


def _sync_xplane(value):
    global _xplane_on
    _xplane_on = bool(value)


_flags.watch_flag("device_profile_every_n_steps", _sync_every)
_flags.watch_flag("device_profile_xplane", _sync_xplane)

_cap_warned = False
_parse_warned = False


def active() -> bool:
    """Whether executors should sample device profiles (telemetry on
    and ``device_profile_every_n_steps`` > 0)."""
    return _every > 0 and _monitor.enabled()


# PER-PROGRAM phase-sampled-step counters; counter-based (not
# absolute-step modulo) so the cadence is literally "every Nth
# phase-sampled step" — a modulo over the absolute index would need
# the step to divide BOTH periods and silently stretch the cadence to
# lcm(step_phases_every_n, device_profile_every_n_steps). Per program
# (not one process-global counter) because interleaved programs whose
# call pattern shares parity with the period would otherwise starve
# each other: train/eval alternating with _every=2 would profile the
# train program on every even count and the eval program NEVER.
# Bounded like _PROFILES (insertion-ordered, oldest evicted).
_sample_counts: Dict[int, int] = {}


def take_sample(program=None) -> bool:
    """Executor gate, called once per phase-SAMPLED step/window of
    ``program``: True on every ``device_profile_every_n_steps``-th
    call for that program (the first call profiles immediately, so
    warmup is visible). Returns False — and advances nothing — while
    the plane is off."""
    if _every <= 0 or not _monitor.enabled():
        return False
    uid = int(program._uid) if program is not None else -1
    with _LOCK:
        count = _sample_counts.pop(uid, 0)
        _sample_counts[uid] = count + 1  # re-insert: LRU refresh
        while len(_sample_counts) > MAX_PROFILES:
            _sample_counts.pop(next(iter(_sample_counts)))
    return count % _every == 0


class _Capture:
    """One armed xplane capture around a sampled step (executor use).
    ``stop()`` is idempotent and never raises; a failed start/stop
    degrades the step to the estimate path with one warning per
    process."""

    __slots__ = ("dir", "started")

    def __init__(self):
        self.dir = tempfile.mkdtemp(prefix="pt_roofline_")
        self.started = False

    def stop(self) -> Optional[str]:
        if not self.started:
            self.cleanup()
            return None
        self.started = False
        try:
            import jax

            jax.profiler.stop_trace()
            return self.dir
        except Exception as e:
            _warn_capture_once(f"jax.profiler.stop_trace() failed: {e!r}")
            self.cleanup()
            return None

    def cleanup(self):
        shutil.rmtree(self.dir, ignore_errors=True)


def _warn_capture_once(msg: str):
    global _cap_warned
    if not _cap_warned:
        _cap_warned = True
        warnings.warn(
            f"device-profile xplane capture unavailable ({msg}); "
            f"profiles degrade to source=\"estimate\"", RuntimeWarning)


def begin_capture() -> Optional[_Capture]:
    """Arm an xplane capture for a sampled step (None when the
    ``device_profile_xplane`` flag is off or starting the trace fails
    — the step then profiles via the estimate path)."""
    if not _xplane_on:
        return None
    cap = _Capture()
    try:
        import jax

        jax.profiler.start_trace(cap.dir)
        cap.started = True
        return cap
    except Exception as e:
        _warn_capture_once(f"jax.profiler.start_trace() failed: {e!r}")
        cap.cleanup()
        return None


def note_step(program, lowered, *, steps: int = 1,
              device_s: Optional[float] = None,
              wall_s: Optional[float] = None,
              capture: Optional[_Capture] = None):
    """Executor hook: build + record this sampled step's device profile.
    Never raises. ``capture`` (an armed ``begin_capture`` handle) is
    stopped and parsed here; without one — or when the parse degrades —
    the profile is the compile-report-derived estimate with the
    executor's measured device phase as device time."""
    global _parse_warned
    try:
        hist = getattr(lowered, "op_histogram", None)
        trace_dir = capture.stop() if capture is not None else None
        if trace_dir is not None:
            try:
                prof = profile_from_xplane(
                    trace_dir, program, steps=steps,
                    wall_seconds=wall_s, device_seconds=device_s,
                    op_histogram=hist, warn=not _parse_warned)
                if prof.get("source") == "estimate":
                    # warn once per process, not once per sampled step
                    _parse_warned = True
            finally:
                capture.cleanup()
        else:
            estimate_profile(
                program, device_seconds=device_s, steps=steps,
                wall_seconds=wall_s, op_histogram=hist)
    except Exception as e:
        try:
            warnings.warn(f"device profile dropped: {e!r}",
                          RuntimeWarning)
        except Exception:
            pass
