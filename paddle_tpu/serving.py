"""Production inference serving plane: continuous batching over an
on-device KV cache.

Reference seam: the AnalysisPredictor C-API (inference.py) serves one
request batch per call; real serving traffic is a stream of requests of
different lengths arriving at different times. The reference framework
dedicates its ``inference_transpiler``/server layer to this; here the
serving plane is built on the pieces the training stack already proved:

- **Continuous batch assembly**: a bounded request queue feeds a fixed
  set of batch *slots*. Requests are admitted and evicted at token
  boundaries — one compiled single-token decode executable serves every
  mix of in-flight requests (no per-batch-shape recompiles, ever).
- **Prefill/decode split** (models/transformer.py ``build_prefill`` /
  ``build_decode_step``): admission runs the encoder once and writes the
  request's cross-attention K/V into slot-indexed, device-resident cache
  tensors; each decode step appends one self-attention K/V row per slot
  and emits one greedy token per slot. The cache rides the executor's
  donated-state path — it never round-trips through the host.
- **Async decode loop**: decode steps dispatch with ``async_fetch``
  (executor.LazyFetches), so step N's device->host token fetch
  materializes under step N+1's dispatch — the serving twin of the
  training pipeline's overlapped fetch.
- **Warm replica start**: engines sharing a geometry share program
  objects (transformer.build_serving), so the persistent compile cache
  (``compile_cache_dir`` flag) resolves a fresh replica's prefill +
  decode executables from disk — zero fresh XLA compiles at spin-up.
- **SLO plane for free**: ``pt_serve_*`` metrics (queue depth, tokens/s,
  TTFT + per-token latency histograms) ride the monitor registry; the
  live endpoint serves an engine summary at ``/serve``; chaos plans can
  arm ``serve.enqueue`` / ``serve.decode`` fault sites.

Deployable artifacts: an engine loads weights from a live Scope, a
Predictor, or a saved inference-model directory — including the int8 PTQ
artifact (``slim/calibration.py``), whose weights deploy dequantized
into the decode programs (weight-only int8: 4x smaller artifact, same
serving surface).
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu import faults as _faults
from paddle_tpu import flags as _flags
from paddle_tpu import monitor as _monitor
from paddle_tpu.executor import Executor, Scope, scope_guard
from paddle_tpu.framework import CPUPlace, TPUPlace

# --- telemetry (no-ops while the 'telemetry' flag is off) ---

_M_REQUESTS = _monitor.counter(
    "pt_serve_requests_total",
    "serving requests by terminal outcome (completed / length / "
    "expired / rejected / drained / error)")
_M_QUEUE_DEPTH = _monitor.gauge(
    "pt_serve_queue_depth", "requests waiting for a batch slot")
_M_SLOTS_ACTIVE = _monitor.gauge(
    "pt_serve_slots_active", "batch slots holding an in-flight request")
_M_PREFILLS = _monitor.counter(
    "pt_serve_prefill_total", "admissions (prefill program runs)")
_M_DECODE_STEPS = _monitor.counter(
    "pt_serve_decode_steps_total",
    "single-token decode steps (each serves every active slot)")
_M_TOKENS = _monitor.counter(
    "pt_serve_tokens_total", "tokens emitted across all requests")
_M_TOKEN_SECONDS = _monitor.histogram(
    "pt_serve_token_seconds",
    "per-token latency (decode-step dispatch -> token on host)",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5))
_M_TTFT_SECONDS = _monitor.histogram(
    "pt_serve_ttft_seconds",
    "time to first token (request submit -> first token on host)",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             30.0))
_M_ENGINE_STATE = _monitor.gauge(
    "pt_serve_engine_state",
    "per-engine lifecycle state by engine id: 0=serving, 1=draining, "
    "2=closed — a replica being rotated out is observable BEFORE its "
    "queue is torn down")

ENGINE_STATES = ("serving", "draining", "closed")
# engine id -> lifecycle state, bounded (closed engines age out so the
# /healthz payload and the gauge's label set stay small). Mutated by
# engine threads and iterated by the monitor server's handler threads:
# every access holds _ENGINE_STATE_LOCK.
_ENGINE_STATE_CAP = 32
_ENGINE_STATE_LOCK = threading.Lock()
_ENGINE_STATES: "collections.OrderedDict[int, str]" = \
    collections.OrderedDict()


def _note_engine_state(engine_id: int, state: str):
    with _ENGINE_STATE_LOCK:
        _ENGINE_STATES[engine_id] = state
        _ENGINE_STATES.move_to_end(engine_id)
        while len(_ENGINE_STATES) > _ENGINE_STATE_CAP:
            _ENGINE_STATES.popitem(last=False)
        snapshot = list(_ENGINE_STATES.items())
    # the gauge mirrors the bounded map wholesale (Gauge.replace, its
    # own atomic swap): engines aged out of the map drop their cells
    # too, so a process churning many short-lived engines never
    # accretes stale labels
    _M_ENGINE_STATE.replace(
        [({"engine": str(k)}, float(ENGINE_STATES.index(v)))
         for k, v in snapshot])


def engine_states() -> Dict[str, str]:
    """{engine id -> "serving" | "draining" | "closed"} for the
    /healthz monitor route: a serving replica's lifecycle is liveness
    information — a load balancer must stop routing to a draining
    engine before its queue disappears."""
    with _ENGINE_STATE_LOCK:
        return {str(k): v for k, v in _ENGINE_STATES.items()}

# chaos hooks (faults.py): a raise at serve.enqueue drills queue-path
# failures, a delay/raise at serve.decode drills a stalled/failed decode
# loop (the fault fires BEFORE the step dispatch, so device state stays
# consistent and the engine can keep serving after the drill)
_F_ENQUEUE = _faults.site("serve.enqueue")
_F_DECODE = _faults.site("serve.decode")

REQUEST_OUTCOMES = ("completed", "length", "expired", "rejected",
                    "drained", "error")


class QueueFull(RuntimeError):
    """submit() backpressure: the request queue is at serve_queue_depth."""


class EngineClosed(RuntimeError):
    """submit()/step() on a closed engine."""


class ServeRequest:
    """One in-flight generation request (handle returned by submit)."""

    # itertools.count: atomic under CPython — submit() is meant for
    # concurrent callers and ids must stay unique across threads
    _uid = itertools.count(1)

    def __init__(self, src_ids, src_pad, max_new_tokens, deadline_s):
        self.id = next(ServeRequest._uid)
        self.src_ids = src_ids
        self.src_pad = src_pad
        self.max_new_tokens = max_new_tokens
        self.submit_ts = time.perf_counter()
        self.deadline_ts = (self.submit_ts + deadline_s
                            if deadline_s else None)
        self.tokens: List[int] = []
        self.outcome: Optional[str] = None
        self.ttft_s: Optional[float] = None
        self._done = threading.Event()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request reaches a terminal outcome; returns
        the emitted tokens (EOS excluded)."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.id} not finished within {timeout}s")
        return list(self.tokens)

    def _finish(self, outcome: str):
        self.outcome = outcome
        _M_REQUESTS.inc(labels={"outcome": outcome})
        self._done.set()


def _load_weights_into(scope: Scope, weights) -> bool:
    """Install model weights into the engine's private scope. Accepts a
    Scope (weights COPIED — donation would otherwise delete buffers the
    source scope still references), a Predictor (its scope is the
    source), or a saved inference-model directory (fp32 or int8 PTQ
    artifact). Returns True when the int8 artifact path was taken."""
    from paddle_tpu import inference as _inference

    if isinstance(weights, _inference.Predictor):
        weights = weights.scope
    if isinstance(weights, Scope):
        for name in weights.var_names():
            scope.set(name, np.array(np.asarray(weights.find_var(name))))
        return False
    if isinstance(weights, str):
        if os.path.exists(os.path.join(weights, "__params_int8__.npz")):
            from paddle_tpu.slim.calibration import (
                load_int8_inference_model,
            )

            load_int8_inference_model(weights, None, scope=scope)
            return True
        from paddle_tpu import io as _io

        path = os.path.join(weights, _io._PARAMS_FILE)
        with np.load(path) as data:
            for name in data.files:
                scope.set(name, np.asarray(data[name]))
        return False
    raise TypeError(
        f"weights must be a Scope, Predictor or model dir, got "
        f"{type(weights).__name__}")


class _Slot:
    """Host-side view of one batch slot."""

    __slots__ = ("request",)

    def __init__(self):
        self.request: Optional[ServeRequest] = None


class ServingEngine:
    """Continuous-batching serving engine over the transformer zoo.

    One engine = one model + one batch geometry: ``slots`` concurrent
    requests, sources padded/bucketed to ``src_len``, at most
    ``max_len - 1`` generated tokens per request. ``submit()`` enqueues
    (with queue-depth backpressure and optional per-request deadlines);
    the caller drives ``step()`` — or ``run_until_idle()`` — to make
    progress; ``drain()`` stops admissions and finishes the in-flight
    set; ``close()`` drains and releases the compiled entries. The
    lifecycle (serving -> draining -> closed) is observable: ``state``
    here, ``pt_serve_engine_state`` on /metrics, and per-engine rows on
    the /healthz route (``engine_states``).
    """

    _eid = itertools.count(1)

    def __init__(self, cfg, weights, *, slots: int = 4, src_len: int = 32,
                 max_len: int = 32, bos_id: int = 0, end_id: int = 1,
                 place=None, queue_depth: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 pipeline_depth: int = 1):
        from paddle_tpu.models import transformer as _T

        if slots < 1:
            raise ValueError("need at least one batch slot")
        self.cfg = cfg
        self.slots = int(slots)
        self.src_len, self.max_len = int(src_len), int(max_len)
        self.bos_id, self.end_id = int(bos_id), int(end_id)
        self.queue_depth = (int(_flags.get_flag("serve_queue_depth"))
                            if queue_depth is None else int(queue_depth))
        default_deadline = (float(_flags.get_flag("serve_deadline_ms"))
                            if deadline_ms is None else float(deadline_ms))
        self.deadline_s = default_deadline / 1e3 if default_deadline else 0.0
        # 1 = double-buffered decode (step N's fetch materializes under
        # step N+1's dispatch); 0 = fully synchronous steps
        self.pipeline_depth = 1 if pipeline_depth else 0
        self._progs = _T.build_serving(cfg, self.slots, self.src_len,
                                       self.max_len, bos_id=self.bos_id,
                                       end_id=self.end_id)
        self.scope = Scope()
        self._exe = Executor(place if place is not None else CPUPlace()
                             if not _is_tpu_default() else TPUPlace(0))
        self.int8 = _load_weights_into(self.scope, weights)
        # device-resident serving state, zero-initialized (live=False
        # everywhere: every slot starts free)
        for name, (shape, dtype) in self._progs["state_specs"].items():
            self.scope.set(name, np.zeros(shape, dtype=np.dtype(dtype)))
        self._queue: "collections.deque[ServeRequest]" = collections.deque()
        self._slots = [_Slot() for _ in range(self.slots)]
        self._pending = None  # (LazyFetches, per-slot request snapshot, t0)
        self._lock = threading.Lock()
        self._draining = False
        self._closed = False
        self.decode_steps = 0
        self.tokens_emitted = 0
        self.completed = 0
        self.engine_id = next(ServingEngine._eid)
        _ENGINES.add(self)
        _note_engine_state(self.engine_id, "serving")

    # --- request intake ---

    def submit(self, src_ids: Sequence[int],
               src_pad: Optional[Sequence[float]] = None,
               max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> ServeRequest:
        """Enqueue a generation request. ``src_ids`` shorter than the
        engine's ``src_len`` is padded (mask derived); longer raises.
        Backpressure: raises QueueFull beyond ``serve_queue_depth``."""
        _F_ENQUEUE.hit()
        ids = np.asarray(src_ids, np.int64).reshape(-1)
        if ids.shape[0] > self.src_len:
            raise ValueError(
                f"source length {ids.shape[0]} exceeds the engine's "
                f"src_len {self.src_len}")
        if src_pad is None:
            pad = (np.arange(self.src_len) < ids.shape[0]).astype(
                np.float32)
        else:
            # accepted at either the request's own length or the
            # engine's full src_len (the training graph's mask shape)
            mask = np.asarray(src_pad, np.float32).reshape(-1)
            if mask.shape[0] == self.src_len:
                pad = mask
            elif mask.shape[0] == ids.shape[0]:
                pad = np.zeros(self.src_len, np.float32)
                pad[:ids.shape[0]] = mask
            else:
                raise ValueError(
                    f"src_pad length {mask.shape[0]} matches neither "
                    f"the source length {ids.shape[0]} nor the "
                    f"engine's src_len {self.src_len}")
        full = np.zeros(self.src_len, np.int64)
        full[:ids.shape[0]] = ids
        cap = self.max_len - 1
        if max_new_tokens is not None and int(max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        want = cap if max_new_tokens is None else min(int(max_new_tokens),
                                                     cap)
        deadline_s = (self.deadline_s if deadline_ms is None
                      else float(deadline_ms) / 1e3)
        req = ServeRequest(full, pad, want, deadline_s)
        with self._lock:
            # closed/draining re-checked under the SAME lock drain()
            # clears the queue with: a submit racing a drain must either
            # land before the sweep or raise, never enqueue onto an
            # engine nobody will step again
            if self._closed:
                raise EngineClosed("submit() on a closed engine")
            if self._draining:
                raise EngineClosed("submit() on a draining engine")
            if len(self._queue) >= self.queue_depth:
                req._finish("rejected")
                _publish_gauges()
                raise QueueFull(
                    f"serving queue at capacity ({self.queue_depth})")
            self._queue.append(req)
            _publish_gauges()
        return req

    # --- the scheduler tick ---

    def step(self) -> int:
        """One scheduler tick: resolve the previously dispatched decode
        step (handing tokens to their requests and freeing finished
        slots), admit queued requests into free slots (prefill), and
        dispatch the next single-token decode step. Returns the number
        of tokens handed out this tick."""
        if self._closed:
            raise EngineClosed("step() on a closed engine")
        emitted = self._process_ready()
        self._admit()
        self._dispatch()
        if self.pipeline_depth == 0:
            emitted += self._process_ready()
        return emitted

    def run_until_idle(self, max_steps: int = 100_000) -> int:
        """Drive step() until no request is queued or in flight; returns
        total tokens emitted. ``max_steps`` bounds a runaway loop."""
        total = 0
        for _ in range(max_steps):
            total += self.step()
            if not self.busy():
                break
        # resolve a still-pending final step
        total += self._process_ready()
        return total

    def busy(self) -> bool:
        with self._lock:
            queued = bool(self._queue)
        return (queued or self._pending is not None
                or any(s.request is not None for s in self._slots))

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful drain: stop admissions, finish the in-flight set.
        Queued-but-unadmitted requests finish with outcome 'drained'.
        Returns True when everything settled inside ``timeout_s``."""
        with self._lock:
            if self._closed:
                # nothing left to drain — and the published lifecycle
                # must not regress closed -> draining for an idempotent
                # caller (checked under the SAME lock close() flips
                # _closed with, so a drain racing a close cannot pass
                # the check and then publish 'draining' afterwards)
                return True
            # flag + queue sweep under one lock: a racing submit either
            # landed (and is drained here) or raises EngineClosed
            self._draining = True
            while self._queue:
                self._queue.popleft()._finish("drained")
            _publish_gauges()
            _note_engine_state(self.engine_id, "draining")
        t0 = time.perf_counter()
        while self.busy():
            self.step()
            if time.perf_counter() - t0 > timeout_s:
                return False
        return True

    def close(self, drain_timeout_s: float = 30.0):
        """Drain, then release the engine's compiled entries + staged
        feeds and its device-resident state. A drain that times out
        (stalled decode loop) must not strand callers: every still
        in-flight handle is finished with outcome 'drained' (partial
        output kept) so ``result()`` never blocks forever on a closed
        engine."""
        if self._closed:
            return
        self.drain(drain_timeout_s)
        with self._lock:
            # under the same lock drain() checks: once this flips, a
            # concurrent drain can no longer publish 'draining' over
            # the terminal 'closed' state below
            self._closed = True
        self._pending = None
        for s in self._slots:
            req, s.request = s.request, None
            if req is not None and req.outcome is None:
                req._finish("drained")
        self._exe.release_scope(self.scope)
        self.scope.clear()
        _ENGINES.discard(self)
        _note_engine_state(self.engine_id, "closed")
        _publish_gauges()

    # --- internals ---

    def _active_mask(self) -> np.ndarray:
        return np.asarray(
            [s.request is not None and s.request.outcome is None
             for s in self._slots], bool)

    def _admit(self):
        """Admissions at the token boundary: free slot x queued request
        -> prefill. The prefill program executes after the already
        dispatched decode step, so the newcomer joins at the next one."""
        while True:
            free = next((i for i, s in enumerate(self._slots)
                         if s.request is None), None)
            if free is None:
                return
            with self._lock:
                if not self._queue:
                    return
                req = self._queue.popleft()
                _publish_gauges()
            if (req.deadline_ts is not None
                    and time.perf_counter() > req.deadline_ts):
                req._finish("expired")
                continue
            pre = self._progs["prefill"]
            try:
                with scope_guard(self.scope), \
                        _monitor.span("serve.prefill"):
                    self._exe.run(
                        self._progs["prefill_program"],
                        feed={
                            pre["feeds"][0].name: req.src_ids[None, :],
                            pre["feeds"][1].name: req.src_pad[None, :],
                            pre["feeds"][2].name:
                                np.asarray([free], np.int64),
                        },
                        fetch_list=[])
            except Exception:
                # the request is already off the queue and owns no slot:
                # finish the handle before propagating — result() must
                # never block forever on a failed admission
                req._finish("error")
                raise
            self._slots[free].request = req
            _M_PREFILLS.inc()
            _publish_gauges()

    def _dispatch(self):
        """Launch one single-token decode step for the active set (a
        no-op tick when every slot is free)."""
        mask = self._active_mask()
        if not mask.any():
            return
        _F_DECODE.hit()
        dec = self._progs["decode"]
        t0 = time.perf_counter()
        with scope_guard(self.scope), _monitor.span("serve.decode"):
            fetches = self._exe.run(
                self._progs["decode_program"],
                feed={dec["feeds"][0].name: mask},
                fetch_list=[dec["emit"], dec["live"], dec["pos"]],
                async_fetch=True)
        snapshot = [s.request if m else None
                    for s, m in zip(self._slots, mask)]
        self._pending = (fetches, snapshot, t0)
        self.decode_steps += 1
        _M_DECODE_STEPS.inc()

    def _process_ready(self) -> int:
        """Materialize the pending decode step's fetches and hand each
        slot's token to its request; evict finished/expired requests
        (their slots free for the next admission round)."""
        if self._pending is None:
            return 0
        fetches, snapshot, t0 = self._pending
        self._pending = None
        emit, live, pos = [np.asarray(a) for a in fetches]
        now = time.perf_counter()
        step_s = now - t0
        emitted = 0
        for i, req in enumerate(snapshot):
            if req is None or req.outcome is not None:
                continue
            tok = int(emit[i])
            alive = bool(live[i])
            if not alive and tok == self.end_id:
                # EOS (or a dead-slot freeze): terminal, token dropped
                self._finish_slot(i, req, "completed")
                continue
            req.tokens.append(tok)
            emitted += 1
            self.tokens_emitted += 1
            _M_TOKENS.inc()
            _M_TOKEN_SECONDS.observe(step_s)
            if req.ttft_s is None:
                req.ttft_s = now - req.submit_ts
                _M_TTFT_SECONDS.observe(req.ttft_s)
            if not alive or len(req.tokens) >= req.max_new_tokens:
                # device length cap (max_len positions) or the request's
                # own token budget: terminal without an EOS
                self._finish_slot(i, req, "length")
            elif (req.deadline_ts is not None and now > req.deadline_ts):
                # deadline eviction AT the token boundary: the slot is
                # freed now; the partial output stays on the handle
                self._finish_slot(i, req, "expired")
        _publish_gauges()
        return emitted

    def _finish_slot(self, i: int, req: ServeRequest, outcome: str):
        req._finish(outcome)
        self.completed += 1
        self._slots[i].request = None

    @property
    def state(self) -> str:
        return ("closed" if self._closed
                else "draining" if self._draining else "serving")

    def stats(self) -> Dict:
        """One JSON-able row for the /serve route."""
        with self._lock:
            queued = len(self._queue)
        return {
            "engine_id": self.engine_id,
            "state": self.state,
            "slots": self.slots,
            "slots_active": int(self._active_mask().sum()),
            "queue_depth": queued,
            "queue_capacity": self.queue_depth,
            "src_len": self.src_len,
            "max_len": self.max_len,
            "decode_steps": self.decode_steps,
            "tokens_emitted": self.tokens_emitted,
            "requests_completed": self.completed,
            "draining": self._draining,
            "int8": self.int8,
            "pipeline_depth": self.pipeline_depth,
        }


def _is_tpu_default() -> bool:
    import jax

    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


_ENGINES: "weakref.WeakSet[ServingEngine]" = weakref.WeakSet()


def _publish_gauges():
    """Refresh the process-wide queue/slot gauges as SUMS across live
    engines — per-engine .set() calls would let an idle engine zero out
    a saturated neighbor's reading (the per-engine split lives in
    /serve's stats rows)."""
    engines = list(_ENGINES)
    _M_QUEUE_DEPTH.set(sum(len(e._queue) for e in engines))
    _M_SLOTS_ACTIVE.set(sum(
        1 for e in engines for s in e._slots
        if s.request is not None and s.request.outcome is None))


def serve(cfg, weights, **kwargs) -> ServingEngine:
    """Predictor-style front end: build a ServingEngine over ``weights``
    (a Scope, a Predictor, or a saved inference-model directory — the
    int8 PTQ artifact deploys dequantized). See ServingEngine for the
    geometry/SLO knobs."""
    return ServingEngine(cfg, weights, **kwargs)


def summary() -> Dict:
    """The /serve route payload: one stats row per live engine."""
    engines = [e.stats() for e in list(_ENGINES)]
    return {
        "engines": engines,
        "engine_count": len(engines),
        "tokens_total": int(_M_TOKENS.value()),
        "decode_steps_total": int(_M_DECODE_STEPS.value()),
        "token_latency_s": {
            label: _M_TOKEN_SECONDS.quantile(q)
            for label, q in _monitor.QUANTILE_LABELS
        },
        "ttft_s": {
            label: _M_TTFT_SECONDS.quantile(q)
            for label, q in _monitor.QUANTILE_LABELS
        },
    }
