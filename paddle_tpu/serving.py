"""Production inference serving plane: continuous batching over an
on-device KV cache, with fault containment and supervised self-healing.

Reference seam: the AnalysisPredictor C-API (inference.py) serves one
request batch per call; real serving traffic is a stream of requests of
different lengths arriving at different times. The reference framework
dedicates its ``inference_transpiler``/server layer to this — a
long-lived, self-healing predictor process; here the serving plane is
built on the pieces the training stack already proved:

- **Continuous batch assembly**: a bounded request queue feeds a fixed
  set of batch *slots*. Requests are admitted and evicted at token
  boundaries — one compiled single-token decode executable serves every
  mix of in-flight requests (no per-batch-shape recompiles, ever).
- **Prefill/decode split** (models/transformer.py ``build_prefill`` /
  ``build_decode_step``): admission runs the encoder once and writes the
  request's cross-attention K/V into slot-indexed, device-resident cache
  tensors; each decode step appends one self-attention K/V row per slot
  and emits one greedy token per slot. The cache rides the executor's
  donated-state path — it never round-trips through the host.
- **Async decode loop**: decode steps dispatch with ``async_fetch``
  (executor.LazyFetches), so step N's device->host token fetch
  materializes under step N+1's dispatch — the serving twin of the
  training pipeline's overlapped fetch.
- **Warm replica start**: engines sharing a geometry share program
  objects (transformer.build_serving), so the persistent compile cache
  (``compile_cache_dir`` flag) resolves a fresh replica's prefill +
  decode executables from disk — zero fresh XLA compiles at spin-up.
- **SLO plane for free**: ``pt_serve_*`` metrics (queue depth, tokens/s,
  TTFT + per-token latency histograms) ride the monitor registry; the
  live endpoint serves an engine summary at ``/serve``; chaos plans can
  arm ``serve.enqueue`` / ``serve.prefill`` / ``serve.decode`` /
  ``serve.fetch`` fault sites.
- **Request-scoped tracing** (serving_trace.py): every request carries
  a trace id + measured per-phase latencies (queue wait / prefill /
  decode / fetch), its whole life lands on one Chrome-trace track, the
  terminal breakdown is served at ``/requests``, and the ``pt_slo_*``
  counters score it against the ``serve_slo_*`` flag targets —
  including deadline attribution on expired/rejected_early requests.

Resilience (the serving analog of the training fault-tolerance plane):

- **Decode fault containment**: a decode/fetch failure that names its
  poisoned slot(s) (``slot=N`` in the error text — the chaos-plan
  ``raise(slot=N)`` protocol, and the shape a per-slot device error
  report takes) evicts ONLY those slots: the request finishes with
  outcome ``evicted`` keeping its partial output, the slot's device
  rows are scrubbed (a NaN K/V row would re-poison the next occupant
  through the softmax mask: 0 * NaN = NaN), and every healthy slot
  keeps decoding byte-identically. Non-finite logits are caught per
  slot via the decode program's max-|logit| probe and contained the
  same way (outcome ``error``; reported through the numerics plane).
  An UNATTRIBUTABLE failure (no slot hint, or RESOURCE_EXHAUSTED —
  which additionally runs OOM forensics with ``phase="serve"``) fails
  the engine: device state can no longer be trusted.
- **Supervised warm restart**: ``EngineSupervisor`` owns the engine, a
  decode-loop thread, and a watchdog riding engine heartbeats (a wedge
  declaration also emits a ``monitor`` stall record for site
  ``serve.decode``). A crashed (engine-fatal error) or wedged
  (heartbeat older than ``serve_wedge_timeout_ms`` while busy) engine
  is torn down and rebuilt through the persistent compile cache (zero
  fresh compiles — the warm-replica path), and every surviving queued +
  in-flight request is re-prefilled under a retry.py budget; greedy
  decode is deterministic, so replayed requests produce byte-identical
  tokens. Metered by ``pt_serve_engine_restarts_total`` and
  ``pt_serve_requests_replayed_total``.
- **Overload protection**: deadline-aware admission control refuses a
  request at submit() when the measured per-token latency (EWMA of
  decode-step wall time) times its estimated queue position says even
  the first token cannot land before the deadline (outcome
  ``rejected_early``, DeadlineUnmeetable raised — the request is never
  queued); and a brownout mode (``serve_brownout_*`` flags) caps
  admissions' ``max_new_tokens`` under sustained queue saturation, so
  the engine degrades tokens-per-request instead of letting queue
  latency collapse.

Deployable artifacts: an engine loads weights from a live Scope, a
Predictor, or a saved inference-model directory — including the int8 PTQ
artifact (``slim/calibration.py``), whose weights deploy dequantized
into the decode programs (weight-only int8: 4x smaller artifact, same
serving surface).
"""

from __future__ import annotations

import collections
import itertools
import os
import re
import threading
import time
import warnings
import weakref
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu import faults as _faults
from paddle_tpu import flags as _flags
from paddle_tpu import monitor as _monitor
from paddle_tpu import numerics as _numerics
from paddle_tpu import retry as _retry
from paddle_tpu import serving_trace as _strace
from paddle_tpu.executor import Executor, Scope, scope_guard
from paddle_tpu.framework import CPUPlace, TPUPlace

# --- telemetry (no-ops while the 'telemetry' flag is off) ---

_M_REQUESTS = _monitor.counter(
    "pt_serve_requests_total",
    "serving requests by terminal outcome (completed / length / "
    "expired / rejected / rejected_early / drained / error / evicted)")
_M_QUEUE_DEPTH = _monitor.gauge(
    "pt_serve_queue_depth", "requests waiting for a batch slot")
_M_SLOTS_ACTIVE = _monitor.gauge(
    "pt_serve_slots_active", "batch slots holding an in-flight request")
_M_PREFILLS = _monitor.counter(
    "pt_serve_prefill_total", "admissions (prefill program runs)")
_M_DECODE_STEPS = _monitor.counter(
    "pt_serve_decode_steps_total",
    "single-token decode steps (each serves every active slot)")
_M_TOKENS = _monitor.counter(
    "pt_serve_tokens_total", "tokens emitted across all requests")
_M_TOKEN_SECONDS = _monitor.histogram(
    "pt_serve_token_seconds",
    "per-token latency (decode-step dispatch -> token on host)",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5))
_M_TTFT_SECONDS = _monitor.histogram(
    "pt_serve_ttft_seconds",
    "time to first token (request submit -> first token on host)",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             30.0))
_M_ENGINE_STATE = _monitor.gauge(
    "pt_serve_engine_state",
    "per-engine lifecycle state by engine id: 0=serving, 1=draining, "
    "2=closed, 3=failed — a replica being rotated out (or killed by a "
    "decode fault) is observable BEFORE its queue is torn down; closed "
    "rows age out after ENGINE_STATE_TTL_S")
_M_SLOT_EVICTIONS = _monitor.counter(
    "pt_serve_slot_evictions_total",
    "poisoned batch slots evicted by decode fault containment, by "
    "cause (fault = slot-hinted decode/fetch error, nonfinite = "
    "non-finite logits caught by the per-slot probe); the request "
    "keeps its partial output and every healthy slot keeps decoding")
_M_RESTARTS = _monitor.counter(
    "pt_serve_engine_restarts_total",
    "supervised warm engine restarts (crashed or wedged decode loop "
    "torn down and rebuilt through the persistent compile cache)")
_M_REPLAYED = _monitor.counter(
    "pt_serve_requests_replayed_total",
    "queued + in-flight requests re-prefilled onto the restarted "
    "engine after a supervised restart (greedy decode is "
    "deterministic: a replay returns byte-identical tokens)")
_M_BROWNOUT = _monitor.gauge(
    "pt_serve_brownout_engines",
    "engines currently in brownout (sustained queue saturation: "
    "admissions' max_new_tokens capped by "
    "serve_brownout_max_new_tokens)")
_M_BROWNOUT_CAPPED = _monitor.counter(
    "pt_serve_brownout_capped_total",
    "admissions whose max_new_tokens was cut by an engaged brownout")

ENGINE_STATES = ("serving", "draining", "closed", "failed")
# Terminal 'closed' rows age out of the /healthz payload and the gauge
# after this many seconds (a rotated replica's state is liveness
# information for a while, not forever). Tests may override.
ENGINE_STATE_TTL_S = 300.0
# engine id -> (lifecycle state, transition ts), bounded (closed engines
# age out so the /healthz payload and the gauge's label set stay small).
# Mutated by engine threads and iterated by the monitor server's handler
# threads: every access holds _ENGINE_STATE_LOCK.
_ENGINE_STATE_CAP = 32
_ENGINE_STATE_LOCK = threading.Lock()
_ENGINE_STATES: "collections.OrderedDict[int, tuple]" = \
    collections.OrderedDict()


def _sweep_engine_states_locked():
    """Drop terminal 'closed' rows older than ENGINE_STATE_TTL_S.
    Caller holds _ENGINE_STATE_LOCK; returns True when rows dropped."""
    now = time.monotonic()
    stale = [k for k, (state, ts) in _ENGINE_STATES.items()
             if state == "closed" and now - ts > ENGINE_STATE_TTL_S]
    for k in stale:
        del _ENGINE_STATES[k]
    return bool(stale)


def _publish_engine_states(snapshot):
    # the gauge mirrors the bounded map wholesale (Gauge.replace, its
    # own atomic swap): engines aged/evicted out of the map drop their
    # cells too, so a process churning many short-lived engines never
    # accretes stale labels
    _M_ENGINE_STATE.replace(
        [({"engine": str(k)}, float(ENGINE_STATES.index(state)))
         for k, (state, _ts) in snapshot])


def _note_engine_state(engine_id: int, state: str):
    with _ENGINE_STATE_LOCK:
        _ENGINE_STATES[engine_id] = (state, time.monotonic())
        _ENGINE_STATES.move_to_end(engine_id)
        _sweep_engine_states_locked()
        while len(_ENGINE_STATES) > _ENGINE_STATE_CAP:
            _ENGINE_STATES.popitem(last=False)
        # publish INSIDE the lock: a concurrent publisher holding a
        # stale snapshot could otherwise overwrite a newer transition
        # (lock order is always state lock -> monitor registry lock)
        _publish_engine_states(list(_ENGINE_STATES.items()))


def engine_states() -> Dict[str, str]:
    """{engine id -> "serving" | "draining" | "closed" | "failed"} for
    the /healthz monitor route: a serving replica's lifecycle is
    liveness information — a load balancer must stop routing to a
    draining (or failed) engine before its queue disappears. Closed
    rows age out after ENGINE_STATE_TTL_S so a rotated replica's
    terminal state is not served forever."""
    with _ENGINE_STATE_LOCK:
        swept = _sweep_engine_states_locked()
        snapshot = list(_ENGINE_STATES.items())
        if swept:
            _publish_engine_states(snapshot)
    return {str(k): state for k, (state, _ts) in snapshot}

# chaos hooks (faults.py): serve.enqueue drills queue-path failures;
# serve.prefill tears the admission seam; serve.decode drills the
# decode loop (delay = wedge, raise(slot=N) = contained poisoned slot,
# unhinted raise = engine-fatal); serve.fetch tears the async
# materialization seam the same way.
_F_ENQUEUE = _faults.site("serve.enqueue")
_F_PREFILL = _faults.site("serve.prefill")
_F_DECODE = _faults.site("serve.decode")
_F_FETCH = _faults.site("serve.fetch")

REQUEST_OUTCOMES = ("completed", "length", "expired", "rejected",
                    "rejected_early", "drained", "error", "evicted")

# poisoned-slot attribution in a decode/fetch error's text: the chaos
# plan's raise(slot=N[,M]) protocol, and the shape a real per-slot
# device error report takes. No match = unattributable = engine-fatal.
_SLOT_HINT_RE = re.compile(r"slots?\s*[=:]\s*(\d+(?:\s*,\s*\d+)*)")


def _slot_hints(exc) -> Optional[List[int]]:
    m = _SLOT_HINT_RE.search(str(exc))
    if m is None:
        return None
    return sorted({int(p) for p in m.group(1).split(",")})


class QueueFull(RuntimeError):
    """submit() backpressure: the request queue is at serve_queue_depth."""


class EngineClosed(RuntimeError):
    """submit()/step() on a closed engine."""


class EngineFailed(RuntimeError):
    """The engine hit an unattributable decode/fetch failure: device
    state can no longer be trusted, only a (supervised) rebuild can
    serve again. ``submit()``/``step()`` raise this until close()."""


class DeadlineUnmeetable(RuntimeError):
    """Deadline-aware admission control refused the request at submit:
    measured per-token latency x estimated queue position says even the
    first token cannot land before the deadline. The handle is finished
    with outcome ``rejected_early`` and never queued."""

    def __init__(self, message: str, request=None,
                 estimate_s: Optional[float] = None):
        super().__init__(message)
        self.request = request
        self.estimate_s = estimate_s


class ServeRequest:
    """One in-flight generation request (handle returned by submit)."""

    # itertools.count: atomic under CPython — submit() is meant for
    # concurrent callers and ids must stay unique across threads
    _uid = itertools.count(1)

    def __init__(self, src_ids, src_pad, max_new_tokens, deadline_s):
        self.id = next(ServeRequest._uid)
        self.src_ids = src_ids
        self.src_pad = src_pad
        self.max_new_tokens = max_new_tokens
        self.submit_ts = time.perf_counter()
        self.deadline_ts = (self.submit_ts + deadline_s
                            if deadline_s else None)
        self.tokens: List[int] = []
        self.outcome: Optional[str] = None
        self.ttft_s: Optional[float] = None
        self.replays = 0  # supervised-restart replays of this request
        self.capped = False  # max_new_tokens cut by brownout
        # request-scoped observability (serving_trace.py): measured
        # per-phase latencies, the deadline attribution, the censored
        # flag (terminal before first token), and the request's pinned
        # Chrome-trace track. Plain attributes set by the engine's
        # scheduler tick — reading a clock and storing a float keeps
        # the telemetry-off hot path allocation-free in the new plane.
        self.engine_id: Optional[int] = None
        self.admit_ts: Optional[float] = None
        self.finish_ts: Optional[float] = None
        self.queue_wait_s: Optional[float] = None
        self.prefill_s: Optional[float] = None
        self.decode_s = 0.0
        self.fetch_s = 0.0
        self.censored = False
        self.deadline_attr: Optional[Dict] = None
        self.trace_tid: Optional[int] = None
        self._replay_intake_ts: Optional[float] = None
        # set by the supervisor's replay intake; the RESET (token wipe)
        # is deferred to the rebuilt engine's admission so a replay
        # that never reaches prefill keeps its partial output
        self._replay_pending = False
        self._done = threading.Event()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def trace_id(self) -> str:
        """Stable id tying the handle to its timeline track, /requests
        rows and log lines — survives supervised-restart replays."""
        return f"r{self.id}"

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request reaches a terminal outcome; returns
        the emitted tokens (EOS excluded)."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.id} not finished within {timeout}s")
        return list(self.tokens)

    def _finish(self, outcome: str):
        self.outcome = outcome
        _M_REQUESTS.inc(labels={"outcome": outcome})
        # the one funnel every terminal path flows through: censored
        # TTFT metering, SLO scoring, deadline attribution, and the
        # /requests ring record happen here, BEFORE waiters wake
        _strace.note_terminal(self)
        self._done.set()

    def _reset_for_replay(self):
        """Applied at the rebuilt engine's ADMISSION (not at harvest —
        a replay that is drained/errored before prefill must keep its
        partial output): decode restarts from scratch (greedy is
        deterministic — the final stream is byte-identical); TTFT
        re-measures from the original submit."""
        self._replay_pending = False
        self.tokens = []
        self.ttft_s = None
        # the phase decomposition restarts with the replay; queue wait
        # re-derives from the ORIGINAL submit at the rebuilt engine's
        # admission, so the restart gap lands in the queue phase and
        # the phase sum still covers the request's wall time
        self.queue_wait_s = None
        self.prefill_s = None
        self.decode_s = 0.0
        self.fetch_s = 0.0
        self.replays += 1
        _M_REPLAYED.inc()
        _strace.note_restart(self)


def _load_weights_into(scope: Scope, weights) -> bool:
    """Install model weights into the engine's private scope. Accepts a
    Scope (weights COPIED — donation would otherwise delete buffers the
    source scope still references), a Predictor (its scope is the
    source), or a saved inference-model directory (fp32 or int8 PTQ
    artifact). Returns True when the int8 artifact path was taken."""
    from paddle_tpu import inference as _inference

    if isinstance(weights, _inference.Predictor):
        weights = weights.scope
    if isinstance(weights, Scope):
        for name in weights.var_names():
            scope.set(name, np.array(np.asarray(weights.find_var(name))))
        return False
    if isinstance(weights, str):
        if os.path.exists(os.path.join(weights, "__params_int8__.npz")):
            from paddle_tpu.slim.calibration import (
                load_int8_inference_model,
            )

            load_int8_inference_model(weights, None, scope=scope)
            return True
        from paddle_tpu import io as _io

        path = os.path.join(weights, _io._PARAMS_FILE)
        with np.load(path) as data:
            for name in data.files:
                scope.set(name, np.asarray(data[name]))
        return False
    raise TypeError(
        f"weights must be a Scope, Predictor or model dir, got "
        f"{type(weights).__name__}")


class _Slot:
    """Host-side view of one batch slot."""

    __slots__ = ("request",)

    def __init__(self):
        self.request: Optional[ServeRequest] = None


class ServingEngine:
    """Continuous-batching serving engine over the transformer zoo.

    One engine = one model + one batch geometry: ``slots`` concurrent
    requests, sources padded/bucketed to ``src_len``, at most
    ``max_len - 1`` generated tokens per request. ``submit()`` enqueues
    (with queue-depth backpressure, optional per-request deadlines, and
    deadline-aware admission control); the caller drives ``step()`` —
    or ``run_until_idle()`` — to make progress; ``drain()`` stops
    admissions and finishes the in-flight set; ``close()`` drains and
    releases the compiled entries. The lifecycle (serving -> draining
    -> closed, or -> failed on an unattributable decode fault) is
    observable: ``state`` here, ``pt_serve_engine_state`` on /metrics,
    and per-engine rows on the /healthz route (``engine_states``). For
    a self-healing engine, wrap it in ``EngineSupervisor`` (or
    ``serve(..., supervised=True)``).
    """

    _eid = itertools.count(1)

    def __init__(self, cfg, weights, *, slots: int = 4, src_len: int = 32,
                 max_len: int = 32, bos_id: int = 0, end_id: int = 1,
                 place=None, queue_depth: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 pipeline_depth: int = 1):
        from paddle_tpu.models import transformer as _T

        if slots < 1:
            raise ValueError("need at least one batch slot")
        self.cfg = cfg
        self.slots = int(slots)
        self.src_len, self.max_len = int(src_len), int(max_len)
        self.bos_id, self.end_id = int(bos_id), int(end_id)
        self.queue_depth = (int(_flags.get_flag("serve_queue_depth"))
                            if queue_depth is None else int(queue_depth))
        default_deadline = (float(_flags.get_flag("serve_deadline_ms"))
                            if deadline_ms is None else float(deadline_ms))
        self.deadline_s = default_deadline / 1e3 if default_deadline else 0.0
        # 1 = double-buffered decode (step N's fetch materializes under
        # step N+1's dispatch); 0 = fully synchronous steps
        self.pipeline_depth = 1 if pipeline_depth else 0
        self._progs = _T.build_serving(cfg, self.slots, self.src_len,
                                       self.max_len, bos_id=self.bos_id,
                                       end_id=self.end_id)
        self.scope = Scope()
        self._exe = Executor(place if place is not None else CPUPlace()
                             if not _is_tpu_default() else TPUPlace(0))
        self.int8 = _load_weights_into(self.scope, weights)
        # device-resident serving state, zero-initialized (live=False
        # everywhere: every slot starts free)
        for name, (shape, dtype) in self._progs["state_specs"].items():
            self.scope.set(name, np.zeros(shape, dtype=np.dtype(dtype)))
        self._queue: "collections.deque[ServeRequest]" = collections.deque()
        self._slots = [_Slot() for _ in range(self.slots)]
        # (LazyFetches, per-slot request snapshot, t0, retried, step)
        self._pending = None
        self._lock = threading.Lock()
        self._draining = False
        self._closed = False
        self._failed = False
        self.last_error: Optional[str] = None
        # decode-loop heartbeat (EngineSupervisor wedge detection) and
        # the measured per-token latency estimator (admission control;
        # EWMA of decode-step wall time, independent of telemetry)
        self._beat = time.perf_counter()
        self._token_ewma_s: Optional[float] = None
        self._ewma_skipped_first = False
        # recent decode-step walls (dispatch -> tokens on host), for
        # the stats() latency row + overload drills; the first
        # (compile-carrying) step is excluded like the EWMA
        self._step_walls: "collections.deque[float]" = collections.deque(
            maxlen=256)
        # per-dispatch stall_guard deadline override; 0 = the global
        # stall_timeout_ms flag (default 0 = disarmed, a shared
        # nullcontext — the hot path stays Timer-free)
        self.stall_deadline_ms = 0.0
        # brownout (overload shedding) state
        self.brownout = False
        self._saturated_ticks = 0
        self.decode_steps = 0
        self.tokens_emitted = 0
        self.completed = 0
        self.engine_id = next(ServingEngine._eid)
        _ENGINES.add(self)
        _note_engine_state(self.engine_id, "serving")

    # --- request intake ---

    def submit(self, src_ids: Sequence[int],
               src_pad: Optional[Sequence[float]] = None,
               max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> ServeRequest:
        """Enqueue a generation request. ``src_ids`` shorter than the
        engine's ``src_len`` is padded (mask derived); longer raises.
        Backpressure: raises QueueFull beyond ``serve_queue_depth``;
        a deadline the measured per-token latency says is unmeetable
        raises DeadlineUnmeetable (outcome ``rejected_early``) without
        queueing — see the ``serve_admission_control`` flag."""
        _F_ENQUEUE.hit()
        ids = np.asarray(src_ids, np.int64).reshape(-1)
        if ids.shape[0] > self.src_len:
            raise ValueError(
                f"source length {ids.shape[0]} exceeds the engine's "
                f"src_len {self.src_len}")
        if src_pad is None:
            pad = (np.arange(self.src_len) < ids.shape[0]).astype(
                np.float32)
        else:
            # accepted at either the request's own length or the
            # engine's full src_len (the training graph's mask shape)
            mask = np.asarray(src_pad, np.float32).reshape(-1)
            if mask.shape[0] == self.src_len:
                pad = mask
            elif mask.shape[0] == ids.shape[0]:
                pad = np.zeros(self.src_len, np.float32)
                pad[:ids.shape[0]] = mask
            else:
                raise ValueError(
                    f"src_pad length {mask.shape[0]} matches neither "
                    f"the source length {ids.shape[0]} nor the "
                    f"engine's src_len {self.src_len}")
        full = np.zeros(self.src_len, np.int64)
        full[:ids.shape[0]] = ids
        cap = self.max_len - 1
        if max_new_tokens is not None and int(max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        want = cap if max_new_tokens is None else min(int(max_new_tokens),
                                                     cap)
        deadline_s = (self.deadline_s if deadline_ms is None
                      else float(deadline_ms) / 1e3)
        req = ServeRequest(full, pad, want, deadline_s)
        req.engine_id = self.engine_id
        with self._lock:
            # closed/draining re-checked under the SAME lock drain()
            # clears the queue with: a submit racing a drain must either
            # land before the sweep or raise, never enqueue onto an
            # engine nobody will step again
            if self._closed:
                raise EngineClosed("submit() on a closed engine")
            if self._failed:
                raise EngineFailed(
                    f"submit() on a failed engine ({self.last_error}); "
                    f"an EngineSupervisor would have restarted it")
            if self._draining:
                raise EngineClosed("submit() on a draining engine")
            if len(self._queue) >= self.queue_depth:
                req._finish("rejected")
                _publish_gauges()
                raise QueueFull(
                    f"serving queue at capacity ({self.queue_depth})")
            if (req.deadline_ts is not None
                    and self._token_ewma_s is not None
                    and _flags.get_flag("serve_admission_control")):
                eta_s = self._estimate_first_token_s()
                if req.submit_ts + eta_s > req.deadline_ts:
                    # refused AT SUBMIT, never queued: queueing work
                    # that provably cannot emit one token before its
                    # deadline only inflates every neighbor's latency.
                    # The ESTIMATED queue wait is the refusal's whole
                    # story — recorded so the deadline attribution can
                    # name the phase that ate the budget.
                    req.queue_wait_s = eta_s
                    req._finish("rejected_early")
                    _publish_gauges()
                    raise DeadlineUnmeetable(
                        f"deadline unmeetable: first token estimated "
                        f"in {eta_s * 1e3:.1f} ms (measured "
                        f"{self._token_ewma_s * 1e3:.2f} ms/token x "
                        f"queue position) vs a "
                        f"{(req.deadline_ts - req.submit_ts) * 1e3:.1f}"
                        f" ms deadline", request=req, estimate_s=eta_s)
            # the heartbeat also resets at WORK ARRIVAL — but only when
            # the engine is truly IDLE: after an idle gap longer than
            # the wedge timeout, the first submit flips busy() before
            # the loop's next step() can beat (the watchdog would read
            # the idle age as a wedge). An engine with work in flight
            # gets no reset: steady submit traffic onto a genuinely
            # wedged decode loop must not defer its detection.
            idle = (not self._queue and self._pending is None
                    and all(s.request is None for s in self._slots))
            if idle:
                self._beat = time.perf_counter()
            self._queue.append(req)
            _publish_gauges()
        _strace.note_submit(req)
        return req

    def _estimate_first_token_s(self) -> float:
        """Estimated delay until a request submitted NOW sees its first
        token: tokens still owed ahead of it (queue + in-flight),
        drained ``slots`` at a time, at the measured per-token EWMA.
        Caller holds the lock."""
        backlog = sum(r.max_new_tokens for r in self._queue)
        for s in self._slots:
            r = s.request
            if r is not None and r.outcome is None:
                backlog += max(0, r.max_new_tokens - len(r.tokens))
        return self._token_ewma_s * (backlog / float(self.slots) + 1.0)

    # --- the scheduler tick ---

    def step(self) -> int:
        """One scheduler tick: resolve the previously dispatched decode
        step (handing tokens to their requests and freeing finished
        slots), admit queued requests into free slots (prefill), and
        dispatch the next single-token decode step. Returns the number
        of tokens handed out this tick."""
        if self._closed:
            raise EngineClosed("step() on a closed engine")
        if self._failed:
            raise EngineFailed(
                f"step() on a failed engine ({self.last_error})")
        self._beat = time.perf_counter()
        self._brownout_tick()
        emitted = self._process_ready()
        self._admit()
        self._dispatch()
        if self.pipeline_depth == 0:
            emitted += self._process_ready()
        self._beat = time.perf_counter()
        return emitted

    def run_until_idle(self, max_steps: int = 100_000) -> int:
        """Drive step() until no request is queued or in flight; returns
        total tokens emitted. ``max_steps`` bounds a runaway loop."""
        total = 0
        for _ in range(max_steps):
            total += self.step()
            if not self.busy():
                break
        # resolve a still-pending final step
        total += self._process_ready()
        return total

    def busy(self) -> bool:
        with self._lock:
            queued = bool(self._queue)
        return (queued or self._pending is not None
                or any(s.request is not None for s in self._slots))

    def heartbeat_age_s(self) -> float:
        """Seconds since the decode loop last made progress (step entry
        or completion) — the EngineSupervisor's wedge signal."""
        return time.perf_counter() - self._beat

    def request_drain(self) -> bool:
        """The non-stepping front half of drain(): stop admissions and
        finish every queued-but-unadmitted request with outcome
        'drained'. The in-flight set keeps decoding (whoever drives
        step() — the caller or a supervisor loop — finishes it).
        Returns False when the engine is already closed."""
        with self._lock:
            if self._closed:
                return False
            # flag + queue sweep under one lock: a racing submit either
            # landed (and is drained here) or raises EngineClosed
            self._draining = True
            while self._queue:
                self._queue.popleft()._finish("drained")
            _publish_gauges()
            _note_engine_state(self.engine_id, "draining")
        return True

    def handoff_queued(self) -> List[ServeRequest]:
        """Fleet-rollout front half of a drain: stop admissions, but
        TAKE the queued-but-unadmitted requests instead of finishing
        them 'drained' — the router re-homes them on another replica,
        so a rolling weight rollout rejects nothing. The in-flight set
        keeps decoding (whoever drives step() finishes it). Returns []
        on a closed engine."""
        out: List[ServeRequest] = []
        with self._lock:
            if self._closed:
                return out
            self._draining = True
            while self._queue:
                r = self._queue.popleft()
                if r.outcome is None:
                    out.append(r)
            _publish_gauges()
            _note_engine_state(self.engine_id, "draining")
        return out

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful drain: stop admissions, finish the in-flight set.
        Queued-but-unadmitted requests finish with outcome 'drained'.
        Returns True when everything settled inside ``timeout_s``."""
        with self._lock:
            if self._closed:
                # nothing left to drain — and the published lifecycle
                # must not regress closed -> draining for an idempotent
                # caller (checked under the SAME lock close() flips
                # _closed with, so a drain racing a close cannot pass
                # the check and then publish 'draining' afterwards)
                return True
        if not self.request_drain():
            return True
        if self._failed:
            # a failed engine cannot step: the queue is swept, the
            # in-flight set is close()'s (or the supervisor's) problem
            return not self.busy()
        t0 = time.perf_counter()
        while self.busy():
            try:
                self.step()
            except (EngineClosed, EngineFailed):
                return False
            if time.perf_counter() - t0 > timeout_s:
                return False
        return True

    def close(self, drain_timeout_s: float = 30.0):
        """Drain, then release the engine's compiled entries + staged
        feeds and its device-resident state. A drain that times out
        (stalled decode loop) or a failed engine must not strand
        callers: every still in-flight handle is finished — outcome
        'drained' (partial output kept), or 'error' when the engine
        failed — so ``result()`` never blocks forever on a closed
        engine."""
        if self._closed:
            return
        if not self._failed:
            self.drain(drain_timeout_s)
        with self._lock:
            # under the same lock drain() checks: once this flips, a
            # concurrent drain can no longer publish 'draining' over
            # the terminal 'closed' state below
            self._closed = True
            self._pending = None
            leftovers = []
            for s in self._slots:
                req, s.request = s.request, None
                if req is not None and req.outcome is None:
                    leftovers.append(req)
            while self._queue:
                r = self._queue.popleft()
                if r.outcome is None:
                    leftovers.append(r)
        outcome = "error" if self._failed else "drained"
        for req in leftovers:
            req._finish(outcome)
        self._exe.release_scope(self.scope)
        self.scope.clear()
        _ENGINES.discard(self)
        _note_engine_state(self.engine_id, "closed")
        _publish_gauges()

    # --- internals ---

    def _active_mask(self) -> np.ndarray:
        return np.asarray(
            [s.request is not None and s.request.outcome is None
             for s in self._slots], bool)

    def _brownout_tick(self):
        """Overload shedding: once the queue has held >= factor x
        capacity entries for `serve_brownout_window` consecutive ticks,
        cap admissions' max_new_tokens — degrade tokens-per-request
        instead of letting queue latency collapse. Disengages as soon
        as a tick sees the queue below the threshold."""
        factor = float(_flags.get_flag("serve_brownout_queue_factor"))
        if factor <= 0.0:
            if self.brownout:
                self.brownout = False
                _publish_gauges()
            self._saturated_ticks = 0
            return
        threshold = max(1, int(round(factor * self.queue_depth)))
        with self._lock:
            qlen = len(self._queue)
        if qlen >= threshold:
            self._saturated_ticks += 1
            if (not self.brownout and self._saturated_ticks
                    >= int(_flags.get_flag("serve_brownout_window"))):
                self.brownout = True
                warnings.warn(
                    f"serving engine {self.engine_id}: brownout engaged "
                    f"(queue held >= {threshold}/{self.queue_depth} for "
                    f"{self._saturated_ticks} ticks); admissions capped "
                    f"at {_flags.get_flag('serve_brownout_max_new_tokens')}"
                    f" new tokens", RuntimeWarning)
                _publish_gauges()
        else:
            self._saturated_ticks = 0
            if self.brownout:
                self.brownout = False
                _publish_gauges()

    def _admit(self):
        """Admissions at the token boundary: free slot x queued request
        -> prefill. The prefill program executes after the already
        dispatched decode step, so the newcomer joins at the next one."""
        while True:
            free = next((i for i, s in enumerate(self._slots)
                         if s.request is None), None)
            if free is None:
                return
            with self._lock:
                if self._failed or not self._queue:
                    return
                req = self._queue.popleft()
                _publish_gauges()
            now = time.perf_counter()
            if req.deadline_ts is not None and now > req.deadline_ts:
                # the deadline elapsed while QUEUED: the measured queue
                # wait is what ate the budget — record it before the
                # terminal accounting attributes the expiry
                req.queue_wait_s = now - req.submit_ts
                req._finish("expired")
                continue
            was_replay = req._replay_pending
            if was_replay:
                # the token wipe happens HERE, where the replay really
                # re-enters decode — not at harvest time
                req._reset_for_replay()
            if self.brownout and not was_replay:
                # replays are exempt: capping one would break the
                # byte-identical-replay invariant (and could return
                # fewer tokens than its pre-restart partial output)
                cap = int(_flags.get_flag("serve_brownout_max_new_tokens"))
                if cap >= 1 and req.max_new_tokens > cap:
                    req.max_new_tokens = cap
                    req.capped = True
                    _M_BROWNOUT_CAPPED.inc()
            # phase decomposition: the queue span closes at the pop
            # (replays re-measure from the ORIGINAL submit — the
            # restart gap is queue time from the request's view)
            req.admit_ts = time.perf_counter()
            req.queue_wait_s = req.admit_ts - req.submit_ts
            pre = self._progs["prefill"]
            try:
                _F_PREFILL.hit()
                with scope_guard(self.scope), \
                        _monitor.span("serve.prefill"):
                    self._exe.run(
                        self._progs["prefill_program"],
                        feed={
                            pre["feeds"][0].name: req.src_ids[None, :],
                            pre["feeds"][1].name: req.src_pad[None, :],
                            pre["feeds"][2].name:
                                np.asarray([free], np.int64),
                        },
                        fetch_list=[])
                req.prefill_s = time.perf_counter() - req.admit_ts
            except Exception as e:
                # the request is already off the queue and owns no slot:
                # finish the handle before propagating — result() must
                # never block forever on a failed admission
                req._finish("error")
                _monitor.maybe_record_oom(
                    e, program=self._progs["prefill_program"],
                    phase="serve")
                raise
            self._slots[free].request = req
            _M_PREFILLS.inc()
            _strace.note_admit(req)
            _publish_gauges()

    def _dispatch(self):
        """Launch one single-token decode step for the active set (a
        no-op tick when every slot is free)."""
        if self._pending is not None:
            # a contained fetch fault re-pended the step's fetches for
            # retry: dispatching over them would clobber the healthy
            # slots' already-computed tokens and fork their streams
            return
        mask = self._active_mask()
        if not mask.any():
            return
        dec = self._progs["decode"]
        t0 = time.perf_counter()
        try:
            with scope_guard(self.scope), _monitor.span("serve.decode"), \
                    _monitor.stall_guard("serve.decode",
                                         self.stall_deadline_ms or None):
                _F_DECODE.hit()
                fetches = self._exe.run(
                    self._progs["decode_program"],
                    feed={dec["feeds"][0].name: mask},
                    fetch_list=[dec["emit"], dec["live"], dec["pos"],
                                dec["maxabs"], dec["score"]],
                    async_fetch=True)
        except Exception as e:
            self._contain_decode_error(e)
            return
        snapshot = [s.request if m else None
                    for s, m in zip(self._slots, mask)]
        self._pending = (fetches, snapshot, t0, False, self.decode_steps)
        self.decode_steps += 1
        _M_DECODE_STEPS.inc()

    def _attribute_or_fail(self, exc) -> List[int]:
        """Shared decode/fetch failure classification: RESOURCE_EXHAUSTED
        runs the OOM forensics hook (phase="serve"; the executor already
        ran donated-buffer hygiene) and fails the engine; an error with
        no slot hint is unattributable and fails the engine; otherwise
        the candidate slot list is returned for the caller's eviction
        body. One policy, two call sites — they must not diverge."""
        if _monitor.is_oom_error(exc):
            _monitor.maybe_record_oom(
                exc, program=self._progs["decode_program"], phase="serve")
            self._fail(exc)
            raise exc
        hints = _slot_hints(exc)
        if hints is None:
            self._fail(exc)
            raise exc
        return hints

    def _contain_decode_error(self, exc):
        """Dispatch-path failure policy: a slot-hinted error evicts only
        the poisoned slots (the fault fired before/at dispatch — device
        state for the healthy slots is consistent, no token was lost);
        anything unattributable fails the engine."""
        hints = self._attribute_or_fail(exc)
        evicted = []
        with self._lock:
            for i in hints:
                if 0 <= i < self.slots:
                    req = self._slots[i].request
                    if req is not None and req.outcome is None:
                        _strace.note_evicted(req, "fault", i)
                        self._finish_slot(i, req, "evicted")
                        _M_SLOT_EVICTIONS.inc(labels={"cause": "fault"})
                        evicted.append((i, req))
            _publish_gauges()
        if not evicted:
            # the hint named no active slot (out of range, or already
            # finished): nothing was contained — swallowing it would
            # livelock a persistently failing decode step
            self._fail(exc)
            raise exc
        self._scrub_evicted(evicted)

    def _contain_fetch_error(self, exc, fetches, snapshot, t0,
                             retried, step) -> List:
        """Materialization-path failure policy (caller holds the lock):
        a slot-hinted error evicts the poisoned slots and re-pends the
        step's fetches for ONE retry (the healthy slots' tokens are
        still in the buffers — dropping them would fork their streams);
        a second failure or an unattributable one fails the engine.
        Returns the evicted (slot, request) pairs for the caller to
        scrub OUTSIDE the lock (the scrub is a blocking device call)."""
        hints = self._attribute_or_fail(exc)
        if retried:
            self._fail(exc)
            raise exc
        evicted = []
        for i in hints:
            if 0 <= i < self.slots:
                req = self._slots[i].request
                if (req is not None and req.outcome is None
                        and snapshot[i] is req):
                    _strace.note_evicted(req, "fault", i)
                    self._finish_slot(i, req, "evicted")
                    _M_SLOT_EVICTIONS.inc(labels={"cause": "fault"})
                    snapshot[i] = None
                    evicted.append((i, req))
        if not evicted:
            # hint matched no active slot: nothing was contained (see
            # _contain_decode_error — a swallow here would livelock)
            self._fail(exc)
            raise exc
        self._pending = (fetches, snapshot, t0, True, step)
        _publish_gauges()
        return evicted

    def _scrub_evicted(self, slots: List):
        """Run the per-slot device scrub AFTER the engine lock is
        released — a blocking device call under the lock would wedge
        submit()/busy()/the supervisor watchdog (the exact hang the
        watchdog exists to recover from). Safe lock-free: only the one
        driver thread admits, so a freed slot cannot be re-occupied
        before its scrub runs. A FAILING scrub fails the engine: an
        unscrubbed slot would re-poison its next occupant. ``slots``
        carries (slot, victim request) pairs so the scrub lands on the
        victim's timeline track."""
        for i, req in slots:
            try:
                self._scrub_slot_state(i)
            except Exception as e:
                self._fail(e)
                raise
            _strace.note_scrub(req, i)

    def _fail(self, exc):
        """Mark the engine failed (unattributable decode/fetch fault:
        device state untrusted). Pending handles stay pending — an
        EngineSupervisor harvests and replays them; an unsupervised
        caller's close() finishes them with outcome 'error'."""
        if self._failed:
            return
        self._failed = True
        self.last_error = f"{type(exc).__name__}: {exc}"[:500]
        _note_engine_state(self.engine_id, "failed")
        _publish_gauges()

    def _scrub_slot_state(self, i: int):
        """Zero slot ``i``'s row in every device-resident serving
        tensor. A poisoned occupant's non-finite K/V rows would
        re-poison the NEXT occupant straight through the softmax mask
        (a masked weight underflows to exactly 0.0, and 0 * NaN = NaN),
        so eviction must scrub, not just free, the slot. Runs the
        compiled slot-scrub program (transformer.build_slot_scrub) so
        the caches stay on device — a host round-trip of the full KV
        rings to zero one row would stall every healthy slot."""
        scr = self._progs["scrub"]
        with scope_guard(self.scope):
            self._exe.run(
                self._progs["scrub_program"],
                feed={scr["feeds"][0].name: np.asarray([i], np.int64)},
                fetch_list=[])

    def _process_ready(self) -> int:
        """Materialize the pending decode step's fetches and hand each
        slot's token to its request; evict finished/expired/poisoned
        requests (their slots free for the next admission round).

        The blocking device wait runs OUTSIDE the engine lock: a hung
        fetch must not wedge submit()/busy()/the supervisor watchdog
        behind it (the lock is taken only to swap the pending step out
        and to apply its results)."""
        with self._lock:
            if self._failed or self._closed:
                self._pending = None
                return 0
            if self._pending is None:
                return 0
            fetches, snapshot, t0, retried, step = self._pending
            self._pending = None
        try:
            # decode/fetch phase split: device work runs dispatch->t_f0,
            # the host materialization t_f0->t_f1 (with async_fetch the
            # device wait resolves inside np.asarray)
            t_f0 = time.perf_counter()
            _F_FETCH.hit()
            emit, live, pos, maxabs, score = [np.asarray(a)
                                              for a in fetches]
            t_f1 = time.perf_counter()
        except Exception as e:
            with self._lock:
                if self._failed or self._closed:
                    return 0
                to_scrub = self._contain_fetch_error(
                    e, fetches, snapshot, t0, retried, step)
            self._scrub_evicted(to_scrub)  # device call: outside lock
            return 0
        with self._lock:
            if self._failed or self._closed:
                # harvested/closed while we were waiting: the snapshot's
                # requests may already be replaying elsewhere — discard
                return 0
            now = time.perf_counter()
            step_s = now - t0
            # measured per-token latency (admission-control estimator).
            # The engine's FIRST decode step carries the XLA compile (or
            # the disk-cache load) — 10-100x a steady-state step — so it
            # never seeds the EWMA: a compile-poisoned estimate would
            # make every deadline look meetable for dozens of steps.
            if not self._ewma_skipped_first:
                self._ewma_skipped_first = True
            else:
                self._step_walls.append(step_s)
                if self._token_ewma_s is None:
                    self._token_ewma_s = step_s
                else:
                    self._token_ewma_s = (0.8 * self._token_ewma_s
                                          + 0.2 * step_s)
            emitted = 0
            to_scrub = []
            # per-request phase accumulation: the step's device wall is
            # decode time, the host materialization fetch time — every
            # request served by this step pays the same split
            decode_d = t_f0 - t0
            fetch_d = t_f1 - t_f0
            traced = _monitor.trace_step_sampled(step)
            for i, req in enumerate(snapshot):
                if req is None or req.outcome is not None:
                    continue
                if not np.isfinite(maxabs[i]):
                    # poisoned slot: non-finite logits. Contained — the
                    # request keeps its partial output, the slot is
                    # scrubbed (below, outside the lock) + freed,
                    # healthy slots keep decoding. Reported through the
                    # numerics plane (counter + provenance record).
                    _numerics.note_nonfinite(
                        "decode_step", f"slot{i}:logits",
                        program_uid=self._progs["decode_program"]._uid,
                        step=self.decode_steps, kind="serve",
                        maxabs=float(maxabs[i]))
                    _strace.note_evicted(req, "nonfinite", i)
                    self._finish_slot(i, req, "error")
                    to_scrub.append((i, req))
                    _M_SLOT_EVICTIONS.inc(labels={"cause": "nonfinite"})
                    continue
                req.decode_s += decode_d
                req.fetch_s += fetch_d
                tok = int(emit[i])
                alive = bool(live[i])
                if traced:
                    _strace.note_decode_step(
                        req, step, t0, t_f0, t_f1, tok, int(pos[i]),
                        float(score[i]))
                if not alive and tok == self.end_id:
                    # EOS (or a dead-slot freeze): terminal, token dropped
                    self._finish_slot(i, req, "completed")
                    continue
                req.tokens.append(tok)
                emitted += 1
                self.tokens_emitted += 1
                _M_TOKENS.inc()
                _M_TOKEN_SECONDS.observe(step_s)
                if req.ttft_s is None:
                    req.ttft_s = now - req.submit_ts
                    _M_TTFT_SECONDS.observe(req.ttft_s)
                if not alive or len(req.tokens) >= req.max_new_tokens:
                    # device length cap (max_len positions) or the
                    # request's own token budget: terminal without EOS
                    self._finish_slot(i, req, "length")
                elif (req.deadline_ts is not None
                        and now > req.deadline_ts):
                    # deadline eviction AT the token boundary: the slot
                    # is freed now; the partial output stays on the
                    # handle (also the path a deadline expiring while
                    # the async fetch was in flight resolves through)
                    self._finish_slot(i, req, "expired")
            _publish_gauges()
        # the scrubs run with the lock RELEASED and the whole token loop
        # already applied: a scrub failure cannot drop a healthy slot's
        # materialized token, and a hung scrub stays watchdog-visible
        self._scrub_evicted(to_scrub)
        return emitted

    def _finish_slot(self, i: int, req: ServeRequest, outcome: str):
        req._finish(outcome)
        self.completed += 1
        self._slots[i].request = None

    def _harvest_for_replay(self) -> List[ServeRequest]:
        """Supervisor-only: atomically mark the engine failed and take
        every pending (outcome-less) request — in-flight first (their
        admission order), then the queue — so close() cannot finish
        them and the restarted engine can replay them."""
        with self._lock:
            self._failed = True
            if self.last_error is None:
                self.last_error = "harvested for supervised restart"
            out = []
            for s in self._slots:
                req, s.request = s.request, None
                if req is not None and req.outcome is None:
                    out.append(req)
            while self._queue:
                r = self._queue.popleft()
                if r.outcome is None:
                    out.append(r)
            self._pending = None
            _publish_gauges()
        _note_engine_state(self.engine_id, "failed")
        return out

    def _enqueue_replay(self, req: ServeRequest):
        """Supervisor replay intake: bypasses backpressure + admission
        control (the requests were already admitted once — refusing a
        replay would turn one engine fault into request failures). The
        partial output survives until the replay actually re-prefills;
        a dead intake finishes the handle 'error' with it intact."""
        req._replay_intake_ts = time.perf_counter()
        req.engine_id = self.engine_id
        with self._lock:
            if self._closed or self._failed:
                req._finish("error")
                return
            req._replay_pending = True
            if (not self._queue and self._pending is None
                    and all(s.request is None for s in self._slots)):
                self._beat = time.perf_counter()  # idle-only, as submit
            self._queue.append(req)
            _publish_gauges()

    @property
    def state(self) -> str:
        return ("closed" if self._closed
                else "failed" if self._failed
                else "draining" if self._draining else "serving")

    def stats(self) -> Dict:
        """One JSON-able row for the /serve route."""
        with self._lock:
            queued = len(self._queue)
        return {
            "engine_id": self.engine_id,
            "state": self.state,
            "slots": self.slots,
            "slots_active": int(self._active_mask().sum()),
            "queue_depth": queued,
            "queue_capacity": self.queue_depth,
            "src_len": self.src_len,
            "max_len": self.max_len,
            "decode_steps": self.decode_steps,
            "tokens_emitted": self.tokens_emitted,
            "requests_completed": self.completed,
            "draining": self._draining,
            "brownout": self.brownout,
            "last_error": self.last_error,
            "token_ewma_ms": (None if self._token_ewma_s is None
                              else round(self._token_ewma_s * 1e3, 3)),
            "step_wall_ms_p99": (
                None if not self._step_walls
                else round(float(np.percentile(
                    list(self._step_walls), 99)) * 1e3, 3)),
            "int8": self.int8,
            "pipeline_depth": self.pipeline_depth,
        }


class EngineSupervisor:
    """Self-healing serving process: owns a ServingEngine, the thread
    that drives its decode loop, and a watchdog that warm-restarts it.

    Failure handling:

    - **crashed**: an engine-fatal error (unattributable decode/fetch
      fault, device OOM) escapes ``step()`` on the loop thread;
    - **wedged**: the engine is busy but its decode heartbeat is older
      than ``serve_wedge_timeout_ms`` (e.g. a hung device call) — the
      watchdog declares it dead without waiting for it to return, and
      emits the stall record a ``monitor.stall_guard`` would have
      produced (site ``serve.decode``; a per-dispatch guard would cost
      one Timer thread per decode step). Wedge
      detection arms only after the engine's FIRST decode step
      completes: a first-step XLA compile legitimately holds the
      heartbeat for 10-100x a steady-state step and must not read as a
      wedge (set ``compile_cache_dir`` so rebuilds skip even that).

    Either way the old engine is harvested (every queued + in-flight
    handle taken before close() can finish it), torn down, and a new
    engine is built — through the persistent compile cache when
    ``compile_cache_dir`` is set, i.e. zero fresh XLA compiles — under
    a retry.py policy; the harvested requests are re-prefilled in their
    original order and decode from scratch (greedy is deterministic:
    byte-identical tokens). The restart budget (``serve_max_restarts``)
    bounds a permanently failing engine: past it, pending handles
    finish with outcome 'error' and the supervisor closes.

    Metered: ``pt_serve_engine_restarts_total``,
    ``pt_serve_requests_replayed_total``.
    """

    def __init__(self, cfg, weights, *,
                 wedge_timeout_ms: Optional[float] = None,
                 max_restarts: Optional[int] = None,
                 restart_policy: Optional["_retry.RetryPolicy"] = None,
                 restart_deadline_s: float = 60.0,
                 poll_s: float = 0.02,
                 on_handoff=None, **engine_kwargs):
        self._cfg = cfg
        self._weights = weights
        # fleet seam: called with the pending request list when this
        # supervisor fails TERMINALLY (restart budget exhausted or
        # rebuild failed). A truthy return means the callee took
        # ownership (the fleet router replays them on survivors);
        # otherwise they finish 'error' as before. Called under
        # self._lock — the callee must only hand the list off (no
        # synchronous replay, no supervisor calls).
        self._on_handoff = on_handoff
        self._engine_kwargs = dict(engine_kwargs)
        self.wedge_timeout_s = (
            float(_flags.get_flag("serve_wedge_timeout_ms"))
            if wedge_timeout_ms is None else float(wedge_timeout_ms)) / 1e3
        self.max_restarts = (int(_flags.get_flag("serve_max_restarts"))
                             if max_restarts is None else int(max_restarts))
        self._restart_policy = restart_policy or _retry.RetryPolicy(
            base_delay=0.05, max_delay=2.0, max_attempts=3,
            retry_on=(Exception,))
        self._restart_deadline_s = float(restart_deadline_s)
        self._poll_s = float(poll_s)
        self.restarts = 0
        self.replayed = 0
        self._lock = threading.RLock()
        self._closed = False
        self._gen = 0
        self._work = threading.Event()
        self._engine = self._build()
        self._loop_thread = self._start_loop(self._gen, self._engine)
        self._watch_thread = threading.Thread(
            target=self._watch, name="pt-serve-watchdog", daemon=True)
        self._watch_thread.start()

    def _build(self) -> ServingEngine:
        # NOTE: the supervisor does NOT arm a per-dispatch stall_guard —
        # a threading.Timer per few-ms decode step is real thread churn
        # on the hot path. The watchdog emits the equivalent stall
        # record itself when it declares a wedge (same site, same
        # deadline); engines still honor the global stall_timeout_ms
        # flag like every other guarded plane.
        return ServingEngine(self._cfg, self._weights,
                             **self._engine_kwargs)

    def _start_loop(self, gen: int, eng: ServingEngine):
        t = threading.Thread(target=self._serve_loop, args=(gen, eng),
                             name=f"pt-serve-loop-{eng.engine_id}",
                             daemon=True)
        t.start()
        return t

    # --- public surface ---

    @property
    def engine(self) -> ServingEngine:
        with self._lock:
            return self._engine

    @property
    def state(self) -> str:
        with self._lock:
            return "closed" if self._closed else self._engine.state

    def submit(self, *args, **kwargs) -> ServeRequest:
        """Enqueue onto the CURRENT engine; a submit racing a restart
        retries onto the rebuilt one. QueueFull / DeadlineUnmeetable
        propagate (overload is the caller's signal, not the
        supervisor's problem)."""
        deadline = time.monotonic() + max(10.0, self._restart_deadline_s)
        while True:
            with self._lock:
                if self._closed:
                    raise EngineClosed("submit() on a closed supervisor")
                eng = self._engine
            try:
                req = eng.submit(*args, **kwargs)
            except (EngineFailed, EngineClosed):
                with self._lock:
                    if self._closed:
                        raise
                    current = self._engine
                if current is eng and not eng._failed:
                    # the engine is draining/closed by an EXPLICIT
                    # drain, not mid-replacement: fail fast instead of
                    # spinning the retry window
                    raise
                if time.monotonic() > deadline:
                    raise
                time.sleep(self._poll_s)
                continue
            self._work.set()
            return req

    def busy(self) -> bool:
        return self.engine.busy()

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Stop admissions and wait for the loop thread to finish the
        in-flight set (re-applied to the rebuilt engine if a restart
        lands mid-drain)."""
        t0 = time.perf_counter()
        while True:
            eng = self.engine
            eng.request_drain()
            self._work.set()
            if not eng.busy() and eng is self.engine:
                return True
            if time.perf_counter() - t0 > timeout_s:
                return False
            time.sleep(self._poll_s)

    def enqueue_replay(self, req: ServeRequest) -> bool:
        """Fleet failover intake: accept an already-admitted request
        harvested from ANOTHER replica. Bypasses backpressure and
        admission control exactly like the supervised-restart replay
        path — the request was admitted once; greedy decode keeps the
        replayed stream byte-identical. Returns False (handle
        untouched) when this supervisor cannot take it, so the router
        can try the next survivor."""
        with self._lock:
            if self._closed:
                return False
            eng = self._engine
            if eng._closed or eng._failed:
                # mid-replacement: let the router retry rather than
                # racing the rebuilt engine's installation
                return False
            self.replayed += 1
        eng._enqueue_replay(req)
        self._work.set()
        # a fault racing the intake can still finish the handle
        # 'error'; outcome-less means the engine owns it now
        return req.outcome is None or req.done

    def harvest(self) -> List[ServeRequest]:
        """Fleet failover: terminally stop this supervisor and TAKE
        every pending (outcome-less) request instead of finishing it —
        in-flight first (their admission order), then the queue — so
        the router can replay the set on surviving replicas (partial
        outputs intact until each replay re-prefills). Idempotent: a
        second call returns []."""
        with self._lock:
            if self._closed:
                return []
            self._closed = True
            self._gen += 1  # stops the loop thread at its next check
            eng = self._engine
        self._work.set()
        for t in (self._loop_thread, self._watch_thread):
            if t is not threading.current_thread():
                t.join(timeout=5.0)
        pending = eng._harvest_for_replay()
        try:
            eng.close(drain_timeout_s=0.0)
        except Exception:
            pass
        return pending

    def handoff(self, timeout_s: float = 30.0) -> List[ServeRequest]:
        """Rolling-rollout drain: stop admissions, immediately take the
        queued-but-unadmitted requests (the router re-homes them on
        another replica instead of finishing them 'drained'), give the
        loop thread up to ``timeout_s`` to finish the in-flight set,
        then harvest whatever remains. Terminal for this supervisor;
        returns every request the caller must re-home (possibly [])."""
        t0 = time.perf_counter()
        moved: List[ServeRequest] = []
        swept = set()
        while True:
            with self._lock:
                if self._closed:
                    break
                eng = self._engine
            if id(eng) not in swept:
                # re-applied to the rebuilt engine when a supervised
                # restart lands mid-handoff (its replay intake holds
                # the old engine's queue)
                swept.add(id(eng))
                moved.extend(eng.handoff_queued())
                self._work.set()
            if not eng.busy() and eng is self.engine:
                break
            if time.perf_counter() - t0 > timeout_s:
                break
            time.sleep(self._poll_s)
        moved.extend(self.harvest())
        return moved

    def close(self, drain_timeout_s: float = 30.0):
        """Drain, stop the loop + watchdog threads, close the engine.
        Every still-pending handle is finished — result() never hangs
        on a closed supervisor."""
        with self._lock:
            if self._closed:
                return
        self.drain(drain_timeout_s)
        with self._lock:
            self._closed = True
            self._gen += 1  # stops the loop thread at its next check
            eng = self._engine
        self._work.set()
        for t in (self._loop_thread, self._watch_thread):
            if t is not threading.current_thread():
                t.join(timeout=5.0)
        eng.close(drain_timeout_s=0.0)

    def stats(self) -> Dict:
        eng = self.engine
        return {
            "supervised": True,
            "state": self.state,
            "restarts": self.restarts,
            "max_restarts": self.max_restarts,
            # intakes, not admissions: pt_serve_requests_replayed_total
            # is the re-prefill count and can lag this by replays that
            # died (drained/errored) before reaching prefill
            "replays_enqueued": self.replayed,
            "wedge_timeout_ms": self.wedge_timeout_s * 1e3,
            "engine": eng.stats(),
        }

    # --- the supervised loop + watchdog ---

    def _serve_loop(self, gen: int, eng: ServingEngine):
        while True:
            with self._lock:
                if self._closed or gen != self._gen:
                    return
            try:
                if eng.busy():
                    eng.step()
                else:
                    self._work.wait(self._poll_s)
                    self._work.clear()
            except EngineClosed:
                return
            except Exception as e:
                if eng._failed:
                    self._on_engine_failure(gen, eng, e)
                    return
                # non-fatal (e.g. a torn admission already surfaced on
                # its handle): the engine is healthy, keep serving
                warnings.warn(
                    f"supervised engine {eng.engine_id}: non-fatal "
                    f"serving error: {type(e).__name__}: {e}",
                    RuntimeWarning)

    def _watch(self):
        while True:
            time.sleep(self._poll_s)
            with self._lock:
                if self._closed:
                    return
                eng, gen = self._engine, self._gen
            # decode_steps > 0: wedge detection only on a WARMED engine
            # (a first-step compile holds the heartbeat legitimately)
            if (not eng._failed and not eng._closed
                    and eng.decode_steps > 0 and eng.busy()
                    and eng.heartbeat_age_s() > self.wedge_timeout_s):
                if _monitor.enabled():
                    # the stall record a per-dispatch stall_guard would
                    # have produced, emitted once at declaration (the
                    # monitor helper is same-package and never raises)
                    _monitor._record_stall(
                        "serve.decode", self.wedge_timeout_s * 1e3,
                        self._loop_thread.name, ())
                with self._lock:
                    if self._closed or gen != self._gen:
                        continue
                    self._restart_locked(
                        eng, reason=f"wedged (heartbeat "
                        f"{eng.heartbeat_age_s() * 1e3:.0f} ms old)")

    def _on_engine_failure(self, gen: int, eng: ServingEngine, exc):
        with self._lock:
            if self._closed or gen != self._gen:
                return
            self._restart_locked(
                eng, reason=f"{type(exc).__name__}: {exc}")

    def _fail_pending_locked(self, pending: List[ServeRequest]):
        """Terminal-failure epilogue: offer the pending set to the
        fleet (``on_handoff``) before failing it — a router with
        surviving replicas turns a dead supervisor into failovers
        instead of request errors. Caller holds self._lock."""
        if pending and self._on_handoff is not None:
            try:
                if self._on_handoff(list(pending)):
                    return
            except Exception as e:  # the fleet must not kill teardown
                warnings.warn(
                    f"serving supervisor: on_handoff failed "
                    f"({type(e).__name__}: {e}); failing "
                    f"{len(pending)} pending request(s)",
                    RuntimeWarning)
        for r in pending:
            r._finish("error")

    def _restart_locked(self, old: ServingEngine, reason: str):
        """Tear down + rebuild + replay. Caller holds self._lock."""
        pending = old._harvest_for_replay()
        if self.restarts >= self.max_restarts:
            warnings.warn(
                f"serving supervisor: restart budget "
                f"({self.max_restarts}) exhausted ({reason}); failing "
                f"{len(pending)} pending request(s)", RuntimeWarning)
            self._fail_pending_locked(pending)
            self._closed = True
            self._gen += 1
            try:
                old.close(drain_timeout_s=0.0)
            except Exception:
                pass
            return
        self.restarts += 1
        _M_RESTARTS.inc()
        warnings.warn(
            f"serving supervisor: restarting engine {old.engine_id} "
            f"({reason}); replaying {len(pending)} request(s)",
            RuntimeWarning)
        try:
            old.close(drain_timeout_s=0.0)
        except Exception:
            pass
        try:
            # warm rebuild under the retry budget: with
            # compile_cache_dir set every executable resolves from disk
            # (zero fresh compiles — the warm-replica path)
            new = _retry.call(self._build, site="serve.restart",
                              policy=self._restart_policy,
                              retry_on=(Exception,),
                              deadline_s=self._restart_deadline_s)
        except Exception as e:
            warnings.warn(
                f"serving supervisor: engine rebuild failed after "
                f"retries ({type(e).__name__}: {e}); failing "
                f"{len(pending)} pending request(s)", RuntimeWarning)
            self._fail_pending_locked(pending)
            self._closed = True
            self._gen += 1
            return
        self._gen += 1
        self._engine = new
        for r in pending:
            # self.replayed counts replay INTAKES; the token wipe and
            # the pt_serve_requests_replayed_total tick happen at the
            # new engine's ADMISSION (_reset_for_replay), so a replay
            # that never reaches prefill keeps its partial output and
            # the metric counts only true re-prefills
            self.replayed += 1
            new._enqueue_replay(r)
        self._work.set()
        self._loop_thread = self._start_loop(self._gen, new)


def _is_tpu_default() -> bool:
    import jax

    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


_ENGINES: "weakref.WeakSet[ServingEngine]" = weakref.WeakSet()


def _publish_gauges():
    """Refresh the process-wide queue/slot/brownout gauges as SUMS
    across live engines — per-engine .set() calls would let an idle
    engine zero out a saturated neighbor's reading (the per-engine
    split lives in /serve's stats rows)."""
    engines = list(_ENGINES)
    _M_QUEUE_DEPTH.set(sum(len(e._queue) for e in engines))
    _M_SLOTS_ACTIVE.set(sum(
        1 for e in engines for s in e._slots
        if s.request is not None and s.request.outcome is None))
    _M_BROWNOUT.set(sum(1 for e in engines if e.brownout))


def serve(cfg, weights, *, supervised: bool = False, **kwargs):
    """Predictor-style front end: build a ServingEngine over ``weights``
    (a Scope, a Predictor, or a saved inference-model directory — the
    int8 PTQ artifact deploys dequantized). ``supervised=True`` wraps
    it in an EngineSupervisor (self-driving decode loop + watchdog +
    warm restart). See ServingEngine for the geometry/SLO knobs."""
    if supervised:
        return EngineSupervisor(cfg, weights, **kwargs)
    return ServingEngine(cfg, weights, **kwargs)


def summary() -> Dict:
    """The /serve route payload: one stats row per live engine."""
    engines = [e.stats() for e in list(_ENGINES)]
    return {
        "engines": engines,
        "engine_count": len(engines),
        "tokens_total": int(_M_TOKENS.value()),
        "decode_steps_total": int(_M_DECODE_STEPS.value()),
        "engine_restarts_total": int(_M_RESTARTS.value()),
        "requests_replayed_total": int(_M_REPLAYED.value()),
        "token_latency_s": {
            label: _M_TOKEN_SECONDS.quantile(q)
            for label, q in _monitor.QUANTILE_LABELS
        },
        "ttft_s": {
            label: _M_TTFT_SECONDS.quantile(q)
            for label, q in _monitor.QUANTILE_LABELS
        },
    }
